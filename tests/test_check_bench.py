"""scripts/check_bench.py: bench metric-line schema audit.

Fast CPU checks: the historical BENCH_r01-05 artifacts audit clean
under -legacy-ok (and fail loudly without it — they predate the
round-6 attempts/discarded metadata), and synthetic good/bad
new-schema lines pass/fail as designed.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "check_bench.py"
ARTIFACTS = sorted(REPO.glob("BENCH_r0*.json"))

# round 12: the session-calibration fingerprint digest every new
# metric line carries (lux_tpu/observe.py) — grade must be
# "canonical" or the line is rejected from the trajectory
GOOD_CAL = {
    "schema": 1, "session": "a1b2c3d4e5f6", "platform": "tpu",
    "backend": "tpu", "ndev": 1, "grade": "canonical",
    "deviation": 1.07,
    "probe": {"gather_small_ns": 9.6, "gather_small_mad_ns": 0.2,
              "pair_dot_row_ns": 121.0, "pair_dot_row_mad_ns": 4.0},
    "audit": {"errors": 0, "warnings": 0},
}

GOOD_LINE = {
    "metric": "pagerank_mp_rmat23_gteps_per_chip",
    "value": 0.1118, "unit": "GTEPS", "vs_baseline": 0.1118,
    "samples": [0.1116, 0.1118, 0.112],
    "attempts": 4, "discarded": [0.0107], "np": 4,
    "ne": 10**9,
    # round 7: per-run seconds (one per attempt, reruns included)
    # re-deriving each recorded sample, plus the counter digest
    "telemetry": {
        "runs": [
            {"repeat": 0, "iters": 10, "seconds": 89.605735},
            {"repeat": 1, "iters": 10, "seconds": 89.445438},
            {"repeat": 2, "iters": 10, "seconds": 89.285714},
            {"repeat": 0, "iters": 10, "seconds": 934.579439},
        ],
        "counters": {"kind": "pull", "iters": 10, "truncated": False,
                     "residual_first": 3.5e-4,
                     "residual_last": 9.7e-8,
                     "changed_last": 12, "changed_sum": 480},
    },
    "calibration": GOOD_CAL,
}


def run_check(*argv):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *map(str, argv)],
        capture_output=True, text=True)


def test_current_artifacts_audit_clean_as_legacy():
    assert ARTIFACTS, "no BENCH_r*.json artifacts in the repo root"
    r = run_check("-legacy-ok", *ARTIFACTS)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_current_artifacts_fail_strict_schema():
    """Pre-round-6 lines lack attempts/discarded; the default (strict)
    mode must fail LOUDLY, naming the missing metadata."""
    r = run_check(*ARTIFACTS)
    assert r.returncode == 1
    assert "missing resilience metadata" in r.stderr
    assert "FAILED" in r.stderr


def test_good_new_schema_line_passes(tmp_path):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(GOOD_LINE) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda d: d.pop("attempts"), "missing resilience metadata"),
    (lambda d: d.update(attempts=9), "inconsistent"),
    (lambda d: d.update(value=0.0107), "not the median"),
    (lambda d: d.update(samples=[]), "non-empty list"),
    (lambda d: d.pop("value"), "missing required key"),
    (lambda d: d.update(run_attempts=1), "run_attempts"),
    (lambda d: d.update(samples=[0.1116, 0.1118, 0.0107],
                        value=0.1116, attempts=4),
     "both samples and discarded"),
    # round-7 telemetry field
    (lambda d: d.pop("telemetry"), "missing telemetry"),
    (lambda d: d["telemetry"].update(runs=d["telemetry"]["runs"][:2]),
     "timed runs"),
    (lambda d: d["telemetry"]["runs"][0].update(seconds=50.0),
     "matches no recorded sample"),
    (lambda d: d["telemetry"].update(counters={"kind": "sideways"}),
     "counters malformed"),
    (lambda d: d["telemetry"].update(runs=[{"repeat": 0, "iters": 10,
                                            "seconds": 0.0}] * 4),
     "telemetry.runs"),
    (lambda d: d.update(telemetry={"runs": []}), "telemetry must be"),
])
def test_bad_lines_fail(tmp_path, mutate, needle):
    d = json.loads(json.dumps(GOOD_LINE))   # deep copy: mutators
    mutate(d)                               # touch nested dicts
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def _with_imbalance(imb=None, **counter_over):
    d = json.loads(json.dumps(GOOD_LINE))
    d["telemetry"]["counters"].update(counter_over)
    d["telemetry"]["imbalance"] = imb
    return d


def test_imbalance_digest_accepted(tmp_path):
    """Round-13 telemetry.imbalance: a consistent digest passes,
    null passes (iter-stats off), absent passes (older schema)."""
    good = _with_imbalance({"kind": "pull", "index": 1.5,
                            "parts": [180, 120, 60, 120]},
                           changed_sum=480)
    for line in (good, _with_imbalance(None), GOOD_LINE):
        p = tmp_path / "bench.jsonl"
        p.write_text(json.dumps(line) + "\n")
        r = run_check(p)
        assert r.returncode == 0, (line, r.stderr)


@pytest.mark.parametrize("imb,counters,needle", [
    # parts don't sum to the scalar counter — the health-digest
    # contradiction pattern: per-part and scalar are the SAME
    # device-side values, so disagreement is rejected
    ({"kind": "pull", "index": 1.5, "parts": [180, 120, 60, 121]},
     {"changed_sum": 480}, "contradicts the counter digest"),
    # index contradicting its own parts
    ({"kind": "pull", "index": 3.0, "parts": [180, 120, 60, 120]},
     {"changed_sum": 480}, "contradicts its own parts"),
    ({"kind": "pull", "index": 0.5, "parts": [180, 120, 60, 120]},
     {"changed_sum": 480}, "must be a finite number >= 1"),
    ({"kind": "sideways", "index": 1.5, "parts": [1, 2]},
     {}, "not push|pull"),
    ({"kind": "pull", "index": 1.0, "parts": []},
     {}, "non-empty list"),
    ({"kind": "pull", "index": 1.0, "parts": [1, -2]},
     {}, "non-empty list of ints"),
    ("not-a-dict", {}, "must be null or a dict"),
])
def test_bad_imbalance_digests_fail(tmp_path, imb, counters, needle):
    d = _with_imbalance(imb, **counters)
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_health_digest_accepted_and_typechecked(tmp_path):
    """Round-9 telemetry.health digest (bench.py -health): a clean
    digest passes, null passes (watchdog off), and malformed or
    contradictory digests fail."""
    good = json.loads(json.dumps(GOOD_LINE))
    good["telemetry"]["health"] = {"engine": "pull", "tripped": False,
                                   "flags": [], "iters": 10}
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(good) + "\n")
    assert run_check(p).returncode == 0, run_check(p).stderr
    good["telemetry"]["health"] = None
    p.write_text(json.dumps(good) + "\n")
    assert run_check(p).returncode == 0


@pytest.mark.parametrize("health,needle", [
    ({"engine": "gpu", "tripped": False, "flags": [], "iters": 10},
     "not push|pull"),
    ({"engine": "pull", "tripped": "no", "flags": [], "iters": 10},
     "tripped must be a bool"),
    ({"engine": "pull", "tripped": False, "flags": ["made_up"],
      "iters": 10}, "unknown checks"),
    ({"engine": "pull", "tripped": True,
      "flags": ["nonfinite_state"], "iters": 10},
     "cannot publish a metric line"),
    ({"engine": "pull", "tripped": False, "flags": [], "iters": -1},
     "iters"),
    ("clean", "null or a dict"),
])
def test_bad_health_digests_fail(tmp_path, health, needle):
    d = json.loads(json.dumps(GOOD_LINE))
    d["telemetry"]["health"] = health
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_audit_digest_accepted(tmp_path):
    """Round-10 audit digest (bench.py -audit, lux_tpu/audit.py): a
    clean digest passes, null passes (-audit off), absence passes
    (older artifacts)."""
    good = json.loads(json.dumps(GOOD_LINE))
    good["audit"] = {"mode": "warn", "errors": 0, "warnings": 1,
                     "failed_checks": []}
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(good) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr
    good["audit"] = None
    p.write_text(json.dumps(good) + "\n")
    assert run_check(p).returncode == 0


@pytest.mark.parametrize("audit,needle", [
    ({"mode": "loud", "errors": 0, "warnings": 0,
      "failed_checks": []}, "not warn|error"),
    ({"mode": "warn", "errors": -1, "warnings": 0,
      "failed_checks": []}, "audit.errors"),
    ({"mode": "warn", "errors": 0, "warnings": 0,
      "failed_checks": ["made-up-check"]}, "unknown checks"),
    ({"mode": "warn", "errors": 2, "warnings": 0,
      "failed_checks": ["gather-budget"]}, "audit-FAILING build"),
    ({"mode": "warn", "errors": 0, "warnings": 0,
      "failed_checks": ["identity-init"]}, "audit-FAILING build"),
    ({"mode": "warn", "errors": 0, "warnings": 0,
      "failed_checks": "gather-budget"}, "failed_checks must be"),
    ("clean", "null or a dict"),
])
def test_bad_audit_digests_fail(tmp_path, audit, needle):
    """A published metric line whose build failed the static audit is
    a contradiction — the number was measured on a build violating
    the structural invariants."""
    d = json.loads(json.dumps(GOOD_LINE))
    d["audit"] = audit
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


# -- round-12 calibration fingerprint (lux_tpu/observe.py) -------------

def test_missing_calibration_fails_strict(tmp_path):
    """Pre-round-12 lines lack the fingerprint; strict mode fails
    loudly, -legacy-ok downgrades (historical artifacts)."""
    d = json.loads(json.dumps(GOOD_LINE))
    del d["calibration"]
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1 and "missing calibration" in r.stderr
    assert run_check("-legacy-ok", p).returncode == 0


@pytest.mark.parametrize("mutate,needle", [
    # a crashed probe (null) leaves the line unlabeled — rejected
    (lambda c: None, "calibration is null"),
    # the 10x tunnel session, detected and labeled — rejected
    (lambda c: dict(c, grade="degraded", deviation=9.7),
     "DEGRADED session"),
    # CPU test-mesh numbers must never enter the TPU trajectory
    (lambda c: dict(c, grade="uncalibrated", platform="cpu",
                    deviation=0.16), "UNCALIBRATED session"),
    # a self-contradicting digest (claims canonical, deviation 5x)
    (lambda c: dict(c, deviation=5.0), "contradicts itself"),
    (lambda c: dict(c, grade="excellent"), "calibration.grade"),
    (lambda c: dict(c, deviation="fast"), "calibration.deviation"),
    (lambda c: dict(c, probe={}), "calibration.probe"),
    # a probe that failed its own static audit measured nothing
    (lambda c: dict(c, audit={"errors": 1, "warnings": 0}),
     "failed their own static audit"),
    (lambda c: dict(c, audit=None), "calibration.audit"),
    (lambda c: dict(c, ndev=0), "calibration.ndev"),
    (lambda c: dict(c, session=""), "calibration.session"),
    (lambda c: "calibrated", "null or a dict"),
])
def test_bad_calibration_digests_fail(tmp_path, mutate, needle):
    d = json.loads(json.dumps(GOOD_LINE))
    d["calibration"] = mutate(json.loads(json.dumps(GOOD_CAL)))
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr, r.stderr


def test_fast_deviation_also_contradicts(tmp_path):
    """deviation < 1/3 on a 'canonical' grade is as contradictory as
    > 3 — a probe that measured 5x FASTER than canon is lying about
    something (clock, fence, or shapes)."""
    d = json.loads(json.dumps(GOOD_LINE))
    d["calibration"] = dict(GOOD_CAL, deviation=0.2)
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1 and "contradicts itself" in r.stderr


def test_failed_config_line_schema(tmp_path):
    good = {"metric": "sssp_FAILED", "error": "RuntimeError: worker",
            "attempts": 3, "failure_class": "retryable"}
    bad = {"metric": "sssp_FAILED", "error": "RuntimeError: worker"}
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(good) + "\n")
    assert run_check(p).returncode == 0
    p.write_text(json.dumps(bad) + "\n")
    r = run_check(p)
    assert r.returncode == 1 and "failure line missing" in r.stderr
    # legacy mode tolerates it (historical crash lines)
    assert run_check("-legacy-ok", p).returncode == 0


def test_crashed_rerun_line_accepted(tmp_path):
    """An outlier rerun that crashed after its timed_run event landed
    leaves runs > attempts with no matching sample; the recorded
    rerun_error legitimizes both (bench.py's crash-tolerant path)."""
    d = json.loads(json.dumps(GOOD_LINE))
    d["samples"] = [0.1116, 0.1118, 0.112]
    d["value"] = 0.1118
    d["discarded"] = []
    d["attempts"] = 3
    d["rerun_error"] = "RuntimeError: tunnel died"
    d["rerun_error_class"] = "retryable"
    # 4th run's sample never recorded — the crashed rerun
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr


def test_events_jsonl_accepted(tmp_path):
    """An -events telemetry log (kind/t objects, no metric lines)
    audits as events instead of failing (round-7 acceptance: both
    checkers accept the -events JSONL)."""
    p = tmp_path / "events.jsonl"
    p.write_text(
        '{"t": 1.0, "kind": "run_start", "app": "sssp"}\n'
        '{"t": 1.2, "kind": "timed_run", "repeat": 0, "iters": 5, '
        '"seconds": 0.02}\n')
    assert run_check(p).returncode == 0
    p.write_text('{"t": 1.0, "kind": "segment", "seconds": "fast"}\n')
    r = run_check(p)
    assert r.returncode == 1 and "non-finite seconds" in r.stderr


def test_unparseable_and_empty_inputs(tmp_path):
    p = tmp_path / "junk.jsonl"
    p.write_text('{"metric": broken\n')
    r = run_check(p)
    assert r.returncode == 1 and "unparseable" in r.stderr
    p.write_text("nothing here\n")
    r = run_check(p)
    assert r.returncode == 1 and "no metric lines" in r.stderr


# ---- round-8 script lines (netflix / bigscale) ----------------------

NETFLIX_LINE = {
    "metric": "colfilter_netflix100m_np4_gteps_per_chip",
    "value": 0.09, "unit": "GTEPS", "vs_baseline": 0.09,
    "samples": [0.09, 0.0905, 0.0896], "attempts": 3, "discarded": [],
    "np": 4, "ne": 186_000_000, "iters": 3, "pair_threshold": 16,
    "min_fill": "auto", "pair_stream": True,
    "telemetry": {"runs": [
        {"repeat": 0, "iters": 3, "seconds": 186e6 * 3 / 0.09 / 1e9},
        {"repeat": 1, "iters": 3, "seconds": 186e6 * 3 / 0.0905 / 1e9},
        {"repeat": 2, "iters": 3, "seconds": 186e6 * 3 / 0.0896 / 1e9},
    ], "counters": None},
    "calibration": GOOD_CAL,
    "rmse": [2.926, 2.800, 2.714],
}

BIGSCALE_LINE = {
    "metric": "pagerank_rmat27_np8_gteps_per_chip",
    "value": 0.11, "unit": "GTEPS", "vs_baseline": 0.11,
    "samples": [0.11], "attempts": 1, "discarded": [],
    "np": 8, "scale": 27, "ne": 2_147_483_648, "iters": 1,
    "pair_threshold": 16, "min_fill": 16, "exchange": "owner",
    "sparse": True, "start": None, "seg": None,
    "telemetry": {"runs": [
        {"repeat": 0, "iters": 1, "seconds": 2_147_483_648 / 0.11 / 1e9},
    ], "counters": None},
    "calibration": GOOD_CAL,
}


def _audit_one(tmp_path, obj):
    p = tmp_path / "line.json"
    p.write_text(json.dumps(obj))
    return run_check(p)


def test_netflix_and_bigscale_lines_pass_strict(tmp_path):
    for obj in (NETFLIX_LINE, BIGSCALE_LINE):
        r = _audit_one(tmp_path, obj)
        assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda o: o.update(rmse=[2.9, 2.95, 2.8]), "not strictly"),
    (lambda o: o.update(rmse=[2.9]), ">= 2 finite"),
    (lambda o: o.pop("rmse"), "missing"),
    (lambda o: o.update(min_fill="bogus"), "min_fill"),
    (lambda o: o.update(pair_threshold=0), "pair_threshold"),
])
def test_bad_netflix_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(NETFLIX_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad netflix line"
    assert needle in r.stderr, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda o: o.update(scale=26), "contradicts"),
    (lambda o: o.update(exchange="bogus"), "exchange"),
    (lambda o: o.update(iters=0), "iters"),
    (lambda o: o.pop("exchange"), "missing"),
    (lambda o: o.update(min_fill=0), "min_fill"),
])
def test_bad_bigscale_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(BIGSCALE_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad bigscale line"
    assert needle in r.stderr, r.stderr


# -- round-11 telemetry.topology (degraded-mesh rejection) -------------

def test_null_topology_digest_accepted(tmp_path):
    d = json.loads(json.dumps(GOOD_LINE))
    d["telemetry"]["topology"] = None
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr


def test_mid_run_mesh_shrink_rejected(tmp_path):
    """The round-11 satellite: a metric line whose telemetry records
    a mid-run mesh shrink must FAIL — a degraded-mesh GTEPS compared
    against full-mesh lines silently is exactly the kind of quiet
    apples-to-oranges this checker exists to prevent."""
    d = json.loads(json.dumps(GOOD_LINE))
    d["telemetry"]["topology"] = {"shrinks": 1, "ndev_final": 4}
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert "mesh shrink" in r.stderr
    assert "degraded-mesh" in r.stderr


@pytest.mark.parametrize("topo,needle", [
    ({"shrinks": "two"}, "shrinks"),
    ({"shrinks": 0, "ndev_final": 0}, "ndev_final"),
    # a non-null digest claiming zero shrinks dodges the rejection
    # while asserting degradation metadata exists — malformed
    ({"shrinks": 0, "ndev_final": 4}, "null digest means no shrink"),
    ("shrunk", "must be null or a dict"),
])
def test_malformed_topology_digests_fail(tmp_path, topo, needle):
    d = json.loads(json.dumps(GOOD_LINE))
    d["telemetry"]["topology"] = topo
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr, r.stderr


# -- round-14 query-batched lines (bench.py batch-sweep) ---------------

BATCH_LINE = {
    "metric": "ksssp_b8_rmat20_gteps_per_chip",
    "value": 0.17, "unit": "GTEPS", "vs_baseline": 0.17,
    "batch": 8, "query_gteps": 1.36,
    "per_query_edge_ns": 0.7353,
    "samples": [0.17], "attempts": 1, "discarded": [],
    "np": 1, "ne": 16 * (1 << 20),
    "telemetry": {
        "runs": [{"repeat": 0, "iters": 10,
                  "seconds": 16 * (1 << 20) * 10 / 0.17 / 1e9}],
        "counters": None},
    "calibration": GOOD_CAL,
}


def test_batched_line_passes_strict(tmp_path):
    r = _audit_one(tmp_path, BATCH_LINE)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda o: o.update(query_gteps=0.5),
     "contradicts the machine rate"),
    (lambda o: o.pop("query_gteps"), "missing query_gteps"),
    (lambda o: o.update(batch=4), "contradicts the metric name"),
    (lambda o: o.update(batch="8"), "positive int"),
    (lambda o: o.update(per_query_edge_ns=9.0),
     "contradicts 1/query_gteps"),
])
def test_bad_batched_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(BATCH_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad batched line"
    assert needle in r.stderr, r.stderr


# -- round-17 serving SLO lines (bench.py -config serve-slo) -----------

SERVE_SLO_LINE = {
    "metric": "serve_slo_q45_rmat12_qps_per_chip",
    "value": 41.2, "unit": "qps", "vs_baseline": 41.2,
    "samples": [41.2], "attempts": 1, "discarded": [],
    "np": 2, "scale": 12, "ef": 8, "serve_batch": 4,
    "kinds": ["sssp", "components", "pagerank"], "queries": 36,
    "offered_qps": 44.8, "achieved_qps": 41.2,
    "p50_ms": 18.4, "p99_ms": 61.0,
    "slo_target_ms": {"sssp": 250.0, "components": 250.0,
                      "pagerank": 1000.0},
    "slo_good_fraction": 0.972,
    "served": 35, "submitted": 36,
    "telemetry": {"runs": [{"repeat": 0, "iters": 35,
                            "seconds": 0.85}],
                  "counters": None},
    "calibration": GOOD_CAL,
}


def test_serve_slo_line_passes_strict(tmp_path):
    r = _audit_one(tmp_path, SERVE_SLO_LINE)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    # the three contradiction rejects of the round-17 schema
    (lambda o: o.update(p99_ms=9.0), "p99_ms=9.0 < p50_ms"),
    (lambda o: o.update(achieved_qps=50.0, value=50.0,
                        samples=[50.0]), "outrun arrivals"),
    (lambda o: o.update(slo_good_fraction=1.2), "slo_good_fraction"),
    (lambda o: o.update(slo_good_fraction=-0.1),
     "slo_good_fraction"),
    # record completeness + self-consistency
    (lambda o: o.pop("offered_qps"), "serve-slo line missing"),
    (lambda o: o.pop("slo_target_ms"), "serve-slo line missing"),
    (lambda o: o.update(value=12.0, samples=[12.0]),
     "achieved_qps"),
    (lambda o: o.update(offered_qps=-3.0), "offered_qps"),
    (lambda o: o.update(slo_target_ms={}), "slo_target_ms"),
    (lambda o: o.update(slo_target_ms={"sssp": 0}), "slo_target_ms"),
    (lambda o: o.update(p50_ms="fast"), "p50_ms"),
])
def test_bad_serve_slo_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(SERVE_SLO_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad serve-slo line"
    assert needle in r.stderr, r.stderr


# -- round-18 serving chaos lines (bench.py -config serve-chaos) -------

SERVE_CHAOS_LINE = {
    **json.loads(json.dumps(SERVE_SLO_LINE)),
    "metric": "serve_chaos_q45_rmat12_qps_per_chip",
    "replicas": 2, "failovers": 3, "shed": 1,
    "shed_fraction": round(1 / 36, 4), "slo_accounted": 35,
    # round 24: the self-healing record rides every chaos line
    "respawns": 1, "quarantines": 0, "mttr_s": 0.42,
    "journal_replayed": 2,
}


def test_serve_chaos_line_passes_strict(tmp_path):
    r = _audit_one(tmp_path, SERVE_CHAOS_LINE)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    # the round-18 contradiction rejects
    (lambda o: o.update(shed_fraction=1.2), "shed_fraction"),
    (lambda o: o.update(shed_fraction=-0.1), "shed_fraction"),
    (lambda o: o.update(replicas=1), "no surviving replica"),
    (lambda o: o.update(slo_accounted=36),
     "computed over shed queries"),
    (lambda o: o.update(shed=3), "partition the offered load"),
    (lambda o: o.update(shed_fraction=0.5), "disagrees with"),
    # record completeness + types
    (lambda o: o.pop("replicas"), "serve-chaos line missing"),
    (lambda o: o.pop("shed_fraction"), "serve-chaos line missing"),
    (lambda o: o.update(failovers=-1), "failovers"),
    (lambda o: o.update(replicas="two"), "replicas"),
    # the serve-slo contradictions stay armed on chaos lines
    (lambda o: o.update(p99_ms=9.0), "p99_ms=9.0 < p50_ms"),
    # round 24: the self-healing record
    (lambda o: o.pop("respawns"), "self-healing record"),
    (lambda o: o.pop("journal_replayed"), "self-healing record"),
    (lambda o: o.update(respawns=-1), "respawns"),
    (lambda o: o.update(respawns=1, replicas=1, failovers=0,
                        shed=0, shed_fraction=0.0, served=36,
                        slo_accounted=36), "with replicas=1"),
    (lambda o: o.update(quarantines=-1), "quarantines"),
    (lambda o: o.update(mttr_s=-0.5), "mttr_s"),
    (lambda o: o.update(mttr_s="fast"), "mttr_s"),
    (lambda o: o.update(failovers=0, respawns=0, shed=0,
                        shed_fraction=0.0, served=36,
                        slo_accounted=36), "no outage to time"),
    (lambda o: o.update(journal_replayed=99), "never offered"),
])
def test_bad_serve_chaos_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(SERVE_CHAOS_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad serve-chaos line"
    assert needle in r.stderr, r.stderr


def test_serve_chaos_zero_failovers_with_replicas_ok(tmp_path):
    """failovers=0 with any replica count (and shed=0) is a
    legitimate quiet run — only the impossible combinations
    reject."""
    obj = json.loads(json.dumps(SERVE_CHAOS_LINE))
    obj.update(failovers=0, shed=0, shed_fraction=0.0,
               served=36, slo_accounted=36)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 0, r.stderr


# -- round-20 live-graph serving lines (bench.py -config serve-live) ---

SERVE_LIVE_LINE = {
    "metric": "serve_live_rmat12_qps_per_chip",
    "value": 9.2, "unit": "qps", "vs_baseline": 9.2,
    "samples": [9.2], "attempts": 1, "discarded": [],
    "np": 2, "scale": 12, "ef": 8, "serve_batch": 4,
    "kinds": ["sssp", "components", "pagerank"],
    "delta_capacity": 64, "compact_threshold": 0.75,
    "submitted": 36, "served": 36,
    "mutations": 72, "mutation_rate_per_s": 18.3,
    "epochs_advanced": 6, "compactions": 1,
    # round 21: the mutation-algebra record rides on every line
    "deletions": 3, "reweights": 2, "reseeds": 2,
    "scheduler_compactions": 1,
    "cache_hit_fraction": 0.4615, "peak_occupancy": 0.75,
    "telemetry": {"runs": [{"repeat": 0, "iters": 36,
                            "seconds": 3.91}],
                  "counters": None},
    "calibration": GOOD_CAL,
}


def test_serve_live_line_passes_strict(tmp_path):
    r = _audit_one(tmp_path, SERVE_LIVE_LINE)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    # the round-20 contradiction rejects
    (lambda o: o.update(mutations=0), "with mutations=0"),
    (lambda o: o.update(epochs_advanced=0),
     "epoch-invisible"),
    (lambda o: o.update(epochs_advanced=100),
     "more epochs than edges"),
    (lambda o: o.update(cache_hit_fraction=1.2),
     "cache_hit_fraction"),
    (lambda o: o.update(cache_hit_fraction=-0.1),
     "cache_hit_fraction"),
    # sub-threshold occupancy only contradicts a compaction when no
    # anti-monotone op could have triggered the fold instead
    (lambda o: o.update(peak_occupancy=0.3, deletions=0,
                        reweights=0, reseeds=0),
     "never reached compact_threshold"),
    # round-21 mutation-algebra contradictions
    (lambda o: o.update(deletions=0, reweights=0),
     "nothing to re-seed FROM"),
    (lambda o: o.update(deletions=100),
     "the algebra counters exceed"),
    (lambda o: o.update(scheduler_compactions=5),
     "cannot have folded more"),
    (lambda o: o.update(deletions=0, reweights=0, reseeds=0,
                        peak_occupancy=0.3),
     "neither scheduler trigger"),
    # record completeness + types
    (lambda o: o.pop("mutations"), "serve-live line missing"),
    (lambda o: o.pop("compactions"), "serve-live line missing"),
    (lambda o: o.pop("peak_occupancy"), "serve-live line missing"),
    (lambda o: o.pop("deletions"), "serve-live line missing"),
    (lambda o: o.pop("scheduler_compactions"),
     "serve-live line missing"),
    (lambda o: o.update(compactions=-1), "compactions"),
    (lambda o: o.update(reseeds=-1), "reseeds"),
    (lambda o: o.update(deletions="some"), "deletions"),
    (lambda o: o.update(peak_occupancy=1.5), "peak_occupancy"),
    (lambda o: o.update(compact_threshold=0.0), "compact_threshold"),
    (lambda o: o.update(delta_capacity=0), "delta_capacity"),
    (lambda o: o.update(mutations="many"), "mutations"),
])
def test_bad_serve_live_lines_fail(tmp_path, mutate, needle):
    obj = json.loads(json.dumps(SERVE_LIVE_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad serve-live line"
    assert needle in r.stderr, r.stderr


def test_serve_live_quiet_run_ok(tmp_path):
    """Zero mutations + zero epochs + zero compactions (a static
    drain through the live path) is legitimate — only the impossible
    combinations reject, and a sub-threshold peak occupancy is fine
    when nothing compacted."""
    obj = json.loads(json.dumps(SERVE_LIVE_LINE))
    obj.update(mutations=0, epochs_advanced=0, compactions=0,
               peak_occupancy=0.0, mutation_rate_per_s=0.0,
               deletions=0, reweights=0, reseeds=0,
               scheduler_compactions=0)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------
# round 16: gather-ab reorder field + pairing rule


def _gather_line(mode="paged", reorder=None, fill=9.5, tag="rmat21"):
    d = json.loads(json.dumps(GOOD_LINE))
    rtok = "" if reorder in (None, "none") else f"{reorder}_"
    d["metric"] = f"pagerank_{mode}_{rtok}{tag}_gteps_per_chip"
    d["gather"] = mode
    d["page_ratio"] = 0.61
    d["page_fill"] = fill
    if reorder is not None:
        d["reorder"] = reorder
    return d


def test_gather_reorder_lines_accepted(tmp_path):
    """A reordered pair whose fill ROSE passes, including the
    pagemajor mode and the community shape tag."""
    lines = [_gather_line("paged", "none", 8.2),
             _gather_line("paged", "hillclimb", 31.0),
             _gather_line("flat", "none", 8.2),
             _gather_line("flat", "native", 24.0),
             _gather_line("pagemajor", "none", 9.0, tag="comm14")]
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("line,needle", [
    (_gather_line("paged", "sorted"), "reorder="),
    # reorder field contradicting the metric name's token
    ({**_gather_line("paged", "hillclimb"), "reorder": "none"},
     "contradicts the metric name's reorder"),
    ({**_gather_line("paged"), "reorder": "native"},
     "contradicts the metric name's reorder"),
])
def test_bad_reorder_fields_fail(tmp_path, line, needle):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(line) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_reorder_pair_fill_decrease_rejected(tmp_path):
    """The cross-line rule: a reordered line published WITH its
    paired none line must not show a fill drop — the reorder
    hill-climbs fill, so a drop is a mislabeled pair or a broken
    reorderer."""
    lines = [_gather_line("paged", "none", 9.5),
             _gather_line("paged", "hillclimb", 7.0)]
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 1
    assert "DECREASED" in r.stderr
    # without the paired none line the (possibly historical) single
    # line stands on its own
    p.write_text(json.dumps(lines[1]) + "\n")
    assert run_check(p).returncode == 0


def test_reorder_pair_cross_np_not_compared(tmp_path):
    """num_parts is part of the pairing identity: padded fill shifts
    legitimately with the parts' common depth profile, so a
    reordered np=4 line never pairs against a none np=1 baseline."""
    none1 = _gather_line("paged", "none", 20.0)
    none1["np"] = 1
    ro4 = _gather_line("paged", "hillclimb", 12.0)
    ro4["np"] = 4
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in [none1, ro4]))
    assert run_check(p).returncode == 0
    # same np: the drop IS a contradiction
    ro4["np"] = 1
    p.write_text("".join(json.dumps(d) + "\n" for d in [none1, ro4]))
    r = run_check(p)
    assert r.returncode == 1 and "DECREASED" in r.stderr


# ---------------------------------------------------------------------
# round-19 comm-ledger digest (lux_tpu/comms.py, bench.py _comm_build)

GOOD_COMM = {"errors": 0, "ndev": 4, "exchange": "owner",
             "tier": "ici", "bytes_per_iter": 250000,
             "comm_bytes_per_edge": 0.001, "messages": 2,
             "comm_frac": 0.0021}


def _with_comm(**over):
    d = json.loads(json.dumps(GOOD_LINE))
    d["comm"] = dict(GOOD_COMM, **over)
    return d


def test_comm_digest_accepted(tmp_path):
    """A clean byte-ledger digest passes strict mode; off-mesh
    single-device digests legitimately carry all-zero bytes."""
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(_with_comm()) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr
    single = _with_comm(ndev=1, tier="local", bytes_per_iter=0,
                        comm_bytes_per_edge=0.0, messages=0,
                        comm_frac=0.0)
    p.write_text(json.dumps(single) + "\n")
    assert run_check(p).returncode == 0
    # lines without the field (pre-round-19, script lines) still pass
    d = json.loads(json.dumps(GOOD_LINE))
    p.write_text(json.dumps(d) + "\n")
    assert run_check(p).returncode == 0


@pytest.mark.parametrize("over,needle", [
    # a digest from a ledger-failing build can never publish
    ({"errors": 1, "error": "CommLedgerError: oracle disagrees"},
     "LEDGER-FAILING"),
    # comm_frac is a fraction of one iteration by construction
    ({"comm_frac": 1.2}, "comm_frac"),
    ({"comm_frac": -0.1}, "comm_frac"),
    # a single device has no link to ship over
    ({"ndev": 1, "tier": "local"}, "SINGLE device"),
    ({"ndev": 1, "bytes_per_iter": 0, "comm_bytes_per_edge": 0.0,
      "comm_frac": 0.0, "tier": "ici"}, "no link tier"),
    # a mesh owner exchange cannot ship zero bytes
    ({"bytes_per_iter": 0, "comm_bytes_per_edge": 0.0,
      "comm_frac": 0.0}, "cannot ship zero bytes"),
    # per-edge must re-derive from the per-iteration bill
    ({"comm_bytes_per_edge": 0.5}, "contradicts the per-iteration"),
    ({"tier": "hyperloop"}, "comm.tier"),
    ({"bytes_per_iter": -3}, "bytes_per_iter"),
    ({"messages": True}, "comm.messages"),
])
def test_bad_comm_digests_fail(tmp_path, over, needle):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(_with_comm(**over)) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_null_comm_digest_rejected(tmp_path):
    d = json.loads(json.dumps(GOOD_LINE))
    d["comm"] = None
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert "comm digest is null" in r.stderr


# ---------------------------------------------------------------------
# round-22 memory digest (lux_tpu/memwatch.py, bench.py _mem_build)

GOOD_MEM = {"where": "PushEngine", "grade": "modeled",
            "peak_bytes": 1048576, "ledger_bytes": 1000000,
            "ratio": 1.0486, "tol": 0.5, "errors": 0, "warnings": 0}


def _with_mem(pop=(), **over):
    d = json.loads(json.dumps(GOOD_LINE))
    d["mem"] = {k: v for k, v in dict(GOOD_MEM, **over).items()
                if k not in pop}
    return d


def test_mem_digest_accepted(tmp_path):
    """A clean watermark-vs-ledger verdict passes strict mode; an
    explicitly-skipped digest (backend without AOT stats, or a
    padding-dominated shape under the check floor) passes with its
    warning; lines without the field (pre-round-22) still pass."""
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(_with_mem()) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr
    skipped = _with_mem(pop=("peak_bytes", "ratio"), warnings=1,
                        skipped="memory_analysis unavailable: axon")
    p.write_text(json.dumps(skipped) + "\n")
    assert run_check(p).returncode == 0
    measured = _with_mem(grade="measured")
    p.write_text(json.dumps(measured) + "\n")
    assert run_check(p).returncode == 0
    d = json.loads(json.dumps(GOOD_LINE))
    p.write_text(json.dumps(d) + "\n")
    assert run_check(p).returncode == 0


@pytest.mark.parametrize("over,needle", [
    # a drifting build can never publish
    ({"errors": 1, "error": "MemoryDriftError: ratio 2.07"},
     "DRIFTING"),
    # errors=0 alongside an error string is a self-contradiction
    ({"error": "boom"}, "cannot claim a clean bill"),
    ({"grade": "guessed"}, "mem.grade"),
    ({"peak_bytes": -1}, "mem.peak_bytes"),
    ({"ledger_bytes": "big"}, "mem.ledger_bytes"),
    ({"tol": 0}, "mem.tol"),
    ({"ratio": -2.0}, "mem.ratio"),
    # a ratio outside tolerance contradicts its own errors=0 claim
    ({"ratio": 3.0}, "contradicts its own clean verdict"),
    ({"ratio": 0.1}, "contradicts its own clean verdict"),
    # a withheld verdict must count as a warning
    ({"skipped": "below check floor", "warnings": 0},
     "must count as a warning"),
])
def test_bad_mem_digests_fail(tmp_path, over, needle):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(_with_mem(**over)) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_null_mem_digest_rejected(tmp_path):
    d = json.loads(json.dumps(GOOD_LINE))
    d["mem"] = None
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert "mem digest is null" in r.stderr


# round-22 weighted serve-live schema extension

def test_serve_live_weighted_line_passes(tmp_path):
    obj = json.loads(json.dumps(SERVE_LIVE_LINE))
    obj["weighted"] = True        # reweights=2 in the fixture
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda o: o.update(weighted=False), "UNWEIGHTED line"),
    (lambda o: o.update(weighted=True, reweights=0),
     "weighted headline"),
    (lambda o: o.update(weighted="yes"), "must be a bool"),
])
def test_bad_weighted_serve_live_lines_fail(tmp_path, mutate,
                                            needle):
    obj = json.loads(json.dumps(SERVE_LIVE_LINE))
    mutate(obj)
    r = _audit_one(tmp_path, obj)
    assert r.returncode == 1, "audit passed a bad weighted line"
    assert needle in r.stderr, r.stderr


# ---------------------------------------------------------------------
# round-23 MXU A/B lines (bench.py -config mxu-ab, ops/tiled.py)


def _mxu_line(mode="mxu", scale=16, np_=1, mxu_ns=176.0,
              vpu_ns=1008.0):
    d = json.loads(json.dumps(GOOD_LINE))
    d["metric"] = f"ppr_{mode}_comm{scale}_gteps_per_chip"
    d["np"] = np_
    d["batch"] = 8
    d["query_gteps"] = round(8 * d["value"], 4)
    d["per_query_edge_ns"] = round(1.0 / d["query_gteps"], 4)
    d["mxu"] = mode
    d["use_mxu"] = mode == "mxu"
    d["reduce_kind"] = "sum"
    d["mxu_row_ns"] = mxu_ns
    d["vpu_row_ns"] = vpu_ns
    d["page_fill"] = 41.4
    return d


def _mxu_pair(**kw):
    return [_mxu_line("mxu", **kw), _mxu_line("vpu", **kw)]


def test_mxu_pair_passes(tmp_path):
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in _mxu_pair()))
    r = run_check(p)
    assert r.returncode == 0, r.stderr


def test_lone_mxu_line_rejected(tmp_path):
    """An mxu line may only publish next to its paired vpu baseline —
    a lone MXU number has no step-change to show.  The vpu side
    stands alone fine (it IS a baseline)."""
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(_mxu_line("mxu")) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert "NO paired vpu baseline" in r.stderr
    p.write_text(json.dumps(_mxu_line("vpu")) + "\n")
    assert run_check(p).returncode == 0


def test_mxu_pair_cross_scale_or_np_not_paired(tmp_path):
    """Scale and num_parts are the pairing identity: a vpu line at a
    different shape is NOT the mxu line's baseline."""
    lines = [_mxu_line("mxu", scale=16), _mxu_line("vpu", scale=18)]
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 1 and "NO paired vpu baseline" in r.stderr
    lines = [_mxu_line("mxu", np_=1), _mxu_line("vpu", np_=2)]
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 1 and "NO paired vpu baseline" in r.stderr


def test_mxu_pair_model_disagreement_rejected(tmp_path):
    """Both sides stamp the modeled rates from ONE payload width; a
    disagreement means the lines are not the same experiment."""
    lines = [_mxu_line("mxu", mxu_ns=176.0),
             _mxu_line("vpu", mxu_ns=180.0)]
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 1
    assert "not one experiment" in r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda o: o.update(mxu="tensor"), "must be 'mxu' or 'vpu'"),
    # mode contradicting the metric name
    (lambda o: o.update(mxu="vpu", use_mxu=False),
     "contradicts the metric name's _mxu_"),
    # resolved engine flag contradicting the mode of record
    (lambda o: o.update(use_mxu=False),
     "the engine ran the other reduce path"),
    (lambda o: o.update(use_mxu="yes"), "must be a bool"),
    (lambda o: o.update(reduce_kind="prod"), "reduce_kind"),
    (lambda o: o.update(mxu_row_ns=0), "mxu_row_ns"),
    (lambda o: o.pop("vpu_row_ns"), "vpu_row_ns"),
    # identical models = the payload width was never resolved
    (lambda o: o.update(mxu_row_ns=1008.0), "no step-change"),
    (lambda o: o.update(page_fill=0.0), "page_fill"),
])
def test_bad_mxu_fields_fail(tmp_path, mutate, needle):
    lines = _mxu_pair()
    mutate(lines[0])
    p = tmp_path / "bench.jsonl"
    p.write_text("".join(json.dumps(d) + "\n" for d in lines))
    r = run_check(p)
    assert r.returncode == 1, "audit passed a bad mxu line"
    assert needle in r.stderr, r.stderr
