"""scripts/check_bench.py: bench metric-line schema audit.

Fast CPU checks: the historical BENCH_r01-05 artifacts audit clean
under -legacy-ok (and fail loudly without it — they predate the
round-6 attempts/discarded metadata), and synthetic good/bad
new-schema lines pass/fail as designed.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "check_bench.py"
ARTIFACTS = sorted(REPO.glob("BENCH_r0*.json"))

GOOD_LINE = {
    "metric": "pagerank_mp_rmat23_gteps_per_chip",
    "value": 0.1118, "unit": "GTEPS", "vs_baseline": 0.1118,
    "samples": [0.1116, 0.1118, 0.112],
    "attempts": 4, "discarded": [0.0107], "np": 4,
}


def run_check(*argv):
    return subprocess.run(
        [sys.executable, str(SCRIPT), *map(str, argv)],
        capture_output=True, text=True)


def test_current_artifacts_audit_clean_as_legacy():
    assert ARTIFACTS, "no BENCH_r*.json artifacts in the repo root"
    r = run_check("-legacy-ok", *ARTIFACTS)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout


def test_current_artifacts_fail_strict_schema():
    """Pre-round-6 lines lack attempts/discarded; the default (strict)
    mode must fail LOUDLY, naming the missing metadata."""
    r = run_check(*ARTIFACTS)
    assert r.returncode == 1
    assert "missing resilience metadata" in r.stderr
    assert "FAILED" in r.stderr


def test_good_new_schema_line_passes(tmp_path):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(GOOD_LINE) + "\n")
    r = run_check(p)
    assert r.returncode == 0, r.stderr


@pytest.mark.parametrize("mutate,needle", [
    (lambda d: d.pop("attempts"), "missing resilience metadata"),
    (lambda d: d.update(attempts=9), "inconsistent"),
    (lambda d: d.update(value=0.0107), "not the median"),
    (lambda d: d.update(samples=[]), "non-empty list"),
    (lambda d: d.pop("value"), "missing required key"),
    (lambda d: d.update(run_attempts=1), "run_attempts"),
    (lambda d: d.update(samples=[0.1116, 0.1118, 0.0107],
                        value=0.1116, attempts=4),
     "both samples and discarded"),
])
def test_bad_lines_fail(tmp_path, mutate, needle):
    d = dict(GOOD_LINE)
    mutate(d)
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(d) + "\n")
    r = run_check(p)
    assert r.returncode == 1
    assert needle in r.stderr


def test_failed_config_line_schema(tmp_path):
    good = {"metric": "sssp_FAILED", "error": "RuntimeError: worker",
            "attempts": 3, "failure_class": "retryable"}
    bad = {"metric": "sssp_FAILED", "error": "RuntimeError: worker"}
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(good) + "\n")
    assert run_check(p).returncode == 0
    p.write_text(json.dumps(bad) + "\n")
    r = run_check(p)
    assert r.returncode == 1 and "failure line missing" in r.stderr
    # legacy mode tolerates it (historical crash lines)
    assert run_check("-legacy-ok", p).returncode == 0


def test_unparseable_and_empty_inputs(tmp_path):
    p = tmp_path / "junk.jsonl"
    p.write_text('{"metric": broken\n')
    r = run_check(p)
    assert r.returncode == 1 and "unparseable" in r.stderr
    p.write_text("nothing here\n")
    r = run_check(p)
    assert r.returncode == 1 and "no metric lines" in r.stderr
