"""Push engine: SSSP/BFS and Connected Components vs NumPy oracles,
plus the fixed-point audits and the mesh path."""

import jax
import numpy as np
import pytest

from lux_tpu import check
from lux_tpu.apps import components, sssp
from lux_tpu.convert import rmat_edges, uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def chain_graph(n=10):
    """0 -> 1 -> ... -> n-1 plus an unreachable island {n, n+1}."""
    src = np.concatenate([np.arange(n - 1), [n]]).astype(np.uint32)
    dst = np.concatenate([np.arange(1, n), [n + 1]]).astype(np.uint32)
    return Graph.from_edges(src, dst, n + 2)


class TestSSSP:
    def test_chain_hops(self):
        g = chain_graph(10)
        dist, iters = sssp.run(g, start_vertex=0, num_parts=2)
        assert dist[:10].tolist() == list(range(10))
        assert sssp.unreachable(dist)[10:].all()
        assert iters == 10  # 9 propagation steps + 1 empty-frontier probe

    @pytest.mark.parametrize("num_parts", [1, 4])
    def test_random_matches_oracle(self, num_parts):
        src, dst = uniform_random_edges(250, 1800, seed=13)
        g = Graph.from_edges(src, dst, 250)
        dist, _ = sssp.run(g, start_vertex=3, num_parts=num_parts)
        want = sssp.reference_sssp(g, start_vertex=3)
        reach = ~sssp.unreachable(dist)
        np.testing.assert_array_equal(dist[reach], want[reach])
        assert np.array_equal(sssp.unreachable(dist),
                              want >= int(sssp.HOP_INF))

    def test_dense_only_app_passthrough(self):
        """The big-scale fit lever: apps expose enable_sparse=False /
        owner_tile_e (sssp/components.build_engine), dropping the
        src-sorted view; results must still match the oracle."""
        src, dst = uniform_random_edges(250, 1800, seed=13)
        g = Graph.from_edges(src, dst, 250)
        eng = sssp.build_engine(g, start_vertex=3, num_parts=2,
                                enable_sparse=False, exchange="owner",
                                owner_tile_e=128)
        assert eng.owner is not None and "src_ids" not in eng.arrays
        dist, _ = eng.run()
        want = sssp.reference_sssp(g, start_vertex=3)
        reach = ~sssp.unreachable(dist)
        np.testing.assert_array_equal(dist[reach], want[reach])

    def test_weighted_matches_oracle(self):
        src, dst, w = uniform_random_edges(120, 900, seed=21,
                                           weighted=True)
        g = Graph.from_edges(src, dst, 120, weights=w)
        dist, _ = sssp.run(g, start_vertex=0, num_parts=3, weighted=True)
        want = sssp.reference_sssp(g, start_vertex=0, weighted=True)
        np.testing.assert_allclose(dist, want.astype(np.float32),
                                   rtol=1e-6)

    @pytest.mark.parametrize("delta", ["auto", 2.5])
    def test_delta_stepping_matches_oracle(self, delta):
        src, dst, w = uniform_random_edges(120, 900, seed=22,
                                           weighted=True)
        g = Graph.from_edges(src, dst, 120, weights=w)
        dist, iters = sssp.run(g, start_vertex=0, num_parts=2,
                               weighted=True, delta=delta)
        want = sssp.reference_sssp(g, start_vertex=0, weighted=True)
        np.testing.assert_allclose(dist, want.astype(np.float32),
                                   rtol=1e-6)
        assert iters > 0

    def test_delta_stepping_mesh_matches_single(self, mesh8):
        src, dst, w = uniform_random_edges(200, 1400, seed=23,
                                           weighted=True)
        g = Graph.from_edges(src, dst, 200, weights=w)
        d1, _ = sssp.run(g, start_vertex=5, num_parts=1, weighted=True,
                         delta="auto")
        d8, _ = sssp.run(g, start_vertex=5, num_parts=8, mesh=mesh8,
                         weighted=True, delta="auto")
        np.testing.assert_allclose(d8, d1, rtol=1e-6)

    def test_delta_below_ulp_terminates(self):
        # Regression: with float32 labels and a bucket width below one
        # ulp at the current distance magnitude, active_min + delta
        # rounds back to active_min and the bucket advance used to
        # livelock inside the compiled while_loop (ADVICE round 1).
        # Weights ~1e8 with delta=1.0 reproduce it: 1.0 < ulp(1e8)=8.
        # max_iters caps only relax iterations, not advances, so a
        # regressed livelock would HANG here — fail via alarm instead.
        import signal

        def boom(signum, frame):
            raise TimeoutError("delta advance livelock regressed")

        old = signal.signal(signal.SIGALRM, boom)
        signal.alarm(120)
        try:
            src = np.array([0, 1, 2], np.uint32)
            dst = np.array([1, 2, 3], np.uint32)
            w = np.full(3, 1e8, np.float32)
            g = Graph.from_edges(src, dst, 4, weights=w)
            dist, _ = sssp.run(g, start_vertex=0, weighted=True,
                               delta=1.0, max_iters=100)
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
        np.testing.assert_allclose(
            dist, np.array([0, 1e8, 2e8, 3e8], np.float32), rtol=1e-6)

    def test_delta_rejects_max_program(self):
        from lux_tpu.engine.push import PushEngine
        g = chain_graph(6)
        from lux_tpu.graph import ShardedGraph
        sg = ShardedGraph.build(g, 1)
        from lux_tpu.apps.components import make_program
        with pytest.raises(ValueError, match="min"):
            PushEngine(sg, make_program(), delta=1.0)

    def test_check_task(self):
        src, dst = uniform_random_edges(150, 1000, seed=17)
        g = Graph.from_edges(src, dst, 150)
        dist, _ = sssp.run(g, start_vertex=0, num_parts=2)
        res = check.check_sssp(g, dist)
        assert res.ok, str(res)
        # a corrupted result must FAIL the audit: inflate the distance
        # of a vertex that has an in-edge from a reached vertex
        d64 = dist.astype(np.int64)
        s, t = g.edge_arrays()
        ok_edges = d64[s] < int(sssp.HOP_INF)
        victim = t[ok_edges][0]
        bad = dist.copy()
        bad[victim] = d64[s[ok_edges][0]] + 10
        assert not check.check_sssp(g, bad).ok

    def test_max_iters_cap(self):
        g = chain_graph(20)
        dist, iters = sssp.run(g, start_vertex=0, max_iters=3)
        assert iters == 3
        assert dist[3] == 3 and sssp.unreachable(dist)[6]

    def test_mesh_matches_single(self, mesh8):
        src, dst, nv = rmat_edges(scale=10, edge_factor=6, seed=6)
        g = Graph.from_edges(src, dst, nv)
        d1, i1 = sssp.run(g, start_vertex=1, num_parts=8)
        d8, i8 = sssp.run(g, start_vertex=1, num_parts=8, mesh=mesh8)
        np.testing.assert_array_equal(d1, d8)
        assert i1 == i8

    def test_verbose_stepwise_matches(self, capsys):
        g = chain_graph(5)
        d1, _ = sssp.run(g, start_vertex=0, num_parts=2, verbose=True)
        out = capsys.readouterr().out
        assert "frontier=" in out
        d2, _ = sssp.run(g, start_vertex=0, num_parts=2)
        np.testing.assert_array_equal(d1, d2)


class TestComponents:
    def test_two_islands(self):
        # undirected pairs: {0,1,2} and {3,4}
        src = np.array([0, 1, 3], dtype=np.uint32)
        dst = np.array([1, 2, 4], dtype=np.uint32)
        s, d = components.symmetrize(src, dst)
        g = Graph.from_edges(s, d, 5)
        labels, _ = components.run(g, num_parts=2)
        assert labels[0] == labels[1] == labels[2] == 2
        assert labels[3] == labels[4] == 4

    @pytest.mark.parametrize("num_parts", [1, 5])
    def test_random_matches_oracle(self, num_parts):
        src, dst = uniform_random_edges(300, 600, seed=31)
        s, d = components.symmetrize(src, dst)
        g = Graph.from_edges(s, d, 300)
        labels, _ = components.run(g, num_parts=num_parts)
        want = components.reference_components(g)
        np.testing.assert_array_equal(labels, want)
        assert check.check_components(g, labels).ok

    def test_mesh_matches_single(self, mesh8):
        src, dst = uniform_random_edges(400, 900, seed=33)
        s, d = components.symmetrize(src, dst)
        g = Graph.from_edges(s, d, 400)
        l1, _ = components.run(g, num_parts=8)
        l8, _ = components.run(g, num_parts=8, mesh=mesh8)
        np.testing.assert_array_equal(l1, l8)

    def test_check_catches_corruption(self):
        src = np.array([0, 1], dtype=np.uint32)
        dst = np.array([1, 0], dtype=np.uint32)
        g = Graph.from_edges(src, dst, 2)
        labels, _ = components.run(g)
        assert check.check_components(g, labels).ok
        assert not check.check_components(g, np.array([5, 0])).ok


def test_pagerank_residual_check():
    from lux_tpu.apps import pagerank
    src, dst = uniform_random_edges(100, 800, seed=41)
    g = Graph.from_edges(src, dst, 100)
    ranks = pagerank.run(g, 60, num_parts=2)
    assert check.check_pagerank(g, ranks, tol=1e-5).ok


def test_delta_rejects_nonpositive():
    src, dst, w = uniform_random_edges(60, 300, seed=30, weighted=True)
    g = Graph.from_edges(src, dst, 60, weights=w)
    with pytest.raises(ValueError, match="not > 0"):
        sssp.build_engine(g, 0, weighted=True, delta=0.0)
    # fractional delta on int32 hop labels truncates to 0 -> rejected
    with pytest.raises(ValueError, match="not > 0"):
        sssp.build_engine(g, 0, weighted=False, delta=0.5)


@pytest.mark.parametrize("app", ["sssp", "cc"])
def test_push_streamed_dense_matches_default(app):
    """stream_msgs=True (billion-edge memory mode) dense iterations
    must reach the same fixed point as the fused form."""
    from lux_tpu.apps import components, sssp
    from lux_tpu.convert import rmat_graph
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.graph import Graph, ShardedGraph

    g = rmat_graph(scale=9, edge_factor=8, seed=15)
    if app == "cc":
        s, d = components.symmetrize(*g.edge_arrays())
        g = Graph.from_edges(s, d, g.nv)
        prog = components.make_program()
        ref = components.reference_components(g)
    else:
        prog = sssp.make_program(0)
        ref = sssp.reference_sssp(g, 0)
    # disable sparse so every iteration exercises the DENSE streamed
    # path
    eng = PushEngine(ShardedGraph.build(g, 2), prog,
                     enable_sparse=False, stream_msgs=True)
    assert eng.stream_chunks
    label, active = eng.init_state()
    label, active, _ = eng.converge(label, active, 200)
    np.testing.assert_array_equal(
        eng.unpad(label).astype(np.int64), ref)
