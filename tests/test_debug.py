"""Failure-detection guards and multi-host helpers."""

import numpy as np
import pytest

from lux_tpu import debug
from lux_tpu.apps import pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph


def test_check_finite_passes_and_fails():
    debug.check_finite((np.ones(3), np.zeros(2, np.int32)))
    with pytest.raises(debug.DivergenceError, match="non-finite"):
        debug.check_finite((np.array([1.0, np.nan]),), where="x")


def test_run_guarded_matches_plain():
    src, dst = uniform_random_edges(80, 500, seed=71)
    g = Graph.from_edges(src, dst, 80)
    eng = pagerank.build_engine(g, num_parts=2)
    want = eng.unpad(eng.run(eng.init_state(), 9))
    got = eng.unpad(debug.run_guarded(eng, eng.init_state(), 9,
                                      segment=4))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_converge_guarded_matches_plain():
    src, dst = uniform_random_edges(150, 1100, seed=72)
    g = Graph.from_edges(src, dst, 150)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    want, _ = sssp.run(g, start_vertex=0, num_parts=2)
    got, iters = debug.converge_guarded(eng, segment=3)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])
    assert iters > 0


def test_converge_guarded_weighted_inf_ok():
    """+inf sentinel distances must NOT trip the divergence guard."""
    src, dst, w = uniform_random_edges(100, 600, seed=73, weighted=True)
    g = Graph.from_edges(src, dst, 100, weights=w)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2,
                            weighted=True)
    got, _ = debug.converge_guarded(eng, segment=2)
    want = sssp.reference_sssp(g, start_vertex=0, weighted=True)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-6)


def test_converge_guarded_chain_no_false_stall():
    """A path graph keeps frontier size 1 every iteration — progress
    must be detected from labels, not counts."""
    n = 40
    src = np.arange(n - 1, dtype=np.uint32)
    dst = np.arange(1, n, dtype=np.uint32)
    g = Graph.from_edges(src, dst, n)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=1)
    got, iters = debug.converge_guarded(eng, segment=3,
                                        stall_segments=3)
    assert got[n - 1] == n - 1 and iters >= n - 1


def test_multihost_single_process():
    from lux_tpu.parallel import multihost
    multihost.initialize()          # no-op without a coordinator
    mesh = multihost.global_mesh(4)
    assert mesh.devices.size == 4
    assert list(multihost.process_parts(8)) == list(range(8))
