"""lux_tpu/comms.py: the communication observatory (round 19).

The acceptance surface: the per-collective byte ledger of every
exchange mode agrees BITWISE with the independent NumPy message-count
oracle at ndev 1/2/8 (batched B > 1 included), a deliberately
mis-counted synthetic program raises the typed CommLedgerError, the
decompose comm verdict rides the telemetry trail through
events_summary cleanly, and the CLI round-trips.
"""

import functools
import json
import subprocess
import sys
import types
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lux_tpu import comms, observe, scalemodel, telemetry
from lux_tpu.graph import Graph

REPO = Path(__file__).resolve().parent.parent


def mk_graph(nv=256, ne=2048, weighted=False, seed=0):
    r = np.random.default_rng(seed)
    src = r.integers(0, nv, ne)
    dst = r.integers(0, nv, ne)
    w = (r.integers(1, 6, ne).astype(np.float32) if weighted
         else None)
    return Graph.from_edges(src, dst, nv, weights=w)


def mesh_of(n):
    from lux_tpu.parallel.mesh import make_mesh
    return make_mesh(n)


# ---------------------------------------------------------------------
# hop convention + tier classification

def test_shipped_bytes_convention():
    # ring algorithms, integer arithmetic (module docstring table)
    assert comms.shipped_bytes("ppermute", 1024, 4) == 1024
    assert comms.shipped_bytes("all_gather", 1000, 4) == 3000
    assert comms.shipped_bytes("reduce_scatter", 1024, 4) == 768
    assert comms.shipped_bytes("psum_scatter", 1024, 4) == 768
    assert comms.shipped_bytes("all_to_all", 1024, 4) == 768
    assert comms.shipped_bytes("psum", 4, 2) == 4      # RS + AG
    for prim in ("ppermute", "all_gather", "psum"):
        assert comms.shipped_bytes(prim, 4096, 1) == 0
    with pytest.raises(ValueError):
        comms.shipped_bytes("broadcast", 4, 2)


def test_mesh_tier_slice_topology():
    def fake_mesh(slice_ids):
        devs = np.array([types.SimpleNamespace(slice_index=s)
                         for s in slice_ids], dtype=object)
        return types.SimpleNamespace(devices=devs)

    assert comms.mesh_tier(None) == "local"
    assert comms.mesh_tier(fake_mesh([0, 0, 0, 0])) == "ici"
    assert comms.mesh_tier(fake_mesh([0, 0, 1, 1])) == "dcn"
    # CPU devices carry no slice_index: one slice, ici
    assert comms.mesh_tier(mesh_of(2)) == "ici"


# ---------------------------------------------------------------------
# ledger vs oracle, every exchange mode, ndev 1 / 2 / 8

def _mode_engines():
    """(label, engine) covering every exchange family the ISSUE
    names: owner psum_scatter / all_to_all / fused ring / pagemajor
    routing, the gather all_gather, sparse-queue branches, batched
    B > 1 — at ndev 1, 2 and 8."""
    from lux_tpu.apps import components, pagerank, sssp

    g = mk_graph()
    gs = mk_graph(512, 4096, seed=2)
    out = []
    out.append(("owner_sum_ndev1",
                pagerank.build_engine(g, num_parts=4,
                                      exchange="owner")))
    out.append(("gather_mesh2",
                pagerank.build_engine(g, num_parts=2,
                                      mesh=mesh_of(2))))
    out.append(("owner_sum_mesh2",
                pagerank.build_engine(g, num_parts=2, mesh=mesh_of(2),
                                      exchange="owner")))
    out.append(("owner_sum_mesh8",
                pagerank.build_engine(gs, num_parts=8,
                                      mesh=mesh_of(8),
                                      exchange="owner")))
    out.append(("owner_a2a_mesh2",
                components.build_engine(g, num_parts=2,
                                        mesh=mesh_of(2),
                                        exchange="owner")))
    out.append(("owner_a2a_dense_mesh2",
                components.build_engine(g, num_parts=2,
                                        mesh=mesh_of(2),
                                        exchange="owner",
                                        enable_sparse=False)))
    out.append(("owner_ring_mesh2",
                components.build_engine(g, num_parts=2,
                                        mesh=mesh_of(2),
                                        exchange="owner",
                                        owner_minmax_fused=True)))
    out.append(("owner_ring_mesh8",
                components.build_engine(gs, num_parts=8,
                                        mesh=mesh_of(8),
                                        exchange="owner",
                                        owner_minmax_fused=True)))
    out.append(("owner_pagemajor_mesh2",
                pagerank.build_engine(g, num_parts=2, mesh=mesh_of(2),
                                      exchange="owner",
                                      gather="pagemajor")))
    out.append(("sparse_gather_mesh2",
                sssp.build_engine(g, 0, num_parts=2,
                                  mesh=mesh_of(2))))
    # batched B > 1: the trailing query axis rides every payload
    out.append(("owner_sum_batched_mesh2",
                pagerank.build_engine(g, num_parts=2, mesh=mesh_of(2),
                                      sources=[0, 3, 7, 11],
                                      exchange="owner")))
    out.append(("ksssp_batched_mesh2",
                sssp.build_engine(g, num_parts=2, mesh=mesh_of(2),
                                  sources=[0, 3, 7, 11])))
    return out


@pytest.mark.parametrize("label_eng", _mode_engines(),
                         ids=lambda le: le[0])
def test_ledger_bitwise_equals_oracle(label_eng):
    label, eng = label_eng
    # ledger_for(check=True) raises CommLedgerError on ANY
    # disagreement; the explicit bitwise assertions pin the contract
    led = comms.ledger_for(eng, where=label)
    oracle = comms.oracle_for(eng)
    ob, om = comms._oracle_totals(oracle)
    assert led.bytes_per_iter == ob
    assert led.messages == om
    assert sorted(e.key() for e in led.entries) == \
        sorted(e.key() for e in oracle)
    if eng.ndev == 1:
        assert led.bytes_per_iter == 0 and not led.entries
        assert led.tier == "local"
    else:
        assert led.bytes_per_iter > 0
        assert led.tier == "ici"
        assert led.bytes_per_edge == pytest.approx(
            led.bytes_per_iter * eng.ndev / eng.sg.ne)


def test_mode_shapes_pinned():
    """The per-mode collective shapes of record: ring = ndev-1
    ppermute hops of the per-device chunk; sum = one reduce_scatter
    of the full contribution table; pagemajor = one all_to_all of
    [P_local, P, Mg, 128] message rows."""
    from lux_tpu.apps import components, pagerank

    g = mk_graph(512, 4096, seed=2)
    ring = components.build_engine(g, num_parts=8, mesh=mesh_of(8),
                                   exchange="owner",
                                   owner_minmax_fused=True)
    led = comms.ledger_for(ring)
    hops = [e for e in led.entries if e.prim == "ppermute"]
    assert len(hops) == 7                       # ndev - 1
    assert all(e.shape[0] == 1 for e in hops)   # [P/ndev, ntw]
    pm = pagerank.build_engine(mk_graph(), num_parts=2,
                               mesh=mesh_of(2), exchange="owner",
                               gather="pagemajor")
    led = comms.ledger_for(pm)
    (a2a,) = [e for e in led.entries if e.prim == "all_to_all"]
    Mg = int(pm.page_plan.route)
    assert a2a.shape == (1, 2, Mg, 128)
    assert a2a.shipped_bytes == a2a.payload_bytes // 2


def test_engine_comm_ledger_method():
    from lux_tpu.apps import pagerank
    eng = pagerank.build_engine(mk_graph(), num_parts=2,
                                mesh=mesh_of(2), exchange="owner")
    led = eng.comm_ledger()
    assert led.bytes_per_iter > 0
    with pytest.raises(KeyError, match="no registered program"):
        eng.audit_variant("definitely_not_a_variant")


def test_full_audit_matrix_ledgers():
    """The acceptance command's body: one oracle-checked ledger per
    audit-matrix config (the same engines the repo-wide audit
    traces), every mesh owner config shipping real bytes."""
    out = comms.run_matrix(emit_events=False)
    assert len(out) >= 30
    by = {d["config"]: d for d in out}
    assert by["pagerank_mesh2_owner_sum"]["bytes_per_iter"] > 0
    assert by["pagerank_np2_gather"]["bytes_per_iter"] == 0
    assert all(d["oracle_ok"] for d in out)
    # single-device configs ship nothing; mesh owner/gather configs
    # always ship something
    for d in out:
        if d["ndev"] == 1:
            assert d["bytes_per_iter"] == 0 and d["tier"] == "local"
        elif d["exchange"] in ("owner", "gather"):
            assert d["bytes_per_iter"] > 0


# ---------------------------------------------------------------------
# typed errors: the mis-counted synthetic program (test-pinned)

def _synthetic_ledger(n_collectives):
    mesh = mesh_of(2)
    P = jax.sharding.PartitionSpec

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("parts"),
                       out_specs=P("parts"))
    def prog(x):
        for _ in range(n_collectives):
            x = jax.lax.psum_scatter(
                x, "parts", scatter_dimension=0, tiled=True)
            x = jnp.concatenate([x, x], axis=0)
        return x

    closed = prog.trace(
        jax.ShapeDtypeStruct((8, 64), jnp.float32)).jaxpr
    return comms.ledger_of_jaxpr(closed, ndev=2, where="synthetic")


def test_miscounted_program_raises_typed_error():
    """A program running TWO reduce-scatters where the oracle expects
    one is exactly the double-exchange bug class the ledger exists to
    catch — typed CommLedgerError, with the disagreement itemized."""
    led2 = _synthetic_ledger(2)
    oracle1 = [e for e in led2.entries][:1]
    with pytest.raises(comms.CommLedgerError) as ei:
        comms.cross_check(led2, oracle1, where="synthetic")
    assert "disagrees with the NumPy oracle" in str(ei.value)
    assert ei.value.details
    # the honest single-collective program passes its own entries
    led1 = _synthetic_ledger(1)
    comms.cross_check(led1, list(led1.entries), where="synthetic")


def test_byte_total_mismatch_raises():
    led = _synthetic_ledger(1)
    wrong = [comms.CollectiveEntry(
        prim=e.prim, shape=e.shape, dtype=e.dtype,
        payload_bytes=e.payload_bytes,
        shipped_bytes=e.shipped_bytes + 4, mult=e.mult,
        tier=e.tier, branch=e.branch) for e in led.entries]
    with pytest.raises(comms.CommLedgerError, match="bytes_per_iter"):
        comms.cross_check(led, wrong)


def test_count_only_multiset_mismatch_raises():
    """A count-only disagreement with IDENTICAL byte totals (ledger
    2x key A vs oracle 1x A + 1x same-byte key B) must still raise —
    the multiset contract compares per-key counts, not totals."""
    led = _synthetic_ledger(2)
    e = led.entries[0]
    swapped = comms.CollectiveEntry(
        prim=e.prim, shape=e.shape, dtype="int32",
        payload_bytes=e.payload_bytes,
        shipped_bytes=e.shipped_bytes, mult=e.mult, tier=e.tier)
    oracle = [e, swapped]
    assert comms._oracle_totals(oracle)[0] == led.bytes_per_iter
    with pytest.raises(comms.CommLedgerError) as ei:
        comms.cross_check(led, oracle)
    assert "traced program carries 2x" in str(ei.value)


def test_audit_spec_contradiction_raises(monkeypatch):
    """A ledger whose eqn set violates the collective-schedule
    expectations (here: the auditor told to demand a ring the sum
    program does not run) raises the typed error — the two
    subsystems read one registry and must agree."""
    from lux_tpu import audit
    from lux_tpu.apps import pagerank

    eng = pagerank.build_engine(mk_graph(), num_parts=2,
                                mesh=mesh_of(2), exchange="owner")
    real = audit.engine_spec

    def fake_spec(e, aval):
        return audit.ProgramSpec(
            **{**real(e, aval).__dict__, "ppermute_hops": 1})

    monkeypatch.setattr(audit, "engine_spec", fake_spec)
    with pytest.raises(comms.CommLedgerError, match="ppermute"):
        comms.ledger_for(eng)


# ---------------------------------------------------------------------
# measured link calibration + scalemodel feed

def test_link_registry_and_projection_feed():
    assert scalemodel.link_bytes_per_s("ici") > 0
    assert scalemodel.link_bytes_per_s("dcn") == pytest.approx(
        scalemodel.link_bytes_per_s("ici")
        / scalemodel.DCN_THINNESS_MODEL)
    with pytest.raises(ValueError):
        scalemodel.link_bytes_per_s("local")
    with pytest.raises(ValueError):
        scalemodel.set_measured_link("ici", -1.0)
    try:
        scalemodel.set_measured_link("ici", 1e9)
        assert scalemodel.measured_link("ici") == 1e9
        # project_pull now prices comm from the measured figure
        slow = scalemodel.project_pull(1 << 24, 1 << 20, 8)
        scalemodel._MEASURED_LINKS.clear()
        fast = scalemodel.project_pull(1 << 24, 1 << 20, 8)
        assert slow.comm_s > fast.comm_s
    finally:
        scalemodel._MEASURED_LINKS.clear()


def test_calibrate_links_cpu_mesh_records_but_never_feeds():
    import itertools
    clk = itertools.count()
    scalemodel._MEASURED_LINKS.clear()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        links = observe.calibrate_links(
            payload_elems=(1 << 10,), repeats=2,
            clock=lambda: next(clk) * 1e-3)
    assert "ici" in links
    rec = links["ici"]
    assert rec["bytes_per_s"] > 0
    assert rec["prim"] == "ppermute"
    assert rec["fed_scalemodel"] is False       # CPU: labeled, not fed
    assert scalemodel.measured_link("ici") is None
    assert observe.link_rate("ici") == rec["bytes_per_s"]
    kinds = [e["kind"] for e in ev.events]
    assert "link_calibration" in kinds
    observe._LINKS.clear()


def test_dcn_probe_gated_on_single_slice():
    import dataclasses as dc
    fp = dc.replace(observe.calibrate(), platform="tpu", ndev=8)
    collected, skipped = observe.collect_debts(
        fp, None, only={"dcn-bandwidth-probe"})
    assert collected == []
    assert len(skipped) == 1
    did, reason = skipped[0]
    assert did == "dcn-bandwidth-probe"
    assert "gated" in reason and "slice" in reason


# ---------------------------------------------------------------------
# decompose comm verdict + events_summary round-trip

def test_decompose_comm_verdict_and_events(tmp_path):
    from lux_tpu.apps import pagerank

    evp = tmp_path / "ev.jsonl"
    ev = telemetry.EventLog(str(evp))
    fp = observe.calibrate()
    g = mk_graph()
    with telemetry.use(events=ev):
        # off-mesh: honestly no-comm
        d1 = observe.decompose(
            pagerank.build_engine(g, num_parts=2), "pagerank",
            iters=2, fingerprint=fp)
        # mesh owner engine with a measured session link rate: the
        # wire lower bound grades the gen_exchange phase
        observe.calibrate_links(payload_elems=(1 << 10,), repeats=2)
        d2 = observe.decompose(
            pagerank.build_engine(g, num_parts=2, mesh=mesh_of(2),
                                  exchange="owner"),
            "pagerank_mesh", iters=2, fingerprint=fp)
    ev.close()
    assert d1.comm["verdict"] == "no-comm"
    assert d1.comm["bytes_per_iter"] == 0
    assert d2.comm["bytes_per_iter"] > 0
    assert d2.comm["verdict"] in ("ok", "drift_fast")
    assert d2.comm["predicted_s"] is not None
    assert d2.comm["audit_eqns"] == {"reduce_scatter": 1}
    assert d1.as_dict()["comm"]["verdict"] == "no-comm"
    # the comm line renders in the human report
    rep = observe.render_report([d1, d2], fp)
    assert "comm: 0 B/iter" in rep
    assert "comm:" in rep and "[ici]" in rep
    # ... and the comm_ledger events render + audit clean through
    # events_summary (the acceptance criterion)
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "events_summary.py"), str(evp)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "comm ledger [pagerank_mesh]" in r.stdout
    assert "reduce_scatter" in r.stdout
    assert "link calibration [ici]" in r.stdout
    observe._LINKS.clear()


def test_tampered_comm_ledger_event_fails_summary(tmp_path):
    """events_summary FAILS a comm_ledger whose breakdown contradicts
    the audit eqn set it carries (the established contradiction-check
    pattern)."""
    evp = tmp_path / "ev.jsonl"
    good = {"t": 1.0, "tm": 1.0, "kind": "comm_ledger",
            "app": "pagerank", "exchange": "owner", "ndev": 2,
            "ne": 2048, "bytes_per_iter": 1024, "bytes_per_edge": 1.0,
            "messages": 1, "tier": "ici",
            "per_collective": [
                {"prim": "reduce_scatter", "branch": "", "count": 1,
                 "eqns": 1, "shipped_bytes": 1024,
                 "payload_bytes": 2048, "tier": "ici"}],
            "audit_eqns": {"reduce_scatter": 1}, "verdict": "ok"}
    evp.write_text(json.dumps(good) + "\n")
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "events_summary.py"), str(evp)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    bad = dict(good, audit_eqns={"reduce_scatter": 2})
    evp.write_text(json.dumps(bad) + "\n")
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "events_summary.py"), str(evp)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "contradict" in r.stderr

    bad2 = dict(good, per_collective=[
        dict(good["per_collective"][0], prim="broadcast")])
    evp.write_text(json.dumps(bad2) + "\n")
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "events_summary.py"), str(evp)],
        capture_output=True, text=True)
    assert r.returncode == 1
    assert "unknown collective" in r.stderr


# ---------------------------------------------------------------------
# tracing: per-collective spans inside exchange phases

def test_collective_spans_in_trace():
    from lux_tpu import tracing

    events = [
        {"t": 1.0, "tm": 1.0, "kind": "config_start",
         "config": "pagerank_mesh", "session": "s", "pid": 1},
        # a SECOND app's ledger in the same run: per-app matching
        # must keep its (huge) bytes out of pagerank_mesh's phases
        {"t": 1.2, "tm": 1.2, "kind": "comm_ledger",
         "app": "other_app", "ndev": 2, "tier": "ici",
         "bytes_per_iter": 1 << 30, "messages": 1, "session": "s",
         "pid": 1, "predicted_s": 9.0, "verdict": "ok",
         "per_collective": [
             {"prim": "all_to_all", "count": 1, "eqns": 1,
              "shipped_bytes": 1 << 30, "tier": "ici",
              "branch": ""}]},
        {"t": 1.5, "tm": 1.5, "kind": "comm_ledger",
         "app": "pagerank_mesh", "ndev": 2, "tier": "ici",
         "bytes_per_iter": 1024, "messages": 2, "session": "s",
         "pid": 1, "predicted_s": 0.004, "verdict": "ok",
         "per_collective": [
             {"prim": "reduce_scatter", "count": 1, "eqns": 1,
              "shipped_bytes": 768, "tier": "ici", "branch": ""},
             {"prim": "psum", "count": 1, "eqns": 1,
              "shipped_bytes": 256, "tier": "ici", "branch": ""},
             # two cond ALTERNATIVES: only the heavier branch is the
             # steady path predicted_s prices, so the lighter one
             # must not render as a span
             {"prim": "all_gather", "count": 1, "eqns": 1,
              "shipped_bytes": 512, "tier": "ici",
              "branch": "cond[5]#0"},
             {"prim": "pmin", "count": 1, "eqns": 1,
              "shipped_bytes": 4, "tier": "ici",
              "branch": "cond[5]#1"}]},
        {"t": 2.0, "tm": 2.0, "kind": "phases", "session": "s",
         "pid": 1, "app": "pagerank_mesh",
         "report": [{"gen_exchange": 0.01, "apply": 0.005}]},
    ]
    doc = tracing.trace_export(events)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {s["name"] for s in spans}
    assert "i0:gen_exchange" in names
    assert "i0:gen_exchange:reduce_scatter" in names
    assert "i0:gen_exchange:psum" in names
    # the other app's ledger and the lighter branch never render;
    # the heavier branch (the steady path) does
    assert "i0:gen_exchange:all_to_all" not in names
    assert "i0:gen_exchange:all_gather" in names
    assert "i0:gen_exchange:pmin" not in names
    # children lie inside the phase span, proportional to bytes
    ph = next(s for s in spans if s["name"] == "i0:gen_exchange")
    rs = next(s for s in spans
              if s["name"] == "i0:gen_exchange:reduce_scatter")
    ps = next(s for s in spans
              if s["name"] == "i0:gen_exchange:psum")
    assert ph["ts"] <= rs["ts"]
    assert rs["ts"] + rs["dur"] <= ph["ts"] + ph["dur"] + 2
    assert rs["dur"] == pytest.approx(3 * ps["dur"], rel=0.01)
    assert tracing.validate_trace(doc) == []
    # no priced wire time -> no collective spans (a guess must not
    # render as measurement)
    events[2] = dict(events[2], predicted_s=None)
    doc2 = tracing.trace_export(events)
    names2 = {e["name"] for e in doc2["traceEvents"]
              if e.get("ph") == "X"}
    assert "i0:gen_exchange:reduce_scatter" not in names2


# ---------------------------------------------------------------------
# bench digest + forecaster + CLI round-trip

def test_bench_digest_and_comm_fraction():
    from lux_tpu.apps import pagerank

    eng = pagerank.build_engine(mk_graph(), num_parts=2,
                                mesh=mesh_of(2), exchange="owner")
    led = comms.ledger_for(eng)
    d = comms.bench_digest(led, compute_ns=1e6)
    assert d["errors"] == 0
    assert d["ndev"] == 2 and d["exchange"] == "owner"
    assert d["bytes_per_iter"] == led.bytes_per_iter
    assert 0.0 <= d["comm_frac"] <= 1.0
    assert d["comm_bytes_per_edge"] == pytest.approx(
        led.bytes_per_iter * 2 / eng.sg.ne)
    # off-mesh: zero everything
    led1 = comms.ledger_for(
        pagerank.build_engine(mk_graph(), num_parts=2))
    d1 = comms.bench_digest(led1, compute_ns=1e6)
    assert d1["bytes_per_iter"] == 0 and d1["comm_frac"] == 0.0


def test_forecast_table_prices_quantization():
    t = comms.forecast_table(shapes=(("rmat21", 21, 16),),
                             chip_counts=(8,))
    assert "| shape | chips | thinness | quant |" in t
    rows = [ln for ln in t.splitlines() if ln.startswith("| rmat21")]
    assert len(rows) == 4 * 3          # thinness x quant
    # at every thinness, int8 ships fewer ms than bf16 than f32

    def ms(row):
        return float(row.split("|")[5])

    for i in range(0, len(rows), 3):
        f32, bf16, int8 = rows[i], rows[i + 1], rows[i + 2]
        assert ms(int8) < ms(bf16) < ms(f32)
    # quant factors themselves: int8 carries the block-scale overhead
    assert scalemodel.QUANT_FACTORS["int8"] == pytest.approx(0.28125)


def test_cli_roundtrip(tmp_path, capsys):
    evp = tmp_path / "ev.jsonl"
    rc = comms.main(["-configs", "pagerank_np2_gather",
                     "pagerank_mesh2_owner_sum",
                     "cc_mesh2_owner_ring",
                     "-events", str(evp)])
    out = capsys.readouterr().out
    assert rc == 0
    lines = [json.loads(ln) for ln in out.splitlines()
             if ln.startswith("{")]
    assert len(lines) == 3
    by = {d["config"]: d for d in lines}
    assert by["pagerank_np2_gather"]["bytes_per_iter"] == 0
    assert by["pagerank_mesh2_owner_sum"]["bytes_per_iter"] > 0
    ring = by["cc_mesh2_owner_ring"]
    prims = {g["prim"] for g in ring["per_collective"]}
    assert "ppermute" in prims
    # the emitted events render + audit clean
    r = subprocess.run(
        [sys.executable,
         str(REPO / "scripts" / "events_summary.py"), str(evp)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "comm ledger" in r.stdout


def test_cli_project_smoke(capsys):
    rc = comms.main(["-project"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "thinness" in out and "int8" in out
