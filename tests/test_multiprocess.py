"""Real multi-process execution: 2 jax.distributed processes on one
machine, 4 virtual CPU devices each — the TPU-native analogue of the
reference's multi-node GASNet runs (reference README.md:33-38) and the
"multi-node without a cluster" test the reference never shipped
(SURVEY.md §4).

The workers (tests/mp_worker.py) check sharded PageRank and SSSP runs
against the NumPy oracles, both with full host arrays and with
per-host partition loading (native.load_partition feeding
jax.make_array_from_process_local_data).
"""

import os
import re
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 2

# Capability gate: some XLA CPU builds cannot run multi-process
# collectives at all ("Multiprocess computations aren't implemented on
# the CPU backend" at the first cross-process all_gather) — a missing
# platform capability, not a lux_tpu regression.  When a worker dies
# with exactly that signature the test SKIPS (tier-1 stays green by
# construction); any other failure still fails loudly.
_CPU_MP_UNSUPPORTED = re.compile(
    r"[Mm]ultiprocess computations aren'?t implemented on the CPU "
    r"backend")


def test_two_process_engines(tmp_path):
    from lux_tpu import native
    from lux_tpu.convert import rmat_graph
    from lux_tpu.format import write_lux

    # build the native lib up front so the workers don't race `make`
    native.ensure_built()

    g = rmat_graph(scale=10, edge_factor=8, seed=3)
    path = str(tmp_path / "mp.lux")
    write_lux(path, g.row_ptrs, g.col_idx, degrees=g.out_degrees)

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    # Env must be set before python starts: jax reads JAX_PLATFORMS /
    # XLA_FLAGS at import time (and any TPU plugin in the parent env
    # must not leak into the CPU workers).
    env = dict(os.environ)
    env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    worker = os.path.join(REPO, "tests", "mp_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), str(NPROC), str(port), path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(NPROC)]
    try:
        outs = [p.communicate(timeout=600)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and _CPU_MP_UNSUPPORTED.search(out):
            pytest.skip("this jaxlib's CPU backend does not implement "
                        "multi-process computations (capability probe "
                        "hit the known XLA signature); the test is "
                        "meaningful only where the platform supports "
                        "CPU collectives")
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_OK pid={i}" in out, out
