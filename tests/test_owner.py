"""Owner-side exchange (ops/owner.py + PullEngine exchange='owner')
oracle tests — single device, mesh (psum_scatter and all_to_all
paths), pair composition, weighted programs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lux_tpu.apps import pagerank
from lux_tpu.convert import rmat_edges
from lux_tpu.engine.program import PullProgram
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph, pair_relabel
from lux_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def graph():
    src, dst, nv = rmat_edges(scale=9, edge_factor=8, seed=0)
    return Graph.from_edges(src, dst, nv)


@pytest.fixture(scope="module")
def ref5(graph):
    return pagerank.reference_pagerank(graph, 5)


def test_owner_single_device(graph, ref5):
    eng = PullEngine(ShardedGraph.build(graph, 4),
                     pagerank.make_program(), exchange="owner")
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_owner_single_part(graph, ref5):
    eng = PullEngine(ShardedGraph.build(graph, 1),
                     pagerank.make_program(), exchange="owner")
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_owner_with_pairs(graph):
    g2, _perm, starts = pair_relabel(graph, 4, pair_threshold=8)
    ref = pagerank.reference_pagerank(g2, 5)
    sg = ShardedGraph.build(g2, 4, starts=starts, pair_threshold=8)
    eng = PullEngine(sg, pagerank.make_program(), exchange="owner",
                     pair_threshold=8)
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-8)


def test_owner_mesh(graph, ref5):
    mesh = make_mesh(8)
    eng = PullEngine(ShardedGraph.build(graph, 8),
                     pagerank.make_program(), mesh=mesh,
                     exchange="owner")
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_owner_mesh_two_rows_per_device(graph, ref5):
    mesh = make_mesh(8)
    eng = PullEngine(ShardedGraph.build(graph, 16),
                     pagerank.make_program(), mesh=mesh,
                     exchange="owner")
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_owner_mesh_with_pairs(graph):
    g2, _perm, starts = pair_relabel(graph, 8, pair_threshold=8)
    ref = pagerank.reference_pagerank(g2, 5)
    mesh = make_mesh(8)
    sg = ShardedGraph.build(g2, 8, starts=starts, pair_threshold=8)
    eng = PullEngine(sg, pagerank.make_program(), mesh=mesh,
                     exchange="owner", pair_threshold=8)
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-8)


def _min_program():
    def edge_value(src_val, dst_val, weight):
        return src_val

    def apply(old, red, ctx):
        return jnp.minimum(old, red)

    def init(sg):
        rng = np.random.default_rng(0)
        return rng.random((sg.num_parts, sg.vpad)).astype(np.float32)

    return PullProgram(reduce="min", edge_value=edge_value, apply=apply,
                       init=init)


def test_owner_mesh_min_reduce(graph):
    """min-reduce rides the all_to_all (not psum_scatter) exchange."""
    mesh = make_mesh(8)
    eng = PullEngine(ShardedGraph.build(graph, 8), _min_program(),
                     mesh=mesh, exchange="owner")
    st0 = eng.init_state()
    st0h = np.asarray(jax.device_get(st0))
    out = eng.unpad(eng.step(st0))
    sg = eng.sg
    flat = np.full(graph.nv, np.inf)
    for p in range(sg.num_parts):
        v0, v1 = int(sg.starts[p]), int(sg.starts[p + 1])
        flat[v0:v1] = st0h[p, :v1 - v0]
    src, dst = graph.edge_arrays()
    acc = np.full(graph.nv, np.inf)
    np.minimum.at(acc, dst, flat[src])
    np.testing.assert_allclose(out, np.minimum(flat, acc), rtol=1e-6)


def _weighted_sum_program():
    def edge_value(src_val, dst_val, weight):
        return src_val * weight

    def apply(old, red, ctx):
        return red

    def init(sg):
        return np.ones((sg.num_parts, sg.vpad), np.float32)

    return PullProgram(reduce="sum", edge_value=edge_value, apply=apply,
                       init=init)


def test_owner_weighted():
    rng = np.random.default_rng(0)
    nv, ne = 500, 4000
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 6, ne).astype(np.int32)
    g = Graph.from_edges(src, dst, nv, weights=w)
    s2, d2 = g.edge_arrays()
    acc = np.zeros(nv)
    np.add.at(acc, d2, np.asarray(g.weights, np.float64))
    eng = PullEngine(ShardedGraph.build(g, 4), _weighted_sum_program(),
                     exchange="owner")
    out = eng.unpad(eng.step(eng.init_state()))
    np.testing.assert_allclose(out, acc, rtol=1e-6)


def test_owner_phases(graph):
    eng = PullEngine(ShardedGraph.build(graph, 4),
                     pagerank.make_program(), exchange="owner")
    state, report = eng.timed_phases(eng.init_state(), 2)
    assert len(report) == 2
    assert set(report[0]) == {"gen_exchange", "apply"}
    # the instrumented path computes the same state as the fused step
    fused = eng.run(eng.init_state(), 2)
    np.testing.assert_allclose(np.asarray(jax.device_get(state)),
                               np.asarray(jax.device_get(fused)),
                               rtol=1e-6)


def _hub_start(graph):
    src, _dst = graph.edge_arrays()
    return int(np.bincount(src, minlength=graph.nv).argmax())


def test_push_owner_dense_only(graph):
    """Dense iterations forced every step (enable_sparse=False): the
    whole convergence runs through the owner exchange."""
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine

    start = _hub_start(graph)
    want = sssp.reference_sssp(graph, start)
    eng = PushEngine(ShardedGraph.build(graph, 4),
                     sssp.make_program(start), enable_sparse=False,
                     exchange="owner")
    dist, iters = eng.run()
    assert iters > 1
    np.testing.assert_array_equal(dist.astype(np.int64), want)


def test_push_owner_sparse_mix(graph):
    """Adaptive sparse/dense switching with the owner dense branch."""
    from lux_tpu.apps import sssp

    start = _hub_start(graph)
    want = sssp.reference_sssp(graph, start)
    eng = sssp.build_engine(graph, start_vertex=start, num_parts=4,
                            exchange="owner")
    dist, _iters = eng.run()
    np.testing.assert_array_equal(dist.astype(np.int64), want)


def test_push_owner_mesh(graph):
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine

    start = _hub_start(graph)
    want = sssp.reference_sssp(graph, start)
    mesh = make_mesh(8)
    eng = PushEngine(ShardedGraph.build(graph, 8),
                     sssp.make_program(start), mesh=mesh,
                     enable_sparse=False, exchange="owner")
    dist, _iters = eng.run()
    np.testing.assert_array_equal(dist.astype(np.int64), want)


def test_push_owner_cc_with_pairs(graph):
    from lux_tpu.apps import components

    src, dst = graph.edge_arrays()
    s2, d2 = components.symmetrize(src, dst)
    gc = Graph.from_edges(s2, d2, graph.nv)
    want = components.reference_components(gc)
    g2, perm, starts = pair_relabel(gc, 4, pair_threshold=8)
    eng = components.build_engine(g2, num_parts=4, pair_threshold=8,
                                  starts=starts, exchange="owner")
    labels, _iters = eng.run()
    rank = np.empty(graph.nv, np.int64)
    rank[perm] = np.arange(graph.nv)

    def canon(lab):
        # canonical partition id: classes numbered by first occurrence
        # (label VALUES differ between spaces; the partition must not)
        _u, first, inv = np.unique(lab, return_index=True,
                                   return_inverse=True)
        return np.argsort(np.argsort(first))[inv]

    # same partition into components (labels live in relabeled space)
    np.testing.assert_array_equal(canon(labels[rank]), canon(want))


def test_push_owner_weighted(graph):
    from lux_tpu.apps import sssp

    src, dst = graph.edge_arrays()
    rng = np.random.default_rng(1)
    w = rng.integers(1, 6, len(src)).astype(np.int32)
    gw = Graph.from_edges(src, dst, graph.nv, weights=w)
    start = _hub_start(graph)
    want = sssp.reference_sssp(gw, start, weighted=True)
    eng = sssp.build_engine(gw, start_vertex=start, num_parts=4,
                            weighted=True, exchange="owner")
    dist, _iters = eng.run()
    np.testing.assert_allclose(dist, want)


def test_push_owner_phases(graph):
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine

    start = _hub_start(graph)
    eng = PushEngine(ShardedGraph.build(graph, 4),
                     sssp.make_program(start), enable_sparse=False,
                     exchange="owner")
    label, active = eng.init_state()
    _l, _a, rep = eng.timed_phases(label, active, 2)
    assert all("gen_exchange" in r for r in rep)


def test_owner_rejects_needs_dst(graph):
    prog = pagerank.make_program()
    bad = PullProgram(reduce=prog.reduce, edge_value=prog.edge_value,
                      apply=prog.apply, init=prog.init, needs_dst=True)
    with pytest.raises(ValueError, match="owner"):
        PullEngine(ShardedGraph.build(graph, 4), bad, exchange="owner")


def test_owner_layout_covers_every_edge(graph):
    """Structural audit: the layout's (src_local, gtile, rel) triples
    reproduce the exact edge multiset."""
    from lux_tpu.ops.owner import OwnerLayout

    sg = ShardedGraph.build(graph, 4)
    lay = OwnerLayout.build(sg, E=64, packed=False)
    got = []
    for s in range(sg.num_parts):
        for c in range(lay.n_chunks):
            lanes = lay.rel_dst[s, c] >= 0
            if not lanes.any():
                continue
            # chunk's tile: recover from last_chunk inverse is awkward;
            # use the chunk_start/tile walk instead
            got.append((s, c, lay.src_local[s, c][lanes],
                        lay.rel_dst[s, c][lanes]))
    n_edges = sum(len(x[2]) for x in got)
    assert n_edges == sg.ne


def test_resolve_exchange_auto(graph):
    """The auto rule: owner above the 96 MB state-table threshold for
    eligible programs; gather below it and for every ineligible
    shape (dst-dependent, dot-path, local-parts)."""
    import dataclasses

    from lux_tpu.engine.pull import OWNER_AUTO_BYTES, resolve_exchange

    sg = ShardedGraph.build(graph, 4)
    prog = pagerank.make_program()
    assert resolve_exchange("auto", sg, prog) == "gather"  # tiny table
    needed = OWNER_AUTO_BYTES // (sg.num_parts * 4) + 1
    big = dataclasses.replace(sg, vpad=needed)
    assert resolve_exchange("auto", big, prog) == "owner"
    # ineligible: dst-dependent edge values
    bad = PullProgram(reduce=prog.reduce, edge_value=prog.edge_value,
                      apply=prog.apply, init=prog.init, needs_dst=True)
    assert resolve_exchange("auto", big, bad) == "gather"
    # ineligible: dot-path programs
    dot = PullProgram(reduce=prog.reduce, edge_value=prog.edge_value,
                      apply=prog.apply, init=prog.init,
                      edge_value_from_dot=lambda s, d, w: s)
    assert resolve_exchange("auto", big, dot) == "gather"
    # push programs route through the same rule via their identity
    from lux_tpu.apps import sssp
    pprog = sssp.make_program(0)
    assert resolve_exchange("auto", big, pprog) == "owner"
    # explicit values pass through; unknowns raise
    assert resolve_exchange("gather", big, prog) == "gather"
    assert resolve_exchange("owner", sg, prog) == "owner"
    with pytest.raises(ValueError, match="unknown exchange"):
        resolve_exchange("bogus", sg, prog)
    # wide-payload programs declare state_bytes: the table estimate
    # sees the trailing dims and triggers owner K-times earlier
    wide = dataclasses.replace(prog, state_bytes=80)
    midpad = OWNER_AUTO_BYTES // (sg.num_parts * 80) + 1
    mid = dataclasses.replace(sg, vpad=midpad)
    assert resolve_exchange("auto", mid, prog) == "gather"
    assert resolve_exchange("auto", mid, wide) == "owner"


def test_owner_local_parts_build_matches_full(graph):
    """A single-process parts=range(P) build takes the multi-host
    path (_local_src_edges + allreduced geometry) and must produce
    byte-identical layout arrays to the full build: the edge stream
    visits dst parts in the same order the full build concatenates
    them (VERDICT r3 missing #3)."""
    from lux_tpu.ops.owner import OwnerLayout

    P = 8
    full = ShardedGraph.build(graph, P)
    loc = ShardedGraph.build(graph, P, parts=range(P))
    assert loc.local_parts is not None
    lay_f = OwnerLayout.build(full, E=64)
    lay_l = OwnerLayout.build(loc, E=64)
    assert (lay_f.n_chunks, lay_f.needs_scan, lay_f.G) == \
        (lay_l.n_chunks, lay_l.needs_scan, lay_l.G)
    assert lay_f.packed and lay_l.packed      # small vpad: auto-packed
    np.testing.assert_array_equal(lay_f.src_rel, lay_l.src_rel)
    np.testing.assert_array_equal(lay_f.n_valid, lay_l.n_valid)
    np.testing.assert_array_equal(lay_f.chunk_start, lay_l.chunk_start)
    np.testing.assert_array_equal(lay_f.last_chunk, lay_l.last_chunk)


def test_owner_local_parts_engine(graph, ref5):
    """exchange='owner' on a local-parts build (the multi-host code
    path, degenerate single-process cover) matches the oracle, and
    'auto' no longer silently degrades to gather there."""
    from lux_tpu.engine.pull import resolve_exchange

    mesh = make_mesh(8)
    sg = ShardedGraph.build(graph, 8, parts=range(8))
    eng = PullEngine(sg, pagerank.make_program(), mesh=mesh,
                     exchange="owner")
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)
    # the auto rule now treats local-parts builds as eligible
    import dataclasses
    from lux_tpu.engine.pull import OWNER_AUTO_BYTES
    big = dataclasses.replace(
        sg, vpad=OWNER_AUTO_BYTES // (sg.num_parts * 4) + 1)
    assert resolve_exchange("auto", big,
                            pagerank.make_program()) == "owner"


def test_owner_local_parts_push(graph):
    """The push engine's owner-side dense iterations on a local-parts
    build (components: max-reduce rides the all_to_all exchange)."""
    from lux_tpu.apps import components
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.graph import Graph as _G

    s, d = components.symmetrize(*graph.edge_arrays())
    g = _G.from_edges(s, d, graph.nv)
    want = components.reference_components(g)
    mesh = make_mesh(8)
    sg = ShardedGraph.build(g, 8, parts=range(8))
    eng = PushEngine(sg, components.make_program(), mesh=mesh,
                     exchange="owner", enable_sparse=False)
    label, active = eng.init_state()
    label, active, _it = eng.converge(label, active)
    np.testing.assert_array_equal(
        eng.unpad(label).astype(np.int64), want)


def test_owner_local_parts_rejects_partial_cover(graph):
    """A direct OwnerLayout.build on a local build whose rows do not
    cover every partition must fail loudly — uncovered parts' zero
    placeholders would otherwise be mistaken for real edges."""
    from lux_tpu.ops.owner import OwnerLayout

    sg = ShardedGraph.build(graph, 8, parts=range(4))
    with pytest.raises(ValueError, match="cover every"):
        OwnerLayout.build(sg, E=64)


def test_owner_fused_streamed_combine(graph, ref5, monkeypatch):
    """Force the fused streamed combine (streamed_chunk_combined):
    gather+message+partials+segmented combine+extraction in one scan,
    never materializing [C, W] — the RMAT27 HBM enabler (PERF_NOTES
    round 4).  Must match the unfused owner engine and the oracle."""
    import lux_tpu.ops.owner as owner_mod
    import lux_tpu.ops.tiled as tiled

    monkeypatch.setattr(owner_mod, "STREAM_MSG_BYTES", 1)
    monkeypatch.setattr(tiled, "STREAM_BLOCK_CHUNKS", 16)
    eng = PullEngine(ShardedGraph.build(graph, 4),
                     pagerank.make_program(), exchange="owner",
                     owner_tile_e=32)
    assert "own_ep" in eng.arrays          # fused path engaged
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_owner_fused_weighted_min(monkeypatch):
    """Fused combine with weights + min-reduce (all_to_all family)."""
    import lux_tpu.ops.owner as owner_mod
    import lux_tpu.ops.tiled as tiled
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine

    rng = np.random.default_rng(3)
    nv, ne = 600, 5000
    src = rng.integers(0, nv, ne)
    dst = rng.integers(0, nv, ne)
    w = rng.integers(1, 6, ne).astype(np.int32)
    g = Graph.from_edges(src, dst, nv, weights=w)
    deg = np.bincount(src, minlength=nv)
    hub = int(deg.argmax())
    want = sssp.reference_sssp(g, hub, weighted=True)

    monkeypatch.setattr(owner_mod, "STREAM_MSG_BYTES", 1)
    monkeypatch.setattr(tiled, "STREAM_BLOCK_CHUNKS", 16)
    eng = PushEngine(ShardedGraph.build(g, 4),
                     sssp.make_program(hub, weighted=True),
                     exchange="owner", enable_sparse=False,
                     owner_tile_e=32)
    assert "own_ep" in eng.arrays
    label, active = eng.init_state()
    label, active, _it = eng.converge(label, active)
    got = eng.unpad(label).astype(np.float64)
    got[~np.isfinite(want)] = np.inf
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_owner_fused_mesh(graph, ref5, monkeypatch):
    """Fused combine under shard_map (scan xs sharded over parts)."""
    import lux_tpu.ops.owner as owner_mod
    import lux_tpu.ops.tiled as tiled

    monkeypatch.setattr(owner_mod, "STREAM_MSG_BYTES", 1)
    monkeypatch.setattr(tiled, "STREAM_BLOCK_CHUNKS", 16)
    mesh = make_mesh(8)
    eng = PullEngine(ShardedGraph.build(graph, 8),
                     pagerank.make_program(), mesh=mesh,
                     exchange="owner", owner_tile_e=32)
    assert "own_ep" in eng.arrays
    out = eng.unpad(eng.run(eng.init_state(), 5))
    np.testing.assert_allclose(out, ref5, rtol=1e-5, atol=1e-8)


def test_packed_layout_decodes_to_classic(graph):
    """The packed uint32 encoding + live-lane counts must decode to
    exactly the classic (src_local, rel_dst) arrays."""
    import jax.numpy as jnp

    from lux_tpu.ops.owner import OwnerLayout
    from lux_tpu.ops.tiled import unpack_src_rel

    sg = ShardedGraph.build(graph, 4)
    classic = OwnerLayout.build(sg, E=64, packed=False)
    packed = OwnerLayout.build(sg, E=64, packed=True)
    assert packed.src_local is None and packed.rel_dst is None
    for r in range(4):
        src, rel = unpack_src_rel(jnp.asarray(packed.src_rel[r]),
                                  jnp.asarray(packed.n_valid[r]))
        np.testing.assert_array_equal(np.asarray(src),
                                      classic.src_local[r])
        np.testing.assert_array_equal(np.asarray(rel),
                                      classic.rel_dst[r])


@pytest.mark.parametrize("use_mesh", [False, True])
def test_packed_owner_engine_matches_unpacked(graph, ref5, use_mesh):
    """Pull engine results must be identical under the packed and
    classic owner encodings, single device and on the mesh."""
    mesh = make_mesh(8) if use_mesh else None
    P = 8 if use_mesh else 4
    from lux_tpu.ops import owner as owner_mod

    sg = ShardedGraph.build(graph, P)
    eng_p = PullEngine(sg, pagerank.make_program(), mesh=mesh,
                       exchange="owner")
    assert eng_p.owner.packed
    got = eng_p.unpad(eng_p.run(eng_p.init_state(), 5))
    np.testing.assert_allclose(got, ref5, rtol=2e-5, atol=1e-9)

    import unittest.mock as mock
    real_build = owner_mod.OwnerLayout.build.__func__
    with mock.patch.object(
            owner_mod.OwnerLayout, "build",
            classmethod(lambda cls, sg_, E=256, packed=None:
                        real_build(cls, sg_, E=E, packed=False))):
        eng_c = PullEngine(sg, pagerank.make_program(), mesh=mesh,
                           exchange="owner")
    assert not eng_c.owner.packed
    want = eng_c.unpad(eng_c.run(eng_c.init_state(), 5))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


# ---- fused (ring reduce-scatter) min/max exchange (round 8) ---------


def test_ring_reduce_scatter_matches_all_to_all():
    """owner_exchange(minmax_fused=True) — the psum_scatter-style ring
    that combines en route — must agree bitwise with the all_to_all +
    local-combine path AND the elementwise numpy reduce, for min and
    max, per-device-distinct inputs."""
    import functools

    from jax.sharding import PartitionSpec as P

    from lux_tpu.ops.owner import owner_exchange
    from lux_tpu.parallel.mesh import PARTS_AXIS

    mesh = make_mesh(8)
    ndev, Pn, ntw = 8, 16, 256
    rng = np.random.default_rng(7)
    acc = rng.random((ndev, Pn, ntw)).astype(np.float32)

    for kind in ("min", "max"):
        def body(a, fused, kind=kind):
            return owner_exchange(a.reshape(Pn, ntw), kind,
                                  axis=PARTS_AXIS, ndev=ndev,
                                  minmax_fused=fused)[None]

        run = functools.partial(jax.shard_map, mesh=mesh,
                                in_specs=P(PARTS_AXIS),
                                out_specs=P(PARTS_AXIS))
        want = np.asarray(run(lambda a: body(a, False))(acc))
        got = np.asarray(run(lambda a: body(a, True))(acc))
        np.testing.assert_array_equal(got.reshape(Pn, ntw),
                                      want.reshape(Pn, ntw))
        op = np.minimum if kind == "min" else np.maximum
        np.testing.assert_array_equal(want.reshape(Pn, ntw),
                                      op.reduce(acc, axis=0))


def test_owner_mesh_min_fused(graph):
    """Engine-level oracle: the fused min exchange reproduces the
    all_to_all engine's result on the 8-device mesh."""
    mesh = make_mesh(8)
    base = PullEngine(ShardedGraph.build(graph, 8), _min_program(),
                      mesh=mesh, exchange="owner")
    fused = PullEngine(ShardedGraph.build(graph, 8), _min_program(),
                       mesh=mesh, exchange="owner",
                       owner_minmax_fused=True)
    st = base.init_state()
    want = base.unpad(base.step(st))
    got = fused.unpad(fused.step(fused.init_state()))
    np.testing.assert_array_equal(got, want)


def test_push_owner_mesh_fused_minmax(graph):
    """cc/sssp inherit the fused exchange through PushEngine: a dense
    owner-mode sssp converge on the mesh with minmax_fused must match
    the reference distances."""
    from lux_tpu.apps import sssp
    from lux_tpu.engine.push import PushEngine

    start = _hub_start(graph)
    want = sssp.reference_sssp(graph, start)
    mesh = make_mesh(8)
    eng = PushEngine(ShardedGraph.build(graph, 8),
                     sssp.make_program(start), mesh=mesh,
                     enable_sparse=False, exchange="owner",
                     owner_minmax_fused=True)
    dist, _iters = eng.run()
    np.testing.assert_array_equal(dist.astype(np.int64), want)
