"""Per-iteration phase timing (engine.timed_phases + CLI -phases):
the instrumented phase-split step must advance state identically to
the fused step, on single device and the 8-device mesh, with and
without pair-lane delivery."""

import numpy as np
import pytest

from lux_tpu.convert import rmat_graph
from lux_tpu.graph import Graph, pair_relabel


@pytest.fixture(scope="module")
def graph():
    return rmat_graph(scale=9, edge_factor=8, seed=2)


def mesh8():
    from lux_tpu.parallel.mesh import make_mesh
    return make_mesh(8)


@pytest.mark.parametrize("np_mesh,pair", [((2, False), None),
                                          ((8, True), None),
                                          ((2, False), 4)])
def test_pull_phases_advance_like_step(graph, np_mesh, pair):
    from lux_tpu.apps import pagerank
    (num_parts, use_mesh) = np_mesh
    mesh = mesh8() if use_mesh else None
    g = graph
    starts = None
    if pair is not None:
        g, _perm, starts = pair_relabel(g, num_parts, pair_threshold=pair)
    eng = pagerank.build_engine(g, num_parts=num_parts, mesh=mesh,
                                pair_threshold=pair, starts=starts)
    want = eng.run(eng.init_state(), 3, fused=False)

    state, report = eng.timed_phases(eng.init_state(), iters=3)
    np.testing.assert_allclose(np.asarray(state), np.asarray(want),
                               rtol=1e-6)
    assert len(report) == 3
    for t in report:
        assert set(t) == {"exchange", "gather", "reduce", "apply"}
        assert all(v >= 0 for v in t.values())


def test_flat_dot_path_phases(graph):
    """Dot-path programs (colfilter) on the FLAT layout run the
    generic gather pipeline, so the generic phases time them — the
    round-4 stub raised NotImplementedError here."""
    from lux_tpu.apps import colfilter
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.graph import ShardedGraph

    rng = np.random.default_rng(3)
    g = graph
    gw = Graph(nv=g.nv, ne=g.ne, row_ptrs=g.row_ptrs, col_idx=g.col_idx,
               weights=rng.integers(1, 6, size=g.ne).astype(np.int32),
               out_degrees=g.out_degrees)
    sg = ShardedGraph.build(gw, 2)
    eng = PullEngine(sg, colfilter.make_program(), layout="flat")
    want = eng.run(eng.init_state(), 2, fused=False)

    state, report = eng.timed_phases(eng.init_state(), iters=2)
    np.testing.assert_allclose(np.asarray(state), np.asarray(want),
                               rtol=1e-6, atol=1e-8)
    for t in report:
        assert set(t) == {"exchange", "gather", "reduce", "apply"}


@pytest.mark.parametrize("use_mesh", [False, True])
def test_push_phases_reach_fixed_point(graph, use_mesh):
    from lux_tpu.apps import sssp
    mesh = mesh8() if use_mesh else None
    eng = sssp.build_engine(graph, start_vertex=0,
                            num_parts=8 if use_mesh else 2, mesh=mesh)
    label, active = eng.init_state()
    report_all = []
    for _ in range(200):
        label, active, rep = eng.timed_phases(label, active, iters=1)
        report_all += rep
        if rep[0]["frontier"] == 0:
            break
    ref = sssp.reference_sssp(graph, 0)
    np.testing.assert_array_equal(
        eng.unpad(label).astype(np.int64), ref)
    # small frontiers time as 'sparse'; big ones split into phases
    kinds = {frozenset(t) - {"frontier"} for t in report_all}
    assert frozenset(["sparse"]) in kinds
    phased = frozenset(["exchange", "relax", "reduce", "update"])
    assert any(k == phased for k in kinds) or all(
        t["frontier"] <= eng.queue_cap for t in report_all)


def test_cli_phases_flag(tmp_path, capsys, graph):
    from lux_tpu.format import write_lux
    from lux_tpu import cli
    path = str(tmp_path / "g.lux")
    write_lux(path, graph.row_ptrs, graph.col_idx,
              degrees=graph.out_degrees)
    rc = cli.main(["pagerank", "-file", path, "-ni", "2", "-phases", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gather=" in out and "apply=" in out
    rc = cli.main(["sssp", "-file", path, "-phases", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "frontier=" in out


def test_phases_streamed_engines(graph):
    """Streamed engines report the fused gather_reduce/relax_reduce
    phase and still advance state identically."""
    import numpy as np
    from lux_tpu.apps import pagerank, sssp
    from lux_tpu.engine.pull import PullEngine
    from lux_tpu.engine.push import PushEngine
    from lux_tpu.graph import ShardedGraph

    eng = PullEngine(ShardedGraph.build(graph, 2),
                     pagerank.make_program(), stream_msgs=True)
    want = eng.run(eng.init_state(), 2, fused=False)
    state, rep = eng.timed_phases(eng.init_state(), 2)
    np.testing.assert_allclose(np.asarray(state), np.asarray(want),
                               rtol=1e-6)
    assert set(rep[0]) == {"exchange", "gather_reduce", "apply"}

    p = PushEngine(ShardedGraph.build(graph, 2), sssp.make_program(0),
                   enable_sparse=False, stream_msgs=True)
    label, active = p.init_state()
    label, active, rep = p.timed_phases(label, active, 2)
    assert set(rep[0]) == {"frontier", "exchange", "relax_reduce",
                           "update"}


def test_dot_path_phases(graph):
    """Colfilter (edge_value_from_dot) phase timing — the round-2
    NotImplementedError hole, closed: exchange / dot_reduce / apply
    advance state exactly like the fused step."""
    from lux_tpu.apps import colfilter

    rng = np.random.default_rng(3)
    src, dst = graph.edge_arrays()
    w = rng.integers(1, 6, len(src)).astype(np.int32)
    g = Graph.from_edges(src, dst, graph.nv, weights=w)
    eng = colfilter.build_engine(g, num_parts=2)
    want = eng.run(eng.init_state(), 2, fused=False)
    state, rep = eng.timed_phases(eng.init_state(), 2)
    np.testing.assert_allclose(np.asarray(state), np.asarray(want),
                               rtol=1e-6)
    assert set(rep[0]) == {"exchange", "dot_reduce", "apply"}


def test_delta_phases_run_delta_schedule(graph):
    """A delta engine's timed_phases instruments the ACTUAL bucket
    schedule (round-2 observability hole): entries carry bucket/
    advances, and running it to convergence matches the oracle."""
    import jax

    from lux_tpu.apps import sssp

    rng = np.random.default_rng(4)
    src, dst = graph.edge_arrays()
    w = rng.integers(1, 6, len(src)).astype(np.int32)
    g = Graph.from_edges(src, dst, graph.nv, weights=w)
    start = int(np.bincount(src, minlength=g.nv).argmax())
    want = sssp.reference_sssp(g, start, weighted=True)
    eng = sssp.build_engine(g, start_vertex=start, num_parts=2,
                            weighted=True, delta="auto")
    label, active = eng.init_state()
    label, active, rep = eng.timed_phases(label, active, 500)
    assert all({"frontier", "bucket", "advances"} <= set(r)
               for r in rep)
    assert int(np.asarray(jax.device_get(active)).sum()) == 0
    np.testing.assert_allclose(eng.unpad(label), want)
