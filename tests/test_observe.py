"""lux_tpu/observe.py: the calibrated measurement subsystem.

CPU tier-1 coverage: deterministic-clock calibration fingerprinting,
MAD-based drift detection on synthetic fast/slow sessions, perf-ledger
append/validate round-trip, carried-debt matching/collection, the
observatory no-op proof (instrumentation never alters engine outputs
— the audit no-op proof pattern), and the repo-wide four-app CLI
smoke (the acceptance command: python -m lux_tpu.observe).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from lux_tpu import observe, telemetry
from lux_tpu.timing import loop_bench

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_calibration():
    """Tests that force-calibrate with fake clocks must not leak their
    fingerprint into the process cache other tests read."""
    saved = observe._FP
    observe._FP = None
    yield
    observe._FP = saved


def fake_clock(step_s: float):
    """Deterministic clock: every call advances by step_s, so a timed
    region spanning two calls always measures exactly step_s."""
    t = {"v": 0.0}

    def clock():
        t["v"] += step_s
        return t["v"]

    return clock


def synthetic_fp(platform="tpu", ndev=4, gather_ns=9.6,
                 session="feedc0ffee12"):
    """A Fingerprint without running the probe — for tests exercising
    grading/ledger/debt logic."""
    deviation = gather_ns / observe.CANONICAL["gather_small_ns"]
    return observe.Fingerprint(
        schema=observe.SCHEMA, session=session, pid=os.getpid(),
        backend=platform, platform=platform, ndev=ndev,
        probe={"gather_small_ns": gather_ns,
               "gather_small_mad_ns": 0.1,
               "pair_dot_row_ns": 120.0, "pair_dot_row_mad_ns": 2.0},
        canonical=dict(observe.CANONICAL), deviation=deviation,
        grade=observe._grade(platform, deviation),
        audit={"mode": "error", "errors": 0, "warnings": 0,
               "failed_checks": []})


# ---------------------------------------------------------------------
# pillar 1: calibration

def test_loop_bench_deterministic_clock():
    import jax.numpy as jnp

    def step(c):
        (x,) = c
        sv = jnp.sum(x)
        return sv, (x + sv * 1e-30,)

    samples, out = loop_bench(step, (jnp.ones(8),), k=4, repeats=3,
                              clock=fake_clock(0.02))
    # each repeat spans exactly one clock step: 0.02 s / 4 loop steps
    assert samples == [pytest.approx(0.005)] * 3
    assert out == pytest.approx(32.0)  # 4 steps x sum(ones(8)) = 32


def test_calibrate_deterministic_clock_fingerprint():
    step = 0.008                        # 8 ms per timed region
    fp = observe.calibrate(force=True, clock=fake_clock(step))
    want_gather = step / observe.PROBE_LOOP_K / observe.PROBE_GATHER_N \
        * 1e9
    assert fp.probe["gather_small_ns"] == pytest.approx(want_gather)
    assert fp.probe["gather_small_mad_ns"] == pytest.approx(0.0)
    want_dot = step / observe.PROBE_LOOP_K / observe.PROBE_DOT_ROWS \
        * 1e9
    assert fp.probe["pair_dot_row_ns"] == pytest.approx(want_dot)
    assert fp.deviation == pytest.approx(
        want_gather / observe.CANONICAL["gather_small_ns"])
    # the CPU test mesh has no canonical figures: labeled, not graded
    assert fp.platform == "cpu" and fp.grade == "uncalibrated"
    assert fp.session == telemetry.session_id()
    assert fp.ndev == 8 and fp.pid == os.getpid()
    # the probe programs satisfy the structural invariants they referee
    assert fp.audit["errors"] == 0
    # cached until forced
    assert observe.calibrate() is fp
    d = fp.digest()
    assert d["grade"] == "uncalibrated" and d["session"] == fp.session
    assert set(d["probe"]) == set(fp.probe)


def test_grades_and_session_scale():
    assert observe._grade("tpu", 1.0) == "canonical"
    assert observe._grade("axon", 2.9) == "canonical"
    assert observe._grade("tpu", 9.7) == "degraded"     # the 10x trap
    assert observe._grade("tpu", 0.2) == "degraded"     # lying-fast
    assert observe._grade("cpu", 1.0) == "uncalibrated"
    slow = synthetic_fp(gather_ns=96.0)                  # 10x session
    assert slow.grade == "degraded"
    assert observe.session_scale(slow) == pytest.approx(
        96.0 / observe.CANONICAL["gather_small_ns"])
    ok = synthetic_fp(gather_ns=9.6)
    assert ok.grade == "canonical"


def test_calibration_emits_event():
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        observe.calibrate(force=True, clock=fake_clock(0.008))
    kinds = ev.counts()
    assert kinds.get("calibration") == 1
    e = ev.events[-1]
    assert e["grade"] == "uncalibrated" and "probe" in e


def test_events_carry_monotonic_pid_session():
    ev = telemetry.EventLog()
    a = ev.emit("x")
    b = ev.emit("y")
    assert a["pid"] == b["pid"] == os.getpid()
    assert a["session"] == b["session"] == telemetry.session_id()
    assert b["tm"] >= a["tm"]


# ---------------------------------------------------------------------
# pillar 2: drift detection

def test_median_mad():
    m, mad = observe.median_mad([1.0, 2.0, 10.0])
    assert m == 2.0 and mad == 1.0
    with pytest.raises(ValueError):
        observe.median_mad([])


def test_drift_verdicts_fast_slow_sessions():
    # tight samples on the model: ok
    assert observe.drift_verdict([1.0, 1.01, 0.99], 1.0) == "ok"
    # the synthetic slow session: 10x the model with tight MAD
    assert observe.drift_verdict([10.0, 10.1, 9.9], 1.0) \
        == "drift_slow"
    # the synthetic fast session (model overshoots 10x)
    assert observe.drift_verdict([0.1, 0.1, 0.1], 1.0) == "drift_fast"
    # no model: honestly unmodeled, never a false drift
    assert observe.drift_verdict([1.0], None) == "unmodeled"
    assert observe.drift_verdict([1.0], 0.0) == "unmodeled"


def test_drift_bound_is_variance_aware():
    """Noisy samples widen the bound: a 6x ratio with a 5x-of-median
    MAD is NOT called drift (the variance says it could be noise),
    while the same ratio with tight samples IS."""
    noisy = [1.0, 6.0, 12.0]            # median 6, MAD 5
    assert observe.drift_verdict(noisy, 1.0) == "ok"
    tight = [6.0, 6.0, 6.0]
    assert observe.drift_verdict(tight, 1.0) == "drift_slow"


# ---------------------------------------------------------------------
# pillar 2: phase attribution + the no-op proof

def _tiny_pagerank():
    from lux_tpu.apps import pagerank
    from lux_tpu.convert import rmat_graph
    g = rmat_graph(scale=8, edge_factor=4, seed=0)
    return pagerank.build_engine(g, num_parts=1), g


def test_decompose_reports_and_is_a_noop():
    """The audit no-op proof pattern: running the observatory's phase
    attribution must not perturb the engine — a run after decompose is
    BITWISE identical to one before."""
    eng, _g = _tiny_pagerank()
    before = eng.unpad(eng.run(eng.init_state(), 3))
    fp = synthetic_fp()
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        d = observe.decompose(eng, "pagerank", iters=2, fingerprint=fp)
    after = eng.unpad(eng.run(eng.init_state(), 3))
    np.testing.assert_array_equal(before, after)

    assert d.app == "pagerank" and d.engine == "pull"
    assert d.session == fp.session
    names = {p.phase for p in d.phases}
    assert "apply" in names             # every pull split has apply
    allowed = {"ok", "drift_slow", "drift_fast", "unmodeled"}
    assert all(p.verdict in allowed for p in d.phases)
    assert all(len(p.samples) == 2 for p in d.phases)
    # every phase emitted its attribution event
    assert ev.counts().get("phase_cost") == len(d.phases)
    # report renders without error and names every phase
    rep = observe.render_report([d], fp)
    assert all(p.phase in rep for p in d.phases)
    # as_dict round-trips through JSON (ledger payload)
    assert json.loads(json.dumps(d.as_dict()))["app"] == "pagerank"


def test_decompose_push_engine():
    from lux_tpu.apps import components
    from lux_tpu.convert import rmat_graph
    from lux_tpu.graph import Graph
    g = rmat_graph(scale=8, edge_factor=4, seed=0)
    s, dst = components.symmetrize(*g.edge_arrays())
    eng = components.build_engine(Graph.from_edges(s, dst, g.nv))
    before, it0 = eng.run()
    d = observe.decompose(eng, "cc", iters=2,
                          fingerprint=synthetic_fp())
    after, it1 = eng.run()
    np.testing.assert_array_equal(before, after)
    assert it0 == it1
    assert d.engine == "push" and len(d.phases) > 0


# ---------------------------------------------------------------------
# pillar 3: ledger + debts

def test_ledger_append_validate_roundtrip(tmp_path):
    path = str(tmp_path / "PERFLEDGER.jsonl")
    led = observe.PerfLedger(path)
    fp = synthetic_fp()
    led.append("probe", {"probe": fp.probe}, fp)
    led.append("phase", {"app": "pagerank", "phases": []}, fp)
    led.append("bench", {"metric": "pagerank_gteps_per_chip",
                         "value": 0.17}, fp)
    led.append("debt", {"debt": "pair-dot-row-k-sweep"}, fp)
    assert observe.validate_ledger(path) == []
    recs = [r for _i, r, _e in observe.iter_ledger(path)]
    assert [r["kind"] for r in recs] == ["probe", "phase", "bench",
                                         "debt"]
    assert all(r["session"] == fp.session for r in recs)
    assert all(r["calibration"]["grade"] == "canonical" for r in recs)

    with pytest.raises(ValueError):
        led.append("vibes", {}, fp)


def test_ledger_validation_catches_rot(tmp_path):
    path = str(tmp_path / "led.jsonl")
    led = observe.PerfLedger(path)
    fp = synthetic_fp()
    led.append("probe", {"probe": fp.probe}, fp)
    with open(path, "a") as f:
        f.write("not json\n")
        f.write(json.dumps({"schema": 1, "kind": "bench",
                            "session": "x"}) + "\n")   # no calibration
        f.write(json.dumps({"schema": 1, "kind": "phase",
                            "session": "x",
                            "calibration": {"grade": "sideways",
                                            "deviation": 1.0}}) + "\n")
    errs = observe.validate_ledger(path)
    assert any("unparseable" in e for e in errs)
    assert any("missing calibration" in e for e in errs)
    assert any("grade" in e for e in errs)
    assert any("phases list" in e or "metric name" in e for e in errs)
    assert observe.validate_ledger(str(tmp_path / "led.jsonl")) == errs
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert observe.validate_ledger(str(empty)) == ["empty ledger"]


def test_debt_registry_matching():
    tpu4 = synthetic_fp(platform="tpu", ndev=4)
    ids = {d.id for d in observe.match_debts(tpu4)}
    assert ids == {d.id for d in observe.DEBTS}
    tpu1 = synthetic_fp(platform="tpu", ndev=1)
    ids1 = {d.id for d in observe.match_debts(tpu1)}
    assert "fused-exchange-ici-ab" not in ids1      # needs a mesh
    assert "elastic-shrink-drill" not in ids1
    assert "pagemajor-route-ab" not in ids1         # needs a mesh
    assert "pair-dot-row-k-sweep" in ids1
    # the CPU test mesh can collect no TPU-hardware debts — only the
    # platform-any probes: the reorder fill trail (round 16,
    # host-measured by construction) and the link-bandwidth sweep
    # (round 19 — measured anywhere, recorded with its fingerprint
    # label, fed into scalemodel only on canonical platforms)
    cpu_ids = {d.id for d in
               observe.match_debts(synthetic_fp(platform="cpu"))}
    assert cpu_ids == {"reorder-fill-ab", "ici-bandwidth-probe"}
    # the DCN probe is TPU-gated at the registry level AND slice-gated
    # inside its probe (a single-slice session must never record an
    # ICI rate wearing a DCN label)
    assert "dcn-bandwidth-probe" not in cpu_ids
    assert "dcn-bandwidth-probe" in ids


def test_collect_debts(tmp_path, monkeypatch):
    """Matched debts with an implemented probe are collected into the
    ledger; manual ones are skipped with their PERF_NOTES pointer."""
    monkeypatch.setattr(observe, "PROBE_DOT_ROWS", 8)
    monkeypatch.setattr(observe, "PROBE_PAGE_ROWS", 16)
    monkeypatch.setattr(observe, "PROBE_PAGE_TABLE", 8)
    monkeypatch.setattr(observe, "PROBE_LOOP_K", 2)
    path = str(tmp_path / "led.jsonl")
    fp = synthetic_fp(platform="tpu", ndev=4)
    collected, skipped = observe.collect_debts(
        fp, observe.PerfLedger(path),
        only={"pair-dot-row-k-sweep", "paged-gather-ab",
              "netflix-pair-run"})
    assert [c["debt"] for c in collected] == ["pair-dot-row-k-sweep",
                                              "paged-gather-ab"]
    sweep = collected[0]["sweep"]
    assert set(sweep) == {"1", "4", "8", "16", "20", "32"}
    assert all(v["row_ns"] >= 0 for v in sweep.values())
    ab = collected[1]
    assert ab["flat_ns_per_edge"] > 0 and ab["paged_ns_per_edge"] > 0
    assert ab["speedup"] == pytest.approx(
        ab["flat_ns_per_edge"] / ab["paged_ns_per_edge"], rel=1e-2)
    assert observe.validate_ledger(path) == []
    skipped_ids = {i for i, _r in skipped}
    assert "netflix-pair-run" in skipped_ids
    assert all("PERF_NOTES" in r for _i, r in skipped)


# ---------------------------------------------------------------------
# the acceptance command: repo-wide observatory smoke (tier-1)

def test_observe_cli_four_app_smoke(tmp_path, capsys):
    """python -m lux_tpu.observe emits a calibrated four-app phase
    report with drift verdicts, appends a validating ledger, and
    leaves an event log both validators accept."""
    led = tmp_path / "PERFLEDGER.jsonl"
    ev = tmp_path / "events.jsonl"
    rc = observe.main(["-scale", "8", "-ef", "4", "-iters", "2",
                       "-ledger", str(led), "-events", str(ev)])
    out = capsys.readouterr().out
    assert rc == 0
    for app in observe.APPS:
        assert f"== {app} " in out
    assert "grade=uncalibrated" in out          # CPU session, labeled
    assert "verdict" in out
    # one probe record + one phase record per app, all validating
    assert observe.validate_ledger(str(led)) == []
    kinds = [r["kind"] for _i, r, _e in observe.iter_ledger(str(led))]
    assert kinds == ["probe"] + ["phase"] * len(observe.APPS)
    # the event log renders in events_summary and audits clean
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "events_summary.py"),
         str(ev)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "calibration:" in r.stdout


def test_observe_cli_debt_listing_is_read_only(tmp_path, capsys,
                                               monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = observe.main(["-debts"])
    out = capsys.readouterr().out
    assert rc == 0
    # CPU session: the only matching debt is the platform-any
    # reorder fill trail (host-measured; round 16) — no hardware
    # debts are listed
    assert "debt reorder-fill-ab" in out
    assert "paged-gather-ab" not in out
    # a pure listing never grows the append-only ledger
    assert not (tmp_path / observe.LEDGER_DEFAULT).exists()


# ---------------------------------------------------------------------
# bench.py artifact self-writing (the empty-trajectory fix)

def _load_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_artifact_numbering_and_schema(tmp_path):
    bench = _load_bench()
    assert bench.next_artifact_path(str(tmp_path)).endswith(
        "BENCH_r01.json")
    (tmp_path / "BENCH_r05.json").write_text("{}")
    (tmp_path / "BENCH_r07.json").write_text("{}")
    path = bench.next_artifact_path(str(tmp_path))
    assert path.endswith("BENCH_r08.json")

    line = {"metric": "pagerank_rmat21_gteps_per_chip", "value": 0.17,
            "unit": "GTEPS", "vs_baseline": 0.17, "samples": [0.17],
            "attempts": 1, "discarded": [], "ne": 10,
            "telemetry": {"runs": [{"repeat": 0, "iters": 1,
                                    "seconds": 1.0}],
                          "counters": None},
            "calibration": synthetic_fp().digest()}
    bench.write_artifact(path, [line], line["calibration"], 0,
                         ["-config", "pagerank"])
    doc = json.loads(Path(path).read_text())
    assert doc["round"] == 8
    assert doc["calibration"]["grade"] == "canonical"
    # the artifact audits clean under the strict check_bench schema
    # ... except the telemetry re-derivation: ne*iters/seconds must
    # hit the sample — make it consistent above: 10*1/1.0/1e9 != 0.17
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_bench.py"),
         path], capture_output=True, text=True)
    assert "matches no recorded sample" in r.stderr


def test_bench_artifact_consistent_line_passes(tmp_path):
    bench = _load_bench()
    ne, iters, secs = 10**9, 10, 58.8235
    g = ne * iters / secs / 1e9
    line = {"metric": "pagerank_rmat21_gteps_per_chip",
            "value": round(g, 4), "unit": "GTEPS",
            "vs_baseline": round(g, 4), "samples": [round(g, 4)],
            "attempts": 1, "discarded": [], "ne": ne,
            "telemetry": {"runs": [{"repeat": 0, "iters": iters,
                                    "seconds": secs}],
                          "counters": None},
            "calibration": synthetic_fp().digest()}
    path = str(tmp_path / "BENCH_r09.json")
    bench.write_artifact(path, [line], line["calibration"], 0, [])
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_bench.py"),
         path], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
