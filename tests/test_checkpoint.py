"""Checkpoint/resume and profiling utilities."""

import os

import numpy as np

from lux_tpu import checkpoint as ckpt
from lux_tpu.apps import pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.profiling import PhaseTimer


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "c.npz")
    state = (np.arange(6, dtype=np.float32).reshape(2, 3),
             np.array([True, False]))
    ckpt.save(p, state, {"iter": 7})
    leaves, meta = ckpt.load(p)
    assert meta == {"iter": 7}
    np.testing.assert_array_equal(leaves[0], state[0])
    np.testing.assert_array_equal(leaves[1], state[1])


def test_pull_checkpointed_matches_plain(tmp_path):
    src, dst = uniform_random_edges(100, 700, seed=61)
    g = Graph.from_edges(src, dst, 100)
    eng = pagerank.build_engine(g, num_parts=2)
    p = str(tmp_path / "pr.npz")

    want = eng.unpad(eng.run(eng.init_state(), 10))
    got_state = ckpt.run_checkpointed(eng, eng.init_state(), 10, p,
                                      segment=3)
    np.testing.assert_allclose(eng.unpad(got_state), want, rtol=1e-6)
    leaves, meta = ckpt.load(p)
    assert meta["iter"] == 10
    # resume from the iteration-6 structure: load and continue
    (state_arr,), meta = ckpt.load(p)
    assert np.isfinite(state_arr).all()


def test_push_converge_checkpointed_resume(tmp_path):
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    p = str(tmp_path / "ss.npz")

    want, _ = sssp.run(g, start_vertex=0, num_parts=2)

    # run only 2 iterations' worth of segments, then "crash"
    l, a, total = ckpt.converge_checkpointed(eng, p, segment=2,
                                             max_iters=2)
    assert os.path.exists(p) and total == 2
    # resume to convergence
    l, a, total = ckpt.converge_checkpointed(eng, p, segment=3,
                                             resume=True)
    got = eng.unpad(l)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])


def test_phase_timer(capsys):
    pt = PhaseTimer()
    with pt.phase("a"):
        pass
    with pt.phase("b", fence=np.zeros(3)):
        pass
    phases = pt.report()
    out = capsys.readouterr().out
    assert "a" in out and "total" in out
    # round 7: report() RETURNS the phases list so callers consume
    # the data instead of re-parsing stdout
    assert [name for name, _t in phases] == ["a", "b"]
    assert all(t >= 0 for _n, t in phases)
