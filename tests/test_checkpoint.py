"""Checkpoint/resume and profiling utilities (+ round-9 integrity:
per-leaf CRC32 verification, two-generation rotation, and the
corrupt-newest-generation fallback path)."""

import os

import numpy as np
import pytest

from lux_tpu import checkpoint as ckpt
from lux_tpu.apps import pagerank, sssp
from lux_tpu.convert import uniform_random_edges
from lux_tpu.graph import Graph
from lux_tpu.profiling import PhaseTimer


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "c.npz")
    state = (np.arange(6, dtype=np.float32).reshape(2, 3),
             np.array([True, False]))
    ckpt.save(p, state, {"iter": 7})
    leaves, meta = ckpt.load(p)
    assert meta == {"iter": 7}
    np.testing.assert_array_equal(leaves[0], state[0])
    np.testing.assert_array_equal(leaves[1], state[1])


def test_pull_checkpointed_matches_plain(tmp_path):
    src, dst = uniform_random_edges(100, 700, seed=61)
    g = Graph.from_edges(src, dst, 100)
    eng = pagerank.build_engine(g, num_parts=2)
    p = str(tmp_path / "pr.npz")

    want = eng.unpad(eng.run(eng.init_state(), 10))
    got_state = ckpt.run_checkpointed(eng, eng.init_state(), 10, p,
                                      segment=3)
    np.testing.assert_allclose(eng.unpad(got_state), want, rtol=1e-6)
    leaves, meta = ckpt.load(p)
    assert meta["iter"] == 10
    # resume from the iteration-6 structure: load and continue
    (state_arr,), meta = ckpt.load(p)
    assert np.isfinite(state_arr).all()


def test_push_converge_checkpointed_resume(tmp_path):
    src, dst = uniform_random_edges(200, 1500, seed=62)
    g = Graph.from_edges(src, dst, 200)
    eng = sssp.build_engine(g, start_vertex=0, num_parts=2)
    p = str(tmp_path / "ss.npz")

    want, _ = sssp.run(g, start_vertex=0, num_parts=2)

    # run only 2 iterations' worth of segments, then "crash"
    l, a, total = ckpt.converge_checkpointed(eng, p, segment=2,
                                             max_iters=2)
    assert os.path.exists(p) and total == 2
    # resume to convergence
    l, a, total = ckpt.converge_checkpointed(eng, p, segment=3,
                                             resume=True)
    got = eng.unpad(l)
    reach = ~sssp.unreachable(got)
    np.testing.assert_array_equal(got[reach], want[reach])


# -- integrity + generation fallback (round 9) -------------------------

def test_save_rotates_two_generations(tmp_path):
    p = str(tmp_path / "g.npz")
    state = (np.arange(4, dtype=np.float32),)
    ckpt.save(p, state, {"iter": 1})
    assert not os.path.exists(ckpt.prev_path(p))
    ckpt.save(p, state, {"iter": 2})
    assert ckpt.load(p)[1]["iter"] == 2
    assert ckpt.load(ckpt.prev_path(p))[1]["iter"] == 1
    ckpt.save(p, state, {"iter": 3})
    assert ckpt.load(ckpt.prev_path(p))[1]["iter"] == 2   # rolls
    assert ckpt.any_generation(p)
    ckpt.remove(p)
    assert not ckpt.any_generation(p)


def test_load_catches_bitflip(tmp_path):
    """A zip-valid payload bit flip — exactly what the container's own
    member CRC canNOT catch — fails the per-leaf CRC32."""
    from lux_tpu import faults

    p = str(tmp_path / "c.npz")
    ckpt.save(p, (np.arange(8, dtype=np.float32),), {"iter": 3})
    faults.bitflip_checkpoint(p)
    with pytest.raises(ckpt.CorruptCheckpointError, match="CRC32"):
        ckpt.load(p)


def test_load_wraps_truncated_and_garbage(tmp_path):
    """Truncated/garbage containers raise the TYPED error (never a
    raw zipfile.BadZipFile / KeyError), so resilience.classify routes
    them to generation fallback, not the deterministic-OSError fatal
    bucket.  A MISSING file stays FileNotFoundError."""
    from lux_tpu import faults

    p = str(tmp_path / "c.npz")
    ckpt.save(p, (np.arange(8, dtype=np.float32),), {"iter": 3})
    faults.truncate_checkpoint(p)
    with pytest.raises(ckpt.CorruptCheckpointError):
        ckpt.load(p)
    with open(p, "w") as f:
        f.write("not a checkpoint at all")
    with pytest.raises(ckpt.CorruptCheckpointError):
        ckpt.load(p)
    with pytest.raises(FileNotFoundError):
        ckpt.load(str(tmp_path / "never.npz"))


def test_load_any_falls_back_one_generation(tmp_path):
    from lux_tpu import faults, telemetry

    p = str(tmp_path / "c.npz")
    state = (np.arange(8, dtype=np.float32),)
    ckpt.save(p, state, {"iter": 5})
    ckpt.save(p, state, {"iter": 10})
    faults.bitflip_checkpoint(p)
    ev = telemetry.EventLog()
    with telemetry.use(events=ev):
        leaves, meta, used = ckpt.load_any(p)
    assert meta["iter"] == 5 and used == ckpt.prev_path(p)
    np.testing.assert_array_equal(leaves[0], state[0])
    fb = [e for e in ev.events if e["kind"] == "checkpoint_fallback"]
    assert len(fb) == 1 and fb[0]["path"] == p
    # the corrupt newest is QUARANTINED: a repeat load_any reads the
    # good generation without re-reporting, and the next save's
    # rotation cannot promote the corrupt file over the good one
    assert not os.path.exists(p) and os.path.exists(ckpt.corrupt_path(p))
    with telemetry.use(events=ev):
        _l, meta2, _u = ckpt.load_any(p)
    assert meta2["iter"] == 5
    assert sum(e["kind"] == "checkpoint_fallback"
               for e in ev.events) == 1
    ckpt.save(p, state, {"iter": 20})
    assert ckpt.load(p)[1]["iter"] == 20
    assert ckpt.load(ckpt.prev_path(p))[1]["iter"] == 5   # still good
    ckpt.remove(p)
    assert not os.path.exists(ckpt.corrupt_path(p))


def test_load_any_both_generations_corrupt_raises(tmp_path):
    from lux_tpu import faults

    p = str(tmp_path / "c.npz")
    state = (np.arange(8, dtype=np.float32),)
    ckpt.save(p, state, {"iter": 5})
    ckpt.save(p, state, {"iter": 10})
    faults.bitflip_checkpoint(p)
    faults.truncate_checkpoint(ckpt.prev_path(p))
    with pytest.raises(ckpt.CorruptCheckpointError):
        ckpt.load_any(p)


def test_resume_falls_back_and_replays_lost_segment(tmp_path):
    """run_checkpointed resume with a corrupt newest generation: falls
    back to .prev and re-runs the lost iterations — the result is
    BITWISE the uninterrupted run's."""
    from lux_tpu import faults
    from lux_tpu.convert import uniform_random_edges as ure

    src, dst = ure(100, 700, seed=61)
    g = Graph.from_edges(src, dst, 100)
    eng = pagerank.build_engine(g, num_parts=2)
    p = str(tmp_path / "pr.npz")
    want = eng.unpad(eng.run(eng.init_state(), 10))

    ckpt.run_checkpointed(eng, eng.init_state(), 10, p, segment=3)
    # newest generation (iter 10) corrupt -> resume replays from 9
    faults.bitflip_checkpoint(p)
    got = ckpt.run_checkpointed(eng, eng.init_state(), 10, p,
                                segment=3, resume=True)
    np.testing.assert_array_equal(eng.unpad(got), want)
    assert ckpt.load(p)[1]["iter"] == 10   # re-saved clean


def test_phase_timer(capsys):
    pt = PhaseTimer()
    with pt.phase("a"):
        pass
    with pt.phase("b", fence=np.zeros(3)):
        pass
    phases = pt.report()
    out = capsys.readouterr().out
    assert "a" in out and "total" in out
    # round 7: report() RETURNS the phases list so callers consume
    # the data instead of re-parsing stdout
    assert [name for name, _t in phases] == ["a", "b"]
    assert all(t >= 0 for _n, t in phases)
