"""Property tests for the edge-balanced partitioner (SURVEY.md §7 step 2)."""

import numpy as np
import pytest

from lux_tpu.convert import edges_to_csc, rmat_edges, uniform_random_edges
from lux_tpu.partition import (edge_balanced_bounds, frontier_capacity,
                               part_edge_counts)


def _row_ptrs(nv, ne, seed=0):
    src, dst = uniform_random_edges(nv, ne, seed=seed)
    rp, _, _, _ = edges_to_csc(src, dst, nv)
    return rp


@pytest.mark.parametrize("num_parts", [1, 2, 3, 8, 17])
def test_partition_invariants(num_parts):
    rp = _row_ptrs(500, 4000)
    starts = edge_balanced_bounds(rp, num_parts)
    assert starts[0] == 0 and starts[-1] == 500
    assert np.all(np.diff(starts) >= 1)
    counts = part_edge_counts(rp, starts)
    assert counts.sum() == 4000


def test_edge_balance_quality():
    """On a skew-free graph, no part should exceed ~2x the ideal load."""
    rp = _row_ptrs(10_000, 200_000)
    starts = edge_balanced_bounds(rp, 16)
    counts = part_edge_counts(rp, starts)
    ideal = 200_000 / 16
    assert counts.max() <= 2 * ideal


def test_skewed_degrees_rmat():
    """Power-law graph: partitioner must stay balanced despite hubs."""
    src, dst, nv = rmat_edges(scale=12, edge_factor=8, seed=1)
    rp, _, _, _ = edges_to_csc(src, dst, nv)
    starts = edge_balanced_bounds(rp, 8)
    counts = part_edge_counts(rp, starts)
    # a single hub vertex can exceed the ideal, but each part should not
    # exceed ideal + max single-vertex in-degree
    in_deg = np.diff(np.concatenate(([0], rp))).max()
    assert counts.max() <= rp[-1] / 8 + in_deg


def test_degenerate_single_hub():
    """All edges into one vertex: every part still gets >= 1 vertex."""
    nv, ne = 64, 1000
    dst = np.zeros(ne, dtype=np.uint32)
    src = np.arange(ne, dtype=np.uint32) % nv
    rp, _, _, _ = edges_to_csc(src, dst, nv)
    starts = edge_balanced_bounds(rp, 8)
    assert np.all(np.diff(starts) >= 1)
    assert starts[-1] == nv


def test_num_parts_bounds():
    rp = _row_ptrs(10, 50)
    with pytest.raises(ValueError):
        edge_balanced_bounds(rp, 0)
    with pytest.raises(ValueError):
        edge_balanced_bounds(rp, 11)
    starts = edge_balanced_bounds(rp, 10)  # one vertex per part
    assert np.all(np.diff(starts) == 1)


def test_frontier_capacity_rule():
    # reference push_model.inl:393-397 with SPARSE_THRESHOLD=16
    assert frontier_capacity(1600) == 200
    assert frontier_capacity(0) == 100


def test_weighted_balanced_bounds_aligned():
    from lux_tpu.partition import weighted_balanced_bounds
    rng = np.random.default_rng(3)
    nv = 4096
    cost = rng.random(nv) * np.linspace(3, 1, nv)  # front-loaded
    cum = np.cumsum(cost)
    starts = weighted_balanced_bounds(cum, 4, align=128)
    assert starts[0] == 0 and starts[-1] == nv
    assert (np.diff(starts) > 0).all()
    assert (starts[1:-1] % 128 == 0).all()
    # balance: every part within 35% of the mean cost
    per = np.diff(np.concatenate(([0.0], cum[starts[1:] - 1])))
    assert per.max() / (cum[-1] / 4) < 1.35


def test_weighted_balanced_bounds_fallback_small():
    from lux_tpu.partition import weighted_balanced_bounds
    # nv < parts * align -> falls back to unaligned but still valid
    cum = np.cumsum(np.ones(100))
    starts = weighted_balanced_bounds(cum, 4, align=128)
    assert starts[0] == 0 and starts[-1] == 100
    assert (np.diff(starts) > 0).all()


def test_weighted_matches_edge_balanced_on_degrees():
    from lux_tpu.partition import (edge_balanced_bounds,
                                   weighted_balanced_bounds)
    rng = np.random.default_rng(5)
    deg = rng.integers(0, 50, 500)
    row_ptrs = np.cumsum(deg).astype(np.uint64)
    a = edge_balanced_bounds(row_ptrs, 5)
    b = weighted_balanced_bounds(row_ptrs.astype(np.float64), 5)
    np.testing.assert_array_equal(a, b)
