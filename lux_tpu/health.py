"""Device-side health watchdog: O(1) in-loop checks, host-side trips.

The reference has no numeric guards at all, and until round 9 neither
did the fused loops here: a NaN residual compared False against
``run_until``'s ``res > tol`` predicate and the loop EXITED, reporting
convergence on a garbage state.  This module is the guarded-execution
layer closing that class of silent wrongness:

- Engines grow health-recording loop variants (compiled lazily beside
  the untouched watchdog-free programs, exactly like the round-7
  counter variants): ``PullEngine.run_health`` / ``run_until_health``
  and ``PushEngine.converge_health`` accumulate a fixed ``int32[6]``
  HEALTH WORD inside the ``fori_loop``/``while_loop`` and EXIT the
  loop the iteration a fatal flag trips — no in-loop host syncs, the
  word is fetched once per run/segment boundary (24 bytes), the same
  O(KB)-per-segment discipline as the telemetry counters.
- A tripped word raises a typed :class:`HealthError` carrying the
  diagnosis (which checks, which iteration, which part);
  ``resilience.classify`` treats it as FATAL-with-diagnosis — the
  corruption is in the state itself, so a resume from the last
  checkpoint cannot be trusted blindly and a human (or the caller)
  decides.

Health word layout (int32[6], see ARCHITECTURE.md "Data integrity &
guarded execution"):

    [0] flags      bitmask of tripped checks (0 = healthy)
    [1] iteration  first iteration any check tripped (-1 = none)
    [2] part       first part with non-finite state at trip (-1 = n/a)
    [3] count      non-finite values at trip (clamped to int32)
    [4] aux        pull: float32 residual at trip, bitcast to int32;
                   push: global frontier size at trip
    [5] tick       iterations the watchdog has observed — the word
                   (plus its window/stall aux, the WATCH tuple) is
                   THREADED across segment boundaries by the
                   segmented drivers, so trailing-window checks keep
                   their history when segments are shorter than the
                   window and trip iterations are global to the run

Checks, engine by engine:

- pull (``NONFINITE_STATE``/``NONFINITE_RESIDUAL``): any NaN/Inf in
  the new state / in the iteration residual.
- pull ``DIVERGENCE``: the trailing ``WINDOW`` residuals are strictly
  increasing AND grew by more than ``DIVERGENCE_GROWTH`` over the
  window — a blowing-up iteration caught before it reaches Inf/NaN.
- pull ``OSCILLATION``: the trailing-window residual differences
  strictly alternate in sign with no net decrease — a limit cycle
  that will never satisfy any tolerance.
- push ``NONFINITE_STATE``: NaN labels (+Inf is the legitimate
  unreached sentinel and never trips).
- push ``FRONTIER_STALL``: ``STALL_N`` consecutive iterations with a
  non-empty frontier, an unchanged active count and bit-identical
  labels — the truncation livelock debug.converge_guarded could only
  catch host-side per segment, now caught (and EXITED) in-loop.

Window checks need ``WINDOW`` iterations of history, so runs shorter
than the window can only trip the non-finite checks — deliberate:
short probes never false-positive on startup transients.
"""

from __future__ import annotations

import numpy as np

# flag bits — one per check; FLAG_NAMES is the wire/diagnosis naming
NONFINITE_STATE = 1
NONFINITE_RESIDUAL = 2
DIVERGENCE = 4
OSCILLATION = 8
FRONTIER_STALL = 16

FLAG_NAMES = {
    NONFINITE_STATE: "nonfinite_state",
    NONFINITE_RESIDUAL: "nonfinite_residual",
    DIVERGENCE: "divergence",
    OSCILLATION: "oscillation",
    FRONTIER_STALL: "frontier_stall",
}

# trailing-residual window (pull divergence/oscillation) — must be
# small: it rides the loop carry of every health-variant iteration
WINDOW = 8
# divergence needs strict growth AND this much net blow-up over the
# window, so a noisy-but-converging SGD trajectory cannot trip it
DIVERGENCE_GROWTH = 16.0
# consecutive no-progress iterations before a push stall trips
STALL_N = 16

HEALTH_LEN = 6


class HealthError(RuntimeError):
    """The watchdog tripped.  Carries the diagnosis: ``checks`` (list
    of FLAG_NAMES values), ``iteration`` (global, -1 unknown), ``part``
    (-1 n/a), ``engine`` ('pull'|'push').  resilience.classify treats
    it as FATAL — the corruption is in the state, not the transport,
    so blind retry/resume would rerun into the same diagnosis."""

    def __init__(self, message: str, *, checks=(), iteration: int = -1,
                 part: int = -1, engine: str = "?", count: int = 0):
        super().__init__(message)
        self.checks = list(checks)
        self.iteration = int(iteration)
        self.part = int(part)
        self.engine = str(engine)
        self.count = int(count)


# -- device-side word construction (jnp; traced inside engine loops) ---

def init_word():
    import jax.numpy as jnp
    return jnp.array([0, -1, -1, 0, 0, 0], jnp.int32)


def init_window():
    import jax.numpy as jnp
    return jnp.zeros((WINDOW,), jnp.float32)


def record(h, flags, part, count, aux):
    """Fold one iteration's tripped ``flags`` into the word ``h``:
    flags accumulate (OR), the diagnosis slots are written only by the
    FIRST tripping iteration (at the current tick, h[5], which this
    also advances)."""
    import jax.numpy as jnp
    flags = flags.astype(jnp.int32)
    tick = h[5]
    first = (h[0] == 0) & (flags != 0)
    h = h.at[0].set(h[0] | flags)
    h = h.at[1].set(jnp.where(first, tick, h[1]))
    h = h.at[2].set(jnp.where(first, part.astype(jnp.int32), h[2]))
    h = h.at[3].set(jnp.where(first, count.astype(jnp.int32), h[3]))
    h = h.at[4].set(jnp.where(first, aux.astype(jnp.int32), h[4]))
    h = h.at[5].set(tick + 1)
    return h


def nonfinite_parts(state):
    """Per-part non-finite counts [num_parts] int32 (zeros for
    integer states — integers cannot hold NaN/Inf)."""
    import jax.numpy as jnp
    if not jnp.issubdtype(state.dtype, jnp.inexact):
        return jnp.zeros((state.shape[0],), jnp.int32)
    bad = ~jnp.isfinite(state)
    return jnp.sum(bad.reshape(state.shape[0], -1),
                   axis=1).astype(jnp.int32)


def nan_parts(state):
    """Per-part NaN counts [rows] int32 — the push-label check:
    +/-Inf is the legitimate unreached sentinel and never trips
    (zeros for integer labels)."""
    import jax.numpy as jnp
    if not jnp.issubdtype(state.dtype, jnp.inexact):
        return jnp.zeros((state.shape[0],), jnp.int32)
    bad = jnp.isnan(state)
    return jnp.sum(bad.reshape(state.shape[0], -1),
                   axis=1).astype(jnp.int32)


def first_bad_part(bad_pp):
    """Index of the first part with non-finite values, -1 if none."""
    import jax.numpy as jnp
    any_bad = jnp.any(bad_pp > 0)
    return jnp.where(any_bad, jnp.argmax(bad_pp > 0), -1).astype(
        jnp.int32)


def _f32_bits(x):
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(x.astype(jnp.float32),
                                        jnp.int32)


def pull_update(h, win, state, res):
    """One pull iteration's health update.  ``state`` is the NEW
    global [num_parts, vpad, ...] state (sharded arrays are fine —
    this runs in the jit wrapper OUTSIDE shard_map), ``res`` the
    iteration's max-abs residual.  Returns (word, window) — thread
    both across segments so the trailing-window checks keep their
    history (the word's tick, h[5], indexes the ring)."""
    import jax.numpy as jnp
    tick = h[5]
    bad_pp = nonfinite_parts(state)
    nf = jnp.sum(bad_pp)
    res_bad = ~jnp.isfinite(res)
    win = win.at[tick % WINDOW].set(res.astype(jnp.float32))
    # chronological view of the ring (oldest first)
    chron = jnp.roll(win, -(tick % WINDOW) - 1)
    d = chron[1:] - chron[:-1]
    full = tick >= WINDOW - 1
    div = (full & jnp.all(d > 0)
           & (chron[-1] > DIVERGENCE_GROWTH * chron[0]))
    osc = (full & jnp.all(d[1:] * d[:-1] < 0)
           & (chron[-1] + chron[-2] >= chron[0] + chron[1]))
    flags = ((nf > 0) * NONFINITE_STATE
             + res_bad * NONFINITE_RESIDUAL
             + div * DIVERGENCE + osc * OSCILLATION)
    return record(h, flags, first_bad_part(bad_pp), nf,
                  _f32_bits(res)), win


# -- host-side decode / raise ------------------------------------------

def _fetch(hvec) -> np.ndarray:
    import jax
    if isinstance(hvec, (tuple, list)):    # a WATCH tuple (word, aux)
        hvec = hvec[0]
    return np.asarray(jax.device_get(hvec)).astype(np.int64)


def flag_names(flags: int) -> list[str]:
    return [name for bit, name in sorted(FLAG_NAMES.items())
            if flags & bit]


def digest(hvec, engine: str, base_iter: int = 0) -> dict:
    """Host-side diagnosis dict of a (possibly device) health word.
    ``base_iter`` offsets the in-run iteration to a global count when
    the run was one segment of a longer whole."""
    h = _fetch(hvec)
    flags = int(h[0])
    out = {"engine": engine, "tripped": bool(flags),
           "flags": flag_names(flags)}
    if flags:
        out["iteration"] = int(h[1]) + base_iter if h[1] >= 0 else -1
        out["part"] = int(h[2])
        out["count"] = int(h[3])
        if engine == "pull":
            out["residual"] = float(
                np.int32(h[4]).view(np.float32))
        else:
            out["frontier"] = int(h[4])
    return out


def ensure_ok(hvec, engine: str, base_iter: int = 0,
              where: str = "run") -> dict:
    """Fetch + decode one health word; healthy returns the digest, a
    tripped word emits a ``health_trip`` telemetry event and raises
    HealthError with the full diagnosis."""
    from lux_tpu import telemetry

    d = digest(hvec, engine, base_iter)
    if not d["tripped"]:
        return d
    telemetry.current().emit("health_trip", where=where, **d)
    detail = (f"residual={d.get('residual'):.6g}" if engine == "pull"
              else f"frontier={d.get('frontier')}")
    raise HealthError(
        f"{where}: health watchdog tripped "
        f"[{'+'.join(d['flags'])}] at iteration {d['iteration']}, "
        f"part {d['part']} ({d['count']} bad values, {detail})",
        checks=d["flags"], iteration=d["iteration"], part=d["part"],
        engine=engine, count=d["count"])
