"""Correctness audits — the reference's ``-check`` GPU tasks, grown up.

The reference audits only fixed-point properties per partition
(reference sssp_gpu.cu:773-798: a "mistake" is labels[dst] >
labels[src]+1; components_gpu.cu:788: labels[dst] < labels[src]) and
prints [PASS]/[FAIL] per part (sssp_gpu.cu:837-842).  We keep those
audits (they catch divergence bugs cheaply on full-scale graphs) and
add residual checks the reference lacks (SURVEY.md §4 item 4).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from lux_tpu.graph import Graph


@dataclasses.dataclass(frozen=True)
class CheckResult:
    name: str
    violations: int
    checked: int
    # device audits report per-partition counts, like the reference's
    # per-part [PASS]/[FAIL] prints (reference sssp_gpu.cu:837-842)
    per_part: tuple | None = None

    @property
    def ok(self) -> bool:
        return self.violations == 0

    def __str__(self):
        tag = "PASS" if self.ok else "FAIL"
        s = (f"[{tag}] {self.name}: {self.violations} violations "
             f"over {self.checked} edges")
        if self.per_part is not None and not self.ok:
            failing = {p: c for p, c in enumerate(self.per_part) if c}
            s += f" (by part: {failing})"
        return s


def check_sssp(g: Graph, dist: np.ndarray,
               weighted: bool = False) -> CheckResult:
    """Fixed point: dist[dst] <= dist[src] + w for every edge
    (reference sssp_gpu.cu:792-796 with w = 1)."""
    src, dst = g.edge_arrays()
    if weighted:
        w = np.asarray(g.weights, dtype=np.float64)
        d = np.asarray(dist, dtype=np.float64)
    else:
        w = 1
        d = np.asarray(dist, dtype=np.int64)
    bad = int(np.count_nonzero(d[dst] > d[src] + w))
    return CheckResult("sssp triangle inequality", bad, g.ne)


def check_components(g: Graph, labels: np.ndarray) -> CheckResult:
    """Fixed point: labels[dst] >= labels[src] for every edge
    (reference components_gpu.cu:788)."""
    src, dst = g.edge_arrays()
    lab = np.asarray(labels, dtype=np.int64)
    bad = int(np.count_nonzero(lab[dst] < lab[src]))
    return CheckResult("components monotonicity", bad, g.ne)


def check_colfilter(g: Graph, state: np.ndarray) -> CheckResult:
    """Training audit the reference lacks: the learned factors must
    predict ratings no worse than the uniform sqrt(1/K) init."""
    from lux_tpu.apps.colfilter import K, rmse
    init = np.full((g.nv, state.shape[1] if state.ndim > 1 else K),
                   np.sqrt(1.0 / state.shape[1]), dtype=np.float64)
    bad = int(rmse(g, state) > rmse(g, init) + 1e-9)
    return CheckResult("colfilter rmse non-increase", bad, g.ne)


def check_pagerank(g: Graph, norm_ranks: np.ndarray,
                   tol: float = 1e-6) -> CheckResult:
    """Residual audit the reference lacks: one more iteration moves
    every (degree-normalized) rank by less than ``tol`` — only
    meaningful near convergence; with few iterations use a loose tol."""
    from lux_tpu.apps.pagerank import ALPHA
    src, dst = g.edge_arrays()
    deg = g.out_degrees.astype(np.float64)
    state = np.asarray(norm_ranks, dtype=np.float64)
    acc = np.zeros(g.nv)
    np.add.at(acc, dst, state[src])
    pr = (1.0 - ALPHA) / g.nv + ALPHA * acc
    nxt = np.where(deg > 0, pr / np.maximum(deg, 1), pr)
    bad = int(np.count_nonzero(np.abs(nxt - state) > tol))
    return CheckResult(f"pagerank residual(tol={tol})", bad, g.nv)
