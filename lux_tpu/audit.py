"""Compile-time program auditor: jaxpr invariant checks.

The reference enforces its execution contract through C++ templates —
an app that violates the pull/push task shapes does not compile
(reference core/graph.h:146-225).  lux_tpu's equivalent contracts
lived only as prose in CLAUDE.md/PERF_NOTES.md and regressed silently;
this module machine-checks them by tracing every engine program
variant to a jaxpr on the CPU backend (tracing never executes or
compiles device code, so auditing a billion-edge engine costs about
the same as a toy one — the single size-dependent step is one host
``program.init`` per engine to learn the state shape, whose result
the next ``init_state`` call reuses) and walking the jaxpr for
structural violations.

Check catalogue (check name -> typed error):

  gather-budget        GatherBudgetError
      Per fused-loop body, the number of per-element gathers whose
      operand IS the flat vertex-state table [num_parts*vpad, ...]
      must not exceed the engine's budget: dense push masks inactive
      sources into the label vector PRE-gather so one gather serves
      the iteration (PERF_NOTES: the gather is ~90% of an iteration);
      owner exchange exists to have ZERO table gathers (per-shard
      gathers ride the lax.scan); pair-lane row fetches are
      row-granular by design (tile-reshaped operand) and exempt.
  const-bytes          ConstBytesError
      Closed-over constants above a byte ceiling: the remote compiler
      rejects programs with large baked-in constants (HTTP 413), so
      graph arrays must arrive as jit ARGUMENTS.  Caught here before
      any tunnel round-trip.
  dtype-discipline     DtypeDisciplineError
      No f64/complex anywhere, and no silent promotion past the
      program's state dtype (any aval wider than
      max(4, state itemsize) bytes).
  loop-invariant       LoopInvariantError (warning severity)
      Expensive ops (gather/dot_general/scatter/sort) inside a
      while/scan body whose inputs are ALL loop-invariant: XLA hoists
      them out of the loop, so a benchmark timing that loop measures
      nothing (the CLAUDE.md benchmarking trap, now a warning class).
  collective-schedule  CollectiveScheduleError
      The owner exchange must be a lax.scan over source parts (a
      vmapped batched gather still pays the big-table rate,
      scripts/profile_owner.py); sum exchanges reduce-scatter; fused
      min/max rings take exactly ndev-1 ppermute hops of full ndev
      cycles (cf. the collective-schedule discipline of portable
      reduce-scatter lowerings, PAPERS.md).
  callback-in-loop     CallbackInLoopError
      No pure_callback/io_callback/debug_callback primitives inside
      fused loops — a host round-trip per iteration through the
      tunnel is the exact failure mode the fused designs exist to
      avoid.
  identity-init        IdentityInitError
      Scatter-reduce inits must equal the reduction identity: a
      scatter-min onto a zeros-initialized buffer silently clamps
      every positive result (the one-identity/sentinel convention,
      CLAUDE.md).  Only statically-resolvable (broadcast-of-literal)
      inits are judged; reductions onto carried state are semantic
      relaxations and pass.
  ledger-drift         LedgerDriftError
      XLA ``memory_analysis`` of the CPU-compiled step vs
      ``graph.memory_report(...)`` within a stated tolerance, so the
      priced ledger can never drift from the compiler again.
      Tolerance rationale: the ledger prices epad-based lower bounds
      while the compiled arrays carry chunk/tile padding (measured
      1.1-1.3x on bench-shaped graphs, 10x+ on toy graphs whose
      padding dominates) — the check exists to catch order-of-
      magnitude drift, not byte equality, and is only meaningful on
      graphs dense enough that edges dominate padding.

Usage:

  engine-build audit (CLI ``-audit warn|error``, engines'
  ``audit=``):  every lazily-compiled loop variant (run/run_until/
  converge x stats/health) is traced and checked at build time.
  ``python -m lux_tpu.audit`` runs the repo-wide engine matrix on the
  CPU backend (no TPU needed) — the tier-1 test wraps the same entry.

  Exemptions, two granularities:
  - per-eqn source pragma ``# audit: allow(check-name)`` on the
    offending line (or the comment block directly above it), honored
    through jaxpr source info for the eqn-anchored checks:
    gather-budget, dtype-discipline, loop-invariant,
    callback-in-loop, identity-init.  scripts/lint_lux.py honors the
    same syntax for its AST findings.
  - ``allow={"check-name", ...}`` at the audit call site, for the
    program-level checks (const-bytes, collective-schedule,
    ledger-drift) that aggregate over the whole jaxpr and have no
    single source line to carry a pragma.  Record WHY next to the
    call.
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np

__all__ = [
    "AuditError", "AuditWarning", "Finding", "ProgramSpec",
    "GatherBudgetError", "ConstBytesError", "DtypeDisciplineError",
    "LoopInvariantError", "CollectiveScheduleError",
    "CallbackInLoopError", "IdentityInitError", "LedgerDriftError",
    "audit_jaxpr", "audit_engine", "engine_spec", "check_ledger",
    "matrix_configs", "run_repo_audit", "main",
]

# ---------------------------------------------------------------------
# typed errors

class AuditError(Exception):
    """Base of every auditor violation; ``findings`` carries the full
    list behind a raised (possibly aggregated) error."""
    check = "audit"

    def __init__(self, message: str, findings=()):
        super().__init__(message)
        self.findings = list(findings)


class GatherBudgetError(AuditError):
    check = "gather-budget"


class ConstBytesError(AuditError):
    check = "const-bytes"


class DtypeDisciplineError(AuditError):
    check = "dtype-discipline"


class LoopInvariantError(AuditError):
    check = "loop-invariant"


class CollectiveScheduleError(AuditError):
    check = "collective-schedule"


class CallbackInLoopError(AuditError):
    check = "callback-in-loop"


class IdentityInitError(AuditError):
    check = "identity-init"


class LedgerDriftError(AuditError):
    check = "ledger-drift"


ERROR_TYPES = {cls.check: cls for cls in (
    GatherBudgetError, ConstBytesError, DtypeDisciplineError,
    LoopInvariantError, CollectiveScheduleError, CallbackInLoopError,
    IdentityInitError, LedgerDriftError)}

CHECKS = tuple(sorted(ERROR_TYPES))


class AuditWarning(UserWarning):
    """Category used for ``mode='warn'`` reporting."""


@dataclasses.dataclass(frozen=True)
class Finding:
    check: str          # one of CHECKS
    severity: str       # "error" | "warn"
    where: str          # "<engine>.<variant>" or caller-supplied
    detail: str

    def __str__(self):
        return f"[{self.check}] {self.where}: {self.detail}"


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """Expectations for one traced program.

    table_shape     aval shape of the FLAT vertex-state table (the
                    all-parts [num_parts*vpad, ...] array the dense
                    per-edge gather reads); None skips gather-budget.
    gather_budget   max table gathers per fused-loop body.
    const_bytes_max closed-over constant ceiling (HTTP-413 guard).
    state_itemsize  bytes per element of the iterated state; avals
                    wider than max(4, this) fail dtype-discipline.
    require_scan_len  owner exchange: a lax.scan of exactly this
                    length (the per-source-part generation scan)
                    whose body gathers from a per-part state SHARD
                    (operand shape ``require_scan_shard_shape``) must
                    exist; None skips.  The shard-gather requirement
                    stops the fused iteration loop (fori -> scan)
                    from satisfying the check by length coincidence.
    require_scan_shard_shape  aval shape of one state shard
                    ([vpad, ...]); used with require_scan_len.
    ppermute_hops   fused min/max ring: exact ppermute eqn count
                    (ndev - 1); None skips.
    ring_size       devices on the ring (each ppermute perm must be a
                    full ring_size cycle); None skips.
    expect_reduce_scatter  mesh sum owner exchange: require a
                    reduce_scatter/psum_scatter eqn.
    expect_all_to_all      mesh min/max (non-fused) owner exchange:
                    require an all_to_all eqn and forbid ppermute.
    """
    table_shape: tuple | None = None
    gather_budget: int | None = None
    const_bytes_max: int = 1 << 20
    state_itemsize: int = 4
    require_scan_len: int | None = None
    require_scan_shard_shape: tuple | None = None
    ppermute_hops: int | None = None
    ring_size: int | None = None
    expect_reduce_scatter: bool = False
    expect_all_to_all: bool = False
    # paged engines (ops/pagegather.py): the page row-fetch whose
    # operand is the TILE-RESHAPED state table ([T, 128, ...] /
    # [T, 128*K]) IS the iteration's one state-table access — these
    # shapes count against the same gather budget, so a paged dense
    # iteration stays machine-checked at exactly 1 with no pragma.
    # (The plan pads its buffer/row dims to NEVER collide with these
    # shapes, pagegather._pad8_distinct.)
    paged_table_shapes: tuple = ()


# ---------------------------------------------------------------------
# jaxpr walking utilities

def _literal_type():
    from jax.extend import core as jex_core
    return jex_core.Literal


def _sub_jaxprs(params: dict):
    """Every Jaxpr nested in an eqn's params (ClosedJaxpr unwrapped),
    as (jaxpr, consts) pairs — robust across primitives (while, scan,
    cond, pjit, shard_map, custom_* ...)."""
    for v in params.values():
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            if hasattr(x, "jaxpr") and hasattr(x, "consts"):
                yield x.jaxpr, x.consts
            elif hasattr(x, "eqns") and hasattr(x, "invars"):
                yield x, ()


LOOP_PRIMS = ("while", "scan")

# ---------------------------------------------------------------------
# source pragmas: ``# audit: allow(check-name)`` on (or just above)
# the offending source line exempts that eqn from ``check-name``,
# with the justification living next to the code it covers — the
# same syntax scripts/lint_lux.py honors for AST-level findings.

import functools as _functools
import re as _re

_PRAGMA_RE = _re.compile(r"#\s*audit:\s*allow\(([a-z-]+)\)")


@_functools.lru_cache(maxsize=256)
def _file_lines(path: str):
    try:
        with open(path) as f:
            return f.readlines()
    except OSError:
        return []


def _eqn_source(eqn):
    """(file_name, line) of the user frame that traced ``eqn``, or
    (None, None)."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None, None
        return frame.file_name, frame.start_line
    except Exception:  # noqa: BLE001 — tracebacks disabled/changed
        return None, None


def _pragma_allows(eqn, check: str, stack: tuple = ()) -> bool:
    """True when the source line that traced ``eqn`` — or an
    enclosing call eqn's line (see ``_iter_eqns`` on trace caching) —
    or the contiguous comment block directly above either statement,
    carries an explicit ``# audit: allow(check)`` pragma."""
    for e in (eqn,) + tuple(reversed(stack)):
        if _pragma_allows_line(e, check):
            return True
    return False


def _pragma_allows_line(eqn, check: str) -> bool:
    fname, line = _eqn_source(eqn)
    if fname is None or line is None:
        return False
    lines = _file_lines(fname)
    if not 0 < line <= len(lines):
        return False

    def hit(text):
        return any(m.group(1) == check
                   for m in _PRAGMA_RE.finditer(text))

    if hit(lines[line - 1]):
        return True
    ln = line - 2
    while ln >= 0:
        stripped = lines[ln].strip()
        if stripped.startswith("#"):
            if hit(stripped):
                return True
            ln -= 1
        elif not stripped:
            ln -= 1
        else:
            break
    return False


def _where_src(eqn, where: str) -> str:
    fname, line = _eqn_source(eqn)
    if fname is None:
        return where
    import os
    return f"{where} ({os.path.basename(fname)}:{line})"


def _iter_eqns(jaxpr, in_loop: bool = False, stack: tuple = ()):
    """Yield (eqn, in_loop, stack) over ``jaxpr`` and every nested
    jaxpr; ``in_loop`` is True inside any while/scan body (incl. cond
    branches and inner pjits reached from one); ``stack`` is the
    chain of enclosing call eqns (pjit/while/scan/...), innermost
    last — pragma lookups consult it because jax CACHES traced
    sub-jaxprs, so an eqn inside a reused jnp-op trace carries the
    FIRST call site's source info, while its enclosing call eqn
    carries the real one."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop, stack
        inner = in_loop or eqn.primitive.name in LOOP_PRIMS
        for sub, _ in _sub_jaxprs(eqn.params):
            yield from _iter_eqns(sub, inner, stack + (eqn,))


def _outer_loops(jaxpr, path=""):
    """(description, body_jaxpr) for each OUTERMOST while/scan — the
    fused-loop bodies the per-loop budgets apply to.  Nested loops
    (e.g. the owner scan inside a fused while) are audited as part of
    their enclosing body."""
    out = []
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        if name in LOOP_PRIMS:
            for sub, _ in _sub_jaxprs(eqn.params):
                out.append((f"{path}{name}[{i}]", sub))
        else:
            for sub, _ in _sub_jaxprs(eqn.params):
                out.extend(_outer_loops(sub, f"{path}{name}[{i}]/"))
    return out


def _count_prims(jaxpr, names) -> int:
    return sum(1 for eqn, _, _ in _iter_eqns(jaxpr)
               if eqn.primitive.name in names)


# ---------------------------------------------------------------------
# check 1: gather budget

def _table_gathers(jaxpr, table_shape, paged_shapes=()):
    """Gather eqns whose operand aval IS the flat state table (exact
    shape match: per-part arrays are rank+1 batched [P_local, vpad,
    ...], shards are [vpad, ...], pair row fetches are tile-reshaped
    [n_tiles, 128*...] — none collide with [num_parts*vpad, ...]).
    ``paged_shapes`` (ops/pagegather.py engines) adds the tile-
    reshaped table shapes of the page row-fetch, counted against the
    SAME budget: the paged path's page fetch is THE state-table
    access of a dense iteration.  A gather carrying an explicit
    ``# audit: allow(gather-budget)`` source pragma does not count."""
    shapes = {tuple(table_shape)}
    shapes.update(tuple(s) for s in paged_shapes)
    n = 0
    for eqn, _, stack in _iter_eqns(jaxpr):
        if eqn.primitive.name == "gather":
            aval = eqn.invars[0].aval
            if (tuple(aval.shape) in shapes
                    and not _pragma_allows(eqn, "gather-budget",
                                           stack)):
                n += 1
    return n


def check_gather_budget(closed, spec: ProgramSpec, where: str):
    if spec.table_shape is None or spec.gather_budget is None:
        return []
    findings = []
    bodies = _outer_loops(closed.jaxpr) or [("program", closed.jaxpr)]
    for desc, body in bodies:
        n = _table_gathers(body, spec.table_shape,
                           spec.paged_table_shapes)
        if n > spec.gather_budget:
            findings.append(Finding(
                "gather-budget", "error", where,
                f"{n} state-table gathers (operand "
                f"{tuple(spec.table_shape)}) in fused-loop body "
                f"{desc}; budget is {spec.gather_budget} — mask into "
                f"the value vector pre-gather instead of gathering "
                f"twice (PERF_NOTES: the gather is ~90% of an "
                f"iteration)"))
    return findings


# ---------------------------------------------------------------------
# check 2: constvar byte ceiling

def _const_bytes(closed) -> int:
    total = 0
    for c in closed.consts:
        try:
            total += np.asarray(c).nbytes
        except Exception:  # noqa: BLE001 — non-array const (rare)
            continue
    Literal = _literal_type()
    for eqn, _, _ in _iter_eqns(closed.jaxpr):
        for v in eqn.invars:
            if isinstance(v, Literal) and np.ndim(v.val) > 0:
                total += np.asarray(v.val).nbytes
        for sub, consts in _sub_jaxprs(eqn.params):
            for c in consts:
                if hasattr(c, "nbytes"):
                    total += c.nbytes
    return total


def check_const_bytes(closed, spec: ProgramSpec, where: str):
    total = _const_bytes(closed)
    if total <= spec.const_bytes_max:
        return []
    return [Finding(
        "const-bytes", "error", where,
        f"{total} bytes of closed-over constants exceed the "
        f"{spec.const_bytes_max}-byte ceiling — the remote compiler "
        f"rejects large baked-in constants (HTTP 413); pass arrays "
        f"as jit arguments")]


# ---------------------------------------------------------------------
# check 3: dtype discipline

def check_dtypes(closed, spec: ProgramSpec, where: str):
    limit = max(4, int(spec.state_itemsize))
    offenders = {}
    for eqn, _, stack in _iter_eqns(closed.jaxpr):
        for v in list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            dt = np.dtype(dt)
            bad = (dt.kind == "c"
                   or (dt.kind in "fiu" and dt.itemsize > limit))
            if bad and not _pragma_allows(eqn, "dtype-discipline",
                                          stack):
                key = (str(dt), eqn.primitive.name)
                offenders[key] = offenders.get(key, 0) + 1
    if not offenders:
        return []
    det = ", ".join(f"{d} out of {p} x{n}"
                    for (d, p), n in sorted(offenders.items()))
    return [Finding(
        "dtype-discipline", "error", where,
        f"avals wider than the {limit}-byte state dtype ceiling "
        f"(no f64/complex, no silent promotions): {det}")]


# ---------------------------------------------------------------------
# check 4: loop-invariant operands (warning class)

EXPENSIVE_PRIMS = frozenset({
    "gather", "dot_general", "conv_general_dilated", "sort",
    "scatter", "scatter-add", "scatter-min", "scatter-max",
    "scatter_add", "scatter_min", "scatter_max", "reduce_window",
})

# flag only work worth hoisting: tiny invariant ops are free either way
_INVARIANT_MIN_ELEMS = 4096


def _eqn_elems(eqn) -> int:
    sizes = [int(np.prod(v.aval.shape))
             for v in list(eqn.outvars) + list(eqn.invars)
             if hasattr(getattr(v, "aval", None), "shape")]
    return max(sizes or [0])


def _scan_invariant(jaxpr, inv_in, where, findings, stack=()):
    """Propagate loop-invariance through one body jaxpr; flag
    expensive all-invariant eqns (XLA hoists them out of the loop —
    the timed loop then measures nothing)."""
    Literal = _literal_type()
    inv = dict(zip(jaxpr.invars, inv_in))
    for cv in jaxpr.constvars:
        inv[cv] = True

    def is_inv(a):
        return isinstance(a, Literal) or inv.get(a, False)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [is_inv(a) for a in eqn.invars]
        all_inv = bool(ins) and all(ins)
        deeper = stack + (eqn,)
        if name == "while":
            # loop consts are invariant BY DEFINITION of the loop
            # (wherever their values came from); carry is variant
            bn = eqn.params["body_nconsts"]
            body = eqn.params["body_jaxpr"].jaxpr
            binv = [True] * bn + [False] * (len(body.invars) - bn)
            _scan_invariant(body, binv, where, findings, deeper)
        elif name == "scan":
            nc = eqn.params["num_consts"]
            body = eqn.params["jaxpr"].jaxpr
            binv = [True] * nc + [False] * (len(body.invars) - nc)
            _scan_invariant(body, binv, where, findings, deeper)
        elif name == "cond":
            for sub, _ in _sub_jaxprs(eqn.params):
                binv = ins[1:1 + len(sub.invars)]
                binv += [False] * (len(sub.invars) - len(binv))
                _scan_invariant(sub, binv, where, findings, deeper)
        else:
            subs = list(_sub_jaxprs(eqn.params))
            if subs:
                for sub, _ in subs:
                    if len(sub.invars) == len(ins):
                        _scan_invariant(sub, ins, where, findings,
                                        deeper)
                    else:           # conservative: unknown call conv
                        _scan_invariant(
                            sub, [False] * len(sub.invars), where,
                            findings, deeper)
            elif (all_inv and name in EXPENSIVE_PRIMS
                    and _eqn_elems(eqn) >= _INVARIANT_MIN_ELEMS
                    and not _pragma_allows(eqn, "loop-invariant",
                                           stack)):
                findings.append(Finding(
                    "loop-invariant", "warn", _where_src(eqn, where),
                    f"{name} ({_eqn_elems(eqn)} elems) inside a "
                    f"while/scan body depends only on loop-invariant "
                    f"operands — XLA hoists it out, so a timed loop "
                    f"does not measure it (CLAUDE.md benchmarking "
                    f"trap); make it consume the carry or move it "
                    f"out of the loop explicitly"))
        for ov in eqn.outvars:
            inv[ov] = all_inv and name not in LOOP_PRIMS


def check_loop_invariant(closed, spec: ProgramSpec, where: str):
    # walk from the top with every program input VARIANT — only
    # while/scan bodies introduce invariance (their const positions),
    # which is exactly the hoisting trap this check is about
    findings = []
    _scan_invariant(closed.jaxpr,
                    [False] * len(closed.jaxpr.invars), where,
                    findings)
    return findings


# ---------------------------------------------------------------------
# check 5: collective schedule

_REDUCE_SCATTER = frozenset({"reduce_scatter", "psum_scatter"})


def check_collectives(closed, spec: ProgramSpec, where: str):
    findings = []
    if spec.require_scan_len is not None:
        scans = [eqn for eqn, _, _ in _iter_eqns(closed.jaxpr)
                 if eqn.primitive.name == "scan"]
        lens = [e.params.get("length") for e in scans]

        def shard_gather_in(eqn):
            # the generation scan's body gathers from ONE [vpad, ...]
            # state shard — without this, the fused iteration loop
            # (fori -> scan) could satisfy the check whenever
            # num_iters happens to equal the local part count
            if spec.require_scan_shard_shape is None:
                return True
            body = eqn.params.get("jaxpr")
            if body is None:
                return False
            want = tuple(spec.require_scan_shard_shape)
            return any(
                e.primitive.name == "gather"
                and tuple(e.invars[0].aval.shape) == want
                for e, _, _ in _iter_eqns(body.jaxpr))

        ok = any(e.params.get("length") == spec.require_scan_len
                 and shard_gather_in(e) for e in scans)
        if not ok:
            findings.append(Finding(
                "collective-schedule", "error", where,
                f"owner exchange must generate contributions with a "
                f"lax.scan over the {spec.require_scan_len} local "
                f"source parts whose body gathers from the "
                f"[vpad, ...] state shard (scan lengths seen: "
                f"{sorted(set(lens))}) — a vmapped batched gather "
                f"still pays the big-table rate "
                f"(scripts/profile_owner.py)"))
    if spec.ppermute_hops is not None:
        perms = [eqn.params.get("perm")
                 for eqn, _, _ in _iter_eqns(closed.jaxpr)
                 if eqn.primitive.name == "ppermute"]
        if len(perms) != spec.ppermute_hops:
            findings.append(Finding(
                "collective-schedule", "error", where,
                f"ring reduce-scatter must take exactly "
                f"{spec.ppermute_hops} ppermute hops (P-1); found "
                f"{len(perms)}"))
        if spec.ring_size is not None:
            for p in perms:
                if p is None:
                    continue
                pairs = sorted(tuple(x) for x in p)
                full = sorted((j, (j + 1) % spec.ring_size)
                              for j in range(spec.ring_size))
                if pairs != full:
                    findings.append(Finding(
                        "collective-schedule", "error", where,
                        f"ppermute perm {pairs} is not the full "
                        f"{spec.ring_size}-device ring cycle"))
    if spec.expect_reduce_scatter:
        if _count_prims(closed.jaxpr, _REDUCE_SCATTER) < 1:
            findings.append(Finding(
                "collective-schedule", "error", where,
                "mesh sum owner exchange must lower through "
                "psum_scatter/reduce_scatter (found none)"))
    if spec.expect_all_to_all:
        if _count_prims(closed.jaxpr, {"all_to_all"}) < 1:
            findings.append(Finding(
                "collective-schedule", "error", where,
                "mesh min/max owner exchange (non-fused) must route "
                "through all_to_all (found none)"))
    return findings


# ---------------------------------------------------------------------
# check 6: callbacks inside fused loops

CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "python_callback",
})


def check_callbacks(closed, spec: ProgramSpec, where: str):
    findings = []
    for eqn, in_loop, stack in _iter_eqns(closed.jaxpr):
        if (in_loop and eqn.primitive.name in CALLBACK_PRIMS
                and not _pragma_allows(eqn, "callback-in-loop",
                                       stack)):
            findings.append(Finding(
                "callback-in-loop", "error", _where_src(eqn, where),
                f"{eqn.primitive.name} inside a fused while/scan "
                f"body — a host round-trip per iteration through the "
                f"tunnel; accumulate device-side and fetch at "
                f"run/segment boundaries instead "
                f"(lux_tpu/telemetry.py)"))
    return findings


# ---------------------------------------------------------------------
# check 7: identity-sentinel scatter inits

_SCATTER_KIND = {
    "scatter-add": "sum", "scatter_add": "sum",
    "scatter-min": "min", "scatter_min": "min",
    "scatter-max": "max", "scatter_max": "max",
}

_PASSTHROUGH = frozenset({
    "broadcast_in_dim", "convert_element_type", "reshape", "squeeze",
    "expand_dims", "copy", "sharding_constraint", "transpose",
})


def _identity_value(kind: str, dtype):
    dt = np.dtype(dtype)
    if dt.kind == "b":
        return {"sum": False, "max": False, "min": True}[kind]
    from lux_tpu.ops.segment import identity_for
    return np.asarray(identity_for(kind, dt))


def _resolve_broadcast_literal(var, defs, depth=0):
    """Chase ``var`` through shape-only ops to a scalar literal; None
    when it derives from real data (a carried accumulator etc.)."""
    Literal = _literal_type()
    if isinstance(var, Literal):
        val = np.asarray(var.val)
        if val.size == 1:
            return val.reshape(())
        if val.size and (val == val.flat[0]).all():
            return np.asarray(val.flat[0])
        return None
    if depth > 12:
        return None
    eqn = defs.get(var)
    if eqn is None:
        return None
    if eqn.primitive.name in _PASSTHROUGH:
        return _resolve_broadcast_literal(eqn.invars[0], defs,
                                          depth + 1)
    return None


def _check_identity_in(jaxpr, where, findings, stack=()):
    defs = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            defs[ov] = eqn
    for eqn in jaxpr.eqns:
        kind = _SCATTER_KIND.get(eqn.primitive.name)
        if kind is not None:
            operand = eqn.invars[0]
            val = _resolve_broadcast_literal(operand, defs)
            if val is not None:
                dt = operand.aval.dtype
                ident = _identity_value(kind, dt)
                same = (np.asarray(val, np.dtype(dt)) ==
                        np.asarray(ident, np.dtype(dt)))
                # NaN init is never the identity; == already fails it
                if not bool(same) and not _pragma_allows(
                        eqn, "identity-init", stack):
                    findings.append(Finding(
                        "identity-init", "error",
                        _where_src(eqn, where),
                        f"{eqn.primitive.name} initialized with "
                        f"constant {np.asarray(val)} but the "
                        f"{kind}-reduce identity for {np.dtype(dt)} "
                        f"is {ident} — padding/empty segments will "
                        f"contribute a non-identity value (CLAUDE.md "
                        f"one-identity convention)"))
        for sub, _ in _sub_jaxprs(eqn.params):
            _check_identity_in(sub, where, findings, stack + (eqn,))


def check_identity_inits(closed, spec: ProgramSpec, where: str):
    findings = []
    _check_identity_in(closed.jaxpr, where, findings)
    return findings


# ---------------------------------------------------------------------
# jaxpr-level driver

def audit_jaxpr(closed, spec: ProgramSpec | None = None,
                where: str = "<jaxpr>"):
    """Run every jaxpr-level check on one ClosedJaxpr; returns the
    Finding list (empty = clean).  ``spec=None`` runs the
    program-independent checks only."""
    spec = spec or ProgramSpec()
    findings = []
    findings += check_gather_budget(closed, spec, where)
    findings += check_const_bytes(closed, spec, where)
    findings += check_dtypes(closed, spec, where)
    findings += check_loop_invariant(closed, spec, where)
    findings += check_collectives(closed, spec, where)
    findings += check_callbacks(closed, spec, where)
    findings += check_identity_inits(closed, spec, where)
    return findings


def raise_findings(findings, where: str = "",
                   warnings_as_errors: bool = False):
    """Raise the typed AuditError for ``findings`` (the specific
    subclass when they share one check); warnings raise only under
    ``warnings_as_errors``."""
    errs = [f for f in findings
            if f.severity == "error"
            or (warnings_as_errors and f.severity == "warn")]
    if not errs:
        return
    checks = {f.check for f in errs}
    cls = ERROR_TYPES[next(iter(checks))] if len(checks) == 1 \
        else AuditError
    msg = "; ".join(str(f) for f in errs[:8])
    if len(errs) > 8:
        msg += f" (+{len(errs) - 8} more)"
    raise cls(f"audit failed{' for ' + where if where else ''}: "
              f"{msg}", errs)


# ---------------------------------------------------------------------
# engine-level driver

def engine_spec(engine, state_aval) -> ProgramSpec:
    """The ProgramSpec an engine's own configuration implies."""
    sg = engine.sg
    trail = tuple(state_aval.shape[2:])
    table_shape = (sg.num_parts * sg.vpad,) + trail
    owner = engine.exchange == "owner"
    paged = getattr(engine, "page_plan", None) is not None
    # page-major owner (round 16): the generation scan still gathers
    # page-reshaped shards, but the exchange is the ROUTING hop — one
    # all_to_all of complete message rows for EVERY reduce kind, and
    # never a psum_scatter (there are no pre-reduced partials to sum)
    pagemajor = paged and engine.page_plan.mode == "pagemajor"
    ndev = 1 if engine.mesh is None else engine.mesh.devices.size
    # the owner generation scan runs per DEVICE (inside shard_map on
    # a mesh): its length is the device-local source-part count
    rows = sg.num_parts // ndev
    reduce_kind = getattr(engine.program, "reduce", "sum")
    fused = bool(getattr(engine, "owner_minmax_fused", False))
    on_mesh = engine.mesh is not None
    # paged engines access the table through its tile-reshaped view:
    # [T, 128, ...] (scalar/batched) or [T, 128*prod(trail)] (the
    # SDDMM path's flattened [T, 128*K] rows) — the page fetch on
    # either shape counts against the same budget
    T = sg.num_parts * sg.vpad // 128
    paged_shapes = ()
    if paged and not owner:
        paged_shapes = ((T, 128) + trail,)
        if trail:
            paged_shapes += ((T, 128 * int(np.prod(trail))),)
    # the owner paged scan gathers from the PAGE-RESHAPED shard
    shard_shape = (sg.vpad,) + trail
    if paged:
        shard_shape = (sg.vpad // 128, 128) + trail
    return ProgramSpec(
        table_shape=table_shape,
        # dense iterations mask into the value vector PRE-gather:
        # one table access (the flat per-element gather, or the paged
        # page row-fetch), zero in owner mode (per-shard gathers ride
        # the scan; pair row fetches are tile-reshaped and exempt)
        gather_budget=0 if owner else 1,
        paged_table_shapes=paged_shapes,
        state_itemsize=np.dtype(state_aval.dtype).itemsize,
        require_scan_len=rows if owner else None,
        require_scan_shard_shape=shard_shape if owner else None,
        ppermute_hops=(ndev - 1) if (owner and on_mesh and fused
                                     and not pagemajor
                                     and reduce_kind in ("min", "max"))
        else None,
        ring_size=ndev if (owner and on_mesh and fused
                           and not pagemajor) else None,
        expect_reduce_scatter=(owner and on_mesh and not pagemajor
                               and reduce_kind == "sum"),
        expect_all_to_all=(owner and on_mesh
                           and (pagemajor
                                or (not fused
                                    and reduce_kind in ("min",
                                                        "max")))),
    )


def trace_variant(jitted, args):
    """ClosedJaxpr of one registered engine variant — tracing only,
    no compile, no device execution (CPU-safe at any graph scale)."""
    return jitted.trace(*args).jaxpr


def audit_engine(engine, mode: str | None = "error",
                 allow=frozenset(), ledger: bool = False,
                 ledger_tol: float = 0.5):
    """Trace every registered program variant of ``engine`` and run
    the full check catalogue; optionally cross-validate the memory
    ledger (compiles the single step on the current backend — keep it
    for CPU audits).  Returns the Finding list; ``mode='error'``
    raises the typed AuditError on any error finding, ``mode='warn'``
    emits an AuditWarning, ``mode=None`` only returns the findings;
    any other mode string is a typed ValueError (a typo must not
    silently disable enforcement).  ``allow`` drops named checks
    (record WHY at the call site — the pragma mechanism's
    programmatic form)."""
    if mode not in (None, "warn", "error"):
        raise ValueError(
            f"audit mode {mode!r} is not None|'warn'|'error' — an "
            f"unknown mode must not silently skip enforcement")
    findings = []
    variants = engine.audit_programs()
    eng_name = type(engine).__name__
    spec = None
    for name, (jitted, args_thunk) in variants.items():
        args = args_thunk()
        if spec is None:
            import jax
            state_aval = (args[0] if hasattr(args[0], "dtype")
                          else jax.ShapeDtypeStruct((), np.float32))
            spec = engine_spec(engine, state_aval)
        closed = trace_variant(jitted, args)
        findings += audit_jaxpr(closed, spec,
                                where=f"{eng_name}.{name}")
    if ledger:
        findings += check_ledger(engine, tol=ledger_tol)
    findings = [f for f in findings if f.check not in allow]
    if mode == "error":
        raise_findings(findings, where=eng_name)
    elif mode == "warn":
        for f in findings:
            warnings.warn(str(f), AuditWarning, stacklevel=2)
    return findings


# ---------------------------------------------------------------------
# check 8: ledger cross-validation

def report_kwargs(engine) -> dict:
    """The ``sg.memory_report(...)`` kwargs matching this engine's
    actual build (exchange / page plan / pair plan / push sparsity /
    query batch) — factored out of ``check_ledger`` so the runtime
    memory observatory (lux_tpu/memwatch.py, round 22) prices the
    SAME program the compile-time drift check audits; two
    independently-maintained kwarg derivations would let the two
    ledgers silently diverge."""
    from lux_tpu.engine.push import PushEngine
    is_push = isinstance(engine, PushEngine)
    kw = dict(exchange=engine.exchange)
    if getattr(engine, "page_plan", None) is not None:
        # paged engines carry the plan arrays + page buffer instead
        # of the tiled/owner edge layout (memory_report prices the
        # actual plan array bytes)
        kw["page_plan"] = engine.page_plan
        if not is_push:
            from lux_tpu.engine.pull import _dot_kdim
            kw["pair_kdim"] = _dot_kdim(engine.program)
    if engine.pairs is not None:
        kw["pairs"] = engine.pairs
        if not is_push:
            from lux_tpu.engine.pull import _dot_kdim
            kw["pair_kdim"] = _dot_kdim(engine.program)
    if is_push:
        kw["push_sparse"] = bool(engine.enable_sparse)
        # query-batched labels [P, vpad, B]: the ledger must price
        # the B-wide state + active mask or every batched build
        # would read as drift (ROADMAP item 2; memory_report's
        # query_batch pricing) — pull engines carry B through
        # state_bytes instead (the correction below)
        kw["query_batch"] = int(getattr(engine, "batch", None) or 1)
    if getattr(engine, "use_mxu", False):
        # the MXU one-hot reduce materializes the [C, E, W] int8
        # lane matrix (round 23) — price it at the engine's actual
        # chunk width so a use_mxu build's ledger stays honest
        kw["use_mxu"] = True
        lay = getattr(engine, "tiles", None) \
            or getattr(engine, "owner", None)
        if lay is not None and getattr(lay, "E", None):
            kw["mxu_tile_e"] = int(lay.E)
    return kw


def priced_argument_bytes(engine) -> int:
    """The ledger's price for the engine's resident ARGUMENT arrays —
    ``memory_report`` total minus the per-iteration temporary terms,
    plus the program-level state-width/extra-array corrections.  This
    is the ``expected`` side of the ledger-drift comparison, shared
    by ``check_ledger`` and the runtime observatory's per-replica
    byte ledger (lux_tpu/memwatch.py)."""
    ledger = engine.sg.memory_report(**report_kwargs(engine))
    expected = int(ledger["total_bytes"])
    # memory_analysis argument bytes cover resident ARGUMENT arrays
    # only — subtract the advisor's per-iteration temporary terms
    # (pair/paged delivery intermediates, the page buffer) so the
    # drift comparison is apples to apples
    for tk in ("pair_temp_bytes_per_part",
               "page_buffer_bytes_per_part",
               "page_temp_bytes_per_part",
               "mxu_temp_bytes_per_part"):
        expected -= engine.sg.num_parts * int(ledger.get(tk, 0))
    # the ledger prices scalar f32 state; K-vector programs carry
    # state_bytes per vertex — correct the vertex term so colfilter's
    # [vpad, 20] table does not read as edge-ledger drift
    sb = getattr(engine.program, "state_bytes", None)
    if sb:
        expected += engine.sg.num_parts * engine.sg.vpad * (sb - 4)
    # program-contributed extra arrays (batched reset vectors, the
    # round-21 pull deg_corr columns) are jit ARGUMENTS by the
    # no-closure convention — price their actual bytes, or every
    # extra-carrying program reads as edge-ledger drift (batched ppr
    # rode the tolerance on one [vpad, B] extra and tripped it on
    # the second)
    xa = getattr(engine.program, "extra_arrays", None)
    if xa is not None:
        expected += sum(np.asarray(v).nbytes
                        for v in xa(engine.sg).values())
    return expected


def check_ledger(engine, tol: float = 0.5, where: str | None = None):
    """Compile the engine's single step on the CURRENT backend and
    compare XLA ``memory_analysis`` argument bytes against the priced
    ledger ``sg.memory_report(...)``.  The ratio must stay within
    [1/(1+tol), 1+tol] — see the module docstring for the tolerance
    rationale (chunk/tile padding sits above the ledger's epad-based
    lower bounds; only meaningful on graphs dense enough that edges
    dominate padding)."""
    where = where or type(engine).__name__
    variants = engine.audit_programs()
    jitted, args_thunk = variants["step"]
    try:
        compiled = jitted.lower(*args_thunk()).compile()
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — backend without AOT stats
        return [Finding("ledger-drift", "warn", where,
                        f"memory_analysis unavailable ({e}); ledger "
                        f"cross-validation skipped")]
    if ma is None or not getattr(ma, "argument_size_in_bytes", 0):
        return []
    measured = int(ma.argument_size_in_bytes)
    expected = priced_argument_bytes(engine)
    ratio = measured / max(1, expected)
    if not (1.0 / (1.0 + tol) <= ratio <= 1.0 + tol):
        return [Finding(
            "ledger-drift", "error", where,
            f"compiled step argument bytes {measured} vs priced "
            f"ledger {expected} (ratio {ratio:.2f}) outside the "
            f"stated tolerance x{1 + tol:.2f} — "
            f"graph.memory_report has drifted from the compiler "
            f"(exchange={engine.exchange})")]
    return []


# ---------------------------------------------------------------------
# repo-wide audit (the tier-1 entry; python -m lux_tpu.audit)

def _matrix_graphs():
    from lux_tpu.graph import Graph

    def mk(nv, ne, weighted=False, seed=0):
        r = np.random.default_rng(seed)
        src = r.integers(0, nv, ne)
        dst = r.integers(0, nv, ne)
        w = (r.integers(1, 6, ne).astype(np.float32)
             if weighted else None)
        return Graph.from_edges(src, dst, nv, weights=w)

    return {
        "tiny": mk(256, 2048),
        "tiny_w": mk(256, 2048, weighted=True),
        # dense enough that edge arrays dominate padding: the ledger
        # cross-check is meaningful here (see check_ledger docstring)
        "dense": mk(2048, 32768),
        "dense_w": mk(2048, 32768, weighted=True, seed=1),
    }


def matrix_configs(ledger: bool = True):
    """The repo-wide engine configuration matrix: [(label, build
    thunk, ledger?)] — shared by ``run_repo_audit`` and the
    communication observatory (lux_tpu/comms.py walks the SAME
    engines' step programs for its per-collective byte ledger, so
    the two subsystems can never audit different programs).  Mesh
    configurations are included when >= 2 devices are visible (the
    tier-1 test runs on the 8-virtual-device conftest mesh)."""
    import jax

    from lux_tpu.apps import colfilter, components, pagerank, sssp
    from lux_tpu.graph import pair_relabel

    graphs = _matrix_graphs()
    ndev = len(jax.devices())
    mesh = None
    if ndev >= 2:
        from lux_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(2)

    configs = []   # (label, build thunk, ledger?)
    g = graphs["tiny"]
    gw = graphs["tiny_w"]
    configs.append(("pagerank_np2_gather",
                    lambda: pagerank.build_engine(g, num_parts=2),
                    False))
    configs.append(("pagerank_np4_owner",
                    lambda: pagerank.build_engine(g, num_parts=4,
                                                  exchange="owner"),
                    False))

    def _pair_engine():
        g2, _perm, starts = pair_relabel(g, 2, pair_threshold=8)
        return pagerank.build_engine(g2, num_parts=2,
                                     pair_threshold=8, starts=starts)

    configs.append(("pagerank_np2_pair", _pair_engine, False))
    configs.append(("sssp_np2_sparse",
                    lambda: sssp.build_engine(g, 0, num_parts=2),
                    False))
    configs.append(("sssp_np2_delta_w",
                    lambda: sssp.build_engine(
                        gw, 0, num_parts=2, weighted=True,
                        delta=1.0),
                    False))
    configs.append(("cc_np2_dense_only",
                    lambda: components.build_engine(
                        g, num_parts=2, enable_sparse=False),
                    False))
    configs.append(("colfilter_np1_dot",
                    lambda: colfilter.build_engine(gw, num_parts=1),
                    False))

    def _pair_dot_engine():
        g2, _perm, starts = pair_relabel(gw, 2, pair_threshold=8)
        return colfilter.build_engine(g2, num_parts=2,
                                      pair_threshold=8, starts=starts)

    configs.append(("colfilter_np2_pair_dot", _pair_dot_engine, False))
    # paged two-level gather (ops/pagegather.py, round 15): the page
    # row-fetch + Pallas lane shuffle must hold the SAME one-access
    # budget as the flat gather (paged_table_shapes) with no pragma —
    # dense pull, dense push, the SDDMM dot path, the owner-side
    # generation scan (page-reshaped shard gathers), and a batched
    # B > 1 build
    configs.append(("pagerank_np2_paged",
                    lambda: pagerank.build_engine(g, num_parts=2,
                                                  gather="paged"),
                    False))
    configs.append(("sssp_np2_paged",
                    lambda: sssp.build_engine(g, 0, num_parts=2,
                                              gather="paged"),
                    False))
    configs.append(("pagerank_np4_owner_paged",
                    lambda: pagerank.build_engine(g, num_parts=4,
                                                  exchange="owner",
                                                  gather="paged"),
                    False))
    configs.append(("colfilter_np2_paged_dot",
                    lambda: colfilter.build_engine(gw, num_parts=2,
                                                   gather="paged"),
                    False))
    configs.append(("ppr_np2_paged_batched",
                    lambda: pagerank.build_engine(g, num_parts=2,
                                                  sources=[0, 3, 7],
                                                  gather="paged"),
                    False))
    # page-major layout (round 16, ops/pagegather.py): the full-fill
    # gather rows + virtual-row takes must hold the same one-access
    # budget (the virtual take's operand is the [Rg, 128] value
    # buffer, shape-distinct from the table by _pad8_distinct); the
    # OWNER page-major routing must keep the generation scan AND
    # lower its exchange through all_to_all — for sum too (engine_
    # spec: no psum_scatter, there are no pre-reduced partials)
    configs.append(("pagerank_np2_pagemajor",
                    lambda: pagerank.build_engine(
                        g, num_parts=2, gather="pagemajor"),
                    False))
    configs.append(("cc_np2_pagemajor",
                    lambda: components.build_engine(
                        g, num_parts=2, enable_sparse=False,
                        gather="pagemajor"),
                    False))
    configs.append(("pagerank_np4_owner_pagemajor",
                    lambda: pagerank.build_engine(
                        g, num_parts=4, exchange="owner",
                        gather="pagemajor"),
                    False))
    # query-batched engines (ROADMAP item 2): the gather budget must
    # hold at B > 1 — ONE [P*vpad, B] table gather per dense pull/push
    # iteration, ZERO in owner mode — and the owner collective
    # schedule must be unchanged by the trailing query axis
    QB = [0, 3, 7, 11]
    configs.append(("ksssp_np2_batched",
                    lambda: sssp.build_engine(g, num_parts=2,
                                              sources=QB),
                    False))
    configs.append(("ksssp_np4_owner_batched",
                    lambda: sssp.build_engine(g, num_parts=4,
                                              sources=QB,
                                              exchange="owner"),
                    False))
    configs.append(("ppr_np2_batched",
                    lambda: pagerank.build_engine(g, num_parts=2,
                                                  sources=QB),
                    False))
    configs.append(("ppr_np4_owner_batched",
                    lambda: pagerank.build_engine(g, num_parts=4,
                                                  sources=QB,
                                                  exchange="owner"),
                    False))
    configs.append(("cc_np2_batched",
                    lambda: components.build_engine(g, num_parts=2,
                                                    sources=QB[:2]),
                    False))

    # live-graph delta revalidation (round 20, lux_tpu/livegraph.py):
    # the delta-relax step rides the SAME gather budget as the dense
    # iterations — ONE state-table gather (the delta-source fetch;
    # improvements come from a whole-table compare, never a second
    # gather) — and the dense programs themselves are UNCHANGED by
    # serving a live graph, so the budget holds across the whole
    # matrix with no pragma.
    def _live(builder):
        from lux_tpu.livegraph import LiveGraph
        lg = LiveGraph(g, capacity=64)
        lg.append_edges([1, 2, 3], [9, 17, 33])
        # a published TOMBSTONE slot (round 21): the audited step
        # must keep its single state-table gather with the d_kind
        # mask in the jaxpr, not just for pure-append deltas
        lg.delete_edges([1], [9])
        eng = builder()
        lg.register_audit(eng)
        return eng

    configs.append(("sssp_np2_live_delta",
                    lambda: _live(lambda: sssp.build_engine(
                        g, 0, num_parts=2)),
                    False))
    configs.append(("ksssp_np2_live_batched",
                    lambda: _live(lambda: sssp.build_engine(
                        g, num_parts=2, sources=QB)),
                    False))
    configs.append(("cc_np2_live_delta",
                    lambda: _live(lambda: components.build_engine(
                        g, num_parts=2)),
                    False))

    # MXU compute core (round 23, ops/tiled use_mxu): the one-hot
    # contraction programs must hold the SAME static guarantees as
    # the VPU formulations — gather budget 1 (the tournament's
    # route-back is a matmul, never a second table gather), dtype
    # discipline (int8 one-hot, int32 vote accumulators, uint32
    # order encodings — all <= 4 B), and identity-init (the frontier
    # MXU path's delta scatter-ADD is zero-initialized = the sum
    # identity, NO pragma).  ppr_np2_batched above already audits the
    # AUTO-engaged MXU path (B=8 >= the scalemodel break-even);
    # these force it onto the kinds/exchanges auto leaves on the VPU.
    configs.append(("pagerank_np2_mxu",
                    lambda: pagerank.build_engine(g, num_parts=2,
                                                  use_mxu=True),
                    False))
    configs.append(("sssp_np2_mxu",
                    lambda: sssp.build_engine(g, 0, num_parts=2,
                                              use_mxu=True),
                    False))
    configs.append(("cc_np2_mxu_dense",
                    lambda: components.build_engine(
                        g, num_parts=2, enable_sparse=False,
                        use_mxu=True),
                    False))
    configs.append(("pagerank_np4_owner_mxu",
                    lambda: pagerank.build_engine(g, num_parts=4,
                                                  exchange="owner",
                                                  use_mxu=True),
                    False))
    if ledger:
        gd = graphs["dense"]
        gdw = graphs["dense_w"]
        configs.append(("pagerank_np2_ledger",
                        lambda: pagerank.build_engine(gd, num_parts=2),
                        True))
        configs.append(("sssp_np2_ledger",
                        lambda: sssp.build_engine(gdw, 0, num_parts=2,
                                                  weighted=True),
                        True))
        # the ledger-drift check must stay honest at B > 1: the
        # priced [P*vpad, B] state table (memory_report query_batch /
        # the pull state_bytes correction) vs the compiled step's
        # argument bytes
        configs.append(("ksssp_np2_batched_ledger",
                        lambda: sssp.build_engine(
                            gd, num_parts=2, sources=list(range(8))),
                        True))
        configs.append(("ppr_np2_batched_ledger",
                        lambda: pagerank.build_engine(
                            gd, num_parts=2, sources=list(range(8))),
                        True))
        # paged ledger: the priced plan arrays + page buffer vs the
        # compiled step's argument bytes
        configs.append(("pagerank_np2_paged_ledger",
                        lambda: pagerank.build_engine(
                            gd, num_parts=2, gather="paged"),
                        True))
        # MXU ledger: the priced mxu_temp one-hot term must keep a
        # forced use_mxu build inside the drift tolerance (the
        # [C, E, W] int8 matrix is a TEMPORARY — subtracted for the
        # argument-bytes comparison, named for the runtime ledger)
        configs.append(("pagerank_np2_mxu_ledger",
                        lambda: pagerank.build_engine(
                            gd, num_parts=2, use_mxu=True),
                        True))
    if mesh is not None:
        configs.append(("pagerank_mesh2_gather",
                        lambda: pagerank.build_engine(g, num_parts=2,
                                                      mesh=mesh),
                        False))
        configs.append(("pagerank_mesh2_owner_sum",
                        lambda: pagerank.build_engine(
                            g, num_parts=2, mesh=mesh,
                            exchange="owner"),
                        False))
        configs.append(("cc_mesh2_owner_a2a",
                        lambda: components.build_engine(
                            g, num_parts=2, mesh=mesh,
                            exchange="owner"),
                        False))
        configs.append(("cc_mesh2_owner_ring",
                        lambda: components.build_engine(
                            g, num_parts=2, mesh=mesh,
                            exchange="owner",
                            owner_minmax_fused=True),
                        False))
        configs.append(("sssp_mesh2_sparse",
                        lambda: sssp.build_engine(g, 0, num_parts=2,
                                                  mesh=mesh),
                        False))
        # forced-MXU mesh config: the contraction core must leave
        # the collective schedule untouched (the one-hot matmuls are
        # purely part-local; only the reduce formulation changes)
        configs.append(("sssp_mesh2_mxu",
                        lambda: sssp.build_engine(g, 0, num_parts=2,
                                                  mesh=mesh,
                                                  use_mxu=True),
                        False))
        # batched mesh configs: the single-gather hold AND the owner
        # collective schedule (psum_scatter / all_to_all) at B > 1
        configs.append(("ksssp_mesh2_batched",
                        lambda: sssp.build_engine(g, num_parts=2,
                                                  mesh=mesh,
                                                  sources=QB),
                        False))
        configs.append(("ppr_mesh2_owner_batched",
                        lambda: pagerank.build_engine(
                            g, num_parts=2, mesh=mesh, sources=QB,
                            exchange="owner"),
                        False))
        configs.append(("cc_mesh2_owner_batched",
                        lambda: components.build_engine(
                            g, num_parts=2, mesh=mesh,
                            sources=QB[:2], exchange="owner"),
                        False))
        # page-major owner ROUTING on a real mesh axis (round 19):
        # the all_to_all of complete message rows — audited for
        # schedule here and priced per byte by the comm ledger
        # (lux_tpu/comms.py oracle: [P_local, P, Mg, 128] rows)
        configs.append(("pagerank_mesh2_owner_pagemajor",
                        lambda: pagerank.build_engine(
                            g, num_parts=2, mesh=mesh,
                            exchange="owner", gather="pagemajor"),
                        False))
    if ndev >= 4:
        from lux_tpu.parallel.mesh import make_mesh
        mesh4 = make_mesh(4)
        # the POST-SHRINK shape (round 11, elastic recovery): parts
        # fixed at 8, device mapping changed to a smaller mesh — the
        # owner generation scan must cover 2 device-local parts and
        # the collective schedule must hold at the new ndev (the
        # acceptance gate resilience's re-placement relies on)
        configs.append(("pagerank_mesh4x8parts_owner_shrunk",
                        lambda: pagerank.build_engine(
                            g, num_parts=8, mesh=mesh4,
                            exchange="owner"),
                        False))
    return configs


def run_repo_audit(verbose: bool = False, ledger: bool = True):
    """Build the engine matrix (``matrix_configs``) on the current
    (CPU) backend and audit every program variant of every
    configuration.  Returns the list of error/warn Findings (empty =
    clean)."""
    all_findings = []
    for label, build, do_ledger in matrix_configs(ledger=ledger):
        eng = build()
        fs = audit_engine(eng, mode=None, ledger=do_ledger)
        if verbose:
            n_err = sum(1 for f in fs if f.severity == "error")
            print(f"# audit {label}: "
                  f"{len(eng.audit_programs())} variants, "
                  f"{n_err} errors, "
                  f"{len(fs) - n_err} warnings")
        for f in fs:
            all_findings.append(dataclasses.replace(
                f, where=f"{label}/{f.where}"))
    return all_findings


def digest(findings, mode: str = "warn") -> dict:
    """JSON-serializable summary of an audit — the field bench.py
    metric lines carry.  ``mode`` is the -audit mode the build ran
    under; scripts/check_bench.py requires it ('warn'|'error') and
    rejects metric lines whose digest carries errors."""
    errs = [f for f in findings if f.severity == "error"]
    warns = [f for f in findings if f.severity == "warn"]
    return {
        "mode": mode,
        "errors": len(errs),
        "warnings": len(warns),
        "failed_checks": sorted({f.check for f in errs}),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.audit",
        description="repo-wide compile-time program audit on the CPU "
                    "backend (no TPU needed)")
    ap.add_argument("-no-ledger", action="store_true",
                    dest="no_ledger",
                    help="skip the ledger cross-validation (no "
                         "CPU compiles, tracing only)")
    ap.add_argument("-warnings-as-errors", action="store_true",
                    dest="werror",
                    help="exit 1 on warning-severity findings too "
                         "(loop-invariant)")
    ap.add_argument("-v", "-verbose", action="store_true",
                    dest="verbose")
    args = ap.parse_args(argv)

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        # backend already initialized (e.g. under pytest) — the
        # conftest pins CPU there; on a TPU session tracing is still
        # host-side and the audit stays valid
        pass

    findings = run_repo_audit(verbose=args.verbose,
                              ledger=not args.no_ledger)
    errors = [f for f in findings if f.severity == "error"]
    warns = [f for f in findings if f.severity == "warn"]
    for f in findings:
        print(("ERROR: " if f.severity == "error" else "WARNING: ")
              + str(f))
    bad = errors + (warns if args.werror else [])
    if bad:
        print(f"audit: {len(errors)} error(s), {len(warns)} "
              f"warning(s) — FAILED")
        return 1
    print(f"audit: clean ({len(warns)} warning(s))")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
