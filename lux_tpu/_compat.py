"""Compatibility shims for the pinned environment's jax.

jax 0.4.37 (this container) predates two things the engines rely on
(both are plain aliases/identities upstream; newer jax has them and
each registration below is skipped):

- the upstream batching rule for ``lax.optimization_barrier`` (added
  in 0.4.38).  The engines place the barrier inside vmapped per-part
  steps (engine/pull.py, engine/push.py, ops/{tiled,pairs,owner}.py),
  so without the rule every vmapped engine trace dies with
  ``NotImplementedError: Batching rule for 'optimization_barrier' not
  implemented`` — the bulk of the seed test failures.  The rule is
  the identity (the barrier is semantically a no-op), exactly what
  upstream registered.
- the top-level ``jax.shard_map`` export (graduated from
  ``jax.experimental.shard_map`` later).  The mesh engines and
  device_check call it by the stable name with the renamed
  ``check_vma=`` kwarg; the alias translates it to 0.4.37's
  ``check_rep=``.
- ``jax.lax.pcast``: the varying-manual-axes (VMA) cast newer
  shard_map tracing requires for constant scan carries
  (ops/owner.py, ops/tiled.py).  0.4.37's shard_map has no VMA
  analysis, so the value-level identity is the correct shim.
"""

from __future__ import annotations


def register() -> None:
    try:
        import jax
        from jax._src.lax import lax as _lax
        from jax.interpreters import batching
    except Exception:           # noqa: BLE001 — no/odd jax: nothing to fix
        return
    prim = getattr(_lax, "optimization_barrier_p", None)
    if prim is not None and prim not in batching.primitive_batchers:
        def _batcher(batched_args, batch_dims, **params):
            return prim.bind(*batched_args, **params), batch_dims

        batching.primitive_batchers[prim] = _batcher

    if "shard_map" not in jax.__dict__:
        try:
            import inspect

            from jax.experimental.shard_map import shard_map
        except Exception:       # noqa: BLE001 — neither name: leave it
            shard_map = None    # (the pcast shim below still applies)
        if shard_map is None:
            pass
        elif "check_vma" in inspect.signature(shard_map).parameters:
            jax.shard_map = shard_map
        else:
            import functools

            @functools.wraps(shard_map)
            def _shard_map(f, /, *args, **kwargs):
                if "check_vma" in kwargs:
                    kwargs["check_rep"] = kwargs.pop("check_vma")
                # old check_rep has no replication rule for while_loop
                # (the engines' converge loops); it is a safety
                # analysis only — off matches what newer jax accepts
                kwargs.setdefault("check_rep", False)
                return shard_map(f, *args, **kwargs)

            jax.shard_map = _shard_map

    if not hasattr(jax.lax, "pcast"):
        def _pcast(x, axes=None, *, to=None, **_kw):
            return x

        jax.lax.pcast = _pcast


register()
