"""Resilient run supervision: crash classification, retry/resume,
duration-budgeted segmentation, and bench sample screening.

The reference inherits fault tolerance from the Legion/Realm runtime
it sits on (SURVEY §1); lux_tpu's substrate is JAX over the axon
tunnel, whose measured failure modes (PERF_NOTES round 5) are:
transient TPU worker death (one bench config crashed outright and a
pagerank-mp sample collapsed 10x in BENCH_r05), the ~55 s
single-execution duration wall, and HTTP 413 rejects for
constant-heavy programs.  This module is the recovery story:

- ``classify`` sorts failures into RETRYABLE (tunnel/worker death,
  injected crashes, NaN escapes caught by debug.check_finite — the
  last checkpoint predates the corruption, so resuming can help),
  TOPOLOGY (round 11: devices or worker processes GONE — device-
  unavailable / coordination-service-heartbeat signatures, injected
  device loss, heartbeat deadline misses — retrying on the same mesh
  replays the same dead topology, but re-placing onto the survivors
  can finish the run) and FATAL (HTTP 413 / OOM compile rejects,
  StallError livelocks, programming errors — deterministic, retrying
  reruns the same bug).  A deterministic divergence still surfaces:
  it recurs until the retry budget is exhausted and the last error
  propagates.
- ``supervise`` retries retryable failures with exponential backoff
  (decorrelated-jittered: synchronized backoff across worker
  processes is a retry stampede on the coordination service).
  TOPOLOGY failures route through an ``on_topology`` handler — the
  elastic re-placement path below — and are fatal without one.
- the ELASTIC path (``supervised_run(..., elastic=make_engine)``): a
  topology fault rebuilds the mesh over the surviving devices (parts
  P fixed — the largest device count dividing num_parts; checkpoints
  hold the global ``[P, vpad, ...]`` host view, so re-sharding is
  just ``eng.place`` on the new engine), resets the duration budget's
  learned rate, and resumes from the last checkpoint — bitwise-equal
  to an uninterrupted run on the smaller mesh.  Multi-process runs
  pair this with per-segment heartbeat supervision
  (lux_tpu/heartbeat.py): survivors detect the death at a segment
  boundary, agree on the shrunken topology, and relaunch degraded
  (jax.distributed cannot drop a member in-process).
- ``supervised_run`` / ``supervised_converge`` compose the retry loop
  with checkpoint.py's segmented paths: every segment checkpoints
  atomically, retries AUTO-RESUME from the last checkpoint instead of
  restarting, and optional fault injection (lux_tpu/faults.py) plus
  the debug.py finite guard run at each boundary.
- a ``seg_budget`` sizes segments with ``segmented.DurationBudget``
  so each XLA execution stays under the duration wall.
- ``screen_outliers`` is bench.py's discard-and-rerun rule for
  tunnel-variance collapses (samples >3x off the median).
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from statistics import median
from typing import Callable

import numpy as np

RETRYABLE = "retryable"
FATAL = "fatal"
TOPOLOGY = "topology"

# Topology-fault signatures: the mesh itself changed underneath the
# program (a chip died, a worker process left the coordination
# service).  Scanned BEFORE the fatal/transient word scans — a
# topology signature is strictly more specific than the generic
# "unavailable"/"heartbeat" transient words (which would wrongly
# retry on the same dead mesh) and misclassifying one as fatal aborts
# a run that re-placement could finish.
_TOPOLOGY_RE = re.compile(
    r"device(?:s)?\s+(?:\S+\s+)?(?:is\s+|are\s+)?unavailable|"
    r"DEVICE_UNAVAILABLE|"
    r"device\s+\S+\s+(?:lost|removed|failed)|"
    r"coordination\s+service|"
    r"heartbeat\s+(?:deadline|timeout|timed[\s_-]?out|missed)|"
    r"slice\s+health|task\s+\d+\s+(?:left|lost|missing)", re.I)

# Deterministic failures — retrying replays the same program into the
# same rejection.  Checked before the transient MESSAGE patterns: an
# XlaRuntimeError carrying an HTTP 413 or an OOM must not match the
# worker/tunnel signatures below.  \b413\b so a port / byte count /
# request id containing "413..." cannot condemn a transient error.
_FATAL_RE = re.compile(
    r"\b413\b|too\s+large|resource.?exhausted|out of memory|"
    r"failed to allocate|program shape", re.I)

# Transient tunnel/worker signatures: connection loss, worker death,
# deadline blowouts — the things a fresh attempt can outlive.
_RETRYABLE_RE = re.compile(
    r"unavailable|connection|socket|deadline|timed?[\s_-]?out|"
    r"worker|terminated|cancell?ed|aborted|heartbeat|broken pipe|"
    r"reset by peer|transport|tunnel", re.I)

_RETRYABLE_TYPES = (ConnectionError, TimeoutError, BrokenPipeError,
                    EOFError)

# Deterministic filesystem failures (a bad -resume path, a read-only
# checkpoint dir): OSError subclasses a retry cannot fix.
_FATAL_OSERRORS = (FileNotFoundError, NotADirectoryError,
                   IsADirectoryError, PermissionError, FileExistsError)


def classify(exc: BaseException) -> str:
    """RETRYABLE, TOPOLOGY or FATAL for one failure (see module
    docstring for the taxonomy).  Typed checks outrank every message
    scan (the PR-1 convention)."""
    from lux_tpu import checkpoint, debug, faults, health

    if isinstance(exc, (faults.InjectedDeviceLoss,
                        faults.InjectedWorkerKill)):
        return TOPOLOGY
    from lux_tpu import heartbeat
    if isinstance(exc, heartbeat.WorkerLostError):
        return TOPOLOGY        # a peer missed its heartbeat deadline:
        #                        its devices are gone with it
    if isinstance(exc, faults.InjectedWorkerCrash):
        return RETRYABLE
    from lux_tpu import fleet
    if isinstance(exc, fleet.AdmissionError):
        return FATAL            # an intentional shed is a DECISION,
        #                         not a failure: a supervisor that
        #                         retried it would re-admit a query
        #                         the serving tier chose to reject
        #                         (and its message says 'shed'/
        #                         'deadline', which must never hit
        #                         the retryable word scan below)
    from lux_tpu import audit
    if isinstance(exc, audit.AuditError):
        return FATAL            # a static-audit violation is a
        #                         property of the BUILD: retrying
        #                         re-traces the same program into the
        #                         same typed refusal (and the finding
        #                         text may mention 'tunnel'/'413',
        #                         which must not hit the retryable
        #                         message scan below)
    if isinstance(exc, health.HealthError):
        return FATAL            # fatal-with-diagnosis: the watchdog
        #                         saw corruption in the STATE itself
        #                         (which check/part/iteration is on
        #                         the exception) — blind retry/resume
        #                         reruns into the same diagnosis
    if isinstance(exc, checkpoint.CorruptCheckpointError):
        return RETRYABLE        # the retry's resume goes through
        #                         load_any, which falls back one
        #                         GENERATION and replays the lost
        #                         segment — never the deterministic-
        #                         OSError fatal bucket below
    if isinstance(exc, debug.StallError):
        return FATAL
    if isinstance(exc, debug.DivergenceError):
        return RETRYABLE        # possible transient corruption;
        #                         deterministic NaN exhausts retries
    if isinstance(exc, _RETRYABLE_TYPES):
        return RETRYABLE        # typed transport errors outrank any
        #                         message scan ("...writing request
        #                         payload too large buffer" etc.)
    msg = f"{type(exc).__name__}: {exc}"
    if _TOPOLOGY_RE.search(msg):
        return TOPOLOGY        # XlaRuntimeError device-unavailable /
        #                        coordination-service signatures (the
        #                        raw form a real chip/worker loss
        #                        surfaces as through jax.distributed)
    if _FATAL_RE.search(msg):
        return FATAL
    if isinstance(exc, _FATAL_OSERRORS):
        return FATAL
    if isinstance(exc, OSError):
        return RETRYABLE        # tunnel I/O
    if _RETRYABLE_RE.search(msg):
        return RETRYABLE
    return FATAL


@dataclasses.dataclass
class RetryPolicy:
    """Backoff for retryable failures.  ``sleep`` is injectable so
    tests (and dry runs) never actually wait.

    Delays use DECORRELATED JITTER (delay_k drawn uniformly from
    [backoff_s, min(max, 3 * delay_{k-1})]): plain exponential
    backoff is synchronized across worker processes — after a shared
    transient (a coordination-service hiccup hits every worker at
    once) they all retry at the same instants, a retry stampede that
    re-knocks the service over.  The draw is SEEDED (default: derived
    from the pid, so workers decorrelate; pass ``jitter_seed`` for
    bit-deterministic tests) and cached per failure index, so
    ``delay_s(k)`` is stable within one policy instance.
    ``jitter=0`` restores the exact exponential schedule."""

    retries: int = 3
    backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 60.0
    sleep: Callable[[float], None] = time.sleep
    jitter: float = 1.0
    jitter_seed: int | None = None
    _delays: dict = dataclasses.field(default_factory=dict, init=False,
                                      repr=False, compare=False)
    _rng: object = dataclasses.field(default=None, init=False,
                                     repr=False, compare=False)

    def delay_s(self, failure_index: int) -> float:
        k = int(failure_index)
        exp = min(self.backoff_s * self.backoff_factor ** k,
                  self.max_backoff_s)
        if not self.jitter:
            return exp
        if k in self._delays:
            return self._delays[k]
        if self._rng is None:
            seed = (self.jitter_seed if self.jitter_seed is not None
                    else (os.getpid() * 2654435761) & 0xFFFFFFFF)
            self._rng = np.random.default_rng(seed)
        prev = self._delays.get(k - 1, self.backoff_s)
        lo = self.backoff_s
        hi = min(self.max_backoff_s, max(lo, 3.0 * prev))
        frac = float(self._rng.random()) * min(1.0, max(0.0,
                                                        self.jitter))
        d = min(self.max_backoff_s, lo + (hi - lo) * frac)
        self._delays[k] = d
        return d


@dataclasses.dataclass
class FlapDetector:
    """Deaths-in-a-window flap detection (round 24, the self-healing
    fleet's quarantine trigger).  A replica that keeps dying right
    after resurrection is burning respawn/recompile/canary work and
    churning the routing table — past ``threshold`` deaths inside
    ``window_s`` the supervisor should stop resurrecting it and
    quarantine typed (lux_tpu/fleet.py) instead of flapping forever.
    ``clock`` is injectable so tests drive the window
    deterministically."""

    threshold: int = 3
    window_s: float = 60.0
    clock: Callable[[], float] = time.monotonic
    _deaths: dict = dataclasses.field(default_factory=dict, init=False,
                                      repr=False, compare=False)

    def record(self, name: str) -> int:
        """Record one death of ``name`` now; returns the death count
        inside the rolling window (>= threshold means flapping)."""
        now = float(self.clock())
        ds = [t for t in self._deaths.get(name, ())
              if now - t <= self.window_s]
        ds.append(now)
        self._deaths[name] = ds
        return len(ds)

    def deaths(self, name: str) -> int:
        now = float(self.clock())
        return sum(1 for t in self._deaths.get(name, ())
                   if now - t <= self.window_s)

    def flapping(self, name: str) -> bool:
        return self.deaths(name) >= self.threshold


@dataclasses.dataclass
class RunReport:
    """What the supervisor did: for logs and bench JSON lines."""

    attempts: int = 0
    failures: list = dataclasses.field(default_factory=list)
    #           ^ (exception type name, message[:200], classification)
    resumed_from: list = dataclasses.field(default_factory=list)
    #           ^ checkpoint iteration counter at each resume
    initial_resume: int | None = None
    #           ^ iteration a PRE-EXISTING checkpoint supplied to the
    #             first attempt (explicit resume=True only) — in-run
    #             retry resumes redo work this run already did and
    #             are deliberately NOT counted here
    total_iters: int = 0
    segments: int = 0
    counters: dict | None = None
    #           ^ device-side iteration-counter digest
    #             (telemetry.IterStats.summary()) when the run was
    #             supervised under an active iter-stats handle
    topology: list = dataclasses.field(default_factory=list)
    #           ^ one {from_ndev, to_ndev, lost_devices} per elastic
    #             mesh shrink (round 11) — a run that finished
    #             degraded says so on its report

    def as_dict(self) -> dict:
        return dict(attempts=self.attempts, segments=self.segments,
                    resumed_from=list(self.resumed_from),
                    initial_resume=self.initial_resume,
                    failures=[list(f) for f in self.failures],
                    total_iters=self.total_iters,
                    counters=self.counters,
                    topology=[dict(t) for t in self.topology])


def _flight_dump(exc: BaseException, kind: str) -> None:
    """Crash flight recorder hook (lux_tpu/tracing.py, round 13):
    dump the recent-event ring + last health word + placement
    metadata to FLIGHT.json when a recorder is installed.  The dump
    is best-effort by design — a postmortem writer must never mask
    the fault it is recording."""
    try:
        from lux_tpu import tracing
        tracing.flight_dump(
            reason=f"{type(exc).__name__}: {exc}"[:300],
            classification=kind)
    except Exception:           # noqa: BLE001 — see docstring
        pass


def supervise(attempt: Callable, policy: RetryPolicy | None = None,
              report: RunReport | None = None, on_topology=None):
    """Run ``attempt(k)`` (k = 0-based attempt index) under classified
    retries: retryable failures back off and retry, fatal ones (and
    retry-budget exhaustion) re-raise.  Returns (result, report).

    ``on_topology(exc)`` handles TOPOLOGY-classified failures (device
    or worker loss): it re-places the run onto a surviving topology
    and returns True, after which the next attempt proceeds WITHOUT
    backoff (the fault is structural, not congestion — idling the
    survivors buys nothing).  Returning False — or having no handler
    — makes the topology fault fatal: retrying on the same dead mesh
    replays the same failure."""
    from lux_tpu import telemetry

    policy = policy or RetryPolicy()
    report = report or RunReport()
    tel = telemetry.current()
    for k in range(max(0, policy.retries) + 1):
        report.attempts += 1
        try:
            return attempt(k), report
        except Exception as e:      # noqa: BLE001 — classified below
            kind = classify(e)
            report.failures.append(
                (type(e).__name__, str(e)[:200], kind))
            handled = False
            if (kind == TOPOLOGY and on_topology is not None
                    and k < policy.retries):
                handled = bool(on_topology(e))
            if kind == TOPOLOGY:
                tel.emit("topology_fault", attempt=k,
                         error=type(e).__name__, message=str(e)[:200],
                         handled=handled)
                # flight recorder (round 13): a topology transition —
                # handled or not — is postmortem-worthy; the dump
                # happens AFTER the event so the ring includes it
                _flight_dump(e, kind)
            fatal = (kind == FATAL
                     or (kind == TOPOLOGY and not handled)
                     or k >= policy.retries)
            if fatal:
                tel.emit("failure", attempt=k,
                         error=type(e).__name__, message=str(e)[:200],
                         classification=kind)
                if kind != TOPOLOGY:      # topology already dumped
                    _flight_dump(e, kind)
                raise
            if kind == TOPOLOGY:
                continue            # re-placed: retry immediately
            d = policy.delay_s(k)
            tel.emit("retry", attempt=k, error=type(e).__name__,
                     message=str(e)[:200], classification=kind,
                     backoff_s=round(d, 3))
            policy.sleep(d)
    raise AssertionError("unreachable")


def _make_segment(segment, seg_budget, per_size_compile=True):
    if seg_budget:
        from lux_tpu.segmented import DurationBudget
        return DurationBudget(float(seg_budget),
                              per_size_compile=per_size_compile)
    return segment


def _mesh_device_ids(eng):
    """Device ids of the engine's mesh (None for single-device
    engines) — what fault plans resolve DEVICE_LOSS/WORKER_KILL
    against."""
    if getattr(eng, "mesh", None) is None:
        return None
    return [d.id for d in eng.mesh.devices.flat]


def _mesh_after_loss(eng, exc):
    """The surviving-device mesh after a topology fault, or None when
    no shrink is possible: single-device engines have no topology to
    shrink; multi-host local-parts builds re-place by coordinated
    relaunch (lux_tpu/heartbeat.py), not in-process; and a fault that
    names no losses (and the backend re-probe shows everything alive)
    leaves nothing to shrink away.

    Parts P stay FIXED — the new mesh is the largest surviving device
    count dividing num_parts (graph.compatible_mesh_sizes), so the
    padded layout, every program shape, and the checkpointed global
    ``[P, vpad, ...]`` view are all reusable unchanged; only the
    part -> device mapping moves."""
    import jax

    from lux_tpu.parallel.mesh import make_mesh

    if getattr(eng, "mesh", None) is None:
        return None
    if eng.sg.local_parts is not None:
        return None
    devs = list(eng.mesh.devices.flat)
    lost = getattr(exc, "lost_devices", None)
    if lost:
        gone = {int(d) for d in lost}
        survivors = [d for d in devs if d.id not in gone]
    else:
        # no named losses: re-probe the backend and keep the mesh
        # devices the runtime still lists
        alive = {d.id for d in jax.devices()}
        survivors = [d for d in devs if d.id in alive]
    if len(survivors) == len(devs):
        return None
    sizes = eng.sg.compatible_mesh_sizes(len(survivors))
    if not sizes:
        return None
    return make_mesh(devices=survivors[:sizes[0]])


def _elastic_handler(box, make_engine, segment, report):
    """The supervise() on_topology hook for elastic runs: shrink the
    mesh over the survivors, rebuild the engine (``make_engine(mesh)``
    — engines compile per-mesh automatically since graph arrays are
    jit arguments), and reset the duration budget's learned rate (a
    per-segment rate measured on 8 devices is stale on 4 and would
    blow the duration wall on the first post-shrink segment).  The
    actual data movement happens on the retry's checkpoint resume:
    checkpoint.py re-shards the global host view via the NEW engine's
    ``place`` and emits the ``replace`` event."""

    def on_topology(exc):
        from lux_tpu import telemetry
        from lux_tpu.segmented import DurationBudget

        eng = box["eng"]
        mesh = _mesh_after_loss(eng, exc)
        if mesh is None:
            return False
        old = int(eng.mesh.devices.size)
        new = int(mesh.devices.size)
        lost = sorted(getattr(exc, "lost_devices", ()) or ())
        t0 = time.perf_counter()
        neweng = make_engine(mesh)
        if neweng.sg.num_parts != eng.sg.num_parts:
            raise ValueError(
                f"elastic engine factory changed num_parts "
                f"({eng.sg.num_parts} -> {neweng.sg.num_parts}); "
                f"re-placement keeps parts FIXED and changes only "
                f"the device mapping")
        box["eng"] = neweng
        if isinstance(segment, DurationBudget):
            segment.reset_rate(reason="mesh_shrink")
        report.topology.append(
            {"from_ndev": old, "to_ndev": new,
             "lost_devices": [int(d) for d in lost]})
        telemetry.current().emit(
            "mesh_shrink", from_ndev=old, to_ndev=new,
            lost=[int(d) for d in lost],
            parts=int(eng.sg.num_parts), error=type(exc).__name__,
            rebuild_seconds=round(time.perf_counter() - t0, 3))
        return True

    return on_topology


def _int_sentinel(eng):
    """The integer identity/sentinel value of the engine's program (the
    one-sentinel convention: faults.corrupt_state pokes it into
    integer-labeled states — sssp hop counts, components ids — so a
    seeded NAN plan can corrupt all four apps instead of crashing on
    the float-only nan_corrupt).  None for float programs."""
    ident = getattr(getattr(eng, "program", None), "identity", None)
    if ident is None:
        return None
    ident = np.asarray(ident)
    return int(ident) if np.issubdtype(ident.dtype, np.integer) else None


def _record_resume(path, report):
    from lux_tpu import checkpoint

    if checkpoint.any_generation(path):
        try:
            # generation-fallback-aware: records the iteration the
            # resume will ACTUALLY continue from (the .prev one when
            # the newest file is corrupt — a meta-only peek would
            # misreport the corrupt file's own counter, so this pays
            # the verifying load).  load_any QUARANTINES a corrupt
            # newest, so the fallback detection, its event and its
            # CRC cost all happen ONCE here; the attempt's resume
            # then reads the good generation directly.
            _leaves, meta, _used = checkpoint.load_any(path)
            report.resumed_from.append(int(meta.get("iter", 0)))
        except Exception:           # noqa: BLE001 — all gens corrupt
            pass                    # the attempt itself will surface it


def supervised_run(eng, num_iters: int, path: str, *,
                   policy: RetryPolicy | None = None,
                   segment=50, seg_budget: float | None = None,
                   resume: bool = False, faults=None,
                   guard: bool = True, report: RunReport | None = None,
                   elastic=None, heartbeat=None):
    """Supervised pull-engine fixed-iteration run: segmented +
    checkpointed to ``path``, with classified retries resuming from
    the last atomic checkpoint.  Returns (state, report).

    resume=False starts fresh (a stale file at ``path`` is removed so
    a crash before the first save cannot resurrect it); retries within
    the run always resume.  ``faults`` (faults.FaultPlan) and the
    finite ``guard`` run at each segment boundary BEFORE the save, so
    injected/real corruption never reaches a checkpoint.

    ``elastic`` (round 11): an engine FACTORY ``make_engine(mesh) ->
    engine`` — a TOPOLOGY-classified failure then rebuilds the mesh
    over the surviving devices and resumes on it instead of dying
    (see _elastic_handler).  ``heartbeat`` (lux_tpu/heartbeat.py): a
    Heartbeat board multi-process runs sync at every segment boundary
    — a dead peer raises a TOPOLOGY-classified WorkerLostError there
    instead of hanging the next collective."""
    from lux_tpu import checkpoint, debug

    report = report or RunReport()
    if not resume:
        checkpoint.remove(path)     # BOTH generations: a stale .prev
        #                             must not resurrect either
    if faults is not None and hasattr(faults, "bind_checkpoint"):
        faults.bind_checkpoint(path)
    # ONE segment sizer for the whole supervised run (not per
    # attempt): the duration budget's learned rate survives plain
    # retries and is explicitly reset on a topology change
    seg = _make_segment(segment, seg_budget)
    box = {"eng": eng}

    def hook(s, done):
        report.segments += 1
        out = None
        if faults is not None:
            res = faults.fire(s, int_value=_int_sentinel(box["eng"]),
                              device_ids=_mesh_device_ids(box["eng"]))
            if res is not None:
                s = out = box["eng"].place(res)
        if heartbeat is not None:
            heartbeat.sync(report.segments - 1)
        if guard:
            debug.check_finite(
                s, f"supervised pull run @ iteration {done}")
        return out

    # eng.run DONATES its state buffers, so a consumed state cannot
    # feed a second attempt — but a resuming attempt whose checkpoint
    # exists only reads the pytree STRUCTURE (checkpoint.py), so a
    # spent state (or an abstract eval_shape stub on a fresh-process
    # resume) serves as structure donor and the attempt skips
    # re-placing a fresh multi-hundred-MB state on device.  The
    # structure is mesh-independent, so it survives a re-placement.
    state0 = None

    def attempt(k):
        nonlocal state0
        cur = box["eng"]
        do_resume = resume or k > 0
        if do_resume:
            _record_resume(path, report)
            if k == 0 and report.resumed_from:
                report.initial_resume = report.resumed_from[0]
        will_load = do_resume and checkpoint.any_generation(path)
        if will_load and state0 is None:
            import jax
            try:                    # structure-only: no placement
                state0 = jax.eval_shape(cur.init_state)
            except Exception:       # noqa: BLE001 — untraceable init
                state0 = cur.init_state()
        elif not will_load:
            state0 = cur.init_state()
        return checkpoint.run_checkpointed(
            cur, state0, num_iters, path,
            segment=seg, resume=do_resume, on_segment=hook)

    on_topology = (None if elastic is None
                   else _elastic_handler(box, elastic, seg, report))
    state, report = supervise(attempt, policy, report,
                              on_topology=on_topology)
    if heartbeat is not None:
        heartbeat.finish()
    report.total_iters = num_iters
    _attach_counters(report)
    return state, report


def _attach_counters(report):
    """Fold the active iter-stats digest (device-side per-iteration
    counters accumulated by the segmented drivers) into the report, so
    RunReport.as_dict() carries the counter summary."""
    from lux_tpu import telemetry

    st = telemetry.current().iter_stats
    if st is not None:
        report.counters = st.summary()


def supervised_converge(eng, path: str, *,
                        policy: RetryPolicy | None = None,
                        segment=50, seg_budget: float | None = None,
                        resume: bool = False,
                        max_iters: int | None = None, faults=None,
                        guard: bool = True,
                        report: RunReport | None = None,
                        elastic=None, heartbeat=None):
    """Supervised push-engine convergence: segmented + checkpointed to
    ``path``, with classified retries resuming from the last atomic
    checkpoint.  Returns (label, active, total_iters, report).

    The boundary guard runs check_finite(allow_inf=True) — +inf is the
    legitimate unreached sentinel; NaN raises DivergenceError, which
    classifies retryable (the checkpoint predates the corruption).

    ``elastic`` / ``heartbeat``: same degraded-mesh recovery contract
    as supervised_run (engine factory re-placement on TOPOLOGY
    failures; per-segment heartbeat sync for multi-process runs)."""
    from lux_tpu import checkpoint, debug

    report = report or RunReport()
    if not resume:
        checkpoint.remove(path)
    if faults is not None and hasattr(faults, "bind_checkpoint"):
        faults.bind_checkpoint(path)
    seg = _make_segment(segment, seg_budget, per_size_compile=False)
    box = {"eng": eng}

    def hook(lbl, act, total, cnt):
        report.segments += 1
        out = None
        if faults is not None:
            res = faults.fire((lbl, act),
                              int_value=_int_sentinel(box["eng"]),
                              device_ids=_mesh_device_ids(box["eng"]))
            if res is not None:
                lbl, act = box["eng"].place(
                    *[np.asarray(x) for x in res])
                out = (lbl, act)
        if heartbeat is not None:
            heartbeat.sync(report.segments - 1)
        if guard:
            debug.check_finite(
                lbl, f"supervised converge @ iteration {total}",
                allow_inf=True)
        return out

    def attempt(k):
        do_resume = resume or k > 0
        if do_resume:
            _record_resume(path, report)
            if k == 0 and report.resumed_from:
                report.initial_resume = report.resumed_from[0]
        return checkpoint.converge_checkpointed(
            box["eng"], path, segment=seg,
            resume=do_resume, max_iters=max_iters, on_segment=hook)

    on_topology = (None if elastic is None
                   else _elastic_handler(box, elastic, seg, report))
    (label, active, total), report = supervise(
        attempt, policy, report, on_topology=on_topology)
    if heartbeat is not None:
        heartbeat.finish()
    report.total_iters = total
    _attach_counters(report)
    return label, active, total, report


def screen_outliers(samples, rerun: Callable[[], float] | None,
                    factor: float = 3.0):
    """bench.py's discard-and-rerun rule (round-5 VERDICT #7): a
    sample more than ``factor``x off the median of its batch is a
    tunnel collapse (BENCH_r05 pagerank-mp: [0.1116, 0.0107, 0.1118]),
    not a measurement — it is discarded and re-run ONCE, and the
    discards are reported so the JSON line cannot silently median
    over a collapse.

    Returns (kept_samples, discarded, attempts) where ``attempts``
    counts every timed run (original batch + reruns).  factor<=0
    disables screening.
    """
    from lux_tpu import telemetry

    tel = telemetry.current()
    samples = list(samples)
    attempts = len(samples)
    if len(samples) < 2 or not factor or factor <= 0:
        return samples, [], attempts
    m = median(samples)

    def is_outlier(s):
        return s < m / factor or s > m * factor

    kept = [s for s in samples if not is_outlier(s)]
    discarded = [s for s in samples if is_outlier(s)]
    if not kept:        # mutual disagreement: nothing to trust more
        return samples, [], attempts
    for d in list(discarded):
        tel.emit("outlier_discard", sample=round(d, 6),
                 median=round(m, 6), factor=factor)
        if rerun is None:
            continue
        s = rerun()
        attempts += 1
        if is_outlier(s):
            discarded.append(s)     # the rerun ALSO collapsed: record
            #                         it, never median it (reruns get
            #                         one chance — no retry loops)
        else:
            kept.append(s)
        tel.emit("outlier_rerun", sample=round(s, 6),
                 kept=not is_outlier(s))
    return kept, discarded, attempts
