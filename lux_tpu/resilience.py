"""Resilient run supervision: crash classification, retry/resume,
duration-budgeted segmentation, and bench sample screening.

The reference inherits fault tolerance from the Legion/Realm runtime
it sits on (SURVEY §1); lux_tpu's substrate is JAX over the axon
tunnel, whose measured failure modes (PERF_NOTES round 5) are:
transient TPU worker death (one bench config crashed outright and a
pagerank-mp sample collapsed 10x in BENCH_r05), the ~55 s
single-execution duration wall, and HTTP 413 rejects for
constant-heavy programs.  This module is the recovery story:

- ``classify`` sorts failures into RETRYABLE (tunnel/worker death,
  injected crashes, NaN escapes caught by debug.check_finite — the
  last checkpoint predates the corruption, so resuming can help) and
  FATAL (HTTP 413 / OOM compile rejects, StallError livelocks,
  programming errors — deterministic, retrying reruns the same bug).
  A deterministic divergence still surfaces: it recurs until the
  retry budget is exhausted and the last error propagates.
- ``supervise`` retries retryable failures with exponential backoff.
- ``supervised_run`` / ``supervised_converge`` compose the retry loop
  with checkpoint.py's segmented paths: every segment checkpoints
  atomically, retries AUTO-RESUME from the last checkpoint instead of
  restarting, and optional fault injection (lux_tpu/faults.py) plus
  the debug.py finite guard run at each boundary.
- a ``seg_budget`` sizes segments with ``segmented.DurationBudget``
  so each XLA execution stays under the duration wall.
- ``screen_outliers`` is bench.py's discard-and-rerun rule for
  tunnel-variance collapses (samples >3x off the median).
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from statistics import median
from typing import Callable

import numpy as np

RETRYABLE = "retryable"
FATAL = "fatal"

# Deterministic failures — retrying replays the same program into the
# same rejection.  Checked before the transient MESSAGE patterns: an
# XlaRuntimeError carrying an HTTP 413 or an OOM must not match the
# worker/tunnel signatures below.  \b413\b so a port / byte count /
# request id containing "413..." cannot condemn a transient error.
_FATAL_RE = re.compile(
    r"\b413\b|too\s+large|resource.?exhausted|out of memory|"
    r"failed to allocate|program shape", re.I)

# Transient tunnel/worker signatures: connection loss, worker death,
# deadline blowouts — the things a fresh attempt can outlive.
_RETRYABLE_RE = re.compile(
    r"unavailable|connection|socket|deadline|timed?[\s_-]?out|"
    r"worker|terminated|cancell?ed|aborted|heartbeat|broken pipe|"
    r"reset by peer|transport|tunnel", re.I)

_RETRYABLE_TYPES = (ConnectionError, TimeoutError, BrokenPipeError,
                    EOFError)

# Deterministic filesystem failures (a bad -resume path, a read-only
# checkpoint dir): OSError subclasses a retry cannot fix.
_FATAL_OSERRORS = (FileNotFoundError, NotADirectoryError,
                   IsADirectoryError, PermissionError, FileExistsError)


def classify(exc: BaseException) -> str:
    """RETRYABLE or FATAL for one failure (see module docstring for
    the taxonomy)."""
    from lux_tpu import checkpoint, debug, faults, health

    if isinstance(exc, faults.InjectedWorkerCrash):
        return RETRYABLE
    from lux_tpu import audit
    if isinstance(exc, audit.AuditError):
        return FATAL            # a static-audit violation is a
        #                         property of the BUILD: retrying
        #                         re-traces the same program into the
        #                         same typed refusal (and the finding
        #                         text may mention 'tunnel'/'413',
        #                         which must not hit the retryable
        #                         message scan below)
    if isinstance(exc, health.HealthError):
        return FATAL            # fatal-with-diagnosis: the watchdog
        #                         saw corruption in the STATE itself
        #                         (which check/part/iteration is on
        #                         the exception) — blind retry/resume
        #                         reruns into the same diagnosis
    if isinstance(exc, checkpoint.CorruptCheckpointError):
        return RETRYABLE        # the retry's resume goes through
        #                         load_any, which falls back one
        #                         GENERATION and replays the lost
        #                         segment — never the deterministic-
        #                         OSError fatal bucket below
    if isinstance(exc, debug.StallError):
        return FATAL
    if isinstance(exc, debug.DivergenceError):
        return RETRYABLE        # possible transient corruption;
        #                         deterministic NaN exhausts retries
    if isinstance(exc, _RETRYABLE_TYPES):
        return RETRYABLE        # typed transport errors outrank any
        #                         message scan ("...writing request
        #                         payload too large buffer" etc.)
    msg = f"{type(exc).__name__}: {exc}"
    if _FATAL_RE.search(msg):
        return FATAL
    if isinstance(exc, _FATAL_OSERRORS):
        return FATAL
    if isinstance(exc, OSError):
        return RETRYABLE        # tunnel I/O
    if _RETRYABLE_RE.search(msg):
        return RETRYABLE
    return FATAL


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff for retryable failures.  ``sleep`` is
    injectable so tests (and dry runs) never actually wait."""

    retries: int = 3
    backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 60.0
    sleep: Callable[[float], None] = time.sleep

    def delay_s(self, failure_index: int) -> float:
        return min(self.backoff_s * self.backoff_factor ** failure_index,
                   self.max_backoff_s)


@dataclasses.dataclass
class RunReport:
    """What the supervisor did: for logs and bench JSON lines."""

    attempts: int = 0
    failures: list = dataclasses.field(default_factory=list)
    #           ^ (exception type name, message[:200], classification)
    resumed_from: list = dataclasses.field(default_factory=list)
    #           ^ checkpoint iteration counter at each resume
    initial_resume: int | None = None
    #           ^ iteration a PRE-EXISTING checkpoint supplied to the
    #             first attempt (explicit resume=True only) — in-run
    #             retry resumes redo work this run already did and
    #             are deliberately NOT counted here
    total_iters: int = 0
    segments: int = 0
    counters: dict | None = None
    #           ^ device-side iteration-counter digest
    #             (telemetry.IterStats.summary()) when the run was
    #             supervised under an active iter-stats handle

    def as_dict(self) -> dict:
        return dict(attempts=self.attempts, segments=self.segments,
                    resumed_from=list(self.resumed_from),
                    initial_resume=self.initial_resume,
                    failures=[list(f) for f in self.failures],
                    total_iters=self.total_iters,
                    counters=self.counters)


def supervise(attempt: Callable, policy: RetryPolicy | None = None,
              report: RunReport | None = None):
    """Run ``attempt(k)`` (k = 0-based attempt index) under classified
    retries: retryable failures back off and retry, fatal ones (and
    retry-budget exhaustion) re-raise.  Returns (result, report)."""
    from lux_tpu import telemetry

    policy = policy or RetryPolicy()
    report = report or RunReport()
    for k in range(max(0, policy.retries) + 1):
        report.attempts += 1
        try:
            return attempt(k), report
        except Exception as e:      # noqa: BLE001 — classified below
            kind = classify(e)
            report.failures.append(
                (type(e).__name__, str(e)[:200], kind))
            fatal = kind == FATAL or k >= policy.retries
            telemetry.current().emit(
                "failure" if fatal else "retry", attempt=k,
                error=type(e).__name__, message=str(e)[:200],
                classification=kind,
                **({} if fatal
                   else {"backoff_s": round(policy.delay_s(k), 3)}))
            if fatal:
                raise
            policy.sleep(policy.delay_s(k))
    raise AssertionError("unreachable")


def _make_segment(segment, seg_budget, per_size_compile=True):
    if seg_budget:
        from lux_tpu.segmented import DurationBudget
        return DurationBudget(float(seg_budget),
                              per_size_compile=per_size_compile)
    return segment


def _int_sentinel(eng):
    """The integer identity/sentinel value of the engine's program (the
    one-sentinel convention: faults.corrupt_state pokes it into
    integer-labeled states — sssp hop counts, components ids — so a
    seeded NAN plan can corrupt all four apps instead of crashing on
    the float-only nan_corrupt).  None for float programs."""
    ident = getattr(getattr(eng, "program", None), "identity", None)
    if ident is None:
        return None
    ident = np.asarray(ident)
    return int(ident) if np.issubdtype(ident.dtype, np.integer) else None


def _record_resume(path, report):
    from lux_tpu import checkpoint

    if checkpoint.any_generation(path):
        try:
            # generation-fallback-aware: records the iteration the
            # resume will ACTUALLY continue from (the .prev one when
            # the newest file is corrupt — a meta-only peek would
            # misreport the corrupt file's own counter, so this pays
            # the verifying load).  load_any QUARANTINES a corrupt
            # newest, so the fallback detection, its event and its
            # CRC cost all happen ONCE here; the attempt's resume
            # then reads the good generation directly.
            _leaves, meta, _used = checkpoint.load_any(path)
            report.resumed_from.append(int(meta.get("iter", 0)))
        except Exception:           # noqa: BLE001 — all gens corrupt
            pass                    # the attempt itself will surface it


def supervised_run(eng, num_iters: int, path: str, *,
                   policy: RetryPolicy | None = None,
                   segment=50, seg_budget: float | None = None,
                   resume: bool = False, faults=None,
                   guard: bool = True, report: RunReport | None = None):
    """Supervised pull-engine fixed-iteration run: segmented +
    checkpointed to ``path``, with classified retries resuming from
    the last atomic checkpoint.  Returns (state, report).

    resume=False starts fresh (a stale file at ``path`` is removed so
    a crash before the first save cannot resurrect it); retries within
    the run always resume.  ``faults`` (faults.FaultPlan) and the
    finite ``guard`` run at each segment boundary BEFORE the save, so
    injected/real corruption never reaches a checkpoint."""
    from lux_tpu import checkpoint, debug

    report = report or RunReport()
    if not resume:
        checkpoint.remove(path)     # BOTH generations: a stale .prev
        #                             must not resurrect either
    if faults is not None and hasattr(faults, "bind_checkpoint"):
        faults.bind_checkpoint(path)

    def hook(s, done):
        report.segments += 1
        out = None
        if faults is not None:
            res = faults.fire(s, int_value=_int_sentinel(eng))
            if res is not None:
                s = out = eng.place(res)
        if guard:
            debug.check_finite(
                s, f"supervised pull run @ iteration {done}")
        return out

    # eng.run DONATES its state buffers, so a consumed state cannot
    # feed a second attempt — but a resuming attempt whose checkpoint
    # exists only reads the pytree STRUCTURE (checkpoint.py), so a
    # spent state (or an abstract eval_shape stub on a fresh-process
    # resume) serves as structure donor and the attempt skips
    # re-placing a fresh multi-hundred-MB state on device.
    state0 = None

    def attempt(k):
        nonlocal state0
        do_resume = resume or k > 0
        if do_resume:
            _record_resume(path, report)
            if k == 0 and report.resumed_from:
                report.initial_resume = report.resumed_from[0]
        will_load = do_resume and checkpoint.any_generation(path)
        if will_load and state0 is None:
            import jax
            try:                    # structure-only: no placement
                state0 = jax.eval_shape(eng.init_state)
            except Exception:       # noqa: BLE001 — untraceable init
                state0 = eng.init_state()
        elif not will_load:
            state0 = eng.init_state()
        return checkpoint.run_checkpointed(
            eng, state0, num_iters, path,
            segment=_make_segment(segment, seg_budget),
            resume=do_resume, on_segment=hook)

    state, report = supervise(attempt, policy, report)
    report.total_iters = num_iters
    _attach_counters(report)
    return state, report


def _attach_counters(report):
    """Fold the active iter-stats digest (device-side per-iteration
    counters accumulated by the segmented drivers) into the report, so
    RunReport.as_dict() carries the counter summary."""
    from lux_tpu import telemetry

    st = telemetry.current().iter_stats
    if st is not None:
        report.counters = st.summary()


def supervised_converge(eng, path: str, *,
                        policy: RetryPolicy | None = None,
                        segment=50, seg_budget: float | None = None,
                        resume: bool = False,
                        max_iters: int | None = None, faults=None,
                        guard: bool = True,
                        report: RunReport | None = None):
    """Supervised push-engine convergence: segmented + checkpointed to
    ``path``, with classified retries resuming from the last atomic
    checkpoint.  Returns (label, active, total_iters, report).

    The boundary guard runs check_finite(allow_inf=True) — +inf is the
    legitimate unreached sentinel; NaN raises DivergenceError, which
    classifies retryable (the checkpoint predates the corruption)."""
    from lux_tpu import checkpoint, debug

    report = report or RunReport()
    if not resume:
        checkpoint.remove(path)
    if faults is not None and hasattr(faults, "bind_checkpoint"):
        faults.bind_checkpoint(path)

    def hook(lbl, act, total, cnt):
        report.segments += 1
        out = None
        if faults is not None:
            res = faults.fire((lbl, act),
                              int_value=_int_sentinel(eng))
            if res is not None:
                lbl, act = eng.place(*[np.asarray(x) for x in res])
                out = (lbl, act)
        if guard:
            debug.check_finite(
                lbl, f"supervised converge @ iteration {total}",
                allow_inf=True)
        return out

    def attempt(k):
        do_resume = resume or k > 0
        if do_resume:
            _record_resume(path, report)
            if k == 0 and report.resumed_from:
                report.initial_resume = report.resumed_from[0]
        return checkpoint.converge_checkpointed(
            eng, path,
            segment=_make_segment(segment, seg_budget,
                                  per_size_compile=False),
            resume=do_resume, max_iters=max_iters, on_segment=hook)

    (label, active, total), report = supervise(attempt, policy, report)
    report.total_iters = total
    _attach_counters(report)
    return label, active, total, report


def screen_outliers(samples, rerun: Callable[[], float] | None,
                    factor: float = 3.0):
    """bench.py's discard-and-rerun rule (round-5 VERDICT #7): a
    sample more than ``factor``x off the median of its batch is a
    tunnel collapse (BENCH_r05 pagerank-mp: [0.1116, 0.0107, 0.1118]),
    not a measurement — it is discarded and re-run ONCE, and the
    discards are reported so the JSON line cannot silently median
    over a collapse.

    Returns (kept_samples, discarded, attempts) where ``attempts``
    counts every timed run (original batch + reruns).  factor<=0
    disables screening.
    """
    from lux_tpu import telemetry

    tel = telemetry.current()
    samples = list(samples)
    attempts = len(samples)
    if len(samples) < 2 or not factor or factor <= 0:
        return samples, [], attempts
    m = median(samples)

    def is_outlier(s):
        return s < m / factor or s > m * factor

    kept = [s for s in samples if not is_outlier(s)]
    discarded = [s for s in samples if is_outlier(s)]
    if not kept:        # mutual disagreement: nothing to trust more
        return samples, [], attempts
    for d in list(discarded):
        tel.emit("outlier_discard", sample=round(d, 6),
                 median=round(m, 6), factor=factor)
        if rerun is None:
            continue
        s = rerun()
        attempts += 1
        if is_outlier(s):
            discarded.append(s)     # the rerun ALSO collapsed: record
            #                         it, never median it (reruns get
            #                         one chance — no retry loops)
        else:
            kept.append(s)
        tel.emit("outlier_rerun", sample=round(s, 6),
                 kept=not is_outlier(s))
    return kept, discarded, attempts
