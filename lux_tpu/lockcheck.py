"""lockcheck — host-concurrency & durability static analyzer for the
serving substrate (round 25).

The Lux execution model is race-free by construction ON DEVICE
(pull_model.inl:1 parity is the engines' problem); the production
substrate above it — serve.py, fleet.py, livegraph.py,
journal.py, heartbeat.py, metrics.py, telemetry.py, checkpoint.py — is ~6k lines of host-side threaded, durability-
critical Python, and CHANGES.md records six review rounds of
hand-caught concurrency bugs there (the compact() lock-window
double-loss, the stamp-then-admit TOCTOU, the refresh_live/run/
compact three-way deadlock, the iterate-while-mutated collector
race, the durable-before-visible fsync contract).  The repo's idiom
is that every invariant defended in a review round becomes a machine
check: lux_tpu/audit.py checks traced jaxprs, scripts/lint_lux.py
checks source conventions, and THIS module checks the one layer
those two cannot see — lock discipline and durability ordering in
the threaded host code.  AST/CFG only: no imports of the checked
modules, no tracing, seconds on CPU.

Five check classes, each raising ``LockCheckError(check=...)`` in
error mode:

  guarded-field
      Lockset inference.  A class that owns a lock (an attribute
      assigned ``threading.Lock()`` / ``RLock()`` / ``Condition()``)
      defines a GUARDED field the moment any method mutates that
      field under the lock; every other mutation site of the same
      field must then hold the lock too (``__init__`` and
      locally-constructed instances are construction-phase and
      exempt; private helpers whose every intra-class call site
      holds the lock inherit it — the documented
      "caller holds the lock" idiom).  The motivating bug is the
      PR-15/20 compact() lock WINDOW: a fold that released the lock
      mid-operation lost a concurrent append twice over
      (livegraph.LiveGraph.compact docstring).

  lock-order
      Cross-module lock-acquisition graph.  An edge A -> B is
      recorded when code acquires B while holding A — directly
      (nested ``with``) or transitively through method calls
      (receiver types resolved from ``self.attr = ClassName(...)``
      assignments, falling back to a unique-method-name match).
      Any cycle among DISTINCT locks is a potential deadlock; the
      PR-15 fifth-review refresh_live/run/compact three-way
      deadlock is the motivating fixture
      (tests/test_lockcheck.py).

  durable-before-visible
      Record-stream durability ordering (journal.py / livegraph.py
      WAL / checkpoint.py contract, stated until now only in
      comments).  Within a function, every path from a RECORD write
      (``.write()`` on a binary-mode handle, ``np.save``/
      ``np.savez``/``pickle.dump`` into one) to a VISIBLE action —
      a ``return`` (explicit or fall-through), a telemetry
      ``emit``, a queue ``put``/``notify``, an ``os.rename``/
      ``os.replace`` publish — must cross an ``os.fsync``.
      Checkpoints must follow write-tmp -> fsync -> rename; the
      subprocess spool's json must be written LAST (its presence
      marks a complete pair — a json published before its sidecars
      advertises a torn answer).  Text-mode writes (heartbeats,
      spool manifests) are liveness signals, lossy by design, and
      exempt by mode.

  snapshot-iteration
      Iterating a guarded container outside its lock without a
      ``list()``/``tuple()``/``sorted()``/``set()`` snapshot — the
      PR-15 fifth-review collector race (refresh_live iterating
      ``self.collectors`` while submit threads append; dicts raise
      RuntimeError mid-resize, lists silently skip).

  toctou-gate
      A guarded field read OUTSIDE the lock feeding a condition
      that gates a mutation INSIDE it, with no re-check under the
      lock — the stamp-then-admit window class (PR-16: a separate
      epoch read + admit let a concurrent mutate+compact fold the
      stamped view away before the admission ledger protected it;
      livegraph.LiveGraph.admit is the one-acquisition fix).

Suppression: ``# lockcheck: allow(<check>)`` on the flagged line or
in the contiguous comment block directly above it, with a one-line
justification — the same syntax audit.py and lint_lux.py honor.

Usage:  python -m lux_tpu.lockcheck [PATHS...]
        (default: the threaded host modules, HOST_MODULES)
Exit status: 0 clean, 1 any unsuppressed finding.  Tier-1 gate:
tests/test_audit.py runs the repo-wide check beside the audit and
lint gates; tests/test_lockcheck.py holds the per-check violating
fixtures and the historical bug reproductions.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the threaded host modules this analyzer exists for (ISSUE 20);
# main() checks these by default, check_paths takes any .py files
HOST_MODULES = ("serve.py", "fleet.py", "livegraph.py", "journal.py",
                "heartbeat.py", "metrics.py", "telemetry.py",
                "checkpoint.py")

CHECKS = ("guarded-field", "lock-order", "durable-before-visible",
          "snapshot-iteration", "toctou-gate")

PRAGMA_RE = re.compile(r"#\s*lockcheck:\s*allow\(([a-z-]+)\)")

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# container-mutating method names (called on a field -> mutation)
MUTATOR_METHODS = {"append", "extend", "insert", "add", "update",
                   "pop", "popitem", "popleft", "appendleft",
                   "remove", "discard", "clear", "setdefault",
                   "move_to_end", "sort", "reverse"}

# sanctioned snapshot wrappers for iterating a guarded container
SNAPSHOT_FUNCS = {"list", "tuple", "sorted", "set", "frozenset",
                  "dict"}

# container constructors (self.f = ...) marking a field container-ish
CONTAINER_FACTORIES = {"list", "dict", "set", "OrderedDict", "deque",
                       "Counter", "defaultdict"}

# visible-action call names for durable-before-visible
EMIT_NAMES = {"emit", "_emit", "emit_sampled"}
ENQUEUE_NAMES = {"put", "notify", "notify_all"}
PUBLISH_NAMES = {"rename", "replace"}       # os.rename / os.replace


class LockCheckError(Exception):
    """Typed lock-discipline violation: ``check`` names the violated
    check class (one of CHECKS), ``findings`` carries every site."""

    def __init__(self, check: str, message: str, findings=()):
        super().__init__(message)
        self.check = check
        self.findings = list(findings)


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    check: str
    message: str

    def __str__(self):
        rel = os.path.relpath(self.path, REPO)
        return f"{rel}:{self.line}: [{self.check}] {self.message}"


def _suppressed(lines, line_no: int, check: str) -> bool:
    """Pragma on the flagged line or the contiguous comment block
    directly above it (mirrors scripts/lint_lux.py)."""

    def hit(text):
        return any(m.group(1) == check
                   for m in PRAGMA_RE.finditer(text))

    if 0 < line_no <= len(lines) and hit(lines[line_no - 1]):
        return True
    ln = line_no - 2
    while ln >= 0:
        stripped = lines[ln].strip()
        if stripped.startswith("#"):
            if hit(stripped):
                return True
            ln -= 1
        elif not stripped or stripped.startswith("@"):
            ln -= 1
        else:
            break
    return False


# ---------------------------------------------------------------------
# model


def _is_lock_factory(expr) -> bool:
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    if isinstance(f, ast.Attribute) and f.attr in LOCK_FACTORIES \
            and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id in LOCK_FACTORIES


def _call_name(expr):
    """'ClassName' for ``ClassName(...)`` / ``cls(...)``, else None."""
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name):
            return expr.func.id
        if isinstance(expr.func, ast.Attribute):
            return expr.func.attr
    return None


def _self_field(expr):
    """'f' for ``self.f`` (one level), else None."""
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _is_binary_open(expr) -> bool:
    """``open(path, 'ab')`` / ``os.fdopen(fd, 'wb')`` with a binary
    WRITE mode — the record-stream handle discriminator (text-mode
    writes are liveness/manifest signals, exempt by contract)."""
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name not in ("open", "fdopen"):
        return False
    mode = None
    if len(expr.args) >= 2 and isinstance(expr.args[1], ast.Constant):
        mode = expr.args[1].value
    for kw in expr.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return (isinstance(mode, str) and "b" in mode
            and any(c in mode for c in "wax+"))


@dataclasses.dataclass
class _Fact:
    """One collected site; ``held`` is a frozenset of lock keys."""
    line: int
    held: frozenset
    field: str = ""
    in_init: bool = False
    extra: tuple = ()


class _FuncModel:
    def __init__(self, node, cls, mod):
        self.node = node
        self.cls = cls                     # _ClassModel or None
        self.mod = mod
        self.name = node.name
        self.is_init = node.name in ("__init__", "__post_init__")
        self.mutations: list[_Fact] = []   # field mutations (self.*)
        self.iterations: list[_Fact] = []  # unwrapped field iteration
        self.acquisitions: list[_Fact] = []  # field=lock key acquired
        self.calls: list[_Fact] = []       # extra=(kind, a, b)
        self.if_nodes: list[tuple] = []    # (If/While node, heldset)
        self.outside_reads: dict[str, set] = {}  # local -> fields
        self.self_call_sites: dict[str, list] = {}  # name -> [held]
        self.inherited: frozenset = frozenset()  # inferred held locks


class _ClassModel:
    def __init__(self, node, mod):
        self.node = node
        self.mod = mod
        self.name = node.name
        self.lock_attrs: set[str] = set()
        self.attr_types: dict[str, str] = {}
        self.container_attrs: set[str] = set()
        self.binary_handle_attrs: set[str] = set()
        self.methods: dict[str, _FuncModel] = {}

    def lock_key(self, attr: str) -> str:
        return f"{self.mod}:{self.name}.{attr}"

    @property
    def lock_keys(self) -> set[str]:
        return {self.lock_key(a) for a in self.lock_attrs}


class _FileModel:
    def __init__(self, path, src):
        self.path = path
        self.lines = src.splitlines()
        self.mod = os.path.basename(path)[:-3]
        self.tree = ast.parse(src, filename=path)
        self.classes: dict[str, _ClassModel] = {}
        self.functions: dict[str, _FuncModel] = {}
        self.module_locks: set[str] = set()

    def lock_key(self, name: str) -> str:
        return f"{self.mod}:{name}"


def _prescan(fm: _FileModel) -> None:
    """Phase 1: class skeletons — lock attrs, attr types, container
    and binary-handle attrs — plus module-level locks.  Runs before
    fact collection so ``with <local>._lock`` and receiver types can
    resolve across classes and files."""
    for node in fm.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_lock_factory(node.value):
            fm.module_locks.add(node.targets[0].id)
        if not isinstance(node, ast.ClassDef):
            continue
        cm = _ClassModel(node, fm.mod)
        fm.classes[node.name] = cm
        for n in ast.walk(node):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                continue
            field = _self_field(n.targets[0])
            if field is None:
                continue
            if _is_lock_factory(n.value):
                cm.lock_attrs.add(field)
            elif _is_binary_open(n.value):
                cm.binary_handle_attrs.add(field)
            else:
                cname = _call_name(n.value)
                if cname in CONTAINER_FACTORIES or isinstance(
                        n.value, (ast.List, ast.Dict, ast.Set)):
                    cm.container_attrs.add(field)
                elif cname and cname[:1].isupper():
                    # self.attr = ClassName(...) — receiver typing
                    # for cross-class lock-order resolution
                    cm.attr_types[field] = cname


# ---------------------------------------------------------------------
# phase 2: fact collection (lock contexts, mutations, iterations,
# calls) — one structured recursive walk per function


class _Collector:
    """Walks one function body tracking the held-lock context."""

    def __init__(self, fmodel: _FuncModel, file_model: _FileModel,
                 registry: "dict[str, list[_ClassModel]]"):
        self.f = fmodel
        self.file = file_model
        self.registry = registry
        self.ctor_locals: dict[str, str] = {}   # name -> class name

    # -- lock expression resolution -----------------------------------

    def _lock_key_of(self, expr) -> str | None:
        """Lock key for a with-item / acquire() receiver, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in self.file.module_locks:
                return self.file.lock_key(expr.id)
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and self.f.cls is not None \
                    and attr in self.f.cls.lock_attrs:
                return self.f.cls.lock_key(attr)
            cname = self.ctor_locals.get(base)
            if cname:
                for cm in self.registry.get(cname, ()):
                    if attr in cm.lock_attrs:
                        return cm.lock_key(attr)
        return None

    # -- entry ---------------------------------------------------------

    def run(self):
        self.block(self.f.node.body, frozenset())

    def block(self, stmts, held):
        for st in stmts:
            self.stmt(st, held)

    # -- statements ----------------------------------------------------

    def stmt(self, st, held):
        if isinstance(st, ast.With):
            add = set()
            for item in st.items:
                key = self._lock_key_of(item.context_expr)
                if key is not None:
                    self.f.acquisitions.append(_Fact(
                        line=st.lineno, held=held, field=key))
                    add.add(key)
                else:
                    self.expr(item.context_expr, held)
            self.block(st.body, held | add)
        elif isinstance(st, (ast.If, ast.While)):
            self.f.if_nodes.append((st, held))
            self.expr(st.test, held)
            self.block(st.body, held)
            self.block(st.orelse, held)
        elif isinstance(st, ast.For):
            self._iteration(st.iter, held, st.lineno)
            self.expr(st.iter, held, top_iter=True)
            self.block(st.body, held)
            self.block(st.orelse, held)
        elif isinstance(st, ast.Try):
            self.block(st.body, held)
            for h in st.handlers:
                self.block(h.body, held)
            self.block(st.orelse, held)
            self.block(st.finalbody, held)
        elif isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assignment(st, held)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._mutation_target(t, held, st.lineno)
        elif isinstance(st, ast.Expr):
            self.expr(st.value, held)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.expr(st.value, held)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass          # nested defs analyzed at their call sites
        elif isinstance(st, ast.ClassDef):
            pass
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.expr(child, held)
                elif isinstance(child, ast.stmt):
                    self.stmt(child, held)

    def _assignment(self, st, held):
        targets = st.targets if isinstance(st, ast.Assign) \
            else [st.target]
        value = st.value
        if value is not None:
            self.expr(value, held)
        for t in targets:
            self._mutation_target(t, held, st.lineno)
        # locally-constructed instances (construction phase — their
        # field writes are thread-confined until published)
        if isinstance(st, ast.Assign) and len(targets) == 1 \
                and isinstance(targets[0], ast.Name):
            cname = _call_name(value)
            if cname == "cls" and self.f.cls is not None:
                self.ctor_locals[targets[0].id] = self.f.cls.name
            elif cname and cname in self.registry:
                self.ctor_locals[targets[0].id] = cname
            # local snapshot of a guarded read OUTSIDE the lock:
            # feeds the toctou variable-mediated pattern
            fields = {_self_field(n) for n in ast.walk(value)
                      if _self_field(n)}
            fields.discard(None)
            if fields and isinstance(targets[0], ast.Name):
                own = (self.f.cls.lock_keys if self.f.cls else set())
                if not (held & own):
                    self.f.outside_reads.setdefault(
                        targets[0].id, set()).update(fields)

    def _mutation_target(self, t, held, line):
        """self.f = / self.f[k] = / del self.f[k] style mutations."""
        field = _self_field(t)
        if field is None and isinstance(t, ast.Subscript):
            field = _self_field(t.value)
        if field is None and isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._mutation_target(el, held, line)
            return
        if field is not None:
            self.f.mutations.append(_Fact(
                line=line, held=held, field=field,
                in_init=self.f.is_init))

    # -- iteration facts ----------------------------------------------

    def _iter_field(self, expr):
        """'f' when expr iterates ``self.f`` (or its .items()/
        .values()/.keys()) directly, else None."""
        field = _self_field(expr)
        if field is not None:
            return field
        if isinstance(expr, ast.Call) and not expr.args \
                and isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("items", "values", "keys"):
            return _self_field(expr.func.value)
        return None

    def _iteration(self, expr, held, line):
        field = self._iter_field(expr)
        if field is not None:
            self.f.iterations.append(_Fact(
                line=line, held=held, field=field,
                in_init=self.f.is_init))

    # -- expressions ---------------------------------------------------

    def expr(self, e, held, top_iter=False):
        if e is None:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node, held)
            elif isinstance(node, (ast.GeneratorExp, ast.ListComp,
                                   ast.SetComp, ast.DictComp)):
                for comp in node.generators:
                    self._iteration(comp.iter, held,
                                    getattr(comp.iter, "lineno",
                                            node.lineno))

    def _call(self, node, held):
        f = node.func
        # snapshot wrappers sanction a direct field iteration
        if isinstance(f, ast.Name) and f.id in SNAPSHOT_FUNCS \
                and node.args:
            field = self._iter_field(node.args[0])
            if field is not None:
                # drop the matching iteration fact if a comprehension
                # walk already recorded it (list(self.f) is the
                # sanctioned snapshot, not a violation)
                self.f.iterations = [
                    it for it in self.f.iterations
                    if not (it.field == field
                            and it.line == getattr(node.args[0],
                                                   "lineno",
                                                   node.lineno))]
                return
        # min()/max()/sum()/any()/all() over a raw guarded field are
        # iterations too
        if isinstance(f, ast.Name) \
                and f.id in ("min", "max", "sum", "any", "all"):
            for a in node.args:
                self._iteration(a, held, node.lineno)
        if isinstance(f, ast.Attribute):
            # container-mutator on a self field
            field = _self_field(f.value)
            if field is not None and f.attr in MUTATOR_METHODS:
                self.f.mutations.append(_Fact(
                    line=node.lineno, held=held, field=field,
                    in_init=self.f.is_init))
            # explicit lock.acquire()
            if f.attr == "acquire":
                key = self._lock_key_of(f.value)
                if key is not None:
                    self.f.acquisitions.append(_Fact(
                        line=node.lineno, held=held, field=key))
            # call-graph facts for lock-order
            if isinstance(f.value, ast.Name):
                base = f.value.id
                if base == "self":
                    self.f.calls.append(_Fact(
                        line=node.lineno, held=held,
                        extra=("self", f.attr, None)))
                    self.f.self_call_sites.setdefault(
                        f.attr, []).append((held, self.f.name))
                else:
                    cname = self.ctor_locals.get(base)
                    self.f.calls.append(_Fact(
                        line=node.lineno, held=held,
                        extra=("name", f.attr, cname)))
            elif _self_field(f.value) is not None:
                self.f.calls.append(_Fact(
                    line=node.lineno, held=held,
                    extra=("attr", f.attr, _self_field(f.value))))
        elif isinstance(f, ast.Name):
            self.f.calls.append(_Fact(
                line=node.lineno, held=held,
                extra=("func", f.id, None)))


def _collect(fm: _FileModel, registry) -> None:
    for node in fm.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fmodel = _FuncModel(node, None, fm.mod)
            fm.functions[node.name] = fmodel
            _Collector(fmodel, fm, registry).run()
        elif isinstance(node, ast.ClassDef):
            cm = fm.classes[node.name]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fmodel = _FuncModel(sub, cm, fm.mod)
                    cm.methods[sub.name] = fmodel
                    _Collector(fmodel, fm, registry).run()


def _infer_lock_held_helpers(cm: _ClassModel) -> None:
    """Private helpers whose EVERY non-__init__ intra-class call site
    holds a lock inherit that lock — the documented 'caller holds
    the lock' idiom (AnswerCache._pop, LiveGraph._fresh_delta).
    Fixpoint over the intra-class call graph."""
    for _ in range(8):
        changed = False
        # name -> list of effective held sets at each call site
        sites: dict[str, list] = {}
        for m in cm.methods.values():
            if m.is_init:
                continue
            eff = m.inherited
            for name, calls in m.self_call_sites.items():
                for held, _src in calls:
                    sites.setdefault(name, []).append(held | eff)
        for name, heldsets in sites.items():
            m = cm.methods.get(name)
            if m is None or m.is_init \
                    or not name.startswith("_") \
                    or name.startswith("__"):
                continue
            common = frozenset.intersection(
                *[frozenset(h) for h in heldsets]) if heldsets \
                else frozenset()
            common = frozenset(common) & frozenset(cm.lock_keys)
            if common and common != m.inherited:
                m.inherited = frozenset(common)
                changed = True
        if not changed:
            break


def _effective(fact_held: frozenset, m: _FuncModel) -> frozenset:
    return frozenset(fact_held) | m.inherited


# ---------------------------------------------------------------------
# check: guarded-field


def _guard_map(cm: _ClassModel) -> dict[str, set]:
    """field -> set of lock keys under which it is mutated (the
    inferred lockset).  Fields touched only in __init__ don't
    count — construction is single-threaded by convention."""
    guards: dict[str, set] = {}
    for m in cm.methods.values():
        for mu in m.mutations:
            if mu.in_init:
                continue
            eff = _effective(mu.held, m)
            hit = eff & cm.lock_keys
            if hit:
                guards.setdefault(mu.field, set()).update(hit)
    # a lock attribute is never its own guarded field
    for a in cm.lock_attrs:
        guards.pop(a, None)
    return guards


def check_guarded_field(fm: _FileModel) -> list[Finding]:
    findings = []
    for cm in fm.classes.values():
        if not cm.lock_attrs:
            continue
        guards = _guard_map(cm)
        for m in cm.methods.values():
            for mu in m.mutations:
                g = guards.get(mu.field)
                if not g or mu.in_init:
                    continue
                if _effective(mu.held, m) & g:
                    continue
                if _suppressed(fm.lines, mu.line, "guarded-field"):
                    continue
                locks = ", ".join(sorted(k.split(":", 1)[1]
                                         for k in g))
                findings.append(Finding(
                    fm.path, mu.line, "guarded-field",
                    f"{cm.name}.{mu.field} is mutated under "
                    f"{locks} elsewhere but {m.name} mutates it "
                    f"with no lock held — the compact()-window bug "
                    f"class (every mutation site of a guarded "
                    f"field must hold the lock, or carry a "
                    f"justified pragma)"))
    return findings


# ---------------------------------------------------------------------
# check: snapshot-iteration


def check_snapshot_iteration(fm: _FileModel) -> list[Finding]:
    findings = []
    for cm in fm.classes.values():
        if not cm.lock_attrs:
            continue
        guards = _guard_map(cm)
        for m in cm.methods.values():
            for it in m.iterations:
                g = guards.get(it.field)
                if not g or it.in_init:
                    continue
                if not (cm.container_attrs & {it.field}
                        or it.field in guards):
                    continue
                if _effective(it.held, m) & g:
                    continue
                if _suppressed(fm.lines, it.line,
                               "snapshot-iteration"):
                    continue
                findings.append(Finding(
                    fm.path, it.line, "snapshot-iteration",
                    f"{m.name} iterates guarded container "
                    f"{cm.name}.{it.field} outside its lock with "
                    f"no list()/tuple() snapshot — the refresh_live "
                    f"collector-race class (a concurrent mutation "
                    f"mid-iteration raises or silently skips)"))
    return findings


# ---------------------------------------------------------------------
# check: toctou-gate


def _test_reads(test, guards, outside_reads) -> set:
    """Guarded fields the condition reads — directly or through a
    local previously snapshotted outside the lock."""
    fields = set()
    for n in ast.walk(test):
        f = _self_field(n)
        if f in guards:
            fields.add(f)
        if isinstance(n, ast.Name) and n.id in outside_reads:
            fields.update(outside_reads[n.id] & set(guards))
    return fields


def check_toctou_gate(fm: _FileModel) -> list[Finding]:
    findings = []
    for cm in fm.classes.values():
        if not cm.lock_attrs:
            continue
        guards = _guard_map(cm)
        if not guards:
            continue
        for m in cm.methods.values():
            if m.is_init:
                continue
            for node, held in m.if_nodes:
                eff = _effective(held, m)
                if eff & cm.lock_keys:
                    continue          # gate already under the lock
                gated = _test_reads(node.test, guards,
                                    m.outside_reads)
                if not gated:
                    continue
                hit = self_mutating_with(node, cm, guards)
                if hit is None:
                    continue
                if _suppressed(fm.lines, node.lineno, "toctou-gate"):
                    continue
                findings.append(Finding(
                    fm.path, node.lineno, "toctou-gate",
                    f"{m.name} reads guarded "
                    f"{cm.name}.{'/'.join(sorted(gated))} outside "
                    f"the lock to gate a mutation inside it (line "
                    f"{hit}) with no re-check under the lock — the "
                    f"stamp-then-admit window class (take the lock "
                    f"around read+mutate, or re-validate inside)"))
    return findings


def self_mutating_with(gate_node, cm: _ClassModel,
                       guards) -> int | None:
    """Line of a with-own-lock block inside the gate body that
    mutates a guarded field WITHOUT re-checking any guarded field
    under the lock; None when the gated mutation is safe."""
    for w in ast.walk(gate_node):
        if not isinstance(w, ast.With):
            continue
        acquires = False
        for item in w.items:
            f = _self_field(item.context_expr)
            if f in cm.lock_attrs:
                acquires = True
        if not acquires:
            continue
        mutates = rechecks = False
        for n in ast.walk(w):
            if isinstance(n, (ast.If, ast.While)) and n is not w:
                if any(_self_field(x) in guards
                       for x in ast.walk(n.test)):
                    rechecks = True
            t = None
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    f = _self_field(t)
                    if f is None and isinstance(t, ast.Subscript):
                        f = _self_field(t.value)
                    if f in guards:
                        mutates = True
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in MUTATOR_METHODS \
                    and _self_field(n.func.value) in guards:
                mutates = True
        if mutates and not rechecks:
            return w.lineno
    return None


# ---------------------------------------------------------------------
# check: lock-order


def _build_registry(models) -> dict[str, list[_ClassModel]]:
    reg: dict[str, list[_ClassModel]] = {}
    for fm in models:
        for cm in fm.classes.values():
            reg.setdefault(cm.name, []).append(cm)
    return reg


def _method_owners(models) -> dict[str, list]:
    """method name -> [(class model, func model)] over every class
    (lock-order call-resolution fallback: unique names only)."""
    owners: dict[str, list] = {}
    for fm in models:
        for cm in fm.classes.values():
            for name, m in cm.methods.items():
                owners.setdefault(name, []).append((cm, m))
    return owners


def _resolve_call(fact, m: _FuncModel, registry, owners):
    """-> _FuncModel of the callee, or None."""
    kind, name, hint = fact.extra
    if kind == "self" and m.cls is not None:
        return m.cls.methods.get(name)
    if kind == "attr" and m.cls is not None:
        tname = m.cls.attr_types.get(hint)
        if tname:
            for cm in registry.get(tname, ()):
                if name in cm.methods:
                    return cm.methods[name]
    if kind == "name" and hint:
        for cm in registry.get(hint, ()):
            if name in cm.methods:
                return cm.methods[name]
    if kind == "func":
        return None       # module functions resolved by the caller
    # fallback: unique method name among lock-relevant classes
    if kind in ("attr", "name"):
        cands = [(cm, fn) for cm, fn in owners.get(name, ())
                 if cm.lock_attrs]
        if len(cands) == 1:
            return cands[0][1]
    return None


def check_lock_order(models) -> list[Finding]:
    registry = _build_registry(models)
    owners = _method_owners(models)
    funcs: list[tuple[_FileModel, _FuncModel]] = []
    for fm in models:
        funcs += [(fm, f) for f in fm.functions.values()]
        for cm in fm.classes.values():
            funcs += [(fm, f) for f in cm.methods.values()]
    by_model = {id(f): fm for fm, f in funcs}

    def callee_of(fact, m):
        kind, name, _hint = fact.extra
        if kind == "func":
            fm = by_model.get(id(m))
            return fm.functions.get(name) if fm else None
        return _resolve_call(fact, m, registry, owners)

    # may_acquire fixpoint over the resolved call graph
    may: dict[int, frozenset] = {
        id(f): frozenset(a.field for a in f.acquisitions)
        for _fm, f in funcs}
    for _ in range(12):
        changed = False
        for _fm, f in funcs:
            acc = set(may[id(f)])
            for c in f.calls:
                callee = callee_of(c, f)
                if callee is not None and id(callee) in may:
                    acc |= may[id(callee)]
            froz = frozenset(acc)
            if froz != may[id(f)]:
                may[id(f)] = froz
                changed = True
        if not changed:
            break

    # edges a -> b (b acquired or reachable while a held)
    edges: dict[tuple, tuple] = {}
    for fm, f in funcs:
        for a in f.acquisitions:
            for h in _effective(a.held, f):
                if h != a.field:
                    edges.setdefault((h, a.field),
                                     (fm.path, a.line, f.name))
        for c in f.calls:
            callee = callee_of(c, f)
            if callee is None:
                continue
            for h in _effective(c.held, f):
                for b in may.get(id(callee), ()):
                    if h != b:
                        edges.setdefault((h, b),
                                         (fm.path, c.line, f.name))

    # cycle detection (DFS over the lock graph)
    graph: dict[str, set] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen_cycles = set()
    findings = []
    line_index = {fm.path: fm.lines for fm in models}

    def dfs(start):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start and len(path) > 1:
                    canon = frozenset(path)
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    cyc = path + [start]
                    ex_path, ex_line, ex_fn = edges[(path[0],
                                                     path[1])]
                    if _suppressed(line_index.get(ex_path, []),
                                   ex_line, "lock-order"):
                        continue
                    findings.append(Finding(
                        ex_path, ex_line, "lock-order",
                        f"lock-acquisition cycle "
                        f"{' -> '.join(cyc)} (first edge in "
                        f"{ex_fn}) — a potential deadlock: two "
                        f"threads entering the cycle at different "
                        f"points wait on each other forever (the "
                        f"refresh_live/run/compact three-way class)"
                    ))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for start in sorted(graph):
        dfs(start)
    return findings


# ---------------------------------------------------------------------
# check: durable-before-visible


class _DurableState:
    __slots__ = ("dirty", "json_published")

    def __init__(self, dirty=frozenset(), json_published=False):
        self.dirty = frozenset(dirty)
        self.json_published = json_published

    def merge(self, other):
        return _DurableState(self.dirty | other.dirty,
                             self.json_published
                             or other.json_published)


def _contains_json_literal(expr) -> bool:
    return any(isinstance(n, ast.Constant)
               and isinstance(n.value, str) and ".json" in n.value
               for n in ast.walk(expr))


class _DurableWalker:
    """Statement-level abstract interpretation: track binary handles
    dirty (written, not yet fsynced) and flag visible actions
    crossed while dirty (see module docstring)."""

    def __init__(self, fm: _FileModel, fname: str, cls, findings):
        self.fm = fm
        self.fname = fname
        self.cls = cls
        self.findings = findings
        self.handles: set[str] = set()        # binary handle names
        if cls is not None:
            self.handles |= {f"self.{a}"
                             for a in cls.binary_handle_attrs}

    def flag(self, line, what):
        if _suppressed(self.fm.lines, line, "durable-before-visible"):
            return
        self.findings.append(Finding(
            self.fm.path, line, "durable-before-visible",
            f"{self.fname}: {what} with unsynced record bytes "
            f"pending — every path from a record write to a "
            f"visible action must cross os.fsync (journal/WAL/"
            f"checkpoint durable-before-visible contract)"))

    def flag_json(self, line):
        if _suppressed(self.fm.lines, line, "durable-before-visible"):
            return
        self.findings.append(Finding(
            self.fm.path, line, "durable-before-visible",
            f"{self.fname}: file write AFTER the spool json "
            f"publish — the json's presence marks a complete "
            f"pair, so it must be written LAST"))

    # -- handle tracking ----------------------------------------------

    def _handle_of(self, expr) -> str | None:
        if isinstance(expr, ast.Name) and expr.id in self.handles:
            return expr.id
        f = _self_field(expr)
        if f is not None and f"self.{f}" in self.handles:
            return f"self.{f}"
        return None

    # -- walk ----------------------------------------------------------

    def block(self, stmts, state):
        for st in stmts:
            state = self.stmt(st, state)
        return state

    def stmt(self, st, state):
        if isinstance(st, ast.With):
            for item in st.items:
                if item.optional_vars is not None \
                        and isinstance(item.optional_vars, ast.Name):
                    if _is_binary_open(item.context_expr):
                        self.handles.add(item.optional_vars.id)
                        continue
                    # rebinding a tracked name to a non-binary
                    # stream (text-mode json/manifest) drops it
                    self.handles.discard(item.optional_vars.id)
                state = self.scan_expr(item.context_expr, state)
            return self.block(st.body, state)
        if isinstance(st, ast.If):
            state = self.scan_expr(st.test, state)
            s1 = self.block(st.body, state)
            s2 = self.block(st.orelse, state)
            return s1.merge(s2)
        if isinstance(st, (ast.For, ast.While)):
            if isinstance(st, ast.For):
                state = self.scan_expr(st.iter, state)
            else:
                state = self.scan_expr(st.test, state)
            # dirty bytes carry across iterations; the json-last
            # contract is PER ITERATION (each loop pass writes a
            # fresh answer pair), so json_published resets at the
            # body entry and never leaks out of the loop
            once = self.block(st.body,
                              _DurableState(state.dirty, False))
            merged = _DurableState(state.dirty | once.dirty, False)
            twice = self.block(st.body, merged)
            dirty = merged.dirty | twice.dirty
            tail = self.block(st.orelse,
                              _DurableState(dirty,
                                            state.json_published))
            return _DurableState(dirty | tail.dirty,
                                 state.json_published
                                 or tail.json_published)
        if isinstance(st, ast.Try):
            after = self.block(st.body, state)
            worst = state.merge(after)
            for h in st.handlers:
                worst = worst.merge(self.block(h.body, worst))
            worst = worst.merge(self.block(st.orelse, after))
            return self.block(st.finalbody, worst)
        if isinstance(st, ast.Return):
            if st.value is not None:
                state = self.scan_expr(st.value, state)
            if state.dirty:
                self.flag(st.lineno, "return (visible to callers)")
            return _DurableState()
        if isinstance(st, ast.Raise):
            return _DurableState()    # error path: nothing published
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if st.value is not None:
                state = self.scan_expr(st.value, state)
            # f = open(path, 'ab') binds a persistent binary handle;
            # rebinding a tracked name to anything else drops it
            if isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                name = t.id if isinstance(t, ast.Name) else (
                    f"self.{_self_field(t)}"
                    if _self_field(t) is not None else None)
                if name is not None:
                    if _is_binary_open(st.value):
                        self.handles.add(name)
                    else:
                        self.handles.discard(name)
            return state
        if isinstance(st, ast.Expr):
            return self.scan_expr(st.value, state)
        # default: scan expressions, recurse into child statements
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                state = self.scan_expr(child, state)
        return state

    def scan_expr(self, e, state):
        if e is None:
            return state
        for node in ast.walk(e):
            if not isinstance(node, ast.Call):
                continue
            state = self._call(node, state)
        return state

    def _call(self, node, state):
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        name = f.id if isinstance(f, ast.Name) else None
        dirty, json_pub = set(state.dirty), state.json_published

        def record_write(h):
            # a pragma AT THE WRITE SITE exempts this record stream
            # from the durability contract entirely (the spool-file
            # escape hatch: same-host IPC, journal-reconstructible)
            if json_pub:
                self.flag_json(node.lineno)
            if not _suppressed(self.fm.lines, node.lineno,
                               "durable-before-visible"):
                dirty.add(h)

        # record writes
        if attr == "write" and isinstance(f, ast.Attribute):
            h = self._handle_of(f.value)
            if h is not None:
                record_write(h)
        if attr in ("save", "savez", "savez_compressed") \
                and node.args:
            h = self._handle_of(node.args[0])
            if h is not None:
                record_write(h)
        if attr == "dump" and len(node.args) >= 2:
            h = self._handle_of(node.args[1])
            if h is not None:
                record_write(h)

        # fsync clears (the one relevant handle in this codebase;
        # matching fd expressions would be false precision)
        if attr == "fsync":
            dirty = set()

        # visible actions
        if dirty:
            if attr in ENQUEUE_NAMES:
                self.flag(node.lineno, f".{attr}() enqueue")
            elif attr in EMIT_NAMES or name in EMIT_NAMES:
                self.flag(node.lineno, "telemetry emit")
            elif attr in PUBLISH_NAMES and isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "os":
                self.flag(node.lineno, f"os.{attr} publish")
        if attr in PUBLISH_NAMES and isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) \
                and f.value.id == "os" and len(node.args) >= 2:
            if json_pub:
                self.flag_json(node.lineno)
            elif _contains_json_literal(node.args[1]):
                json_pub = True
        return _DurableState(dirty, json_pub)


def check_durable_before_visible(fm: _FileModel) -> list[Finding]:
    findings = []

    def run(fmodel, cls):
        w = _DurableWalker(fm, fmodel.name, cls, findings)
        end = w.block(fmodel.node.body, _DurableState())
        if end.dirty:
            # fall-through end == implicit return
            last = fmodel.node.body[-1]
            if not _suppressed(fm.lines, last.lineno,
                               "durable-before-visible"):
                w.flag(last.lineno,
                       "function end (implicit return)")

    for f in fm.functions.values():
        run(f, None)
    for cm in fm.classes.values():
        for m in cm.methods.values():
            run(m, cm)
    return findings


# ---------------------------------------------------------------------
# driver


def _load(path: str) -> _FileModel | None:
    with open(path) as f:
        src = f.read()
    return _FileModel(path, src)


def analyze_paths(paths) -> list[Finding]:
    """Run all five checks over ``paths`` (.py files); lock-order is
    computed over the whole set at once (the cross-module graph)."""
    models = []
    findings: list[Finding] = []
    for p in paths:
        p = os.path.abspath(p)
        try:
            models.append(_load(p))
        except SyntaxError as e:
            findings.append(Finding(p, e.lineno or 1, "parse",
                                    f"syntax error: {e.msg}"))
    for fm in models:
        _prescan(fm)
    registry = _build_registry(models)
    for fm in models:
        _collect(fm, registry)
        for cm in fm.classes.values():
            _infer_lock_held_helpers(cm)
    for fm in models:
        findings += check_guarded_field(fm)
        findings += check_snapshot_iteration(fm)
        findings += check_toctou_gate(fm)
        findings += check_durable_before_visible(fm)
    findings += check_lock_order(models)
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    seen, uniq = set(), []
    for f in findings:
        key = (f.path, f.line, f.check, f.message)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def default_paths() -> list[str]:
    base = os.path.join(REPO, "lux_tpu")
    return [os.path.join(base, m) for m in HOST_MODULES
            if os.path.isfile(os.path.join(base, m))]


def run_lockcheck(paths=None, mode: str = "error") -> list[Finding]:
    """Library entry: analyze and either return the findings
    (``mode='findings'``), print them as warnings (``'warn'``), or
    raise the typed ``LockCheckError`` of the first finding's check
    class (``'error'`` — the tier-1 gate's form)."""
    if mode not in ("error", "warn", "findings"):
        raise ValueError(f"unknown lockcheck mode {mode!r}; choose "
                         f"error|warn|findings")
    findings = analyze_paths(paths if paths is not None
                             else default_paths())
    if not findings:
        return []
    if mode == "warn":
        for f in findings:
            print(f"lockcheck warning: {f}", file=sys.stderr)
        return findings
    if mode == "error":
        first = findings[0]
        raise LockCheckError(
            first.check,
            "\n".join(str(f) for f in findings),
            findings)
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="host-concurrency & durability static analyzer "
                    "(guarded-field, lock-order, "
                    "durable-before-visible, snapshot-iteration, "
                    "toctou-gate)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files to check (default: the threaded "
                         "host modules)")
    ap.add_argument("-q", action="store_true", dest="quiet")
    args = ap.parse_args(argv)
    paths = args.paths or default_paths()
    findings = analyze_paths(paths)
    for f in findings:
        print(str(f), file=sys.stderr)
    if findings:
        print(f"lockcheck: {len(findings)} finding(s) — FAILED",
              file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"lockcheck: clean ({len(paths)} module(s), "
              f"checks: {', '.join(CHECKS)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
