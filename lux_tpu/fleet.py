"""Resilient serving tier: replicated runners, heartbeat failover,
admission control, and brownout shedding.

The reference inherits fault tolerance from the Legion/Realm runtime
(task re-mapping under node loss, reference README.md:33-38); the
serving front-end (lux_tpu/serve.py) has no such layer — one Server
holds one BatchRunner per kind, and a topology fault kills every
in-flight query with it.  This module is that layer, composed from
pieces earlier rounds already proved: the heartbeat board (round 11),
the classified-retry machinery (rounds 6/11), the SLO metrics
substrate (round 17), and the continuous-batching runners themselves.

- **ReplicaPool**: a :class:`FleetServer` owns N replicas, each a full
  per-kind runner set (``serve.PushBatchRunner`` /
  ``PullBatchRunner``) — in-process by default, plus capability-gated
  SUBPROCESS replicas (``add_subprocess_replica``: an independent OS
  process running a whole ``serve.Server`` fed through a shared spool
  directory, hard-killable, its liveness visible only through the
  shared-dir :class:`heartbeat.ReplicaBoard`).  Every replica beats
  the board at each segment boundary (the runners' ``on_boundary``
  hook), and ``replica_up`` / ``replica_lost`` events trail the
  membership.

- **Admission control** (``submit``): requests carry
  tenant/priority/deadline (serve.Request); admission sheds with a
  typed :class:`AdmissionError` — reasons, in check order:
  ``no_capacity`` (no healthy replica), ``brownout`` (surviving
  capacity dropped and the request's priority is below the brownout
  floor — lowest-priority tenants shed FIRST), ``quota`` (the
  tenant's in-flight+queued count at its configured cap),
  ``queue_full`` (bounded per-kind queue), and ``deadline``
  (projected wait — queue-ahead x mean observed service time /
  surviving column capacity, the ``fleet_service_seconds`` histogram
  mean — exceeds the query's own deadline).  Admitted requests queue
  in a deadline-priority :class:`serve.PriorityCollector` (aged
  requests past half their deadline cannot be displaced
  indefinitely — the pinned aging rule) and are routed to the
  healthiest replica: min (beat age + burn-weighted SLO burn, load).
  Every shed gets a ``query_shed`` event and a record in
  ``shed_records``; resilience.classify treats AdmissionError as
  FATAL (an intentional rejection must never be retried into
  re-admission by a supervisor).

- **Failover** (exactly-once): a replica death mid-drain
  (heartbeat.WorkerLostError, faults.InjectedWorkerKill/
  InjectedDeviceLoss from a :class:`faults.ReplicaKillPlan`, a
  subprocess exit, or beat staleness past ``replica_deadline_s``)
  marks the replica lost and re-dispatches its un-retired in-flight
  queries to survivors — per query, after a
  ``resilience.RetryPolicy`` decorrelated-jitter backoff — each with
  a ``failover`` event naming from/to replicas.  Retirement is
  EXACTLY-ONCE: the front-end dedups on qid (``_retired``), a
  replayed query that already retired is dropped
  (``dup_dropped``), and because engines are deterministic in the
  graph arrays and the source, a re-dispatched integer-app query's
  answer is bitwise-equal to a fault-free run's.  The chaos
  acceptance (tests/test_fleet.py) kills a replica mid-load under
  oversubscribed mixed-kind loadgen traffic on the 8-virtual-device
  mesh and proves: every admitted answer oracle-correct, zero
  duplicate retirements, every shed typed, SLO-good fraction over
  admitted queries at target.

- **Brownout**: losing a replica raises the brownout level (one per
  lost replica); while browned out, admission requires
  ``priority >= brownout_min_priority``, so the lowest-priority
  tenants shed first and the surviving capacity serves the paying
  traffic.  The floor defaults to 0 — brownout shedding is an
  OPERATOR POLICY (which tenants are sacrificial), not a default: a
  fleet that silently dropped every default-priority query on the
  first replica loss would fail its admitted-SLO contract exactly
  when resilience matters.  A ``brownout`` event marks each level change, and
  per-replica health gauges (``fleet_replica_beat_age``) plus the
  fleet gauges (``fleet_replicas_healthy``, ``fleet_brownout_level``)
  ride the shared metrics registry.

- **Self-healing** (round 24): three layers on top of failover, so a
  fleet recovers CAPACITY and even a whole-process crash, not just
  in-flight queries.  (1) The durable ADMISSION JOURNAL
  (``journal_path=``, lux_tpu/journal.py — the MutationLog's
  CRC-chained LUXJ sidecar): every admit is fsynced to disk BEFORE it
  queues and every retirement (answer or late shed) is journaled at
  the exactly-once gate, so :meth:`FleetServer.recover` can restart a
  crashed fleet — replay the journal (torn tail truncated like the
  WAL's), seed the persisted qid dedup, and re-dispatch every
  admitted-unretired query at its ORIGINAL admission epoch
  (bitwise-equal answers for the integer apps; the only recovery
  sheds are the typed ``reset_unavailable`` / ``epoch_folded``).
  Recovery ordering is WAL replay -> generation adoption -> journal
  re-dispatch (ARCHITECTURE.md "Self-healing fleet").  (2) REPLICA
  RESURRECTION (``heal=True``): the run loop's supervisor respawns
  lost in-process replicas under ``respawn_retry`` decorrelated-
  jitter backoff; N deaths of one name inside ``flap_window_s``
  (resilience.FlapDetector) trip a typed QUARANTINE instead, and
  routing re-entry is gated on an ORACLE-CHECKED CANARY query (a
  wrong-computing replica is strictly worse than a dead one) — the
  brownout level decays as replicas rejoin, and ``fleet_mttr_seconds``
  records first-loss -> pool-whole.  (3) The WHOLE-FLEET KILL drill
  (faults.FLEET_CRASH / REPLICA_FLAP, tests/test_fleet.py) proves
  zero lost admitted queries, zero duplicate retirements, and
  oracle-equal answers at pre-crash epochs across a full
  crash-restart.

Bench: ``bench.py -config serve-chaos`` drives a FleetServer under an
open-loop load with an armed kill plan and emits serve-slo lines
extended with shed_fraction/failovers/replicas plus the round-24
healing gauges (respawns/quarantines/mttr_s/journal_replayed;
scripts/check_bench.py rejects the contradictions); the real-TPU
kill-under-load drill is carried as debt ``serve-chaos-on-device``
(lux_tpu/observe.py).  Smoke: ``python -m lux_tpu.fleet`` drains an
oversubscribed mixed load across 2 replicas with replica 1 killed
mid-drain and oracle-checks every retired answer.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

from lux_tpu import faults as faults_mod
from lux_tpu import heartbeat as heartbeat_mod
from lux_tpu import journal as journal_mod
from lux_tpu import resilience
from lux_tpu import serve as serve_mod
from lux_tpu.serve import (KINDS, DEFAULT_SEG_ITERS, PriorityCollector,
                           PullBatchRunner, PushBatchRunner, Request,
                           Response, _emit)

# shed reasons (AdmissionError.reason / query_shed events), in the
# order admission checks them
SHED_NO_CAPACITY = "no_capacity"
SHED_BROWNOUT = "brownout"
SHED_QUOTA = "quota"
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"
SHED_RETRIES = "retries"
# round 20 (live graphs, lux_tpu/livegraph.py): the INGEST path's
# backpressure — the fixed-capacity delta blocks are full because
# mutations outran compaction, so the append is shed with the same
# typed AdmissionError discipline as a query (never silently dropped,
# never blocking the serving loop)
SHED_DELTA_FULL = "delta_full"
# round 22 (memory observatory, lux_tpu/memwatch.py): admitting B
# more columns is priced in BYTES (batch state + answer-cache
# headroom on top of the replica's unified ledger) and shed typed
# when the projection crosses the per-replica budget — the same
# projected-resource pattern as the deadline check, applied to the
# resource ROADMAP item 3 names as the wall
SHED_MEMORY = "memory"
# round 24 (self-healing fleet): RECOVERY-only shed reasons.  A
# journalled admit whose reset vector the recovering caller did not
# re-supply (the journal stores only the digest — a reset vector is
# nv floats and cannot live in a fixed record), and an admission
# epoch the recovered generation can no longer REPRODUCE (a durable
# compaction folded past it before the crash) — both are closed
# TYPED at recovery, never silently dropped: the journal gets a
# RETIRE(shed) record and the trail a query_shed event
SHED_RESET_UNAVAILABLE = "reset_unavailable"
SHED_EPOCH_FOLDED = "epoch_folded"

# routing health score: beat age (s) + BURN_WEIGHT x the replica's
# rolling SLO-burn fraction — a replica burning its whole SLO budget
# scores like one BURN_WEIGHT seconds behind on its heartbeat
BURN_WEIGHT = 5.0

# parent poll cadence while only subprocess answers are outstanding
REMOTE_POLL_S = 0.01
# a subprocess replica may queue up to this many x batch requests
# beyond its resident columns before routing passes it over
REMOTE_QUEUE_FACTOR = 2


class AdmissionError(RuntimeError):
    """Typed shed: the serving tier REJECTED a query instead of
    admitting it.  Carries qid/kind/tenant/reason (one of the SHED_*
    constants) and the projected wait when the deadline check shed
    it.  resilience.classify treats this as FATAL — an intentional
    rejection is a DECISION, not a failure; retrying would re-admit a
    query the tier chose to shed."""

    def __init__(self, qid: int, kind: str, tenant: str, reason: str,
                 projected_wait_s: float | None = None,
                 deadline_s: float | None = None,
                 projected_bytes: int | None = None,
                 budget_bytes: int | None = None):
        msg = (f"query {qid} [{kind}] from tenant {tenant!r} shed: "
               f"{reason}")
        if projected_wait_s is not None:
            msg += (f" (projected wait {projected_wait_s:.3f}s vs "
                    f"deadline {deadline_s}s)")
        if projected_bytes is not None:
            msg += (f" (projected {projected_bytes} bytes vs budget "
                    f"{budget_bytes} bytes)")
        super().__init__(msg)
        self.qid = int(qid)
        self.kind = kind
        self.tenant = tenant
        self.reason = reason
        self.projected_wait_s = projected_wait_s
        self.deadline_s = deadline_s
        self.projected_bytes = projected_bytes
        self.budget_bytes = budget_bytes


class _InProcessReplica:
    """One in-process runner set (one batched engine per kind) plus
    its health bookkeeping."""

    remote = False

    def __init__(self, fleet: "FleetServer", name: str, index: int):
        self.fleet = fleet
        self.name = name
        self.index = int(index)
        self.state = "up"
        self.error: BaseException | None = None
        self._runners: dict = {}
        self._collectors: dict = {}

    def runner(self, kind: str):
        if kind not in self._runners:
            r = self.fleet._build_runner(kind)
            r.replica = self.name
            r.on_boundary = lambda runner, rep=self: \
                self.fleet._boundary(rep, runner)
            self._runners[kind] = r
        return self._runners[kind]

    def collector(self, kind: str) -> PriorityCollector:
        if kind not in self._collectors:
            self._collectors[kind] = PriorityCollector(
                metrics=self.fleet.metrics, kind=kind,
                replica=self.name)
        return self._collectors[kind]

    def pending(self, kind: str) -> int:
        n = len(self._collectors[kind]) if kind in self._collectors \
            else 0
        if kind in self._runners:
            n += sum(1 for s in self._runners[kind].slots
                     if s is not None)
        return n

    def pending_total(self) -> int:
        kinds = set(self._collectors) | set(self._runners)
        return sum(self.pending(k) for k in kinds)

    def slo_burn(self) -> float:
        """Mean rolling SLO-burn fraction over this replica's
        runners (0.0 when no SLO accounting ran yet)."""
        fracs = []
        for r in self._runners.values():
            if r._slo_window:
                fracs.append(sum(r._slo_window) / len(r._slo_window))
        return sum(fracs) / len(fracs) if fracs else 0.0


class _SubprocessReplica:
    """A replica in its own OS process (a whole serve.Server fed
    through a spool directory).  Liveness comes from the replica
    board (and the process exit code); answers arrive as
    npy+json file pairs, json written LAST so its presence marks a
    complete answer."""

    remote = True

    def __init__(self, fleet: "FleetServer", name: str, index: int,
                 spool: str, proc, batch: int):
        self.fleet = fleet
        self.name = name
        self.index = int(index)
        self.state = "up"
        self.error: BaseException | None = None
        self.spool = spool
        self.inbox = os.path.join(spool, f"inbox_{name}")
        self.outdir = os.path.join(spool, f"out_{name}")
        self.proc = proc
        self.batch = int(batch)
        self.inflight: dict[int, Request] = {}

    def free(self) -> int:
        return REMOTE_QUEUE_FACTOR * self.batch - len(self.inflight)

    def pending(self, kind: str) -> int:
        return sum(1 for r in self.inflight.values()
                   if r.kind == kind)

    def pending_total(self) -> int:
        return len(self.inflight)

    def slo_burn(self) -> float:
        return 0.0          # worker-side burn is not exported (yet)

    def dispatch(self, req: Request) -> None:
        doc = {"qid": req.qid, "kind": req.kind, "source": req.source}
        if req.reset is not None:
            # personalized-pagerank reset vectors ride an npy
            # sidecar, written BEFORE the request json (the json's
            # presence marks a complete request pair)
            fd, tmp = tempfile.mkstemp(dir=self.spool,
                                       suffix=".rst.tmp")
            with os.fdopen(fd, "wb") as f:
                # lockcheck: allow(durable-before-visible) same-host
                # IPC spool, not a durability record: a torn/lost
                # reset is re-dispatched from the admission journal
                np.save(f, np.asarray(req.reset, np.float32))
            os.replace(tmp, os.path.join(
                self.inbox, f"q{req.qid:08d}.reset.npy"))
            doc["reset"] = True
        fd, tmp = tempfile.mkstemp(dir=self.spool, suffix=".req.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(self.inbox,
                                     f"q{req.qid:08d}.json"))
        self.inflight[req.qid] = req

    def stop(self) -> None:
        try:
            with open(os.path.join(self.spool, "stop"), "w") as f:
                f.write("stop\n")
        except OSError:
            pass
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.terminate()
            except OSError:
                pass


class FleetServer:
    """The resilient serving tier above serve.Server: route queries
    by kind across a pool of replicas with admission control,
    heartbeat-supervised failover and brownout shedding (module
    docstring has the full contract).  Duck-type compatible with
    serve.Server for scripts/loadgen.py: ``g``/``submit``/``run``/
    ``set_metrics``/``emit_metrics_snapshot``/``_collectors``."""

    def __init__(self, g, *, replicas: int = 2, batch: int = 4,
                 num_parts: int = 1, mesh=None, exchange: str = "auto",
                 health: bool = False, weighted: bool = False,
                 seg_iters: int = DEFAULT_SEG_ITERS, tol: float = 1e-8,
                 slo_ms: dict | None = None, metrics=None,
                 snapshot_every_s: float = 1.0,
                 board_path: str | None = None,
                 max_queue: int = 256, quota: dict | None = None,
                 brownout_min_priority: int = 0,
                 retry: resilience.RetryPolicy | None = None,
                 fault: faults_mod.ReplicaKillPlan | None = None,
                 replica_deadline_s: float = 3.0, live=None,
                 cache: bool = False,
                 mem_budget_bytes: int | None = None,
                 mem_horizon_s: float = 5.0,
                 mem_clock=time.monotonic,
                 journal_path: str | None = None,
                 heal: bool = False,
                 respawn_retry: resilience.RetryPolicy | None = None,
                 flap_threshold: int = 3,
                 flap_window_s: float = 60.0,
                 heal_clock=time.monotonic):
        if replicas < 1:
            raise ValueError(f"fleet needs >= 1 replica, got "
                             f"{replicas}")
        self.g = g
        # live-graph serving (round 20, lux_tpu/livegraph.py): one
        # SHARED LiveGraph across every in-process replica — its
        # published delta blocks are immutable, so a failed-over
        # query re-runs on the survivor at its ORIGINAL admission
        # epoch and (integer apps) answers bitwise-identically.
        # Subprocess replicas serve the static graph spec and carry
        # no live handle, so a live fleet REFUSES them (typed, in
        # add_subprocess_replica): a remote answer computed on the
        # static base would wear epoch=None and evade both the
        # torn-epoch audit and check_live_answers.
        self.live = live
        if live is not None and g is not live.base:
            raise ValueError(
                "FleetServer(live=...) requires g to be live.base")
        if cache is True:
            from lux_tpu.serve import AnswerCache
            self.cache = AnswerCache.from_slo(slo_ms)
        elif cache:
            self.cache = cache
        else:
            self.cache = None
        self.batch = int(batch)
        self.opts = dict(num_parts=num_parts, mesh=mesh,
                         exchange=exchange, health=health)
        self.weighted = bool(weighted)
        self.seg_iters = int(seg_iters)
        self.tol = float(tol)
        self.slo_ms = dict(slo_ms or {})
        for k in self.slo_ms:
            if k not in KINDS:
                raise ValueError(f"slo_ms names unknown kind {k!r}; "
                                 f"choose from {KINDS}")
        if metrics is False:
            self.metrics = None
        elif metrics is None:
            from lux_tpu import metrics as metrics_mod
            self.metrics = metrics_mod.Registry()
        else:
            self.metrics = metrics
        self.snapshot_every_s = float(snapshot_every_s)
        self._last_snapshot = 0.0
        self.board = heartbeat_mod.ReplicaBoard(
            board_path or tempfile.mkdtemp(prefix="lux_fleet_board_"),
            deadline_s=float(replica_deadline_s))
        self.replica_deadline_s = float(replica_deadline_s)
        self.max_queue = int(max_queue)
        self.quota = dict(quota or {})
        self.brownout_min_priority = int(brownout_min_priority)
        self.retry = retry or resilience.RetryPolicy(
            retries=3, backoff_s=0.02, max_backoff_s=0.5)
        self.fault = fault
        # round-22 memory observatory: per-replica byte budget (None
        # = unbudgeted — no memory admission, no forecaster) + the
        # boundary-fed occupancy trails (built lazily per replica at
        # its first boundary; fake-clock-injectable for tests)
        self.mem_budget_bytes = (None if mem_budget_bytes is None
                                 else int(mem_budget_bytes))
        self.mem_horizon_s = float(mem_horizon_s)
        self.mem_clock = mem_clock
        self._mem_trails: dict = {}

        import threading
        # RLock: admission (submitter threads) and retirement /
        # late-shed bookkeeping (the drain thread) share the tenant
        # and qid maps; _shed runs both under the lock (inside
        # _admission) and outside it
        self._lock = threading.RLock()
        # all kinds pre-created: _queues is never mutated after
        # construction, so the run loop / pending views can iterate
        # it while submitter threads insert requests (a lazy
        # setdefault here would be a dict-changed-size crash)
        self._queues: dict[str, PriorityCollector] = {
            k: PriorityCollector(metrics=None, kind=k)
            for k in KINDS}
        self._replicas: list = []
        self._next_qid = 0
        self._qreq: dict[int, Request] = {}
        self._retired: set[int] = set()
        self._attempts: dict[int, int] = {}
        self._tenant_load: dict[str, int] = {}
        self.failovers = 0
        self.dup_dropped = 0
        self.shed_records: list[AdmissionError] = []
        self._brownout = 0
        # round-24 self-healing state.  The admission journal makes
        # every admit durable BEFORE it queues (and every retirement
        # durable at the exactly-once gate), so FleetServer.recover
        # can re-dispatch a crashed fleet's admitted-unretired
        # queries at their original epochs; ``heal`` arms the
        # resurrection supervisor (respawn under decorrelated-jitter
        # backoff, flap -> quarantine, canary-gated routing
        # re-entry).
        self.journal = (None if journal_path is None else
                        journal_mod.AdmissionJournal(journal_path,
                                                     nv=g.nv))
        self._journaled: set[int] = set()
        self.heal = bool(heal)
        self.respawn_retry = respawn_retry or self.retry
        self.heal_clock = heal_clock
        self.flap = resilience.FlapDetector(
            threshold=int(flap_threshold),
            window_s=float(flap_window_s), clock=heal_clock)
        self.respawns = 0
        self.quarantines = 0
        self.journal_replayed = 0
        self.mttr_s: float | None = None
        self._respawn_at: dict[str, float] = {}
        self._respawn_attempts: dict[str, int] = {}
        self._canaries: set[int] = set()
        self._t_degraded: float | None = None
        for i in range(int(replicas)):
            self._add_inproc_replica()

    # -- replica pool --------------------------------------------------

    @property
    def replica_names(self) -> list[str]:
        return [r.name for r in self._replicas]

    def _add_inproc_replica(self):
        name = f"r{len(self._replicas)}"
        rep = _InProcessReplica(self, name, len(self._replicas))
        self._replicas.append(rep)
        self.board.beat(name, status="up", boundary=0)
        _emit("replica_up", replica=name, remote=False,
              capacity=self.batch)
        self._health_gauges()
        return rep

    def add_subprocess_replica(self, graph_spec: dict, *,
                               workdir: str | None = None,
                               num_parts: int = 1,
                               kill_boundary: int | None = None,
                               spawn_budget_s: float = 60.0):
        """Spawn a subprocess replica (capability probe included):
        launch the worker, wait up to ``spawn_budget_s`` for its
        first board beat, and return the replica — or None when the
        environment cannot spawn one in budget (the caller falls back
        to an in-process replica; the chaos drill's documented
        fallback path).  ``graph_spec`` must rebuild the SAME graph
        the parent serves (see ``_graph_from_spec``);
        ``kill_boundary`` arms a hard-kill ReplicaKillPlan inside the
        worker."""
        import subprocess

        if self.live is not None:
            raise ValueError(
                "subprocess replicas serve the static graph spec "
                "and cannot answer at a live admission epoch — a "
                "live-graph fleet is in-process only")
        name = f"r{len(self._replicas)}"
        spool = workdir or tempfile.mkdtemp(prefix="lux_fleet_")
        os.makedirs(os.path.join(spool, f"inbox_{name}"),
                    exist_ok=True)
        os.makedirs(os.path.join(spool, f"out_{name}"), exist_ok=True)
        spec = {"name": name, "dir": spool, "board": self.board.path,
                "graph": dict(graph_spec), "batch": self.batch,
                "num_parts": int(num_parts),
                "seg_iters": self.seg_iters, "tol": self.tol,
                "weighted": self.weighted,
                "kill_boundary": kill_boundary}
        spec_path = os.path.join(spool, f"spec_{name}.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        proc = subprocess.Popen(
            [sys.executable, "-m", "lux_tpu.fleet", "-worker",
             spec_path],
            env=_worker_env(ndev=num_parts), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        t0 = time.monotonic()
        ok = False
        while time.monotonic() - t0 < float(spawn_budget_s):
            if self.board.read(name) is not None:
                ok = True
                break
            if proc.poll() is not None:
                break
            time.sleep(0.05)
        if not ok:
            if proc.poll() is None:
                proc.kill()
            return None
        rep = _SubprocessReplica(self, name, len(self._replicas),
                                 spool, proc, self.batch)
        self._replicas.append(rep)
        _emit("replica_up", replica=name, remote=True,
              capacity=self.batch)
        self._health_gauges()
        return rep

    def _build_runner(self, kind: str):
        mkw = dict(metrics=self.metrics,
                   slo_ms=self.slo_ms.get(kind),
                   live=self.live, cache=self.cache)
        if kind == "pagerank":
            return PullBatchRunner(kind, self.g, self.batch,
                                   seg_iters=self.seg_iters,
                                   tol=self.tol, **mkw, **self.opts)
        return PushBatchRunner(kind, self.g, self.batch,
                               weighted=self.weighted,
                               seg_iters=self.seg_iters, **mkw,
                               **self.opts)

    def _boundary(self, rep, runner) -> None:
        """Per-replica segment-boundary hook: beat the board, sample
        the memory trail (budgeted fleets only — the forecaster's
        mem_pressure warning must land BEFORE any memory shed or
        DeltaFullError in the event trail), then fire the chaos plan
        (whose raise propagates out of the drain as a mid-drain
        death)."""
        self.board.beat(rep.name, status="up", kind=runner.kind)
        if self.mem_budget_bytes is not None:
            self.mem_trail(rep.name).sample(
                where=f"{runner.kind}:boundary")
        if self.fault is not None:
            self.fault.fire(rep.name)

    def mem_trail(self, name: str):
        """The named replica's boundary-fed occupancy trail
        (memwatch.MemoryTrail, built lazily; budget + forecaster
        attached when the fleet carries ``mem_budget_bytes``).  The
        trail's bytes source is the replica's UNIFIED ledger —
        static engine terms + the shared dynamic consumers — priced
        by host arithmetic only (no compile, no device traffic: the
        boundary hook contract)."""
        from lux_tpu import memwatch

        if name not in self._mem_trails:
            rep = next(r for r in self._replicas if r.name == name)
            self._mem_trails[name] = memwatch.MemoryTrail(
                bytes_fn=lambda: self._replica_bytes(rep),
                metrics=self.metrics or None, replica=name,
                budget_bytes=self.mem_budget_bytes,
                horizon_s=self.mem_horizon_s, clock=self.mem_clock)
        return self._mem_trails[name]

    def _replica_bytes(self, rep) -> int:
        """One replica's unified-ledger total right now (memwatch
        pillar 2): its built runners' static terms + the tier-shared
        cache/live/staging consumers."""
        from lux_tpu import memwatch

        return memwatch.replica_ledger(self, rep).total_bytes

    def set_fault(self, plan) -> None:
        """Arm (or disarm with None) a faults.ReplicaKillPlan — bench
        arms it AFTER the engine-compile warmup so the kill boundary
        counts only loaded traffic."""
        self.fault = plan

    def _healthy(self) -> list:
        return [r for r in self._replicas if r.state == "up"]

    def _score(self, rep, kind: str) -> float:
        age = self.board.age(rep.name)
        return (age if age is not None else 0.0) \
            + BURN_WEIGHT * rep.slo_burn()

    def _pick(self, kind: str):
        """Healthiest replica with room: min (health score, load)."""
        cands = [r for r in self._healthy()
                 if not r.remote or r.free() > 0]
        if not cands:
            return None
        return min(cands, key=lambda r: (round(self._score(r, kind),
                                               6),
                                         r.pending_total(), r.index))

    def routing_target(self, kind: str) -> str | None:
        """The replica name the NEXT query of ``kind`` would route
        to (None when none is healthy).  Chaos drills arm their kill
        plans on this: routing is a positive-feedback loop — the
        picked replica drains, which refreshes its beat, which keeps
        it the pick — so a plan armed on any FIXED index is a coin
        flip on millisecond beat timing inside warm(), and the
        losing side is a drill whose kill never fires (the round-22
        serve-chaos fix; tests/test_memwatch.py pins it)."""
        rep = self._pick(kind)
        return None if rep is None else rep.name

    def _health_gauges(self) -> None:
        if self.metrics is None:
            return
        m = self.metrics
        m.gauge("fleet_replicas_healthy").set(len(self._healthy()))
        m.gauge("fleet_brownout_level").set(self._brownout)
        for rep in self._replicas:
            age = self.board.age(rep.name)
            m.gauge("fleet_replica_beat_age",
                    replica=rep.name).set(age if age is not None
                                          else -1.0)

    # -- admission -----------------------------------------------------

    def _queue(self, kind: str) -> PriorityCollector:
        # fleet queues carry no metrics handle: queue-wait is
        # observed once, at column collection in the replica's own
        # collector (double-observing would halve every percentile)
        if kind not in self._queues:
            raise ValueError(f"unknown query kind {kind!r}; choose "
                             f"from {KINDS}")
        return self._queues[kind]

    def _projected_wait(self, kind: str) -> float:
        """Queue-ahead x mean observed service time / surviving
        column capacity — 0.0 until the first retirement seeds the
        service-time histogram (cold admission is optimistic by
        design: shedding on no evidence would brown out an idle
        tier)."""
        mean = None
        if self.metrics is not None:
            mean = self.metrics.histogram("fleet_service_seconds",
                                          kind=kind).mean()
        if mean is None:
            return 0.0
        ahead = len(self._queue(kind)) + sum(
            r.pending(kind) for r in self._healthy())
        cap = self.batch * max(1, len(self._healthy()))
        return ahead * mean / cap

    def _shed(self, req: Request, reason: str, *,
              projected: float | None = None,
              projected_bytes: int | None = None,
              raise_: bool = True):
        err = AdmissionError(req.qid, req.kind, req.tenant, reason,
                             projected_wait_s=projected,
                             deadline_s=req.deadline_s,
                             projected_bytes=projected_bytes,
                             budget_bytes=(self.mem_budget_bytes
                                           if projected_bytes
                                           is not None else None))
        with self._lock:
            self.shed_records.append(err)
            if req.qid in self._qreq:   # late shed of an admitted req
                self._qreq.pop(req.qid, None)
                self._tenant_load[req.tenant] = max(
                    0, self._tenant_load.get(req.tenant, 1) - 1)
                if self.live is not None:
                    self.live.release()
            if (self.journal is not None
                    and req.qid in self._journaled):
                # a late shed RETIRES the journal entry (cause
                # "shed"): recover() must not resurrect a query the
                # fleet already rejected with a typed AdmissionError
                self.journal.append_retire(req.qid, "shed")
                self._journaled.discard(req.qid)
        if self.metrics is not None:
            self.metrics.counter("fleet_shed_total", kind=req.kind,
                                 reason=reason).inc()
        extra = {} if projected is None else {
            "projected_wait_s": round(projected, 6)}
        if projected_bytes is not None:
            extra["projected_bytes"] = int(projected_bytes)
            extra["budget_bytes"] = self.mem_budget_bytes
        _emit("query_shed", qid=req.qid, query_kind=req.kind,
              tenant=req.tenant, priority=req.priority,
              reason=reason, **extra)
        if raise_:
            raise err
        return err

    def _admission(self, req: Request) -> None:
        if not self._healthy():
            self._shed(req, SHED_NO_CAPACITY)
        if self._brownout and req.priority < self.brownout_min_priority:
            self._shed(req, SHED_BROWNOUT)
        cap = self.quota.get(req.tenant)
        if cap is not None \
                and self._tenant_load.get(req.tenant, 0) >= cap:
            self._shed(req, SHED_QUOTA)
        if len(self._queue(req.kind)) >= self.max_queue:
            self._shed(req, SHED_QUEUE_FULL)
        if req.deadline_s is not None:
            p = self._projected_wait(req.kind)
            if p > req.deadline_s:
                self._shed(req, SHED_DEADLINE, projected=p)
        if self.mem_budget_bytes is not None:
            b = self._projected_bytes(req.kind)
            if b is not None and b > self.mem_budget_bytes:
                self._shed(req, SHED_MEMORY, projected_bytes=b)

    def _projected_bytes(self, kind: str) -> int | None:
        """Projected resident bytes of the routing target AFTER
        admitting this query's batch (memwatch pillar 3): the
        replica's unified-ledger total + batch x (column state +
        answer-cache headroom).  None when no replica is routable
        (the no_capacity check upstream already shed) or the target
        replica has not built the kind's engine yet (a cold replica
        cannot be priced per column — cold admission stays
        optimistic, exactly like _projected_wait)."""
        from lux_tpu import memwatch

        rep = self._pick(kind)
        if rep is None or rep.remote:
            return None
        runner = rep._runners.get(kind)
        if runner is None:
            return None
        return memwatch.projected_admission_bytes(
            self._replica_bytes(rep), batch=self.batch,
            column_bytes=memwatch.column_state_bytes(runner.eng),
            answer_bytes=(0 if self.cache is None
                          else self.g.nv
                          * memwatch.ANSWER_BYTES_PER_VERTEX))

    def _admission_epoch(self, kind: str) -> int | None:
        """READ the epoch a query of ``kind`` would pin (cache
        sweeps; admission itself stamps atomically through
        serve.admit_query).  The pin survives failover re-dispatch,
        so a re-run on a survivor answers at the same epoch bitwise
        (serve._engine_family is the one kind-to-family rule)."""
        return serve_mod.admission_epoch(self.live, kind)

    def mutate(self, src, dst, weights=None,
               tenant: str = "default", op: str = "append") -> int:
        """The serving tier's INGEST path: publish one mutation
        batch into the shared live graph — ``op`` routes the full
        round-21 algebra ("append" default / "delete" / "reweight",
        serve.Server.mutate's rule).  When the delta blocks are full
        (ingest outran compaction) the mutation is shed with a
        typed ``AdmissionError(reason="delta_full")`` — recorded in
        shed_records and as a query_shed event like every other
        rejection — instead of blocking or silently dropping."""
        from lux_tpu import livegraph

        if self.live is None:
            raise ValueError("mutate() needs a live graph "
                             "(FleetServer(live=LiveGraph(...)))")
        if op not in ("append", "delete", "reweight"):
            raise ValueError(f"unknown mutation op {op!r}; choose "
                             f"from ('append', 'delete', "
                             f"'reweight')")
        try:
            if op == "delete":
                return self.live.delete_edges(src, dst)
            if op == "reweight":
                return self.live.reweight_edges(src, dst, weights)
            return self.live.append_edges(src, dst, weights)
        except livegraph.DeltaFullError:
            with self._lock:
                qid = self._next_qid
                self._next_qid += 1
            req = Request(qid=qid, kind="mutation",
                          t_enqueue=time.monotonic(),
                          tenant=str(tenant))
            self._shed(req, SHED_DELTA_FULL)

    def slo_burn(self) -> float:
        """Worst replica rolling SLO-burn fraction — the
        CompactionScheduler's backoff input (the same per-replica
        gauge routing already weighs, taken fleet-wide)."""
        return max((rep.slo_burn() for rep in self._replicas),
                   default=0.0)

    def refresh_live(self) -> None:
        """Adopt the live graph's new generation after a compaction
        (serve.Server.refresh_live's fleet analogue): every replica's
        runners are dropped and lazily rebuilt over the compacted
        base.  Refuses while queries are dispatched/resident at a
        replica, or CENTRALLY queued at an epoch the new base cannot
        REPRODUCE (serve._epoch_reproducible — both families replay
        any epoch >= base_epoch: push via the delta mask, pull via
        the degree-correction step; serve.Server.refresh_live's
        rule)."""
        if self.live is None:
            return
        stale = [req for q in self._queues.values()
                 for req in q.pending_requests()
                 if not serve_mod._epoch_reproducible(self.live,
                                                     req)]
        if stale:
            raise RuntimeError(
                f"refresh_live with {len(stale)} query(ies) queued "
                f"at an epoch the new generation cannot reproduce — "
                f"drain first")
        if any(rep.pending_total() for rep in self._healthy()):
            raise RuntimeError("refresh_live with queries still "
                               "dispatched or resident — drain "
                               "first")
        self.g = self.live.base
        for rep in self._replicas:
            if not rep.remote:
                rep._runners.clear()

    def submit(self, kind: str, source: int | None = None,
               reset=None, tenant: str = "default", priority: int = 0,
               deadline_s: float | None = None) -> int:
        """Admit-or-shed: returns the qid, or raises a typed
        AdmissionError (which also leaves a query_shed event and a
        shed_records entry — every rejection is accounted)."""
        q = self._queue(kind)           # validates kind first
        with self._lock:
            qid = self._next_qid
            self._next_qid += 1
        req = Request(qid=qid, kind=kind,
                      source=None if source is None else int(source),
                      reset=(None if reset is None
                             else np.asarray(reset, np.float32)),
                      t_enqueue=time.monotonic(), tenant=str(tenant),
                      priority=int(priority),
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)),
                      # stamp + admission-ledger entry atomically
                      # (serve.admit_query): the pinned epoch must
                      # stay serveable until this query's
                      # exactly-once retirement (_accept) or
                      # post-admission shed — released there; an
                      # admission-time shed releases below
                      epoch=serve_mod.admit_query(self.live, kind))
        if self.metrics is not None:
            self.metrics.counter("serve_queries_total",
                                 kind=kind).inc()
        _emit("query_enqueue", qid=qid, query_kind=kind,
              source=req.source, tenant=req.tenant,
              priority=req.priority, queued=len(q))
        with self._lock:
            try:
                self._admission(req)
            except AdmissionError:
                if self.live is not None:
                    self.live.release()
                raise
            if self.journal is not None:
                # durable BEFORE visible: the admit record reaches
                # the platter (write+flush+fsync) before the query
                # enters a routing queue, so a crash can lose an
                # un-acknowledged submit but never an acknowledged
                # one — recover() re-dispatches exactly this set
                try:
                    self.journal.append_admit(req)
                except BaseException:
                    if self.live is not None:
                        self.live.release()
                    raise
                self._journaled.add(qid)
            self._qreq[qid] = req
            self._tenant_load[req.tenant] = \
                self._tenant_load.get(req.tenant, 0) + 1
            q.put(req)
        return qid

    def warm(self, kinds=None) -> int:
        """Compile EVERY (replica, kind) engine outside a measured
        load: one throwaway query per replica per kind, assigned
        DIRECTLY to each replica (load-spread routing would warm one
        replica and leave the others' runners cold, billing XLA
        compilation to the first measured queries that land there —
        the warm contract loadgen's single-server warm cannot keep
        for a fleet).  Returns the number of warm responses
        drained."""
        kinds = list(kinds or KINDS)
        for rep in self._replicas:
            if rep.state != "up":
                continue
            for k in kinds:
                with self._lock:
                    qid = self._next_qid
                    self._next_qid += 1
                req = Request(qid=qid, kind=k, source=0,
                              t_enqueue=time.monotonic(),
                              epoch=serve_mod.admit_query(self.live,
                                                          k),
                              no_cache=True)
                _emit("query_enqueue", qid=qid, query_kind=k,
                      source=0, tenant=req.tenant,
                      priority=req.priority, queued=0)
                with self._lock:
                    self._qreq[qid] = req
                    self._tenant_load[req.tenant] = \
                        self._tenant_load.get(req.tenant, 0) + 1
                self._assign(rep, req)
        return len(self.run())

    # -- dispatch / drain / failover -----------------------------------

    def _assign(self, rep, req: Request) -> None:
        if rep.remote:
            rep.dispatch(req)
        else:
            rep.collector(req.kind).put(req)

    def _accept(self, resp: Response) -> bool:
        """Exactly-once retirement: False (and dropped) when the qid
        already retired — the replayed-query guard."""
        with self._lock:
            if resp.qid in self._retired:
                self.dup_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter("fleet_dup_dropped_total",
                                         kind=resp.kind).inc()
                return False
            self._retired.add(resp.qid)
            req = self._qreq.pop(resp.qid, None)
            if req is not None:
                self._tenant_load[req.tenant] = max(
                    0, self._tenant_load.get(req.tenant, 1) - 1)
                if self.live is not None:
                    # exactly-once: the pop above is the dedup gate,
                    # so a replayed answer can never double-release
                    self.live.release()
            if (self.journal is not None
                    and resp.qid in self._journaled):
                # the _retired gate above makes this exactly-once on
                # disk too: a replayed answer returns False before
                # reaching here, so no qid retires twice in the
                # journal (retire_dup is rot, not replay)
                self.journal.append_retire(resp.qid, "answered")
                self._journaled.discard(resp.qid)
        if self.metrics is not None and not resp.cached:
            # cache hits retire in ~0s and never touch an engine —
            # feeding them into the service-time histogram would
            # drag down the mean the deadline-admission projection
            # divides by, admitting queries that will actually wait
            # a full engine drain instead of shedding them typed
            self.metrics.histogram(
                "fleet_service_seconds", kind=resp.kind).observe(
                max(0.0, resp.latency_s - resp.wait_s))
        return True

    def _drain_inproc(self, rep, kind: str) -> list[Response]:
        runner = rep.runner(kind)
        n0 = len(runner.responses)
        err = None
        try:
            runner.drain(rep.collector(kind), deadline_s=0.0)
        except (heartbeat_mod.WorkerLostError,
                faults_mod.InjectedWorkerKill,
                faults_mod.InjectedDeviceLoss) as e:
            err = e
        out = [r for r in runner.responses[n0:] if self._accept(r)]
        if err is not None:
            self._mark_lost(rep, err)
        return out

    def _mark_lost(self, rep, err: BaseException) -> None:
        if rep.state == "lost":
            return
        rep.state = "lost"
        rep.error = err
        inflight: list[Request] = []
        if rep.remote:
            inflight = list(rep.inflight.values())
            rep.inflight.clear()
        else:
            for runner in rep._runners.values():
                for c, slot in enumerate(runner.slots):
                    if slot is not None:
                        inflight.append(slot.req)
                        runner.slots[c] = None
                        if runner.live is not None:
                            # the dead replica's resident queries no
                            # longer pin the generation; the
                            # re-dispatch pins again at _start
                            runner.live.unpin()
            for coll in rep._collectors.values():
                # suppress the dead collector's metrics for this
                # drain: the requests are about to re-queue on a
                # survivor, and observing their partial wait HERE
                # would double-count serve_wait_seconds (the replica
                # is lost — its collectors are never used again)
                coll.metrics = None
                inflight += coll.collect(len(coll))
        inflight = [r for r in inflight if r.qid not in self._retired]
        # a dead replica's CANARY dies with it: the probe exists to
        # exercise THAT replica's engine — failing it over to a
        # survivor would answer a question nobody asked and pollute
        # run()'s responses with throwaway qids
        canaries = [r for r in inflight if r.qid in self._canaries]
        inflight = [r for r in inflight
                    if r.qid not in self._canaries]
        with self._lock:
            for r in canaries:
                self._retired.add(r.qid)
                if self._qreq.pop(r.qid, None) is not None:
                    self._tenant_load[r.tenant] = max(
                        0, self._tenant_load.get(r.tenant, 1) - 1)
                    if self.live is not None:
                        self.live.release()
                self._canaries.discard(r.qid)
        _emit("replica_lost", replica=rep.name,
              error=type(err).__name__, message=str(err)[:200],
              inflight=len(inflight))
        if self.metrics is not None:
            self.metrics.counter("fleet_replica_lost_total").inc()
        # self-healing bookkeeping BEFORE the failovers below: MTTR
        # counts from the first detection that degraded the fleet,
        # and the flap verdict decides whether this death schedules
        # a resurrection or trips the quarantine
        if self._t_degraded is None:
            self._t_degraded = float(self.heal_clock())
        deaths = self.flap.record(rep.name)
        if not rep.remote:
            # the verdict applies whether healing is automatic
            # (run-loop _heal) or manual (resurrect()): a flapping
            # name must stop consuming respawns either way
            if deaths >= self.flap.threshold:
                self._quarantine(rep, reason="flap", deaths=deaths)
            else:
                k = self._respawn_attempts.get(rep.name, 0)
                self._respawn_at[rep.name] = (
                    float(self.heal_clock())
                    + self.respawn_retry.delay_s(k))
        self._set_brownout()
        self._health_gauges()
        t_detect = time.monotonic()
        for req in sorted(inflight, key=lambda r: r.t_enqueue):
            self._failover(req, rep, t_detect=t_detect)

    def _set_brownout(self) -> None:
        """Recompute the brownout level from the CURRENT pool state
        — one level per replica not serving (lost or quarantined) —
        and emit the level-change event both ways: resurrection
        DECAYS the level as replicas rejoin (down to 0 when the pool
        is whole again), the round-24 contract the original
        lost-count-only computation could never express."""
        level = sum(1 for r in self._replicas if r.state != "up")
        if level != self._brownout:
            self._brownout = level
            total = max(1, len(self._replicas))
            _emit("brownout", level=level,
                  capacity_frac=round(len(self._healthy()) / total,
                                      4),
                  min_priority=self.brownout_min_priority)

    def _quarantine(self, rep, reason: str, deaths: int = 0) -> None:
        """Typed removal from the resurrection loop: the replica is
        neither routed to nor respawned until an operator replaces
        it.  ``reason`` is "flap" (threshold deaths inside the flap
        window) or "canary" (the warm-up probe answered WRONG — a
        replica that computes incorrect answers is strictly worse
        than a dead one)."""
        rep.state = "quarantined"
        self.quarantines += 1
        self._respawn_at.pop(rep.name, None)
        _emit("replica_quarantine", replica=rep.name, reason=reason,
              deaths=int(deaths),
              window_s=round(self.flap.window_s, 3))
        if self.metrics is not None:
            self.metrics.counter("fleet_quarantines_total").inc()
        self._set_brownout()
        self._health_gauges()

    # -- resurrection (round 24) ---------------------------------------

    def _heal(self) -> None:
        """Non-blocking supervisor tick (run-loop hook): respawn
        every lost in-process replica whose decorrelated-jitter
        backoff has expired.  Quarantined replicas are never
        touched."""
        if not self.heal:
            return
        now = float(self.heal_clock())
        for rep in list(self._replicas):
            if rep.state != "lost" or rep.remote:
                continue
            due = self._respawn_at.get(rep.name)
            if due is not None and now >= due:
                self._respawn(rep)

    def resurrect(self, wait: bool = True) -> list[str]:
        """Drive resurrection to QUIESCENCE outside a serve loop:
        respawn every lost in-process replica (waiting out each
        backoff when ``wait``), repeating while the respawns
        themselves die (the flap pattern), until every replica is
        either up or quarantined.  Returns the names that re-entered
        routing.  Works with ``heal=False`` too — manual healing
        between drains."""
        out: list[str] = []
        while True:
            targets = [r for r in self._replicas
                       if r.state == "lost" and not r.remote]
            if not targets:
                break
            for rep in targets:
                now = float(self.heal_clock())
                due = self._respawn_at.get(rep.name)
                if due is None:
                    k = self._respawn_attempts.get(rep.name, 0)
                    due = now + self.respawn_retry.delay_s(k)
                    self._respawn_at[rep.name] = due
                if due > now:
                    if not wait:
                        return out
                    self.respawn_retry.sleep(due - now)
                if self._respawn(rep):
                    out.append(rep.name)
        return out

    def _respawn(self, rep) -> bool:
        """One resurrection attempt: replace the dead replica with a
        fresh runner set under the SAME name/index, warm it up — the
        canary recompiles its engine over the CURRENT base
        (generation adoption: runners build from ``self.g``, which
        refresh_live keeps at ``live.base``; in-process replicas
        share the live handle, so the published delta needs no
        catch-up) — and gate routing re-entry on the canary
        answering its NumPy oracle exactly.  Returns True when the
        replica re-entered routing."""
        name = rep.name
        k = self._respawn_attempts.get(name, 0)
        self._respawn_attempts[name] = k + 1
        self._respawn_at.pop(name, None)
        new = _InProcessReplica(self, name, rep.index)
        new.state = "warming"       # invisible to _pick until canary
        self._replicas[rep.index] = new
        # the old replica's memory-trail closure prices dead runners
        self._mem_trails.pop(name, None)
        self.board.beat(name, status="warming", boundary=0)
        ok = self._run_canary(new)
        if new.state != "warming":
            # died mid-warm-up: _mark_lost already recorded the
            # death, and its flap verdict re-scheduled or
            # quarantined — nothing more to do here
            return False
        if not ok:
            # a replica that computes WRONG answers is strictly
            # worse than a dead one — never route to it
            self._quarantine(new, reason="canary",
                             deaths=self.flap.deaths(name))
            return False
        new.state = "up"
        self.respawns += 1
        self._respawn_attempts[name] = 0    # healthy: fresh incident
        _emit("replica_respawn", replica=name, attempt=k + 1,
              backoff_s=round(self.respawn_retry.delay_s(k), 4),
              canary_ok=True)
        if self.metrics is not None:
            self.metrics.counter("fleet_respawns_total").inc()
        self.board.beat(name, status="up", boundary=0)
        self._set_brownout()
        self._health_gauges()
        if (self._t_degraded is not None
                and all(r.state == "up" for r in self._replicas)):
            # MTTR: first loss detection -> pool whole again
            self.mttr_s = (float(self.heal_clock())
                           - self._t_degraded)
            self._t_degraded = None
            if self.metrics is not None:
                self.metrics.gauge("fleet_mttr_seconds").set(
                    round(self.mttr_s, 6))
        return True

    def _run_canary(self, rep, kind: str = "components") -> bool:
        """Oracle-checked warm-up probe: one throwaway query
        assigned DIRECTLY to the warming replica (like warm(), no
        routing — the probe must exercise THIS replica's engine).
        True iff the replica stayed up through the drain and the
        answer matches its NumPy oracle — live fleets at the
        canary's own admission epoch (check_live_answers), static
        fleets against the base graph.  The default kind is
        components: integer-labeled (bitwise comparison) and
        weight-agnostic, so one canary rule covers weighted and
        unweighted fleets."""
        with self._lock:
            qid = self._next_qid
            self._next_qid += 1
        req = Request(qid=qid, kind=kind, source=0,
                      t_enqueue=time.monotonic(),
                      epoch=serve_mod.admit_query(self.live, kind),
                      no_cache=True)
        with self._lock:
            self._qreq[qid] = req
            self._tenant_load[req.tenant] = \
                self._tenant_load.get(req.tenant, 0) + 1
            self._canaries.add(qid)
        # the canary is a PROBE, not traffic: suppress the runner's
        # SLO/latency metrics for its drain (slo_accounted over
        # loadgen traffic must not count warm-up probes), exactly
        # like _mark_lost suppresses a dead collector's wait metrics
        runner = rep.runner(kind)
        coll = rep.collector(kind)
        saved = (runner.metrics, coll.metrics)
        runner.metrics = coll.metrics = None
        self._assign(rep, req)
        resps: list[Response] = []
        try:
            while rep.state == "warming" and rep.pending(kind):
                resps += self._drain_inproc(rep, kind)
        finally:
            runner.metrics, coll.metrics = saved
        with self._lock:
            self._canaries.discard(qid)
        canary = next((r for r in resps if r.qid == qid), None)
        if rep.state != "warming" or canary is None:
            _emit("canary", replica=rep.name, qid=qid,
                  query_kind=kind, ok=False, reason="died")
            return False
        if self.live is not None:
            from lux_tpu import livegraph
            bad = livegraph.check_live_answers(self.live, [canary],
                                               self.weighted)
        else:
            bad = serve_mod._check_answers(self.g, [canary])
        ok = bad == 0
        _emit("canary", replica=rep.name, qid=qid, query_kind=kind,
              ok=ok,
              **({} if ok else {"reason": "oracle_mismatch"}))
        return ok

    def _failover(self, req: Request, from_rep,
                  t_detect: float | None = None) -> None:
        with self._lock:
            # the replayed-query guard: a query whose retirement
            # raced the loss detection must not run twice — checked
            # AND counted under the lock (a lock-free check here is
            # the stamp-then-admit window, lockcheck toctou-gate)
            if req.qid in self._retired:
                self.dup_dropped += 1
                if self.metrics is not None:
                    self.metrics.counter("fleet_dup_dropped_total",
                                         kind=req.kind).inc()
                return
        k = self._attempts.get(req.qid, 0)
        self._attempts[req.qid] = k + 1
        if k >= self.retry.retries:
            self._shed(req, SHED_RETRIES, raise_=False)
            return
        # each query's jittered delay is a NOT-BEFORE offset from the
        # detection instant, so a batch of failovers stalls the
        # dispatcher for at most the LARGEST single delay (not the
        # sum) — the survivors' queries must not be billed a serial
        # backoff chain, while each query still gets its own
        # attempt-indexed decorrelated delay
        d = self.retry.delay_s(k)
        waited = 0.0 if t_detect is None \
            else time.monotonic() - t_detect
        if d > waited:
            self.retry.sleep(d - waited)
        to = self._pick(req.kind)
        if to is None:
            self._shed(req, SHED_NO_CAPACITY, raise_=False)
            return
        self.failovers += 1
        if self.metrics is not None:
            self.metrics.counter("fleet_failovers_total",
                                 kind=req.kind).inc()
        _emit("failover", qid=req.qid, query_kind=req.kind,
              from_replica=from_rep.name, to_replica=to.name,
              attempt=k + 1, backoff_s=round(d, 4))
        self._assign(to, req)

    # -- subprocess answer path ----------------------------------------

    def _poll_remote(self) -> list[Response]:
        out: list[Response] = []
        for rep in self._replicas:
            if not rep.remote:
                continue
            try:
                names = sorted(os.listdir(rep.outdir))
            except OSError:
                continue
            for f in names:
                if not f.endswith(".json"):
                    continue
                jpath = os.path.join(rep.outdir, f)
                npath = jpath[:-5] + ".npy"
                try:
                    with open(jpath) as fh:
                        meta = json.load(fh)
                    answer = np.load(npath)
                except (OSError, ValueError, json.JSONDecodeError):
                    continue            # torn pair: retry next poll
                for p in (jpath, npath):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                qid = int(meta["qid"])
                req = rep.inflight.pop(qid, None) \
                    or self._qreq.get(qid)
                with self._lock:
                    # a late answer from a replica we already failed
                    # over: the exactly-once guard drops it — gate
                    # and counter share one acquisition (toctou)
                    dup = qid in self._retired or req is None
                    if dup:
                        self.dup_dropped += 1
                        if self.metrics is not None:
                            self.metrics.counter(
                                "fleet_dup_dropped_total",
                                kind=meta.get("kind", "?")).inc()
                if dup:
                    continue
                out.append(self._accept_remote(rep, req, meta,
                                               answer))
        return [r for r in out if r is not None]

    def _accept_remote(self, rep, req: Request, meta: dict,
                       answer) -> Response | None:
        now = time.monotonic()
        latency = max(0.0, now - req.t_enqueue)
        service = float(meta.get("service_s") or 0.0)
        resp = Response(
            qid=req.qid, kind=req.kind, source=req.source,
            answer=np.asarray(answer),
            iters=int(meta.get("iters", 0)),
            segments=int(meta.get("segments", 0)),
            latency_s=latency,
            wait_s=max(0.0, latency - service),
            converged=bool(meta.get("converged", True)))
        slo = {}
        slo_ms = self.slo_ms.get(req.kind)
        if slo_ms is not None:
            ok = resp.latency_s * 1e3 <= slo_ms
            slo = {"slo_ms": slo_ms, "slo_ok": ok}
        if self.metrics is not None:
            m = self.metrics
            m.histogram("serve_latency_seconds",
                        kind=req.kind).observe(resp.latency_s)
            m.counter("serve_retired_total", kind=req.kind).inc()
            if slo:
                m.counter("serve_slo_good_total" if slo["slo_ok"]
                          else "serve_slo_violation_total",
                          kind=req.kind).inc()
        if not self._accept(resp):
            return None
        _emit("query_done", qid=resp.qid, query_kind=resp.kind,
              col=-1, iters=resp.iters, segments=resp.segments,
              latency_s=round(resp.latency_s, 6),
              wait_s=round(resp.wait_s, 6),
              converged=resp.converged, replica=rep.name, **slo)
        return resp

    def _check_remote_health(self) -> None:
        for rep in self._replicas:
            if not rep.remote or rep.state != "up":
                continue
            rc = rep.proc.poll() if rep.proc is not None else None
            age = self.board.age(rep.name)
            if rc is not None and rc != 0:
                self._mark_lost(rep, heartbeat_mod.WorkerLostError(
                    [rep.index], -1, self.replica_deadline_s))
            elif age is not None and age > self.replica_deadline_s:
                self._mark_lost(rep, heartbeat_mod.WorkerLostError(
                    [rep.index], -1, self.replica_deadline_s))

    # -- the serve loop ------------------------------------------------

    def _pending_any(self) -> bool:
        if any(len(q) for q in self._queues.values()):
            return True
        for rep in self._healthy():
            if rep.pending_total():
                return True
        return False

    def run(self) -> list[Response]:
        """Serve until every admitted query retired (or shed): routes
        queued requests to the healthiest replicas, drains in-process
        replicas through continuous-batching refill, polls subprocess
        answers, and fails over on any replica death observed on the
        way.  Returns this call's responses in retirement order."""
        if self.live is not None and self.g is not self.live.base:
            # generation adoption is ENFORCED (serve.Server.run's
            # guard, fleet-wide): replica engines built over a stale
            # base would serve old-base + empty delta — a wrong
            # answer the torn-epoch audit cannot see
            raise RuntimeError(
                "live graph compacted to a new generation — call "
                "refresh_live() before serving")
        if self.cache is not None and self.live is not None:
            # invalidation on epoch advance (serve.Server.run's
            # sweep, fleet-wide: the cache is SHARED across replicas)
            self.cache.sweep({k: self._admission_epoch(k)
                              for k in KINDS})
        out: list[Response] = []
        while True:
            progressed = False
            got = self._poll_remote()
            if got:
                out += got
                progressed = True
            self._check_remote_health()
            if self.heal:
                self._heal()
            for kind in list(self._queues):
                q = self._queues[kind]
                if len(q):
                    if not self._healthy():
                        if self.heal and any(
                                r.state == "lost" and not r.remote
                                for r in self._replicas):
                            # a resurrection is scheduled: HOLD the
                            # queue instead of mass-shedding — the
                            # respawn either succeeds (queries route
                            # again) or the flap verdict quarantines
                            # the name (loop falls through to the
                            # shed below once nothing is lost)
                            continue
                        for req in q.collect(len(q)):
                            self._shed(req, SHED_NO_CAPACITY,
                                       raise_=False)
                        progressed = True
                        continue
                    reqs = q.collect(len(q))
                    leftover = []
                    for req in reqs:
                        if req.qid in self._retired:
                            continue
                        rep = self._pick(kind)
                        if rep is None:
                            leftover.append(req)
                            continue
                        self._assign(rep, req)
                        progressed = True
                    for req in leftover:
                        q.put(req)      # full remotes: wait, not shed
                for rep in list(self._replicas):
                    if (rep.state == "up" and not rep.remote
                            and rep.pending(kind)):
                        out += self._drain_inproc(rep, kind)
                        progressed = True
            if not self._pending_any():
                if not (self.heal and any(
                        r.state == "lost" and not r.remote
                        for r in self._replicas)):
                    break
                # heal-armed run() also restores the POOL before
                # returning: every lost in-process replica either
                # resurrects (canary-gated) or quarantines — so the
                # caller's next submit sees the healed capacity and
                # mttr_s is final, not still counting
            if not progressed:
                time.sleep(REMOTE_POLL_S)
        self._health_gauges()
        now = time.monotonic()
        if out and now - self._last_snapshot >= self.snapshot_every_s:
            self._last_snapshot = now
            self.emit_metrics_snapshot()
        return out

    # -- serve.Server duck-type surface --------------------------------

    @property
    def _collectors(self) -> dict:
        """Per-kind pending views (queued + replica-resident +
        subprocess-in-flight) — the drain predicate
        scripts/loadgen.py polls between Server.run calls."""
        return {k: _PendingView(self, k) for k in self._queues}

    def set_metrics(self, registry) -> None:
        self.metrics = registry
        for rep in self._replicas:
            if rep.remote:
                continue
            for coll in rep._collectors.values():
                coll.metrics = registry
            for runner in rep._runners.values():
                runner.metrics = registry

    def emit_metrics_snapshot(self, **extra):
        if self.metrics is None:
            return None
        return self.metrics.emit_snapshot(**extra)

    def close(self) -> None:
        for rep in self._replicas:
            if rep.remote:
                rep.stop()
        if self.journal is not None:
            self.journal.close()

    # -- whole-fleet crash recovery (round 24) --------------------------

    @classmethod
    def recover(cls, g, journal_path: str, /, *, resets=None,
                live=None, **kw) -> "FleetServer":
        """Restart a crashed fleet from its durable admission
        journal: replay the journal (truncating a torn tail in
        place, exactly like MutationLog.replay), seed the
        exactly-once retirement set from the persisted retire
        records, and RE-DISPATCH every admitted-unretired query so
        the next run() answers it at its ORIGINAL admission epoch
        (live fleets: ``livegraph.graph_at`` through the runners'
        epoch plumbing — bitwise-equal for integer apps).

        Recovery ordering is load-bearing (ARCHITECTURE.md
        "Self-healing fleet"): the caller replays the mutation WAL
        FIRST (``LiveGraph.recover``) and passes the recovered
        handle as ``live`` with ``g = live.base`` — journal
        re-dispatch needs the generation adopted before any epoch
        reproducibility verdict.

        Re-dispatch is unconditional (the queries already passed
        admission, durably) except for two typed, journal-retired
        sheds: ``reset_unavailable`` — a pagerank reset query whose
        vector is not in ``resets`` (a qid-keyed mapping; the
        journal persists only an 8-byte blake2b digest, and a
        mismatching vector is the same shed: recovery must never
        silently answer a DIFFERENT query than the one admitted) —
        and ``epoch_folded`` — a live fleet whose recovered base
        already folded past the record's admission epoch, so a
        bitwise answer at that epoch is unreachable.  Deadlines
        restart from re-dispatch (the crash consumed wall-clock the
        query never got).

        Remaining constructor keywords pass through ``**kw`` —
        ``journal_path`` must NOT be among them (the journal is
        resumed, not re-created; a second recover() on the same path
        replays the same open set minus what retired since)."""
        if "journal_path" in kw:
            raise ValueError(
                "recover() resumes the journal at journal_path; do "
                "not also pass journal_path= (that would O_EXCL-"
                "create over the evidence)")
        opens, retired, torn, jrnl = journal_mod.AdmissionJournal \
            .replay(journal_path, nv=g.nv)
        flt = cls(g, live=live, **kw)
        flt.journal = jrnl
        with flt._lock:
            flt._retired.update(retired)
            seen = [rec.qid for rec in opens] + list(retired)
            if seen:
                flt._next_qid = max(seen) + 1
        flt.journal_replayed = len(opens)
        _emit("journal_replay", path=journal_path,
              replayed=len(opens), retired=len(retired),
              torn_bytes=torn)
        if flt.metrics is not None:
            flt.metrics.counter("fleet_journal_replayed_total").inc(
                len(opens))
        resets = dict(resets or {})
        for rec in opens:
            reset = None
            if rec.digest is not None:
                reset = resets.get(rec.qid)
                if reset is not None:
                    reset = np.asarray(reset, np.float32)
                ok = (reset is not None
                      and journal_mod.reset_digest(reset)
                      == rec.digest)
                if not ok:
                    req = Request(qid=rec.qid, kind=rec.kind,
                                  source=None, reset=reset,
                                  t_enqueue=time.monotonic(),
                                  tenant=rec.tenant,
                                  priority=rec.priority,
                                  deadline_s=rec.deadline_s,
                                  epoch=None)
                    flt._journaled.add(rec.qid)
                    flt._shed(req, SHED_RESET_UNAVAILABLE,
                              raise_=False)
                    continue
            req = Request(qid=rec.qid, kind=rec.kind,
                          source=rec.source, reset=reset,
                          t_enqueue=time.monotonic(),
                          tenant=rec.tenant, priority=rec.priority,
                          deadline_s=rec.deadline_s, epoch=rec.epoch)
            if live is not None:
                if not serve_mod._epoch_reproducible(live, req):
                    flt._journaled.add(rec.qid)
                    flt._shed(req, SHED_EPOCH_FOLDED, raise_=False)
                    continue
                # take a fresh admission-ledger entry for the
                # re-dispatch (released at the exactly-once
                # retirement like any admit); the query still
                # ANSWERS at its original journaled epoch — the
                # entry only keeps the generation serveable
                live.admit(serve_mod._engine_family(rec.kind))
            with flt._lock:
                flt._journaled.add(rec.qid)
                flt._qreq[rec.qid] = req
                flt._tenant_load[req.tenant] = \
                    flt._tenant_load.get(req.tenant, 0) + 1
                flt._queue(rec.kind).put(req)
            _emit("query_enqueue", qid=rec.qid, query_kind=rec.kind,
                  source=req.source, tenant=req.tenant,
                  priority=req.priority,
                  queued=len(flt._queue(rec.kind)), recovered=True)
        return flt


class _PendingView:
    def __init__(self, fleet: FleetServer, kind: str):
        self.fleet = fleet
        self.kind = kind

    def __len__(self) -> int:
        n = len(self.fleet._queues[self.kind])
        for rep in self.fleet._healthy():
            n += rep.pending(self.kind)
        return n


# ---------------------------------------------------------------------
# subprocess replica worker

def _worker_env(ndev: int = 2) -> dict:
    """Worker env: CPU backend pinned BEFORE interpreter start and
    the axon site dropped (CLAUDE.md: sitecustomize imports jax at
    startup, so in-process env changes are too late).  The virtual
    device count scales with the worker's num_parts and other
    caller-set XLA flags are PRESERVED — overwriting them would cap
    a 4-part worker at 2 devices and misdiagnose the crash as a
    spawn-capability failure."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    pp = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
          if p and ".axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join([repo] + pp)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith(
                 "--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count="
                 f"{max(2, int(ndev))}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def _graph_from_spec(spec: dict):
    """Rebuild the (deterministic, seeded) graph a subprocess replica
    serves — it must match the parent's bit-for-bit or the answers
    cannot be oracle-equal."""
    from lux_tpu.graph import Graph

    kind = spec.get("kind", "uniform")
    if kind == "uniform":
        from lux_tpu.convert import uniform_random_edges
        src, dst = uniform_random_edges(int(spec["nv"]),
                                        int(spec["ne"]),
                                        seed=int(spec.get("seed", 0)))
        return Graph.from_edges(src, dst, int(spec["nv"]))
    if kind == "rmat":
        from lux_tpu.convert import rmat_graph
        return rmat_graph(scale=int(spec["scale"]),
                          edge_factor=int(spec["ef"]),
                          seed=int(spec.get("seed", 0)))
    raise ValueError(f"unknown graph spec kind {spec!r}")


def _worker_main(spec_path: str) -> int:
    from lux_tpu import serve

    with open(spec_path) as f:
        spec = json.load(f)
    name = spec["name"]
    board = heartbeat_mod.ReplicaBoard(spec["board"])
    plan = None
    if spec.get("kill_boundary") is not None:
        plan = faults_mod.ReplicaKillPlan(
            {name: int(spec["kill_boundary"])}, hard_kill=True)
    state = {"boundary": 0}

    def on_boundary(runner):
        state["boundary"] += 1
        board.beat(name, status="up", boundary=state["boundary"])
        if plan is not None:
            plan.fire(name)

    g = _graph_from_spec(spec["graph"])
    srv = serve.Server(g, batch=int(spec["batch"]),
                       num_parts=int(spec["num_parts"]),
                       seg_iters=int(spec["seg_iters"]),
                       tol=float(spec.get("tol", 1e-8)),
                       weighted=bool(spec.get("weighted", False)),
                       metrics=False, on_boundary=on_boundary,
                       replica=name)
    inbox = os.path.join(spec["dir"], f"inbox_{name}")
    outdir = os.path.join(spec["dir"], f"out_{name}")
    stop = os.path.join(spec["dir"], "stop")
    qmap: dict[int, int] = {}
    board.beat(name, status="up", boundary=0)
    while not os.path.exists(stop):
        board.beat(name, status="up", boundary=state["boundary"])
        for f in sorted(os.listdir(inbox)):
            if not f.endswith(".json"):
                continue
            p = os.path.join(inbox, f)
            try:
                with open(p) as fh:
                    doc = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue
            reset = None
            if doc.get("reset"):
                rp = p[:-5] + ".reset.npy"
                try:
                    reset = np.load(rp)
                except (OSError, ValueError):
                    continue    # torn pair: json kept, retry next loop
            os.remove(p)
            if doc.get("reset"):
                try:
                    os.remove(rp)
                except OSError:
                    pass
            wq = srv.submit(doc["kind"], source=doc.get("source"),
                            reset=reset)
            qmap[wq] = int(doc["qid"])
        for r in srv.run():
            fq = qmap.pop(r.qid)
            base = os.path.join(outdir, f"q{fq:08d}")
            fd, tmp = tempfile.mkstemp(dir=spec["dir"],
                                       suffix=".npy.tmp")
            with os.fdopen(fd, "wb") as fh:
                # lockcheck: allow(durable-before-visible) same-host
                # answer spool, not a durability record: a lost
                # answer re-runs from the journal; fsync per answer
                # would serialize the drain on disk latency
                np.save(fh, r.answer)
            os.replace(tmp, base + ".npy")
            meta = {"qid": fq, "kind": r.kind, "source": r.source,
                    "iters": r.iters, "segments": r.segments,
                    "converged": r.converged,
                    "service_s": round(r.latency_s, 6)}
            fd, tmp = tempfile.mkstemp(dir=spec["dir"],
                                       suffix=".json.tmp")
            with os.fdopen(fd, "w") as fh:
                json.dump(meta, fh)
            # json LAST: its presence marks a complete answer pair
            os.replace(tmp, base + ".json")
        time.sleep(0.02)
    return 0


# ---------------------------------------------------------------------
# smoke: python -m lux_tpu.fleet

def main(argv=None) -> int:
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "-worker":
        return _worker_main(argv[1])

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.fleet",
        description="serving-fleet chaos smoke: an oversubscribed "
                    "mixed-kind load across N replicas with one "
                    "replica killed mid-drain; every admitted answer "
                    "is oracle-checked, shed queries carry typed "
                    "rejections, and no qid retires twice")
    ap.add_argument("-scale", type=int, default=8)
    ap.add_argument("-ef", type=int, default=8)
    ap.add_argument("-batch", type=int, default=2)
    ap.add_argument("-replicas", type=int, default=2)
    ap.add_argument("-np", type=int, default=2, dest="num_parts")
    ap.add_argument("-queries", type=int, default=0,
                    help="total mixed queries (default 4B)")
    ap.add_argument("-kill-boundary", type=int, default=1,
                    dest="kill_boundary",
                    help="segment boundary of the last replica at "
                         "which the kill plan fires (-1 disables)")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-events", default=None, metavar="FILE")
    args = ap.parse_args(argv)

    from lux_tpu import telemetry
    from lux_tpu.serve import _check_answers, _smoke_graph

    g = _smoke_graph(args.scale, args.ef, args.seed)
    rng = np.random.default_rng(args.seed + 1)
    n = args.queries or 4 * args.batch
    kinds = list(KINDS)
    ev = telemetry.EventLog(args.events) if args.events else \
        telemetry.EventLog()
    with telemetry.use(events=ev):
        ev.emit("run_start", schema=telemetry.SCHEMA, app="fleet",
                file=f"<rmat{args.scale}>", np=args.num_parts)
        flt = FleetServer(g, replicas=args.replicas,
                          batch=args.batch,
                          num_parts=args.num_parts,
                          retry=resilience.RetryPolicy(
                              retries=3, backoff_s=0.01,
                              max_backoff_s=0.05, jitter_seed=0))
        if args.kill_boundary >= 0 and args.replicas > 1:
            # arm the replica routing WILL pick (routing_target):
            # a fixed index is a coin flip on beat timing, and the
            # losing side is a kill that never fires (round 22)
            flt.set_fault(faults_mod.ReplicaKillPlan(
                {flt.routing_target(kinds[0]): args.kill_boundary}))
        for i in range(n):
            flt.submit(kinds[i % len(kinds)],
                       source=int(rng.integers(0, g.nv)))
        t0 = time.perf_counter()
        responses = flt.run()
        ev.emit("run_done",
                seconds=round(time.perf_counter() - t0, 6),
                iters=sum(r.iters for r in responses))
    ev.close()
    qids = [r.qid for r in responses]
    shed_qids = {e.qid for e in flt.shed_records}
    print(f"# served {len(responses)}/{n} queries across "
          f"{args.replicas} replica(s); failovers={flt.failovers} "
          f"shed={len(flt.shed_records)} dup_dropped="
          f"{flt.dup_dropped}")
    if len(set(qids)) != len(qids):
        print("error: duplicate retirement")
        return 1
    if set(qids) | shed_qids != set(range(n)) or \
            set(qids) & shed_qids:
        print("error: served + shed do not partition the admitted "
              "queries")
        return 1
    if args.kill_boundary >= 0 and args.replicas > 1 \
            and not flt.failovers and not flt.fault.fired:
        print("error: the kill plan never fired")
        return 1
    bad = _check_answers(g, responses)
    if bad:
        print(f"error: {bad} answer(s) mismatched their oracle")
        return 1
    print("# all served answers match their NumPy oracles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
