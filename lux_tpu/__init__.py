"""lux_tpu — a TPU-native distributed graph-processing framework.

A from-scratch reimplementation of the capabilities of Lux (Jia et al.,
"A Distributed Multi-GPU System for Fast Graph Processing", PVLDB 11(3),
2017; reference tree at /root/reference) designed for TPU hardware:

- compute path: JAX/XLA (gathers + segmented reductions on the VPU/MXU),
  with optional Pallas kernels for the hot edge loops;
- distribution: ``jax.sharding.Mesh`` + ``shard_map`` over a ``parts``
  axis, with the per-iteration vertex-state exchange expressed as
  ``lax.all_gather`` over ICI (the reference's Legion/GASNet region
  all-gather, see reference core/pull_model.inl:454-469);
- convergence-driven apps compile the *entire* run into one XLA program
  (``lax.while_loop`` + ``psum`` halt detection), replacing the
  reference's SLIDING_WINDOW=4 host-pipelining trick
  (reference sssp/sssp.cc:111-129) with zero host round-trips;
- host-side native tooling (graph converter, partition-slice file
  loader) implemented in C++ (lux_tpu/native/).

Layout:
  format.py     .lux binary CSC file format (read/write/inspect)
  convert.py    edge-list <-> .lux conversion + synthetic generators (RMAT)
  partition.py  edge-balanced contiguous vertex partitioner
  graph.py      host Graph + padded device-resident ShardedGraph layout
  ops/          segmented reductions (XLA + Pallas fast paths)
  engine/       pull (dense gather-apply) and push (frontier) engines
  parallel/     mesh construction and sharding helpers
  apps/         PageRank, SSSP/BFS, ConnectedComponents, CollabFilter
  check.py      fixed-point correctness audits (the reference's -check)
  audit.py      compile-time program auditor (jaxpr invariant checks;
                repo-wide: python -m lux_tpu.audit)
  observe.py    performance observatory: session-calibration probe,
                phase-cost attribution vs scalemodel, persistent perf
                ledger + carried-debt registry
                (report: python -m lux_tpu.observe)
  livegraph.py  live graphs: CRC-chained mutation WAL, snapshot-
                isolated epochs, incremental revalidation, chaos-
                drilled compaction (round 20, ROADMAP item 4)
  native/       C++ converter CLI and partition-slice loader
"""

__version__ = "0.1.0"

from lux_tpu import _compat  # noqa: F401  (jax version shims)
from lux_tpu.format import LuxFileHeader, read_lux, write_lux, peek_lux
from lux_tpu.graph import Graph, ShardedGraph
from lux_tpu.partition import edge_balanced_bounds

# round-9 guarded-execution typed errors, re-exported for callers
# that catch rather than build (see ARCHITECTURE.md "Data integrity
# & guarded execution")
from lux_tpu.checkpoint import CorruptCheckpointError
from lux_tpu.format import GraphFormatError
from lux_tpu.health import HealthError

# round-10 static-guarantee typed error (ARCHITECTURE.md "Static
# guarantees"); the check-specific subclasses live in lux_tpu.audit.
# Lazy (module __getattr__): an eager import here would pre-load
# lux_tpu.audit into sys.modules and make ``python -m lux_tpu.audit``
# execute the module twice (runpy RuntimeWarning + duplicate class
# objects that break isinstance across the copies).


def __getattr__(name):
    if name == "AuditError":
        from lux_tpu.audit import AuditError
        return AuditError
    # round-20 live-graph typed errors: lazy for the same
    # python -m double-import reason as AuditError
    if name in ("LiveGraphError", "MutationLogError",
                "DeltaFullError"):
        from lux_tpu import livegraph
        return getattr(livegraph, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
