"""Checkpoint / resume for long iterative runs.

The reference has no checkpointing (SURVEY.md §5: its USE_HDF flag is
dead).  For a framework running thousand-iteration PageRank or long
convergence loops on preemptible TPU pods, save/resume is table
stakes, so it is first-class here:

- ``save(path, state, meta)`` / ``load(path)``: one atomic .npz with a
  JSON metadata blob.  State pytrees may hold device arrays (fetched
  to host, which also fences outstanding computation) including
  mesh-sharded arrays (device_get assembles the global view).
- Pull engines: checkpoint between fused-run segments
  (``run_checkpointed``).
- Push engines: converge runs in segments of ``max_iters`` so a
  preempted convergence resumes from the last completed segment.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np


def _to_host(tree):
    import jax

    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def save(path: str, state, meta: dict | None = None) -> None:
    """Atomically write a checkpoint: ``state`` is a pytree of arrays
    (list/tuple/dict nesting), ``meta`` a JSON-serializable dict."""
    import jax

    leaves, _treedef = jax.tree.flatten(_to_host(state))
    payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, _meta=json.dumps(meta or {}),
                     _n=len(leaves), **payload)
            # os.replace is atomic against process kill, but only an
            # fsync before the rename makes the checkpoint durable
            # against host crash / power loss.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str):
    """Returns (leaves list, meta dict).  Leaves are in the order they
    were flattened at save time; re-assemble with your own structure
    (engines' states are flat tuples, so this is direct)."""
    with np.load(path, allow_pickle=False) as z:
        n = int(z["_n"])
        meta = json.loads(str(z["_meta"]))
        leaves = [z[f"leaf_{i}"] for i in range(n)]
    return leaves, meta


def run_checkpointed(eng, state, num_iters: int, path: str,
                     segment: int = 50, start_iter: int = 0):
    """Run a pull engine ``num_iters`` iterations, checkpointing every
    ``segment`` iterations.  Resume by loading the checkpoint and
    passing its iteration counter as ``start_iter``."""
    from lux_tpu.segmented import run_segments

    return run_segments(
        eng, state, num_iters, segment, start_iter=start_iter,
        on_segment=lambda s, done:
            save(path, (s,), {"iter": done, "kind": "pull"}))


def converge_checkpointed(eng, path: str, segment: int = 50,
                          resume: bool = False,
                          max_iters: int | None = None):
    """Run a push engine to convergence in ``segment``-iteration
    slices, checkpointing after each slice.  Returns
    (labels, active, total_iters)."""
    from lux_tpu.segmented import converge_segments

    if resume and os.path.exists(path):
        leaves, meta = load(path)
        if meta.get("kind") != "push" or len(leaves) != 2:
            raise ValueError(
                f"{path} is not a push-engine checkpoint "
                f"(kind={meta.get('kind')!r}, {len(leaves)} arrays)")
        label, active = eng.place(*leaves)
        done = int(meta["iter"])
    else:
        label, active = eng.init_state()
        done = 0
    return converge_segments(
        eng, label, active, segment, max_iters, start_iter=done,
        on_segment=lambda lbl, act, total, cnt:
            save(path, (lbl, act), {"iter": total, "kind": "push"}))
