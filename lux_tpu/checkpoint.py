"""Checkpoint / resume for long iterative runs.

The reference has no checkpointing (SURVEY.md §5: its USE_HDF flag is
dead).  For a framework running thousand-iteration PageRank or long
convergence loops on preemptible TPU pods, save/resume is table
stakes, so it is first-class here:

- ``save(path, state, meta)`` / ``load(path)``: one atomic .npz with a
  JSON metadata blob.  State pytrees may hold device arrays (fetched
  to host, which also fences outstanding computation) including
  mesh-sharded arrays (device_get assembles the global view).
- Pull engines: checkpoint between fused-run segments
  (``run_checkpointed``).
- Push engines: converge runs in segments of ``max_iters`` so a
  preempted convergence resumes from the last completed segment.

Integrity + generations (round 9): ``save`` records a per-leaf CRC32
alongside the payload and rotates the previous file to
``<path>.prev`` before the atomic rename, keeping TWO generations on
disk.  ``load`` re-checksums every leaf, so a bit-flipped — or torn
but still zip-well-formed — payload raises a typed
:class:`CorruptCheckpointError` instead of resuming silently (the
zip container's own CRC only covers its members as written; a
payload rewritten wrong with a consistent member CRC passes it).
``load_any`` is the resume entry point: a corrupt newest generation
falls back one generation (emitting a ``checkpoint_fallback``
telemetry event), and the resilience supervisor then replays the
lost segment instead of dying — or resuming garbage.

Placement metadata + re-placement (round 11): every save records the
engine's mesh shape, device count and config fingerprint
(``placement``: ndev / num_parts / vpad / exchange).  Resume
VALIDATES num_parts/vpad/exchange — a mismatch is a wrong-config
checkpoint and errors — while an ndev difference is the ELASTIC
RE-PLACEMENT contract: the saved state is the global host view, so
``eng.place`` re-shards it onto the resuming engine's (smaller or
larger) mesh, recorded as a ``replace`` telemetry event.  Multi-
process runs assemble the global view collectively
(multihost.fetch_global) and write from process 0 only (a shared
checkpoint dir), so the checkpoint a degraded relaunch resumes from
is always whole.
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib

import numpy as np


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file failed integrity verification (CRC mismatch,
    truncated/garbage container, missing members).  Carries ``path``;
    resilience.classify treats it as RETRYABLE — the retry's resume
    goes through ``load_any``, which falls back one generation."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"{path}: corrupt checkpoint — {detail}")
        self.path = path
        self.detail = detail


def prev_path(path: str) -> str:
    """The previous-generation file ``save`` rotates into."""
    return path + ".prev"


def any_generation(path: str) -> bool:
    """True if either generation exists on disk."""
    return os.path.exists(path) or os.path.exists(prev_path(path))


def corrupt_path(path: str) -> str:
    """Where ``load_any`` quarantines a corrupt newest generation."""
    return path + ".corrupt"


def remove(path: str) -> None:
    """Remove BOTH generations (fresh-start paths must clear the
    fallback too, or a stale .prev could resurrect after one crash)
    plus any quarantined corrupt file."""
    for p in (path, prev_path(path), corrupt_path(path)):
        if os.path.exists(p):
            os.unlink(p)


def _to_host(tree):
    """Fetch a (possibly mesh-sharded) pytree to host numpy as the
    GLOBAL view.  Multi-process arrays are assembled over the process
    group (multihost.fetch_global — a collective: every process must
    call save() together, which the lockstep segmented drivers do);
    single-process arrays take the plain device_get path."""
    from lux_tpu.parallel.multihost import fetch_global

    import jax

    return jax.tree.map(lambda x: np.asarray(fetch_global(x)), tree)


def _leaf_crc(leaf: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(leaf).tobytes()) & 0xFFFFFFFF


def chained_crc32(data: bytes, prev: int = 0) -> int:
    """CRC32 of ``data`` seeded with the previous link's CRC — the
    per-record integrity discipline the checkpoint's per-leaf CRCs
    use, extended into a CHAIN for append-only logs: record i's CRC
    covers record i's bytes AND (through the seed) every byte before
    it, so a torn or reordered tail cannot re-validate.  Shared with
    the live-graph mutation log (lux_tpu/livegraph.MutationLog)."""
    return zlib.crc32(data, prev & 0xFFFFFFFF) & 0xFFFFFFFF


def save(path: str, state, meta: dict | None = None,
         rotate: bool = True) -> int:
    """Atomically write a checkpoint: ``state`` is a pytree of arrays
    (list/tuple/dict nesting), ``meta`` a JSON-serializable dict.
    Returns the staged byte total (the host-assembled global view —
    the transient consumer the round-22 memory ledger prices as
    ``checkpoint_staging``; 0 on non-writer processes).

    A per-leaf CRC32 rides alongside the payload (``load`` verifies
    it), and with ``rotate`` (the default) an existing file at
    ``path`` becomes the previous generation ``<path>.prev`` before
    the atomic rename — ``load_any``'s corruption fallback."""
    import jax

    leaves, _treedef = jax.tree.flatten(_to_host(state))
    if jax.process_count() > 1 and jax.process_index() != 0:
        # the global view above was assembled COLLECTIVELY (all
        # processes participate); one writer per shared checkpoint
        # dir — every process resumes from the same file
        return 0
    staged = sum(int(leaf.nbytes) for leaf in leaves)
    payload = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    crcs = [_leaf_crc(leaf) for leaf in leaves]
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".ckpt.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, _meta=json.dumps(meta or {}),
                     _n=len(leaves), _crc=json.dumps(crcs), **payload)
            # os.replace is atomic against process kill, but only an
            # fsync before the rename makes the checkpoint durable
            # against host crash / power loss.
            f.flush()
            os.fsync(f.fileno())
        if rotate and os.path.exists(path):
            # a crash between the two renames leaves only .prev —
            # exactly the state load_any's fallback recovers from
            os.replace(path, prev_path(path))
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
        except OSError:
            return staged
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return staged


def load(path: str, verify: bool = True):
    """Returns (leaves list, meta dict).  Leaves are in the order they
    were flattened at save time; re-assemble with your own structure
    (engines' states are flat tuples, so this is direct).

    Unreadable containers (truncated file, garbage bytes, missing
    members) and — with ``verify`` — per-leaf CRC mismatches raise
    :class:`CorruptCheckpointError`; a missing FILE keeps raising
    FileNotFoundError (absent and corrupt are different conditions:
    only the latter has a generation to fall back to)."""
    import zipfile

    try:
        with np.load(path, allow_pickle=False) as z:
            n = int(z["_n"])
            meta = json.loads(str(z["_meta"]))
            leaves = [z[f"leaf_{i}"] for i in range(n)]
            crcs = (json.loads(str(z["_crc"]))
                    if "_crc" in z.files else None)
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, KeyError, ValueError, EOFError,
            OSError, json.JSONDecodeError) as e:
        raise CorruptCheckpointError(
            path, f"unreadable ({type(e).__name__}: {e})") from e
    if verify and crcs is not None:
        if len(crcs) != len(leaves):
            raise CorruptCheckpointError(
                path, f"{len(leaves)} leaves but {len(crcs)} CRCs")
        for i, (want, leaf) in enumerate(zip(crcs, leaves)):
            got = _leaf_crc(leaf)
            if got != want:
                raise CorruptCheckpointError(
                    path, f"leaf {i} CRC32 {got:#010x} != recorded "
                          f"{want:#010x} (bit flip or torn write)")
    return leaves, meta


def load_any(path: str):
    """Newest-first load with generation fallback: returns (leaves,
    meta, used_path).  A corrupt newest generation emits a
    ``checkpoint_fallback`` telemetry event and falls back to
    ``<path>.prev``; raises CorruptCheckpointError when the only (or
    both) generations are corrupt, FileNotFoundError when neither
    exists."""
    from lux_tpu import telemetry

    prev = prev_path(path)
    if os.path.exists(path):
        try:
            leaves, meta = load(path)
            return leaves, meta, path
        except CorruptCheckpointError as e:
            if not os.path.exists(prev):
                raise
            telemetry.current().emit(
                "checkpoint_fallback", path=path, fallback=prev,
                error=str(e)[:200])
            leaves, meta = load(prev)
            # QUARANTINE the corrupt newest (kept on disk for
            # forensics): if it stayed at ``path``, the next save's
            # rotation would promote it to .prev — destroying the
            # only good generation while the new write is still in
            # flight.  It also makes repeat load_any calls (the
            # supervisor's resume bookkeeping + the actual resume)
            # read and report the corruption only once.
            try:
                os.replace(path, corrupt_path(path))
            except OSError:
                pass
            return leaves, meta, prev
    leaves, meta = load(prev)
    return leaves, meta, prev


def _check_leaves(path, expect, leaves):
    """Shape/dtype-match loaded ``leaves`` against ``expect`` (live,
    donated, or eval_shape-abstract arrays — all carry shape/dtype).
    A count-only check would let a checkpoint from a DIFFERENT
    graph/scale resume silently: XLA's clamping gathers would then
    produce wrong results instead of an error."""
    for i, (d, l) in enumerate(zip(expect, leaves)):
        if (tuple(d.shape) != tuple(l.shape)
                or np.dtype(d.dtype) != np.dtype(l.dtype)):
            raise ValueError(
                f"{path} leaf {i} is {l.dtype}{tuple(l.shape)}, "
                f"engine expects {np.dtype(d.dtype)}"
                f"{tuple(d.shape)} — checkpoint from a different "
                f"graph/scale?")


def _placement_of(eng) -> dict:
    """{"placement": {...}} metadata fragment for a save — the mesh
    shape, device count and engine config fingerprint (round 11:
    checkpoint metadata records where and how the state was running,
    so a resume can tell a legitimate re-placement from a wrong-config
    checkpoint).  Empty for engines without the surface."""
    meta = getattr(eng, "placement_meta", None)
    if meta is None:
        return {}
    return {"placement": meta()}


def _check_placement(used: str, meta: dict, eng, kind: str) -> None:
    """Validate a checkpoint's recorded placement against the resuming
    engine.  num_parts / vpad / exchange must MATCH (parts and the
    padded layout are fixed across any recovery; a different exchange
    mode reduces floats in a different order, so resuming across one
    silently breaks bitwise reproducibility).  A DEVICE-COUNT
    difference is not an error — it is the re-placement contract:
    checkpoints hold the global ``[P, vpad, ...]`` host view, which
    ``eng.place`` re-shards onto any mesh whose size divides
    num_parts — and is ROUTED, not ignored: a ``replace`` telemetry
    event records the old -> new mesh (lux_tpu/resilience.py's
    elastic path and the degraded relaunch both resume through
    here)."""
    from lux_tpu import telemetry

    pl = meta.get("placement")
    want = getattr(eng, "placement_meta", None)
    if not isinstance(pl, dict) or want is None:
        return                      # legacy checkpoint / bare engine
    want = want()
    for key in ("num_parts", "vpad", "exchange"):
        if key in pl and pl[key] != want[key]:
            raise ValueError(
                f"{used} was written with {key}={pl[key]!r}, this "
                f"engine has {key}={want[key]!r} — re-placement keeps "
                f"the partitioning and exchange FIXED and changes "
                f"only the device mapping (rebuild the engine with "
                f"the checkpoint's config, or start fresh)")
    old_ndev = pl.get("ndev")
    if isinstance(old_ndev, int) and old_ndev != want["ndev"]:
        telemetry.current().emit(
            "replace", engine=kind, from_ndev=old_ndev,
            to_ndev=want["ndev"], iter=int(meta.get("iter", 0)),
            path=used)


def _timed_save(path, state, meta):
    """save() wrapped in a profiler annotation + telemetry event (the
    full-state fetch a checkpoint costs is worth seeing by name in
    traces and event logs)."""
    import time

    from lux_tpu import memwatch, telemetry
    from lux_tpu.profiling import annotation

    t0 = time.perf_counter()
    with annotation("lux_checkpoint_save"):
        staged = save(path, state, meta)
    # the staged global view is a real transient memory consumer —
    # the round-22 unified byte ledger prices it at its last
    # observed size (memwatch.consumer_terms)
    memwatch.note_staging(staged)
    telemetry.current().emit(
        "checkpoint_save", iter=int(meta.get("iter", 0)),
        engine=meta.get("kind"), path=path,
        staged_bytes=int(staged),
        seconds=round(time.perf_counter() - t0, 6))


def run_checkpointed(eng, state, num_iters: int, path: str,
                     segment=50, start_iter: int = 0,
                     resume: bool = False, on_segment=None):
    """Run a pull engine ``num_iters`` iterations, checkpointing every
    segment (``segment``: int size or segmented.DurationBudget).

    resume=True loads the checkpoint at ``path`` (if present), places
    its state on the engine's devices (eng.place) and continues from
    its iteration counter — the passed ``state`` supplies the pytree
    structure.  A corrupt newest generation falls back to
    ``<path>.prev`` (load_any) and the segments past its iteration
    counter are simply re-run — replay, not loss.  ``on_segment(state,
    done)`` runs BEFORE each save and may raise (the save is skipped,
    so the checkpoint stays at the last good segment) or return a
    replacement state (which is what gets checkpointed — the
    fault-injection harness relies on the guard raising before a
    corrupted state can reach the save)."""
    import jax

    from lux_tpu.segmented import run_segments

    from lux_tpu import telemetry

    if resume and any_generation(path):
        leaves, meta, used = load_any(path)
        treedef = jax.tree.structure(state)
        if meta.get("kind") != "pull" or treedef.num_leaves != len(leaves):
            raise ValueError(
                f"{used} is not a matching pull-engine checkpoint "
                f"(kind={meta.get('kind')!r}, {len(leaves)} arrays)")
        _check_leaves(used, jax.tree.leaves(state), leaves)
        _check_placement(used, meta, eng, "pull")
        state = eng.place(jax.tree.unflatten(treedef, leaves))
        start_iter = int(meta["iter"])
        telemetry.current().emit("checkpoint_resume", engine="pull",
                                 iter=start_iter, path=used)

    def seg_hook(s, done):
        out = None
        if on_segment is not None:
            res = on_segment(s, done)
            if res is not None:
                s = out = res
        _timed_save(path, (s,), {"iter": done, "kind": "pull",
                                 **_placement_of(eng)})
        return out

    return run_segments(eng, state, num_iters, segment,
                        start_iter=start_iter, on_segment=seg_hook)


def converge_checkpointed(eng, path: str, segment=50,
                          resume: bool = False,
                          max_iters: int | None = None,
                          on_segment=None):
    """Run a push engine to convergence in segment slices
    (``segment``: int size or segmented.DurationBudget),
    checkpointing after each slice.  ``on_segment(label, active,
    total, cnt)`` runs BEFORE each save, with the same raise/replace
    contract as run_checkpointed.  Returns
    (labels, active, total_iters)."""
    from lux_tpu import telemetry
    from lux_tpu.segmented import converge_segments

    if resume and any_generation(path):
        leaves, meta, used = load_any(path)
        if meta.get("kind") != "push" or len(leaves) != 2:
            raise ValueError(
                f"{used} is not a push-engine checkpoint "
                f"(kind={meta.get('kind')!r}, {len(leaves)} arrays)")
        try:                            # abstract: no device work
            import jax
            expect = jax.tree.leaves(jax.eval_shape(eng.init_state))
        except Exception:               # noqa: BLE001 — untraceable
            expect = None
        if expect is not None and len(expect) == len(leaves):
            _check_leaves(used, expect, leaves)
        _check_placement(used, meta, eng, "push")
        label, active = eng.place(*leaves)
        done = int(meta["iter"])
        telemetry.current().emit("checkpoint_resume", engine="push",
                                 iter=done, path=used)
    else:
        label, active = eng.init_state()
        done = 0

    def seg_hook(lbl, act, total, cnt):
        out = None
        if on_segment is not None:
            res = on_segment(lbl, act, total, cnt)
            if res is not None:
                lbl, act = res
                out = res
        _timed_save(path, (lbl, act), {"iter": total, "kind": "push",
                                       **_placement_of(eng)})
        return out

    return converge_segments(
        eng, label, active, segment, max_iters, start_iter=done,
        on_segment=seg_hook)
