"""Timing utilities with reliable completion fences.

The reference drains its deferred-execution pipeline with an execution
fence + TimingLauncher before reading wall clocks (reference
sssp.cc:132-135).  The TPU analogue: on remote-tunnel platforms
``block_until_ready`` can return before the device finishes, so the
only trustworthy fence is a host fetch.
"""

from __future__ import annotations

import time

import numpy as np


def fetch(x) -> np.ndarray:
    """Force completion of everything ``x`` depends on; returns host
    value."""
    import jax
    return np.asarray(jax.device_get(x))


def _cksum(*leaves):
    """Tiny completion-fence checksum (first 8 elements per leaf).

    float32 carries a 24-bit mantissa: casting INTEGER leaves wider
    than 24 bits (e.g. the packed uint32 pair rows, src<<7|rel)
    through it collapses values differing only above bit 24 into the
    same checksum.  Wide integer leaves therefore sum exactly in
    int32 (wraparound keeps determinism) and ride two separate
    sub-24-bit float channels, each exactly representable — the
    result is a [3] vector, one float channel + the int sum's
    low-12/high-20 bit channels."""
    import jax.numpy as jnp
    f = jnp.float32(0)
    i = jnp.int32(0)
    for leaf in leaves:
        x = leaf.reshape(-1)[:8]
        if jnp.issubdtype(x.dtype, jnp.integer) and x.dtype.itemsize > 3:
            i = i + jnp.sum(x.astype(jnp.int32))
        else:
            f = f + jnp.sum(x.astype(jnp.float32))
    return jnp.stack([f, (i & 0xFFF).astype(jnp.float32),
                      ((i >> 12) & 0xFFFFF).astype(jnp.float32)])


_cksum_jit = None


def fence(x) -> None:
    """Tunnel-safe completion fence that ships O(1) bytes: fetches a
    tiny checksum DEPENDENT on ``x`` instead of ``x`` itself.  A full
    ``fetch`` of a multi-GB state bills its device->host transfer
    (~60-300 MB/s through the tunnel) to whatever is being timed —
    measured as seconds/iteration of phantom cost at RMAT25.

    One module-level jitted checksum: repeat calls with the same leaf
    shapes hit the jit cache, so no (remote) compile lands inside a
    timed window after the warmup call."""
    import jax

    global _cksum_jit
    if _cksum_jit is None:
        _cksum_jit = jax.jit(_cksum)
    fetch(_cksum_jit(*jax.tree.leaves(x)))


def loop_bench(step, carry0, k: int, repeats: int = 3,
               clock=time.perf_counter):
    """The trusted microbenchmark recipe (PERF_NOTES rounds 2-3) as a
    library call: ``step(carry) -> (scalar, carry)`` runs ``k`` times
    inside ONE jitted ``fori_loop`` with a loop-DEPENDENT carry and a
    scalar output, so XLA can neither hoist the work out of the loop
    nor dead-code it, and the scalar fetch is the completion fence —
    no multi-MB transfer is ever billed to the timed window.

    Big operands ride the carry (jit ARGUMENTS, never closed-over
    constants — the HTTP-413 wall); leave inputs you don't mutate in
    the carry untouched.  One compile happens on the warmup call;
    ``repeats`` timed calls follow on the warm cache.

    ``clock`` is injectable for deterministic tests
    (tests/test_observe.py).  Returns (seconds_per_step list — one
    entry per repeat — and the warmup's scalar output).
    """
    import jax
    import jax.numpy as jnp

    def run(c0):
        def body(_, c):
            acc, cur = c
            sv, cur = step(cur)
            return (acc + sv, cur)
        return jax.lax.fori_loop(0, k, body,
                                 (jnp.float32(0), c0))[0]

    r = jax.jit(run)
    out = float(fetch(r(carry0)))      # compile + warm; fetch = fence
    samples = []
    for _ in range(repeats):
        t0 = clock()
        float(fetch(r(carry0)))
        samples.append((clock() - t0) / k)
    return samples, out


def _trace_ctx(trace_dir):
    from lux_tpu.profiling import trace
    return trace(trace_dir)


def timed_fused_run(eng, num_iters: int, trace_dir: str | None = None,
                    repeats: int = 1):
    """Warm up a pull engine ONCE with the SAME static iteration count
    (num_iters is a static jit arg — a different count would recompile
    inside the timed region), then time ``repeats`` fresh fused runs.
    When trace_dir is set, a profiler trace captures ONLY the timed
    runs (warmup and compilation are excluded).

    With telemetry iter-stats active (telemetry.use(iter_stats=...)),
    every run is the counter-recording variant (eng.run_stats — same
    program warmed and timed) and the LAST timed repeat's counters are
    fetched AFTER its elapsed time is recorded, so the download is
    never billed.  Per-repeat seconds are emitted as ``timed_run``
    events.

    Returns (final_state, [elapsed_seconds per repeat]).
    """
    from lux_tpu import telemetry
    from lux_tpu.profiling import step_annotation

    tel = telemetry.current()
    st = tel.iter_stats
    guarded = getattr(eng, "health", False)

    def one(state):
        if guarded:
            # the watchdog loop variant IS the timed program; the
            # 24-byte word is checked after the elapsed time is
            # recorded, so the check is never billed
            s, _it, rb, cb, rbp, cbp, h = eng.run_health(state,
                                                         num_iters)
            return s, rb, cb, rbp, cbp, h
        if st is not None:
            return (*eng.run_stats(state, num_iters), None)
        return eng.run(state, num_iters), None, None, None, None, None

    state, res_b, chg_b, res_p, chg_p, hvec = one(eng.init_state())
    fence(state)
    elapsed = []
    with _trace_ctx(trace_dir):
        for i in range(repeats):
            state = eng.init_state()
            fence(state)       # H2D upload is async: keep it untimed
            with step_annotation("lux_timed_run", i):
                t0 = time.perf_counter()
                state, res_b, chg_b, res_p, chg_p, hvec = one(state)
                fence(state)   # O(1)-byte fence, not a state download
                elapsed.append(time.perf_counter() - t0)
            tel.emit("timed_run", repeat=i, iters=num_iters,
                     seconds=round(elapsed[-1], 6))
    if guarded:
        from lux_tpu import health
        tel.emit("health", **health.ensure_ok(
            hvec, engine="pull", where="timed pull run"),
            iters=num_iters)
    if st is not None:
        st.begin_run()         # counters describe the LAST timed run
        st.extend_pull(res_b, chg_b, num_iters, res_p, chg_p)
    return state, elapsed


def timed_converge(eng, max_iters=None, verbose: bool = False,
                   trace_dir: str | None = None, repeats: int = 1):
    """Warm up a push engine's converge program ONCE (replaying
    per-iteration frontier sizes from the warmup's device counters
    when verbose), then time ``repeats`` fresh whole-run converges; a
    trace_dir captures only the timed runs.  With telemetry iter-stats
    active the timed program is eng.converge_stats and the last timed
    repeat's counters are fetched after its elapsed time is recorded.
    Returns (labels, iters, [elapsed_seconds per repeat])."""
    from lux_tpu import telemetry
    from lux_tpu.profiling import step_annotation

    tel = telemetry.current()
    st = tel.iter_stats
    guarded = getattr(eng, "health", False)

    def one(label, active):
        if guarded:
            return eng.converge_health(label, active, max_iters)
        if st is not None:
            return (*eng.converge_stats(label, active, max_iters),
                    None)
        l, a, it = eng.converge(label, active, max_iters)
        return l, a, it, None, None, None, None, None

    if verbose and st is None:
        # one extra run purely to replay counters; with an active
        # iter-stats handle the caller replays the TIMED run's
        # counters instead (printing here would double the series)
        eng.run(max_iters=max_iters, verbose=True)
    label, active = eng.init_state()
    l2, a2, _it, _f, _e, _fp, _ep, _h = one(label, active)  # compile
    fence(l2)
    elapsed = []
    with _trace_ctx(trace_dir):
        for i in range(repeats):
            label, active = eng.init_state()
            fence((label, active))   # keep the async upload untimed
            with step_annotation("lux_timed_converge", i):
                t0 = time.perf_counter()
                label, active, it_d, fsz, fed, fszp, fedp, hvec = \
                    one(label, active)
                iters = int(fetch(it_d))
                elapsed.append(time.perf_counter() - t0)
            tel.emit("timed_run", repeat=i, iters=iters,
                     seconds=round(elapsed[-1], 6))
    if guarded:
        from lux_tpu import health
        tel.emit("health", **health.ensure_ok(
            hvec, engine="push", where="timed converge"),
            iters=iters)
    if st is not None:
        st.begin_run()
        st.extend_push(fsz, fed, iters, fszp, fedp)
    return eng.unpad(label), iters, elapsed


def timed_run_until(eng, tol: float, max_iters: int,
                    trace_dir: str | None = None):
    """Warm a pull engine's convergence program with a one-iteration
    call of the SAME executable (tol/max_iters are traced args, so no
    recompile), then time a fresh run-to-convergence; a trace_dir
    captures only the timed run.  With telemetry iter-stats active the
    program is eng.run_until_stats (per-iteration residuals fetched
    after the elapsed time is recorded).  Returns (state, iters,
    residual, elapsed)."""
    from lux_tpu import telemetry

    tel = telemetry.current()
    st = tel.iter_stats
    guarded = getattr(eng, "health", False)

    def one(state, cap):
        if guarded:
            return eng.run_until_health(state, tol, max_iters=cap)
        if st is not None:
            return (*eng.run_until_stats(state, tol, max_iters=cap),
                    None)
        s, it, res = eng.run_until(state, tol, max_iters=cap)
        return s, it, res, None, None, None, None, None

    s0, _it, _res, _rb, _cb, _rp, _cp, _h = one(eng.init_state(), 1)
    fence(s0)
    state0 = eng.init_state()
    fence(state0)              # keep the async upload untimed
    with _trace_ctx(trace_dir):
        t0 = time.perf_counter()
        state, it, res, rb, cb, rbp, cbp, hvec = one(state0,
                                                     max_iters)
        iters = int(fetch(it))
        elapsed = time.perf_counter() - t0
    tel.emit("timed_run", repeat=0, iters=iters,
             seconds=round(elapsed, 6))
    if guarded:
        from lux_tpu import health
        tel.emit("health", **health.ensure_ok(
            hvec, engine="pull", where="timed run_until"),
            iters=iters)
    if st is not None:
        st.begin_run()
        st.extend_pull(rb, cb, iters, rbp, cbp)
    return state, iters, float(fetch(res)), elapsed
