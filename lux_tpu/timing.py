"""Timing utilities with reliable completion fences.

The reference drains its deferred-execution pipeline with an execution
fence + TimingLauncher before reading wall clocks (reference
sssp.cc:132-135).  The TPU analogue: on remote-tunnel platforms
``block_until_ready`` can return before the device finishes, so the
only trustworthy fence is a host fetch.
"""

from __future__ import annotations

import time

import numpy as np


def fetch(x) -> np.ndarray:
    """Force completion of everything ``x`` depends on; returns host
    value."""
    import jax
    return np.asarray(jax.device_get(x))


def _cksum(*leaves):
    import jax.numpy as jnp
    return sum(jnp.sum(leaf.reshape(-1)[:8].astype(jnp.float32))
               for leaf in leaves)


_cksum_jit = None


def fence(x) -> None:
    """Tunnel-safe completion fence that ships O(1) bytes: fetches a
    tiny checksum DEPENDENT on ``x`` instead of ``x`` itself.  A full
    ``fetch`` of a multi-GB state bills its device->host transfer
    (~60-300 MB/s through the tunnel) to whatever is being timed —
    measured as seconds/iteration of phantom cost at RMAT25.

    One module-level jitted checksum: repeat calls with the same leaf
    shapes hit the jit cache, so no (remote) compile lands inside a
    timed window after the warmup call."""
    import jax

    global _cksum_jit
    if _cksum_jit is None:
        _cksum_jit = jax.jit(_cksum)
    fetch(_cksum_jit(*jax.tree.leaves(x)))


def _trace_ctx(trace_dir):
    from lux_tpu.profiling import trace
    return trace(trace_dir)


def timed_fused_run(eng, num_iters: int, trace_dir: str | None = None,
                    repeats: int = 1):
    """Warm up a pull engine ONCE with the SAME static iteration count
    (num_iters is a static jit arg — a different count would recompile
    inside the timed region), then time ``repeats`` fresh fused runs.
    When trace_dir is set, a profiler trace captures ONLY the timed
    runs (warmup and compilation are excluded).

    Returns (final_state, [elapsed_seconds per repeat]).
    """
    state = eng.init_state()
    state = eng.run(state, num_iters)
    fence(state)
    elapsed = []
    with _trace_ctx(trace_dir):
        for _ in range(repeats):
            state = eng.init_state()
            fence(state)       # H2D upload is async: keep it untimed
            t0 = time.perf_counter()
            state = eng.run(state, num_iters)
            fence(state)       # O(1)-byte fence, not a state download
            elapsed.append(time.perf_counter() - t0)
    return state, elapsed


def timed_converge(eng, max_iters=None, verbose: bool = False,
                   trace_dir: str | None = None, repeats: int = 1):
    """Warm up a push engine's converge program ONCE (printing
    per-iteration frontier sizes during the warmup pass when verbose),
    then time ``repeats`` fresh whole-run converges; a trace_dir
    captures only the timed runs.
    Returns (labels, iters, [elapsed_seconds per repeat])."""
    if verbose:
        eng.run(max_iters=max_iters, verbose=True)   # stepwise, printed
    label, active = eng.init_state()
    l2, a2, _ = eng.converge(label, active, max_iters)  # compile
    fence(l2)
    elapsed = []
    with _trace_ctx(trace_dir):
        for _ in range(repeats):
            label, active = eng.init_state()
            fence((label, active))   # keep the async upload untimed
            t0 = time.perf_counter()
            label, active, iters = eng.converge(label, active, max_iters)
            iters = int(fetch(iters))
            elapsed.append(time.perf_counter() - t0)
    return eng.unpad(label), iters, elapsed


def timed_run_until(eng, tol: float, max_iters: int,
                    trace_dir: str | None = None):
    """Warm a pull engine's convergence program with a one-iteration
    call of the SAME executable (tol/max_iters are traced args, so no
    recompile), then time a fresh run-to-convergence; a trace_dir
    captures only the timed run.  Returns (state, iters, residual,
    elapsed)."""
    s0, _it, _res = eng.run_until(eng.init_state(), tol, max_iters=1)
    fence(s0)
    state0 = eng.init_state()
    fence(state0)              # keep the async upload untimed
    with _trace_ctx(trace_dir):
        t0 = time.perf_counter()
        state, it, res = eng.run_until(state0, tol, max_iters)
        iters = int(fetch(it))
        elapsed = time.perf_counter() - t0
    return state, iters, float(fetch(res)), elapsed
