from lux_tpu.parallel.mesh import make_mesh, shard_over_parts
