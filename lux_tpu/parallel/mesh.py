"""Device mesh construction and part-axis sharding helpers.

The reference's placement layer is its Legion mapper: slice_task
round-robins partition tasks over GPUs and pins regions to framebuffer
vs zero-copy memory (reference lux_mapper.cc:97-165).  On TPU the same
role is played declaratively: a 1-D ``Mesh`` over the ``parts`` axis
plus ``NamedSharding`` annotations on the part-major arrays; XLA's SPMD
partitioner then inserts the ICI collectives that Legion/GASNet
performed implicitly (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PARTS_AXIS = "parts"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the ``parts`` axis."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested {num_devices} devices, have {len(devices)}")
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (PARTS_AXIS,))


def parts_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(PARTS_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_part_rows(mesh: Mesh, num_parts: int) -> list[int]:
    """The global leading-axis rows this PROCESS's devices hold under
    the parts sharding (sorted).  Single-process: all rows."""
    sharding = parts_spec(mesh)
    idx_map = sharding.addressable_devices_indices_map((num_parts,))
    rows = set()
    for idx in idx_map.values():
        rows.update(range(*idx[0].indices(num_parts)))
    return sorted(rows)


def shard_over_parts(mesh: Mesh, tree, num_parts: int | None = None):
    """Place every array in ``tree`` sharded on its leading (parts)
    axis.  Leading dims must be divisible by the mesh size.

    Multi-process (jax.distributed): ``num_parts`` gives the global
    leading dim.  Arrays carrying all ``num_parts`` rows are split into
    per-local-device shards; arrays carrying only this process's rows
    (ShardedGraph built with ``parts=``) are assembled with
    ``jax.make_array_from_process_local_data`` — the analogue of the
    reference's per-node region instances that Legion stitches into one
    logical region (reference push_model.inl:8-51).
    """
    sharding = parts_spec(mesh)
    multiproc = jax.process_count() > 1

    def place(x):
        if x is None:
            return None
        if not multiproc:
            return jax.device_put(x, sharding)
        if num_parts is None or x.shape[0] == num_parts:
            # full array present on every process: hand each local
            # device its slice
            idx_map = sharding.addressable_devices_indices_map(x.shape)
            shards = [jax.device_put(np.asarray(x[idx]), d)
                      for d, idx in idx_map.items()]
            return jax.make_array_from_single_device_arrays(
                x.shape, sharding, shards)
        gshape = (num_parts,) + tuple(x.shape[1:])
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(x), gshape)

    return jax.tree.map(place, tree)
