"""Device mesh construction and part-axis sharding helpers.

The reference's placement layer is its Legion mapper: slice_task
round-robins partition tasks over GPUs and pins regions to framebuffer
vs zero-copy memory (reference lux_mapper.cc:97-165).  On TPU the same
role is played declaratively: a 1-D ``Mesh`` over the ``parts`` axis
plus ``NamedSharding`` annotations on the part-major arrays; XLA's SPMD
partitioner then inserts the ICI collectives that Legion/GASNet
performed implicitly (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PARTS_AXIS = "parts"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the ``parts`` axis."""
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested {num_devices} devices, have {len(devices)}")
            devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (PARTS_AXIS,))


def parts_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(PARTS_AXIS))


def replicated_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_over_parts(mesh: Mesh, tree):
    """device_put every array in ``tree`` sharded on its leading (parts)
    axis.  Leading dims must be divisible by the mesh size."""
    sharding = parts_spec(mesh)

    def place(x):
        if x is None:
            return None
        return jax.device_put(x, sharding)

    return jax.tree.map(place, tree)
