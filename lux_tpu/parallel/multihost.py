"""Multi-host initialization and mesh construction.

The reference scales multi-node by running the same binary under
GASNet: Realm address spaces multiply the partition count and the
mapper spreads index points across nodes (reference pagerank.cc:51-53,
lux_mapper.cc:116, README.md:33-38).  The TPU-native equivalent is a
``jax.distributed`` process group: every host runs the same program,
``jax.devices()`` spans the whole slice/pod, and the same
``Mesh('parts')`` code paths shard over ICI within a slice and DCN
across slices — XLA inserts and routes the collectives, exactly as
Legion/GASNet materialized remote regions.

Typical use (same script on every host):

    from lux_tpu.parallel import multihost
    multihost.initialize()                  # env-driven (TPU pods:
                                            # fully automatic)
    mesh = multihost.global_mesh()          # all devices, 'parts' axis
    eng = pagerank.build_engine(g, num_parts=mesh.devices.size,
                                mesh=mesh)

Engines already accept any parts mesh; host-local data feeding uses
``jax.make_array_from_process_local_data`` if the graph is loaded
shard-wise per host (each host loads its partitions' slices with
``native.load_partition`` — the reference's per-part load tasks).
"""

from __future__ import annotations


def initialize(**kwargs) -> None:
    """Join the jax.distributed process group.  On TPU pods all
    parameters come from the environment; pass coordinator_address /
    num_processes / process_id explicitly elsewhere.

    Only the specific "no coordinator configured" case degrades to a
    single-process run; genuine init failures (unreachable
    coordinator, bad env) propagate — silently computing per-host
    answers on a pod would be the worst possible failure mode."""
    import jax

    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            return                     # double-init is harmless
        if not kwargs and "before" in msg:
            # env-driven init after the backend started: single-process
            import logging
            logging.getLogger(__name__).info(
                "jax.distributed not initialized (%s); running "
                "single-process", e)
            return
        raise
    except ValueError as e:
        if kwargs:
            raise
        if "coordinator_address" in str(e):
            import logging
            logging.getLogger(__name__).info(
                "jax.distributed not initialized (%s); running "
                "single-process", e)
            return
        raise


def global_mesh(n_devices: int | None = None):
    """A 1-D 'parts' mesh over all (global) devices — the axis every
    lux_tpu engine shards over."""
    from lux_tpu.parallel.mesh import make_mesh

    import jax

    return make_mesh(n_devices or len(jax.devices()))


def fetch_global(x):
    """Device state -> host numpy with ALL shards, also the ones this
    process cannot address (multi-host runs): gathers the remote
    shards over the process group first.  Single-process: plain
    device_get."""
    import jax
    import numpy as np

    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def allreduce_host(x, op: str = "max"):
    """Elementwise allreduce of a HOST numpy value across the process
    group (planning-time agreement, e.g. the pair planner's common
    depth profile — the analogue of the reference's identical host-
    side Graph ctor on every node).  Single-process: identity."""
    import jax
    import numpy as np

    x = np.asarray(x)
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils
    stacked = multihost_utils.process_allgather(x)   # [nproc, ...]
    return {"max": np.max, "sum": np.sum}[op](stacked, axis=0)


def process_parts(num_parts: int) -> range:
    """The contiguous range of partition ids this host is responsible
    for loading (partition i lives on global device i * P / num_parts).
    Use with native.load_partition to read only this host's slices of
    a .lux file."""
    import jax

    nproc = jax.process_count()
    pid = jax.process_index()
    per = num_parts // nproc
    if num_parts % nproc:
        raise ValueError(
            f"num_parts={num_parts} must divide evenly over "
            f"{nproc} processes")
    return range(pid * per, (pid + 1) * per)
