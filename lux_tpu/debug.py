"""Failure detection and numeric guards.

The reference aborts on assert/FatalError and has no divergence
detection (SURVEY.md §5).  Long iterative runs on real data deserve
better: these helpers catch NaN escapes and stalled convergence loops
with actionable errors, without slowing the compiled hot loop (checks
run on segment boundaries, host-side, via lux_tpu.segmented).

Race detection note: there is nothing to detect.  The engines are
pure-functional XLA programs — no shared mutable state, no atomics;
the only "races" possible in the reference's design (concurrent
region access, atomic update ordering) are excluded by construction
here, and jit(donate_argnums) buffer reuse is checked by JAX itself.
"""

from __future__ import annotations

import numpy as np

from lux_tpu import segmented


class GuardError(RuntimeError):
    """Base of the runtime guards' failures.  resilience.classify keys
    off the subclasses: DivergenceError (NaN escape — possibly a
    transient corruption whose last checkpoint is clean) is retryable
    from a checkpoint; StallError (deterministic livelock) is fatal."""


class DivergenceError(GuardError):
    pass


class StallError(GuardError):
    pass


def check_finite(state, where: str = "state",
                 allow_inf: bool = False) -> None:
    """Raise DivergenceError if any floating leaf holds NaN (or Inf,
    unless allow_inf — push labels legitimately use +inf as the
    unreached sentinel).  Fetches to host; call on segment
    boundaries."""
    import jax

    for i, leaf in enumerate(jax.tree.leaves(state)):
        arr = np.asarray(jax.device_get(leaf))
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        bad = np.isnan(arr) if allow_inf else ~np.isfinite(arr)
        if bad.any():
            kind = "NaN" if allow_inf else "non-finite"
            raise DivergenceError(
                f"{where}: leaf {i} has {int(bad.sum())} {kind} values "
                f"(dtype {arr.dtype}, shape {arr.shape})")


def run_guarded(eng, state, num_iters: int, segment: int = 50,
                where: str = "pull run"):
    """Pull-engine run with a finite check every ``segment``
    iterations; raises DivergenceError naming the failing segment."""
    return segmented.run_segments(
        eng, state, num_iters, segment,
        on_segment=lambda s, done:
            check_finite(s, f"{where} @ iteration {done}"))


def converge_guarded(eng, max_iters: int | None = None,
                     segment: int = 64, stall_segments: int = 3):
    """Push-engine convergence with stall detection.

    Progress is measured by the (monotone) label fingerprint — the sum
    of finite labels — not the frontier size, which legitimately stays
    constant on path-like graphs.  If the fingerprint AND the active
    count are unchanged for ``stall_segments`` consecutive segments
    while the frontier is non-empty, raises StallError (a monotone
    program that stops improving but keeps a frontier indicates a
    broken relax function or truncation livelock).  NaN labels raise
    DivergenceError (+inf sentinels are fine).
    Returns (labels, total_iters).
    """
    import jax

    label0, active0 = eng.init_state()
    history: list[tuple] = []

    def on_segment(label, active, total, cnt):
        if cnt == 0:
            return
        check_finite(label, f"push converge @ iteration {total}",
                     allow_inf=True)
        arr = np.asarray(jax.device_get(label)).astype(np.float64)
        fp = float(arr[np.isfinite(arr)].sum())
        history.append((cnt, fp))
        if len(history) > stall_segments:
            history.pop(0)
        if (len(history) == stall_segments and
                len(set(history)) == 1):
            raise StallError(
                f"frontier stuck at {cnt} active vertices with no "
                f"label progress for {stall_segments * segment} "
                f"iterations")

    label, active, total = segmented.converge_segments(
        eng, label0, active0, segment, max_iters,
        on_segment=on_segment)
    return eng.unpad(label), total
