"""Communication observatory: the per-collective byte ledger.

ROADMAP item 3 (pod-scale meshes: quantized owner exchange +
resharding over DCN) was blocked on a measurement gap: scalemodel
priced comm with an admitted 2-4x error margin ("comm is permille"),
``phase_model`` left exchange/reduce honestly unmodeled, and the
collective call sites across the engines were audited only for
schedule *shape* (lux_tpu/audit.py collective-schedule), never for
*bytes*.  This module makes communication a measured, cross-checked
quantity, mirroring the PR-7 observatory pattern (calibrate /
attribute / persist) in three pillars:

1. **Static comm ledger** (``ledger_for``): trace the exact per-
   iteration program each engine registered via
   ``engine/auditable.py`` (the "step" variant — the same registry
   the auditor's collective-schedule check consumes), walk the jaxpr
   for every collective eqn (ppermute / all_to_all / psum_scatter /
   reduce_scatter / all_gather / psum / pmin / pmax) and price its
   wire bytes: per-device operand payload x the ring-algorithm hop
   factor x per-iteration multiplicity (scan lengths), classified by
   link tier (intra-slice ICI vs inter-slice DCN from the mesh's
   device slice topology).  The result is cross-checked BOTH against
   an independent NumPy message-count oracle (``oracle_for``:
   predicts the collective multiset from the engine's own layout
   config, never reading the jaxpr) AND against the audit's
   collective-schedule expectations (``audit.engine_spec``) —
   disagreement raises the typed ``CommLedgerError``.

2. **Measured link calibration** (lux_tpu/observe.py
   ``calibrate_links`` + the ici/dcn bandwidth debts): ppermute-ring
   and all_to_all payload sweeps on the trusted ``timing.loop_bench``
   recipe feed measured link bytes/s into
   ``scalemodel.set_measured_link``, replacing the hardcoded
   ICI_BYTES_PER_S in the mesh projections; ``observe.decompose``
   grades a comm-attribution verdict (measured exchange-phase time
   vs ledger-bytes / measured-bandwidth — the wire time is a LOWER
   bound on the phase, so a phase faster than its own bytes is a
   contradiction).

3. **Pod-scale forecaster** (``python -m lux_tpu.comms -project``):
   the item-3 decision table — per flagship shape, comm/compute
   ratio at 1-hop ICI vs a DCN thinness sweep (10-100x), including
   the projected int8/bf16 quantized-exchange savings
   (scalemodel.QUANT_FACTORS, the EQuARX-style block-scaled encoding,
   PAPERS.md) so the quantized-exchange build lands against a priced
   target, not a guess.

Byte convention (documented in ARCHITECTURE.md "Communication
observatory"; the oracle implements the same arithmetic
independently):

  per-device wire bytes of one collective launch, payload X = the
  per-device operand bytes as seen inside shard_map, over an
  ``ndev``-device axis (ring algorithms, the TPU lowering):

    ppermute                        X            (one hop per eqn)
    all_gather                      X * (ndev-1)           (X = shard)
    psum_scatter / reduce_scatter   X * (ndev-1) // ndev
    all_to_all                      X * (ndev-1) // ndev
    psum / pmin / pmax              2 * X * (ndev-1) // ndev   (RS+AG)

``bytes_per_iter`` is the per-DEVICE steady-state wire bytes of one
iteration: unconditional eqns plus, per cond, the heaviest branch
(the sparse/dense switch of the push engines makes branches genuine
alternatives; the ledger prices the worst case and reports every
branch in the breakdown).  ``bytes_per_edge`` is the aggregate wire
cost per edge: bytes_per_iter * ndev / ne.

CLI: ``python -m lux_tpu.comms`` emits one JSON ledger line per
config of the repo audit matrix (CPU-runnable, tracing only — no
compile, no execution); ``-project`` renders the pod forecast table.

Reference anchor: the reference's comm accounting is Legion's region
requirements (reference pull_model.inl:454-461) — declared, never
priced; this module is the pricing the TPU port's mesh claims rest
on.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

__all__ = [
    "CommLedgerError", "CollectiveEntry", "CommLedger", "ledger_for",
    "ledger_of_jaxpr", "oracle_for", "cross_check", "mesh_tier",
    "shipped_bytes", "bench_digest", "comm_fraction",
    "forecast_table", "main",
]

# collective primitive names as they appear in traced jaxprs; the
# psum_scatter API lowers to a "reduce_scatter" eqn, normalized below
COLLECTIVE_PRIMS = frozenset({
    "ppermute", "all_to_all", "psum_scatter", "reduce_scatter",
    "all_gather", "psum", "pmin", "pmax",
})

_NORMALIZE = {"psum_scatter": "reduce_scatter"}


class CommLedgerError(Exception):
    """The comm ledger disagrees with its oracle or with the audit's
    collective-schedule expectations — the per-byte accounting cannot
    be trusted, so nothing downstream (bench comm digest, forecast)
    may consume it.  ``details`` carries the itemized disagreements."""

    def __init__(self, message: str, details=()):
        super().__init__(message)
        self.details = list(details)


@dataclasses.dataclass(frozen=True)
class CollectiveEntry:
    """One collective eqn of the per-iteration program.

    ``payload_bytes`` is the per-device operand size; ``shipped_bytes``
    the per-device wire bytes of ONE launch (hop convention above);
    ``mult`` the per-iteration launch count (product of enclosing scan
    lengths); ``branch`` the cond path ("" = unconditional) — entries
    sharing a branch prefix up to the final ``#i`` are alternatives."""

    prim: str
    shape: tuple
    dtype: str
    payload_bytes: int
    shipped_bytes: int
    mult: int
    tier: str
    branch: str = ""

    def as_dict(self) -> dict:
        return {"prim": self.prim, "shape": list(self.shape),
                "dtype": self.dtype,
                "payload_bytes": self.payload_bytes,
                "shipped_bytes": self.shipped_bytes,
                "mult": self.mult, "tier": self.tier,
                "branch": self.branch}

    def key(self):
        """Comparison key for the oracle cross-check: the branch
        LABELS differ between ledger (jaxpr paths) and oracle
        (semantic names), so identity is (prim, shape, dtype, mult,
        conditional?)."""
        return (self.prim, tuple(self.shape), self.dtype,
                int(self.mult), bool(self.branch))


@dataclasses.dataclass(frozen=True)
class CommLedger:
    """Per-iteration communication bill of one engine configuration."""

    where: str
    ndev: int
    exchange: str
    tier: str                 # link tier of the mesh axis
    ne: int                   # edges (aggregate, as the engine runs)
    entries: tuple            # every CollectiveEntry, branches included
    bytes_per_iter: int       # per-device steady-state wire bytes
    messages: int             # collective launches on the steady path
    audit_eqns: dict          # prim -> flat eqn count over the jaxpr

    @property
    def bytes_per_edge(self) -> float:
        """Aggregate wire bytes per edge: every device ships
        bytes_per_iter while the mesh retires ne edges."""
        return self.bytes_per_iter * self.ndev / max(1, self.ne)

    def per_collective(self) -> list:
        """Breakdown grouped by (prim, branch): launch count, eqn
        count, payload and shipped bytes — the table events_summary
        renders (and audits: the per-prim ``eqns`` sums must match
        ``audit_eqns``, or the published trail contradicts the
        program it claims to describe)."""
        groups: dict = {}
        for e in self.entries:
            k = (e.prim, e.branch)
            g = groups.setdefault(k, {"prim": e.prim,
                                      "branch": e.branch, "count": 0,
                                      "eqns": 0,
                                      "shipped_bytes": 0,
                                      "payload_bytes": 0,
                                      "tier": e.tier})
            g["count"] += e.mult
            g["eqns"] += 1
            g["shipped_bytes"] += e.shipped_bytes * e.mult
            g["payload_bytes"] += e.payload_bytes * e.mult
        return [groups[k] for k in sorted(groups)]

    def as_dict(self) -> dict:
        return {
            "config": self.where, "ndev": self.ndev,
            "exchange": self.exchange, "tier": self.tier,
            "ne": self.ne, "bytes_per_iter": self.bytes_per_iter,
            "bytes_per_edge": round(self.bytes_per_edge, 6),
            "messages": self.messages,
            "per_collective": self.per_collective(),
            "audit_eqns": dict(sorted(self.audit_eqns.items())),
        }


# ---------------------------------------------------------------------
# hop convention

def shipped_bytes(prim: str, payload: int, ndev: int) -> int:
    """Per-device wire bytes of ONE launch (ring algorithms — see the
    module docstring; integer arithmetic so ledger and oracle compare
    bitwise)."""
    prim = _NORMALIZE.get(prim, prim)
    if ndev <= 1:
        return 0
    if prim == "ppermute":
        return payload
    if prim == "all_gather":
        return payload * (ndev - 1)
    if prim in ("reduce_scatter", "all_to_all"):
        return payload * (ndev - 1) // ndev
    if prim in ("psum", "pmin", "pmax"):
        return 2 * payload * (ndev - 1) // ndev
    raise ValueError(f"unknown collective {prim!r}")


def mesh_tier(mesh) -> str:
    """Link tier of a mesh's axis: "local" (no mesh / one device),
    "ici" (all devices on one slice — intra-slice interconnect), or
    "dcn" (devices span slices: the axis crosses the data-center
    network, 10-100x thinner — the item-3 regime).  Devices without a
    ``slice_index`` attribute (CPU test meshes) count as one slice."""
    if mesh is None or mesh.devices.size <= 1:
        return "local"
    slices = {getattr(d, "slice_index", 0) or 0
              for d in mesh.devices.flat}
    return "dcn" if len(slices) > 1 else "ici"


# ---------------------------------------------------------------------
# pillar 1a: the jaxpr walk

def _aval_bytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()))
    dt = np.dtype(getattr(aval, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dt.itemsize


def _walk(jaxpr, ndev: int, tier: str, entries: list, mult: int = 1,
          branch: str = ""):
    """Collect CollectiveEntry rows and return (steady_bytes,
    steady_msgs) for this jaxpr: unconditional eqns sum; a cond
    contributes its heaviest branch (ties: first)."""
    from lux_tpu.audit import _sub_jaxprs

    bytes_total, msgs_total = 0, 0
    for i, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            aval = eqn.invars[0].aval
            payload = sum(_aval_bytes(v.aval) for v in eqn.invars
                          if hasattr(getattr(v, "aval", None), "shape"))
            ship = shipped_bytes(name, payload, ndev)
            entries.append(CollectiveEntry(
                prim=_NORMALIZE.get(name, name),
                shape=tuple(aval.shape), dtype=str(aval.dtype),
                payload_bytes=payload, shipped_bytes=ship, mult=mult,
                tier=tier, branch=branch))
            bytes_total += ship * mult
            msgs_total += mult
            continue
        subs = list(_sub_jaxprs(eqn.params))
        if not subs:
            continue
        if name == "cond":
            best = (0, 0)
            for b, (sub, _) in enumerate(subs):
                got = _walk(sub, ndev, tier, entries, mult,
                            f"{branch}cond[{i}]#{b}")
                best = max(best, got)
            bytes_total += best[0]
            msgs_total += best[1]
        else:
            m2 = mult
            if name == "scan":
                m2 = mult * int(eqn.params.get("length", 1))
            for sub, _ in subs:
                b, m = _walk(sub, ndev, tier, entries, m2, branch)
                bytes_total += b
                msgs_total += m
    return bytes_total, msgs_total


def _flat_eqn_counts(closed) -> dict:
    """prim -> eqn count over the WHOLE jaxpr, via the auditor's own
    walker (lux_tpu/audit._iter_eqns) — the collective-schedule
    check's view of the program, cross-checked against the ledger's
    branch-aware walk so a walker bug cannot miscount silently."""
    from lux_tpu.audit import _iter_eqns

    counts: dict = {}
    for eqn, _, _ in _iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            name = _NORMALIZE.get(name, name)
            counts[name] = counts.get(name, 0) + 1
    return counts


def ledger_of_jaxpr(closed, ndev: int, tier: str = "ici",
                    where: str = "<jaxpr>", exchange: str = "?",
                    ne: int = 0) -> CommLedger:
    """Build a CommLedger from one traced ClosedJaxpr (the engine-free
    entry: synthetic programs, tests)."""
    entries: list = []
    steady_bytes, steady_msgs = _walk(closed.jaxpr, ndev, tier,
                                      entries)
    led = CommLedger(
        where=where, ndev=ndev, exchange=exchange, tier=tier, ne=ne,
        entries=tuple(entries), bytes_per_iter=steady_bytes,
        messages=steady_msgs, audit_eqns=_flat_eqn_counts(closed))
    # internal consistency: the branch-aware walk and the auditor's
    # flat walk must see the same eqn multiset (mult collapses scans,
    # so compare entry counts per prim against flat eqn counts)
    flat_entries: dict = {}
    for e in led.entries:
        flat_entries[e.prim] = flat_entries.get(e.prim, 0) + 1
    if flat_entries != led.audit_eqns:
        raise CommLedgerError(
            f"{where}: ledger walk saw {flat_entries} collective "
            f"eqns but the audit walker sees {led.audit_eqns} — the "
            f"two jaxpr walks disagree", [
                f"ledger={flat_entries}", f"audit={led.audit_eqns}"])
    return led


# ---------------------------------------------------------------------
# pillar 1b: the NumPy message-count oracle

def _engine_kind(eng) -> str:
    return "push" if hasattr(eng, "converge") else "pull"


def _push_msg_dtype(eng, lab_dtype):
    """Owner-message dtype of a push engine: relax on the label dtype
    (abstract eval — mirrors PushEngine._dense_parts_owner)."""
    import jax

    weighted = any(k in eng.arrays
                   for k in ("own_w", "own_pg_w", "own_pm_w"))
    w = (jax.ShapeDtypeStruct((1, 1), np.float32) if weighted
         else None)
    return jax.eval_shape(
        lambda v, wt: eng.program.relax(v, wt),
        jax.ShapeDtypeStruct((1, 1), lab_dtype), w).dtype


def _owner_acc_shape(eng, trail) -> tuple:
    """[P, ntw] + trail — the accumulated-contribution operand the
    owner exchange routes (ops/owner.owner_contribs /
    ops/pagegather.paged_owner_contribs)."""
    P = int(eng.sg.num_parts)
    if eng.page_plan is not None:
        ntw = int(eng.page_plan.n_tiles) * 128 // P
    else:
        ntw = int(eng.owner.n_tiles) * 128
    return (P, ntw) + tuple(trail)


def oracle_for(eng) -> list:
    """Predict the step program's collective multiset from the
    engine's OWN configuration — numpy/host metadata only, never the
    jaxpr.  Returns [CollectiveEntry] with semantic branch labels
    ("sparse"/"dense"); cross_check compares on ``key()``."""
    import jax

    ndev = eng.ndev
    tier = mesh_tier(getattr(eng, "mesh", None))
    if ndev <= 1:
        return []
    sg = eng.sg
    kind = _engine_kind(eng)
    P_local = int(sg.num_parts) // ndev
    pagemajor = (eng.page_plan is not None
                 and eng.page_plan.mode == "pagemajor")

    def entry(prim, shape, dtype, branch="", mult=1):
        dt = np.dtype(dtype)
        payload = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        # independent arithmetic, deliberately spelled out (module
        # docstring convention) rather than shared with the ledger
        if prim == "ppermute":
            ship = payload
        elif prim == "all_gather":
            ship = payload * (ndev - 1)
        elif prim in ("reduce_scatter", "all_to_all"):
            ship = payload * (ndev - 1) // ndev
        else:                              # psum / pmin / pmax
            ship = 2 * payload * (ndev - 1) // ndev
        return CollectiveEntry(
            prim=prim, shape=tuple(int(s) for s in shape),
            dtype=str(np.dtype(dtype)), payload_bytes=payload,
            shipped_bytes=ship, mult=mult, tier=tier, branch=branch)

    if kind == "pull":
        sds = eng._audit_state_sds
        trail = tuple(sds.shape[2:])
        state_dt = sds.dtype
        shard = (P_local, int(sg.vpad)) + trail
        out = []
        if eng.exchange == "gather":
            out.append(entry("all_gather", shard, state_dt))
            return out
        msg_dt = eng._msg_dtype(sds)
        if pagemajor:
            Mg = int(eng.page_plan.route)
            shape = (P_local, int(sg.num_parts), Mg, 128) + trail
            out.append(entry("all_to_all", shape, msg_dt))
        else:
            acc = _owner_acc_shape(eng, trail)
            reduce_kind = getattr(eng.program, "reduce", "sum")
            if reduce_kind == "sum":
                out.append(entry("reduce_scatter", acc, msg_dt))
            elif eng.owner_minmax_fused:
                ring = (acc[0] // ndev,) + acc[1:]
                for _ in range(ndev - 1):
                    out.append(entry("ppermute", ring, msg_dt))
            else:
                out.append(entry("all_to_all", acc, msg_dt))
        if eng.pairs is not None:
            out.append(entry("all_gather", shard, state_dt))
        return out

    # push: step = psum(count) -> body -> psum(new count); the body is
    # a sparse/dense cond when the sparse queue machinery is usable
    lab_sds, _act_sds = eng._audit_state_sds
    trail = tuple(lab_sds.shape[2:])
    lab_dt = lab_sds.dtype
    shard = (P_local, int(sg.vpad)) + trail
    out = [entry("psum", (), np.int32), entry("psum", (), np.int32)]
    use_sparse, _limit = eng._sparse_mode()
    dense_branch = "dense" if use_sparse else ""

    dense = []
    if eng.exchange == "owner":
        msg_dt = _push_msg_dtype(eng, lab_dt)
        if pagemajor:
            Mg = int(eng.page_plan.route)
            shape = (P_local, int(sg.num_parts), Mg, 128) + trail
            dense.append(entry("all_to_all", shape, msg_dt,
                               branch=dense_branch))
        else:
            acc = _owner_acc_shape(eng, trail)
            reduce_kind = getattr(eng.program, "reduce", "sum")
            if reduce_kind == "sum":
                dense.append(entry("reduce_scatter", acc, msg_dt,
                                   branch=dense_branch))
            elif eng.owner_minmax_fused:
                ring = (acc[0] // ndev,) + acc[1:]
                for _ in range(ndev - 1):
                    dense.append(entry("ppermute", ring, msg_dt,
                                       branch=dense_branch))
            else:
                dense.append(entry("all_to_all", acc, msg_dt,
                                   branch=dense_branch))
        if eng.pairs is not None:
            dense.append(entry("all_gather", shard, lab_dt,
                               branch=dense_branch))
    else:
        dense.append(entry("all_gather", shard, lab_dt,
                           branch=dense_branch))
        dense.append(entry("all_gather", shard, np.bool_,
                           branch=dense_branch))
    out += dense

    if use_sparse:
        Q = int(eng.queue_cap)
        out.append(entry("all_gather", (P_local, Q), np.int32,
                         branch="sparse"))
        out.append(entry("all_gather", (P_local, Q), lab_dt,
                         branch="sparse"))
        out.append(entry("pmin", (), np.int32, branch="sparse"))
    del jax
    return out


def _oracle_totals(entries) -> tuple:
    """(bytes_per_iter, messages) under the same steady-state
    convention as the ledger walk: unconditional entries sum; branch
    groups contribute their heaviest alternative."""
    uncond_b = sum(e.shipped_bytes * e.mult for e in entries
                   if not e.branch)
    uncond_m = sum(e.mult for e in entries if not e.branch)
    groups: dict = {}
    for e in entries:
        if e.branch:
            g = groups.setdefault(e.branch, [0, 0])
            g[0] += e.shipped_bytes * e.mult
            g[1] += e.mult
    if groups:
        best = max(groups.values(), key=lambda g: g[0])
        uncond_b += best[0]
        uncond_m += best[1]
    return uncond_b, uncond_m


def cross_check(ledger: CommLedger, oracle_entries,
                where: str = "") -> None:
    """Raise CommLedgerError unless the traced ledger and the NumPy
    oracle agree on (a) the collective multiset — prim, per-device
    shape, dtype, multiplicity, conditionality — and (b) the
    steady-state byte/message totals, bitwise."""
    import collections

    where = where or ledger.where
    details = []
    led_keys = collections.Counter(e.key() for e in ledger.entries)
    ora_keys = collections.Counter(e.key() for e in oracle_entries)
    if led_keys != ora_keys:
        for k in sorted(set(led_keys) | set(ora_keys)):
            lk, ok = led_keys.get(k, 0), ora_keys.get(k, 0)
            if lk < ok:
                details.append(f"oracle predicts {ok}x {k} but the "
                               f"traced program carries {lk}")
            elif lk > ok:
                details.append(f"traced program carries {lk}x {k} "
                               f"but the oracle predicts {ok}")
    ora_bytes, ora_msgs = _oracle_totals(oracle_entries)
    if ledger.bytes_per_iter != ora_bytes:
        details.append(f"bytes_per_iter {ledger.bytes_per_iter} != "
                       f"oracle {ora_bytes}")
    if ledger.messages != ora_msgs:
        details.append(f"messages {ledger.messages} != oracle "
                       f"{ora_msgs}")
    if details:
        raise CommLedgerError(
            f"comm ledger disagrees with the NumPy oracle for "
            f"{where}: " + "; ".join(details[:6])
            + (f" (+{len(details) - 6} more)"
               if len(details) > 6 else ""), details)


def _check_against_audit(eng, ledger: CommLedger) -> None:
    """The ledger's eqn set must satisfy the collective-schedule
    expectations the auditor enforces (lux_tpu/audit.engine_spec) —
    the two subsystems read the same registry, so disagreement means
    one of them is lying about the program."""
    import jax

    from lux_tpu import audit

    jitted, thunk = eng.audit_variant("step")
    args = thunk()
    first = args[0] if hasattr(args[0], "dtype") else \
        jax.ShapeDtypeStruct((), np.float32)
    spec = audit.engine_spec(eng, first)
    counts = ledger.audit_eqns
    details = []
    if spec.expect_reduce_scatter and counts.get("reduce_scatter",
                                                 0) < 1:
        details.append("audit expects a psum_scatter/reduce_scatter; "
                       "the ledger found none")
    if spec.expect_all_to_all and counts.get("all_to_all", 0) < 1:
        details.append("audit expects an all_to_all; the ledger "
                       "found none")
    if spec.ppermute_hops is not None \
            and counts.get("ppermute", 0) != spec.ppermute_hops:
        details.append(f"audit expects {spec.ppermute_hops} ppermute "
                       f"hops; the ledger counted "
                       f"{counts.get('ppermute', 0)}")
    if details:
        raise CommLedgerError(
            f"comm ledger contradicts the audit collective-schedule "
            f"expectations for {ledger.where}: "
            + "; ".join(details), details)


def ledger_for(eng, where: str | None = None,
               check: bool = True) -> CommLedger:
    """The comm ledger of one built engine: trace its registered
    "step" variant (per-iteration program; tracing only — no compile,
    no execution) and price every collective.  ``check=True`` (the
    default) cross-checks against the NumPy oracle and the audit
    expectations, raising CommLedgerError on any disagreement."""
    from lux_tpu import audit

    where = where or type(eng).__name__
    jitted, thunk = eng.audit_variant("step")
    closed = audit.trace_variant(jitted, thunk())
    led = ledger_of_jaxpr(
        closed, ndev=eng.ndev,
        tier=mesh_tier(getattr(eng, "mesh", None)), where=where,
        exchange=eng.exchange, ne=int(eng.sg.ne))
    if check:
        cross_check(led, oracle_for(eng), where=where)
        _check_against_audit(eng, led)
    return led


# ---------------------------------------------------------------------
# bench digest (the metric-line ``comm`` field)

def comm_fraction(ledger: CommLedger,
                  compute_ns: float | None) -> float:
    """Modeled comm share of one iteration at the engine's own
    placement: wire seconds (ledger bytes at the tier's link rate —
    measured when calibrated, canonical otherwise) over wire +
    compute seconds.  In [0, 1] by construction; 0.0 off-mesh."""
    from lux_tpu import scalemodel

    if ledger.bytes_per_iter <= 0:
        return 0.0
    comm_s = ledger.bytes_per_iter / scalemodel.link_bytes_per_s(
        ledger.tier)
    if not compute_ns or compute_ns <= 0:
        return 1.0
    return comm_s / (comm_s + compute_ns * 1e-9)


def bench_digest(ledger: CommLedger,
                 compute_ns: float | None = None) -> dict:
    """The compact ``comm`` field bench.py metric lines carry
    (scripts/check_bench.py validates it and rejects the
    contradictions)."""
    return {
        "errors": 0,
        "ndev": ledger.ndev,
        "exchange": ledger.exchange,
        "tier": ledger.tier,
        "bytes_per_iter": ledger.bytes_per_iter,
        "comm_bytes_per_edge": round(ledger.bytes_per_edge, 6),
        "messages": ledger.messages,
        "comm_frac": round(comm_fraction(ledger, compute_ns), 6),
    }


# ---------------------------------------------------------------------
# pillar 3: pod-scale forecaster

# flagship shapes (PERF_NOTES trajectory): (label, scale, edge factor)
FLAGSHIP_SHAPES = (("rmat21", 21, 16), ("rmat25", 25, 16),
                   ("rmat27", 27, 16))


def forecast_rows(ne: int, nv: int, chips: int,
                  thinness=(1, 10, 30, 100),
                  quants=("f32", "bf16", "int8")) -> list:
    """Comm/compute decision rows for one shape at one chip count:
    per (link thinness, quantization), the per-iteration comm
    seconds, comm/compute ratio and projected aggregate GTEPS (owner
    exchange pricing — scalemodel.project_pull's compute terms, the
    ledger's wire convention for bytes)."""
    from lux_tpu import scalemodel

    base = scalemodel.project_pull(ne, nv, chips)
    state_bytes = nv * 4
    # the owner reduce_scatter routes the [P, ntw] contribution table:
    # each chip ships ~one state table x (C-1)/C per iteration — the
    # same figure the per-config ledger measures on real programs
    wire = state_bytes * (chips - 1) // chips
    ici = scalemodel.link_bytes_per_s("ici")
    rows = []
    for thin in thinness:
        for q in quants:
            qf = scalemodel.QUANT_FACTORS[q]
            comm_s = wire * qf / (ici / thin)
            iter_s = base.compute_s + comm_s
            gteps = ne / iter_s / 1e9
            rows.append({
                "chips": chips, "thinness": thin, "quant": q,
                "comm_ms": comm_s * 1e3,
                "ratio": comm_s / base.compute_s,
                "gteps": gteps,
                "gteps_per_chip": gteps / chips,
            })
    return rows


def forecast_table(shapes=FLAGSHIP_SHAPES, chip_counts=(8, 64, 256),
                   thinness=(1, 10, 30, 100),
                   quants=("f32", "bf16", "int8")) -> str:
    """The item-3 decision table (markdown): where does the owner
    exchange stop being permille — and how much of the DCN cliff does
    the quantized exchange buy back."""
    from lux_tpu import scalemodel

    lines = [
        f"(link: ici {scalemodel.link_bytes_per_s('ici'):.3g} B/s "
        f"{'measured' if scalemodel.measured_link('ici') else 'model'}"
        f"; thinness 1 = 1-hop ICI, N = DCN at ICI/N; quant factors "
        f"{scalemodel.QUANT_FACTORS})",
        "",
        "| shape | chips | thinness | quant | comm ms/iter | "
        "comm/compute | GTEPS | GTEPS/chip |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for label, scale, ef in shapes:
        nv = 1 << scale
        ne = nv * ef
        for chips in chip_counts:
            for r in forecast_rows(ne, nv, chips, thinness, quants):
                lines.append(
                    f"| {label} | {r['chips']} | {r['thinness']}x | "
                    f"{r['quant']} | {r['comm_ms']:.3f} | "
                    f"{r['ratio']:.4f} | {r['gteps']:.3f} | "
                    f"{r['gteps_per_chip']:.4f} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------
# CLI: python -m lux_tpu.comms

def run_matrix(configs=None, verbose: bool = False,
               emit_events: bool = True) -> list:
    """One checked ledger per audit-matrix config (lux_tpu/audit.py's
    matrix — the same engines the repo-wide audit traces).  Returns
    the ledger dicts; a config whose ledger fails its cross-check
    raises CommLedgerError (nothing downstream may consume it)."""
    from lux_tpu import audit, telemetry

    out = []
    for label, build, _ledger in audit.matrix_configs():
        if configs is not None and label not in configs:
            continue
        eng = build()
        led = ledger_for(eng, where=label, check=True)
        d = led.as_dict()
        d["oracle_ok"] = True
        out.append(d)
        if emit_events:
            telemetry.current().emit("comm_ledger", **d)
        if verbose:
            print(f"# {label}: {led.messages} msg/iter, "
                  f"{led.bytes_per_iter} B/iter ({led.tier})")
    return out


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.comms",
        description="communication observatory: per-collective byte "
                    "ledger over the repo audit matrix (tracing "
                    "only, CPU-runnable) and the pod-scale comm "
                    "forecast")
    ap.add_argument("-configs", nargs="+", default=None,
                    metavar="NAME",
                    help="subset of audit-matrix config labels "
                         "(default: all)")
    ap.add_argument("-project", action="store_true",
                    help="emit the item-3 pod-scale decision table "
                         "(DCN thinness sweep x quantized-exchange "
                         "savings) instead of the per-config ledger")
    ap.add_argument("-events", default=None, metavar="FILE",
                    help="append comm_ledger telemetry events as "
                         "JSONL (scripts/events_summary.py renders "
                         "them)")
    ap.add_argument("-calibrate-links", action="store_true",
                    dest="calibrate_links",
                    help="run the measured link probes first "
                         "(observe.calibrate_links; needs >= 2 "
                         "devices) so the forecast prices from this "
                         "session's measured bytes/s")
    ap.add_argument("-v", "-verbose", action="store_true",
                    dest="verbose")
    args = ap.parse_args(argv)

    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass          # backend already initialized (pytest conftest)

    from lux_tpu import telemetry

    events = telemetry.EventLog(args.events) if args.events else None
    rc = 0
    with telemetry.use(events=events):
        if args.calibrate_links:
            from lux_tpu import observe
            links = observe.calibrate_links()
            if links:
                for tier, rec in links.items():
                    print(f"# link {tier}: "
                          f"{rec['bytes_per_s']:.3g} B/s measured "
                          f"({rec['prim']}, payload "
                          f"{rec['payload_bytes']} B)",
                          file=sys.stderr)
            else:
                print("# link calibration skipped (needs >= 2 "
                      "devices)", file=sys.stderr)
        if args.project:
            print(forecast_table())
        else:
            try:
                for d in run_matrix(configs=args.configs,
                                    verbose=args.verbose):
                    print(json.dumps(d), flush=True)
            except CommLedgerError as e:
                print(f"ERROR: {e}", file=sys.stderr)
                rc = 1
    if events is not None:
        events.close()
    return rc


if __name__ == "__main__":
    import sys
    sys.exit(main())
