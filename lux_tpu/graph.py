"""Host graph representation and the padded device layout.

``Graph`` is the host-side CSC graph (what the reference keeps in
zero-copy memory after its load tasks, reference pull_model.inl:253-320).

``ShardedGraph`` is the TPU-native analogue of the reference's
per-partition device build (init_kernel CSC construction,
reference pagerank_gpu.cu:153-180): all index translation is done ONCE on
the host so that the per-iteration device code is nothing but
static-shape gathers and sorted segmented reductions:

- Partitions are edge-balanced contiguous vertex ranges (partition.py).
- Every per-part array is padded to the max across parts (vertex dim to
  ``vpad``, edge dim to ``epad``) so arrays stack into rectangular
  ``[num_parts, ...]`` tensors that shard cleanly over a mesh axis.
- Vertex state lives in *padded part-major order*: global slot of vertex
  v is ``part(v) * vpad + (v - starts[part(v)])``.  Edge sources are
  pre-translated into these slots (``src_slot``), so the gather of
  source state after an all-gather needs no arithmetic on device.
- Edge destinations are pre-translated to part-local indices
  (``dst_local``); padding edges point at a trash segment ``vpad`` and
  their sources at slot 0.

This replaces the reference's NodeStruct/EdgeStruct FB arrays and its
atomicAdd scatter with a layout where XLA/Pallas see dst-sorted segments
(SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import dataclasses

import numpy as np

from lux_tpu import format as luxfmt
from lux_tpu.partition import edge_balanced_bounds, part_edge_counts


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _check_partition_starts(starts: np.ndarray, num_parts: int,
                            nv: int) -> None:
    """Partition cut-point invariants (ShardedGraph.build): length
    num_parts+1, 0 .. nv, monotone non-decreasing.  A bad ``starts``
    (hand-rolled, or derived from a corrupt file) would otherwise
    build negative-size parts whose gathers silently clamp."""
    if starts.shape[0] != num_parts + 1:
        raise luxfmt.GraphFormatError(
            "starts", "partition_starts",
            f"{starts.shape[0]} cut points for {num_parts} parts "
            f"(need num_parts + 1)")
    if int(starts[0]) != 0 or int(starts[-1]) != nv:
        raise luxfmt.GraphFormatError(
            "starts", "partition_starts",
            f"cut points must span [0, {nv}], got "
            f"[{int(starts[0])}, {int(starts[-1])}]")
    d = np.diff(starts)
    if (d < 0).any():
        at = int(np.argmax(d < 0))
        raise luxfmt.GraphFormatError(
            "starts", "partition_starts",
            f"cut points decrease at part {at} "
            f"({int(starts[at])} -> {int(starts[at + 1])})")


@dataclasses.dataclass
class Graph:
    """Host CSC graph: row_ptrs are END offsets (see format.py)."""

    nv: int
    ne: int
    row_ptrs: np.ndarray          # uint64 [nv], end offsets
    col_idx: np.ndarray           # uint32 [ne], edge sources, dst-sorted
    weights: np.ndarray | None    # [ne] or None
    out_degrees: np.ndarray       # uint32 [nv]

    @classmethod
    def from_file(cls, path: str, weighted: bool | None = None,
                  weight_dtype=np.int32, use_native: bool = False,
                  validate: bool = False,
                  reorder: bool | str = False) -> "Graph":
        """Load a .lux file.  use_native=True routes the bulk reads
        through the C++ pthread-pread loader (lux_tpu.native), the
        analogue of the reference's native per-partition load tasks
        (reference pull_model.inl:253-320); falls back to mmap when
        the native library is unavailable.

        validate=True runs format.validate_graph on the loaded arrays
        (both load paths) — a malformed file raises a typed
        format.GraphFormatError instead of producing wrong results
        through XLA's clamping gathers (the apps' -validate flag and
        scripts/fsck_lux.py surface this).

        reorder: apply the page-aware ``.perm`` sidecar written by
        the reorder pass (lux_tpu/reorder.py; format.py sidecar
        section) at load — True requires the sidecar (typed
        GraphFormatError when absent), "auto" applies it only when
        present.  The sidecar is validated (length, bijection) either
        way; the returned graph is relabeled with perm[new] = old."""
        if reorder not in (False, True, "auto"):
            raise ValueError(f"reorder={reorder!r} must be False, "
                             f"True or 'auto'")
        g = None
        if use_native:
            from lux_tpu import native
            if native.available():
                hdr = luxfmt.peek_lux(path, weighted, weight_dtype)
                row_ptrs, col_idx, weights, _ = native.load_partition(
                    path, hdr.nv, hdr.ne, 0, hdr.nv,
                    weighted=hdr.has_weights, weight_dtype=weight_dtype)
                # degrees: col_idx is already in RAM, so count there
                # rather than re-reading 4*ne bytes from disk
                if validate:
                    luxfmt.validate_graph(hdr.nv, hdr.ne, row_ptrs,
                                          col_idx, path=path)
                degrees = np.bincount(col_idx,
                                      minlength=hdr.nv).astype(np.uint32)
                g = cls(nv=hdr.nv, ne=hdr.ne, row_ptrs=row_ptrs,
                        col_idx=col_idx, weights=weights,
                        out_degrees=degrees)
        if g is None:
            hdr, row_ptrs, col_idx, weights, degrees = luxfmt.read_lux(
                path, weighted, weight_dtype, validate=validate)
            if degrees is None:
                # The reference recomputes out-degrees at load time
                # anyway (PullScanTask, reference
                # pull_model.inl:322-345).
                degrees = np.bincount(
                    col_idx, minlength=hdr.nv).astype(np.uint32)
            g = cls(nv=hdr.nv, ne=hdr.ne, row_ptrs=row_ptrs,
                    col_idx=col_idx, weights=weights,
                    out_degrees=degrees)
        if reorder:
            import os as _os
            sidecar = luxfmt.perm_sidecar_path(path)
            if not _os.path.exists(sidecar):
                if reorder == "auto":
                    return g
                raise luxfmt.GraphFormatError(
                    sidecar, "perm_header",
                    "reorder=True but no .perm sidecar exists "
                    "(write one with lux_tpu.reorder / "
                    "format.write_perm_sidecar, or pass "
                    "reorder='auto')")
            perm = luxfmt.read_perm_sidecar(path, nv=g.nv)
            from lux_tpu.reorder import apply_perm
            return apply_perm(g, perm)
        return g

    @classmethod
    def from_edges(cls, src, dst, nv: int, weights=None) -> "Graph":
        from lux_tpu.convert import edges_to_csc
        row_ptrs, col_idx, w_sorted, deg = edges_to_csc(src, dst, nv, weights)
        return cls(nv=nv, ne=int(col_idx.shape[0]), row_ptrs=row_ptrs,
                   col_idx=col_idx, weights=w_sorted, out_degrees=deg)

    def with_edges(self, src, dst, weights=None) -> "Graph":
        """New Graph = this graph's edge multiset plus (src, dst[,
        weights]) — the live-graph compaction fold (lux_tpu/
        livegraph.py): the canonical (dst, src) CSC rebuild through
        ``convert.edges_to_csc`` is deterministic, so two processes
        folding the same delta into the same base produce
        byte-identical arrays (the WAL-replay bitwise contract).
        Weighted graphs require weights for the new edges and vice
        versa — a silently zero-weighted append would corrupt
        shortest paths instead of erroring."""
        src = np.asarray(src)
        dst = np.asarray(dst)
        if (self.weights is None) != (weights is None):
            raise ValueError(
                f"with_edges weights mismatch: graph is "
                f"{'weighted' if self.weights is not None else 'unweighted'}"
                f" but new edges are "
                f"{'weighted' if weights is not None else 'unweighted'}")
        base_src, base_dst = self.edge_arrays()
        w = None
        if self.weights is not None:
            w = np.concatenate([np.asarray(self.weights),
                                np.asarray(weights)])
        return Graph.from_edges(
            np.concatenate([base_src, src.astype(np.int64)]),
            np.concatenate([base_dst, dst.astype(np.int64)]),
            self.nv, weights=w)

    def in_degrees(self) -> np.ndarray:
        return np.diff(self.row_ptrs.astype(np.int64), prepend=0)

    def edge_arrays(self):
        """(src, dst) int64 arrays in file (dst-sorted) order."""
        src = self.col_idx.astype(np.int64)
        dst = np.repeat(np.arange(self.nv, dtype=np.int64),
                        self.in_degrees())
        return src, dst


def degree_relabel(g: Graph):
    """Relabel vertices by descending total degree — concentrates hubs
    into shared 128-vertex tiles so pair-lane delivery (PullEngine /
    PushEngine ``pair_threshold``; ops/pairs.py) finds dense tile
    pairs.  Returns (relabeled graph, perm) with perm[new] = old."""
    src, dst = g.edge_arrays()
    deg = (np.bincount(src, minlength=g.nv)
           + np.bincount(dst, minlength=g.nv))
    perm = np.argsort(-deg, kind="stable")
    rank = np.empty(g.nv, np.int64)
    rank[perm] = np.arange(g.nv)
    g2 = Graph.from_edges(rank[src], rank[dst], g.nv, weights=g.weights)
    return g2, perm


def pair_relabel(g: Graph, num_parts: int = 1,
                 pair_threshold: int = 16, gather_cost: float = 9.0,
                 pair_cost: float = 2.5, vpad_cap: float = 1.2,
                 verbose: bool = False):
    """Degree-sort, then DEAL whole 128-vertex tiles to parts by
    greedy cost balancing (LPT over degree-ordered tiles).

    For multi-part pair-lane delivery (ops/pairs.py) a plain degree
    sort is hostile twice over: contiguous partitions make the hub
    part's depth profile few-deep-tiles and the tail part's
    many-shallow-tiles — and the common padded class structure parts
    must share (shard_map runs ONE program) inflates to the
    elementwise max (measured 2.9x row padding at RMAT21/np=4) — and
    the tail parts keep nearly all the residual gather-served edges
    (measured 0.8M..5.9M skew).  Dealing tiles in descending degree
    order to the currently-cheapest part gives every part a similar
    depth profile AND balanced estimated cost.  Tile contents are
    unchanged by dealing, so pair coverage is identical to the plain
    degree sort.

    Per-tile cost uses the exact global pair histogram (parts are
    tile-aligned, so part-local pair structure equals the global
    tiling): an in-edge in a dense (src-tile, dst-tile) pair costs
    ``pair_cost`` ns, any other ``gather_cost`` ns (PERF_NOTES.md).

    ``vpad_cap`` bounds each part's TILE COUNT at ceil(cap * mean)
    during the dealing: pure cost-LPT measured a 2.5x vpad blowup at
    RMAT25/np=4 (state padding, exchange bytes and the owner-side
    gather's per-shard table size all scale with the WORST part, and
    a shard past ~64 MB re-enters the big-table gather tax —
    PERF_NOTES round-3 #3); the cap trades a sliver of cost balance
    for 2x+ smaller padding.

    Returns (relabeled graph, perm, starts) with perm[new] = old and
    ``starts`` the partition cut points to pass to ShardedGraph.build
    (tile-aligned; a partial trailing tile is placed last).
    """
    import time as _time

    def _tick(t0, stage):
        if verbose:
            print(f"# pair_relabel/{stage}: {_time.time() - t0:.1f}s",
                  flush=True)
        return _time.time()

    if vpad_cap < 1:
        # cap * P must cover every full tile, or the LPT's all-capped
        # argmin would dump the remainder on part 0 uncapped AND
        # unbalanced
        raise ValueError(f"vpad_cap={vpad_cap} must be >= 1")
    t0 = _time.time()
    src, dst = g.edge_arrays()
    # uint32 endpoint arrays: the whole pipeline below is billion-edge
    # host prep, and every avoided int64 temporary is 8 GB at RMAT26
    src = src.astype(np.uint32)
    dst = dst.astype(np.uint32)
    deg = (np.bincount(src, minlength=g.nv)
           + np.bincount(dst, minlength=g.nv))
    by_deg = np.argsort(-deg, kind="stable")      # degree position -> old
    del deg
    t0 = _tick(t0, "edges+degree_sort")
    Wt = 128
    n_tiles = -(-g.nv // Wt)
    full = n_tiles - 1 if g.nv % Wt else n_tiles
    P = max(1, num_parts)
    if P > 1 and full < P:
        # graph too small for whole-tile dealing; plain degree sort,
        # default (cost-balanced) cuts
        rank = np.empty(g.nv, np.int64)
        rank[by_deg] = np.arange(g.nv)
        g2 = Graph.from_edges(rank[src], rank[dst], g.nv,
                              weights=g.weights)
        return g2, by_deg, None

    if P > 1 and full:
        # estimated per-tile in-edge cost in the DEGREE-SORTED tiling
        rank0 = np.empty(g.nv, np.uint32)
        rank0[by_deg] = np.arange(g.nv, dtype=np.uint32)
        s2t = (rank0[src] // Wt).astype(np.int64)     # src tile
        d2t = (rank0[dst] // Wt).astype(np.int32)     # dst tile
        key = s2t * np.int64(n_tiles)
        key += d2t
        del s2t
        # per-edge pair multiplicity without np.unique's inverse
        # machinery: one FUSED radix sort carrying the edge index as
        # payload (sequential passes, no argsort random reads and no
        # key/index gathers — native.sort_kv, PERF_NOTES round 4),
        # then group boundaries on the sorted keys
        from lux_tpu import native
        idx = np.arange(len(key),
                        dtype=np.uint32 if len(key) < 2**32
                        else np.int64)
        native.sort_kv(key, (idx,))
        newg = np.ones(len(key), bool)
        newg[1:] = key[1:] != key[:-1]
        del key
        gid = (np.cumsum(newg) - 1).astype(np.int32)
        cnt = np.bincount(gid)
        is_pair = np.empty(len(gid), bool)            # per-edge dense?
        is_pair[idx] = cnt[gid] >= pair_threshold
        del idx, newg, gid, cnt
        # per-tile cost without a float64 per-edge array: count the
        # pair-served edges per dst tile, price the two classes
        pair_by_tile = np.bincount(d2t[is_pair], minlength=n_tiles)
        all_by_tile = np.bincount(d2t, minlength=n_tiles)
        del d2t, is_pair
        tile_cost = (pair_cost * pair_by_tile
                     + gather_cost * (all_by_tile - pair_by_tile))
        t0 = _tick(t0, "pair_histogram")
        cap = max(1, int(np.ceil(vpad_cap * full / P)))
        load = np.zeros(P)
        tiles_held = np.zeros(P, np.int64)
        owner = np.empty(full, np.int64)
        for t in range(full):                     # capped LPT greedy
            masked = np.where(tiles_held < cap, load, np.inf)
            p = int(np.argmin(masked))
            owner[t] = p
            load[p] += tile_cost[t]
            tiles_held[p] += 1
        part_tiles = [np.nonzero(owner == p)[0] for p in range(P)]
    else:
        part_tiles = [np.arange(p, full, P) for p in range(P)]

    counts_v = [len(t) * Wt for t in part_tiles]
    if g.nv % Wt:
        part_tiles[-1] = np.concatenate(
            [part_tiles[-1], [full]]).astype(np.int64)
        counts_v[-1] += g.nv % Wt
    starts = np.concatenate(([0], np.cumsum(counts_v))).astype(np.int64)
    tile_seq = np.concatenate(part_tiles)
    vert_order = (tile_seq[:, None] * Wt +
                  np.arange(Wt)[None, :]).reshape(-1)
    vert_order = vert_order[vert_order < g.nv]    # clip partial tile
    perm = by_deg[vert_order]                     # new -> old
    rank = np.empty(g.nv, np.uint32)
    rank[perm] = np.arange(g.nv, dtype=np.uint32)
    t0 = _tick(t0, "lpt_dealing")
    ns = rank[src]
    del src
    nd = rank[dst]
    del dst, rank
    g2 = Graph.from_edges(ns, nd, g.nv, weights=g.weights)
    _tick(t0, "rebuild_csc")
    return g2, perm, starts


@dataclasses.dataclass
class ShardedGraph:
    """Padded part-major device layout (all arrays are host numpy;
    engines move them on device with the right sharding)."""

    nv: int
    ne: int
    num_parts: int
    starts: np.ndarray        # int64 [num_parts+1] partition cut points
    vpad: int                 # padded vertices per part
    epad: int                 # padded edges per part
    nv_part: np.ndarray       # int32 [num_parts] real vertices per part
    ne_part: np.ndarray       # int64 [num_parts] real edges per part
    src_slot: np.ndarray      # int32 [num_parts, epad] padded global src slot
    dst_local: np.ndarray     # int32 [num_parts, epad] local dst, pad -> vpad
    edge_weight: np.ndarray | None  # float32 [num_parts, epad]
    row_ptr_local: np.ndarray  # int32 [num_parts, vpad+1] local END offsets
    vmask: np.ndarray         # bool [num_parts, vpad] valid-vertex mask
    deg_padded: np.ndarray    # int32 [num_parts, vpad] out-degrees, padded

    weighted: bool = False
    # Multi-host builds (parallel/multihost.py): only these parts' rows
    # are materialized in the part-major arrays (None = all parts).
    # Global metadata (nv, starts, vpad, epad, nv_part, ne_part) stays
    # global so every process compiles the SAME program shapes — the
    # analogue of the reference's identical Graph ctor on every node
    # with per-node load tasks (reference pull_model.inl:29-191,253-320).
    local_parts: np.ndarray | None = None
    # Global row_ptrs (END offsets), kept on local builds so chunk
    # geometry (ops/tiled.py) can be sized over ALL parts.
    row_ptr_global: np.ndarray | None = None
    # Max out-degree over the WHOLE graph (push edge budgets must be
    # process-independent static shapes).
    max_out_degree: int = 0

    def compatible_mesh_sizes(self, available: int) -> list[int]:
        """Device counts this padded layout can run on UNCHANGED,
        descending: the divisors of num_parts no larger than
        ``available``.  Parts P are fixed across an elastic mesh
        shrink (resilience.py round 11) — every program shape, the
        pair plan, and the checkpointed global ``[P, vpad, ...]``
        view depend only on P, so re-placement onto any of these
        sizes is pure device re-mapping, no host rebuild."""
        cap = min(int(self.num_parts), int(available))
        return [d for d in range(cap, 0, -1)
                if self.num_parts % d == 0]

    def part_ids(self) -> np.ndarray:
        """Global part id of each materialized array row."""
        if self.local_parts is None:
            return np.arange(self.num_parts, dtype=np.int64)
        return np.asarray(self.local_parts, dtype=np.int64)

    @classmethod
    def build(cls, g: Graph, num_parts: int, vpad_align: int = 8,
              epad_align: int = 128, starts: np.ndarray | None = None,
              pair_threshold: int | None = None,
              parts=None) -> "ShardedGraph":
        """pair_threshold: build FOR pair-lane delivery — forces the
        128-aligned vertex padding the delivery needs and (for
        num_parts > 1) cuts partitions balancing ESTIMATED cost under
        the pair/gather split (ops/pairs.cost_balanced_starts) rather
        than raw edge counts.  ``starts`` overrides the cut points.

        parts: materialize only these parts' array rows (multi-host:
        each process builds its own parts, engines assemble the global
        sharded arrays with jax.make_array_from_process_local_data)."""
        if pair_threshold is not None:
            vpad_align = max(vpad_align, 128)
            if starts is None and num_parts > 1:
                from lux_tpu.ops.pairs import cost_balanced_starts
                starts = cost_balanced_starts(g, num_parts,
                                              pair_threshold)
        if starts is None:
            starts = edge_balanced_bounds(g.row_ptrs, num_parts)
        starts = np.asarray(starts, np.int64)
        _check_partition_starts(starts, num_parts, g.nv)
        nv_part = (starts[1:] - starts[:-1]).astype(np.int32)
        ne_part = part_edge_counts(g.row_ptrs, starts).astype(np.int64)
        vpad = _round_up(max(1, int(nv_part.max())), vpad_align)
        epad = _round_up(max(1, int(ne_part.max())), epad_align)
        if epad >= np.iinfo(np.int32).max:
            raise ValueError(
                f"per-part edge count {epad} overflows int32; "
                f"use more partitions")
        if num_parts * vpad >= np.iinfo(np.int32).max:
            raise ValueError(
                f"padded vertex-slot space {num_parts * vpad} overflows "
                f"int32 src_slot indices")

        rp = g.row_ptrs.astype(np.int64)
        col = g.col_idx
        # part id of every vertex, for the src -> padded-slot translation
        v_part = np.searchsorted(starts, np.arange(g.nv, dtype=np.int64),
                                 side="right") - 1
        v_slot = (v_part * vpad +
                  (np.arange(g.nv, dtype=np.int64) - starts[v_part]))
        v_slot = v_slot.astype(np.int64)

        local = None if parts is None else np.asarray(list(parts), np.int64)
        rows = np.arange(num_parts) if local is None else local
        R = len(rows)
        src_slot = np.zeros((R, epad), dtype=np.int32)
        dst_local = np.full((R, epad), vpad, dtype=np.int32)
        edge_weight = None
        if g.weights is not None:
            edge_weight = np.zeros((R, epad), dtype=np.float32)
        row_ptr_local = np.zeros((R, vpad + 1), dtype=np.int32)
        vmask = np.zeros((R, vpad), dtype=bool)
        deg_padded = np.zeros((R, vpad), dtype=np.int32)

        for r, p in enumerate(rows):
            v0, v1 = int(starts[p]), int(starts[p + 1])
            nep = int(ne_part[p])
            ebegin = int(rp[v0 - 1]) if v0 else 0
            eend = ebegin + nep
            # shard-boundary invariants (the same checks
            # format.validate_graph runs on the whole file, asserted
            # here on each part's slice so an unvalidated malformed
            # graph still errors instead of building garbage gathers)
            local_ends = (rp[v0:v1] - ebegin).astype(np.int64)
            in_deg = np.diff(np.concatenate(([0], local_ends)))
            if nep < 0 or (in_deg < 0).any() or (
                    v1 > v0 and int(local_ends[-1]) != nep):
                raise luxfmt.GraphFormatError(
                    f"part {p}", "partition_edges",
                    f"row_ptrs not monotone within vertices "
                    f"[{v0}, {v1}) or edge count {nep} inconsistent "
                    f"with the part's end offsets")
            srcs = col[ebegin:eend].astype(np.int64)
            if srcs.size and (int(srcs.min()) < 0
                              or int(srcs.max()) >= g.nv):
                bad = int(srcs.max()) if int(srcs.max()) >= g.nv \
                    else int(srcs.min())
                raise luxfmt.GraphFormatError(
                    f"part {p}", "col_idx_range",
                    f"edge source {bad} outside [0, {g.nv})")
            src_slot[r, :nep] = v_slot[srcs]
            # local dst of each edge: expand per-vertex in-degree runs
            dst_local[r, :nep] = np.repeat(
                np.arange(v1 - v0, dtype=np.int32), in_deg)
            if edge_weight is not None:
                edge_weight[r, :nep] = np.asarray(
                    g.weights[ebegin:eend], dtype=np.float32)
            row_ptr_local[r, 1:v1 - v0 + 1] = local_ends
            row_ptr_local[r, v1 - v0 + 1:] = nep
            vmask[r, :v1 - v0] = True
            deg_padded[r, :v1 - v0] = g.out_degrees[v0:v1]

        return cls(nv=g.nv, ne=g.ne, num_parts=num_parts, starts=starts,
                   vpad=vpad, epad=epad, nv_part=nv_part, ne_part=ne_part,
                   src_slot=src_slot, dst_local=dst_local,
                   edge_weight=edge_weight, row_ptr_local=row_ptr_local,
                   vmask=vmask, deg_padded=deg_padded,
                   weighted=g.weights is not None,
                   local_parts=local,
                   row_ptr_global=(g.row_ptrs if local is not None
                                   else None),
                   max_out_degree=int(g.out_degrees.max(initial=0)))

    @classmethod
    def build_from_file(cls, path: str, num_parts: int, parts=None,
                        vpad_align: int = 8, epad_align: int = 128,
                        starts: np.ndarray | None = None,
                        weighted: bool | None = None,
                        weight_dtype=np.int32) -> "ShardedGraph":
        """Per-host sharded load: read only ``parts``' edge slices from
        a .lux file through the native pthread-pread loader
        (lux_tpu.native.load_partition; mmap fallback) — the TPU-native
        analogue of the reference's per-partition CPU load tasks
        (reference pull_model.inl:253-320) running one process per
        node.  Only the (small) row_ptr/degree sections are read in
        full, for globally-consistent partition cuts and paddings.

        Typical multi-host use (same code on every host):

            multihost.initialize()
            mesh = multihost.global_mesh()
            sg = ShardedGraph.build_from_file(
                path, P, parts=multihost.process_parts(P))
            eng = PullEngine(sg, program, mesh=mesh)
        """
        from lux_tpu import native

        hdr = luxfmt.peek_lux(path, weighted, weight_dtype)
        # row_ptrs + degrees: small sections, read whole (mmap)
        _, row_ptrs, col_mm, w_mm, degrees = luxfmt.read_lux(
            path, weighted, weight_dtype)
        row_ptrs = np.asarray(row_ptrs)
        if degrees is not None:
            out_deg = np.asarray(degrees).astype(np.uint32)
        elif native.available():
            out_deg = native.count_degrees(path, hdr.nv, hdr.ne)
        else:
            out_deg = np.bincount(np.asarray(col_mm),
                                  minlength=hdr.nv).astype(np.uint32)

        if parts is None:
            parts = range(num_parts)
        parts = np.asarray(list(parts), np.int64)
        if starts is None:
            starts = edge_balanced_bounds(row_ptrs, num_parts)

        use_native = native.available()

        class _LazyCols:
            """Graph.col_idx stand-in that serves per-part slices from
            the native loader (falls back to the mmap view)."""

            def __getitem__(self, sl):
                lo, hi = sl.start or 0, sl.stop
                if hi <= lo:
                    return np.empty(0, np.uint32)
                if not use_native:
                    return np.asarray(col_mm[sl])
                # vertex range covering this edge slice: parts are
                # vertex-contiguous, so invert via searchsorted
                v0 = int(np.searchsorted(row_ptrs, lo, side="right"))
                v1 = min(hdr.nv, 1 + int(
                    np.searchsorted(row_ptrs, hi, side="left")))
                # weights are served from the mmap view; don't read
                # (and immediately discard) the weight bytes here
                _, cols, _w, e_lo = native.load_partition(
                    path, hdr.nv, hdr.ne, v0, v1, weighted=False)
                return cols[lo - e_lo:hi - e_lo]

        weights = None
        if hdr.has_weights:
            weights = w_mm      # mmap: sliced lazily per part
        g = Graph(nv=hdr.nv, ne=hdr.ne, row_ptrs=row_ptrs,
                  col_idx=_LazyCols(), weights=weights,
                  out_degrees=out_deg)
        return cls.build(g, num_parts, vpad_align=vpad_align,
                         epad_align=epad_align, starts=starts,
                         parts=parts)

    def sizing_row_ptr(self) -> np.ndarray:
        """row_ptr_local for ALL parts — chunk geometry (ops/tiled.py)
        must be identical on every process even when only local parts
        are materialized."""
        if self.local_parts is None:
            return self.row_ptr_local
        rp = np.asarray(self.row_ptr_global).astype(np.int64)
        out = np.zeros((self.num_parts, self.vpad + 1), np.int64)
        for p in range(self.num_parts):
            v0, v1 = int(self.starts[p]), int(self.starts[p + 1])
            ebegin = int(rp[v0 - 1]) if v0 else 0
            out[p, 1:v1 - v0 + 1] = rp[v0:v1] - ebegin
            out[p, v1 - v0 + 1:] = out[p, v1 - v0]
        return out

    # ---- push-model (src-sorted) edge view ---------------------------

    _src_sorted_cache: tuple | None = dataclasses.field(
        default=None, repr=False, compare=False)

    def _src_sorted_raw(self):
        """Per-part src-sort + unique-source compression (host, once)."""
        if self._src_sorted_cache is None:
            ids_l, off_l, dst_l, w_l = [], [], [], []
            max_deg = 0
            for r, p in enumerate(self.part_ids()):
                nep = int(self.ne_part[p])
                # the compressed index narrows edge offsets to int32
                # (src_off, and the cumsum'd off in expand_frontier);
                # safe because nep <= epad and build() rejects epad >=
                # int32 max (the ValueError guard in ShardedGraph.build)
                # global src of each real edge: src_slot is part-major
                # slot; invert the slot translation
                slot = self.src_slot[r, :nep].astype(np.int64)
                sp = slot // self.vpad
                src = self.starts[sp] + (slot - sp * self.vpad)
                order = np.argsort(src, kind="stable")
                uniq, counts = np.unique(src[order], return_counts=True)
                if counts.size:
                    max_deg = max(max_deg, int(counts.max()))
                ids_l.append(uniq.astype(np.int32))
                off_l.append(np.concatenate(
                    ([0], np.cumsum(counts))).astype(np.int32))
                dst_l.append(self.dst_local[r, :nep][order])
                w_l.append(self.edge_weight[r, :nep][order]
                           if self.weighted else None)
            self._src_sorted_cache = (ids_l, off_l, dst_l, w_l, max_deg)
        return self._src_sorted_cache

    def src_unique_max(self) -> int:
        """Max unique-source count over the materialized parts (the
        compressed source index's natural pad size)."""
        return max((len(u) for u in self._src_sorted_raw()[0]),
                   default=1) or 1

    def max_in_deg(self) -> int:
        """Max edges of one source within a part (cheap: reads the
        cached raw src-sort, no padded-array rebuild)."""
        return self._src_sorted_raw()[4]

    def src_sorted(self, s_pad: int | None = None):
        """Per-part edges re-sorted by GLOBAL source id — the dual CSR
        view the reference's push init builds on device with atomic
        degree counting (reference sssp_gpu.cu:550-607) — with a
        COMPRESSED source index: only sources with >=1 edge in the
        part are stored (sorted ids + END offsets), binary-searched at
        frontier-expansion time (engine/frontier.expand_frontier).
        This replaces the reference's nv-wide per-part row pointers
        (reference push_model.inl:321-324) — O(nv) rows per part,
        ~1.1 GB/part int64 at RMAT27 — with O(present sources) rows.

        s_pad pads the source-index dim; multi-host runs must pass a
        process-independent value >= every part's unique-source count
        (PushEngine all-gathers the max).  Default: the local max.

        Returns dict of numpy arrays:
          src_ids   int32 [R, S]    present-source GLOBAL ids, pad=nv
          src_off   int32 [R, S+1]  END offsets into the part's
                                    src-sorted edge list (pad repeats)
          ss_dst    int32 [R, epad] part-local dst, pad->vpad
          ss_weight float32 [R, epad] or None
          max_in_deg int            max edges of one source in a part
        """
        ids_l, off_l, dst_l, w_l, max_deg = self._src_sorted_raw()
        R = len(ids_l)
        need = max((len(u) for u in ids_l), default=0)
        S = max(1, need if s_pad is None else int(s_pad))
        if S < need:
            raise ValueError(f"s_pad={s_pad} < max unique sources {need}")
        src_ids = np.full((R, S), self.nv, dtype=np.int32)
        src_off = np.zeros((R, S + 1), dtype=np.int32)
        ss_dst = np.full((R, self.epad), self.vpad, dtype=np.int32)
        ss_weight = (np.zeros((R, self.epad), dtype=np.float32)
                     if self.weighted else None)
        for r in range(R):
            u, off = ids_l[r], off_l[r]
            src_ids[r, :len(u)] = u
            src_off[r, :len(u) + 1] = off
            src_off[r, len(u) + 1:] = off[-1]
            nep = len(dst_l[r])
            ss_dst[r, :nep] = dst_l[r]
            if ss_weight is not None:
                ss_weight[r, :nep] = w_l[r]
        return dict(src_ids=src_ids, src_off=src_off, ss_dst=ss_dst,
                    ss_weight=ss_weight, max_in_deg=max_deg)

    # ---- state layout conversion -------------------------------------

    def to_padded(self, x: np.ndarray) -> np.ndarray:
        """[nv, ...] user order -> [rows, vpad, ...] padded layout
        (rows = materialized parts; all of them on a full build)."""
        x = np.asarray(x)
        ids = self.part_ids()
        out = np.zeros((len(ids), self.vpad) + x.shape[1:], x.dtype)
        for r, p in enumerate(ids):
            v0, v1 = int(self.starts[p]), int(self.starts[p + 1])
            out[r, :v1 - v0] = x[v0:v1]
        return out

    def from_padded(self, x: np.ndarray) -> np.ndarray:
        """[num_parts, vpad, ...] padded layout -> [nv, ...] user order.

        Requires ALL parts' rows: on a multi-host run fetch the global
        state first (parallel.multihost.fetch_global)."""
        x = np.asarray(x)
        if x.shape[0] != self.num_parts:
            raise ValueError(
                f"from_padded needs all {self.num_parts} part rows, got "
                f"{x.shape[0]} (multi-host: fetch_global the state first)")
        out = np.empty((self.nv,) + x.shape[2:], x.dtype)
        for p in range(self.num_parts):
            v0, v1 = int(self.starts[p]), int(self.starts[p + 1])
            out[v0:v1] = x[p, :v1 - v0]
        return out

    def memory_report(self, *, exchange: str = "gather",
                      owner_slots_per_part: int | None = None,
                      owner_packed: bool | None = None,
                      push_sparse: bool = False,
                      pairs=None, pair_kdim: int = 1,
                      pair_stream: bool | None = None,
                      page_plan=None,
                      query_batch: int = 1,
                      use_mxu: bool = False,
                      mxu_tile_e: int = 512) -> dict:
        """HBM bytes for the engine edge layouts per part — the
        analogue of the reference's startup memory advisor (reference
        pagerank.cc:60-85).  (The flat oracle layout ships int32
        dst_local instead of int8 rel, +3 B/edge.)

        exchange='owner' prices the owner-side layout instead of the
        tiled one: one packed uint32 per slot (the default whenever
        vpad <= 2^25, ops/owner.OwnerLayout) or int32 src + int8 rel
        (+ f32 weight either way); owner_packed=None infers from the
        vpad bound.  owner_slots_per_part defaults to epad — a LOWER
        bound; the real count includes per-(src-part, dst-tile) chunk
        padding and lives in OwnerLayout.stats after the build
        (measured 1.15-1.5x, PERF_NOTES).

        pairs (a StackedPairPlan, typically ``engine.pairs`` — pass
        the RESIDUAL graph's report the same plan the engine holds)
        prices the pair-lane delivery: the materialized row arrays
        (rowbind + int8 rel + f32 weights + tile_pos, + row_tile for
        K-dim/SDDMM plans, ``pair_kdim`` > 1) AND the delivery
        temporaries — at the STREAMED per-block bound when streaming
        engages (the default; ops/pairs.resolve_pair_stream /
        resolve_pair_dot_stream with ``pair_stream`` forwarded), NOT
        the monolithic [Rp, 128, K] tensor that is only real when
        streaming is forced off (67.7 GB at the NetFlix shape,
        PERF_NOTES round 5/8).

        push_sparse adds the push engine's src-sorted frontier view
        (graph.src_sorted): ss_dst int32 over epad AGAIN (+ f32
        weights again) plus the compressed source index — the arrays
        that roughly DOUBLE edge memory and must be priced before any
        big-scale push run (round-4 VERDICT).  The source-index pad S
        uses the cached src-sort when available, else the min(nv-ish,
        epad) upper bound.

        use_mxu prices the MXU one-hot reduce's live intermediate
        (round 23, ops/tiled.chunk_partials): unlike the fused VPU
        masked reduce, the contraction MATERIALIZES the [C, E, W]
        int8 lane-membership matrix — one byte per (edge, lane) over
        W = 128 lanes, bounded by the streamed block
        (ops/tiled.STREAM_BLOCK_CHUNKS x ``mxu_tile_e`` edges) when
        block streaming engages.  Reported as ``mxu_temp`` and
        subtracted by the ledger-drift audit like the other
        per-iteration temporaries (audit.priced_argument_bytes) —
        the term exists so a use_mxu=True build's ledger stays
        honest, per the round-22 rule that every resident consumer
        is named.

        query_batch prices the QUERY-BATCHED state table (ROADMAP
        item 2, engine/program.py ``batch``): B > 1 makes the vertex
        term ``vpad * (5 B + 4)`` — a 4-byte label/rank plus the
        1-byte active mask per (vertex, query), plus the shared int32
        degrees (at B = 1 the legacy ``vpad * 8`` pricing is kept so
        historical reports stay comparable; pull engines carry no
        mask, so the 5 B term over-prices them by B/(4B+4) — inside
        the ledger-drift tolerance).  The owner exchange's per-
        iteration contribution accumulator also widens to ``vpad * 4
        * B`` per part — reported as ``owner_msg_bytes_per_part`` but
        NOT folded into ``total_bytes``, which prices resident
        ARGUMENT arrays (the quantity the ledger-drift audit check
        compares against XLA memory_analysis)."""
        if query_batch < 1:
            raise ValueError(f"query_batch must be >= 1, got "
                             f"{query_batch}")
        w = 4 if self.weighted else 0
        page_buf = page_temp = 0
        if page_plan is not None:
            # paged gather (ops/pagegather.py): the plan arrays
            # REPLACE the tiled/owner edge layout entirely — price
            # their actual bytes (slot_lane uint32 + rel int8 +
            # weights + row_tile + tile_pos + page_ids), plus the
            # per-iteration temporaries: the deduplicated page buffer
            # [n_pages, 128 (, K, B)] f32 AND the delivered rows —
            # vals + per-row partials, f32 [Rp, 128 (, K, B)] each
            # (the same 2x-Rp-rows term the pair path prices as
            # pair_temp; there is no streamed paged variant yet, so
            # the monolithic bound is what a big build must fit).
            # Both fold into the total like the pair temporaries; the
            # ledger-drift audit compares ARGUMENT arrays only and
            # subtracts the temp fields (audit.check_ledger).
            pp = page_plan
            resident = (pp.slot_lane.nbytes + pp.rel_dst.nbytes
                        + pp.row_tile.nbytes + pp.tile_pos.nbytes
                        + pp.page_ids.nbytes
                        + (pp.weight.nbytes
                           if pp.weight is not None else 0)
                        + (pp.vrow_src.nbytes
                           if getattr(pp, "vrow_src", None)
                           is not None else 0))
            # plan arrays lead with the part (owner: src-part) count
            plan_parts = max(1, pp.slot_lane.shape[0])
            edge_bytes = resident // plan_parts
            wide = max(1, pair_kdim) * query_batch
            page_buf = pp.n_pages * 128 * 4 * wide
            # page-major plans additionally hold the delivered
            # gather-row value buffer [Rg, 128] the virtual rows
            # take from (mode="pagemajor"; Rg = 0 on paged plans)
            page_temp = (2 * pp.Rp + getattr(pp, "Rg", 0)) \
                * 128 * 4 * wide
        elif exchange == "owner":
            slots = (self.epad if owner_slots_per_part is None
                     else int(owner_slots_per_part))
            if owner_packed is None:
                from lux_tpu.ops.owner import OwnerLayout
                owner_packed = self.vpad <= OwnerLayout.PACK_VPAD_MAX
            edge_bytes = slots * ((4 if owner_packed else 5) + w)
        else:
            # src_slot int32 + rel_dst int8 (+ f32 weights)
            edge_bytes = self.epad * (4 + 1 + w)
        sparse_bytes = 0
        if push_sparse:
            if self._src_sorted_cache is not None:
                S = self.src_unique_max()
            else:
                # a part's unique sources are bounded by min(nv, ne):
                # sources come from ANY part (nv ~ num_parts * vpad),
                # not just this one's vpad — the old min(vpad, epad)
                # under-priced exactly the multi-part big-scale fits
                # this advisor gates (~200 MB/part at RMAT25 np=4,
                # round-5 ADVICE #1)
                S = min(self.num_parts * self.vpad, self.epad)
            # src_ids + src_off int32 + ss_dst int32 (+ f32 ss_weight)
            sparse_bytes = 4 * (2 * S + 1) + self.epad * (4 + w)
        pair_bytes = pair_temp = 0
        if pairs is not None:
            from lux_tpu.ops.pairs import (PAIR_DOT_BLOCK_BYTES,
                                           PAIR_STREAM_BLOCK_BYTES,
                                           resolve_pair_dot_stream,
                                           resolve_pair_stream)
            from lux_tpu.ops.pairs import W as _PW
            Rp = int(pairs.Rp)
            wlane = _PW * 4 if pairs.weight is not None else 0
            # rowbind int32 + rel int8[128] (+ f32 weights) + tile_pos
            pair_bytes = Rp * (4 + _PW + wlane) + pairs.tile_pos.shape[1] * 4
            rows = len(self.part_ids())
            if pair_kdim > 1:
                pair_bytes += Rp * 4                       # row_tile
                streamed = resolve_pair_dot_stream(
                    pair_stream, pairs, rows, pair_kdim)
                # streamed: one slot-block of tiles/dots/partials;
                # monolithic: the lax.map-stacked per-row partials
                # PLUS the delivered tile values (XLA materializes
                # both — measured 2x the partials tensor alone,
                # PERF_NOTES round-8 memory_analysis table)
                pair_temp = (PAIR_DOT_BLOCK_BYTES if streamed
                             else 2 * Rp * _PW * pair_kdim * 4)
            else:
                streamed = resolve_pair_stream(pair_stream, pairs)
                # monolithic: delivered f32 value rows + row partials
                pair_temp = (PAIR_STREAM_BLOCK_BYTES if streamed
                             else 2 * Rp * _PW * 4)
        # state f32 + deg int32 (vmask derives from a scalar on
        # device); batched: 4-byte state + 1-byte active per column
        if query_batch == 1:
            vert_bytes = self.vpad * (4 + 4)
        else:
            vert_bytes = self.vpad * (5 * query_batch + 4)
        owner_msg = (self.vpad * 4 * query_batch
                     if exchange == "owner" else 0)
        mxu_temp = 0
        if use_mxu and page_plan is None:
            from lux_tpu.ops.tiled import STREAM_BLOCK_CHUNKS
            # [C, E, 128] int8 one-hot, one byte per (edge, lane);
            # the streamed block bound caps the live chunks
            live_edges = min(self.epad,
                             STREAM_BLOCK_CHUNKS * int(mxu_tile_e))
            mxu_temp = live_edges * 128
        # named per-part decomposition (round 22, lux_tpu/memwatch.py):
        # the unified runtime byte ledger folds these terms alongside
        # the serving/live consumers, and its NumPy oracle re-derives
        # each term independently — total_bytes IS num_parts x the
        # bitwise sum of terms, never a separately-maintained number
        terms = {
            "edge": edge_bytes,
            "push_sparse": sparse_bytes,
            "pair": pair_bytes,
            "pair_temp": pair_temp,
            "page_buffer": page_buf,
            "page_temp": page_temp,
            "mxu_temp": mxu_temp,
            "vertex": vert_bytes,
        }
        per_part = sum(terms.values())
        return {
            "num_parts": self.num_parts,
            "query_batch": query_batch,
            "edge_bytes_per_part": edge_bytes,
            "push_sparse_bytes_per_part": sparse_bytes,
            "pair_bytes_per_part": pair_bytes,
            "pair_temp_bytes_per_part": pair_temp,
            "page_buffer_bytes_per_part": page_buf,
            "page_temp_bytes_per_part": page_temp,
            "mxu_temp_bytes_per_part": mxu_temp,
            "vertex_bytes_per_part": vert_bytes,
            "owner_msg_bytes_per_part": owner_msg,
            "terms_per_part": terms,
            "total_bytes": self.num_parts * per_part,
        }

    def telemetry_header(self, **memory_kwargs) -> dict:
        """Graph shape + the startup memory advisor's per-part HBM
        estimate, as one JSON-serializable dict — the payload of the
        event log's ``header`` event (lux_tpu/telemetry.py), so every
        events JSONL is self-describing.  ``memory_kwargs`` forward to
        ``memory_report`` (exchange=, push_sparse=, ...)."""
        return {
            "nv": int(self.nv), "ne": int(self.ne),
            "weighted": bool(self.weighted),
            "num_parts": int(self.num_parts),
            "vpad": int(self.vpad), "epad": int(self.epad),
            "memory": {k: int(v) for k, v in
                       self.memory_report(**memory_kwargs).items()
                       if not isinstance(v, dict)},
        }
