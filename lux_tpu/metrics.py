"""Streaming serving metrics: counters, gauges, mergeable log-linear
histograms — the SLO measurement substrate for the serving tier.

The reference has no serving story at all (its observability is
-verbose wall clocks, reference sssp_gpu.cu:513-518); lux_tpu's
serving front-end (lux_tpu/serve.py, round 14) emitted raw per-query
``query_done`` events but nothing AGGREGATED — no percentiles, no SLO
accounting, no way to regression-gate "latency SLOs held" (ROADMAP
item 5).  This module is the aggregation layer, deliberately
host-side and O(1)-memory per series so it can ride a long-lived
serving process:

- ``Counter`` / ``Gauge``: monotone totals and last-value samples.
- ``Histogram``: an HDR-style LOG-LINEAR histogram — values bucket by
  (power-of-two octave, ``HIST_SUB`` linear sub-buckets per octave),
  so memory is FIXED (at most ``HIST_BUCKETS`` sparse cells per
  series, never proportional to the observation count) and the
  quantile error is BOUNDED: a nearest-rank quantile read returns
  the containing bucket's midpoint, whose relative error is at most
  ``QUANTILE_REL_ERR`` = 1/HIST_SUB (half a bucket width; pinned by
  test against a NumPy ``inverted_cdf`` oracle,
  tests/test_metrics.py).  Histograms MERGE (bucket-wise add —
  associative and lossless, proven by test), which is what lets a
  load harness combine per-kind series into one distribution and a
  future multi-replica tier combine per-replica snapshots.
- ``Registry``: the label-aware series store.  Series are keyed by
  (name, sorted labels) — per-kind / per-tenant breakdowns are just
  labels — and ``get-or-create`` is thread-safe (the serving queue
  is fed from submitter threads).
- Exposure, two ways: ``Registry.snapshot()`` is a JSON-ready dict
  (each histogram carries count/sum/min/max, p50/p90/p99 AND its
  sparse bucket cells, so a reader can re-merge or cross-audit), and
  ``emit_snapshot()`` publishes it as a ``metrics_snapshot``
  telemetry event riding the existing EventLog — rendered and
  CROSS-AUDITED against the raw query_done stream by
  scripts/events_summary.py.  ``prometheus_text()`` renders the
  Prometheus text exposition (cumulative ``le`` buckets), served by
  ``python -m lux_tpu.metrics -serve PORT`` over stdlib http only.

Hot-path contract: metrics are HOST-side and segment-boundary only —
never inside engine device code or fused loop bodies (the same
rationale as the audited callback-in-loop ban; machine-checked by
scripts/lint_lux.py's ``hot-path-metrics`` check).
"""

from __future__ import annotations

import math
import threading

SCHEMA = 1

# Log-linear histogram geometry (PINNED: merging and the error bound
# are only meaningful between identically-bucketed series).
HIST_SUB = 32                 # linear sub-buckets per power-of-two octave
HIST_EXP_MIN = -27            # lowest octave lower edge = 2**-27 (~7.5 ns)
HIST_EXP_MAX = 21             # highest octave upper edge = 2**21 (~24 days)
HIST_BUCKETS = (HIST_EXP_MAX - HIST_EXP_MIN) * HIST_SUB
# A quantile read returns the containing bucket's midpoint; the bucket
# width is lo/HIST_SUB, so |read - true| <= lo/(2*HIST_SUB) <=
# true/(2*HIST_SUB).  1/HIST_SUB is the published (doubled, safe)
# bound — pinned against the NumPy oracle in tests/test_metrics.py.
QUANTILE_REL_ERR = 1.0 / HIST_SUB

SNAPSHOT_QUANTILES = (0.5, 0.9, 0.99)


def bucket_index(v: float) -> int:
    """Bucket of a positive finite value (values at/under the range
    floor clamp to bucket 0, past the ceiling to the last bucket —
    the error bound holds only inside the range, which spans ~7.5 ns
    to ~24 days and covers any latency a serving tier can observe)."""
    if not v > 0.0 or v != v or v == float("inf"):
        return 0
    m, e = math.frexp(v)            # v = m * 2**e, m in [0.5, 1)
    octave = e - 1                  # v in [2**octave, 2**(octave+1))
    if octave < HIST_EXP_MIN:
        return 0
    if octave >= HIST_EXP_MAX:
        return HIST_BUCKETS - 1
    j = int((2.0 * m - 1.0) * HIST_SUB)     # linear within the octave
    j = min(max(j, 0), HIST_SUB - 1)
    return (octave - HIST_EXP_MIN) * HIST_SUB + j


def bucket_lo(idx: int) -> float:
    octave = HIST_EXP_MIN + idx // HIST_SUB
    j = idx % HIST_SUB
    return math.ldexp(1.0 + j / HIST_SUB, octave)


def bucket_hi(idx: int) -> float:
    octave = HIST_EXP_MIN + idx // HIST_SUB
    j = idx % HIST_SUB
    return math.ldexp(1.0 + (j + 1) / HIST_SUB, octave)


def bucket_mid(idx: int) -> float:
    return 0.5 * (bucket_lo(idx) + bucket_hi(idx))


class Counter:
    """Monotone total.  ``inc`` rejects negative deltas — a counter
    that can go down is a gauge, and mixing the two breaks burn-rate
    arithmetic silently.  Updates are lock-protected: series are fed
    from submitter threads concurrently with the drain thread, and
    an unlocked read-modify-write would lose increments at a GIL
    switch."""

    kind = "counter"

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self.value += n


class Gauge:
    """Last-value sample (queue depth, occupancy, burn rate)."""

    kind = "gauge"

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        # lockcheck: allow(guarded-field) last-value store is ONE
        # GIL-atomic assignment; set_max/inc/dec lock because they
        # read-modify-write
        self.value = float(v)

    def set_max(self, v: float) -> None:
        """Ratchet: keep the LARGEST value ever set — the watermark
        idiom (peak resident bytes, lux_tpu/memwatch.py round 22).
        Lock-protected like inc/dec: two boundary threads racing a
        plain read-compare-set could regress the peak."""
        v = float(v)
        with self._lock:
            if v > self.value:
                self.value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """Mergeable log-linear histogram (see module docstring for the
    geometry and the pinned error bound).  Memory: a sparse dict of
    at most HIST_BUCKETS cells plus four exact scalars — O(1) in the
    observation count."""

    kind = "histogram"

    def __init__(self):
        self.buckets: dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        idx = bucket_index(v)
        with self._lock:
            self.buckets[idx] = self.buckets.get(idx, 0) + 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def _state(self):
        """Consistent (buckets copy, count, sum, min, max) — reads
        must not race a concurrent observe mid-update."""
        with self._lock:
            return (dict(self.buckets), self.count, self.sum,
                    self.min, self.max)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile -> the containing bucket's midpoint
        (relative error <= QUANTILE_REL_ERR inside the bucket range).
        None on an empty histogram."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        buckets, count, _sum, _mn, _mx = self._state()
        if count == 0:
            return None
        rank = max(1, math.ceil(q * count))
        seen = 0
        for idx in sorted(buckets):
            seen += buckets[idx]
            if seen >= rank:
                return bucket_mid(idx)
        return bucket_mid(max(buckets))         # unreachable guard

    def mean(self) -> float | None:
        """Exact mean (sum/count — the scalars are exact even though
        the buckets quantize); None on an empty histogram.  The fleet
        dispatcher's projected-wait estimator input
        (lux_tpu/fleet.py admission control)."""
        with self._lock:
            return self.sum / self.count if self.count else None

    def merge(self, other: "Histogram") -> "Histogram":
        """Bucket-wise sum — associative and commutative (proven by
        test), the multi-series / multi-replica combine."""
        out = Histogram()
        mins, maxs = [], []
        for src in (self, other):
            buckets, count, s, mn, mx = src._state()
            for idx, n in buckets.items():
                out.buckets[idx] = out.buckets.get(idx, 0) + n
            out.count += count
            out.sum += s
            if mn is not None:
                mins.append(mn)
            if mx is not None:
                maxs.append(mx)
        out.min = min(mins) if mins else None
        out.max = max(maxs) if maxs else None
        return out

    def snapshot_entry(self) -> dict:
        """The JSON-ready body of one histogram series in a
        metrics_snapshot event: exact count/sum/min/max, the standard
        quantiles, and the sparse bucket cells (str keys — JSON
        objects key on strings) so readers can re-merge and
        events_summary can cross-audit count == sum(buckets)."""
        buckets, count, s, mn, mx = self._state()
        out = {"count": count, "sum": round(s, 9),
               "min": mn, "max": mx,
               "buckets": {str(i): n
                           for i, n in sorted(buckets.items())}}
        for q in SNAPSHOT_QUANTILES:
            if count == 0:
                out[f"p{int(q * 100)}"] = None
                continue
            rank = max(1, math.ceil(q * count))
            seen = 0
            for idx in sorted(buckets):
                seen += buckets[idx]
                if seen >= rank:
                    out[f"p{int(q * 100)}"] = round(bucket_mid(idx),
                                                    9)
                    break
        return out

    @classmethod
    def from_snapshot(cls, entry: dict) -> "Histogram":
        """Rebuild a mergeable histogram from a snapshot entry (the
        loadgen path: read snapshots back, merge per-kind series)."""
        h = cls()
        h.buckets = {int(k): int(n)
                     for k, n in (entry.get("buckets") or {}).items()}
        h.count = int(entry.get("count", sum(h.buckets.values())))
        h.sum = float(entry.get("sum") or 0.0)
        h.min = entry.get("min")
        h.max = entry.get("max")
        return h


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Registry:
    """Label-aware series store.  get-or-create is thread-safe; a
    name re-registered as a different series type is a hard error
    (silent type punning would corrupt every consumer)."""

    def __init__(self):
        self._series: dict[tuple, object] = {}
        self._types: dict[str, str] = {}
        self._lock = threading.Lock()

    def _get(self, kind: str, name: str, labels: dict):
        key = (name, _label_key(labels))
        with self._lock:
            prev = self._types.get(name)
            if prev is not None and prev != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {prev}, "
                    f"cannot re-register as {kind}")
            self._types[name] = kind
            s = self._series.get(key)
            if s is None:
                s = _KINDS[kind]()
                self._series[key] = s
            return s

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def series(self):
        """[(name, labels dict, series object)] in sorted order."""
        with self._lock:
            items = sorted(self._series.items())
        return [(name, dict(lk), s) for (name, lk), s in items]

    # -- exposure ------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready snapshot: the body of a ``metrics_snapshot``
        telemetry event (schema + one list per series type)."""
        counters, gauges, hists = [], [], []
        for name, labels, s in self.series():
            if s.kind == "counter":
                counters.append({"name": name, "labels": labels,
                                 "value": s.value})
            elif s.kind == "gauge":
                gauges.append({"name": name, "labels": labels,
                               "value": s.value})
            else:
                hists.append({"name": name, "labels": labels,
                              **s.snapshot_entry()})
        return {"schema": SCHEMA, "counters": counters,
                "gauges": gauges, "histograms": hists}

    def emit_snapshot(self, **extra) -> dict | None:
        """Publish the snapshot as a ``metrics_snapshot`` event on
        the ACTIVE telemetry handle (no-op on the null handle) —
        periodic snapshots riding the existing EventLog are how a
        load harness or a postmortem reads the serving tier back."""
        from lux_tpu import telemetry
        return telemetry.current().emit("metrics_snapshot",
                                        **self.snapshot(), **extra)

    def prometheus_text(self) -> str:
        """Prometheus text exposition (0.0.4): counters and gauges as
        plain samples, histograms as CUMULATIVE ``le`` buckets
        (non-empty cells + ``+Inf``) with ``_sum``/``_count`` —
        scrapeable by any Prometheus-compatible collector."""
        by_name: dict[str, list] = {}
        for name, labels, s in self.series():
            by_name.setdefault(name, []).append((labels, s))
        lines = []
        for name in sorted(by_name):
            entries = by_name[name]
            lines.append(f"# TYPE {name} {entries[0][1].kind}")
            for labels, s in entries:
                if s.kind in ("counter", "gauge"):
                    lines.append(f"{name}{_fmt_labels(labels)} "
                                 f"{_fmt_num(s.value)}")
                    continue
                buckets, count, total, _mn, _mx = s._state()
                cum = 0
                for idx in sorted(buckets):
                    cum += buckets[idx]
                    le = dict(labels, le=_fmt_num(bucket_hi(idx)))
                    lines.append(f"{name}_bucket{_fmt_labels(le)} "
                                 f"{cum}")
                inf = dict(labels, le="+Inf")
                lines.append(f"{name}_bucket{_fmt_labels(inf)} "
                             f"{count}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_num(total)}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{count}")
        return "\n".join(lines) + "\n"


def _fmt_num(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(f'{k}="{_escape(str(v))}"'
                    for k, v in sorted(labels.items()))
    return "{" + body + "}"


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-default registry (what ``-serve`` exposes)."""
    return _DEFAULT


# ---------------------------------------------------------------------
# stdlib-http exposition endpoint

def serve_http(registry: Registry, port: int, host: str = "127.0.0.1"):
    """Build (not start) an HTTP server exposing ``/metrics`` as
    Prometheus text — stdlib ``http.server`` only, by contract.
    Returns the server; call ``serve_forever()`` (the CLI does) or
    drive it from a thread (the tests do, with port 0)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):            # noqa: N802 — http.server API
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = registry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):    # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.metrics",
        description="Prometheus text endpoint over the process "
                    "default metrics registry (stdlib http only)")
    ap.add_argument("-serve", type=int, default=None, metavar="PORT",
                    help="serve /metrics on PORT until interrupted")
    ap.add_argument("-host", default="127.0.0.1")
    ap.add_argument("-demo", action="store_true",
                    help="populate the registry with a demo series "
                         "set first (so a fresh endpoint renders "
                         "something scrapeable)")
    args = ap.parse_args(argv)

    reg = default_registry()
    if args.demo:
        rngv = [0.001 * (i % 37 + 1) for i in range(200)]
        for kind in ("sssp", "pagerank"):
            reg.counter("serve_queries_total", kind=kind).inc(100)
            h = reg.histogram("serve_latency_seconds", kind=kind)
            for v in rngv:
                h.observe(v)
    if args.serve is None:
        print(reg.prometheus_text(), end="")
        return 0
    srv = serve_http(reg, args.serve, host=args.host)
    print(f"# serving /metrics on http://{args.host}"
          f":{srv.server_address[1]}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
