"""Durable admission journal — the serving tier's crash ledger
(round 24, self-healing fleet; ROADMAP item 2).

The fleet dispatcher (lux_tpu/fleet.py) holds admission state —
which queries were admitted, which retired — only in memory, so a
whole-fleet crash silently loses every admitted-but-unretired query:
the caller was told "admitted" and nobody will ever answer.  This
module gives admission the same durability bar the mutation WAL
(lux_tpu/livegraph.MutationLog) gives graph state:

* Every ADMITTED query appends one fixed 48-byte CRC-chained record
  (format.py owns the "LUXJ" header: magic + version + nv) and fsyncs
  — durability is per record, the admit is durable before the query
  is queued.
* Every retirement (answer OR late shed) appends a RETIRE record
  closing the entry — the persisted qid set is what makes recovery
  retirement exactly-once.
* ``FleetServer.recover`` replays the journal after a crash and
  re-dispatches every admitted-unretired query at its ORIGINAL
  admission epoch (livegraph.graph_at reproduces the view), so a
  recovered answer is the answer the crashed fleet owed.

The corruption contract mirrors MutationLog record for record: a
torn tail (strict prefix of one record — what a power loss
mid-append leaves) is RECOVERABLE and truncated by ``replay``; a
full-size record failing the chain CRC is rot of a possibly-
acknowledged append and refuses typed (``crc_chain``); ADMIT/RETIRE
pairing is validated at rest (``admit_dup`` / ``retire_unmatched`` /
``retire_dup``) so scripts/fsck_lux.py and the recovery path can
never disagree on validity.

Record layout (12 little-endian uint32 words, 48 bytes):

  w0   record kind: 1=ADMIT, 2=RETIRE
  w1   qid
  ADMIT:  w2 query-kind code (index into serve.KINDS)
          w3 source  (0xFFFFFFFF = personalized/reset query)
          w4 admission epoch (0xFFFFFFFF = static graph)
          w5 deadline in ms (0 = no deadline)
          w6 priority (two's-complement int32)
          w7-w8  tenant, UTF-8, zero-padded to 8 bytes
          w9-w10 first 8 bytes of the blake2b reset digest (zeros
                 when the query has no reset vector)
  RETIRE: w2 cause: 1=answered, 2=shed; w3..w10 zero
  w11  crc = chained_crc32(first 44 bytes, prev record's crc); the
       chain seeds from the header's CRC, so a re-headered journal
       cannot re-validate.

A reset VECTOR is nv floats and cannot live in a fixed record — the
journal stores its digest.  Recovery re-dispatches a reset query
only when the caller re-supplies the vector for that digest
(``FleetServer.recover(resets=...)``); otherwise the entry is closed
as a typed shed, never silently dropped.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np

from lux_tpu import format as luxfmt
from lux_tpu.checkpoint import chained_crc32

# record kinds (w0)
JREC_ADMIT = 1
JREC_RETIRE = 2

# retirement causes (w2 of a RETIRE record).  "answered" closes with
# a delivered Response; "shed" closes with a typed AdmissionError
# AFTER admission (late shed: deadline / retries / recovery without
# the reset vector) — both are terminal, the pairing audit treats
# them identically.
RETIRE_ANSWERED = 1
RETIRE_SHED = 2
_CAUSE_NAMES = {RETIRE_ANSWERED: "answered", RETIRE_SHED: "shed"}
_CAUSE_CODES = {v: k for k, v in _CAUSE_NAMES.items()}

_U32_NONE = 0xFFFFFFFF   # source/epoch "absent" sentinel
TENANT_BYTES = 8
DIGEST_BYTES = 8


def _emit(kind: str, **fields):
    from lux_tpu import telemetry
    telemetry.current().emit(kind, **fields)


class AdmissionJournalError(RuntimeError):
    """The admission journal failed verification.  Carries ``path``,
    ``check`` (torn_tail / crc_chain / record_kind / qid_order /
    admit_dup / retire_unmatched / retire_dup / tenant_size /
    journal_exists) and ``detail`` — the same typed-diagnosis shape
    as livegraph.MutationLogError, consumed by scripts/fsck_lux.py
    (exit 2).  ``torn_tail`` is the RECOVERABLE class: replay
    truncates it; every other check is hard corruption that must
    never re-dispatch."""

    def __init__(self, path: str, check: str, detail: str):
        super().__init__(
            f"{path}: admission journal [{check}] — {detail}")
        self.path = path
        self.check = check
        self.detail = detail


def reset_digest(reset) -> bytes:
    """The journal's 8-byte reset-vector fingerprint (blake2b over
    the float32 bytes — same buffer rule as serve.AnswerCache's
    128-bit cache key, truncated to the record's fixed field)."""
    buf = np.ascontiguousarray(reset, np.float32).tobytes()
    return hashlib.blake2b(buf, digest_size=DIGEST_BYTES).digest()


def _kind_code(kind: str) -> int:
    from lux_tpu.serve import KINDS
    return KINDS.index(kind)


def _kind_name(code: int, path: str, off: int) -> str:
    from lux_tpu.serve import KINDS
    if not 0 <= code < len(KINDS):
        raise AdmissionJournalError(
            path, "record_kind",
            f"ADMIT record at byte {off} names query-kind code "
            f"{code} outside {tuple(range(len(KINDS)))} "
            f"({KINDS}) with a VALID chain CRC — journal written "
            f"by a newer/foreign build, refusing to re-dispatch")
    return KINDS[code]


@dataclasses.dataclass(frozen=True)
class JournalRecord:
    """One decoded ADMIT entry (RETIREs are folded into the scan's
    retired map, not surfaced as records)."""
    qid: int
    kind: str
    source: int | None
    epoch: int | None
    deadline_s: float | None
    priority: int
    tenant: str
    digest: bytes | None       # 8-byte reset digest, None = source query


def _encode_admit(path: str, qid: int, kind: str,
                  source: int | None, epoch: int | None,
                  deadline_s: float | None, priority: int,
                  tenant: str, digest: bytes | None) -> np.ndarray:
    tb = tenant.encode("utf-8")
    if len(tb) > TENANT_BYTES:
        raise AdmissionJournalError(
            path, "tenant_size",
            f"tenant {tenant!r} is {len(tb)} UTF-8 bytes; the "
            f"journal record holds {TENANT_BYTES} — journalled "
            f"fleets need short tenant ids (the quota key must "
            f"survive the crash byte-for-byte, not truncated)")
    tb = tb.ljust(TENANT_BYTES, b"\x00")
    db = (digest or b"").ljust(DIGEST_BYTES, b"\x00")
    if deadline_s is None:
        dl_ms = 0
    else:
        # round UP so a tiny positive deadline cannot collapse into
        # the no-deadline sentinel
        dl_ms = max(1, int(np.ceil(float(deadline_s) * 1000.0)))
    words = np.zeros(11, luxfmt.V_DTYPE)
    words[0] = JREC_ADMIT
    words[1] = qid
    words[2] = _kind_code(kind)
    words[3] = _U32_NONE if source is None else int(source)
    words[4] = _U32_NONE if epoch is None else int(epoch)
    words[5] = min(dl_ms, _U32_NONE - 1)
    words[6] = priority & 0xFFFFFFFF
    words[7:9] = np.frombuffer(tb, luxfmt.V_DTYPE)
    words[9:11] = np.frombuffer(db, luxfmt.V_DTYPE)
    return words


def _decode_admit(words, path: str, off: int) -> JournalRecord:
    source = int(words[3])
    epoch = int(words[4])
    dl_ms = int(words[5])
    prio = int(words[6])
    tenant = words[7:9].tobytes().rstrip(b"\x00").decode("utf-8")
    digest = words[9:11].tobytes()
    return JournalRecord(
        qid=int(words[1]),
        kind=_kind_name(int(words[2]), path, off),
        source=None if source == _U32_NONE else source,
        epoch=None if epoch == _U32_NONE else epoch,
        deadline_s=None if dl_ms == 0 else dl_ms / 1000.0,
        priority=prio if prio < 2 ** 31 else prio - 2 ** 32,
        tenant=tenant,
        digest=None if digest == b"\x00" * DIGEST_BYTES else digest)


class AdmissionJournal:
    """The CRC-chained append-only admission log (module docstring).

    One instance owns an open append handle; each ``append_*``
    writes one 48-byte record and fsyncs — the admit is durable
    before the query enters a queue, the retire before the answer
    is acknowledged as final.  ``replay`` is a classmethod: verify
    the chain + ADMIT/RETIRE pairing, truncate a torn tail (emitting
    a ``journal_truncate`` telemetry event), raise typed
    AdmissionJournalError on anything that cannot be a torn
    append."""

    def __init__(self, path: str, nv: int,
                 version: int = luxfmt.JOURNAL_VERSION,
                 _resume: tuple | None = None):
        self.path = path
        self.nv = int(nv)
        self.version = int(version)
        self.records = 0        # records appended THROUGH this handle
        if _resume is None:
            header = luxfmt.pack_journal_header(self.nv,
                                                version=self.version)
            try:
                fd = os.open(path,
                             os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                             0o644)
            except FileExistsError:
                # restart-after-crash is the situation the journal
                # exists for — refuse typed, pointing at recovery
                raise AdmissionJournalError(
                    path, "journal_exists",
                    "an admission journal already exists at this "
                    "path — a fresh journal would orphan its "
                    "admitted-unretired entries; use "
                    "FleetServer.recover(..., journal_path=path) to "
                    "replay it, or remove the file to start "
                    "over") from None
            self._f = os.fdopen(fd, "wb")
            self._f.write(header)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._crc = chained_crc32(header)
        else:
            size, crc = _resume
            self._f = open(path, "r+b")
            self._f.seek(size)
            self._crc = crc

    # -- append side ---------------------------------------------------

    def _append(self, record: bytes) -> None:
        self._f.write(record)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._crc = int(np.frombuffer(record, luxfmt.V_DTYPE)[11])
        self.records += 1

    def buffer_bytes(self) -> int:
        """Host bytes the open append handle accounts for in the
        unified byte ledger (lux_tpu/memwatch.py): header plus every
        record appended through THIS handle — same accounting rule
        as MutationLog.buffer_bytes."""
        return (luxfmt.JOURNAL_HEADER_SIZE
                + self.records * luxfmt.JOURNAL_RECORD_SIZE)

    def _seal(self, words: np.ndarray) -> bytes:
        body = words.tobytes()
        crc = chained_crc32(body, self._crc)
        return body + np.array([crc], luxfmt.V_DTYPE).tobytes()

    def pack_admit(self, req) -> bytes:
        """Pack one ADMIT record for a serve.Request against the
        CURRENT chain position (the fault-injection hook needs the
        exact bytes the append would write)."""
        digest = (reset_digest(req.reset)
                  if req.reset is not None else None)
        return self._seal(_encode_admit(
            self.path, req.qid, req.kind, req.source, req.epoch,
            req.deadline_s, req.priority, req.tenant, digest))

    def pack_retire(self, qid: int, cause: str) -> bytes:
        words = np.zeros(11, luxfmt.V_DTYPE)
        words[0] = JREC_RETIRE
        words[1] = qid
        words[2] = _CAUSE_CODES[cause]
        return self._seal(words)

    def append_admit(self, req) -> None:
        self._append(self.pack_admit(req))

    def append_retire(self, qid: int, cause: str = "answered") -> None:
        self._append(self.pack_retire(qid, cause))

    def write_torn(self, record: bytes) -> None:
        """Fault-injection hook: persist a STRICT PREFIX of
        ``record`` — what a power loss mid-append leaves on disk —
        and fsync it so the tear is really there for replay."""
        self._f.write(record[:len(record) // 2])
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    # -- replay / verify side ------------------------------------------

    @classmethod
    def scan(cls, path: str, nv: int | None = None):
        """Verify the whole journal WITHOUT modifying it.  Returns
        (open_records, retired, header_nv, torn_bytes):
        ``open_records`` are the admitted-unretired entries in admit
        order, ``retired`` maps closed qid -> cause name,
        ``torn_bytes`` is a recoverable torn tail length (0 =
        clean); hard corruption raises AdmissionJournalError.
        scripts/fsck_lux.py's journal leg and ``replay`` both run
        through here so the checker and the recovery path can never
        disagree on validity."""
        opens, retired, hnv, tail, _crc, _ver = cls._scan(path, nv=nv)
        return opens, retired, hnv, tail

    @classmethod
    def _scan(cls, path: str, nv: int | None = None):
        with open(path, "rb") as f:
            blob = f.read()
        head = blob[:luxfmt.JOURNAL_HEADER_SIZE]
        hnv, ver = luxfmt.read_journal_header(path, nv=nv, head=head)
        crc = chained_crc32(head)
        open_by_qid: dict[int, JournalRecord] = {}
        retired: dict[int, str] = {}
        off = luxfmt.JOURNAL_HEADER_SIZE
        R = luxfmt.JOURNAL_RECORD_SIZE
        last_qid = -1
        bad_at = None
        while off + R <= len(blob):
            raw = blob[off:off + R]
            words = np.frombuffer(raw, luxfmt.V_DTYPE)
            want = chained_crc32(raw[:R - 4], crc)
            if int(words[11]) != want:
                bad_at = off
                break
            rec, qid = int(words[0]), int(words[1])
            if rec == JREC_ADMIT:
                if qid in open_by_qid or qid in retired:
                    raise AdmissionJournalError(
                        path, "admit_dup",
                        f"ADMIT record at byte {off} re-admits qid "
                        f"{qid} with a VALID chain CRC — qids are "
                        f"issued once; the journal is corrupt or "
                        f"spliced")
                if qid <= last_qid:
                    raise AdmissionJournalError(
                        path, "qid_order",
                        f"ADMIT record at byte {off} carries qid "
                        f"{qid} after qid {last_qid} — the monotone "
                        f"qid counter never goes backwards; the "
                        f"journal is corrupt or spliced")
                last_qid = qid
                open_by_qid[qid] = _decode_admit(words, path, off)
            elif rec == JREC_RETIRE:
                cause = int(words[2])
                if cause not in _CAUSE_NAMES:
                    raise AdmissionJournalError(
                        path, "record_kind",
                        f"RETIRE record at byte {off} carries cause "
                        f"{cause} outside "
                        f"{tuple(_CAUSE_NAMES)} with a VALID chain "
                        f"CRC — journal written by a newer/foreign "
                        f"build, refusing to re-dispatch")
                if qid in retired:
                    raise AdmissionJournalError(
                        path, "retire_dup",
                        f"RETIRE record at byte {off} re-retires "
                        f"qid {qid} — exactly-once retirement is "
                        f"the journal's contract; a double close "
                        f"means the writer double-answered or the "
                        f"journal is corrupt")
                if qid not in open_by_qid:
                    raise AdmissionJournalError(
                        path, "retire_unmatched",
                        f"RETIRE record at byte {off} closes qid "
                        f"{qid} that no ADMIT opened — the journal "
                        f"is corrupt or spliced")
                del open_by_qid[qid]
                retired[qid] = _CAUSE_NAMES[cause]
            else:
                raise AdmissionJournalError(
                    path, "record_kind",
                    f"record at byte {off} has kind {rec} outside "
                    f"({JREC_ADMIT}, {JREC_RETIRE}) with a VALID "
                    f"chain CRC — journal written by a "
                    f"newer/foreign build, refusing to re-dispatch")
            crc = int(words[11])
            off += R
        tail = len(blob) - off
        if bad_at is not None:
            # same writer model as MutationLog._scan: a torn append
            # leaves only a STRICT PREFIX (reported as ``tail``); a
            # FULL-SIZE bad-CRC record is rot of a possibly-fsync-
            # acknowledged admit/retire — refusing beats silently
            # forgetting an admitted query or re-answering a
            # retired one
            behind = len(blob) - bad_at - R
            what = (f"with {behind} byte(s) of further records "
                    f"behind it — mid-file corruption"
                    if behind else
                    "at full record size — corruption of a "
                    "possibly-acknowledged final record")
            raise AdmissionJournalError(
                path, "crc_chain",
                f"record at byte {bad_at} fails the CRC chain "
                f"{what}, not a torn append; refusing to "
                f"re-dispatch")
        opens = sorted(open_by_qid.values(), key=lambda r: r.qid)
        return opens, retired, hnv, tail, crc, ver

    @classmethod
    def replay(cls, path: str, nv: int | None = None):
        """Crash-recovery entry: scan, TRUNCATE a torn tail in place
        (the torn record was never acknowledged — the pre-append
        state is the correct durable state), and return
        (open_records, retired, truncated_bytes, resumable
        AdmissionJournal open at the end)."""
        opens, retired, hnv, torn, crc, ver = cls._scan(path, nv=nv)
        good = os.path.getsize(path) - torn
        if torn:
            with open(path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())
            _emit("journal_truncate", path=path, torn_bytes=int(torn),
                  open=len(opens), retired=len(retired))
        journal = cls(path, hnv, version=ver, _resume=(good, crc))
        return opens, retired, torn, journal
