"""Runtime memory observatory (round 22): measured occupancy trail,
watermark-vs-ledger drift, and memory-aware admission.

Every other scarce resource in the framework is observed and
regression-gated — time (lux_tpu/observe.py), wire bytes
(lux_tpu/comms.py), SLOs (lux_tpu/metrics.py) — but memory was priced
only STATICALLY (graph.memory_report + audit's compile-time
ledger-drift check): nothing measured what a running engine, serving
tier, or live graph actually occupies, and ROADMAP item 3 names state
bytes, not query count, as the millions-of-users wall.  This module
is the runtime half, in three pillars:

**Pillar 1 — the measured occupancy trail.**  :class:`MemoryTrail`
samples at SEGMENT BOUNDARIES only (riding the existing
``on_segment``/``on_boundary`` hooks — O(1) host cost, never inside a
fused loop; the same placement discipline as the boundary metrics and
the chaos kill plan).  Where the backend exposes it,
``device.memory_stats()`` gives the real per-device live/peak bytes
and the sample is grade-labeled ``measured``; on CPU and through the
tunnel the sample is the unified byte ledger's model (plus host RSS
as a side channel) and wears grade ``modeled`` — exactly observe.py's
fingerprint-grade discipline, so a modeled number can never
masquerade as a measured one.  The trail keeps the per-process peak
watermark and a bounded live-bytes series, emits ``mem_sample`` (via
telemetry.emit_sampled, throttleable) and ``mem_watermark`` (on every
new peak) events — rendered by scripts/events_summary.py, drawn as a
counter track by lux_tpu/tracing.py, and captured by the flight
recorder so a fatal leaves its memory trail in FLIGHT.json.

**Pillar 2 — the unified per-replica byte ledger + drift verdicts.**
:class:`MemoryLedger` folds the static program pricing
(graph.memory_report through audit.report_kwargs — the SAME kwargs
derivation the compile-time check uses, so the two ledgers cannot
diverge) together with the serving/live consumers rounds 17-21 built
but never priced: AnswerCache bytes (an exact internal ledger that
had a budget but no gauge), the live-graph delta blocks, the WAL
append handle, the lazily-built live-edge multiset, and checkpoint
staging.  ``total_bytes`` is the bitwise sum of named integer terms —
tests re-derive every term independently in NumPy and match exactly.
Measured (or memory_analysis-modeled) peak outside the documented
tolerance of the ledger is a typed :class:`MemoryDriftError`
(warn/error modes); every bench line carries the verdict as a ``mem``
digest and scripts/check_bench.py rejects lines from a drifting
build.

Tolerance rationale: MEM_TOL mirrors audit.check_ledger's 0.5 — the
ledger's epad/vpad-based terms are LOWER bounds (XLA chunk/tile
padding sits above them, measured 1.1-1.3x at bench shapes), and the
comparison is only meaningful on graphs dense enough that edge arrays
dominate padding (audit module docstring has the measured table).

**Pillar 3 — memory-aware admission + OOM forecasting.**
:func:`projected_admission_bytes` prices what admitting B more
columns costs (batch state + answer-cache headroom) — the same
projected-resource pattern as the fleet's deadline check — and
lux_tpu/fleet.py sheds with the typed ``memory`` reason when the
projection crosses the per-replica budget.  :class:`MemoryForecaster`
is the CompactionScheduler-style time-to-full policy over the
occupancy growth rate: a pure, fake-clock-injectable ``decide()``
surfacing a burn-rate gauge (``mem_burn``) and a ``mem_pressure``
event BEFORE DeltaFullError/OOM, so the trail always shows the
warning preceding the shed (scripts/events_summary.py audits exactly
that ordering).

``python -m lux_tpu.memwatch`` is the repo-wide acceptance command
(tier-1-gated like ``python -m lux_tpu.comms``): ledger + drift
verdicts over the audit matrix configs, a serving-tier consumer
cross-check, and a deliberately-overdrifting synthetic program that
MUST raise the typed error.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time

import numpy as np

# sample grades (observe.py's fingerprint-grade discipline): a
# ``measured`` sample came from device.memory_stats(); a ``modeled``
# one from the unified ledger / XLA memory_analysis.  There is no
# third grade — a number is one or the other, explicitly.
GRADE_MEASURED = "measured"
GRADE_MODELED = "modeled"

# watermark-vs-ledger drift tolerance (module docstring rationale;
# mirrors audit.check_ledger's compile-time tolerance)
MEM_TOL = 0.5

# below this many priced argument bytes the comparison is padding-
# dominated, not consumer-dominated (the tiny audit-matrix shapes
# measure 2-3x pure chunk/tile padding — the same reason
# audit.matrix_configs drift-checks only its dense ledger configs);
# bench digests below the floor record the ledger but no verdict
MEM_CHECK_FLOOR_BYTES = 128 * 1024

# admission projection: answer-cache headroom per admitted query —
# one full nv-length answer copy (int64/f64 worst case, the
# AnswerCache's put() copy)
ANSWER_BYTES_PER_VERTEX = 8

# ledger terms that price per-iteration TEMPORARIES, not resident
# argument arrays — subtracted for the memory_analysis comparison
# (audit.check_ledger's subtraction, same term set)
TEMP_TERMS = ("graph_pair_temp", "graph_page_buffer",
              "graph_page_temp", "graph_mxu_temp")


class MemoryDriftError(RuntimeError):
    """Measured (or memory_analysis-modeled) peak bytes drifted
    outside the stated tolerance of the unified byte ledger — either
    the pricing has rotted or an UNPRICED consumer is resident.
    Carries where/grade/measured/ledger/ratio/tol; ``mode="warn"``
    reports instead of raising (the bench digest records the verdict
    either way and check_bench rejects drifting lines)."""

    check = "mem-drift"

    def __init__(self, where: str, grade: str, measured: int,
                 ledger: int, ratio: float, tol: float):
        super().__init__(
            f"{where}: {grade} peak {measured} bytes vs unified "
            f"ledger {ledger} bytes (ratio {ratio:.2f}) outside the "
            f"stated tolerance x{1 + tol:.2f} — an unpriced consumer "
            f"is resident, or graph.memory_report / the serving "
            f"consumer terms have drifted from reality")
        self.where = where
        self.grade = grade
        self.measured = int(measured)
        self.ledger = int(ledger)
        self.ratio = float(ratio)
        self.tol = float(tol)


# ---------------------------------------------------------------------
# host / device byte sources

def host_rss_bytes() -> int:
    """This process's resident set size in bytes (Linux /proc; 0 when
    unavailable).  A SIDE CHANNEL next to the modeled device bytes —
    never summed into them: on CPU the graph arrays already live
    inside RSS, so adding the two would double-count."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def device_memory_stats():
    """Per-device ``memory_stats()`` where the backend exposes them:
    ``[(device_repr, {"bytes_in_use": ..., "peak_bytes_in_use": ...,
    ...}), ...]`` — or None on backends without them (CPU, and the
    tunnel's axon devices; debt ``hbm-watermark-on-device`` collects
    the real trail on the first canonical TPU session).  Only stats
    dicts carrying ``bytes_in_use`` count: a backend returning an
    empty dict must not grade a sample ``measured``."""
    import jax

    out = []
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-backend API surface
            return None
        if not stats or "bytes_in_use" not in stats:
            return None
        out.append((str(d), dict(stats)))
    return out or None


# checkpoint staging (lux_tpu/checkpoint.py notes the host-assembled
# global-view bytes of its latest save here — a transient consumer
# the ledger prices at its last observed size)
_STAGING_BYTES = 0


def note_staging(nbytes: int) -> None:
    """Record the byte size of the most recent checkpoint staging
    buffer (called by checkpoint._timed_save)."""
    global _STAGING_BYTES
    _STAGING_BYTES = int(nbytes)


def staging_bytes() -> int:
    return _STAGING_BYTES


# ---------------------------------------------------------------------
# pillar 2: the unified per-replica byte ledger

class MemoryLedger:
    """Named integer byte terms -> one auditable total.

    ``terms`` maps a consumer name to its priced bytes;
    ``total_bytes`` is their bitwise sum (tests re-derive each term
    independently and match exactly — the ledger can never disagree
    with its own decomposition).  ``argument_bytes`` subtracts the
    per-iteration temporary terms, giving the resident-ARGUMENT
    quantity XLA ``memory_analysis`` reports (audit.check_ledger's
    apples-to-apples rule)."""

    def __init__(self, terms: dict, where: str = ""):
        self.terms = {k: int(v) for k, v in terms.items()}
        self.where = where

    @property
    def total_bytes(self) -> int:
        return sum(self.terms.values())

    def argument_bytes(self) -> int:
        return self.total_bytes - sum(self.terms.get(t, 0)
                                      for t in TEMP_TERMS)

    def __repr__(self):
        return (f"MemoryLedger({self.where or '?'}: "
                f"{self.total_bytes} B over {len(self.terms)} terms)")

    @classmethod
    def for_engine(cls, eng, where: str | None = None
                   ) -> "MemoryLedger":
        """The static program ledger of one engine: memory_report's
        named per-part terms (scaled by num_parts) plus the program
        state-width / extra-array corrections — derived through
        audit.report_kwargs, the SAME kwargs the compile-time drift
        check uses.  Self-checking: the argument-side sum is asserted
        bitwise equal to audit.priced_argument_bytes, so this ledger
        and the audit's can never silently diverge."""
        from lux_tpu import audit

        P = eng.sg.num_parts
        rep = eng.sg.memory_report(**audit.report_kwargs(eng))
        terms = {f"graph_{k}": P * v
                 for k, v in rep["terms_per_part"].items() if v}
        sb = getattr(eng.program, "state_bytes", None)
        if sb:
            # K-vector programs (colfilter) carry state_bytes per
            # vertex where the graph term prices scalar f32
            terms["program_state"] = P * eng.sg.vpad * (sb - 4)
        xa = getattr(eng.program, "extra_arrays", None)
        if xa is not None:
            terms["program_extra"] = sum(
                np.asarray(v).nbytes for v in xa(eng.sg).values())
        led = cls(terms, where or type(eng).__name__)
        priced = audit.priced_argument_bytes(eng)
        assert led.argument_bytes() == priced, (
            f"memwatch/audit ledger divergence: {led.argument_bytes()}"
            f" != {priced} — report_kwargs or the correction terms "
            f"changed on one side only")
        return led

    @classmethod
    def for_server(cls, server, where: str | None = None
                   ) -> "MemoryLedger":
        """The unified PER-REPLICA ledger of a serving tier
        (serve.Server, or one fleet replica via
        :func:`replica_ledger`): every built runner engine's static
        terms (prefixed by kind) + the previously-unpriced dynamic
        consumers — AnswerCache bytes, live-graph delta blocks /
        history / multiset / WAL, checkpoint staging."""
        terms: dict = {}
        runners = getattr(server, "_runners", None) or {}
        for kind, runner in sorted(runners.items()):
            eng = getattr(runner, "eng", None)
            if eng is None:
                continue
            for k, v in cls.for_engine(eng).terms.items():
                terms[f"{kind}_{k}"] = v
        terms.update(consumer_terms(
            cache=getattr(server, "cache", None),
            live=getattr(server, "live", None)))
        return cls(terms, where or type(server).__name__)


def consumer_terms(cache=None, live=None) -> dict:
    """The dynamic (serving/live) consumer terms on their own — the
    piece fleet admission re-prices at every boundary without
    touching the static engine terms."""
    terms: dict = {}
    if cache is not None:
        # the AnswerCache keeps an EXACT internal byte ledger
        # (updated in put/_pop) — the unified ledger adopts it as a
        # term and the registry gauge mirrors it
        terms["cache"] = int(cache.bytes)
    if live is not None:
        terms.update(live.memory_terms())
    if _STAGING_BYTES:
        terms["checkpoint_staging"] = _STAGING_BYTES
    return terms


def replica_ledger(fleet, rep) -> MemoryLedger:
    """One fleet replica's unified ledger: its built runners' static
    terms + the tier-shared dynamic consumers (cache and live graph
    are SHARED across in-process replicas, so each replica's budget
    must absorb them — the conservative accounting; a subprocess
    replica prices only what the parent can see: zero engine terms,
    the shared consumers)."""
    terms: dict = {}
    for kind, runner in sorted(getattr(rep, "_runners", {}).items()):
        for k, v in MemoryLedger.for_engine(runner.eng).terms.items():
            terms[f"{kind}_{k}"] = v
    terms.update(consumer_terms(cache=fleet.cache, live=fleet.live))
    return MemoryLedger(terms, f"replica:{rep.name}")


# ---------------------------------------------------------------------
# pillar 2: drift verdicts + the bench digest

def drift_verdict(measured: int, ledger_bytes: int, *,
                  grade: str, where: str = "",
                  tol: float = MEM_TOL) -> dict:
    """One watermark-vs-ledger comparison -> a JSON-serializable
    verdict dict (the bench line's ``mem`` digest payload).  ``ok``
    is the tolerance test; ``errors`` counts 1 when it fails —
    scripts/check_bench.py rejects metric lines whose digest carries
    errors, so a published number can never ride a drifting build."""
    measured = int(measured)
    ledger_bytes = int(ledger_bytes)
    ratio = measured / max(1, ledger_bytes)
    ok = 1.0 / (1.0 + tol) <= ratio <= 1.0 + tol
    return {"where": where, "grade": grade,
            "peak_bytes": measured, "ledger_bytes": ledger_bytes,
            "ratio": round(ratio, 4), "tol": tol,
            "errors": 0 if ok else 1, "warnings": 0}


def check_drift(measured: int, ledger: MemoryLedger, *,
                grade: str, where: str = "", tol: float = MEM_TOL,
                mode: str = "error") -> dict:
    """drift_verdict + the typed-error policy: a failing verdict
    raises :class:`MemoryDriftError` under ``mode="error"`` and
    warns (warnings module) under ``mode="warn"`` — the verdict dict
    is returned either way so callers can attach it as a digest."""
    import warnings as _warnings

    v = drift_verdict(measured, ledger.total_bytes, grade=grade,
                      where=where or ledger.where, tol=tol)
    if v["errors"]:
        err = MemoryDriftError(v["where"], grade, measured,
                               ledger.total_bytes, v["ratio"], tol)
        if mode == "error":
            raise err
        _warnings.warn(str(err), stacklevel=2)
    return v


def engine_verdict(eng, *, ledger: MemoryLedger | None = None,
                   tol: float = MEM_TOL, mode: str = "warn",
                   where: str | None = None) -> dict:
    """The runtime drift verdict of one engine build: compile the
    step (AOT — nothing executes), read XLA memory_analysis argument
    bytes (grade ``modeled``: the compiler's word, not a device
    watermark), and compare against the unified ledger's
    argument-side total.  Backends without AOT stats return a
    skipped digest (warnings=1) instead of inventing a number."""
    where = where or type(eng).__name__
    ledger = ledger or MemoryLedger.for_engine(eng, where)
    jitted, args_thunk = eng.audit_programs()["step"]
    try:
        ma = jitted.lower(*args_thunk()).compile().memory_analysis()
    except Exception as e:  # noqa: BLE001 — backend without AOT stats
        return {"where": where, "grade": GRADE_MODELED,
                "ledger_bytes": ledger.total_bytes, "tol": tol,
                "errors": 0, "warnings": 1,
                "skipped": f"memory_analysis unavailable: {e}"[:200]}
    if ma is None or not getattr(ma, "argument_size_in_bytes", 0):
        return {"where": where, "grade": GRADE_MODELED,
                "ledger_bytes": ledger.total_bytes, "tol": tol,
                "errors": 0, "warnings": 1,
                "skipped": "memory_analysis empty"}
    measured = int(ma.argument_size_in_bytes)
    v = drift_verdict(measured, ledger.argument_bytes(),
                      grade=GRADE_MODELED, where=where, tol=tol)
    if v["errors"] and mode == "error":
        raise MemoryDriftError(where, GRADE_MODELED, measured,
                               ledger.argument_bytes(), v["ratio"],
                               tol)
    return v


def bench_digest(eng, *, trail: "MemoryTrail | None" = None,
                 consumers: dict | None = None,
                 tol: float = MEM_TOL) -> dict:
    """The metric line's ``mem`` field: the engine's runtime drift
    verdict, widened by the dynamic consumer terms when a serving
    tier is on the line and by the trail's measured watermark when a
    real device trail exists (grade ``measured`` then; the verdict
    compares the watermark against the full ledger total instead of
    the compiler's argument bytes).  The consumer terms are HOST
    bytes (cache copies, WAL buffer, delta blocks) — they widen the
    MEASURED comparison (a device+host watermark sees them) but
    never the modeled one (XLA memory_analysis prices program
    arguments only; billing host consumers against it manufactures
    drift).  The digest reports them separately as
    ``consumer_bytes`` either way, so the line's bill is complete."""
    eng_ledger = MemoryLedger.for_engine(eng)
    ledger = MemoryLedger(dict(eng_ledger.terms), eng_ledger.where)
    if consumers:
        ledger.terms.update({k: int(v)
                             for k, v in consumers.items()})
    if trail is not None and trail.grade == GRADE_MEASURED \
            and trail.peak_bytes:
        v = drift_verdict(trail.peak_bytes, ledger.total_bytes,
                          grade=GRADE_MEASURED,
                          where=ledger.where, tol=tol)
    else:
        v = engine_verdict(eng, ledger=eng_ledger, tol=tol,
                           mode="warn")
    if consumers:
        v["consumer_bytes"] = sum(int(x) for x in consumers.values())
    if v.get("errors") \
            and eng_ledger.argument_bytes() < MEM_CHECK_FLOOR_BYTES:
        # padding-dominated shape: record the ledger, withhold the
        # verdict (module constant rationale) — the drift check
        # stays meaningful only where consumers dominate padding
        v["errors"] = 0
        v["warnings"] = v.get("warnings", 0) + 1
        v["skipped"] = "below check floor (padding-dominated shape)"
    return v


# ---------------------------------------------------------------------
# pillar 3: the time-to-full forecaster

class MemoryForecaster:
    """CompactionScheduler-style pure policy over the occupancy
    growth rate: ``record`` takes (monotonic time, live bytes) at
    each boundary sample, ``decide`` projects time-to-full against
    the per-replica byte budget.  Everything is clock-injectable and
    side-effect-free — the trail (or the fleet) emits the
    ``mem_pressure`` event off the returned decision, once per
    crossing (hysteresis: re-armed when the projection recovers)."""

    def __init__(self, budget_bytes: int, *, horizon_s: float = 5.0,
                 window: int = 8, clock=time.monotonic):
        if budget_bytes <= 0:
            raise ValueError(f"budget_bytes must be > 0, got "
                             f"{budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.horizon_s = float(horizon_s)
        self.clock = clock
        self.samples: collections.deque = collections.deque(
            maxlen=max(2, int(window)))
        self.pressed = False         # hysteresis latch
        self.pressures = 0           # crossings ever signalled

    def record(self, live_bytes: int, t: float | None = None) -> dict:
        """Append one observation and return ``decide()``'s verdict
        for it.  ``fired`` is True only on the ok->pressure crossing
        — the caller emits exactly one event per crossing."""
        self.samples.append((self.clock() if t is None else float(t),
                             int(live_bytes)))
        d = self.decide()
        was = self.pressed
        self.pressed = d["action"] == "pressure"
        d["fired"] = self.pressed and not was
        if d["fired"]:
            self.pressures += 1
        return d

    def rate_bytes_per_s(self) -> float:
        """Growth rate over the window (first-to-last secant — robust
        to per-boundary jitter, zero until two samples span time)."""
        if len(self.samples) < 2:
            return 0.0
        (t0, b0), (t1, b1) = self.samples[0], self.samples[-1]
        if t1 <= t0:
            return 0.0
        return (b1 - b0) / (t1 - t0)

    def time_to_full_s(self) -> float:
        """Projected seconds until live bytes reach the budget at the
        current growth rate (inf when flat/shrinking or empty)."""
        if not self.samples:
            return float("inf")
        live = self.samples[-1][1]
        head = self.budget_bytes - live
        if head <= 0:
            return 0.0
        rate = self.rate_bytes_per_s()
        if rate <= 0:
            return float("inf")
        return head / rate

    def burn(self) -> float:
        """Burn-rate gauge (``mem_burn``): the fraction of the
        REMAINING budget the current growth rate consumes per
        horizon — > 1.0 means the budget is gone within one horizon
        (the SLO burn-rate idiom, applied to bytes)."""
        if not self.samples:
            return 0.0
        live = self.samples[-1][1]
        head = max(1, self.budget_bytes - live)
        return max(0.0, self.rate_bytes_per_s()) \
            * self.horizon_s / head

    def decide(self) -> dict:
        """The pure policy, ordered like CompactionScheduler.decide:
        no samples -> ok; over budget -> pressure(over_budget);
        projected full within the horizon -> pressure(time_to_full);
        else ok.  The dict carries the justifying economics — the
        ``mem_pressure`` event's payload, audited for required
        fields by scripts/events_summary.py."""
        if not self.samples:
            return {"action": "ok", "reason": "empty",
                    "live_bytes": 0,
                    "budget_bytes": self.budget_bytes,
                    "rate_bytes_per_s": 0.0,
                    "time_to_full_s": None,
                    "horizon_s": self.horizon_s, "burn": 0.0}
        live = self.samples[-1][1]
        ttf = self.time_to_full_s()
        base = {"live_bytes": live,
                "budget_bytes": self.budget_bytes,
                "rate_bytes_per_s": round(self.rate_bytes_per_s(), 2),
                "time_to_full_s": (None if ttf == float("inf")
                                   else round(ttf, 4)),
                "horizon_s": self.horizon_s,
                "burn": round(self.burn(), 4)}
        if live >= self.budget_bytes:
            return {"action": "pressure", "reason": "over_budget",
                    **base}
        if ttf <= self.horizon_s:
            return {"action": "pressure", "reason": "time_to_full",
                    **base}
        return {"action": "ok", "reason": "headroom", **base}


# ---------------------------------------------------------------------
# pillar 1: the boundary sampler

@dataclasses.dataclass(frozen=True)
class MemorySample:
    t: float
    where: str
    grade: str
    live_bytes: int
    peak_bytes: int
    host_rss_bytes: int


class MemoryTrail:
    """Per-process (or per-replica) occupancy trail fed at segment
    boundaries.  ``sample`` is O(1) host work: one memory_stats (or
    ledger callable) read, one RSS read, bounded deque append, gauge
    sets — NEVER called inside a fused loop (the boundary hooks are
    the only call sites, the same placement contract as
    serve._boundary_metrics).

    ``bytes_fn`` supplies the modeled live bytes (typically a unified
    ledger total thunk) when the backend has no memory_stats; without
    either, the sample degrades to host RSS — still grade
    ``modeled``, with ``source`` saying which fallback fed it."""

    def __init__(self, *, bytes_fn=None, metrics=None,
                 replica: str | None = None,
                 budget_bytes: int | None = None,
                 horizon_s: float = 5.0, clock=time.monotonic,
                 emit_every: int = 1, keep: int = 256):
        self.bytes_fn = bytes_fn
        self.metrics = metrics
        self.replica = replica
        self.clock = clock
        self.emit_every = max(1, int(emit_every))
        self.samples: collections.deque = collections.deque(
            maxlen=max(1, int(keep)))
        self.peak_bytes = 0
        self.grade: str | None = None
        self.count = 0
        self.forecaster = (None if budget_bytes is None else
                           MemoryForecaster(budget_bytes,
                                            horizon_s=horizon_s,
                                            clock=clock))

    def _labels(self) -> dict:
        return {} if self.replica is None \
            else {"replica": self.replica}

    def sample(self, where: str = "") -> MemorySample:
        from lux_tpu import telemetry

        t = self.clock()
        stats = device_memory_stats()
        if stats is not None:
            grade, source = GRADE_MEASURED, "memory_stats"
            live = sum(s["bytes_in_use"] for _, s in stats)
            dev_peak = max(s.get("peak_bytes_in_use", 0)
                           for _, s in stats)
        elif self.bytes_fn is not None:
            grade, source = GRADE_MODELED, "ledger"
            live, dev_peak = int(self.bytes_fn()), 0
        else:
            grade, source = GRADE_MODELED, "rss"
            live, dev_peak = host_rss_bytes(), 0
        rss = host_rss_bytes()
        self.grade = grade
        new_peak = max(live, dev_peak)
        rose = new_peak > self.peak_bytes
        if rose:
            self.peak_bytes = new_peak
        s = MemorySample(t=t, where=where, grade=grade,
                         live_bytes=live, peak_bytes=self.peak_bytes,
                         host_rss_bytes=rss)
        self.samples.append(s)
        self.count += 1
        telemetry.emit_sampled(
            "mem_sample", every=self.emit_every, where=where,
            grade=grade, source=source, live_bytes=live,
            peak_bytes=self.peak_bytes, host_rss_bytes=rss,
            **self._labels())
        if rose:
            # watermarks are never throttled: the peak series IS the
            # drift verdict's measured side
            telemetry.current().emit(
                "mem_watermark", where=where, grade=grade,
                peak_bytes=self.peak_bytes, live_bytes=live,
                **self._labels())
        if self.metrics is not None:
            m = self.metrics
            m.gauge("mem_live_bytes", **self._labels()).set(live)
            m.gauge("mem_peak_bytes",
                    **self._labels()).set_max(self.peak_bytes)
        if self.forecaster is not None:
            d = self.forecaster.record(live, t=t)
            if self.metrics is not None:
                self.metrics.gauge("mem_burn",
                                   **self._labels()).set(d["burn"])
            if d["fired"]:
                telemetry.current().emit(
                    "mem_pressure", where=where, grade=grade,
                    reason=d["reason"], live_bytes=d["live_bytes"],
                    budget_bytes=d["budget_bytes"],
                    rate_bytes_per_s=d["rate_bytes_per_s"],
                    time_to_full_s=d["time_to_full_s"],
                    horizon_s=d["horizon_s"], burn=d["burn"],
                    **self._labels())
        return s

    def snapshot(self) -> dict:
        """JSON-serializable trail summary (flight recorder /
        postmortem surface)."""
        return {"grade": self.grade, "samples": self.count,
                "peak_bytes": self.peak_bytes,
                "replica": self.replica,
                "series": [dataclasses.asdict(s)
                           for s in list(self.samples)[-32:]]}


# ---------------------------------------------------------------------
# pillar 3: the admission projection

def column_state_bytes(eng) -> int:
    """Per-COLUMN resident state of one batched serving engine: the
    4-byte label/rank + 1-byte active mask per (vertex, column) the
    query_batch pricing adds (graph.memory_report: vpad * 5 per
    column per part; pull engines carry no mask — the 5 B bound
    over-prices them by 1 B/vertex, conservative in the safe
    direction for admission)."""
    return int(eng.sg.num_parts) * int(eng.sg.vpad) * 5


def projected_admission_bytes(current_bytes: int, *, batch: int,
                              column_bytes: int,
                              answer_bytes: int = 0) -> int:
    """Projected resident bytes AFTER admitting ``batch`` more
    columns: the current unified-ledger total + the batch's state
    columns + the answer-cache headroom their retirements will copy
    in (one nv-length answer per query).  The delta blocks are
    preallocated at capacity and already priced in full by the
    ledger, so mutation headroom needs no extra term.  Same
    projected-resource shape as fleet._projected_wait: project the
    cost of saying yes, shed typed when it crosses the budget."""
    return int(current_bytes) \
        + max(0, int(batch)) * (int(column_bytes) + int(answer_bytes))


# ---------------------------------------------------------------------
# repo-wide acceptance (python -m lux_tpu.memwatch; tier-1-gated)

def _fmt_mb(b: int) -> str:
    return f"{b / 1e6:8.2f} MB"


def run_repo_memwatch(tol: float = MEM_TOL, out=None) -> int:
    """Ledger + drift verdicts over the audit matrix configs, the
    serving-tier consumer cross-check, and the synthetic-overdrift
    inversion.  Returns the number of failures (0 = green)."""
    import sys

    from lux_tpu import audit

    out = out or sys.stdout
    failures = 0
    print(f"{'config':34} {'grade':8} {'ledger':>12} "
          f"{'measured':>12} {'ratio':>6}  verdict", file=out)
    for label, build, ledger_cfg in audit.matrix_configs():
        eng = build()
        led = MemoryLedger.for_engine(eng, label)
        v = engine_verdict(eng, ledger=led, tol=tol, mode="warn")
        if v.get("skipped"):
            line = f"skipped ({v['skipped'][:40]})"
        elif not ledger_cfg:
            # audit.check_ledger's rule, verbatim: the tolerance test
            # is only meaningful on graphs dense enough that edges
            # dominate padding — tiny matrix configs measure 2-10x
            # pure chunk/tile padding (audit module docstring), so
            # they get the ledger PRINTED but not the verdict
            line = "unchecked (padding-dominated shape)"
            v["errors"] = 0
        elif v["errors"]:
            line = "DRIFT"
            failures += 1
        else:
            line = "ok"
        print(f"{label:34} {v['grade']:8} "
              f"{_fmt_mb(led.total_bytes):>12} "
              f"{_fmt_mb(v.get('peak_bytes', 0)):>12} "
              f"{v.get('ratio', 0):6.2f}  {line}", file=out)

    failures += _serving_check(tol, out)
    failures += _overdrift_check(tol, out)
    return failures


def _serving_check(tol: float, out) -> int:
    """The serving-tier leg: a real Server with cache + live graph,
    boundary-sampled through a MemoryTrail; the dynamic consumer
    terms are cross-checked against their measured sources EXACTLY
    (the cache's internal byte ledger and the delta arrays' real
    nbytes — these two have no padding slack, so the tolerance is
    zero), and the trail must have sampled at every boundary."""
    import tempfile

    from lux_tpu import livegraph, serve
    from lux_tpu.graph import Graph

    rng = np.random.default_rng(0)
    nv, ne = 128, 512
    g = Graph.from_edges(rng.integers(0, nv, ne),
                         rng.integers(0, nv, ne), nv)
    with tempfile.TemporaryDirectory() as td:
        lv = livegraph.LiveGraph(g, capacity=32,
                                 wal_path=os.path.join(td, "wal"))
        srv = serve.Server(g, batch=2, live=lv, cache=True)
        trail = MemoryTrail(
            bytes_fn=lambda: MemoryLedger.for_server(srv).total_bytes)
        srv.mem = trail
        srv.mutate(rng.integers(0, nv, 4), rng.integers(0, nv, 4))
        for kind in ("sssp", "pagerank"):
            srv.submit(kind, source=int(rng.integers(nv)))
        srv.run()
        # one post-drain sample: the last retirement's cache put
        # lands AFTER the final segment boundary, so the watermark
        # must absorb it here before the ledger comparison
        trail.sample("final")
        led = MemoryLedger.for_server(srv, "serving")
        fails = 0
        # exact consumer cross-checks (no padding slack -> tol 0)
        delta = (lv.d_src.nbytes + lv.d_dst.nbytes + lv.d_w.nbytes
                 + lv.d_kind.nbytes + lv.d_epoch.nbytes)
        checks = [
            ("cache term == AnswerCache.bytes",
             led.terms.get("cache", 0) == srv.cache.bytes),
            ("live_delta term == delta arrays nbytes",
             led.terms.get("live_delta", 0) == delta),
            ("live_wal term == header + records",
             led.terms.get("live_wal", 0)
             == lv._wal.buffer_bytes()),
            ("trail sampled at boundaries", trail.count > 0),
            ("trail grade labeled",
             trail.grade in (GRADE_MEASURED, GRADE_MODELED)),
            ("watermark >= final live bytes",
             trail.peak_bytes >= led.total_bytes
             or trail.grade == GRADE_MEASURED),
        ]
        for name, ok in checks:
            print(f"{'serving:' + name:76} "
                  f"{'ok' if ok else 'FAIL'}", file=out)
            fails += 0 if ok else 1
        lv.close()
        return fails


def _overdrift_check(tol: float, out) -> int:
    """The inversion: a deliberately-overdrifting synthetic program —
    a ledger missing a large consumer term (exactly the failure mode
    the observatory exists to catch) — MUST raise the typed error;
    green means it raised."""
    led = MemoryLedger({"graph_edge": 1_000_000}, "synthetic")
    measured = 4_000_000        # 4x: an unpriced consumer resident
    try:
        check_drift(measured, led, grade=GRADE_MODELED,
                    where="synthetic-overdrift", tol=tol,
                    mode="error")
    except MemoryDriftError as e:
        print(f"{'synthetic-overdrift raises MemoryDriftError':76} "
              f"ok (ratio {e.ratio:.1f})", file=out)
        return 0
    print(f"{'synthetic-overdrift raises MemoryDriftError':76} "
          f"FAIL (no error raised)", file=out)
    return 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lux_tpu.memwatch",
        description="Repo-wide runtime memory acceptance: unified "
                    "byte ledgers + watermark-vs-ledger drift "
                    "verdicts over the audit matrix configs, the "
                    "serving-tier consumer cross-check, and the "
                    "synthetic overdrift inversion.")
    ap.add_argument("-tol", type=float, default=MEM_TOL,
                    help=f"drift tolerance (default {MEM_TOL}; "
                         f"ratio must stay within [1/(1+tol), "
                         f"1+tol])")
    args = ap.parse_args(argv)
    failures = run_repo_memwatch(tol=args.tol)
    if failures:
        print(f"memwatch: {failures} FAILURE(S)")
        return 1
    print("memwatch: all configs green")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
