"""In-loop run telemetry: structured events + device-side counters.

The reference's only observability is ``-verbose`` wall clocks and
per-part phase prints (reference sssp_gpu.cu:513-518,
pagerank.cc:108-118); nothing a tool can consume, and nothing visible
INSIDE a run.  This module is the shared telemetry layer the engines,
the segmented drivers (segmented.py / checkpoint.py), the resilience
supervisor (resilience.py), the CLI and bench.py all emit into:

- ``EventLog``: a structured JSONL event sink (one JSON object per
  line: ``{"t": ..., "kind": ..., ...}``).  Segment start/stop with
  measured rates, checkpoint save/resume, classified retries, outlier
  discards and duration-budget decisions all become events instead of
  ad-hoc prints — round 9 adds the guarded-execution events:
  ``health`` (per-run watchdog digest), ``health_trip`` (the
  diagnosis of a tripped watchdog: checks, iteration, part) and
  ``checkpoint_fallback`` (a corrupt newest generation replaced by
  ``.prev``); round 11 adds the elastic-recovery trail
  (lux_tpu/resilience.py, heartbeat.py): ``topology_fault`` (a
  TOPOLOGY-classified failure, handled or not), ``mesh_shrink`` (the
  decision: from/to device count, lost devices — or the heartbeat
  protocol's from/to process count), ``replace`` (a checkpoint
  written at one device count resumed on another), ``budget_reset``
  (the duration budget's learned rate discarded on a topology
  change) and ``straggler`` (a live-but-behind heartbeat peer).
  ``scripts/events_summary.py`` renders a log into the
  reference-style loadTime/compTime/updateTime table and
  ``scripts/check_bench.py`` validates the schema.
- ``IterStats``: the host-side accumulator for DEVICE-SIDE iteration
  counters.  Engines accumulate per-iteration scalars *inside* their
  fused fori_loop/while_loop (push: frontier size + frontier out-edges
  relaxed per iteration; pull: state residual + changed-vertex count)
  into fixed-shape ``[stats_cap]`` buffers, fetched ONCE per run or
  segment boundary — a few KB independent of graph size, the same
  O(1)-style discipline as ``timing.fence``.  The hot loop gains no
  host syncs and no extra gathers.
- a contextvar-scoped ``Telemetry`` handle (``use()``/``current()``)
  so the cross-cutting run paths (CLI supervised runs, bench configs,
  checkpointed segments) light up without threading parameters
  through every signature.  The default is a null handle: emitting is
  a no-op and engines build their counter-free programs.

Counter semantics (what the buffers mean, engine by engine):

- push classic (``PushEngine.converge_stats``): ``frontier[i]`` is the
  global active count AFTER iteration i — exactly the series the
  stepwise ``-verbose`` path prints; ``edges[i]`` is the out-edge
  count of the frontier ENTERING iteration i (the relax work done by
  that iteration, full-graph out-degrees even when pair-lane delivery
  splits the dense arrays).
- push delta-stepping: ``frontier[i]`` is the bucket-front size
  entering relax step i (the series ``timed_phases`` reports; bucket
  advances relax nothing and are not iterations), ``edges[i]`` the
  front's out-edges.
- pull (``PullEngine.run_stats`` / ``run_until_stats``):
  ``residual[i]`` is the max-abs state change of iteration i (the
  same scalar ``run_until`` converges on), ``changed[i]`` the number
  of vertices whose state changed.
"""

from __future__ import annotations

import binascii
import contextlib
import contextvars
import dataclasses
import json
import os
import time

SCHEMA = 1

# One id per PROCESS, minted at import: heartbeat drills append
# multiple processes' events into one shared file, and wall clocks
# ("t") skew across hosts while monotonic clocks ("tm") only order
# within a process — (session, pid) is the merge key that makes the
# combined log unambiguous (scripts/events_summary.py groups on it).
# The observatory's calibration fingerprint (lux_tpu/observe.py)
# embeds the same id, so a bench metric line, its event trail and its
# PERFLEDGER records all name the same session.
_SESSION = binascii.hexlify(os.urandom(6)).decode()


def session_id() -> str:
    """This process's 12-hex-char telemetry session id."""
    return _SESSION

# engines size their counter buffers with this unless overridden;
# int32+uint32 per entry -> 32 KB fetched per run at the default
DEFAULT_STATS_CAP = 4096


class EventLog:
    """Append-only structured event sink.

    Events are always kept in memory (``self.events``); with ``path``
    set, each event is also written immediately as one JSON line (so a
    crashed run still leaves its trail on disk)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.events: list[dict] = []
        self._f = open(path, "a") if path else None

    def emit(self, kind: str, **fields) -> dict:
        # tm (monotonic) orders events WITHIN a process; t (wall)
        # only roughly aligns processes.  pid+session disambiguate
        # multi-process logs sharing one file (heartbeat drills).
        ev = {"t": round(time.time(), 6),
              "tm": round(time.monotonic(), 6),
              "pid": os.getpid(), "session": _SESSION,
              "kind": str(kind), **fields}
        self.events.append(ev)
        if self._f is not None:
            self._f.write(json.dumps(ev) + "\n")
            self._f.flush()
        return ev

    def counts(self) -> dict:
        """{kind: occurrences} over everything emitted so far."""
        out: dict[str, int] = {}
        for ev in self.events:
            out[ev["kind"]] = out.get(ev["kind"], 0) + 1
        return out

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class IterStats:
    """Host-side accumulator for device-side per-iteration counters.

    ``extend_push``/``extend_pull`` append one segment's fetched
    counter buffers (the single per-boundary fetch); ``begin_run``
    resets, so one-shot timed helpers record only their LAST timed
    run while segmented drivers accumulate across segments."""

    def __init__(self):
        self.kind: str | None = None
        self.frontier: list[int] = []
        self.edges: list[int] = []
        self.residual: list[float] = []
        self.changed: list[int] = []
        self.truncated = False

    def __len__(self):
        return len(self.frontier) if self.kind == "push" \
            else len(self.residual)

    def begin_run(self) -> None:
        self.kind = None
        self.frontier, self.edges = [], []
        self.residual, self.changed = [], []
        self.truncated = False

    def _fetch(self, buf, n: int):
        import numpy as np

        from lux_tpu.timing import fetch
        arr = np.asarray(fetch(buf))
        if n > arr.shape[0]:
            self.truncated = True
        return arr[:min(int(n), arr.shape[0])]

    def extend_push(self, frontier_buf, edges_buf, n: int) -> None:
        """Append ``n`` iterations from a push engine's counter
        buffers (frontier int32 [cap], edges uint32 [cap])."""
        self.kind = "push"
        self.frontier += [int(x) for x in self._fetch(frontier_buf, n)]
        self.edges += [int(x) for x in self._fetch(edges_buf, n)]

    def extend_pull(self, residual_buf, changed_buf, n: int) -> None:
        """Append ``n`` iterations from a pull engine's counter
        buffers (residual float32 [cap], changed uint32 [cap])."""
        self.kind = "pull"
        self.residual += [float(x) for x in self._fetch(residual_buf, n)]
        self.changed += [int(x) for x in self._fetch(changed_buf, n)]

    def summary(self) -> dict | None:
        """Compact digest for event logs / bench JSON lines /
        resilience.RunReport."""
        if self.kind is None:
            return None
        out = {"kind": self.kind, "iters": len(self),
               "truncated": bool(self.truncated)}
        if self.kind == "push":
            if self.frontier:
                out.update(frontier_last=self.frontier[-1],
                           frontier_max=max(self.frontier),
                           frontier_sum=sum(self.frontier),
                           edges_sum=sum(self.edges))
        elif self.residual:
            out.update(residual_first=self.residual[0],
                       residual_last=self.residual[-1],
                       changed_last=self.changed[-1],
                       changed_sum=sum(self.changed))
        return out

    def replay_lines(self):
        """Per-iteration lines in the stepwise -verbose format (push)
        or residual form (pull) — what made 'verbose forces the slow
        stepwise path' unnecessary."""
        if self.kind == "push":
            for i, (f, e) in enumerate(zip(self.frontier, self.edges),
                                       1):
                yield f"iter {i}: frontier={f} edges={e}"
        elif self.kind == "pull":
            for i, (r, c) in enumerate(zip(self.residual, self.changed),
                                       1):
                yield f"iter {i}: residual={r:.6e} changed={c}"
        if self.truncated:
            yield (f"... counters truncated (buffer filled before the "
                   f"run finished)")


@dataclasses.dataclass(frozen=True)
class Telemetry:
    """The pair of sinks a run path consults.  Either may be None;
    ``emit`` is then a no-op and engines skip their counter variants."""

    events: EventLog | None = None
    iter_stats: IterStats | None = None

    def emit(self, kind: str, **fields):
        if self.events is not None:
            return self.events.emit(kind, **fields)
        return None


_NULL = Telemetry()
_current: contextvars.ContextVar[Telemetry] = contextvars.ContextVar(
    "lux_tpu_telemetry", default=_NULL)


def current() -> Telemetry:
    """The active Telemetry handle (a null no-op one by default)."""
    return _current.get()


@contextlib.contextmanager
def use(events: EventLog | None = None,
        iter_stats: IterStats | None = None):
    """Scope a Telemetry handle: every run path entered inside the
    block (engines, segmented drivers, supervisor, timing helpers)
    emits into it."""
    tel = Telemetry(events=events, iter_stats=iter_stats)
    token = _current.set(tel)
    try:
        yield tel
    finally:
        _current.reset(token)
