"""In-loop run telemetry: structured events + device-side counters.

The reference's only observability is ``-verbose`` wall clocks and
per-part phase prints (reference sssp_gpu.cu:513-518,
pagerank.cc:108-118); nothing a tool can consume, and nothing visible
INSIDE a run.  This module is the shared telemetry layer the engines,
the segmented drivers (segmented.py / checkpoint.py), the resilience
supervisor (resilience.py), the CLI and bench.py all emit into:

- ``EventLog``: a structured JSONL event sink (one JSON object per
  line: ``{"t": ..., "kind": ..., ...}``).  Segment start/stop with
  measured rates, checkpoint save/resume, classified retries, outlier
  discards and duration-budget decisions all become events instead of
  ad-hoc prints — round 9 adds the guarded-execution events:
  ``health`` (per-run watchdog digest), ``health_trip`` (the
  diagnosis of a tripped watchdog: checks, iteration, part) and
  ``checkpoint_fallback`` (a corrupt newest generation replaced by
  ``.prev``); round 11 adds the elastic-recovery trail
  (lux_tpu/resilience.py, heartbeat.py): ``topology_fault`` (a
  TOPOLOGY-classified failure, handled or not), ``mesh_shrink`` (the
  decision: from/to device count, lost devices — or the heartbeat
  protocol's from/to process count), ``replace`` (a checkpoint
  written at one device count resumed on another), ``budget_reset``
  (the duration budget's learned rate discarded on a topology
  change) and ``straggler`` (a live-but-behind heartbeat peer).
  ``scripts/events_summary.py`` renders a log into the
  reference-style loadTime/compTime/updateTime table and
  ``scripts/check_bench.py`` validates the schema.
- ``IterStats``: the host-side accumulator for DEVICE-SIDE iteration
  counters.  Engines accumulate per-iteration scalars *inside* their
  fused fori_loop/while_loop (push: frontier size + frontier out-edges
  relaxed per iteration; pull: state residual + changed-vertex count)
  into fixed-shape ``[stats_cap]`` buffers, fetched ONCE per run or
  segment boundary — a few KB independent of graph size, the same
  O(1)-style discipline as ``timing.fence``.  The hot loop gains no
  host syncs and no extra gathers.  Round 13 extends the same
  variants with PER-PART counters (``[stats_cap, P]`` buffers:
  push frontier/out-edges per part, pull residual/changed per part),
  the measured skew signal ROADMAP item 4's locality-aware
  partitioner optimizes: sum-over-parts bitwise-equals the scalar
  series (integer sums; the pull residual is a max, whose
  max-over-parts equals the scalar), and the derived IMBALANCE index
  (max/mean per-part work) rides ``summary()`` into events, bench
  metric lines (``telemetry.imbalance``) and RunReport.
- a contextvar-scoped ``Telemetry`` handle (``use()``/``current()``)
  so the cross-cutting run paths (CLI supervised runs, bench configs,
  checkpointed segments) light up without threading parameters
  through every signature.  The default is a null handle: emitting is
  a no-op and engines build their counter-free programs.

Counter semantics (what the buffers mean, engine by engine):

- push classic (``PushEngine.converge_stats``): ``frontier[i]`` is the
  global active count AFTER iteration i — exactly the series the
  stepwise ``-verbose`` path prints; ``edges[i]`` is the out-edge
  count of the frontier ENTERING iteration i (the relax work done by
  that iteration, full-graph out-degrees even when pair-lane delivery
  splits the dense arrays).
- push delta-stepping: ``frontier[i]`` is the bucket-front size
  entering relax step i (the series ``timed_phases`` reports; bucket
  advances relax nothing and are not iterations), ``edges[i]`` the
  front's out-edges.
- pull (``PullEngine.run_stats`` / ``run_until_stats``):
  ``residual[i]`` is the max-abs state change of iteration i (the
  same scalar ``run_until`` converges on), ``changed[i]`` the number
  of vertices whose state changed.
"""

from __future__ import annotations

import binascii
import contextlib
import contextvars
import dataclasses
import json
import os
import threading
import time

SCHEMA = 1

# One id per PROCESS, minted at import: heartbeat drills append
# multiple processes' events into one shared file, and wall clocks
# ("t") skew across hosts while monotonic clocks ("tm") only order
# within a process — (session, pid) is the merge key that makes the
# combined log unambiguous (scripts/events_summary.py groups on it).
# The observatory's calibration fingerprint (lux_tpu/observe.py)
# embeds the same id, so a bench metric line, its event trail and its
# PERFLEDGER records all name the same session.
_SESSION = binascii.hexlify(os.urandom(6)).decode()


def session_id() -> str:
    """This process's 12-hex-char telemetry session id."""
    return _SESSION

# engines size their counter buffers with this unless overridden;
# int32+uint32 per entry -> 32 KB fetched per run at the default
DEFAULT_STATS_CAP = 4096

# Event observers (lux_tpu/tracing.py's flight recorder): every event
# built by EventLog.emit — or by a sink-less Telemetry.emit while an
# observer is installed — is offered to each observer.  Observer
# failures are swallowed: a postmortem ride-along must never be able
# to fail the run it exists to diagnose.
_OBSERVERS: list = []


def add_observer(fn) -> None:
    if fn not in _OBSERVERS:
        _OBSERVERS.append(fn)


def remove_observer(fn) -> None:
    if fn in _OBSERVERS:
        _OBSERVERS.remove(fn)


def make_event(kind: str, fields: dict) -> dict:
    """One wire-format event dict.  tm (monotonic) orders events
    WITHIN a process; t (wall) only roughly aligns processes.
    pid+session disambiguate multi-process logs sharing one file
    (heartbeat drills)."""
    return {"t": round(time.time(), 6),
            "tm": round(time.monotonic(), 6),
            "pid": os.getpid(), "session": _SESSION,
            "kind": str(kind), **fields}


def _notify(ev: dict) -> None:
    for fn in list(_OBSERVERS):
        try:
            fn(ev)
        except Exception:       # noqa: BLE001 — see _OBSERVERS note
            pass


class EventLog:
    """Append-only structured event sink.

    Events are always kept in memory (``self.events``); with ``path``
    set, each event is also written immediately as one JSON line (so a
    crashed run still leaves its trail on disk).  On-disk appends are
    LINE-ATOMIC under concurrent multi-process writers (heartbeat
    drills share one file): the fd is opened O_APPEND and each event
    goes down as ONE ``os.write`` of one serialized buffer, so two
    processes' lines can never interleave mid-line (POSIX appends are
    atomic per write; buffered ``file.write`` may split a line across
    syscalls).

    ``rotate_bytes`` (round 17) bounds the on-disk JSONL for
    long-lived serving processes: when an append would push the live
    file past the threshold it is renamed to ``.1`` (existing
    generations shift ``.1 -> .2``, the oldest beyond ``generations``
    drops) and a fresh live file opens, stamped with a ``log_rotate``
    event.  The line-atomic contract survives concurrency: rotation
    runs under an flock'd ``<path>.lock`` sidecar, a writer that
    lost the race just follows the rename (its fd still points at a
    complete, un-torn generation; the path/inode check re-opens the
    new live file on its next emit), and every write remains ONE
    O_APPEND ``os.write`` to whichever generation the fd holds.
    ``rotated_paths`` lists the generation set oldest-first —
    scripts/events_summary.py and lux_tpu/tracing.py consume the
    whole set as one stream.  Rotation also bounds the IN-MEMORY
    ``self.events`` (trimmed to the newest ``MEM_KEEP`` at each
    rotation — a log big enough to rotate is too big to keep whole
    in RAM); index-stable ``self.events`` slicing is therefore
    guaranteed only for non-rotating logs (bench.py's
    ``config_telemetry`` relies on it and never rotates)."""

    # in-memory events kept across a rotation (rotation cadence keeps
    # RSS bounded at ~max(events-per-rotate_bytes, MEM_KEEP))
    MEM_KEEP = 4096

    def __init__(self, path: str | None = None,
                 rotate_bytes: int | None = None,
                 generations: int = 2):
        if rotate_bytes is not None and rotate_bytes <= 0:
            raise ValueError(f"rotate_bytes must be > 0, got "
                             f"{rotate_bytes}")
        if generations < 1:
            raise ValueError(f"generations must be >= 1, got "
                             f"{generations}")
        self.path = path
        self.rotate_bytes = rotate_bytes
        self.generations = int(generations)
        self.rotations = 0
        self.events: list[dict] = []
        self._closed = False
        self._fd = self._open() if path else None

    def _open(self) -> int:
        return os.open(self.path, os.O_WRONLY | os.O_CREAT
                       | os.O_APPEND, 0o644)

    def _swap_fd(self) -> None:
        """Close the held fd and reopen the live path, keeping
        ``self._fd`` VALID-OR-NONE at every step: a failed reopen
        must leave None (the next emit retries the open), never a
        stale closed descriptor that a later write would hit with
        EBADF — or worse, that a reused descriptor number would turn
        into silent writes to an unrelated file."""
        fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        self._fd = self._open()

    def _maybe_rotate(self) -> None:
        """Size-triggered rotation check run BEFORE the next event is
        built (so the ``log_rotate`` stamp's monotonic ``tm`` stays
        ordered before it; the live file may overshoot the threshold
        by one line).  Three jobs: recover a sink lost to an earlier
        failed reopen, follow a rotation another process performed
        (path no longer names our inode -> reopen the new live
        file), and rotate ourselves when the live file has crossed
        ``rotate_bytes`` — shift generations, reopen, stamp the new
        file with a ``log_rotate`` event."""
        import fcntl
        try:
            if self._fd is None:
                if not self._closed:
                    self._fd = self._open()   # recover a lost sink
                return
            mine = os.fstat(self._fd)
            try:
                cur = os.stat(self.path)
            except FileNotFoundError:
                cur = None
            if cur is None or (cur.st_dev, cur.st_ino) != \
                    (mine.st_dev, mine.st_ino):
                # someone else rotated: follow to the new live file
                self._swap_fd()
                return
            if mine.st_size <= self.rotate_bytes:
                return
            lfd = os.open(self.path + ".lock",
                          os.O_WRONLY | os.O_CREAT, 0o644)
            try:
                fcntl.flock(lfd, fcntl.LOCK_EX)
                # re-check under the lock: a racing writer may have
                # rotated while we waited
                mine = os.fstat(self._fd)
                try:
                    cur = os.stat(self.path)
                except FileNotFoundError:
                    cur = None
                rotated = False
                if cur is not None \
                        and (cur.st_dev, cur.st_ino) == \
                            (mine.st_dev, mine.st_ino) \
                        and mine.st_size > self.rotate_bytes:
                    for g in range(self.generations - 1, 0, -1):
                        src = f"{self.path}.{g}"
                        if os.path.exists(src):
                            os.replace(src, f"{self.path}.{g + 1}")
                    os.replace(self.path, f"{self.path}.1")
                    rotated = True
                self._swap_fd()
            finally:
                fcntl.flock(lfd, fcntl.LOCK_UN)
                os.close(lfd)
            if rotated:
                self.rotations += 1
                if len(self.events) > self.MEM_KEEP:
                    self.events = self.events[-self.MEM_KEEP:]
                rot = make_event("log_rotate", {
                    "path": self.path, "rotation": self.rotations,
                    "rotate_bytes": self.rotate_bytes,
                    "generations": self.generations})
                self.events.append(rot)
                os.write(self._fd, (json.dumps(rot) + "\n").encode())
                _notify(rot)
        except OSError:
            # rotation is best-effort: a filesystem hiccup must never
            # fail the emit (events always land in memory; _swap_fd
            # guarantees the sink is valid-or-None for the write
            # guard below)
            pass

    def emit(self, kind: str, **fields) -> dict:
        if self.path is not None and self.rotate_bytes is not None:
            self._maybe_rotate()
        ev = make_event(kind, fields)
        self.events.append(ev)
        if self._fd is not None:
            # ONE buffer, ONE write: the line-atomicity contract
            os.write(self._fd, (json.dumps(ev) + "\n").encode())
        _notify(ev)
        return ev

    def counts(self) -> dict:
        """{kind: occurrences} over everything emitted so far."""
        out: dict[str, int] = {}
        for ev in self.events:
            out[ev["kind"]] = out.get(ev["kind"], 0) + 1
        return out

    def close(self) -> None:
        self._closed = True
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def rotated_paths(path: str) -> list[str]:
    """The on-disk generation set of a (possibly rotated) event log,
    OLDEST FIRST: [path.N, ..., path.1, path] for whichever
    generations exist — concatenating them in this order reproduces
    one stream whose per-process monotonic ``tm`` ordering holds.
    A never-rotated log returns [path]."""
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    return [f"{path}.{g}" for g in range(n - 1, 0, -1)] + [path]


class IterStats:
    """Host-side accumulator for device-side per-iteration counters.

    ``extend_push``/``extend_pull`` append one segment's fetched
    counter buffers (the single per-boundary fetch); ``begin_run``
    resets, so one-shot timed helpers record only their LAST timed
    run while segmented drivers accumulate across segments."""

    def __init__(self):
        self.kind: str | None = None
        self.frontier: list[int] = []
        self.edges: list[int] = []
        self.residual: list[float] = []
        self.changed: list[int] = []
        # per-part series (round 13): one [P] row per iteration, from
        # the engines' [stats_cap, P] buffers; empty when the run
        # predates the per-part variants or passed no part buffers
        self.frontier_parts: list[list[int]] = []
        self.edges_parts: list[list[int]] = []
        self.residual_parts: list[list[float]] = []
        self.changed_parts: list[list[int]] = []
        self.truncated = False

    def __len__(self):
        return len(self.frontier) if self.kind == "push" \
            else len(self.residual)

    def begin_run(self) -> None:
        self.kind = None
        self.frontier, self.edges = [], []
        self.residual, self.changed = [], []
        self.frontier_parts, self.edges_parts = [], []
        self.residual_parts, self.changed_parts = [], []
        self.truncated = False

    def _fetch(self, buf, n: int):
        """Fetch the first ``n`` rows of a counter buffer.  The slice
        happens BEFORE the host fetch, so only the live prefix ships
        through the tunnel — a [stats_cap, P] per-part buffer fetched
        whole would be cap*P*8 bytes per segment; the prefix keeps the
        per-boundary cost O(iters x P), i.e. KB for real segments."""
        import numpy as np

        from lux_tpu.timing import fetch
        cap = buf.shape[0]
        if n > cap:
            self.truncated = True
        return np.asarray(fetch(buf[:min(int(n), cap)]))

    def extend_push(self, frontier_buf, edges_buf, n: int,
                    frontier_parts=None, edges_parts=None) -> None:
        """Append ``n`` iterations from a push engine's counter
        buffers (frontier int32 [cap], edges uint32 [cap]; the
        optional per-part buffers are int32/uint32 [cap, P])."""
        self.kind = "push"
        self.frontier += [int(x) for x in self._fetch(frontier_buf, n)]
        self.edges += [int(x) for x in self._fetch(edges_buf, n)]
        if frontier_parts is not None:
            self.frontier_parts += [
                [int(x) for x in row]
                for row in self._fetch(frontier_parts, n)]
        if edges_parts is not None:
            self.edges_parts += [
                [int(x) for x in row]
                for row in self._fetch(edges_parts, n)]

    def extend_pull(self, residual_buf, changed_buf, n: int,
                    residual_parts=None, changed_parts=None) -> None:
        """Append ``n`` iterations from a pull engine's counter
        buffers (residual float32 [cap], changed uint32 [cap]; the
        optional per-part buffers are float32/uint32 [cap, P])."""
        self.kind = "pull"
        self.residual += [float(x) for x in self._fetch(residual_buf, n)]
        self.changed += [int(x) for x in self._fetch(changed_buf, n)]
        if residual_parts is not None:
            self.residual_parts += [
                [float(x) for x in row]
                for row in self._fetch(residual_parts, n)]
        if changed_parts is not None:
            self.changed_parts += [
                [int(x) for x in row]
                for row in self._fetch(changed_parts, n)]

    # -- per-part attribution (round 13) -------------------------------

    def num_parts(self) -> int:
        rows = (self.edges_parts if self.kind == "push"
                else self.changed_parts)
        return len(rows[0]) if rows else 0

    def part_totals(self) -> list[int] | None:
        """Per-part WORK totals over the run — frontier out-edges for
        push (the relax work each part contributed), changed-vertex
        counts for pull.  Sums over parts bitwise-equal the scalar
        ``edges_sum``/``changed_sum`` (integer sums of the same
        device-side values, reduced part-first instead of all at
        once; on graphs past 2^32 edges per iteration the scalar's
        device uint32 wraps while these host totals stay exact — the
        validators compare mod 2^32).  None without per-part data."""
        rows = (self.edges_parts if self.kind == "push"
                else self.changed_parts)
        if not rows:
            return None
        return [sum(r[p] for r in rows) for p in range(len(rows[0]))]

    def imbalance(self) -> float | None:
        """The imbalance index: max/mean of the per-part work totals
        (1.0 = perfectly balanced) — the measured skew signal the
        locality-aware partitioner (ROADMAP item 4) optimizes.  None
        without per-part data or with zero total work."""
        totals = self.part_totals()
        if not totals or sum(totals) == 0:
            return None
        mean = sum(totals) / len(totals)
        return max(totals) / mean

    def imbalance_digest(self) -> dict | None:
        """The ``telemetry.imbalance`` field of a bench metric line
        (scripts/check_bench.py validates it against the counter
        digest): {kind, index, parts} or None."""
        totals = self.part_totals()
        imb = self.imbalance()
        if totals is None or imb is None:
            return None
        return {"kind": self.kind, "index": round(imb, 4),
                "parts": totals}

    def parts_lines(self):
        """Human per-part attribution table (CLI -iter-stats replay /
        events_summary's rendering source)."""
        totals = self.part_totals()
        if totals is None:
            return
        metric = "edges" if self.kind == "push" else "changed"
        tot = sum(totals) or 1
        imb = self.imbalance()
        yield (f"per-part {metric} (imbalance "
               f"{'n/a' if imb is None else f'{imb:.3f}'} max/mean):")
        for p, v in enumerate(totals):
            yield f"  part {p}: {v} ({v / tot * 100:.1f}%)"

    def summary(self) -> dict | None:
        """Compact digest for event logs / bench JSON lines /
        resilience.RunReport."""
        if self.kind is None:
            return None
        out = {"kind": self.kind, "iters": len(self),
               "truncated": bool(self.truncated)}
        if self.kind == "push":
            if self.frontier:
                out.update(frontier_last=self.frontier[-1],
                           frontier_max=max(self.frontier),
                           frontier_sum=sum(self.frontier),
                           edges_sum=sum(self.edges))
        elif self.residual:
            out.update(residual_first=self.residual[0],
                       residual_last=self.residual[-1],
                       changed_last=self.changed[-1],
                       changed_sum=sum(self.changed))
        totals = self.part_totals()
        if totals is not None:
            imb = self.imbalance()
            out["parts"] = len(totals)
            out["parts_edges" if self.kind == "push"
                else "parts_changed"] = totals
            if imb is not None:
                out["imbalance"] = round(imb, 4)
        return out

    def replay_lines(self):
        """Per-iteration lines in the stepwise -verbose format (push)
        or residual form (pull) — what made 'verbose forces the slow
        stepwise path' unnecessary."""
        if self.kind == "push":
            for i, (f, e) in enumerate(zip(self.frontier, self.edges),
                                       1):
                yield f"iter {i}: frontier={f} edges={e}"
        elif self.kind == "pull":
            for i, (r, c) in enumerate(zip(self.residual, self.changed),
                                       1):
                yield f"iter {i}: residual={r:.6e} changed={c}"
        if self.truncated:
            yield (f"... counters truncated (buffer filled before the "
                   f"run finished)")


@dataclasses.dataclass(frozen=True)
class Telemetry:
    """The pair of sinks a run path consults.  Either may be None;
    ``emit`` is then a no-op and engines skip their counter variants."""

    events: EventLog | None = None
    iter_stats: IterStats | None = None

    def emit(self, kind: str, **fields):
        if self.events is not None:
            return self.events.emit(kind, **fields)
        if _OBSERVERS:
            # no event sink, but a flight recorder (or other observer)
            # is installed: the ring still sees the trail
            ev = make_event(kind, fields)
            _notify(ev)
            return ev
        return None


_NULL = Telemetry()
_current: contextvars.ContextVar[Telemetry] = contextvars.ContextVar(
    "lux_tpu_telemetry", default=_NULL)

# per-kind occurrence counters behind emit_sampled (process-global:
# the sampler kinds it throttles are process-wide trails)
_SAMPLED: dict = {}
_SAMPLED_LOCK = threading.Lock()


def emit_sampled(kind: str, every: int = 1, **fields):
    """Throttled ``current().emit`` for high-frequency observability
    kinds (round 22: the memory sampler fires at EVERY segment
    boundary, and a long converge would otherwise swamp the event log
    with ``mem_sample`` lines).  Emits occurrence 0, every, 2*every,
    ... of ``kind`` and drops the rest; each emitted event carries
    ``sampled_skipped`` (events suppressed since the last emitted
    one) so a reader can tell throttling from a silent sampler.
    ``every=1`` is a plain emit with ``sampled_skipped=0``."""
    every = max(1, int(every))
    with _SAMPLED_LOCK:
        n = _SAMPLED.get(kind, 0)
        _SAMPLED[kind] = n + 1
    if n % every:
        return None
    return current().emit(kind, sampled_skipped=min(n, every - 1),
                          **fields)


def current() -> Telemetry:
    """The active Telemetry handle (a null no-op one by default)."""
    return _current.get()


@contextlib.contextmanager
def use(events: EventLog | None = None,
        iter_stats: IterStats | None = None):
    """Scope a Telemetry handle: every run path entered inside the
    block (engines, segmented drivers, supervisor, timing helpers)
    emits into it."""
    tel = Telemetry(events=events, iter_stats=iter_stats)
    token = _current.set(tel)
    try:
        yield tel
    finally:
        _current.reset(token)
