"""Shared segmented-execution drivers.

Checkpointing (checkpoint.py) and runtime guards (debug.py) both run
engines in host-visible segments; this module is the single copy of
that slicing logic so per-segment behaviors (save, finite checks,
stall detection) compose instead of forking.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def run_segments(eng, state, num_iters: int, segment: int,
                 on_segment: Callable | None = None,
                 start_iter: int = 0):
    """Run a pull engine in ``segment``-iteration slices.
    ``on_segment(state, done_iters)`` runs after each slice."""
    done = start_iter
    while done < num_iters:
        n = min(segment, num_iters - done)
        state = eng.run(state, n)
        done += n
        if on_segment is not None:
            on_segment(state, done)
    return state


def converge_segments(eng, label, active, segment: int,
                      max_iters: int | None = None,
                      on_segment: Callable | None = None,
                      start_iter: int = 0):
    """Run a push engine to convergence in slices.

    ``on_segment(label, active, total_iters, active_count)`` runs after
    each slice (may raise to abort).  Convergence is detected from the
    active mask, never from iteration counts (delta-stepping counts
    relax steps only).  Returns (label, active, total_iters).
    """
    import jax
    import jax.numpy as jnp

    total = start_iter
    cap = np.iinfo(np.int32).max if max_iters is None else max_iters
    while total < cap:
        n = min(segment, cap - total)
        label, active, it = eng.converge(label, active, n)
        total += int(np.asarray(jax.device_get(it)))
        cnt = int(np.asarray(jax.device_get(jnp.sum(active))))
        if on_segment is not None:
            on_segment(label, active, total, cnt)
        if cnt == 0:
            break
    return label, active, total
