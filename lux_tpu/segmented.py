"""Shared segmented-execution drivers.

Checkpointing (checkpoint.py), runtime guards (debug.py) and the run
supervisor (resilience.py) all run engines in host-visible segments;
this module is the single copy of that slicing logic so per-segment
behaviors (save, finite checks, stall detection, fault injection,
duration budgeting) compose instead of forking.

Two extensions beyond plain fixed-size slicing:

- ``on_segment`` hooks may RETURN a replacement state to continue
  with (the fault-injection harness corrupts state this way;
  lux_tpu/faults.py) or raise to abort; returning None keeps the
  current state.
- ``segment`` may be an int OR a ``DurationBudget``: each execution
  is then timed (fenced through ``lux_tpu.timing``) and the next
  slice is sized so a single XLA execution stays under the budget —
  the systematic replacement for the ad-hoc ``seg=2`` / small-``ni``
  routing big-scale runs used against the ~55 s tunnel duration wall
  (PERF_NOTES round 5).

Both drivers are telemetry emitters (lux_tpu/telemetry.py): with an
active handle, every slice emits a ``segment`` event (sizes, fenced
seconds) and budget lock/halve decisions emit ``budget_*`` events;
with iter-stats active the slices run the engines' counter-recording
programs and fetch the per-iteration buffers once per boundary.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np


class DurationBudget:
    """Adaptive segment sizing against a per-XLA-execution duration
    budget (default 45 s — safely under the measured ~55 s
    worker-crash envelope, PERF_NOTES round 5).

    Policy, shaped by how the remote tunnel bills time:

    - the first ``warmup`` slices run ``probe_n`` iterations each:
      the FIRST execution of a program includes its (remote) compile,
      so only the last warmup slice's measured rate is trusted;
    - the slice size then LOCKS at ``headroom * budget_s / per_iter``
      clamped to [1, max_segment] — sticky, because pull engines
      compile one fused program per distinct slice length and a
      drifting size would recompile every segment;
    - an execution that overruns the budget halves the lock.  With
      ``per_size_compile=True`` (pull engines: one fused program per
      distinct slice length) the first execution at any new size is
      exempt, since it may carry that size's compile; push converge
      is ONE program with the cap as an argument AND reports actual
      relax steps (which vary every segment), so its callers pass
      False — otherwise every overrun would look like a fresh size
      and stay permanently exempt.
    """

    def __init__(self, budget_s: float = 45.0, probe_n: int = 1,
                 warmup: int = 2, max_segment: int = 4096,
                 headroom: float = 0.8, per_size_compile: bool = True):
        if not budget_s > 0:
            raise ValueError(f"budget_s must be > 0, got {budget_s}")
        self.budget_s = float(budget_s)
        self.probe_n = max(1, int(probe_n))
        self.warmup = max(1, int(warmup))
        self.max_segment = max(1, int(max_segment))
        self.headroom = float(headroom)
        self.per_size_compile = bool(per_size_compile)
        self.locked: int | None = None
        self.per_iter: float | None = None
        self._measured = 0
        self._seen: set[int] = set()

    def reset_rate(self, reason: str = "") -> None:
        """Forget the measured rate and re-enter warmup.  Called on a
        TOPOLOGY change (resilience's elastic re-placement): a
        per-iteration rate learned on 8 devices is stale on 4 — the
        locked segment size would roughly double the execution time
        and blow the duration wall on the first post-shrink segment.
        The per-size compile exemptions reset too (every size is a
        fresh compile on the new mesh)."""
        from lux_tpu import telemetry

        telemetry.current().emit("budget_reset", reason=reason,
                                 locked=self.locked,
                                 per_iter_s=(None if self.per_iter is
                                             None else
                                             round(self.per_iter, 6)))
        self.locked = None
        self.per_iter = None
        self._measured = 0
        self._seen.clear()

    def next_n(self, remaining: int) -> int:
        n = self.locked if self.locked is not None else self.probe_n
        return max(1, min(n, remaining, self.max_segment))

    def observe(self, n: int, seconds: float) -> None:
        """Record one fenced execution of ``n`` iterations."""
        from lux_tpu import telemetry

        first_at_size = self.per_size_compile and n not in self._seen
        self._seen.add(n)
        self._measured += 1
        if self.locked is None:
            if self._measured < self.warmup:
                return                      # compile-contaminated
            self.per_iter = max(seconds / max(n, 1), 1e-9)
            self.locked = max(1, min(
                self.max_segment,
                int(self.headroom * self.budget_s / self.per_iter)))
            telemetry.current().emit(
                "budget_lock", n=self.locked,
                per_iter_s=round(self.per_iter, 6),
                budget_s=self.budget_s)
        elif (seconds > self.budget_s and not first_at_size
              and self.locked > 1):
            self.locked = max(1, self.locked // 2)
            telemetry.current().emit(
                "budget_halve", n=self.locked,
                seconds=round(seconds, 3), budget_s=self.budget_s)


def _next_n(segment, remaining: int) -> int:
    if isinstance(segment, DurationBudget):
        return segment.next_n(remaining)
    return min(segment, remaining)


def run_segments(eng, state, num_iters: int, segment,
                 on_segment: Callable | None = None,
                 start_iter: int = 0, mem=None):
    """Run a pull engine in slices (``segment``: int size or
    DurationBudget).  ``on_segment(state, done_iters)`` runs after
    each slice and may return a replacement state.  ``mem`` is a
    memwatch.MemoryTrail sampled at every segment boundary (the
    round-22 occupancy trail — O(1) host work, outside the fused
    loop by construction).

    With telemetry active (lux_tpu/telemetry.py): each slice emits a
    ``segment`` event with its fenced seconds, and with iter-stats the
    slice runs ``eng.run_stats`` — the device-side per-iteration
    counters are fetched once per segment boundary (a few KB) and
    accumulated across segments."""
    from lux_tpu import telemetry
    from lux_tpu.profiling import step_annotation

    tel = telemetry.current()
    st = tel.iter_stats
    guarded = getattr(eng, "health", False)
    if st is not None and start_iter == 0:
        st.begin_run()          # a resume keeps accumulating instead
    budget = segment if isinstance(segment, DurationBudget) else None
    timed = budget is not None or tel.events is not None
    done = start_iter
    seg_idx = 0
    watch = None           # threaded across segments: the trailing-
    #                        window checks keep their history even
    #                        when segments are shorter than the window
    while done < num_iters:
        n = _next_n(segment, num_iters - done)
        t0 = time.perf_counter()
        with step_annotation("lux_segment", seg_idx):
            if guarded:
                state, _itd, res_b, chg_b, res_p, chg_p, watch = \
                    eng.run_health(state, n, watch)
            elif st is not None:
                state, res_b, chg_b, res_p, chg_p = eng.run_stats(
                    state, n)
            else:
                state = eng.run(state, n)
            if timed or st is not None or guarded:
                from lux_tpu.timing import fence
                fence(state)   # O(1)-byte fence, not a download
        dt = time.perf_counter() - t0
        if guarded:
            # a tripped watchdog raises BEFORE the segment hook, so a
            # corrupted state can never reach a checkpoint save (the
            # trip iteration is already global: the threaded watch's
            # tick counts across segments; start_iter offsets resumes)
            from lux_tpu import health
            health.ensure_ok(watch, engine="pull",
                             base_iter=start_iter,
                             where=f"pull segment {seg_idx}")
        if budget is not None:
            budget.observe(n, dt)
        done += n
        if timed:
            tel.emit("segment", engine="pull", n=n, done=done,
                     seconds=round(dt, 6))
        seg_idx += 1
        if mem is not None:
            mem.sample(where=f"segment:{done}")
        if on_segment is not None:
            res = on_segment(state, done)
            if res is not None:
                state = res
        # counters land only after the segment hook (checkpoint save)
        # survives: a crash in the save window makes the retry re-run
        # this slice, so appending earlier would double-count it
        if st is not None:
            st.extend_pull(res_b, chg_b, n, res_p, chg_p)
    return state


def converge_segments(eng, label, active, segment,
                      max_iters: int | None = None,
                      on_segment: Callable | None = None,
                      start_iter: int = 0, mem=None):
    """Run a push engine to convergence in slices (``segment``: int
    size or DurationBudget).

    ``on_segment(label, active, total_iters, active_count)`` runs after
    each slice (may raise to abort, or return a replacement
    ``(label, active)``).  Convergence is detected from the active
    mask, never from iteration counts (delta-stepping counts relax
    steps only).  Returns (label, active, total_iters).  ``mem`` is
    a memwatch.MemoryTrail sampled at every boundary (round 22).

    With telemetry active: each slice emits a ``segment`` event, and
    with iter-stats the slice runs ``eng.converge_stats`` — frontier/
    edge counters fetched once per boundary and accumulated across
    segments (a resumed run keeps accumulating).
    """
    import jax
    import jax.numpy as jnp

    from lux_tpu import telemetry
    from lux_tpu.profiling import step_annotation

    tel = telemetry.current()
    st = tel.iter_stats
    guarded = getattr(eng, "health", False)
    if st is not None and start_iter == 0:
        st.begin_run()
    budget = segment if isinstance(segment, DurationBudget) else None
    total = start_iter
    seg_idx = 0
    watch = None           # threaded: a stall spanning a segment
    #                        boundary still accumulates
    cap = np.iinfo(np.int32).max if max_iters is None else max_iters
    while total < cap:
        n = _next_n(segment, cap - total)
        t0 = time.perf_counter()
        with step_annotation("lux_segment", seg_idx):
            if guarded:
                label, active, it, fsz, fed, fszp, fedp, watch = \
                    eng.converge_health(label, active, n, watch)
            elif st is not None:
                label, active, it, fsz, fed, fszp, fedp = \
                    eng.converge_stats(label, active, n)
            else:
                label, active, it = eng.converge(label, active, n)
            # the scalar fetch depends on the whole while_loop: it is
            # the completion fence (tunnel-safe, O(1) bytes)
            it = int(np.asarray(jax.device_get(it)))
        dt = time.perf_counter() - t0
        if guarded:
            # raise BEFORE the segment hook: a corrupted/livelocked
            # state never reaches a checkpoint save (trip iterations
            # are global via the threaded watch's tick)
            from lux_tpu import health
            health.ensure_ok(watch, engine="push",
                             base_iter=start_iter,
                             where=f"push segment {seg_idx}")
        if budget is not None and it > 0:
            budget.observe(it, dt)
        total += it
        cnt = int(np.asarray(jax.device_get(jnp.sum(active))))
        tel.emit("segment", engine="push", iters=it, total=total,
                 active=cnt, seconds=round(dt, 6))
        seg_idx += 1
        if mem is not None:
            mem.sample(where=f"segment:{total}")
        if on_segment is not None:
            res = on_segment(label, active, total, cnt)
            if res is not None:
                label, active = res
                cnt = int(np.asarray(jax.device_get(jnp.sum(active))))
        # counters land only after the segment hook (checkpoint save)
        # survives: a crash in the save window makes the retry re-run
        # this slice, so appending earlier would double-count it
        if st is not None:
            st.extend_push(fsz, fed, it, fszp, fedp)
        if cnt == 0:
            break
    return label, active, total
