"""On-device sharded correctness audits (the scalable ``-check``).

The reference's ``-check`` audits run as GPU tasks per partition over
the resident edge arrays, at full graph scale (reference
sssp_gpu.cu:800-843, components_gpu.cu:788, with per-part [PASS]/[FAIL]
prints at sssp_gpu.cu:837-842).  The host audits in ``lux_tpu.check``
re-materialize the whole edge list in NumPy — fine at test scale,
impossible for a sharded billion-edge run on a pod.

Here the same audits are per-part jitted reductions over the
ShardedGraph's part-major edge arrays, sharded over the ``parts`` mesh
axis exactly like the engines (shard_map + all_gather of the audited
state).  The NumPy versions in ``check.py`` remain the oracles
(tests/test_check_device.py verifies count-exact agreement).

Notes:
- Graph arrays are jit ARGUMENTS (never closed over) per the repo
  convention.
- The pagerank residual audit re-derives one pull iteration with the
  portable scatter-based segment reduce — slower than the engines'
  tiled path but a one-off audit, not the hot loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from lux_tpu.check import CheckResult
from lux_tpu.graph import ShardedGraph
from lux_tpu.ops.segment import segment_reduce
from lux_tpu.parallel.mesh import PARTS_AXIS, shard_over_parts


def _as_padded(sg: ShardedGraph, state):
    """Accept either the engine's padded [rows, vpad, ...] state
    (device or host; on multi-host runs the GLOBAL [num_parts, ...]
    array) or a host user-order [nv, ...] array."""
    if (getattr(state, "ndim", 0) >= 2 and state.shape[1] == sg.vpad
            and state.shape[0] in (sg.num_parts, len(sg.part_ids()))):
        return state
    return sg.to_padded(np.asarray(state))


class DeviceChecker:
    """Per-part jitted audits over one ShardedGraph (+ optional mesh).

    Builds the flat part-major edge arrays once (they are independent
    of the engines' chunked layouts) and reuses them across audits.
    """

    def __init__(self, sg: ShardedGraph, mesh=None):
        self.sg = sg
        self.mesh = mesh
        arrays = dict(src_slot=sg.src_slot, dst_local=sg.dst_local,
                      vmask=sg.vmask, deg=sg.deg_padded)
        if sg.weighted:
            arrays["weight"] = sg.edge_weight
        if mesh is not None:
            arrays = shard_over_parts(mesh, arrays, sg.num_parts)
        else:
            arrays = {k: jnp.asarray(v) for k, v in arrays.items()}
        self._keys = sorted(arrays)
        self._args = tuple(arrays[k] for k in self._keys)

    # -- shared machinery ----------------------------------------------

    def _run(self, per_part, state, *extra):
        """vmap ``per_part(flat_state, old_p, g, *extra)`` over this
        device's parts (shard_map over the mesh) -> host [num_parts]
        per-part results."""
        sg, keys = self.sg, self._keys

        def core(state, *args):
            gargs, extra_v = args[:len(keys)], args[len(keys):]
            g = dict(zip(keys, gargs))
            if self.mesh is not None:
                # audit: allow(collective-scope) — the acceptance
                # harness re-creates the engines' state exchange on
                # purpose (it verifies placement, it is never priced)
                full = jax.lax.all_gather(state, PARTS_AXIS, tiled=True)
            else:
                full = state
            flat = full.reshape((sg.num_parts * sg.vpad,) +
                                full.shape[2:])
            return jax.vmap(
                lambda old, gp: per_part(flat, old, gp, *extra_v))(
                state, g)

        if self.mesh is not None:
            P = PartitionSpec
            core = jax.shard_map(
                core, mesh=self.mesh,
                in_specs=(P(PARTS_AXIS),) + (P(PARTS_AXIS),) * len(keys)
                         + (P(),) * len(extra),
                out_specs=P(PARTS_AXIS))
        out = jax.jit(core)(self._place_state(state), *self._args,
                            *extra)
        from lux_tpu.parallel.multihost import fetch_global
        return fetch_global(out)

    def _place_state(self, state):
        state = _as_padded(self.sg, state)
        if isinstance(state, jax.Array) and self.mesh is not None:
            return state            # already placed by the engine
        if self.mesh is not None:
            return shard_over_parts(self.mesh, [np.asarray(state)],
                                    self.sg.num_parts)[0]
        return jnp.asarray(state)

    def _edge_pred_counts(self, state, pred):
        """Count edges violating ``pred(src_val, dst_val, weight)``
        per part."""
        sg = self.sg

        def per_part(flat, old, g):
            src_v = jnp.take(flat, g["src_slot"], axis=0)
            valid = g["dst_local"] < sg.vpad
            dst_v = jnp.take(old, jnp.minimum(g["dst_local"],
                                              sg.vpad - 1), axis=0)
            bad = pred(src_v, dst_v, g.get("weight"))
            return jnp.sum((valid & bad).astype(jnp.int32))

        return self._run(per_part, state)

    # -- the audits ----------------------------------------------------

    def sssp(self, state, weighted: bool = False) -> CheckResult:
        """Fixed point: dist[dst] <= dist[src] + w for every edge
        (reference sssp_gpu.cu:792-796, w = 1 in hops mode)."""
        if weighted and not self.sg.weighted:
            raise ValueError("weighted check needs a weighted graph")

        def pred(src_v, dst_v, w):
            if not weighted:
                w = jnp.asarray(1, src_v.dtype)
            return dst_v > src_v + w

        counts = self._edge_pred_counts(state, pred)
        return CheckResult("sssp triangle inequality (device)",
                           int(counts.sum()), self.sg.ne,
                           per_part=tuple(int(c) for c in counts))

    def components(self, state) -> CheckResult:
        """Fixed point: labels[dst] >= labels[src]
        (reference components_gpu.cu:788)."""
        counts = self._edge_pred_counts(
            state, lambda s, d, w: d < s)
        return CheckResult("components monotonicity (device)",
                           int(counts.sum()), self.sg.ne,
                           per_part=tuple(int(c) for c in counts))

    def pagerank(self, state, tol: float = 1e-6) -> CheckResult:
        """Residual audit: one more (degree-normalized) iteration moves
        every rank by less than ``tol`` (see check.check_pagerank)."""
        from lux_tpu.apps.pagerank import ALPHA
        sg = self.sg

        def per_part(flat, old, g, tol):
            src_v = jnp.take(flat, g["src_slot"], axis=0)
            msgs = jnp.where(g["dst_local"] < sg.vpad, src_v, 0)
            red = segment_reduce(msgs, g["dst_local"], sg.vpad + 1,
                                 "sum")[:sg.vpad]
            pr = (1.0 - ALPHA) / sg.nv + ALPHA * red
            deg = g["deg"].astype(pr.dtype)
            nxt = jnp.where(g["deg"] > 0, pr / jnp.maximum(deg, 1), pr)
            bad = jnp.abs(nxt - old) > tol
            return jnp.sum((bad & g["vmask"]).astype(jnp.int32))

        counts = self._run(per_part, state, jnp.float32(tol))
        return CheckResult(f"pagerank residual(tol={tol}) (device)",
                           int(counts.sum()), self.sg.nv,
                           per_part=tuple(int(c) for c in counts))

    def colfilter(self, state) -> CheckResult:
        """Learned factors must predict ratings no worse than the
        uniform sqrt(1/K) init (see check.check_colfilter).  The init
        prediction is analytically K * (1/K) = 1."""
        sg = self.sg

        def per_part(flat, old, g):
            src_rows = jnp.take(flat, g["src_slot"], axis=0)
            valid = g["dst_local"] < sg.vpad
            dst_rows = jnp.take(old, jnp.minimum(g["dst_local"],
                                                 sg.vpad - 1), axis=0)
            pred = jnp.sum(src_rows * dst_rows, axis=-1)
            w = g["weight"]
            err = jnp.where(valid, w - pred, 0.0)
            err0 = jnp.where(valid, w - 1.0, 0.0)
            return jnp.stack([jnp.sum(err * err),
                              jnp.sum(err0 * err0)])

        sse = self._run(per_part, state)          # [P, 2]
        learned = float(np.sqrt(sse[:, 0].sum() / max(1, sg.ne)))
        init = float(np.sqrt(sse[:, 1].sum() / max(1, sg.ne)))
        bad = int(learned > init + 1e-9)
        return CheckResult("colfilter rmse non-increase (device)",
                           bad, sg.ne)


def check_sssp_device(sg, state, weighted=False, mesh=None):
    return DeviceChecker(sg, mesh).sssp(state, weighted)


def check_components_device(sg, state, mesh=None):
    return DeviceChecker(sg, mesh).components(state)


def check_pagerank_device(sg, state, tol=1e-6, mesh=None):
    return DeviceChecker(sg, mesh).pagerank(state, tol)


def check_colfilter_device(sg, state, mesh=None):
    return DeviceChecker(sg, mesh).colfilter(state)
