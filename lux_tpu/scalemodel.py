"""Analytic mesh-scaling model priced from measured chip constants.

This environment exposes ONE physical TPU chip, so multi-chip GTEPS
cannot be *measured* here; the multi-chip path is correctness-tested
on virtual meshes (``__graft_entry__.dryrun_multichip``,
tests/test_multidevice.py) but its economics would otherwise be a
hope.  This module prices a mesh run of the pull engine from
constants measured on the real chip (PERF_NOTES.md), so the scaling
claim is an auditable calculation:

- compute is per-edge work measured at the owner-exchange slot rate
  (the scan keeps every shard at the small-table gather rate
  regardless of total state size -- the whole point of the owner
  layout, PERF_NOTES "scale-25 decomposition"), and it divides by the
  chip count because parts that a single chip must scan SEQUENTIALLY
  run on their own chips on a mesh;
- communication is the owner exchange's ``psum_scatter`` (plus the
  pair rows' state ``all_gather`` when composed), a fixed
  O(state-table) byte volume per chip per iteration that does NOT
  grow with the mesh -- so efficiency is compute-bound until the
  per-chip edge share gets small.

The model is CALIBRATED: tests/test_scalemodel.py reproduces the
recorded single-chip configurations (RMAT25/26 owner and pair+owner
runs, PERF_NOTES round 3/4) from their recorded layout stats.
``project_table`` renders the markdown mesh-projection table; the
PERF_NOTES "per-chip ceiling" section records its output for the
flagship configurations.

Reference anchor: Lux scales by adding GPUs/nodes to the same
binaries (/root/reference/README.md:33-38); this is the TPU-native
pricing of the same move over ICI instead of GASNet.
"""

from __future__ import annotations

from dataclasses import dataclass

# Measured v5e constants (PERF_NOTES.md).  ns figures are per unit of
# the named work on ONE chip; they are flat across the scales measured
# (scale 21-26) because the owner layout pins the gather to the
# small-shard regime and pair rows are row-granular.
OWNER_SLOT_NS = 9.92     # scan gather + pallas partials + combine,
                         # per padded owner slot ("profile_owner" table)
GATHER_SMALL_NS = 8.96   # per-edge gather, state table <= ~64 MB
GATHER_BIG_NS = 14.6     # per-edge gather past the emitter step
BIG_TABLE_BYTES = 96e6   # auto-exchange threshold (engine/pull.py)
PAIR_ROW_NS = 150.0      # per delivered 128-lane pair row
# K-dim (SDDMM) pair rows: a delivery row additionally fetches TWO
# [128, K] tile blocks (row-granular, cheap) and runs two 128x128xK
# MXU contractions (D = S @ T^T and the one-hot gradient matmul) plus
# the [128, 128] lane select.  2 x 2*128*128*K flops at the f32 MXU
# rate (~half the 24 TFLOP/s bf16 figure) ~= 5.5 ns per K — MODELED
# from the measured primitive costs, not yet swept on-device
# (PERF_NOTES round 8); the scalar row's 150 ns stays as the fixed
# per-row machinery term.
PAIR_DOT_ROW_K_NS = 5.5
# K-dim residual edges (the chunked dot path) pay the ~9 ns/row src
# gather plus per-edge MXU work that also scales with K.
RESIDUAL_EDGE_NS = 9.92
RESIDUAL_DOT_K_NS = 0.11


def pair_row_ns(kdim: int = 1) -> float:
    """Modeled cost of ONE delivered pair row: the measured 150 ns for
    scalar programs; + PAIR_DOT_ROW_K_NS per K for the SDDMM (K-dim)
    delivery (ops/pairs.pair_partial_dot*)."""
    if kdim <= 1:
        return PAIR_ROW_NS
    return PAIR_ROW_NS + PAIR_DOT_ROW_K_NS * kdim


def residual_edge_ns(kdim: int = 1) -> float:
    """Modeled per-edge cost of the residual (gather) path serving the
    same program: ~9.92 ns scalar, + per-K MXU work on the dot path."""
    if kdim <= 1:
        return RESIDUAL_EDGE_NS
    return RESIDUAL_EDGE_NS + RESIDUAL_DOT_K_NS * kdim


def break_even_fill(kdim: int = 1,
                    residual_ns: float | None = None) -> int:
    """min_fill break-even: live lanes a pair row must deliver to beat
    sending its edges down the residual path — row_cost / residual
    per-edge cost, rounded up.  Scalar: 150 / 9.92 ~= 16 (the measured
    RMAT21 optimum basin is F=12..32, PERF_NOTES round 5).  K=20
    (colfilter): 260 / 12.1 ~= 22 — K-dim rows must be FULLER to pay,
    because row cost grows with K faster than residual cost."""
    if residual_ns is None:
        residual_ns = residual_edge_ns(kdim)
    import math
    return max(1, math.ceil(pair_row_ns(kdim) / residual_ns))


# Paged two-level gather (ops/pagegather.py, round 15): the measured
# primitive costs of its stages (PERF_NOTES round 2).  Static row
# movement is cheap — `jnp.take` of [*, 128] rows = 24 ns/row — and
# the Pallas lane shuffle (`take_along_axis` axis=1 ->
# tpu.dynamic_gather dim 1) is the one fast dynamic primitive.
PAGE_ROW_FETCH_NS = 24.0       # one [*, 128] row fetch (0.19 ns/elem)
LANE_SHUFFLE_NS = 0.38         # per element, 128-wide lane shuffle
# Modeled cost of ONE paged delivery row: the pair row's measured
# 150 ns fetch + compare-reduce machinery (same row shape, same
# combine) PLUS the 128-lane shuffle the paged row adds.  MODELED
# from measured primitive costs, not yet measured end-to-end on
# device — the owed A/B is observe.DEBTS "paged-gather-ab".
PAGED_ROW_NS = PAIR_ROW_NS + 128 * LANE_SHUFFLE_NS     # = 198.64
# K-dim (SDDMM) paged rows run THREE 128x128xK MXU contractions
# (one-hot lane shuffle + D = S @ T^T + the gradient matmul) where
# pair rows run two — 1.5x the pair per-K term.
PAGED_DOT_ROW_K_NS = 1.5 * PAIR_DOT_ROW_K_NS


def paged_row_ns(kdim: int = 1) -> float:
    """Modeled cost of one delivered 128-lane paged row."""
    if kdim <= 1:
        return PAGED_ROW_NS
    return PAGED_ROW_NS + PAGED_DOT_ROW_K_NS * kdim


def flat_gather_ns(table_bytes: float) -> float:
    """The flat per-edge gather rate for a state table of this size:
    the measured small-table 8.96 ns/elem, stepping to 14.6 past the
    ~96 MB emitter cliff (PERF_NOTES rounds 2-3)."""
    return GATHER_BIG_NS if table_bytes > BIG_TABLE_BYTES \
        else GATHER_SMALL_NS


def page_gather_ns(page_ratio: float, fill: float,
                   kdim: int = 1) -> float:
    """Modeled delivered ns/edge of the paged two-level gather
    (ops/pagegather.py) from the plan's MEASURED stats:

      page_ratio  unique fetched page elements per edge
                  (unique_pages * 128 / ne — the dedup'd page fetch's
                  share, at the 0.19 ns/elem static row-fetch rate)
      fill        average live lanes per delivery row (ne / rows —
                  the per-row machinery amortizes over this)

    Both are graph-structure dependent (R-MAT tails vs real-graph
    clustering), which is why ``gather="auto"`` resolves from the
    plan's recorded stats rather than a fixed constant."""
    if fill <= 0:
        raise ValueError(f"fill must be > 0, got {fill}")
    if page_ratio < 0:
        raise ValueError(f"page_ratio must be >= 0, got {page_ratio}")
    fetch = page_ratio * (PAGE_ROW_FETCH_NS / 128.0) * max(1, kdim)
    return fetch + paged_row_ns(kdim) / fill


def page_break_even_fill(page_ratio: float = 1.0,
                         table_bytes: float = 0.0,
                         kdim: int = 1) -> int:
    """Row fill above which the paged path beats the flat gather (at
    a given unique-page ratio): rows under this live-lane count pay
    more in row machinery than the 9/14.6 ns flat rate.  The modeled
    small-table scalar threshold — fill >= 23 at page_ratio 1 — is
    the recorded break-even of round 15 (pinned in
    tests/test_pagegather.py)."""
    import math
    rate = flat_gather_ns(table_bytes)
    if kdim > 1:
        rate = residual_edge_ns(kdim)
    margin = rate - page_ratio * (PAGE_ROW_FETCH_NS / 128.0) \
        * max(1, kdim)
    if margin <= 0:
        return 1 << 30          # flat always wins at this page ratio
    return max(1, math.ceil(paged_row_ns(kdim) / margin))


def page_break_even_ratio(fill: float, table_bytes: float = 0.0,
                          kdim: int = 1) -> float:
    """Largest unique-page ratio at which the paged path still beats
    the flat gather for rows of the given fill (negative = paged can
    never win at this fill)."""
    rate = flat_gather_ns(table_bytes)
    if kdim > 1:
        rate = residual_edge_ns(kdim)
    return (rate - paged_row_ns(kdim) / fill) \
        / ((PAGE_ROW_FETCH_NS / 128.0) * max(1, kdim))


# Page-major split (round 16, ops/pagegather.py mode="pagemajor"):
# the PAIR_ROW_NS = 150 per-row machinery decomposes as one 24 ns
# static row fetch + the compare-reduce/class-combine remainder; the
# page-major layout pays fetch+shuffle per FULL gather row and the
# remainder per (low-fill) virtual row, plus one extra 24 ns take
# binding each virtual row to its gather row's delivered values.
# MODELED from the measured primitive costs like PAGED_ROW_NS —
# the owed on-device split is observe.DEBTS "pagemajor-route-ab".
VROW_REDUCE_NS = PAIR_ROW_NS - PAGE_ROW_FETCH_NS       # = 126.0


def pagemajor_gather_ns(page_ratio: float, g_fill: float,
                        v_fill: float, kdim: int = 1,
                        routed: bool = False,
                        itemsize: int = 4) -> float:
    """Modeled delivered ns/edge of the PAGE-MAJOR two-level layout
    from the plan's measured stats: the dedup'd page fetch
    (``page_ratio``) + row fetch and lane shuffle amortized over the
    near-full GATHER rows (``g_fill``) + the compare-reduce machinery
    amortized over the VIRTUAL rows (``v_fill`` — the same joint
    (tile, page) density the plain paged fill measures) + the routing
    hop when the rows cross the mesh (``routed``, the owner plan's
    all_to_all — priced per shipped lane over ICI,
    ``pagemajor_route_ns``).  K-dim (SDDMM) programs are not served
    by this mode (typed refusal, matching ops/pagegather)."""
    if kdim > 1:
        raise ValueError("page-major does not serve K-dim (SDDMM) "
                         "programs; use page_gather_ns")
    if g_fill <= 0 or v_fill <= 0:
        raise ValueError(f"fills must be > 0, got g_fill={g_fill} "
                         f"v_fill={v_fill}")
    if page_ratio < 0:
        raise ValueError(f"page_ratio must be >= 0, got {page_ratio}")
    fetch = page_ratio * (PAGE_ROW_FETCH_NS / 128.0)
    gather = (PAGE_ROW_FETCH_NS + 128 * LANE_SHUFFLE_NS) / g_fill
    reduce = (PAGE_ROW_FETCH_NS + VROW_REDUCE_NS) / v_fill
    route = pagemajor_route_ns(g_fill, itemsize) if routed else 0.0
    return fetch + gather + reduce + route


def pagemajor_route_ns(g_fill: float, itemsize: int = 4) -> float:
    """The routing hop's per-edge price: every (padded) lane of a
    routed 128-lane row ships ``itemsize`` bytes over ICI once, so an
    edge pays itemsize * 128 / g_fill bytes at the link rate — ~0.1
    ns/edge at full rows, which is why trading the hop for full rows
    can pay (the comm-is-permille-of-compute relation the mesh model
    rests on, ICI_BYTES_PER_S)."""
    if g_fill <= 0:
        raise ValueError(f"g_fill must be > 0, got {g_fill}")
    return itemsize * (128.0 / g_fill) / (ICI_BYTES_PER_S * 1e-9)


def pagemajor_break_even_vfill(page_ratio: float = 1.0,
                               g_fill: float = 128.0,
                               table_bytes: float = 0.0,
                               routed: bool = False,
                               itemsize: int = 4) -> int:
    """Virtual-row fill above which page-major beats the flat gather
    (at a given page ratio and gather fill) — the page-major
    counterpart of ``page_break_even_fill``.  The modeled small-table
    threshold at full gather rows — v_fill >= 19 — undercuts the
    plain paged break-even of 23 because the shuffle rides the full
    rows (pinned in tests/test_pagegather.py)."""
    import math
    rate = flat_gather_ns(table_bytes)
    margin = rate - page_ratio * (PAGE_ROW_FETCH_NS / 128.0) \
        - (PAGE_ROW_FETCH_NS + 128 * LANE_SHUFFLE_NS) / g_fill
    if routed:
        margin -= pagemajor_route_ns(g_fill, itemsize)
    if margin <= 0:
        return 1 << 30
    return max(1, math.ceil((PAGE_ROW_FETCH_NS + VROW_REDUCE_NS)
                            / margin))


# MXU compute core (round 23, ops/tiled.chunk_partials use_mxu): the
# per-chunk reduce as one-hot contractions.  The VPU masked reduce
# FUSES (no [C, E, W] intermediate, tiled.py) but runs its
# compare-select machinery once per PAYLOAD SLICE — the wide (K x B)
# payload multiplies the whole row cost.  The MXU path pays a fixed
# per-row toll to MATERIALIZE the [E, W] int8 one-hot (the pair row's
# fetch-shaped cost — modeled at the measured 150 ns pair-row
# machinery + its 0.19 ns/B int8 store, NOT yet measured on device:
# observe.DEBTS "mxu-core-ab"), after which each payload slice is one
# 128x128 int8 systolic pass (~2 ns at the MXU int8 rate).  min/max
# replay that contraction 2x per ORDER BIT (vote + candidacy
# route-back, tiled._mxu_compare_reduce), which is why compare kinds
# essentially never auto-engage — the resolver is deliberately
# honest about that.
ONEHOT_TILE_NS = 160.0   # materialize + load one [128, W] int8 one-hot
MXU_TILE_NS = 2.0        # one 128x128 int8 contraction, per wide slice


def mxu_reduce_rounds(kind: str, nbits: int = 32) -> int:
    """Contractions per chunk row for a reduce kind: sum is ONE
    one-hot matmul; min/max run the bit-serial tournament — one vote
    + one route-back contraction per bit of the order encoding."""
    if kind == "sum":
        return 1
    if kind in ("min", "max"):
        return 2 * nbits
    raise ValueError(f"unknown reduce kind {kind!r}")


def vpu_reduce_row_ns(wide: int = 1) -> float:
    """Modeled VPU masked-reduce cost of one 128-lane chunk row: the
    measured VROW_REDUCE_NS compare-reduce machinery, once per payload
    slice (the broadcast-select-reduce runs over every K x B lane)."""
    if wide < 1:
        raise ValueError(f"wide must be >= 1, got {wide}")
    return VROW_REDUCE_NS * wide


def mxu_reduce_row_ns(wide: int = 1, kind: str = "sum",
                      nbits: int = 32) -> float:
    """Modeled MXU one-hot cost of one 128-lane chunk row: the fixed
    one-hot materialization + one int8 contraction per payload slice
    per tournament round.  The wide (K x B) payload rides as a free
    MXU minor dimension — only the ~2 ns systolic term scales with
    it, not the 160 ns toll."""
    if wide < 1:
        raise ValueError(f"wide must be >= 1, got {wide}")
    return ONEHOT_TILE_NS + MXU_TILE_NS * wide * mxu_reduce_rounds(
        kind, nbits)


def mxu_break_even_wide(kind: str = "sum", nbits: int = 32) -> int:
    """Smallest K x B payload width at which the MXU one-hot reduce
    beats the fused VPU masked reduce for a kind.  sum: width 2 (the
    one-hot toll needs one extra payload slice to amortize — scalar
    sum stays VPU, so f32 scalar flagships keep their bitwise
    behavior).  min/max: the 2 x nbits tournament rounds outrun the
    VPU's per-slice saving at every width (1 << 30 = never) — those
    paths exist for the measured A/B and the pull-kind revalidators,
    not the auto default."""
    import math
    per_slice_margin = VROW_REDUCE_NS \
        - MXU_TILE_NS * mxu_reduce_rounds(kind, nbits)
    if per_slice_margin <= 0:
        return 1 << 30
    return max(1, math.ceil(ONEHOT_TILE_NS / per_slice_margin))


def resolve_use_mxu(kind: str, wide: int = 1, nbits: int = 32) -> bool:
    """The ``use_mxu="auto"`` resolution: engage the MXU reduce when
    the payload is wide enough to amortize the one-hot toll.  wide is
    the product of the program's vector K and query batch B (both are
    free minor dims of the contraction)."""
    return wide >= mxu_break_even_wide(kind, nbits)


# Query batching (ROADMAP item 2, engine/program.py ``batch``): the
# dense iteration's ONE table gather fetches a [B]-wide CONTIGUOUS
# state row per edge instead of one element — the fetch is
# latency-bound, so the extra lanes ride at roughly the wide-row rate
# (modeled from the measured 150 ns / 128-lane pair-row fetch, NOT
# yet swept on-device: observe.DEBTS "batch-sweep-on-device").
BATCH_LANE_NS = PAIR_ROW_NS / 128.0      # ~1.17 ns per extra lane


def batched_edge_ns(B: int, rate: float = GATHER_SMALL_NS) -> float:
    """Modeled per-edge cost of ONE batched dense iteration serving B
    queries: the scalar gather latency + (B-1) ride-along lanes."""
    if B < 1:
        raise ValueError(f"B must be >= 1, got {B}")
    return rate + BATCH_LANE_NS * (B - 1)


def per_query_edge_ns(B: int, rate: float = GATHER_SMALL_NS) -> float:
    """Modeled DELIVERED cost per edge per query at batch width B —
    the ~9/B amortization claim, priced honestly: exactly rate/B only
    if extra lanes were free; the wide-row lane term floors it at
    ~BATCH_LANE_NS (~1.2 ns) for large B.  The bench batch-sweep's
    measured 1/query_gteps is the number this predicts."""
    return batched_edge_ns(B, rate) / B


def batch_sweep_table(widths=(1, 2, 4, 8, 16, 32, 64),
                      rate: float = GATHER_SMALL_NS) -> str:
    """Markdown modeled ~9/B table for PERF_NOTES."""
    lines = ["| B | edge ns (batched iter) | ns/edge/query "
             "| vs B=1 |",
             "|---|---|---|---|"]
    base = per_query_edge_ns(1, rate)
    for b in widths:
        pq = per_query_edge_ns(b, rate)
        lines.append(f"| {b} | {batched_edge_ns(b, rate):.2f} | "
                     f"{pq:.2f} | {base / pq:.1f}x |")
    return "\n".join(lines)


STATE_NS_PER_VERTEX = 6.0  # apply + epilogues, per padded vertex
                           # (the ~0.2 s/iter residual in the RMAT25
                           # np=4 decomposition)
# ICI: one v5e link direction (public scaling-book figure).  The
# conclusions are insensitive to 2-4x error here -- comm is permille
# of compute at the scales this engine targets.  Round 19: when the
# communication observatory has MEASURED a link rate on a canonical
# session (observe.calibrate_links -> set_measured_link), the
# projections price from the measurement instead of this figure.
ICI_BYTES_PER_S = 4.5e10
# DCN: inter-slice links are 10-100x thinner than ICI (ROADMAP item
# 3); no canonical figure exists yet, so the model carries the
# midpoint thinness until a multi-slice session collects the
# dcn-bandwidth-probe debt (lux_tpu/observe.py DEBTS).
DCN_THINNESS_MODEL = 30.0

# Quantized-exchange wire factors (EQuARX-style in-collective block
# quantization, PAPERS.md): owner messages (pagerank partials,
# min-distances) tolerate block-scaled low precision with
# exact-identity padding.  int8 ships 1 payload byte + one f32 scale
# per 32-element block; bf16 halves the word.  These price the
# item-3 target; the quantized exchange itself is not built yet.
QUANT_FACTORS = {"f32": 1.0, "bf16": 0.5,
                 "int8": (32 + 4) / (32 * 4)}

# tier -> measured bytes/s, fed by observe.calibrate_links on
# canonical sessions only (a CPU-mesh "link" rate must never price a
# pod projection; CPU figures stay in the perf ledger, labeled)
_MEASURED_LINKS: dict = {}


def set_measured_link(tier: str, bytes_per_s: float) -> None:
    """Record a MEASURED link rate (observe.calibrate_links).  The
    projections prefer it over the canonical constant from then on."""
    if tier not in ("ici", "dcn"):
        raise ValueError(f"unknown link tier {tier!r}")
    if not bytes_per_s > 0:
        raise ValueError(f"link rate must be > 0, got {bytes_per_s}")
    _MEASURED_LINKS[tier] = float(bytes_per_s)


def measured_link(tier: str) -> float | None:
    """The measured rate for ``tier``, or None when never calibrated."""
    return _MEASURED_LINKS.get(tier)


def link_bytes_per_s(tier: str = "ici") -> float:
    """Link rate of record for a tier: the session's measured figure
    when one exists, else the canonical model (ICI figure; DCN =
    ICI / DCN_THINNESS_MODEL — flagged as model until the
    multi-slice debt is collected).  "local" (single device) has no
    link; pricing comm there is a caller bug."""
    if tier == "local":
        raise ValueError("tier 'local' has no link — single-device "
                         "placements ship zero bytes")
    got = _MEASURED_LINKS.get(tier)
    if got is not None:
        return got
    if tier == "ici":
        return ICI_BYTES_PER_S
    if tier == "dcn":
        return _MEASURED_LINKS.get("ici", ICI_BYTES_PER_S) \
            / DCN_THINNESS_MODEL
    raise ValueError(f"unknown link tier {tier!r}")


@dataclass
class Projection:
    chips: int
    compute_s: float       # per chip, per iteration
    comm_s: float          # per chip, per iteration
    iter_s: float          # compute + comm (no overlap assumed)
    gteps: float           # aggregate: ne / iter_s
    gteps_per_chip: float  # driver metric: aggregate / chips
    efficiency: float      # vs perfect linear scaling of 1 chip

    def row(self) -> str:
        return (f"| {self.chips} | {self.compute_s:.3f} | "
                f"{self.comm_s * 1e3:.1f} | {self.gteps:.3f} | "
                f"{self.gteps_per_chip:.4f} | "
                f"{self.efficiency * 100:.0f}% |")


def project_pull(ne: int, nv: int, chips: int, *,
                 exchange: str = "owner",
                 chunk_inflation: float = 1.2,
                 pair_coverage: float = 0.0,
                 pair_row_inflation: float = 1.0,
                 state_bytes_per_vertex: int = 4,
                 ici_bytes_per_s: float | None = None) -> Projection:
    """Price one pull-engine iteration on a ``chips``-device mesh.

    ``chunk_inflation``/``pair_coverage``/``pair_row_inflation`` come
    from the layout stats the engines already report
    (OwnerLayout.stats; StackedPairPlan.stats "coverage"/"inflation");
    pass a measured configuration's stats to price its mesh run.
    ``ici_bytes_per_s=None`` (default) prices from the link rate of
    record — this session's MEASURED figure when the comm observatory
    calibrated one (set_measured_link), the canonical constant
    otherwise.
    """
    if ici_bytes_per_s is None:
        ici_bytes_per_s = link_bytes_per_s("ici")
    if exchange not in ("owner", "gather"):
        raise ValueError(f"unknown exchange {exchange!r}")
    if not 0.0 <= pair_coverage <= 1.0:
        raise ValueError(f"pair_coverage must be in [0, 1], "
                         f"got {pair_coverage}")
    if chunk_inflation < 1.0:
        raise ValueError(f"chunk_inflation is padded/real slots and "
                         f"cannot be < 1, got {chunk_inflation}")
    if pair_row_inflation < 1.0:
        raise ValueError(f"pair_row_inflation is delivered/ideal rows "
                         f"and cannot be < 1, got {pair_row_inflation}")
    cov = pair_coverage
    pair_rows = ne * cov * pair_row_inflation / 128.0
    residual_ne = ne * (1.0 - cov)
    state_bytes = nv * state_bytes_per_vertex

    if exchange == "owner":
        # every shard stays at the small-table rate; padded slots are
        # the unit of residual work
        edge_ns = residual_ne * chunk_inflation * OWNER_SLOT_NS
        # psum_scatter of per-dst-part partials: each chip ships
        # (P-1)/P of one state table per iteration
        comm_bytes = state_bytes * (chips - 1) / chips
    else:
        per_chip_table = state_bytes  # all_gather materializes it all
        rate = (GATHER_BIG_NS if per_chip_table > BIG_TABLE_BYTES
                else GATHER_SMALL_NS)
        edge_ns = residual_ne * rate
        comm_bytes = state_bytes * (chips - 1) / chips
    if cov > 0.0 and exchange == "owner":
        # pair rows read 128-wide state rows from an all_gather kept
        # only for them (row fetches do not pay the big-table step);
        # the gather path feeds pairs from its one existing all_gather
        comm_bytes += state_bytes * (chips - 1) / chips

    compute_ns = (edge_ns + pair_rows * PAIR_ROW_NS) / chips \
        + nv * STATE_NS_PER_VERTEX / chips
    compute_s = compute_ns * 1e-9
    comm_s = comm_bytes / ici_bytes_per_s
    iter_s = compute_s + comm_s
    gteps = ne / iter_s / 1e9

    one = (edge_ns + pair_rows * PAIR_ROW_NS
           + nv * STATE_NS_PER_VERTEX) * 1e-9
    eff = (gteps / chips) / (ne / one / 1e9)
    return Projection(chips=chips, compute_s=compute_s, comm_s=comm_s,
                      iter_s=iter_s, gteps=gteps,
                      gteps_per_chip=gteps / chips, efficiency=eff)


def phase_model(*, engine: str, exchange: str, ne: int, nv: int,
                kdim: int = 1, pair_coverage: float = 0.0,
                pair_row_inflation: float = 1.0,
                chunk_inflation: float = 1.2,
                state_bytes_per_vertex: int = 4,
                dot: bool = False, scale: float = 1.0,
                paged: bool = False, page_ratio: float = 0.0,
                page_fill: float = 128.0,
                page_scale: float | None = None,
                page_mode: str = "paged",
                page_g_fill: float = 128.0,
                use_mxu: bool = False,
                mxu_wide: int = 1,
                reduce_kind: str = "sum",
                state_nbits: int = 32) -> dict:
    """Per-PHASE predicted nanoseconds for ONE engine iteration — the
    model side of the observatory's measured-vs-model drift check
    (lux_tpu/observe.py).  Keys match the engines' ``timed_phases``
    phase names; a value of None means the phase has no measured
    constant to price it (verdict "unmodeled" downstream) — honesty
    over coverage, per the round-3 rule that un-measured figures are
    flagged models.

    ``scale`` rescales every priced constant by the session
    calibration factor (observe.session_scale: this session's measured
    gather rate over the canonical figure), so predictions are in THIS
    session's nanoseconds — that is what makes a CPU or degraded-
    tunnel comparison meaningful at all.

    Phase attribution of the project_pull aggregate:
    - gather/relax       per-edge delivery (the ~90%% term): residual
                         edges at the gather rate + pair rows at the
                         150+5.5K ns row cost
    - gen_exchange       owner path: the whole per-slot scan
                         (gather+partials+combine folded, per padded
                         slot) + the pair-row term
    - gather_reduce /    streamed single-phase delivery: same total as
      relax_reduce /     gather+reduce (the fused block loop)
      dot_reduce
    - apply/update       per-vertex epilogue (STATE_NS_PER_VERTEX)
    - exchange           all_gather materialization: free on one chip
                         (a reshape), ICI-priced per mesh chip
    - reduce             VPU: no isolated measured constant (None);
                         with ``use_mxu`` the one-hot contraction IS
                         modeled (mxu_reduce_row_ns over the chunk
                         rows at ``mxu_wide`` = K x B payload slices)
                         — the per-phase A/B the round-23 port owes
                         observe.decompose
    """
    if engine not in ("pull", "push"):
        raise ValueError(f"unknown engine {engine!r}")
    cov = pair_coverage
    pair_rows = ne * cov * pair_row_inflation / 128.0
    pair_ns = pair_rows * pair_row_ns(kdim) * scale
    residual_ne = ne * (1.0 - cov)
    state_bytes = nv * state_bytes_per_vertex

    if paged:
        # paged two-level delivery (ops/pagegather.py): priced from
        # the plan's recorded unique-page ratio and row fill — total
        # coverage, so no pair/residual split.  ``page_scale`` is the
        # session's measured page-row probe over its canon (the
        # observe.calibrate page_gather probe) — the paged pipeline's
        # platform factor differs from the flat gather's, so it gets
        # its own scale when the caller has one.  The PAGE-MAJOR mode
        # prices its split gather/virtual rates + the routing hop
        # instead (pagemajor_gather_ns).
        if page_mode == "pagemajor":
            per_edge = pagemajor_gather_ns(
                page_ratio, page_g_fill, page_fill,
                routed=exchange == "owner")
        else:
            per_edge = page_gather_ns(page_ratio, page_fill, kdim)
        deliver = ne * per_edge \
            * (scale if page_scale is None else page_scale)
    elif exchange == "owner":
        deliver = residual_ne * chunk_inflation * OWNER_SLOT_NS * scale
    else:
        rate = (GATHER_BIG_NS if state_bytes > BIG_TABLE_BYTES
                else GATHER_SMALL_NS)
        if dot:
            rate = residual_edge_ns(kdim)
        deliver = residual_ne * rate * scale
    apply_ns = nv * STATE_NS_PER_VERTEX * scale
    if paged:
        pair_ns = 0.0

    model: dict[str, float | None] = {}
    if exchange == "owner":
        model["gen_exchange"] = deliver + pair_ns
    else:
        # single-chip all_gather is a reshape; comm pricing only
        # applies on a mesh (project_pull) — unmodeled here
        model["exchange"] = None
        if dot:
            model["dot_reduce"] = deliver + pair_ns
        else:
            key = "relax" if engine == "push" else "gather"
            model[key] = deliver + pair_ns
            if use_mxu:
                rows = ne * chunk_inflation / 128.0
                reduce_ns = rows * mxu_reduce_row_ns(
                    mxu_wide, reduce_kind, state_nbits) * scale
                model["reduce"] = reduce_ns
                model[f"{key}_reduce"] = deliver + pair_ns + reduce_ns
            else:
                model["reduce"] = None
                model[f"{key}_reduce"] = deliver + pair_ns
    model["update" if engine == "push" else "apply"] = apply_ns
    return model


def project_table(ne: int, nv: int, chip_counts=(1, 4, 8, 16, 64),
                  **kw) -> str:
    """Markdown projection table for PERF_NOTES."""
    lines = ["| chips | compute s/iter | comm ms/iter | GTEPS "
             "| GTEPS/chip | efficiency |",
             "|---|---|---|---|---|---|"]
    lines += [project_pull(ne, nv, c, **kw).row() for c in chip_counts]
    return "\n".join(lines)
