"""The .lux binary CSC graph file format.

Layout (little-endian), exactly the reference's on-disk format
(reference README.md:55-79, tools/converter.cc:108-124,
core/pull_model.inl:288-319):

    offset 0   : nv        uint32      number of vertices
    offset 4   : ne        uint64      number of directed edges
    offset 12  : row_ptrs  uint64[nv]  *end* offsets: in-edges of vertex v
                                       occupy col_idx[row_ptrs[v-1] : row_ptrs[v]]
                                       (row_ptrs[-1] implicitly 0)
    ...        : col_idx   uint32[ne]  edge *sources*, sorted by destination
    ...        : weights   w[ne]       optional; only if the graph is weighted
                                       (reference WeightType is int32,
                                       col_filter/app.h:24; we also accept f32)
    ...        : degrees   uint32[nv]  optional trailing out-degrees
                                       (written by the reference converter,
                                       converter.cc:124, but recomputed at load
                                       time by apps — see SURVEY.md §7 quirks)

The file does not self-describe whether weights/degrees are present (the
reference decides at compile time via the EDGE_WEIGHT macro); we infer
from file size, with explicit overrides available.

Validation (round 9): the reference trusts its inputs completely, and
so did ``read_lux`` — and because XLA's gathers CLAMP out-of-range
indices, a malformed file (non-monotone ``row_ptrs``, out-of-range
``col_idx``) flowed through the engines and produced WRONG RESULTS
instead of an error.  ``validate_graph`` is the crash-don't-corrupt
conversion: structural invariants checked once at load time
(``read_lux(validate=True)``, the apps' ``-validate`` flag,
``scripts/fsck_lux.py`` offline), each failure a typed
:class:`GraphFormatError` naming the check and the first offending
index.  ``ShardedGraph.build`` asserts the same invariants on its
shard boundaries (lux_tpu/graph.py).
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

HEADER_SIZE = 12  # reference FILE_HEADER_SIZE: sizeof(V_ID) + sizeof(E_ID)

V_DTYPE = np.dtype("<u4")  # V_ID
E_DTYPE = np.dtype("<u8")  # E_ID


class GraphFormatError(ValueError):
    """A .lux file (or in-memory CSC graph) failed structural
    validation.  ``check`` names the violated invariant (one of:
    header, section_size, weighted_mismatch, ambiguous_layout,
    row_ptrs_monotone, row_ptrs_total, col_idx_range,
    degrees_length, degrees_consistent, partition_starts,
    partition_edges, perm_header, perm_length, perm_bijection,
    wal_header, wal_version, wal_capacity,
    journal_header, journal_version)."""

    def __init__(self, path: str, check: str, detail: str):
        super().__init__(f"{path}: invalid graph [{check}] — {detail}")
        self.path = path
        self.check = check
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class LuxFileHeader:
    nv: int
    ne: int
    has_weights: bool
    has_degrees: bool
    weight_dtype: np.dtype


def _infer_sections(path: str, nv: int, ne: int,
                    weighted: bool | None, weight_dtype: np.dtype):
    """Infer optional-section presence from total file size."""
    size = os.path.getsize(path)
    base = HEADER_SIZE + 8 * nv + 4 * ne
    wbytes = int(np.dtype(weight_dtype).itemsize) * ne
    candidates = {
        (False, False): base,
        (False, True): base + 4 * nv,
        (True, False): base + wbytes,
        (True, True): base + wbytes + 4 * nv,
    }
    matches = [k for k, v in candidates.items() if v == size]
    if weighted is not None:
        filtered = [m for m in matches if m[0] == weighted]
        if matches and not filtered:
            have = "a weighted" if matches[0][0] else "an unweighted"
            want = "weighted" if weighted else "unweighted"
            raise GraphFormatError(
                path, "weighted_mismatch",
                f"looks like {have} graph but was opened as "
                f"{want} (nv={nv} ne={ne} size={size})")
        matches = filtered
    if not matches:
        raise GraphFormatError(
            path, "section_size",
            f"size {size} does not match any .lux layout for "
            f"nv={nv} ne={ne} (expected one of "
            f"{sorted(candidates.values())}) — truncated or torn file?")
    if len(matches) > 1:
        # Possible when weight bytes == degree bytes (e.g. nv == ne with
        # 4-byte weights): the file cannot be parsed without being told.
        raise GraphFormatError(
            path, "ambiguous_layout",
            f"size matches layouts {matches}; pass weighted=True/"
            f"False explicitly")
    return matches[0]


def peek_lux(path: str, weighted: bool | None = None,
             weight_dtype=np.int32) -> LuxFileHeader:
    """Read only the 12-byte header + infer section layout."""
    with open(path, "rb") as f:
        head = f.read(HEADER_SIZE)
    if len(head) != HEADER_SIZE:
        raise GraphFormatError(path, "header",
                               f"only {len(head)} bytes, a .lux "
                               f"header is {HEADER_SIZE}")
    nv = int(np.frombuffer(head, V_DTYPE, count=1, offset=0)[0])
    ne = int(np.frombuffer(head, E_DTYPE, count=1, offset=4)[0])
    has_w, has_d = _infer_sections(path, nv, ne, weighted, weight_dtype)
    return LuxFileHeader(nv=nv, ne=ne, has_weights=has_w, has_degrees=has_d,
                         weight_dtype=np.dtype(weight_dtype))


def validate_graph(nv: int, ne: int, row_ptrs, col_idx,
                   degrees=None, path: str = "<arrays>") -> None:
    """Structural CSC invariants — every violation is a
    :class:`GraphFormatError` naming the check and the first offending
    index, never a wrong-answer run downstream (XLA's clamping gathers
    would otherwise absorb out-of-range indices silently):

    - ``row_ptrs`` are monotone non-decreasing END offsets;
    - ``row_ptrs[-1] == ne`` (and an empty graph has ne == 0);
    - every ``col_idx`` source lies in ``[0, nv)``;
    - ``degrees`` (when present) has length nv and is EXACTLY the
      out-degree histogram of ``col_idx``.

    O(nv + ne) vectorized numpy — the same order as reading the file.
    """
    row_ptrs = np.asarray(row_ptrs)
    col_idx = np.asarray(col_idx)
    if row_ptrs.shape[0] != nv:
        raise GraphFormatError(
            path, "row_ptrs_total",
            f"{row_ptrs.shape[0]} row_ptrs for nv={nv}")
    if nv:
        d = np.diff(row_ptrs.astype(np.int64))
        if row_ptrs[0] > ne or (d < 0).any():
            at = (0 if row_ptrs[0] > ne
                  else int(np.argmax(d < 0)) + 1)
            raise GraphFormatError(
                path, "row_ptrs_monotone",
                f"end offsets decrease at vertex {at} "
                f"(row_ptrs[{at}]={int(row_ptrs[at])})")
        if int(row_ptrs[-1]) != ne:
            raise GraphFormatError(
                path, "row_ptrs_total",
                f"row_ptrs[-1]={int(row_ptrs[-1])} != ne={ne}")
    elif ne:
        raise GraphFormatError(path, "row_ptrs_total",
                               f"nv=0 but ne={ne}")
    if col_idx.shape[0] != ne:
        raise GraphFormatError(
            path, "col_idx_range",
            f"{col_idx.shape[0]} col_idx entries for ne={ne}")
    if ne:
        c64 = col_idx.astype(np.int64, copy=False)
        bad = (c64 < 0) | (c64 >= nv)
        if bad.any():
            at = int(np.argmax(bad))
            raise GraphFormatError(
                path, "col_idx_range",
                f"col_idx[{at}]={int(c64[at])} outside [0, {nv})")
    if degrees is not None:
        degrees = np.asarray(degrees)
        if degrees.shape[0] != nv:
            raise GraphFormatError(
                path, "degrees_length",
                f"{degrees.shape[0]} degrees for nv={nv}")
        want = np.bincount(col_idx.astype(np.int64, copy=False),
                           minlength=nv)
        got = degrees.astype(np.int64, copy=False)
        if not np.array_equal(got, want):
            at = int(np.argmax(got != want))
            raise GraphFormatError(
                path, "degrees_consistent",
                f"degrees[{at}]={int(got[at])} but col_idx counts "
                f"{int(want[at])} out-edges")


def read_lux(path: str, weighted: bool | None = None, weight_dtype=np.int32,
             mmap: bool = True, validate: bool = False):
    """Read a .lux file.

    Returns (header, row_ptrs[u8 nv], col_idx[u4 ne], weights|None,
    degrees|None). With mmap=True (default) the big arrays are memory
    mapped, so partition slicing downstream does not copy the whole file
    through RAM (the analogue of the reference's per-partition
    fseeko/fread loads, pull_model.inl:288-319; the real native path is
    lux_tpu.native's C++ loader).

    validate=True runs the structural ``validate_graph`` pass (section
    sizes are always checked via peek_lux's layout inference) — a
    malformed file raises :class:`GraphFormatError` instead of flowing
    into the engines' clamping gathers.
    """
    hdr = peek_lux(path, weighted, weight_dtype)
    off = HEADER_SIZE
    if mmap:
        def arr(dtype, count, offset):
            return np.memmap(path, dtype=dtype, mode="r", offset=offset,
                             shape=(count,))
    else:
        buf = open(path, "rb").read()

        def arr(dtype, count, offset):
            return np.frombuffer(buf, dtype=dtype, count=count, offset=offset)

    row_ptrs = arr(E_DTYPE, hdr.nv, off)
    off += 8 * hdr.nv
    col_idx = arr(V_DTYPE, hdr.ne, off)
    off += 4 * hdr.ne
    weights = None
    if hdr.has_weights:
        weights = arr(hdr.weight_dtype, hdr.ne, off)
        off += hdr.weight_dtype.itemsize * hdr.ne
    degrees = None
    if hdr.has_degrees:
        degrees = arr(V_DTYPE, hdr.nv, off)
    if validate:
        validate_graph(hdr.nv, hdr.ne, row_ptrs, col_idx,
                       degrees=degrees, path=path)
    return hdr, row_ptrs, col_idx, weights, degrees


# ---------------------------------------------------------------------
# permutation sidecar (round 16, page-aware reordering)
#
# The page-aware reorderer (lux_tpu/reorder.py, native/reorder.cc)
# persists its vertex permutation BESIDE the .lux file rather than
# rewriting multi-GB edge sections: ``<file>.lux.perm`` holds a tiny
# header (magic "LUXP" + uint32 nv) and uint32[nv] ``perm`` with
# perm[new] = old.  ``Graph.from_file(reorder=...)`` applies it at
# load; scripts/fsck_lux.py validates sidecars at rest.  Validation
# is the same crash-don't-corrupt conversion as validate_graph: a
# truncated or non-bijective sidecar raises a typed GraphFormatError
# instead of silently relabeling into a wrong-answer run.

PERM_MAGIC = b"LUXP"
PERM_SUFFIX = ".perm"


def perm_sidecar_path(lux_path: str) -> str:
    return lux_path + PERM_SUFFIX


def validate_perm(perm, nv: int, path: str = "<perm>") -> None:
    """The sidecar's structural invariants: length nv and a BIJECTION
    of [0, nv) — each violation a typed :class:`GraphFormatError`
    (checks ``perm_length`` / ``perm_bijection``)."""
    perm = np.asarray(perm)
    if perm.ndim != 1 or perm.shape[0] != nv:
        raise GraphFormatError(
            path, "perm_length",
            f"{perm.shape} permutation for nv={nv}")
    if nv:
        p64 = perm.astype(np.int64, copy=False)
        seen = np.zeros(nv, bool)
        bad = (p64 < 0) | (p64 >= nv)
        if bad.any():
            at = int(np.argmax(bad))
            raise GraphFormatError(
                path, "perm_bijection",
                f"perm[{at}]={int(p64[at])} outside [0, {nv})")
        seen[p64] = True
        if not seen.all():
            at = int(np.argmax(~seen))
            raise GraphFormatError(
                path, "perm_bijection",
                f"vertex {at} never appears (duplicate entries "
                f"elsewhere) — not a bijection of [0, {nv})")


def write_perm_sidecar(lux_path: str, perm,
                       path: str | None = None) -> str:
    """Write ``perm`` (perm[new] = old) beside ``lux_path``; the
    permutation is validated against its own length before writing
    (a corrupt sidecar must never be produced, only detected)."""
    perm = np.ascontiguousarray(perm, dtype=V_DTYPE)
    out = path or perm_sidecar_path(lux_path)
    validate_perm(perm, perm.shape[0], out)
    with open(out, "wb") as f:
        f.write(PERM_MAGIC)
        f.write(np.array([perm.shape[0]], V_DTYPE).tobytes())
        f.write(perm.tobytes())
    return out


def read_perm_sidecar(lux_path: str, nv: int | None = None,
                      path: str | None = None) -> np.ndarray:
    """Read and VALIDATE the permutation sidecar next to
    ``lux_path``.  ``nv`` (when given, normally the .lux header's
    vertex count) must match the sidecar's — a sidecar copied from a
    different graph raises instead of silently relabeling."""
    p = path or perm_sidecar_path(lux_path)
    with open(p, "rb") as f:
        head = f.read(8)
        if len(head) != 8 or head[:4] != PERM_MAGIC:
            raise GraphFormatError(
                p, "perm_header",
                f"bad magic {head[:4]!r} (a .perm sidecar starts "
                f"with {PERM_MAGIC!r})")
        n = int(np.frombuffer(head, V_DTYPE, count=1, offset=4)[0])
        perm = np.frombuffer(f.read(), V_DTYPE)
    if perm.shape[0] != n:
        raise GraphFormatError(
            p, "perm_length",
            f"header says nv={n} but payload holds {perm.shape[0]} "
            f"entries — truncated or torn sidecar?")
    if nv is not None and n != nv:
        raise GraphFormatError(
            p, "perm_length",
            f"sidecar nv={n} does not match the graph's nv={nv} — "
            f"sidecar from a different graph?")
    validate_perm(perm, n, p)
    return perm


# ---------------------------------------------------------------------
# mutation-log (WAL) header (round 20, live graphs)
#
# The live-graph subsystem (lux_tpu/livegraph.py) journals every
# mutation into a CRC-chained append-only log BESIDE the graph it
# mutates.  The on-disk format knowledge lives here with the other
# formats (.lux, .perm): a 16-byte header (magic "LUXW" + uint32
# version + uint32 nv + uint32 delta capacity) followed by fixed
# 24-byte records whose chained CRC32 livegraph.MutationLog owns.
# Header validation is the same crash-don't-corrupt conversion as
# validate_graph — a log from a different graph (nv mismatch) or a
# foreign/garbage file raises a typed GraphFormatError instead of
# replaying wrong mutations into a wrong-answer serving epoch.

WAL_MAGIC = b"LUXW"
# v1 (round 20): append-only — record kinds EDGE/COMPACT_START/DONE.
# v2 (round 21): the full mutation algebra — DELETE and REWEIGHT
# record kinds join.  The record LAYOUT is unchanged (24-byte chained
# records), so the v2 reader replays v1 logs bitwise; a v2 record
# kind inside a v1-headered log is typed corruption (the kind set is
# part of the header version's contract — livegraph.MutationLog).
WAL_VERSION = 2
WAL_KNOWN_VERSIONS = (1, 2)
WAL_HEADER_SIZE = 16
WAL_RECORD_SIZE = 24
WAL_SUFFIX = ".wal"


def wal_sidecar_path(lux_path: str) -> str:
    return lux_path + WAL_SUFFIX


def pack_wal_header(nv: int, capacity: int,
                    version: int = WAL_VERSION) -> bytes:
    if version not in WAL_KNOWN_VERSIONS:
        raise ValueError(f"unknown WAL version {version} "
                         f"(known: {WAL_KNOWN_VERSIONS})")
    return WAL_MAGIC + np.array(
        [version, nv, capacity], V_DTYPE).tobytes()


def read_wal_header(path: str, nv: int | None = None,
                    head: bytes | None = None):
    """Read + VALIDATE a mutation-log header; returns (nv, capacity,
    version).  ``nv`` (when given) must match the header's — a log
    copied from a different graph raises instead of silently replaying
    foreign mutations.  ``head`` skips the file read (replay already
    holds the bytes)."""
    if head is None:
        with open(path, "rb") as f:
            head = f.read(WAL_HEADER_SIZE)
    if len(head) != WAL_HEADER_SIZE or head[:4] != WAL_MAGIC:
        raise GraphFormatError(
            path, "wal_header",
            f"bad magic/length {head[:4]!r} ({len(head)} bytes) — a "
            f"mutation log starts with {WAL_MAGIC!r} and a "
            f"{WAL_HEADER_SIZE}-byte header")
    ver, hnv, cap = (int(x) for x in
                     np.frombuffer(head, V_DTYPE, count=3, offset=4))
    if ver not in WAL_KNOWN_VERSIONS:
        raise GraphFormatError(
            path, "wal_version",
            f"log version {ver}, this build reads "
            f"{WAL_KNOWN_VERSIONS}")
    if cap < 1:
        raise GraphFormatError(
            path, "wal_capacity",
            f"delta capacity {cap} must be >= 1")
    if nv is not None and hnv != nv:
        raise GraphFormatError(
            path, "wal_header",
            f"log written for nv={hnv} but the graph has nv={nv} — "
            f"mutation log from a different graph?")
    return hnv, cap, ver


# ---------------------------------------------------------------------
# admission-journal header (round 24, self-healing fleet)
#
# The serving tier (lux_tpu/fleet.py) journals every ADMITTED query
# into a CRC-chained append-only log so a whole-fleet crash cannot
# silently lose admitted-but-unretired work — the same durability bar
# the mutation WAL meets for graph state.  The on-disk knowledge lives
# here beside the WAL's: a 16-byte header (magic "LUXJ" + uint32
# version + uint32 nv + uint32 reserved) followed by fixed 48-byte
# records whose chained CRC32 lux_tpu/journal.AdmissionJournal owns
# (ADMIT records open an entry; RETIRE records close it — pairing is
# validated at rest by the scan and by scripts/fsck_lux.py).  The nv
# in the header binds the journal to its graph: recovered queries
# carry source ids and admission epochs that are meaningless against
# a different graph.

JOURNAL_MAGIC = b"LUXJ"
JOURNAL_VERSION = 1
JOURNAL_KNOWN_VERSIONS = (1,)
JOURNAL_HEADER_SIZE = 16
JOURNAL_RECORD_SIZE = 48
JOURNAL_SUFFIX = ".journal"


def journal_sidecar_path(lux_path: str) -> str:
    return lux_path + JOURNAL_SUFFIX


def pack_journal_header(nv: int,
                        version: int = JOURNAL_VERSION) -> bytes:
    if version not in JOURNAL_KNOWN_VERSIONS:
        raise ValueError(f"unknown journal version {version} "
                         f"(known: {JOURNAL_KNOWN_VERSIONS})")
    return JOURNAL_MAGIC + np.array(
        [version, nv, 0], V_DTYPE).tobytes()


def read_journal_header(path: str, nv: int | None = None,
                        head: bytes | None = None):
    """Read + VALIDATE an admission-journal header; returns (nv,
    version).  ``nv`` (when given) must match the header's — a journal
    copied from a different graph raises instead of re-dispatching
    queries against sources/epochs it was never admitted for."""
    if head is None:
        with open(path, "rb") as f:
            head = f.read(JOURNAL_HEADER_SIZE)
    if len(head) != JOURNAL_HEADER_SIZE or head[:4] != JOURNAL_MAGIC:
        raise GraphFormatError(
            path, "journal_header",
            f"bad magic/length {head[:4]!r} ({len(head)} bytes) — an "
            f"admission journal starts with {JOURNAL_MAGIC!r} and a "
            f"{JOURNAL_HEADER_SIZE}-byte header")
    ver, hnv, _rsvd = (int(x) for x in
                       np.frombuffer(head, V_DTYPE, count=3, offset=4))
    if ver not in JOURNAL_KNOWN_VERSIONS:
        raise GraphFormatError(
            path, "journal_version",
            f"journal version {ver}, this build reads "
            f"{JOURNAL_KNOWN_VERSIONS}")
    if nv is not None and hnv != nv:
        raise GraphFormatError(
            path, "journal_header",
            f"journal written for nv={hnv} but the graph has nv={nv} "
            f"— admission journal from a different graph?")
    return hnv, ver


def write_lux(path: str, row_ptrs, col_idx, weights=None, degrees=None):
    """Write a .lux file from CSC arrays (row_ptrs are END offsets)."""
    row_ptrs = np.ascontiguousarray(row_ptrs, dtype=E_DTYPE)
    col_idx = np.ascontiguousarray(col_idx, dtype=V_DTYPE)
    nv = row_ptrs.shape[0]
    ne = col_idx.shape[0]
    if nv and int(row_ptrs[-1]) != ne:
        raise ValueError(f"row_ptrs[-1]={row_ptrs[-1]} != ne={ne}")
    with open(path, "wb") as f:
        f.write(np.array([nv], V_DTYPE).tobytes())
        f.write(np.array([ne], E_DTYPE).tobytes())
        f.write(row_ptrs.tobytes())
        f.write(col_idx.tobytes())
        if weights is not None:
            w = np.ascontiguousarray(weights)
            if w.shape[0] != ne:
                raise ValueError("weights length mismatch")
            f.write(w.tobytes())
        if degrees is not None:
            d = np.ascontiguousarray(degrees, dtype=V_DTYPE)
            if d.shape[0] != nv:
                raise ValueError("degrees length mismatch")
            f.write(d.tobytes())
