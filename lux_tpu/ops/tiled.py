"""Tiled (chunked) edge layout and scatter-free segment reduction.

The portable ``ops.segment.segment_reduce`` lowers to an XLA scatter,
which TPUs execute (near-)serially — measured ~0.05 GTEPS on the hot
loop.  This module is the TPU-native replacement for the reference's
CUB BlockScan + atomic scatter CTA pattern (reference
pagerank_gpu.cu:49-102, SURVEY.md §3.3): the host re-lays each
partition's dst-sorted edges into fixed-shape chunks bound to output
vertex tiles, so the device-side reduction is nothing but dense,
static-shape VPU/MXU work plus one short segmented scan:

- Output vertices are grouped into tiles of ``W``; edges (already
  dst-sorted and therefore tile-contiguous) are padded so each tile
  owns a whole number of ``E``-edge chunks -> arrays ``[C, E]``.
- Within a chunk, every edge's destination is a *relative* index in
  ``[0, W)`` (``W`` marks padding lanes).  The chunk's partial result
  ``[W]`` is a masked broadcast-reduce (VPU) or a one-hot matmul (MXU)
  — both fuse in XLA, neither scatters.
- Chunks of the same tile are combined with a segmented
  ``associative_scan`` over the chunk axis (flag-reset, exact — no
  cumsum boundary-difference cancellation), then the last chunk of
  each tile is gathered.  When every tile fits in one chunk the scan
  is skipped statically.

Degree skew (the Twitter/RMAT power-law "hard part", SURVEY.md §7) is
absorbed by construction: a hub vertex simply owns many chunks, and
every chunk is the same shape — the TPU analogue of the reference's
edge-parallel load balancing.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from lux_tpu.ops.segment import identity_for


def _ceil_div(a: int, b: int) -> int:
    return (a + b - 1) // b


def warn_sub128_tile(E: int) -> None:
    """Warn on chunk widths that leave the minor dim under the TPU
    tile: [.., C, E] edge arrays with E % 128 pad the minor dim to
    128 (2x HBM at E=64) AND the compiler inserts relayout copies of
    the whole arrays — measured as the difference between fitting and
    OOMing a 16 GB chip (PERF_NOTES round 4).  Shared by TiledLayout
    and OwnerLayout, which stack edges in the same shape."""
    if E % 128:
        import warnings
        warnings.warn(
            f"chunk width E={E} is not a multiple of 128: TPU tiled "
            f"layouts pad the minor dim to 128 and relayout-copy the "
            f"edge arrays (PERF_NOTES round 4); use multiples of 128",
            stacklevel=3)


@dataclasses.dataclass
class TiledLayout:
    """Host-side chunk plan for one partitioned graph (stacked over
    parts; all chunk arrays are ``[num_parts, C, ...]``)."""

    W: int                      # vertices per output tile
    E: int                      # edges per chunk
    n_tiles: int                # ceil(vpad / W), same for every part
    n_chunks: int               # padded chunk count C (max over parts)
    needs_scan: bool            # False when every tile fits in 1 chunk
    edge_gather: np.ndarray     # int64 [P, C, E] index into flat [epad]
    rel_dst: np.ndarray         # int8 [P, C, E] in [0, W); -1 = pad
                                #   lane (int8: quarters the second-
                                #   largest device array; valid values
                                #   are 0..127 and the pad marker only
                                #   needs to MATCH NO LANE, so -1
                                #   serves where W=128 cannot fit)
    chunk_tile: np.ndarray      # int32 [P, C] owning tile; n_tiles = pad
    chunk_start: np.ndarray     # bool  [P, C] True at each tile's 1st chunk
    last_chunk: np.ndarray      # int32 [P, n_tiles] index of tile's last
                                #   chunk, -1 for edge-less tiles

    @classmethod
    def build(cls, row_ptr_local: np.ndarray, dst_local: np.ndarray,
              vpad: int, W: int = 128, E: int = 512,
              sizing_row_ptr: np.ndarray | None = None) -> "TiledLayout":
        """row_ptr_local: int [P, vpad+1] END offsets; dst_local:
        int32 [P, epad] part-local sorted destinations (pad -> vpad).

        sizing_row_ptr: row_ptr_local rows of ALL parts, when
        ``row_ptr_local`` holds only a process's local parts — chunk
        count and scan-necessity are program SHAPE/structure and must
        be identical on every process of a multi-host run."""
        if W > 128:
            raise ValueError(
                f"tile width W={W} > 128: rel_dst is int8 (valid lane "
                f"offsets 0..127, -1 = pad) and wider tiles would wrap "
                f"offsets >= 128 negative, silently dropping edges")
        warn_sub128_tile(E)
        P = row_ptr_local.shape[0]
        n_tiles = max(1, _ceil_div(vpad, W))

        def tile_chunks(rp_row):
            rp = rp_row.astype(np.int64)
            tile_lo = rp[np.minimum(np.arange(n_tiles) * W, vpad)]
            tile_hi = rp[np.minimum((np.arange(n_tiles) + 1) * W, vpad)]
            n_ch = np.maximum(0, _ceil_div_arr(tile_hi - tile_lo, E))
            return tile_lo, tile_hi, n_ch

        per_part = [tile_chunks(row_ptr_local[p]) for p in range(P)]
        sizing = (per_part if sizing_row_ptr is None else
                  [tile_chunks(r) for r in sizing_row_ptr])

        # Pad the chunk count to the Pallas kernel's block granularity
        # (pad chunks are isolated identity segments, dropped by the
        # last-chunk gather).
        C = max(1, int(max(int(x[2].sum()) for x in sizing)))
        C = _ceil_div(C, 8) * 8
        global_needs_scan = any(x[2].max(initial=0) > 1 for x in sizing)

        edge_gather = np.zeros((P, C, E), dtype=np.int64)
        rel_dst = np.full((P, C, E), -1, dtype=np.int8)
        chunk_tile = np.full((P, C), n_tiles, dtype=np.int32)
        chunk_start = np.ones((P, C), dtype=bool)   # pad chunks isolated
        last_chunk = np.full((P, n_tiles), -1, dtype=np.int32)
        needs_scan = global_needs_scan

        lanes = np.arange(E, dtype=np.int64)
        for p in range(P):
            tile_lo, tile_hi, n_ch = per_part[p]
            nc = int(n_ch.sum())
            if nc == 0:
                continue
            # chunk -> owning tile, and chunk's index within that tile
            ct = np.repeat(np.arange(n_tiles, dtype=np.int64), n_ch)
            tile_first = np.concatenate(([0], np.cumsum(n_ch)[:-1]))
            cj = np.arange(nc, dtype=np.int64) - tile_first[ct]
            start = tile_lo[ct] + cj * E
            idx = start[:, None] + lanes[None, :]          # [nc, E]
            valid = idx < tile_hi[ct][:, None]
            idx = np.where(valid, idx, 0)
            edge_gather[p, :nc] = idx
            rel_dst[p, :nc] = np.where(
                valid, dst_local[p][idx] - (ct * W)[:, None], -1)
            chunk_tile[p, :nc] = ct
            chunk_start[p, :nc] = cj == 0
            last_chunk[p] = np.where(n_ch > 0, np.cumsum(n_ch) - 1, -1)

        return cls(W=W, E=E, n_tiles=n_tiles, n_chunks=C,
                   needs_scan=needs_scan, edge_gather=edge_gather,
                   rel_dst=rel_dst, chunk_tile=chunk_tile,
                   chunk_start=chunk_start, last_chunk=last_chunk)

    def chunk(self, flat: np.ndarray) -> np.ndarray:
        """Re-lay a per-part flat edge array [P, epad, ...] into chunk
        form [P, C, E, ...] (host, done once at build time)."""
        parts = np.arange(flat.shape[0])[:, None, None]
        return flat[parts, self.edge_gather]


def _ceil_div_arr(a, b):
    return (a + b - 1) // b


def combine_op(kind: str):
    """The binary combiner for a reduce kind (shared lookup)."""
    return {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}[kind]


_combine = combine_op


class MXUUnsupportedError(ValueError):
    """A (kind, dtype) combination the MXU contraction port does not
    cover.  After the round-23 port the one-hot paths serve sum, min
    and max over every <= 32-bit int/uint/float payload; what remains
    genuinely unsupported is named here so callers (and the auto
    resolver) can fall back to the VPU formulation deliberately
    instead of tripping an anonymous ValueError."""

    def __init__(self, kind: str, dtype, why: str):
        self.kind = kind
        self.dtype = np.dtype(dtype) if dtype is not None else None
        super().__init__(
            f"MXU one-hot path does not support kind={kind!r} on "
            f"dtype {self.dtype}: {why}")


def _lane_onehot(rel_dst, W: int):
    """int8 lane-membership matrix [..., E, W]: row e is one-hot at
    rel_dst[..., e] and ALL-ZERO for pad lanes (rel == -1 matches no
    lane) — int8 is the narrowest operand dtype the mixed-dtype MXU
    contraction accepts (`preferred_element_type` keeps the
    accumulator in the payload dtype), 4x narrower than the payload-
    dtype one-hot the round-5 sum path materialized."""
    return (rel_dst[..., None] ==
            jnp.arange(W, dtype=rel_dst.dtype)).astype(jnp.int8)


# Order-preserving bit encodings for the compare-reduce tournament:
# map the payload to uint bit patterns whose UNSIGNED order matches
# the payload order, so min/max become bitwise votes MSB-first.
_MXU_SIGN32 = jnp.uint32(0x80000000)


def _order_bits(dtype) -> int:
    """Tournament rounds for a payload dtype (bits of its order
    encoding); raises the typed error for unsupported combos."""
    dt = np.dtype(dtype)
    if dt.kind in "iu":
        return dt.itemsize * 8
    if dt.kind == "f":
        if dt.itemsize > 4:
            raise MXUUnsupportedError(
                "min/max", dt, "f64 violates the 4-byte dtype "
                "discipline (no order encoding fits uint32)")
        return dt.itemsize * 8
    raise MXUUnsupportedError(
        "min/max", dt, "no order-preserving bit encoding (only "
        "int/uint/float payloads reduce by comparison)")


def _order_encode(x):
    """Payload -> uint32 whose unsigned order matches the payload
    order.  Ints: two's-complement bias.  Floats: the IEEE-754
    sign-magnitude fold (negative -> flip all bits, else set the sign
    bit) — a TOTAL order agreeing with < on non-NaN values; -0.0
    sorts below +0.0 and NaN payloads are out of contract (the repo's
    oracles never produce them)."""
    dt = np.dtype(x.dtype)
    bits = _order_bits(dt)
    if dt.kind == "u":
        return x.astype(jnp.uint32)
    if dt.kind == "i":
        if dt.itemsize == 4:
            return jax.lax.bitcast_convert_type(
                x, jnp.uint32) ^ _MXU_SIGN32
        # narrow ints: bias into [0, 2^bits) in int32, then reinterpret
        lo = int(np.iinfo(dt).min)
        return (x.astype(jnp.int32) - lo).astype(jnp.uint32)
    # floats: fold via the same-width uint, then widen
    udt = {2: jnp.uint16, 4: jnp.uint32}[dt.itemsize]
    u = jax.lax.bitcast_convert_type(x, udt).astype(jnp.uint32)
    sign = jnp.uint32(1) << (bits - 1)
    mask = (jnp.uint32(0xFFFFFFFF) >> (32 - bits))
    return jnp.where((u & sign) != 0, (~u) & mask, u | sign)


def _order_decode(m, dtype):
    """Inverse of _order_encode (m uint32 -> payload dtype)."""
    dt = np.dtype(dtype)
    bits = _order_bits(dt)
    if dt.kind == "u":
        return m.astype(dt)
    if dt.kind == "i":
        if dt.itemsize == 4:
            return jax.lax.bitcast_convert_type(m ^ _MXU_SIGN32,
                                                jnp.int32)
        lo = int(np.iinfo(dt).min)
        return (m.astype(jnp.int32) + lo).astype(dt)
    sign = jnp.uint32(1) << (bits - 1)
    mask = (jnp.uint32(0xFFFFFFFF) >> (32 - bits))
    u = jnp.where((m & sign) != 0, m ^ sign, (~m) & mask)
    udt = {2: jnp.uint16, 4: jnp.uint32}[dt.itemsize]
    return jax.lax.bitcast_convert_type(u.astype(udt), dt)


def _mxu_compare_reduce(vals, rel_dst, W: int, kind: str):
    """min/max per-chunk reduction as one-hot MXU contractions: a
    radix tournament over the payload's order encoding, MSB first.
    Per bitplane, two contractions against the SHARED int8 one-hot
    lane-membership matrix: a vote (does any still-candidate lane of
    this dst slot carry the bit?) and the transposed route-back that
    narrows each lane's candidacy to the slot's winning prefix — the
    same forward/transpose pairing as the pair path's one-hot
    gradient matmul (ops/pairs.pair_partial_dot).  Bitwise-equal to
    the VPU masked reduce for integer payloads; floats inherit the
    encoding's total order (-0.0/+0.0 ties resolve deterministically
    instead of by reduction order).  K/B trailing payload axes ride
    as free minor dims of every contraction.

    Padding contract: pad lanes (rel == -1) have all-zero one-hot
    rows, so they never vote; slots no live lane maps to keep an
    occupancy of 0 and are filled with the reduce identity — padding
    contributes the identity, per the one-identity convention."""
    if kind not in ("min", "max"):
        raise MXUUnsupportedError(kind, vals.dtype,
                                  "unknown compare-reduce kind")
    bits = _order_bits(vals.dtype)
    onehot = _lane_onehot(rel_dst, W)              # [C, E, W] int8
    m = _order_encode(vals)                        # [C, E, ...] uint32
    if kind == "min":
        # min = bitwise complement of max in the order domain
        m = (~m) & (jnp.uint32(0xFFFFFFFF) >> (32 - bits))
    C, E = m.shape[:2]
    trail = m.shape[2:]
    occ = jnp.einsum("ce,cew->cw", jnp.ones((C, E), jnp.int8), onehot,
                     preferred_element_type=jnp.int32) > 0   # [C, W]
    cand0 = jnp.ones(m.shape, jnp.bool_)
    res0 = jnp.zeros((C, W) + trail, jnp.uint32)

    def bitplane(i, carry):
        cand, res = carry
        b = (bits - 1 - i).astype(jnp.uint32)
        bit = (jnp.right_shift(m, b) & jnp.uint32(1)).astype(jnp.int32)
        t = jnp.where(cand, bit, 0).astype(jnp.int8)
        cnt = jnp.einsum("ce...,cew->cw...", t, onehot,
                         preferred_element_type=jnp.int32)
        has = cnt > 0                                # [C, W, ...]
        res = res | jnp.left_shift(has.astype(jnp.uint32), b)
        back = jnp.einsum("cw...,cew->ce...", has.astype(jnp.int8),
                          onehot, preferred_element_type=jnp.int32)
        cand = cand & (back == bit)
        return cand, res

    _, res = jax.lax.fori_loop(0, bits, bitplane, (cand0, res0))
    if kind == "min":
        res = (~res) & (jnp.uint32(0xFFFFFFFF) >> (32 - bits))
    out = _order_decode(res, vals.dtype)
    ident = identity_for(kind, vals.dtype)
    occb = occ.reshape(occ.shape + (1,) * len(trail))
    return jnp.where(occb, out, ident)


def chunk_partials(vals, rel_dst, W: int, kind: str, use_mxu: bool = False):
    """Per-chunk reduction [C, E, ...] -> [C, W, ...].

    use_mxu=True contracts against an int8 one-hot lane-membership
    matrix on the MXU: sum is one mixed-dtype contraction
    (`preferred_element_type` pins the accumulator to the payload
    dtype, keeping the dtype-discipline audit green); min/max run the
    radix tournament (_mxu_compare_reduce) — bitwise-equal to the VPU
    path for integer payloads, total-order-equal for floats.  The
    default masked broadcast-reduce stays on the VPU and fuses without
    materializing the [C, E, W] intermediate; the MXU path holds the
    one-hot live ([C, E, W] int8 — priced by graph.memory_report's
    ``mxu_temp`` term and amortized by the streamed block bound).
    """
    if use_mxu:
        dt = np.dtype(vals.dtype)
        if dt.kind not in "iuf" or dt.itemsize > 4:
            raise MXUUnsupportedError(
                kind, dt, "payload has no MXU contraction (only "
                "<= 32-bit int/uint/float states)")
        if kind == "sum":
            onehot = _lane_onehot(rel_dst, W)
            # [C, E, ...] x [C, E, W] -> [C, W, ...]; pad lanes have
            # all-zero one-hot rows = the sum identity
            return jnp.einsum("ce...,cew->cw...", vals, onehot,
                              preferred_element_type=vals.dtype)
        if kind in ("min", "max"):
            return _mxu_compare_reduce(vals, rel_dst, W, kind)
        raise MXUUnsupportedError(kind, dt, "unknown reduce kind")
    ident = identity_for(kind, vals.dtype)
    match = rel_dst[..., None] == jnp.arange(W, dtype=rel_dst.dtype)
    if vals.ndim > 2:                       # vector payload [C, E, K]
        match = match[:, :, None, :]        # [C, E, 1, W]
        masked = jnp.where(match, vals[..., None], ident)
        red = _reduce_axis(masked, 1, kind)     # [C, K, W]
        return jnp.moveaxis(red, -1, 1)         # [C, W, K]
    masked = jnp.where(match, vals[..., None], ident)   # [C, E, W]
    return _reduce_axis(masked, 1, kind)


def _reduce_axis(x, axis, kind):
    return {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[kind](
        x, axis=axis)


# combine_chunks switches to the BLOCKED segmented scan once the
# chunk axis passes this length: jax.lax.associative_scan over
# [C, W] materializes O(log C) tree levels of BOTH tuple operands, ~2
# * log2(C) * C * W * 4 bytes of program memory — measured as the
# 11.17 GB "program" term that OOM'd the 16 GB chip at C~1.4M/part
# (RMAT26 pair residual; also the round-3 E=128/scale-26 worker
# crash).  The blocked form scans SCAN_BLOCK-chunk slices with a
# carry, so live memory is one block's tree + the [C, W] output.
SCAN_BLOCK_CHUNKS = 16384
SCAN_BLOCKED_ABOVE = 1 << 17


def _segscan(partials, flags, kind):
    """Flag-reset segmented combine along axis 0 (within one block).
    flags broadcast [C, 1...] bool; True = position starts a segment."""
    comb = _combine(kind)

    def op(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, comb(va, vb)), fa | fb

    vals, _ = jax.lax.associative_scan(
        op, (partials, jnp.broadcast_to(flags, partials.shape)))
    return vals


def combine_chunks(partials, layout: TiledLayout, chunk_start, last_chunk,
                   kind: str, use_mxu: bool = False):
    """Segmented combine of per-chunk partials [C, W, ...] into tile
    results [n_tiles, W, ...]; chunk_start/last_chunk are this part's
    rows of the layout arrays (device).

    use_mxu=True routes the sum-kind scan through _segscan_matmul (the
    TCU-paper scan-as-matmul recurrence); min/max segmented scans stay
    on the VPU — a prefix scan's candidacy is per-OUTPUT-position, so
    the bit-serial tournament that serves chunk_partials has no
    matmul form here (each row of the segment matrix would need its
    own vote), and the flag-reset associative scan is already
    O(C log C) compares."""
    if layout.needs_scan:
        C = partials.shape[0]
        if use_mxu and kind == "sum":
            partials = _segscan_matmul(partials, chunk_start)
        elif C <= SCAN_BLOCKED_ABOVE:
            flags = chunk_start.reshape(
                chunk_start.shape + (1,) * (partials.ndim - 1))
            partials = _segscan(partials, flags, kind)
        else:
            partials = _segscan_blocked(partials, chunk_start, kind)
    ident = identity_for(kind, partials.dtype)
    out = jnp.take(partials, jnp.maximum(last_chunk, 0), axis=0)
    empty = (last_chunk < 0).reshape(
        last_chunk.shape + (1,) * (out.ndim - 1))
    return jnp.where(empty, ident, out)


def _segscan_blocked(partials, chunk_start, kind,
                     block: int | None = None):
    """Blocked segmented combine: lax.scan over SCAN_BLOCK-chunk
    slices; each step runs the in-block associative scan, then folds
    the carry (the previous block's running value) into every
    position BEFORE the block's first segment flag.  Identical result
    to the monolithic scan with O(block) live tree memory."""
    if block is None:
        # read at call time so tests can shrink the module constant
        block = SCAN_BLOCK_CHUNKS
    comb = _combine(kind)
    C = partials.shape[0]
    trail = partials.shape[1:]
    nB = _ceil_div(C, block)
    Cp = nB * block
    ident = identity_for(kind, partials.dtype)
    if Cp != C:
        # pad chunks are isolated identity segments (same convention
        # as the layout's pad chunks)
        partials = jnp.concatenate(
            [partials, jnp.full((Cp - C,) + trail, ident,
                                partials.dtype)], axis=0)
        chunk_start = jnp.concatenate(
            [chunk_start, jnp.ones(Cp - C, bool)], axis=0)

    def step(carry, x):
        p_b, f_b = x
        fb = f_b.reshape(f_b.shape + (1,) * len(trail))
        inner = _segscan(p_b, fb, kind)
        # positions with NO flag at-or-before them continue the
        # previous block's segment
        absorb = jnp.cumsum(f_b.astype(jnp.int32)) == 0
        ab = absorb.reshape(absorb.shape + (1,) * len(trail))
        out = jnp.where(ab, comb(carry, inner), inner)
        return out[-1], out

    carry0 = jnp.full(trail, ident, partials.dtype)
    _, blocks = jax.lax.scan(
        step, carry0,
        (partials.reshape((nB, block) + trail),
         chunk_start.reshape(nB, block)))
    return blocks.reshape((Cp,) + trail)[:C]


# Block length for the scan-as-matmul segmented combine: the int8
# segment matrix is block^2 bytes (64 KB at 256) and one einsum row
# is a 256-wide MXU contraction — small enough to stay resident,
# large enough to amortize the lax.scan step (the blocked-memory
# contract above is preserved: live memory is one block's [B, B]
# matrix + the [C, W] output, never an O(log C) tree).
MXU_SCAN_BLOCK = 256


def _segscan_matmul(partials, chunk_start, block: int | None = None):
    """Segmented inclusive SUM scan along axis 0 as blocked matrix
    products (TCU scan-as-matmul, PAPERS.md): per block the lower-
    triangular same-segment matrix T[i, j] = (i >= j) & (seg i == seg
    j) is built ON DEVICE from cumsum(flags) (no baked constant — the
    413 const-bytes audit stays green) and one int8 contraction
    produces every prefix in the block; the carry folds into rows
    before the block's first flag exactly as _segscan_blocked.
    Sum-only: min/max have no matmul recurrence (see combine_chunks).
    Bitwise-equal to the flag-reset scan for integer payloads."""
    if block is None:
        block = MXU_SCAN_BLOCK
    C = partials.shape[0]
    trail = partials.shape[1:]
    nB = _ceil_div(C, block)
    Cp = nB * block
    ident = identity_for("sum", partials.dtype)
    if Cp != C:
        # pad chunks are isolated identity segments, as in
        # _segscan_blocked
        partials = jnp.concatenate(
            [partials, jnp.full((Cp - C,) + trail, ident,
                                partials.dtype)], axis=0)
        chunk_start = jnp.concatenate(
            [chunk_start, jnp.ones(Cp - C, bool)], axis=0)
    ii = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)

    def step(carry, x):
        p_b, f_b = x
        sid = jnp.cumsum(f_b.astype(jnp.int32))
        T = ((ii >= jj) &
             (sid[:, None] == sid[None, :])).astype(jnp.int8)
        inner = jnp.einsum("ij,j...->i...", T, p_b,
                           preferred_element_type=p_b.dtype)
        absorb = sid == 0       # no flag at-or-before: continue carry
        ab = absorb.reshape(absorb.shape + (1,) * len(trail))
        out = jnp.where(ab, carry + inner, inner)
        return out[-1], out

    carry0 = jnp.full(trail, ident, partials.dtype)
    _, blocks = jax.lax.scan(
        step, carry0,
        (partials.reshape((nB, block) + trail),
         chunk_start.reshape(nB, block)))
    return blocks.reshape((Cp,) + trail)[:C]


# lax.map block size for streamed_chunk_partials (chunks per block)
STREAM_BLOCK_CHUNKS = 1024

# Engines stream the gather + partials once the [rows, C, E] f32
# message/candidate temporary would exceed this many bytes — it is
# what OOMs billion-edge single-chip runs (PERF_NOTES RMAT26 ledger).
STREAM_MSG_BYTES = 1 << 30


def unpack_src_rel(packed, n_valid):
    """Decode the PACKED owner slot encoding (ops/owner.OwnerLayout:
    uint32 src_local << 7 | rel, live-lane counts per chunk) back to
    (src int32, rel int8 with -1 pads) — done INSIDE each streamed
    block so the decoded arrays only ever exist one block at a time
    (the entire point: the packed form saves the int8 rel array's
    2.66 GB at RMAT27, PERF_NOTES round 5)."""
    src = jax.lax.shift_right_logical(
        packed, jnp.uint32(7)).astype(jnp.int32)
    rel = (packed & jnp.uint32(0x7F)).astype(jnp.int8)
    lane = jax.lax.broadcasted_iota(jnp.int32, packed.shape,
                                    packed.ndim - 1)
    live = lane < n_valid[..., None].astype(jnp.int32)
    return jnp.where(live, src, 0), jnp.where(live, rel, jnp.int8(-1))


def _block_partials(flat_state, src_b, rel_b, w_b, msg_fn, kind: str,
                    E: int, W: int, reduce_method: str,
                    use_mxu: bool, nv_b=None):
    """One chunk block's gather + message + per-chunk partials
    [B, E, ...] -> [B, W, ...] (shared by the streamed partial and
    FUSED streamed combine paths — keep the Pallas VMEM sizing and
    the barrier rationale in ONE place).  nv_b set => src_b is the
    packed owner encoding (see unpack_src_rel) and rel_b must be
    None."""
    if nv_b is not None:
        src_b, rel_b = unpack_src_rel(src_b, nv_b)
    vals = jnp.take(flat_state, src_b, axis=0)
    msgs = msg_fn(vals, w_b)
    if reduce_method.startswith("pallas") and msgs.ndim == 2:
        from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
        # the kernel's [bc, E, W] masked intermediate must fit
        # scoped VMEM (~16 MB): bc=64 fits E<=128 (pair-residual
        # tile_e), E=512 needs bc=8
        bc = 64 if E * 64 * W * 4 <= (8 << 20) else 8
        return chunk_partials_pallas(
            msgs, rel_b, W, kind,
            block_c=bc if msgs.shape[0] % bc == 0 else 8,
            interpret=reduce_method == "pallas-interpret")
    # keep the (serial, expensive) gather out of the W-wide
    # broadcast consumer on EVERY non-kernel path (see the barrier
    # note in PullEngine._part_msgs)
    msgs = jax.lax.optimization_barrier(msgs)
    return chunk_partials(msgs, rel_b, W, kind, use_mxu=use_mxu)


def streamed_chunk_partials(flat_state, src_slot, rel_dst, weight,
                            layout: TiledLayout, kind: str, msg_fn,
                            reduce_method: str, use_mxu: bool = False,
                            block_chunks: int = STREAM_BLOCK_CHUNKS,
                            nvalid=None):
    """Gather + message + per-chunk partials for ONE part, streamed in
    lax.map blocks over the chunk axis -> [C, W, ...] partials.

    Bounds the [C, E] message/gather temporaries that OOM billion-edge
    single-chip runs (PERF_NOTES RMAT26 ledger).  msg_fn(vals [B, E,
    ...], weight [B, E]|None) -> messages; dead lanes are masked by
    rel == -1 (matching no output lane) downstream.  Shared by the pull engine's step and the
    push engine's dense iterations."""
    C, E, W = layout.n_chunks, layout.E, layout.W
    B = max(8, min(block_chunks, C))
    nB, rem = divmod(C, B)

    def partial_block(src_b, rel_b, w_b, nv_b=None):
        return _block_partials(flat_state, src_b, rel_b, w_b, msg_fn,
                               kind, E, W, reduce_method, use_mxu,
                               nv_b=nv_b)

    packed = nvalid is not None
    second = nvalid if packed else rel_dst   # rides the block split
    parts = []
    if nB:
        def seg(x):
            return x[:nB * B].reshape((nB, B) + x.shape[1:])

        xs = (seg(src_slot), seg(second)) + \
            (() if weight is None else (seg(weight),))

        def one(x):
            w_b = x[2] if len(x) > 2 else None
            if packed:
                return partial_block(x[0], None, w_b, nv_b=x[1])
            return partial_block(x[0], x[1], w_b)

        blocks = jax.lax.map(one, xs)     # [nB, B, W, ...]
        parts.append(blocks.reshape((nB * B,) + blocks.shape[2:]))
    if rem:
        tail2 = second[nB * B:]
        parts.append(partial_block(
            src_slot[nB * B:], None if packed else tail2,
            None if weight is None else weight[nB * B:],
            nv_b=tail2 if packed else None))
    return jnp.concatenate(parts, axis=0)


def build_extract_plan(last_chunk_rows: np.ndarray, C: int,
                       block: int | None = None,
                       L: int | None = None):
    """Host-side plan for extracting per-tile results from the FUSED
    streamed combine (streamed_chunk_combined) without materializing
    the [C, W] running values: for each ``block``-chunk slice, the
    in-block positions of the tiles whose LAST chunk falls in it.

    last_chunk_rows: int32 [R, n_tiles] (-1 = edge-less tile).
    Returns (extr_pos int32 [R, nB, L], extr_tile int32 [R, nB, L]):
    at each scan step the fused combine reads the block's running
    values at extr_pos (pad -> 0) and SCATTERS them into the carried
    [n_tiles + 1, W] output at extr_tile (pad -> n_tiles, the trash
    row) — carrying the output instead of stacking per-block rows,
    because runs of single-chunk tiles (sparse tails) make every
    chunk a last chunk and a stacked emission degenerates to the very
    [C, W] array this path exists to avoid.  L is the max last-chunk
    count of any (row, block) — it is PROGRAM SHAPE, so multi-host
    callers must pass an allreduced value (OwnerLayout.extract_plan
    does); default = this build's max."""
    lc = np.asarray(last_chunk_rows, np.int64)
    R, n_tiles = lc.shape
    if block is None:
        # read at call time: must match streamed_chunk_combined's
        # block (both default to the module constant)
        block = STREAM_BLOCK_CHUNKS
    nB = max(1, _ceil_div(C, block))
    need = extract_plan_width(lc, C, block)
    if L is None:
        L = need
    elif L < need:
        raise ValueError(f"extract width L={L} < this build's {need}")
    extr_pos = np.zeros((R, nB, L), np.int32)
    extr_tile = np.full((R, nB, L), n_tiles, np.int32)
    for r in range(R):
        live = np.nonzero(lc[r] >= 0)[0]
        if not live.size:
            continue
        c = lc[r][live]
        b = c // block
        order = np.argsort(b, kind="stable")
        bs = b[order]
        newb = np.ones(len(bs), bool)
        newb[1:] = bs[1:] != bs[:-1]
        pos = np.arange(len(bs))
        gst = np.maximum.accumulate(np.where(newb, pos, 0))
        slot = pos - gst                     # rank within block
        extr_pos[r, bs, slot] = (c[order] - bs * block).astype(np.int32)
        extr_tile[r, bs, slot] = live[order].astype(np.int32)
    return extr_pos, extr_tile


def extract_plan_width(last_chunk_rows: np.ndarray, C: int,
                       block: int | None = None) -> int:
    """Max last-chunks per (row, block) — the L this build needs."""
    lc = np.asarray(last_chunk_rows, np.int64)
    if block is None:
        block = STREAM_BLOCK_CHUNKS
    nB = max(1, _ceil_div(C, block))
    best = 1
    for r in range(lc.shape[0]):
        live = lc[r] >= 0
        if live.any():
            cnt = np.bincount(lc[r][live] // block, minlength=nB)
            best = max(best, int(cnt.max()))
    return best


def streamed_chunk_combined(flat_state, src_slot, rel_dst, weight,
                            layout, kind: str, msg_fn,
                            reduce_method: str, chunk_start,
                            extr_pos, extr_tile, last_chunk,
                            use_mxu: bool = False,
                            block_chunks: int | None = None,
                            varying_axis=None, nvalid=None):
    """Fused streamed gather + message + per-chunk partials +
    BLOCKED segmented combine + last-chunk extraction for ONE part:
    returns per-tile results [n_tiles, W, ...] WITHOUT ever
    materializing the [C, W] running values — the two [C, W]
    temporaries (stacked partials + combined output) are what pushes
    billion-edge owner programs past HBM even with the blocked scan
    (PERF_NOTES round 4).

    extr_pos/extr_tile: this part's rows of build_extract_plan(...,
    block=block_chunks); chunk_start bool [C]; last_chunk int32
    [n_tiles] (only its < 0 mask is used here).  The scan carries the
    running segmented value across blocks exactly like
    _segscan_blocked PLUS the [n_tiles + 1, W] output, scattering
    each block's last-chunk rows into it (the trailing trash row
    absorbs pad slots) — the carried output is written in place by
    XLA, so live memory stays one block plus one result."""
    C, E, W = layout.n_chunks, layout.E, layout.W
    if block_chunks is None:
        block_chunks = STREAM_BLOCK_CHUNKS
    B = max(8, min(block_chunks, C))
    nB = _ceil_div(C, B)
    Cp = nB * B
    comb = _combine(kind)

    def pad_c(x, fill):
        if Cp == C:
            return x
        return jnp.concatenate(
            [x, jnp.full((Cp - C,) + x.shape[1:], fill, x.dtype)],
            axis=0)

    packed = nvalid is not None
    src_slot = pad_c(src_slot, 0)
    second = pad_c(nvalid, 0) if packed else pad_c(rel_dst, -1)
    if weight is not None:
        weight = pad_c(weight, 0)
    chunk_start = pad_c(chunk_start, True)

    def partial_block(src_b, rel_b, w_b, nv_b=None):
        return _block_partials(flat_state, src_b, rel_b, w_b, msg_fn,
                               kind, E, W, reduce_method, use_mxu,
                               nv_b=nv_b)

    msg_aval = jax.eval_shape(
        lambda: msg_fn(jnp.take(flat_state,
                                src_slot[:1].astype(jnp.int32),
                                axis=0),
                       None if weight is None else weight[:1]))
    ident = identity_for(kind, msg_aval.dtype)
    trail = msg_aval.shape[2:]

    def step(carry, x):
        run, acc = carry
        src_b, sec_b, f_b, ep, et = x[:5]
        w_b = x[5] if len(x) > 5 else None
        if packed:
            partials = partial_block(src_b, None, w_b, nv_b=sec_b)
        else:
            partials = partial_block(src_b, sec_b, w_b)   # [B, W, ...]
        fb = f_b.reshape(f_b.shape + (1,) * (partials.ndim - 1))
        inner = _segscan(partials, fb, kind)
        absorb = jnp.cumsum(f_b.astype(jnp.int32)) == 0
        ab = absorb.reshape(absorb.shape + (1,) * (partials.ndim - 1))
        out = jnp.where(ab, comb(run, inner), inner)
        # each tile's last chunk occurs exactly once across all
        # blocks: a plain set into the carried output (pad slots land
        # in the trailing trash row)
        acc = acc.at[et].set(jnp.take(out, ep, axis=0))
        return (out[-1], acc), None

    def seg(x):
        return x.reshape((nB, B) + x.shape[1:])

    xs = (seg(src_slot), seg(second), seg(chunk_start), extr_pos,
          extr_tile)
    if weight is not None:
        xs = xs + (seg(weight),)
    n_tiles = last_chunk.shape[0]
    run0 = jnp.full((W,) + trail, ident, msg_aval.dtype)
    acc0 = jnp.full((n_tiles + 1, W) + trail, ident, msg_aval.dtype)
    if varying_axis is not None:
        # under shard_map the constant initial carry must be marked
        # device-varying (the scan folds in sharded contributions)
        run0 = jax.lax.pcast(run0, (varying_axis,), to="varying")
        acc0 = jax.lax.pcast(acc0, (varying_axis,), to="varying")
    (_, acc), _ = jax.lax.scan(step, (run0, acc0), xs)
    out = acc[:n_tiles]                               # [n_tiles, W, ..]
    empty = (last_chunk < 0).reshape(
        last_chunk.shape + (1,) * (out.ndim - 1))
    return jnp.where(empty, ident, out)


def combine_partials(partials, layout: TiledLayout, chunk_start,
                     last_chunk, vpad: int, kind: str,
                     use_mxu: bool = False):
    """Per-chunk partials [C, W, ...] -> flat [vpad, ...] (the shared
    tail of tiled_segment_reduce, also used by the streamed engines
    that produce partials block-wise)."""
    tiles = combine_chunks(partials, layout, chunk_start, last_chunk,
                           kind, use_mxu=use_mxu)
    flatshape = (layout.n_tiles * layout.W,) + tiles.shape[2:]
    return tiles.reshape(flatshape)[:vpad]


def tiled_segment_reduce(vals, layout: TiledLayout, chunk_start,
                         last_chunk, rel_dst, vpad: int, kind: str,
                         use_mxu: bool = False, method: str = "xla",
                         interpret: bool = False):
    """Full scatter-free segment reduce for ONE part.

    vals [C, E, ...] chunked edge messages; returns [vpad, ...] —
    drop-in for ``segment_reduce(msgs, dst_local, vpad+1, kind)[:vpad]``.

    method 'pallas' runs the per-chunk partial reduction as a Pallas
    TPU kernel (ops/pallas_reduce.py) — scalar payloads only; 'xla'
    is the portable broadcast-compare formulation.
    """
    if method == "pallas" and vals.ndim == 2:
        from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
        partials = chunk_partials_pallas(vals, rel_dst, layout.W, kind,
                                         interpret=interpret)
    else:
        partials = chunk_partials(vals, rel_dst, layout.W, kind,
                                  use_mxu=use_mxu)
    return combine_partials(partials, layout, chunk_start, last_chunk,
                            vpad, kind, use_mxu=use_mxu)
