"""Pair-lane delivery: gather-free edge values for dense tile pairs.

Measured fact (PERF_NOTES.md): the XLA gather costs ~9 ns per ROW
fetched, independent of row width.  So edges in a dense (src-tile,
dst-tile) pair — both tiles 128 vertices — can all be served by
fetching the pair's 128-wide source state row ONCE per pair-row:
lane = source offset within the src tile, so the value needs no
selection at all; the existing chunk-partial compare-reduce routes it
to its destination offset (``rel_dst``).

Under a degree-sorted vertex numbering (hubs share tiles), pairs with
>= 8 edges cover ~74% of RMAT edges at ~6x lane inflation — ~3 ns/edge
total against 9 ns for the per-edge gather.  The residual sparse-pair
edges keep the regular gather path.

Row layout: pair (s, t) with maximum per-source multiplicity m gets m
rows; occurrence o of source lane c carries the o-th edge (s*128+c ->
t*128+rel).  Unused lanes carry rel = -1 (matches no lane; int8).
Rows are grouped per destination tile and depth-classed so the
cross-row combine is a static reshape-reduce, like experiments/router.py's
slotted classes.

Reference analogue: the CTA-shared staging of hub vertices in the
reference's GPU kernels (reference colfilter_gpu.cu:41-102 stages a
tile of destination state in shared memory; reference
pull_model.inl:454-461 materializes the whole remote region) — here
the "shared tile" is the 128-lane vector register shape itself.
"""

from __future__ import annotations

import dataclasses

import numpy as np

W = 128


@dataclasses.dataclass
class PairPlan:
    """Per-part pair-lane arrays (host numpy).

    rowbind   int32 [R]      global state2d row (= src tile) per row
    rel_dst   int8 [R, 128] dst offset in [0,128), -1 = dead lane
    weight    f32 [R, 128] | None  per-lane edge weight (0 dead lanes)
    classes   [(tile_start, tile_count, depth)] for the combine; rows
              are tile-major in ``tile_order`` with per-tile depth
              padded to the class depth (dead rows are all -1)
    tile_order int32 [n_tiles] part-local dst tile of each class slot
    residual  bool [ne_part]  True for edges NOT covered by pairs
    """

    rowbind: np.ndarray
    rel_dst: np.ndarray
    weight: np.ndarray | None
    classes: list
    tile_order: np.ndarray
    residual: np.ndarray
    n_tiles: int
    stats: dict
    # part-local dst tile of each row (pair_partial_dot fetches the
    # row's destination tile block for the <src, dst> MXU dots)
    row_tile: np.ndarray | None = None


def occurrence_index(pair: np.ndarray, slot: np.ndarray) -> np.ndarray:
    """Per-element occurrence counter within each (pair, slot) group:
    the o-th edge of a (pair id, source slot) pair gets o (any order).

    Overflow-safe: pair ids reach ~(num_state_rows * n_tiles), which
    passes 2^31 at RMAT25/np4 — a packed ``pair * 2^32 + slot`` key
    silently wraps mod 2^64 there, aliasing distinct groups and
    DROPPING the aliased edges at delivery time (two edges written to
    one (row, lane)).  Two stable FUSED radix passes (lexsort
    semantics: slot minor, pair major; native.sort_kv carries the
    companion key and the edge index as payloads — no argsort
    permutation reads, no post-sort gathers) never form a product."""
    from lux_tpu import native

    n = len(slot)
    # unconditional copies: sort_kv permutes IN PLACE and callers
    # keep using their arrays
    ks = np.array(slot, dtype=np.int64)
    kp = np.array(pair, dtype=np.int64)
    idx = np.arange(n, dtype=np.int64)
    native.sort_kv(ks, (kp, idx))        # stable by slot
    native.sort_kv(kp, (ks, idx))        # then stable by pair
    newg = np.ones(n, bool)
    newg[1:] = (kp[1:] != kp[:-1]) | (ks[1:] != ks[:-1])
    pos = np.arange(n)
    gst = np.maximum.accumulate(np.where(newg, pos, 0))
    occ = np.empty(n, np.int64)
    occ[idx] = pos - gst
    return occ


def fill_histogram(pidx: np.ndarray, occ: np.ndarray):
    """Per-(pair, occurrence-level) fill counts, sorted by (pair,
    occ): returns (gp, go, fill) — pair id, occ level and the number
    of edges at that level (= the live-lane count of that pair row).
    The pack is safe: dense pidx < n_cov < 2^31, occ < max_occ.
    Shared by the min_fill cap (analyze_pairs) and the economics
    model (scripts/pair_fill_hist.py), so the modeled drop is exactly
    the planner's."""
    from lux_tpu import native

    key = (np.asarray(pidx, np.int64) << np.int64(32)) | occ
    native.sort_kv(key, ())
    newg = np.ones(len(key), bool)
    newg[1:] = key[1:] != key[:-1]
    gidx = np.nonzero(newg)[0]
    fill = np.diff(np.concatenate((gidx, [len(key)])))
    gp = (key[gidx] >> np.int64(32)).astype(np.int64)
    go = (key[gidx] & np.int64(0xFFFFFFFF)).astype(np.int64)
    return gp, go, fill


def quantize_depths(depth_sorted: np.ndarray,
                    levels_growth: float = 1.35) -> np.ndarray:
    """Round a descending per-slot row-count profile up to the fixed
    depth ladder (0..8 then *levels_growth), bounding the number of
    distinct classes to O(log max_depth)."""
    levels = [0, 1, 2, 3, 4, 5, 6, 7, 8]
    v = 8
    while v < int(np.max(depth_sorted, initial=0)):
        v = int(v * levels_growth) + 1
        levels.append(v)
    lev = np.asarray(levels, np.int64)
    return lev[np.searchsorted(lev, depth_sorted)]


@dataclasses.dataclass
class PairAnalysis:
    """The threshold-dependent (but layout-independent) half of pair
    planning: everything through the sorted per-tile depth profile.
    plan_sharded_pairs computes it ONCE per part and reuses it for
    both the profile pass and the final layout — at billion-edge
    scale the analysis is several argsorts of the whole edge list,
    previously paid twice (round-4 host-prep work)."""

    ne: int
    n_tiles: int
    residual: np.ndarray       # bool [ne]
    cov: np.ndarray            # int32 [n_cov] covered edge idx
    occ: np.ndarray            # int32 [n_cov] occurrence in (pair,slot)
    pidx: np.ndarray           # int32 [n_cov] dense selected-pair id
    nrows_pair: np.ndarray     # int64 [n_sel]
    pair_dt: np.ndarray        # int64 [n_sel] dst tile of each pair
    tile_sort: np.ndarray      # int64 [n_sel]
    t_order: np.ndarray        # int64 [n_tiles]
    depth_sorted: np.ndarray   # int64 [n_tiles] descending
    # NOTE: src_slot/dst_local are deliberately NOT stored —
    # plan_sharded_pairs holds every part's analysis simultaneously,
    # and int64 copies of the edge arrays would cost tens of GB at
    # billion-edge scale (build_pair_plan re-derives them from its
    # own parameters); cov/occ/pidx are int32 (epad < 2^31 is a
    # ShardedGraph.build invariant)


def resolve_min_fill(min_fill, kdim: int = 1) -> int | None:
    """The K-aware half of the min_fill economics: ``"auto"`` resolves
    to the modeled break-even fill for ``kdim``-wide rows
    (scalemodel.break_even_fill — row cost grows with K, so K-dim rows
    must be fuller to beat the residual: ~16 scalar, ~22 at K=20).
    Integers and None pass through unchanged."""
    if min_fill == "auto":
        from lux_tpu.scalemodel import break_even_fill
        return break_even_fill(kdim)
    if min_fill is not None and not isinstance(min_fill, (int,
                                                          np.integer)):
        raise ValueError(f"min_fill must be an int, None or 'auto', "
                         f"got {min_fill!r}")
    return min_fill


def analyze_pairs(src_slot: np.ndarray, dst_local: np.ndarray,
                  vpad: int, threshold: int = 8,
                  max_occ: int = 128,
                  min_fill: int | str | None = None,
                  kdim: int = 1) -> PairAnalysis:
    """See build_pair_plan; this is its sorting/selection half.

    min_fill (occupancy-aware row packing, round-5 north-star work):
    drop pair rows that would deliver fewer than ``min_fill`` live
    lanes, sending their edges to the residual path instead.  Row
    fill is MONOTONE DECREASING in occurrence depth within a pair
    (row o carries one edge per source lane with multiplicity > o),
    so the underfilled rows are exactly each pair's occurrence TAIL —
    the drop is a per-pair adaptive occurrence cap, computed from one
    (pidx, occ) histogram.  The break-even fill is the measured
    per-row delivery cost over the residual per-edge rate
    (~150 / ~10 ns, PERF_NOTES scale-25 decomposition) ~ 15 lanes;
    R-MAT tails spread multiplicity so hard that mean fill at RMAT25
    is 18.6 (inflation 6.88x) with a long sub-break-even tail.

    min_fill="auto" resolves to the K-aware modeled break-even for
    ``kdim``-wide rows (resolve_min_fill): SDDMM delivery rows
    (pair_partial_dot*) cost more per row than scalar rows, so their
    break-even fill is higher (~22 at K=20 vs ~16 scalar)."""
    min_fill = resolve_min_fill(min_fill, kdim)
    assert vpad % W == 0
    ne = len(dst_local)
    n_tiles = vpad // W
    src_slot = np.asarray(src_slot, np.int64)
    dst_local = np.asarray(dst_local, np.int64)

    st = src_slot // W
    dt = dst_local // W
    pair = st * n_tiles + dt
    # fused radix sort carrying the edge index: replaces argsort +
    # key gather on the whole edge list (native.sort_kv, PERF_NOTES
    # round-4 host prep)
    pp = pair.copy()
    order = np.arange(ne, dtype=np.int64)
    from lux_tpu import native
    native.sort_kv(pp, (order,))
    # a part with zero edges has zero pairs (starts must then be [0],
    # not [0, 0], so the pp[starts[:-1]] lookups below stay in bounds)
    starts = (np.concatenate(
        ([0], np.nonzero(pp[1:] != pp[:-1])[0] + 1, [ne]))
        if ne else np.zeros(1, np.int64))
    sizes = np.diff(starts)
    pair_id = np.repeat(np.arange(len(sizes)), sizes)

    sel_pair = sizes >= threshold
    esel_sorted = sel_pair[pair_id]               # in pair-sorted order
    residual = np.ones(ne, bool)
    residual[order[esel_sorted]] = False

    # occurrence index of each covered edge within (pair, src lane)
    cov = order[esel_sorted]                      # original edge idx
    occ = occurrence_index(pair[cov], src_slot[cov])

    # Optional occurrence-depth cap (edges beyond it ride the residual
    # gather).  Measured on RMAT21: capping LOSES — deep-occurrence
    # rows belong to hub pairs and are well-filled, so the default
    # effectively disables the cap.
    keep = occ < max_occ
    if not keep.all():
        # mark dropped edges residual; rebuild cov/occ on the kept set
        residual[cov[~keep]] = True
        cov = cov[keep]
        occ = occurrence_index(pair[cov], src_slot[cov])

    # per-pair row count = max occurrence + 1 (pair ids of the
    # possibly-reduced covered set, via the sorted unique pair keys)
    pid_cov = np.searchsorted(pp[starts[:-1]], pair[cov])
    # remap selected pair ids to dense [0, P)
    sel_ids = np.nonzero(sel_pair)[0]
    remap = np.full(len(sizes), -1, np.int64)
    remap[sel_ids] = np.arange(len(sel_ids))
    pidx = remap[pid_cov]                         # [n_cov]

    if min_fill is not None and min_fill > 1 and len(cov):
        # fill of row (pair, o) = #edges at occurrence o in the pair;
        # monotone decreasing in o, so the per-pair cap is the count
        # of leading occurrence levels with fill >= min_fill
        gp, go, fill = fill_histogram(pidx, occ)
        # leading run of occ levels with fill >= min_fill per pair:
        # occ levels are contiguous from 0 (groups sorted by occ), so
        # the cap is the first level that is absent or underfilled
        ok = fill >= min_fill
        run = np.zeros(len(sel_ids), np.int64)
        # count o where (pair, o) ok AND all o' < o ok: prefix-and via
        # cummax of the first failure position
        firstbad = np.full(len(sel_ids), np.iinfo(np.int64).max)
        np.minimum.at(firstbad, gp[~ok], go[~ok])
        np.maximum.at(run, gp[ok],
                      np.minimum(go[ok] + 1, firstbad[gp[ok]]))
        cap = run                                  # rows kept per pair
        keep2 = occ < cap[pidx]
        if not keep2.all():
            residual[cov[~keep2]] = True
            cov = cov[keep2]
            pidx = pidx[keep2]
            occ = occ[keep2]   # no holes: kept occ stay < cap

    nrows_pair = np.zeros(len(sel_ids), np.int64)
    if len(cov):
        np.maximum.at(nrows_pair, pidx, occ + 1)

    # order pairs by dst tile (for the per-tile combine), then src tile
    pair_dt = (pp[starts[:-1]][sel_pair] % n_tiles)
    tile_sort = np.argsort(pair_dt, kind="stable")
    # per-tile total rows -> depth classes
    rows_by_tile = np.zeros(n_tiles, np.int64)
    np.add.at(rows_by_tile, pair_dt, nrows_pair)
    t_order = np.argsort(-rows_by_tile, kind="stable")
    depth_sorted = rows_by_tile[t_order]
    return PairAnalysis(
        ne=ne, n_tiles=n_tiles, residual=residual,
        cov=cov.astype(np.int32), occ=occ.astype(np.int32),
        pidx=pidx.astype(np.int32),
        nrows_pair=nrows_pair, pair_dt=pair_dt, tile_sort=tile_sort,
        t_order=t_order, depth_sorted=depth_sorted)


def build_pair_plan(src_slot: np.ndarray, dst_local: np.ndarray,
                    vpad: int, threshold: int = 8,
                    max_occ: int = 128,
                    levels_growth: float = 1.35,
                    weights: np.ndarray | None = None,
                    slot_depths: np.ndarray | None = None,
                    analysis: PairAnalysis | None = None,
                    min_fill: int | str | None = None,
                    kdim: int = 1):
    """src_slot: int [ne] global padded state slots (state2d row =
    slot // 128); dst_local: int [ne] part-local dst in [0, vpad);
    vpad must be a multiple of 128.  weights (optional, [ne]) are laid
    out per lane so weighted programs get each delivered edge's weight
    next to its value.

    slot_depths (optional, [n_tiles] descending, ladder-quantized):
    lay rows out against this EXTERNAL per-slot depth profile instead
    of the part's own — every part of a multi-part graph laid out
    against the elementwise-max profile gets IDENTICAL classes, so
    stacking pads no rows beyond the max profile (see
    plan_sharded_pairs).

    analysis: a precomputed analyze_pairs result for these arrays
    (must match threshold/max_occ/min_fill) — skips the sorting
    half.  min_fill/kdim: see analyze_pairs."""
    if analysis is None:
        analysis = analyze_pairs(src_slot, dst_local, vpad,
                                 threshold=threshold, max_occ=max_occ,
                                 min_fill=min_fill, kdim=kdim)
    a = analysis
    ne, n_tiles = a.ne, a.n_tiles
    src_slot = np.asarray(src_slot, np.int64)
    dst_local = np.asarray(dst_local, np.int64)
    residual, cov, occ, pidx = a.residual, a.cov, a.occ, a.pidx
    nrows_pair, pair_dt = a.nrows_pair, a.pair_dt
    tile_sort, t_order, depth_sorted = (a.tile_sort, a.t_order,
                                        a.depth_sorted)

    if slot_depths is None:
        depth = quantize_depths(depth_sorted, levels_growth)
    else:
        depth = np.asarray(slot_depths, np.int64)
        if depth.shape != (n_tiles,) or (depth < depth_sorted).any():
            raise ValueError("slot_depths must cover this part's own "
                             "sorted per-tile row counts")

    row_off_tile = np.concatenate(([0], np.cumsum(depth)))
    R = int(row_off_tile[-1])

    # rows of each pair: base = tile's offset + exclusive running row
    # count within the tile (pairs in tile_sort order are contiguous
    # per destination tile)
    tile_pos = np.empty(n_tiles, np.int64)        # tile -> class slot
    tile_pos[t_order] = np.arange(n_tiles)
    srt_rows = nrows_pair[tile_sort]
    cum = np.cumsum(srt_rows) - srt_rows          # exclusive prefix
    dts = pair_dt[tile_sort]
    newt = np.ones(len(dts), bool)
    newt[1:] = dts[1:] != dts[:-1]
    grp_base = np.maximum.accumulate(np.where(newt, cum, 0))
    within = cum - grp_base
    pair_base = np.zeros(len(nrows_pair), np.int64)
    pair_base[tile_sort] = row_off_tile[tile_pos[dts]] + within
    assert (within + srt_rows <= depth[tile_pos[dts]]).all()

    rowbind = np.zeros(R, np.int32)
    rel_dst = np.full((R, W), -1, np.int8)
    rows = pair_base[pidx] + occ
    rowbind_rows = (src_slot[cov] // W).astype(np.int32)
    rowbind[rows] = rowbind_rows
    rel_dst[rows, src_slot[cov] % W] = (dst_local[cov] % W).astype(
        np.int8)
    # every covered edge must own a distinct (row, lane) — a colliding
    # write means a planner bug silently DROPPED an edge (the int64
    # occurrence-key wrap at RMAT25/np4 scale did exactly that before
    # occurrence_index); count the delivered lanes, loudly
    delivered = int(np.count_nonzero(rel_dst != -1))
    if delivered != len(cov):
        raise AssertionError(
            f"pair plan dropped {len(cov) - delivered} of {len(cov)} "
            f"covered edges (colliding (row, lane) writes)")
    weight = None
    if weights is not None:
        weight = np.zeros((R, W), np.float32)
        weight[rows, src_slot[cov] % W] = np.asarray(
            weights, np.float32)[cov]

    classes = []
    t0 = 0
    for L in np.unique(depth)[::-1]:
        cnt = int((depth == L).sum())
        if L > 0:
            classes.append((t0, cnt, int(L)))
        t0 += cnt

    # slot s owns depth[s] rows for tile t_order[s], in slot order
    row_tile = np.repeat(t_order.astype(np.int32), depth)

    plan = PairPlan(rowbind=rowbind, rel_dst=rel_dst, weight=weight,
                    classes=classes,
                    tile_order=t_order.astype(np.int32),
                    residual=residual, n_tiles=n_tiles, stats={},
                    row_tile=row_tile)
    ncov = int((~residual).sum())
    plan.stats = dict(ne=ne, covered=ncov, R=R,
                      coverage=ncov / max(ne, 1),
                      inflation=R * W / max(ncov, 1),
                      depth_profile=depth_sorted)
    return plan


def pair_reduce_numpy(plan: PairPlan, state_flat: np.ndarray,
                      kind: str = "sum") -> np.ndarray:
    """Oracle: run the pair-lane delivery + reduce on host.
    Returns [vpad] partial reduction (identity where uncovered)."""
    s2d = np.asarray(state_flat).reshape(-1, W)
    vals = s2d[plan.rowbind]                       # [R, 128]
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    vpad = plan.n_tiles * W
    out = np.full(vpad, ident)
    # per-row compare-reduce + per-tile combine
    row0 = 0
    for (t0, cnt, L) in plan.classes:
        for i in range(cnt):
            tile = plan.tile_order[t0 + i]
            for r in range(row0 + i * L, row0 + (i + 1) * L):
                lanes = plan.rel_dst[r]
                for c in range(W):
                    w = int(lanes[c])   # int8 + python-int arithmetic
                    if 0 <= w < W:
                        out[tile * W + w] = op(out[tile * W + w],
                                               vals[r, c])
        row0 += cnt * L
    return out


# ---------------------------------------------------------------------
# Stacked (multi-part) plans: the per-part PairPlans are padded to ONE
# common class structure so they stack into rectangular [P, ...] arrays
# that vmap over parts and shard over a mesh axis exactly like the rest
# of the graph arrays.  The analogue of the reference running the same
# per-part app task on every partition of the gathered whole-state
# region (reference pull_model.inl:454-469).
# ---------------------------------------------------------------------


@dataclasses.dataclass
class StackedPairPlan:
    """Common-frame pair-lane arrays for all parts (host numpy).

    rowbind   int32 [P, Rp]       global state2d row per delivery row
    rel_dst   int8 [P, Rp, 128]  dst offset in [0,128), -1 = dead
    weight    f32 [P, Rp, 128] | None  per-lane edge weight
    tile_pos  int32 [P, n_tiles]  class slot of each part-local tile;
              tiles with no pair rows point at the trailing identity
              slot ``n_slots``
    classes   [(count, depth)] shared by every part, depth descending;
              a part with fewer tiles at some depth owns dead rows
              there (all-128 rel), which reduce to the identity and
              are never referenced by its tile_pos
    """

    rowbind: np.ndarray
    rel_dst: np.ndarray
    weight: np.ndarray | None
    tile_pos: np.ndarray
    classes: list
    n_tiles: int
    n_slots: int
    R: int
    Rp: int
    stats: dict
    row_tile: np.ndarray | None = None  # int32 [P, Rp], dead rows -> 0


def stack_pair_plans(plans: list, weighted: bool,
                     block_rows: int = 64) -> StackedPairPlan:
    """Pad per-part plans to a common class structure and stack.

    The depth ladder in build_pair_plan is a prefix of one fixed
    sequence, so per-part class depths are subsets of a common
    descending depth list; the common count per depth is the max over
    parts.  Rows are padded to ``block_rows`` granularity for the
    Pallas chunk-partial kernel.
    """
    P = len(plans)
    n_tiles = plans[0].n_tiles
    depths = sorted({L for pl in plans for (_t0, _c, L) in pl.classes},
                    reverse=True)
    cnt_by_depth = {
        L: max((c for pl in plans for (_t0, c, Ld) in pl.classes
                if Ld == L), default=0)
        for L in depths}
    classes = [(cnt_by_depth[L], L) for L in depths]
    n_slots = sum(c for c, _L in classes)
    R = sum(c * L for c, L in classes)
    Rp = max(R, block_rows)
    Rp = -(-Rp // block_rows) * block_rows

    slot_base, row_base = {}, {}
    s = r = 0
    for c, L in classes:
        slot_base[L], row_base[L] = s, r
        s += c
        r += c * L

    rowbind = np.zeros((P, Rp), np.int32)
    rel_dst = np.full((P, Rp, W), -1, np.int8)
    wgt = np.zeros((P, Rp, W), np.float32) if weighted else None
    tile_pos = np.full((P, n_tiles), n_slots, np.int32)
    row_tile = np.zeros((P, Rp), np.int32)
    for p, pl in enumerate(plans):
        prow = 0
        for (t0, c, L) in pl.classes:
            rb, sb = row_base[L], slot_base[L]
            rowbind[p, rb:rb + c * L] = pl.rowbind[prow:prow + c * L]
            rel_dst[p, rb:rb + c * L] = pl.rel_dst[prow:prow + c * L]
            if weighted:
                wgt[p, rb:rb + c * L] = pl.weight[prow:prow + c * L]
            row_tile[p, rb:rb + c * L] = pl.row_tile[prow:prow + c * L]
            tiles = pl.tile_order[t0:t0 + c]
            tile_pos[p, tiles] = sb + np.arange(c, dtype=np.int32)
            prow += c * L

    ne = sum(pl.stats["ne"] for pl in plans)
    cov = sum(pl.stats["covered"] for pl in plans)
    return StackedPairPlan(
        rowbind=rowbind, rel_dst=rel_dst, weight=wgt, tile_pos=tile_pos,
        classes=classes, n_tiles=n_tiles, n_slots=n_slots, R=R, Rp=Rp,
        stats=dict(ne=ne, covered=cov, coverage=cov / max(ne, 1),
                   inflation=P * Rp * W / max(cov, 1)),
        row_tile=row_tile)


def cost_balanced_starts(g, num_parts: int, threshold: int,
                         gather_cost: float = 9.0,
                         pair_cost: float = 2.5) -> np.ndarray:
    """Partition cut points balancing ESTIMATED per-part iteration
    cost under pair-lane delivery, instead of raw edge counts.

    Edge-balanced cuts leave the tail-destination parts with nearly
    all the residual (gather-served, ~9 ns) edges while hub parts'
    edges ride cheap pair rows — measured 0.8M..5.9M residual skew at
    RMAT21/np=4.  Cost model: an edge in a dense GLOBAL (src-tile,
    dst-tile) pair costs ``pair_cost`` ns, any other ``gather_cost``
    ns (PERF_NOTES.md).  Cuts are 128-aligned so part-local tile
    structure equals the global tiling and the estimate is exact.
    """
    from lux_tpu.partition import weighted_balanced_bounds

    src, dst = g.edge_arrays()
    n_st = (g.nv + W - 1) // W
    key = (src // W) * np.int64(n_st) + dst // W
    uniq, inv, cnt = np.unique(key, return_inverse=True,
                               return_counts=True)
    edge_cost = np.where(cnt[inv] >= threshold, pair_cost, gather_cost)
    ccum = np.concatenate(([0.0], np.cumsum(edge_cost)))
    cost_ptrs = ccum[np.asarray(g.row_ptrs, np.int64)]  # END offsets
    return weighted_balanced_bounds(cost_ptrs, num_parts, align=W)


def plan_sharded_pairs(sg, threshold: int,
                       min_fill: int | str | None = None,
                       kdim: int = 1):
    """Build per-part pair plans for a ShardedGraph and the RESIDUAL
    ShardedGraph (uncovered edges, re-padded) the regular gather path
    should run on.  Returns (StackedPairPlan | None, residual_sg);
    None when no pair anywhere meets the threshold (residual is ``sg``
    itself).  Works for any num_parts; requires vpad % 128 == 0
    (build the ShardedGraph with vpad_align=128).

    Multi-host local-parts builds (sg.local_parts set): each process
    plans only its OWN rows, but against a process-group-allreduced
    common depth profile (multihost.allreduce_host — the s_pad-style
    agreement push uses, push.py), so every process compiles the SAME
    class structure and row shapes.

    min_fill="auto" + kdim: K-aware break-even resolution (resolved
    ONCE here so every part — and every process — caps on the same
    fill; see resolve_min_fill)."""
    import dataclasses as _dc

    min_fill = resolve_min_fill(min_fill, kdim)
    if sg.vpad % W:
        raise ValueError("pair delivery needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    P = sg.num_parts
    rows = sg.part_ids()          # global part id per materialized row
    R = len(rows)
    local = sg.local_parts is not None

    def plan_row(r, slot_depths=None, analysis=None):
        nep = int(sg.ne_part[rows[r]])
        wp = (np.asarray(sg.edge_weight[r, :nep])
              if sg.weighted else None)
        return build_pair_plan(
            sg.src_slot[r, :nep], sg.dst_local[r, :nep], sg.vpad,
            threshold=threshold, weights=wp, slot_depths=slot_depths,
            analysis=analysis, min_fill=min_fill)

    if P > 1 or local:
        # Pass 1: per-part analyses (the expensive sorting half, done
        # ONCE and reused by the layout pass) yield sorted row-count
        # profiles.  Pass 2: lay every part out against the
        # elementwise-max profile so classes are IDENTICAL across
        # parts (and processes) and stacking pads no rows beyond the
        # max profile.  (Per-depth max-count stacking of heterogeneous
        # profiles measured 3.4x row inflation at RMAT21/np=4.)
        analyses = []
        for r in range(R):
            nep = int(sg.ne_part[rows[r]])
            analyses.append(analyze_pairs(
                sg.src_slot[r, :nep], sg.dst_local[r, :nep], sg.vpad,
                threshold=threshold, min_fill=min_fill))
        prof_max = (np.maximum.reduce(
            [a.depth_sorted for a in analyses]) if analyses
            else np.zeros(sg.vpad // W, np.int64))
        total = sum(int(a.depth_sorted.sum()) for a in analyses)
        if local:
            from lux_tpu.parallel.multihost import allreduce_host
            prof_max = allreduce_host(prof_max, "max")
            total = int(allreduce_host(np.int64(total), "sum"))
        if total == 0:
            return None, sg             # no pair anywhere dense enough
        common = quantize_depths(prof_max)
        plans = []
        for r in range(R):
            plans.append(plan_row(r, slot_depths=common,
                                  analysis=analyses[r]))
            analyses[r] = None          # release the per-part arrays
    else:
        plans = [plan_row(0)]
        if plans[0].stats["covered"] == 0:
            return None, sg

    sp = stack_pair_plans(plans, sg.weighted)

    ne_r = [int(pl.residual.sum()) for pl in plans]
    if local:
        # residual shapes (epad_r) and global metadata must agree
        # across processes; rows are disjoint, so max merges counts
        from lux_tpu.parallel.multihost import allreduce_host
        ne_part_r = np.zeros(P, np.int64)
        ne_part_r[np.asarray(rows)] = ne_r
        ne_part_r = allreduce_host(ne_part_r, "max")
    else:
        ne_part_r = np.asarray(ne_r, np.int64)
    epad_r = max(128, -(-int(ne_part_r.max(initial=0)) // 128) * 128)
    src_slot = np.zeros((R, epad_r), np.int32)
    dst_local = np.full((R, epad_r), sg.vpad, np.int32)
    ew = np.zeros((R, epad_r), np.float32) if sg.weighted else None
    row_ptr_local = np.zeros((R, sg.vpad + 1), np.int32)
    for r, pl in enumerate(plans):
        nep = int(sg.ne_part[rows[r]])
        res = pl.residual
        nr = ne_r[r]
        src_slot[r, :nr] = sg.src_slot[r, :nep][res]
        r_dst = sg.dst_local[r, :nep][res]
        dst_local[r, :nr] = r_dst
        if ew is not None:
            ew[r, :nr] = sg.edge_weight[r, :nep][res]
        counts = np.bincount(r_dst, minlength=sg.vpad)
        row_ptr_local[r, 1:] = np.cumsum(counts).astype(np.int32)
    # NOTE: a local-parts residual keeps the FULL graph's
    # row_ptr_global, so sizing_row_ptr() (chunk geometry) is an
    # overestimate of the residual's chunks — consistent across
    # processes, just padded; pad chunks are isolated identities.
    residual = _dc.replace(
        sg, src_slot=src_slot, dst_local=dst_local, edge_weight=ew,
        row_ptr_local=row_ptr_local,
        ne_part=ne_part_r, epad=epad_r,
        _src_sorted_cache=None)
    return sp, residual


def pair_partial(sp: StackedPairPlan, flat_state, rowbind, rel, weight,
                 tile_pos, kind: str, msg_fn,
                 reduce_method: str = "xla"):
    """Device-side delivery + reduce for ONE part -> [n_tiles * 128]
    partial (identity where pairs contribute nothing).

    flat_state: [n_state_rows * 128] flat vertex state (the all-
    gathered whole state); rowbind/rel/weight/tile_pos: this part's
    rows of the stacked arrays; msg_fn(vals [R,128],
    weight [R,128]|None) -> per-edge messages (dead lanes carry
    garbage, masked by rel == -1).
    """
    import jax.numpy as jnp

    from lux_tpu.ops.tiled import chunk_partials

    if flat_state.ndim != 1:
        raise ValueError("pair delivery supports scalar vertex state "
                         "only")
    s2d = flat_state.reshape(-1, W)
    vals = jnp.take(s2d, rowbind, axis=0)            # [Rp, 128] rows
    vals = msg_fn(vals, weight)
    if reduce_method.startswith("pallas"):
        from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
        # rows are short (E=128): large blocks amortize the grid
        partials = chunk_partials_pallas(
            vals, rel, W, kind, block_c=64,
            interpret=reduce_method == "pallas-interpret")
    else:
        partials = chunk_partials(vals, rel, W, kind)
    partials = partials[:sp.R]                       # drop pad rows
    red2d = _class_combine(sp, partials, tile_pos, kind)
    return red2d.reshape(-1)


def _class_combine(sp: StackedPairPlan, partials, tile_pos, kind: str):
    """Shared epilogue: per-class reshape-reduce of row partials
    [R, W, ...] into slot results, trailing identity slot, then the
    tile_pos take -> [n_tiles, W, ...]."""
    import jax.numpy as jnp

    from lux_tpu.ops.segment import identity_for

    ident = identity_for(kind, partials.dtype)
    red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[kind]
    outs = []
    row0 = 0
    for (cnt, L) in sp.classes:
        blk = partials[row0:row0 + cnt * L].reshape(
            (cnt, L) + partials.shape[1:])
        outs.append(red(blk, axis=1))
        row0 += cnt * L
    outs.append(jnp.full((1,) + partials.shape[1:], ident,
                         partials.dtype))
    slots = jnp.concatenate(outs, axis=0)            # [n_slots + 1, ...]
    return jnp.take(slots, tile_pos, axis=0)         # [n_tiles, ...]


# scalar streamed-delivery block budget (pair_partial_streamed): the
# delivered f32 value rows of ONE scan block
PAIR_STREAM_BLOCK_BYTES = 64 << 20


def pair_partial_streamed(sp: StackedPairPlan, flat_state, rowbind, rel,
                          weight, tile_pos, kind: str, msg_fn,
                          reduce_method: str = "xla",
                          block_bytes: int = PAIR_STREAM_BLOCK_BYTES):
    """Memory-bounded pair delivery: identical result to
    ``pair_partial`` but the delivered f32 value rows and their
    per-row partials never materialize beyond one scan block.

    At RMAT25 x np4 the monolithic path's vals+partials are ~15 GB
    (each Rp x 128 x f32) and the whole program OOMs a 16 GB chip
    (PERF_NOTES); here each depth class (cnt slots x L contiguous
    rows) is processed as a ``lax.scan`` over blocks of S whole slots
    (S*L rows, sized to ``block_bytes``), each step fetching, reducing
    and emitting per-SLOT results [S, 128] — the cross-row combine
    happens inside the step, so live memory is one block regardless of
    graph scale.
    """
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.segment import identity_for
    from lux_tpu.ops.tiled import chunk_partials

    if flat_state.ndim != 1:
        raise ValueError("pair delivery supports scalar vertex state "
                         "only")
    s2d = flat_state.reshape(-1, W)
    red_axis = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[kind]

    def slot_results(rb, rl, wt, S, L):
        """[S*L] rows -> [S, 128] per-slot results (one block)."""
        vals = jnp.take(s2d, rb, axis=0)               # [S*L, 128]
        msgs = msg_fn(vals, wt)
        B = msgs.shape[0]
        # Pallas needs 8-row block granularity; small unaligned
        # remainder blocks take the XLA formulation instead
        if reduce_method.startswith("pallas") and B % 8 == 0:
            from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
            p = chunk_partials_pallas(
                msgs, rl, W, kind, block_c=64 if B % 64 == 0 else 8,
                interpret=reduce_method == "pallas-interpret")
        else:
            if reduce_method.startswith("pallas"):
                msgs = jax.lax.optimization_barrier(msgs)
            p = chunk_partials(msgs, rl, W, kind)
        return red_axis(p.reshape(S, L, W), axis=1)

    outs = []
    row0 = 0
    for (cnt, L) in sp.classes:
        # whole slots per block, >= 1, sized so vals fit block_bytes;
        # keep S*L a multiple of the Pallas block granularity
        S = max(1, min(cnt, block_bytes // max(1, L * W * 4)))
        if L % 8 and S >= 8:
            S -= S % 8
        nB, rem = divmod(cnt, S)

        def seg(lo, n):
            sl = slice(row0 + lo * L, row0 + (lo + n) * L)
            return (rowbind[sl], rel[sl],
                    None if weight is None else weight[sl])

        cls_out = []
        if nB:
            rb, rl, wt = seg(0, nB * S)
            rb = rb.reshape(nB, S * L)
            rl = rl.reshape(nB, S * L, W)
            xs = (rb, rl) if wt is None else \
                (rb, rl, wt.reshape(nB, S * L, W))

            def step(_, x, S=S, L=L):
                return None, slot_results(
                    x[0], x[1], x[2] if len(x) > 2 else None, S, L)

            _, reds = jax.lax.scan(step, None, xs)     # [nB, S, 128]
            cls_out.append(reds.reshape(nB * S, W))
        if rem:
            rb, rl, wt = seg(nB * S, rem)
            cls_out.append(slot_results(rb, rl, wt, rem, L))
        outs.append(jnp.concatenate(cls_out, axis=0))
        row0 += cnt * L
    # identity slot in the MESSAGE dtype (msg_fn may promote), exactly
    # like pair_partial's partials-dtype identity; with zero classes
    # (plan_sharded_pairs normally returns None first) fall back to
    # the state dtype so the identity take still works
    out_dtype = outs[0].dtype if outs else flat_state.dtype
    ident = identity_for(kind, out_dtype)
    outs.append(jnp.full((1, W), ident, out_dtype))
    slots = jnp.concatenate(outs, axis=0)              # [n_slots+1, W]
    return jnp.take(slots, tile_pos, axis=0).reshape(-1)


def resolve_pair_stream(pair_stream, pairs) -> bool:
    """Streamed pair delivery (pair_partial_streamed) is the default:
    measured FASTER than the monolithic path even at RMAT21
    (0.124-0.127 vs 0.119-0.122 GTEPS, interleaved A/B) and its live
    memory is one scan block instead of Rp x 128 x f32 vals+partials —
    which OOM a 16 GB chip at RMAT25 (PERF_NOTES).  pair_stream=False
    keeps the monolithic path (micro-graphs, debugging)."""
    if pairs is None:
        return False
    return True if pair_stream is None else bool(pair_stream)


def pair_partial_dot(sp: StackedPairPlan, state, rowbind, rel, weight,
                     row_tile, tile_pos, part_tile0, msg_dot_fn,
                     block_rows: int = 256):
    """Pair-lane delivery for VECTOR-state programs whose dst
    dependence is only the inner product <src, dst>
    (PullProgram.edge_value_from_dot, e.g. colfilter's SGD) — the
    blocked-SDDMM formulation of matrix-factorization on the MXU:

    per delivery row (one dense (src-tile, dst-tile) pair occurrence):
      S = src tile block [128, K]   (ONE reshaped-row fetch — the
                                     gather costs ~9 ns per ROW
                                     regardless of width, PERF_NOTES)
      T = dst tile block [128, K]   (one more row fetch)
      D = S @ T^T                   (all (src-lane, dst-lane) dots;
                                     measured FASTER than the
                                     onehot-select-then-dot
                                     formulation, 0.091 vs 0.057
                                     GTEPS at RMAT16 ef128 — the MXU
                                     eats the [128,128] block, XLA
                                     fuses the select into it)
      dot[c] = D[c, rel[c]]         (lane compare-select)
      msgs = msg_dot_fn(S, dot, w)  ((w - dot) * src for colfilter)
      partial = onehot(rel)^T @ msgs  [128, K] to the row's dst tile

    state: [n_state_rows * 128, K] all-gathered flat vertex state;
    rowbind/rel/weight/row_tile/tile_pos: this part's rows of the
    stacked arrays; part_tile0: global state2d row of this part's
    tile 0 (= part index * vpad/128).  Rows are processed in
    ``block_rows`` lax.map blocks to bound the [B, 128, 128]
    intermediates.  Returns [n_tiles * 128, K] partial sum.
    """
    import jax
    import jax.numpy as jnp

    if weight is None:
        raise ValueError("pair_partial_dot needs per-lane weights")
    Kdim = state.shape[-1]
    s3 = state.reshape(-1, W * Kdim)
    Rp = rowbind.shape[0]
    B = max(1, min(block_rows, Rp))
    nB = -(-Rp // B)
    Rpp = nB * B

    def pad(x):
        return jnp.pad(x, ((0, Rpp - Rp),) + ((0, 0),) * (x.ndim - 1))

    lanes = jnp.arange(W, dtype=rel.dtype)

    def block(args):
        rb, rl, wt, rt = args
        S = jnp.take(s3, rb, axis=0).reshape(-1, W, Kdim)
        T = jnp.take(s3, part_tile0 + rt, axis=0).reshape(-1, W, Kdim)
        D = jnp.einsum("rck,rwk->rcw", S, T,
                       preferred_element_type=S.dtype)
        mask = rl[..., None] == lanes                  # [B, 128, 128]
        dot = jnp.sum(jnp.where(mask, D, 0), axis=-1)  # [B, 128]
        msgs = msg_dot_fn(S, dot, wt)                  # [B, 128, K]
        # dead lanes (rel == -1) match no output lane -> contribute 0
        return jnp.einsum("rcw,rck->rwk", mask.astype(S.dtype), msgs)

    partials = jax.lax.map(
        block, (pad(rowbind).reshape(nB, B),
                pad(rel).reshape(nB, B, W),
                pad(weight).reshape(nB, B, W),
                pad(row_tile).reshape(nB, B)))
    partials = partials.reshape(Rpp, W, Kdim)[:sp.R]
    red = _class_combine(sp, partials, tile_pos, "sum")
    return red.reshape(-1, Kdim)


# Streamed SDDMM block budget: live bytes of ONE scan block (delivered
# S/T tiles + the [B, 128, 128] dot blocks + messages/partials).  The
# [*, W, W] dot intermediate dominates for K < 128, so blocks land at
# a few hundred rows — the same order as the monolithic path's
# measured-best lax.map block (DOT_BLOCK_CHUNKS, engine/pull.py).
PAIR_DOT_BLOCK_BYTES = 64 << 20


def pair_partial_dot_streamed(sp: StackedPairPlan, state, rowbind, rel,
                              weight, row_tile, tile_pos, part_tile0,
                              msg_dot_fn,
                              block_bytes: int = PAIR_DOT_BLOCK_BYTES):
    """Memory-bounded SDDMM pair delivery: identical result to
    ``pair_partial_dot`` but neither the delivered [Rp, 128, K] tile
    values nor the per-row [Rp, 128, K] gradient partials ever
    materialize beyond one scan block.

    The monolithic path's lax.map STACKS its per-row partials — at the
    NetFlix shape that is a reproducible f32[6454, 4, 256, 128, 20] =
    67.7 GB compile allocation (PERF_NOTES round 5), 4.3x the chip.
    Here each depth class (cnt slots x L contiguous rows) runs as a
    ``lax.scan`` over blocks of S WHOLE slots (S*L rows, sized to
    ``block_bytes``); each step fetches the block's src/dst tiles,
    forms D = S @ T^T, lane-selects the dots, applies ``msg_dot_fn``,
    reduces through the one-hot gradient matmul AND folds the
    cross-row (occurrence-depth) sum inside the step — emitting
    per-SLOT results [S, 128, K], so live memory is one block at any
    scale.  The scalar analogue (and the original of the slot-block
    discipline) is ``pair_partial_streamed``.
    """
    import jax
    import jax.numpy as jnp

    if weight is None:
        raise ValueError("pair_partial_dot needs per-lane weights")
    Kdim = state.shape[-1]
    s3 = state.reshape(-1, W * Kdim)
    lanes = jnp.arange(W, dtype=rel.dtype)

    def slot_results(rb, rl, wt, rt, S, L):
        """[S*L] delivery rows -> [S, 128, K] per-slot gradient sums
        (one block; the body is pair_partial_dot's per-row pipeline
        plus the in-step depth reduction)."""
        Sv = jnp.take(s3, rb, axis=0).reshape(-1, W, Kdim)
        T = jnp.take(s3, part_tile0 + rt, axis=0).reshape(-1, W, Kdim)
        D = jnp.einsum("rck,rwk->rcw", Sv, T,
                       preferred_element_type=Sv.dtype)
        mask = rl[..., None] == lanes                  # [S*L, 128, 128]
        dot = jnp.sum(jnp.where(mask, D, 0), axis=-1)  # [S*L, 128]
        msgs = msg_dot_fn(Sv, dot, wt)                 # [S*L, 128, K]
        # dead lanes (rel == -1) match no output lane -> contribute 0
        p = jnp.einsum("rcw,rck->rwk", mask.astype(Sv.dtype), msgs)
        return jnp.sum(p.reshape(S, L, W, Kdim), axis=1)

    # per-row live bytes: S + T + msgs + partials tiles [W, K] each,
    # plus the [W, W] dot/mask blocks
    row_bytes = 4 * W * (W + 4 * Kdim)
    outs = []
    row0 = 0
    for (cnt, L) in sp.classes:
        # whole slots per block, >= 1, sized so one block's rows stay
        # under block_bytes
        S = max(1, min(cnt, block_bytes // max(1, L * row_bytes)))
        nB, rem = divmod(cnt, S)

        def seg(lo, n):
            sl = slice(row0 + lo * L, row0 + (lo + n) * L)
            return (rowbind[sl], rel[sl], weight[sl], row_tile[sl])

        cls_out = []
        if nB:
            rb, rl, wt, rt = seg(0, nB * S)
            xs = (rb.reshape(nB, S * L), rl.reshape(nB, S * L, W),
                  wt.reshape(nB, S * L, W), rt.reshape(nB, S * L))

            def step(_, x, S=S, L=L):
                return None, slot_results(*x, S, L)

            _, reds = jax.lax.scan(step, None, xs)   # [nB, S, 128, K]
            cls_out.append(reds.reshape(nB * S, W, Kdim))
        if rem:
            cls_out.append(slot_results(*seg(nB * S, rem), rem, L))
        outs.append(jnp.concatenate(cls_out, axis=0))
        row0 += cnt * L
    # trailing identity slot (sum identity = 0) in the message dtype,
    # exactly like _class_combine's; zero classes degenerate cleanly
    out_dtype = outs[0].dtype if outs else state.dtype
    outs.append(jnp.zeros((1, W, Kdim), out_dtype))
    slots = jnp.concatenate(outs, axis=0)          # [n_slots+1, 128, K]
    return jnp.take(slots, tile_pos, axis=0).reshape(-1, Kdim)


def resolve_pair_dot_stream(pair_stream, sp, rows: int,
                            kdim: int) -> bool:
    """Auto-engage rule for the streamed SDDMM delivery, mirroring the
    engines' chunk-streaming budget (ops/tiled.STREAM_MSG_BYTES, the
    1 GB rule): stream once the monolithic path's stacked per-row
    partials — f32 [rows, Rp, 128, kdim], what vmap over parts
    materializes together and what produced the 67.7 GB NetFlix
    compile allocation — would pass the budget.  pair_stream
    True/False forces; None picks by budget (the default K-dim pair
    path at scale)."""
    if sp is None:
        return False
    if pair_stream is not None:
        return bool(pair_stream)
    from lux_tpu.ops.tiled import STREAM_MSG_BYTES
    return rows * sp.Rp * W * max(1, kdim) * 4 > STREAM_MSG_BYTES


def stacked_pair_reduce_numpy(sp: StackedPairPlan, p: int,
                              state_flat: np.ndarray, kind: str = "sum",
                              msg=None) -> np.ndarray:
    """Oracle for one part of a stacked plan.  msg(vals, weight) maps
    delivered values (+ per-lane weights) to messages; default uses
    the values unchanged."""
    s2d = np.asarray(state_flat).reshape(-1, W)
    vals = s2d[sp.rowbind[p]].astype(np.float64)     # [Rp, 128]
    wp = sp.weight[p] if sp.weight is not None else None
    if msg is not None:
        vals = msg(vals, wp)
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    out = np.full(sp.n_tiles * W, ident)
    row_base = {}
    slot_base = {}
    s = r = 0
    for c, L in sp.classes:
        slot_base[L], row_base[L] = s, r
        s += c
        r += c * L
    for t in range(sp.n_tiles):
        slot = int(sp.tile_pos[p, t])
        if slot == sp.n_slots:
            continue
        for c, L in sp.classes:
            sb, rb = slot_base[L], row_base[L]
            if sb <= slot < sb + c:
                for rr in range(rb + (slot - sb) * L,
                                rb + (slot - sb + 1) * L):
                    lanes = sp.rel_dst[p, rr]
                    for col in range(W):
                        w = int(lanes[col])
                        if 0 <= w < W:
                            out[t * W + w] = op(
                                out[t * W + w], vals[rr, col])
                break
    return out


def stacked_pair_dot_numpy(sp: StackedPairPlan, p: int,
                           state: np.ndarray, part_tile0: int,
                           msg_dot_fn) -> np.ndarray:
    """float64 oracle for one part of the SDDMM pair delivery
    (pair_partial_dot / pair_partial_dot_streamed): per delivery row,
    dot[c] = <S[c], T[rel[c]]> over the row's dst tile, msgs =
    msg_dot_fn(S, dot, w), accumulated into the lane's dst vertex.
    state: [n_state_rows * 128, K]; returns [n_tiles * 128, K].

    With integer-valued states/weights whose products stay under 2^24
    this equals the f32 device result EXACTLY (all sums exact) — the
    equivalence tests' trick for order-independent exact matching."""
    s2 = np.asarray(state, np.float64)
    Kdim = s2.shape[-1]
    out = np.zeros((sp.n_tiles * W, Kdim))
    row_base, slot_base = {}, {}
    s = r = 0
    for c, L in sp.classes:
        slot_base[L], row_base[L] = s, r
        s += c
        r += c * L
    for t in range(sp.n_tiles):
        slot = int(sp.tile_pos[p, t])
        if slot == sp.n_slots:
            continue
        for c, L in sp.classes:
            sb, rb = slot_base[L], row_base[L]
            if sb <= slot < sb + c:
                for rr in range(rb + (slot - sb) * L,
                                rb + (slot - sb + 1) * L):
                    S = s2[sp.rowbind[p, rr] * W:
                           (sp.rowbind[p, rr] + 1) * W]       # [128, K]
                    tile = int(sp.row_tile[p, rr])
                    T = s2[(part_tile0 + tile) * W:
                           (part_tile0 + tile + 1) * W]       # [128, K]
                    lanes = sp.rel_dst[p, rr]
                    for col in range(W):
                        w = int(lanes[col])
                        if not 0 <= w < W:
                            continue
                        # numpy 0-d scalars so broadcasting program
                        # callbacks ((w - dot)[..., None] * src) work
                        dot = S[col] @ T[w]
                        msg = msg_dot_fn(
                            S[col], dot,
                            np.float64(sp.weight[p, rr, col]))
                        out[t * W + w] += np.asarray(msg).reshape(Kdim)
                break
    return out
