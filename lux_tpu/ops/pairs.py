"""Pair-lane delivery: gather-free edge values for dense tile pairs.

Measured fact (PERF_NOTES.md): the XLA gather costs ~9 ns per ROW
fetched, independent of row width.  So edges in a dense (src-tile,
dst-tile) pair — both tiles 128 vertices — can all be served by
fetching the pair's 128-wide source state row ONCE per pair-row:
lane = source offset within the src tile, so the value needs no
selection at all; the existing chunk-partial compare-reduce routes it
to its destination offset (``rel_dst``).

Under a degree-sorted vertex numbering (hubs share tiles), pairs with
>= 8 edges cover ~74% of RMAT edges at ~6x lane inflation — ~3 ns/edge
total against 9 ns for the per-edge gather.  The residual sparse-pair
edges keep the regular gather path.

Row layout: pair (s, t) with maximum per-source multiplicity m gets m
rows; occurrence o of source lane c carries the o-th edge (s*128+c ->
t*128+rel).  Unused lanes carry rel = 128 (the reduce's pad marker).
Rows are grouped per destination tile and depth-classed so the
cross-row combine is a static reshape-reduce, like experiments/router.py's
slotted classes.
"""

from __future__ import annotations

import dataclasses

import numpy as np

W = 128


@dataclasses.dataclass
class PairPlan:
    """Per-part pair-lane arrays (host numpy).

    rowbind   int32 [R]      global state2d row (= src tile) per row
    rel_dst   int32 [R, 128] dst offset in [0,128), 128 = dead lane
    classes   [(tile_start, tile_count, depth)] for the combine; rows
              are tile-major in ``tile_order`` with per-tile depth
              padded to the class depth (dead rows are all-128)
    tile_order int32 [n_tiles] part-local dst tile of each class slot
    residual  bool [ne_part]  True for edges NOT covered by pairs
    """

    rowbind: np.ndarray
    rel_dst: np.ndarray
    classes: list
    tile_order: np.ndarray
    residual: np.ndarray
    n_tiles: int
    stats: dict


def build_pair_plan(src_slot: np.ndarray, dst_local: np.ndarray,
                    vpad: int, threshold: int = 8,
                    max_occ: int = 128,
                    levels_growth: float = 1.35) -> PairPlan:
    """src_slot: int [ne] global padded state slots (state2d row =
    slot // 128); dst_local: int [ne] part-local dst in [0, vpad);
    vpad must be a multiple of 128."""
    assert vpad % W == 0
    ne = len(dst_local)
    n_tiles = vpad // W
    src_slot = np.asarray(src_slot, np.int64)
    dst_local = np.asarray(dst_local, np.int64)

    st = src_slot // W
    dt = dst_local // W
    pair = st * n_tiles + dt
    order = np.argsort(pair, kind="stable")
    pp = pair[order]
    starts = np.concatenate(
        ([0], np.nonzero(pp[1:] != pp[:-1])[0] + 1, [ne]))
    sizes = np.diff(starts)
    pair_id = np.repeat(np.arange(len(sizes)), sizes)

    sel_pair = sizes >= threshold
    esel_sorted = sel_pair[pair_id]               # in pair-sorted order
    residual = np.ones(ne, bool)
    residual[order[esel_sorted]] = False

    # occurrence index of each covered edge within (pair, src lane)
    cov = order[esel_sorted]                      # original edge idx
    key = pair[cov] * (np.int64(1) << 32) + src_slot[cov]
    srt = np.argsort(key, kind="stable")
    ks = key[srt]
    newg = np.ones(len(ks), bool)
    newg[1:] = ks[1:] != ks[:-1]
    pos = np.arange(len(ks))
    gst = np.maximum.accumulate(np.where(newg, pos, 0))
    occ = np.empty(len(ks), np.int64)
    occ[srt] = pos - gst

    # Optional occurrence-depth cap (edges beyond it ride the residual
    # gather).  Measured on RMAT21: capping LOSES — deep-occurrence
    # rows belong to hub pairs and are well-filled, so the default
    # effectively disables the cap.
    keep = occ < max_occ
    if not keep.all():
        # mark dropped edges residual; rebuild cov/occ on the kept set
        dropped = np.zeros(len(cov), bool)
        dropped[srt] = ~keep
        residual[cov[dropped]] = True
        cov = cov[~dropped]
        k2 = np.argsort(pair[cov] * (np.int64(1) << 32) + src_slot[cov],
                        kind="stable")
        ks2 = (pair[cov] * (np.int64(1) << 32) + src_slot[cov])[k2]
        ng2 = np.ones(len(ks2), bool)
        ng2[1:] = ks2[1:] != ks2[:-1]
        pos2 = np.arange(len(ks2))
        gst2 = np.maximum.accumulate(np.where(ng2, pos2, 0))
        occ = np.empty(len(ks2), np.int64)
        occ[k2] = pos2 - gst2

    # per-pair row count = max occurrence + 1 (pair ids of the
    # possibly-reduced covered set, via the sorted unique pair keys)
    pid_cov = np.searchsorted(pp[starts[:-1]], pair[cov])
    # remap selected pair ids to dense [0, P)
    sel_ids = np.nonzero(sel_pair)[0]
    remap = np.full(len(sizes), -1, np.int64)
    remap[sel_ids] = np.arange(len(sel_ids))
    pidx = remap[pid_cov]                         # [n_cov]
    nrows_pair = np.zeros(len(sel_ids), np.int64)
    np.maximum.at(nrows_pair, pidx, occ + 1)

    # order pairs by dst tile (for the per-tile combine), then src tile
    pair_dt = (pp[starts[:-1]][sel_pair] % n_tiles)
    tile_sort = np.argsort(pair_dt, kind="stable")
    # per-tile total rows -> depth classes
    rows_by_tile = np.zeros(n_tiles, np.int64)
    np.add.at(rows_by_tile, pair_dt, nrows_pair)
    t_order = np.argsort(-rows_by_tile, kind="stable")
    depth_sorted = rows_by_tile[t_order]

    levels = [0, 1, 2, 3, 4, 5, 6, 7, 8]
    v = 8
    while v < int(depth_sorted.max(initial=0)):
        v = int(v * levels_growth) + 1
        levels.append(v)
    lev = np.asarray(levels, np.int64)
    depth = lev[np.searchsorted(lev, depth_sorted)]

    row_off_tile = np.concatenate(([0], np.cumsum(depth)))
    R = int(row_off_tile[-1])

    # rows of each pair: base = tile's offset + running offset within
    # the tile (pairs in tile_sort order)
    tile_pos = np.empty(n_tiles, np.int64)        # tile -> class slot
    tile_pos[t_order] = np.arange(n_tiles)
    pair_base = np.zeros(len(sel_ids), np.int64)
    running = np.zeros(n_tiles, np.int64)
    for j in tile_sort:                            # per selected pair
        t = pair_dt[j]
        pair_base[j] = row_off_tile[tile_pos[t]] + running[t]
        running[t] += nrows_pair[j]
    assert (running <= depth[tile_pos]).all()

    rowbind = np.zeros(R, np.int32)
    rel_dst = np.full((R, W), W, np.int32)
    rows = pair_base[pidx] + occ
    rowbind_rows = (src_slot[cov] // W).astype(np.int32)
    rowbind[rows] = rowbind_rows
    rel_dst[rows, src_slot[cov] % W] = (dst_local[cov] % W).astype(
        np.int32)

    classes = []
    t0 = 0
    for L in np.unique(depth)[::-1]:
        cnt = int((depth == L).sum())
        if L > 0:
            classes.append((t0, cnt, int(L)))
        t0 += cnt

    plan = PairPlan(rowbind=rowbind, rel_dst=rel_dst, classes=classes,
                    tile_order=t_order.astype(np.int32),
                    residual=residual, n_tiles=n_tiles, stats={})
    ncov = int((~residual).sum())
    plan.stats = dict(ne=ne, covered=ncov, R=R,
                      coverage=ncov / max(ne, 1),
                      inflation=R * W / max(ncov, 1))
    return plan


def pair_reduce_numpy(plan: PairPlan, state_flat: np.ndarray,
                      kind: str = "sum") -> np.ndarray:
    """Oracle: run the pair-lane delivery + reduce on host.
    Returns [vpad] partial reduction (identity where uncovered)."""
    s2d = np.asarray(state_flat).reshape(-1, W)
    vals = s2d[plan.rowbind]                       # [R, 128]
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    vpad = plan.n_tiles * W
    out = np.full(vpad, ident)
    # per-row compare-reduce + per-tile combine
    row0 = 0
    for (t0, cnt, L) in plan.classes:
        for i in range(cnt):
            tile = plan.tile_order[t0 + i]
            for r in range(row0 + i * L, row0 + (i + 1) * L):
                lanes = plan.rel_dst[r]
                for c in range(W):
                    w = lanes[c]
                    if w < W:
                        out[tile * W + w] = op(out[tile * W + w],
                                               vals[r, c])
        row0 += cnt * L
    return out
