"""Page-binned two-level gather: break the ~9 ns/edge delivery floor.

Every delivery path in the repo bottoms out at XLA's ~9 ns per 4-byte
random access (PERF_NOTES round 2: 8.96 ns/elem, flat from 16 KB to
64 MB tables) — ~90% of a pull iteration.  The same measurements show
the escape hatch on this hardware: STATIC row movement is cheap
(`jnp.take` of [*, 128] rows = 24 ns/row = 0.19 ns/elem) and the one
fast DYNAMIC primitive is the Pallas 128-lane shuffle
(`take_along_axis` axis=1 -> `tpu.dynamic_gather` dim 1, 0.38
ns/elem).  So a gather decomposed into *fetch unique 128-wide pages,
then shuffle within pages* is priced well under 2 ns/edge whenever
edges share pages — which degree-sorted power-law graphs do heavily.

The decomposition (the microbenchmark-driven primitive design of the
IPU dissection paper, PAPERS.md; the fixed-size-page blocking idiom is
Ragged Paged Attention's):

host plan (built once, shipped as jit ARGUMENTS like ops/pairs.py):
  1. bin every edge by (destination tile, source PAGE) where a page is
     one 128-wide row of the reshaped state table ``[T, 128]``;
  2. each bin of n edges becomes ceil(n/128) full delivery rows: a row
     binds to ONE page, its 128 lanes carry (source lane, destination
     offset) pairs — dead lanes carry rel = -1 (the identity-sentinel
     convention: they match no output lane downstream);
  3. pages are DEDUPLICATED per part into ``page_ids [n_pages]``; the
     per-edge (page_slot, lane) pair packs into one uint32
     ``page_slot << 7 | lane`` (lane is exactly 7 bits at W=128 — the
     round-5 owner ``src << 7 | rel`` encoding), and every lane of a
     row shares its page_slot, so the row's page decodes from lane 0;
  4. rows group per destination tile and depth-class exactly like the
     pair plan, so the cross-row combine is the same static
     reshape-reduce (ops/pairs._class_combine).

device (``paged_partial``):
  1. ``pages = take(state2d, page_ids)``     — THE state-table access
     of the iteration (row-granular; audited as the one access of a
     dense iteration, lux_tpu/audit.py gather-budget);
  2. ``rows = take(pages, page_slot)``       — row fetch from the
     small deduplicated buffer (0.19 ns/elem class);
  3. ``vals = take_along_axis(rows, lane)``  — the 0.38 ns/elem lane
     shuffle, as a Pallas kernel on TPU (interpret-mode on CPU like
     ops/pallas_reduce.py; plain XLA on the CPU test mesh);
  4. existing compare-reduce machinery delivers by rel
     (ops/tiled.chunk_partials / chunk_partials_pallas).

Coverage is TOTAL — every edge rides a paged row, so ``gather=
"paged"`` engines produce exactly the reduce the flat gather produces
(bitwise for order-independent min/max reductions; sum reductions
re-associate, proven exact on sub-2^24 integer states like the SDDMM
oracle trick, ops/pairs.stacked_pair_dot_numpy).  Whether the paged
path PAYS depends on the plan's measured row fill and unique-page
ratio: ``gather="auto"`` resolves by the scalemodel break-even
(scalemodel.page_gather_ns) on the stats the plan records.

Reference analogue: the reference stages remote regions whole and
indexes them per edge (reference pull_model.inl:454-461); here the
host pre-factors that per-edge index into static page movement plus a
lane-granular shuffle, because that is what the TPU prices cheaply.
"""

from __future__ import annotations

import dataclasses

import numpy as np

W = 128

# page_slot rides the high 25 bits of the packed uint32 (lane is 7
# bits at W=128) — same bound class as the owner layout's packed
# src << 7 | rel encoding (ops/owner.OwnerLayout.PACK_VPAD_MAX)
PAGE_SLOT_MAX = 1 << 25


@dataclasses.dataclass
class PagedPlan:
    """Stacked (all-parts) page-binned delivery plan (host numpy).

    page_ids  int32 [P, n_pages]    deduplicated state2d page rows per
                                    part (pad rows point at page 0)
    slot_lane uint32 [P, Rp, 128]   packed ``page_slot << 7 | lane``;
                                    every lane of a row shares the
                                    row's page_slot (decode from lane
                                    0); dead lanes carry lane 0
    rel_dst   int8 [P, Rp, 128]     dst offset in [0, 128); -1 = dead
    weight    f32 [P, Rp, 128] | None  per-lane edge weight (0 dead)
    row_tile  int32 [P, Rp]         dst tile of each row (dead -> 0)
    tile_pos  int32 [P, n_tiles]    class slot per tile; tiles with no
                                    slot point at the trailing
                                    identity slot ``n_slots``
    classes   [(count, depth)]      shared by every part (rows laid
                                    out against the common elementwise
                                    -max depth profile, like
                                    ops/pairs.plan_sharded_pairs)
    n_tiles   destination tiles per plan row (per-part tiles for the
              dense engines; GLOBAL tiles G for the owner plan)

    PAGE-MAJOR mode (``mode="pagemajor"``, round 16): delivery rows
    bind to source pages FIRST (full 128-lane GATHER rows — one page
    fetch + lane shuffle serves 128 edges regardless of how few share
    a destination tile), and the reduce runs over VIRTUAL rows — each
    the contiguous lane run of one (gather row, dst tile) pair,
    materialized by a row-granular ``take`` of the delivered values.
    ``slot_lane`` then holds the GATHER rows' packed page/lane pairs
    (leading dim Rg) and ``vrow_src [P, Rp]`` maps each virtual
    (reduce-level) row to its gather row; rel_dst/weight/row_tile/
    tile_pos keep their reduce-level meaning over the Rp virtual
    rows.  The OWNER page-major plan additionally groups gather rows
    by DESTINATION PART (``route`` = Mg rows per (src, dst) pair, the
    all_to_all routing quantum) with sender-side weights so messages
    are complete before the routing hop; vrow_src then indexes the
    RECEIVED ``[P_src * Mg]`` row buffer.
    """

    page_ids: np.ndarray
    slot_lane: np.ndarray
    rel_dst: np.ndarray
    weight: np.ndarray | None
    row_tile: np.ndarray
    tile_pos: np.ndarray
    classes: list
    n_tiles: int
    n_slots: int
    R: int
    Rp: int
    n_pages: int
    stats: dict
    mode: str = "paged"
    vrow_src: np.ndarray | None = None   # int32 [P, Rp] -> gather row
    Rg: int = 0                          # padded gather rows (pm mode)
    route: int = 0                       # Mg rows per (src, dst) pair


# ---------------------------------------------------------------------
# host plan builder
# ---------------------------------------------------------------------


def _part_rows(src_idx, dst_tile, dst_rel, n_dst_tiles: int,
               n_src_rows: int, weights=None):
    """Bin one part's edges by (dst tile, source page) and lay each
    bin into ceil(count/128) full 128-lane rows (tile-major order).

    Returns (row_page, lane int8 [R, 128], rel int8 [R, 128],
    weight f32 [R, 128] | None, row_tile, rows_by_tile) host arrays;
    R = 0 for an edge-less part."""
    ne = len(src_idx)
    if ne == 0:
        z = np.zeros((0, W), np.int8)
        wz = np.zeros((0, W), np.float32) if weights is not None else None
        return (np.zeros(0, np.int64), z, z.copy(), wz,
                np.zeros(0, np.int64),
                np.zeros(n_dst_tiles, np.int64))
    src_idx = np.asarray(src_idx, np.int64)
    page = src_idx // W
    lane = (src_idx % W).astype(np.int8)
    rel8 = np.asarray(dst_rel, np.int64).astype(np.int8)
    key = np.asarray(dst_tile, np.int64) * np.int64(n_src_rows) + page
    idx = np.arange(ne, dtype=np.int64)
    from lux_tpu import native
    native.sort_kv(key, (idx,))          # fused radix: key + edge idx
    newg = np.ones(ne, bool)
    newg[1:] = key[1:] != key[:-1]
    bstart = np.nonzero(newg)[0]
    cnt = np.diff(np.concatenate((bstart, [ne])))
    bin_of = np.cumsum(newg) - 1                     # sorted pos -> bin
    off = np.arange(ne, dtype=np.int64) - bstart[bin_of]
    rows_of_bin = -(-cnt // W)
    row_base = np.concatenate(([0], np.cumsum(rows_of_bin)[:-1]))
    row_of = row_base[bin_of] + off // W
    lanepos = off % W
    R = int(rows_of_bin.sum())
    bin_page = key[bstart] % np.int64(n_src_rows)
    bin_tile = key[bstart] // np.int64(n_src_rows)
    row_page = np.repeat(bin_page, rows_of_bin)
    row_tile = np.repeat(bin_tile, rows_of_bin)
    lane_arr = np.zeros((R, W), np.int8)
    rel_arr = np.full((R, W), -1, np.int8)
    lane_arr[row_of, lanepos] = lane[idx]
    rel_arr[row_of, lanepos] = rel8[idx]
    w_arr = None
    if weights is not None:
        w_arr = np.zeros((R, W), np.float32)
        w_arr[row_of, lanepos] = np.asarray(weights, np.float32)[idx]
    # every edge must own a distinct (row, lane) — the pair planner's
    # loud collision check (ops/pairs.build_pair_plan)
    delivered = int(np.count_nonzero(rel_arr != -1))
    if delivered != ne:
        raise AssertionError(
            f"paged plan dropped {ne - delivered} of {ne} edges "
            f"(colliding (row, lane) writes)")
    rows_by_tile = np.bincount(row_tile, minlength=n_dst_tiles)
    return row_page, lane_arr, rel_arr, w_arr, row_tile, rows_by_tile


def _pad8_distinct(n: int, avoid: int) -> int:
    """Round up to the Pallas 8-row block granularity, keeping the
    result distinct from ``avoid`` — the padded leading dim must never
    equal the reshaped state table's row count, or the audit's
    operand-shape accounting (lux_tpu/audit.py gather-budget paged
    recognition) could mistake a buffer fetch for the table access."""
    n = max(8, -(-n // 8) * 8)
    while n == avoid:
        n += 8
    return n


def _assemble(parts, n_dst_tiles: int, n_src_rows: int, ne_total: int,
              weighted: bool) -> PagedPlan:
    """Stack per-part ``_part_rows`` outputs against a COMMON depth
    profile (elementwise max over parts, ladder-quantized) so every
    part compiles the same class structure — the
    plan_sharded_pairs two-pass discipline."""
    from lux_tpu.ops.pairs import quantize_depths

    if n_src_rows > PAGE_SLOT_MAX:
        raise ValueError(
            f"paged gather needs a state table of <= {PAGE_SLOT_MAX} "
            f"128-wide pages (25-bit page_slot), got {n_src_rows}")
    P = len(parts)
    prof = np.zeros(n_dst_tiles, np.int64)
    for pr in parts:
        prof = np.maximum(prof, np.sort(pr[5])[::-1])
    depth = quantize_depths(prof)
    row_off = np.concatenate(([0], np.cumsum(depth)))
    Rtot = int(row_off[-1])
    Rp = _pad8_distinct(Rtot, n_src_rows)

    classes = []
    for L in np.unique(depth)[::-1]:
        cnt = int((depth == L).sum())
        if L > 0:
            classes.append((cnt, int(L)))
    n_slots = sum(c for c, _L in classes)

    uniq_pages = [np.unique(pr[0]) for pr in parts]
    max_pages = max((len(u) for u in uniq_pages), default=1) or 1
    n_pages = _pad8_distinct(max_pages, n_src_rows)

    page_ids = np.zeros((P, n_pages), np.int32)
    slot_lane = np.zeros((P, Rp, W), np.uint32)
    rel_dst = np.full((P, Rp, W), -1, np.int8)
    wgt = np.zeros((P, Rp, W), np.float32) if weighted else None
    row_tile = np.zeros((P, Rp), np.int32)
    tile_pos = np.full((P, n_dst_tiles), n_slots, np.int32)

    rows_real = 0
    for p, pr in enumerate(parts):
        r_page, lane, rel, w, r_tile, by_tile = pr
        rows_real += len(r_page)
        u = uniq_pages[p]
        page_ids[p, :len(u)] = u.astype(np.int32)
        t_order = np.argsort(-by_tile, kind="stable")
        # slot s (depth[s] > 0) hosts tile t_order[s]; depth-0 slots
        # and the tiles beyond them reduce to the identity slot
        live = depth > 0
        tile_pos[p, t_order[live]] = np.nonzero(live)[0].astype(np.int32)
        if not len(r_page):
            continue
        if (by_tile[t_order] > depth).any():
            raise AssertionError("common depth profile does not cover "
                                 "a part's per-tile row counts")
        # rows come out of _part_rows tile-major: place each tile's
        # run at its slot's row offset
        slot_of_tile = np.full(n_dst_tiles, -1, np.int64)
        slot_of_tile[t_order] = np.arange(n_dst_tiles)
        first = np.zeros(n_dst_tiles, np.int64)
        np.add.at(first, r_tile, 1)
        first = np.concatenate(([0], np.cumsum(first)[:-1]))
        within = np.arange(len(r_page)) - first[r_tile]
        dst = row_off[slot_of_tile[r_tile]] + within
        pslot = np.searchsorted(u, r_page).astype(np.uint32)
        slot_lane[p, dst] = ((pslot[:, None] << np.uint32(7))
                             | lane.astype(np.uint32) & np.uint32(0x7F))
        rel_dst[p, dst] = rel
        row_tile[p, dst] = r_tile.astype(np.int32)
        if weighted:
            wgt[p, dst] = w

    fill = ne_total / max(rows_real, 1)
    unique_total = sum(len(u) for u in uniq_pages)
    stats = dict(
        ne=ne_total, rows=rows_real, fill=fill,
        unique_pages=unique_total,
        page_ratio=unique_total * W / max(ne_total, 1),
        # live lanes per PADDED row: class-ladder pad rows pay the
        # same per-row machinery, so cost models divide by this
        padded_fill=ne_total / max(P * Rp, 1),
        lane_inflation=P * Rp * W / max(ne_total, 1))
    return PagedPlan(
        page_ids=page_ids, slot_lane=slot_lane, rel_dst=rel_dst,
        weight=wgt, row_tile=row_tile, tile_pos=tile_pos,
        classes=classes, n_tiles=n_dst_tiles, n_slots=n_slots,
        R=Rtot, Rp=Rp, n_pages=n_pages, stats=stats)


# ---------------------------------------------------------------------
# page-major layout (round 16): gather rows bind to pages FIRST
# ---------------------------------------------------------------------


def _pm_layout(src_idx, dst_local, n_dst_tiles: int, n_src_rows: int,
               group=None):
    """Shared index math of the page-major layout: sort this part's
    edges by (row group, source page, destination), then derive each
    edge's GATHER row (full 128-lane rows binding to one
    (group, page)) and its VIRTUAL row (the contiguous lane run of
    one (gather row, dst tile) — the reduce-level unit).  ``group``
    is the optional row-group key (the DESTINATION PART for the owner
    routing plan; None = one group).  Returns a dict of per-edge /
    per-row host arrays consumed by both the array builder
    (``_part_rows_pm``) and the counting pass
    (``_part_bin_stats_pm``)."""
    from lux_tpu import native

    ne = len(src_idx)
    src_idx = np.asarray(src_idx, np.int64)
    dst = np.asarray(dst_local, np.int64)
    page = src_idx // W
    lane = (src_idx % W).astype(np.int8)
    grp = (np.zeros(ne, np.int64) if group is None
           else np.asarray(group, np.int64))
    D = np.int64(n_dst_tiles) * W
    key = (grp * np.int64(n_src_rows) + page) * D + dst
    idx = np.arange(ne, dtype=np.int64)
    native.sort_kv(key, (idx,))
    gp_key = key // D                    # group * n_src_rows + page
    dst_s = key % D
    newp = np.ones(ne, bool)
    newp[1:] = gp_key[1:] != gp_key[:-1]
    pstart = np.nonzero(newp)[0]
    pcnt = np.diff(np.concatenate((pstart, [ne])))
    rows_of = -(-pcnt // W)
    row_base = np.concatenate(([0], np.cumsum(rows_of)[:-1]))
    pbin = np.cumsum(newp) - 1
    off = np.arange(ne, dtype=np.int64) - pstart[pbin]
    gr = row_base[pbin] + off // W       # gather row of each edge
    lpos = off % W                       # its lane within that row
    Rg = int(rows_of.sum())
    g_page = np.repeat(gp_key[pstart] % np.int64(n_src_rows), rows_of)
    g_group = np.repeat(gp_key[pstart] // np.int64(n_src_rows),
                        rows_of)
    # virtual rows: contiguous (gather row, dst tile) runs — gr is
    # non-decreasing along the sort and dst is sorted within a
    # (group, page) bin, so the run key is non-decreasing too
    tile = dst_s // W
    vkey = gr * np.int64(n_dst_tiles) + tile
    newv = np.ones(ne, bool)
    newv[1:] = vkey[1:] != vkey[:-1]
    vb = np.nonzero(newv)[0]
    return dict(ne=ne, idx=idx, gr=gr, lpos=lpos, lane=lane,
                dst_s=dst_s, tile=tile, Rg=Rg, g_page=g_page,
                g_group=g_group,
                vid=np.cumsum(newv) - 1, vb=vb,
                vrow_gr=gr[vb], vrow_tile=tile[vb])


def _part_rows_pm(src_idx, dst_local, n_dst_tiles: int,
                  n_src_rows: int, weights=None, group=None):
    """One part's PAGE-MAJOR rows: full gather rows (one per 128
    edges of a (group, page) bin) plus the virtual reduce rows.
    Returns (g_page, g_group, glane int8 [Rg, 128], w_gather,
    vrow_gr, vrow_tile, rel int8 [Rv, 128], w_virtual,
    rows_by_tile)."""
    ne = len(src_idx)
    if ne == 0:
        z8 = np.zeros((0, W), np.int8)
        zw = np.zeros((0, W), np.float32) if weights is not None \
            else None
        zi = np.zeros(0, np.int64)
        return (zi, zi.copy(), z8, zw, zi.copy(), zi.copy(),
                z8.copy(),
                zw.copy() if zw is not None else None,
                np.zeros(n_dst_tiles, np.int64))
    L = _pm_layout(src_idx, dst_local, n_dst_tiles, n_src_rows, group)
    idx, gr, lpos = L["idx"], L["gr"], L["lpos"]
    glane = np.zeros((L["Rg"], W), np.int8)
    glane[gr, lpos] = L["lane"][idx]
    w_g = w_v = None
    if weights is not None:
        ws = np.asarray(weights, np.float32)[idx]
        w_g = np.zeros((L["Rg"], W), np.float32)
        w_g[gr, lpos] = ws
    Rv = len(L["vb"])
    rel = np.full((Rv, W), -1, np.int8)
    rel[L["vid"], lpos] = (L["dst_s"] % W).astype(np.int8)
    if weights is not None:
        w_v = np.zeros((Rv, W), np.float32)
        w_v[L["vid"], lpos] = ws
    # every edge owns a distinct (virtual row, lane) — the planner's
    # loud collision check (same contract as _part_rows)
    delivered = int(np.count_nonzero(rel != -1))
    if delivered != ne:
        raise AssertionError(
            f"page-major plan dropped {ne - delivered} of {ne} edges "
            f"(colliding (row, lane) writes)")
    rows_by_tile = np.bincount(L["vrow_tile"], minlength=n_dst_tiles)
    return (L["g_page"], L["g_group"], glane, w_g, L["vrow_gr"],
            L["vrow_tile"], rel, w_v, rows_by_tile)


def _part_bin_stats_pm(src_idx, dst_local, n_dst_tiles: int,
                       n_src_rows: int, group=None,
                       n_groups: int = 1):
    """Counting half of ``_part_rows_pm``: (virtual rows by tile,
    n virtual rows, n gather rows, gather rows by group) from the
    sort only — what ``gather="auto"`` prices the page-major mode
    from without materializing it."""
    ne = len(src_idx)
    if ne == 0:
        return (np.zeros(n_dst_tiles, np.int64), 0, 0,
                np.zeros(n_groups, np.int64))
    L = _pm_layout(src_idx, dst_local, n_dst_tiles, n_src_rows, group)
    by_tile = np.bincount(L["vrow_tile"], minlength=n_dst_tiles)
    by_group = np.bincount(L["g_group"], minlength=n_groups)
    return by_tile, len(L["vb"]), L["Rg"], by_group


def _assemble_pm(parts, n_dst_tiles: int, n_src_rows: int,
                 ne_total: int, weighted: bool) -> PagedPlan:
    """Stack per-part ``_part_rows_pm`` outputs (dense, group=None)
    against a common depth profile over the VIRTUAL rows — the same
    two-pass discipline as ``_assemble``; the gather rows pad to a
    common Rg."""
    from lux_tpu.ops.pairs import quantize_depths

    if n_src_rows > PAGE_SLOT_MAX:
        raise ValueError(
            f"paged gather needs a state table of <= {PAGE_SLOT_MAX} "
            f"128-wide pages (25-bit page_slot), got {n_src_rows}")
    P = len(parts)
    prof = np.zeros(n_dst_tiles, np.int64)
    for pr in parts:
        prof = np.maximum(prof, np.sort(pr[8])[::-1])
    depth = quantize_depths(prof)
    row_off = np.concatenate(([0], np.cumsum(depth)))
    Rtot = int(row_off[-1])
    Rp = _pad8_distinct(Rtot, n_src_rows)
    classes = []
    for Lv in np.unique(depth)[::-1]:
        cnt = int((depth == Lv).sum())
        if Lv > 0:
            classes.append((cnt, int(Lv)))
    n_slots = sum(c for c, _L in classes)

    uniq_pages = [np.unique(pr[0]) for pr in parts]
    max_pages = max((len(u) for u in uniq_pages), default=1) or 1
    n_pages = _pad8_distinct(max_pages, n_src_rows)
    Rg_max = max((len(pr[0]) for pr in parts), default=1) or 1
    Rg = _pad8_distinct(Rg_max, n_src_rows)

    page_ids = np.zeros((P, n_pages), np.int32)
    gsl = np.zeros((P, Rg, W), np.uint32)
    rel_dst = np.full((P, Rp, W), -1, np.int8)
    wgt = np.zeros((P, Rp, W), np.float32) if weighted else None
    row_tile = np.zeros((P, Rp), np.int32)
    vrow_src = np.zeros((P, Rp), np.int32)
    tile_pos = np.full((P, n_dst_tiles), n_slots, np.int32)

    g_rows_real = v_rows_real = 0
    for p, pr in enumerate(parts):
        (g_page, _gg, glane, _wg, vrow_gr, vrow_tile, rel, w_v,
         by_tile) = pr
        g_rows_real += len(g_page)
        v_rows_real += len(vrow_gr)
        u = uniq_pages[p]
        page_ids[p, :len(u)] = u.astype(np.int32)
        t_order = np.argsort(-by_tile, kind="stable")
        live = depth > 0
        tile_pos[p, t_order[live]] = np.nonzero(live)[0].astype(
            np.int32)
        if not len(g_page):
            continue
        pslot = np.searchsorted(u, g_page).astype(np.uint32)
        gsl[p, :len(g_page)] = ((pslot[:, None] << np.uint32(7))
                                | glane.astype(np.uint32)
                                & np.uint32(0x7F))
        if (by_tile[t_order] > depth).any():
            raise AssertionError("common depth profile does not cover "
                                 "a part's per-tile row counts")
        # virtual rows tile-major into the class slots (like
        # _assemble; they come out page-major, so re-sort by tile)
        ordv = np.argsort(vrow_tile, kind="stable")
        vt = vrow_tile[ordv]
        slot_of_tile = np.full(n_dst_tiles, -1, np.int64)
        slot_of_tile[t_order] = np.arange(n_dst_tiles)
        first = np.zeros(n_dst_tiles, np.int64)
        np.add.at(first, vt, 1)
        first = np.concatenate(([0], np.cumsum(first)[:-1]))
        within = np.arange(len(vt)) - first[vt]
        dst = row_off[slot_of_tile[vt]] + within
        rel_dst[p, dst] = rel[ordv]
        row_tile[p, dst] = vt.astype(np.int32)
        vrow_src[p, dst] = vrow_gr[ordv].astype(np.int32)
        if weighted:
            wgt[p, dst] = w_v[ordv]

    stats = dict(
        ne=ne_total, rows=v_rows_real,
        fill=ne_total / max(v_rows_real, 1),
        unique_pages=sum(len(u) for u in uniq_pages),
        page_ratio=(sum(len(u) for u in uniq_pages) * W
                    / max(ne_total, 1)),
        padded_fill=ne_total / max(P * Rp, 1),
        lane_inflation=P * Rp * W / max(ne_total, 1),
        mode="pagemajor", g_rows=g_rows_real,
        g_fill=ne_total / max(g_rows_real, 1),
        padded_g_fill=ne_total / max(P * Rg, 1))
    return PagedPlan(
        page_ids=page_ids, slot_lane=gsl, rel_dst=rel_dst, weight=wgt,
        row_tile=row_tile, tile_pos=tile_pos, classes=classes,
        n_tiles=n_dst_tiles, n_slots=n_slots, R=Rtot, Rp=Rp,
        n_pages=n_pages, stats=stats, mode="pagemajor",
        vrow_src=vrow_src, Rg=Rg)


def plan_pagemajor(sg) -> PagedPlan:
    """Dense-engine PAGE-MAJOR plan: gather rows bind to pages of the
    full flat state table (merging across the part's own destination
    tiles buys near-full rows), virtual rows carry the per-tile
    reduce.  No routing — a dense part's edges all land in the part.
    Same build requirements as ``plan_paged_gather``."""
    if sg.local_parts is not None:
        raise ValueError("paged gather does not support multi-host "
                         "local-parts builds yet")
    if sg.vpad % W:
        raise ValueError("paged gather needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    n_src_rows = sg.num_parts * sg.vpad // W
    n_dst_tiles = sg.vpad // W
    parts = []
    for r in range(sg.num_parts):
        nep = int(sg.ne_part[r])
        wp = (np.asarray(sg.edge_weight[r, :nep]) if sg.weighted
              else None)
        parts.append(_part_rows_pm(sg.src_slot[r, :nep],
                                   sg.dst_local[r, :nep],
                                   n_dst_tiles, n_src_rows, wp))
    return _assemble_pm(parts, n_dst_tiles, n_src_rows, int(sg.ne),
                        sg.weighted)


def plan_owner_pagemajor(sg) -> PagedPlan:
    """Owner-exchange PAGE-MAJOR plan: each SOURCE part's gather rows
    bind to (destination part, page-of-own-shard) — full rows built
    from the shard, grouped by destination part and padded to a
    common ``Mg`` rows per (src, dst) pair so completed rows ROUTE
    whole through one ``all_to_all`` (the owner machinery's
    collective, ops/owner.owner_exchange's min/max route) — and each
    DESTINATION part reduces its received ``[P_src * Mg]`` row buffer
    through virtual rows over its own local tiles.  Sender-side
    weights: messages are complete before the hop, the receiver only
    reduces."""
    from lux_tpu.ops.pairs import quantize_depths

    if sg.local_parts is not None:
        raise ValueError("paged gather does not support multi-host "
                         "local-parts builds yet")
    if sg.vpad % W:
        raise ValueError("paged gather needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    P, vpad = sg.num_parts, sg.vpad
    n_tiles = vpad // W
    n_src_rows = vpad // W
    if n_src_rows > PAGE_SLOT_MAX:
        raise ValueError(
            f"paged gather needs a state shard of <= {PAGE_SLOT_MAX} "
            f"128-wide pages (25-bit page_slot), got {n_src_rows}")
    built = []
    for srcl, gt, rel, w in _owner_part_edges(sg):
        d = gt // n_tiles
        dstl = (gt % n_tiles) * W + rel
        built.append(_part_rows_pm(srcl, dstl, n_tiles, n_src_rows,
                                   weights=w, group=d))
    # routing quantum: Mg rows per (src, dst) pair — all_to_all needs
    # equal splits, so every pair pads to the max
    Mg = 8
    for pr in built:
        if len(pr[1]):
            Mg = max(Mg, int(np.bincount(pr[1], minlength=P).max()))
    Mg = -(-Mg // 8) * 8

    prof = np.zeros(n_tiles, np.int64)
    by_tile_d = np.zeros((P, n_tiles), np.int64)   # dst part x tile
    for s, pr in enumerate(built):
        (_gp, g_group, _gl, _wg, vrow_gr, vrow_tile, _rel, _wv,
         _bt) = pr
        vg = g_group[vrow_gr]                      # dst part per vrow
        np.add.at(by_tile_d, (vg, vrow_tile), 1)
    for d in range(P):
        prof = np.maximum(prof, np.sort(by_tile_d[d])[::-1])
    depth = quantize_depths(prof)
    row_off = np.concatenate(([0], np.cumsum(depth)))
    Rtot = int(row_off[-1])
    Rp = _pad8_distinct(Rtot, n_src_rows)
    classes = []
    for Lv in np.unique(depth)[::-1]:
        cnt = int((depth == Lv).sum())
        if Lv > 0:
            classes.append((cnt, int(Lv)))
    n_slots = sum(c for c, _L in classes)

    uniq_pages = [np.unique(pr[0]) for pr in built]
    max_pages = max((len(u) for u in uniq_pages), default=1) or 1
    n_pages = _pad8_distinct(max_pages, n_src_rows)

    page_ids = np.zeros((P, n_pages), np.int32)
    gsl = np.zeros((P, P * Mg, W), np.uint32)
    w_send = (np.zeros((P, P * Mg, W), np.float32) if sg.weighted
              else None)
    rel_dst = np.full((P, Rp, W), -1, np.int8)
    row_tile = np.zeros((P, Rp), np.int32)
    vrow_src = np.zeros((P, Rp), np.int32)
    tile_pos = np.full((P, n_tiles), n_slots, np.int32)

    # receiver-side collection: per dst part, virtual rows arrive
    # from every source part (vrow_src indexes the routed buffer
    # [P_src * Mg]); gather per-dst placement cursors from the
    # common profile
    t_order_d, slot_of_tile_d, cursor_d = [], [], []
    for d in range(P):
        t_order = np.argsort(-by_tile_d[d], kind="stable")
        live = depth > 0
        tile_pos[d, t_order[live]] = np.nonzero(live)[0].astype(
            np.int32)
        if (by_tile_d[d][t_order] > depth).any():
            raise AssertionError("common depth profile does not "
                                 "cover a dst part's row counts")
        sot = np.full(n_tiles, -1, np.int64)
        sot[t_order] = np.arange(n_tiles)
        t_order_d.append(t_order)
        slot_of_tile_d.append(sot)
        cursor_d.append(np.zeros(n_tiles, np.int64))

    g_rows_real = v_rows_real = 0
    for s, pr in enumerate(built):
        (g_page, g_group, glane, w_g, vrow_gr, vrow_tile, rel, _wv,
         _bt) = pr
        g_rows_real += len(g_page)
        v_rows_real += len(vrow_gr)
        u = uniq_pages[s]
        page_ids[s, :len(u)] = u.astype(np.int32)
        if not len(g_page):
            continue
        # gather rows grouped by dst part (the sort made them
        # contiguous): row j of the (s -> d) block lands at d*Mg + j
        first_of_d = np.zeros(P, np.int64)
        np.add.at(first_of_d, g_group, 1)
        if (first_of_d > Mg).any():
            raise AssertionError("Mg does not cover a (src, dst) "
                                 "row block")
        first_of_d = np.concatenate(([0], np.cumsum(first_of_d)[:-1]))
        j = np.arange(len(g_page)) - first_of_d[g_group]
        send_pos = g_group * Mg + j
        pslot = np.searchsorted(u, g_page).astype(np.uint32)
        gsl[s, send_pos] = ((pslot[:, None] << np.uint32(7))
                            | glane.astype(np.uint32) & np.uint32(0x7F))
        if w_send is not None and w_g is not None:
            w_send[s, send_pos] = w_g
        # virtual rows land on their dst part's receive plan; the
        # routed buffer index of gather row g is s*Mg + j[g]
        vg = g_group[vrow_gr]
        buf_idx = s * Mg + j[vrow_gr]
        for d in range(P):
            m = vg == d
            if not m.any():
                continue
            vt = vrow_tile[m]
            ordv = np.argsort(vt, kind="stable")
            vt = vt[ordv]
            # per-tile cursors persist across source parts: rows of
            # the same tile from different senders stack in s order
            within = cursor_d[d][vt] + _runpos(vt)
            cursor_d[d][:] += np.bincount(vt, minlength=n_tiles)
            dstp = row_off[slot_of_tile_d[d][vt]] + within
            rel_dst[d, dstp] = rel[m][ordv]
            row_tile[d, dstp] = vt.astype(np.int32)
            vrow_src[d, dstp] = buf_idx[m][ordv].astype(np.int32)

    unique_total = sum(len(u) for u in uniq_pages)
    stats = dict(
        ne=int(sg.ne), rows=v_rows_real,
        fill=int(sg.ne) / max(v_rows_real, 1),
        unique_pages=unique_total,
        page_ratio=unique_total * W / max(int(sg.ne), 1),
        padded_fill=int(sg.ne) / max(P * Rp, 1),
        lane_inflation=P * Rp * W / max(int(sg.ne), 1),
        mode="pagemajor", g_rows=g_rows_real,
        g_fill=int(sg.ne) / max(g_rows_real, 1),
        padded_g_fill=int(sg.ne) / max(P * P * Mg, 1),
        route_rows=P * P * Mg,
        route_inflation=P * P * Mg * W / max(int(sg.ne), 1))
    return PagedPlan(
        page_ids=page_ids, slot_lane=gsl, rel_dst=rel_dst,
        weight=w_send, row_tile=row_tile, tile_pos=tile_pos,
        classes=classes, n_tiles=n_tiles, n_slots=n_slots, R=Rtot,
        Rp=Rp, n_pages=n_pages, stats=stats, mode="pagemajor",
        vrow_src=vrow_src, Rg=P * Mg, route=Mg)


def _runpos(sorted_vals: np.ndarray) -> np.ndarray:
    """Position of each element within its run of equal values
    (``sorted_vals`` sorted ascending)."""
    n = len(sorted_vals)
    if n == 0:
        return np.zeros(0, np.int64)
    new = np.ones(n, bool)
    new[1:] = sorted_vals[1:] != sorted_vals[:-1]
    start = np.nonzero(new)[0]
    return np.arange(n) - start[np.cumsum(new) - 1]


def _part_bin_stats(src_idx, dst_tile, n_dst_tiles: int,
                    n_src_rows: int):
    """The counting half of ``_part_rows``: per-tile row counts, real
    row count and unique-page count from ONE payload-free key sort —
    no lane/rel array fills, so ``gather="auto"`` can price a plan
    without materializing it (the stats formulas must mirror
    ``_assemble``; tests/test_pagegather.py pins the equality)."""
    ne = len(src_idx)
    if ne == 0:
        return np.zeros(n_dst_tiles, np.int64), 0, 0
    page = np.asarray(src_idx, np.int64) // W
    key = np.asarray(dst_tile, np.int64) * np.int64(n_src_rows) + page
    from lux_tpu import native
    native.sort_kv(key, ())
    newg = np.ones(ne, bool)
    newg[1:] = key[1:] != key[:-1]
    bstart = np.nonzero(newg)[0]
    cnt = np.diff(np.concatenate((bstart, [ne])))
    rows_of_bin = -(-cnt // W)
    bin_tile = key[bstart] // np.int64(n_src_rows)
    rows_by_tile = np.zeros(n_dst_tiles, np.int64)
    np.add.at(rows_by_tile, bin_tile, rows_of_bin)
    uniq = len(np.unique(key[bstart] % np.int64(n_src_rows)))
    return rows_by_tile, int(rows_of_bin.sum()), uniq


def plan_paged_stats(sg, exchange: str = "gather",
                     pagemajor: bool = False) -> dict:
    """The plan's recorded stats WITHOUT building the plan arrays:
    the same binning key sort, none of the [P, Rp, 128] assembly —
    what ``gather="auto"`` resolution and the bench A/B's flat line
    read (a flat-resolving billion-edge build must not pay multi-GB
    of discarded plan arrays for a number).

    ``pagemajor=True`` additionally runs the PAGE-MAJOR counting pass
    (one more payload-free sort) and records its gather/virtual row
    stats under ``pm_*`` keys — the inputs of the three-way auto
    arbitration (scalemodel.pagemajor_gather_ns)."""
    from lux_tpu.ops.pairs import quantize_depths

    if sg.local_parts is not None:
        raise ValueError("paged gather does not support multi-host "
                         "local-parts builds yet")
    if sg.vpad % W:
        raise ValueError("paged gather needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    owner = exchange == "owner"
    ntp = sg.vpad // W                        # tiles per part
    if owner:
        n_dst_tiles = sg.num_parts * ntp
        n_src_rows = ntp
        pm_tiles = ntp
        parts = [(srcl, gt, (gt % ntp) * W + rel, gt // ntp)
                 for srcl, gt, rel, _w in _owner_part_edges(sg)]
    else:
        n_dst_tiles = ntp
        n_src_rows = sg.num_parts * sg.vpad // W
        pm_tiles = ntp
        parts = []
        for r in range(sg.num_parts):
            nep = int(sg.ne_part[r])
            dl = sg.dst_local[r, :nep].astype(np.int64)
            parts.append((sg.src_slot[r, :nep], dl // W, dl, None))
    P = len(parts)
    prof = np.zeros(n_dst_tiles, np.int64)
    rows_real = unique_total = 0
    pm_prof = np.zeros(pm_tiles, np.int64)
    pm_vrows = pm_grows = 0
    pm_max_sd = 0
    # owner pm: virtual rows per (dst part, tile) ACCUMULATE across
    # source parts (a dst tile receives rows from every sender)
    pm_bt_d = np.zeros((sg.num_parts, pm_tiles), np.int64)
    for src_idx, dst_tile, dst_local, group in parts:
        by_tile, n_rows, uniq = _part_bin_stats(
            src_idx, dst_tile, n_dst_tiles, n_src_rows)
        prof = np.maximum(prof, np.sort(by_tile)[::-1])
        rows_real += n_rows
        unique_total += uniq
        if pagemajor:
            if owner:
                if len(src_idx):
                    Lm = _pm_layout(src_idx, dst_local, pm_tiles,
                                    n_src_rows, group)
                    vg = Lm["g_group"][Lm["vrow_gr"]]
                    np.add.at(pm_bt_d, (vg, Lm["vrow_tile"]), 1)
                    pm_vrows += len(Lm["vb"])
                    pm_grows += Lm["Rg"]
                    pm_max_sd = max(pm_max_sd, int(np.bincount(
                        Lm["g_group"],
                        minlength=sg.num_parts).max()))
            else:
                bt, nv_rows, ng_rows, _bg = _part_bin_stats_pm(
                    src_idx, dst_local, pm_tiles, n_src_rows)
                pm_prof = np.maximum(pm_prof, np.sort(bt)[::-1])
                pm_vrows += nv_rows
                pm_grows += ng_rows
                # the built plan pads every part's gather rows to the
                # per-part MAX (the _assemble_pm Rg) — the priced
                # g_fill must see the padded count or auto would
                # engage page-major optimistically on part-skewed
                # graphs
                pm_max_sd = max(pm_max_sd, ng_rows)
    if pagemajor and owner:
        for d in range(sg.num_parts):
            pm_prof = np.maximum(pm_prof, np.sort(pm_bt_d[d])[::-1])
    Rtot = int(np.cumsum(quantize_depths(prof))[-1]) if n_dst_tiles \
        else 0
    Rp = _pad8_distinct(Rtot, n_src_rows)
    ne = int(sg.ne)
    stats = dict(
        ne=ne, rows=rows_real, fill=ne / max(rows_real, 1),
        unique_pages=unique_total,
        page_ratio=unique_total * W / max(ne, 1),
        padded_fill=ne / max(P * Rp, 1),
        lane_inflation=P * Rp * W / max(ne, 1))
    if pagemajor:
        pm_Rtot = int(np.cumsum(quantize_depths(pm_prof))[-1]) \
            if pm_tiles else 0
        # receiver plans lead with DST parts (= num_parts) in owner
        # mode; dense pm plans with the same P as the paged plan
        pm_P = sg.num_parts if owner else P
        pm_Rp = _pad8_distinct(pm_Rtot, n_src_rows)
        if owner:
            Mg = max(8, -(-max(pm_max_sd, 1) // 8) * 8)
            pm_Rg_total = sg.num_parts * sg.num_parts * Mg
        else:
            # mirror _assemble_pm exactly: every part pads to the
            # max part's gather-row count (pad8, table-distinct)
            pm_Rg_total = P * _pad8_distinct(max(pm_max_sd, 1),
                                             n_src_rows)
        stats.update(
            pm_rows=pm_vrows,
            pm_vfill=ne / max(pm_vrows, 1),
            pm_padded_vfill=ne / max(pm_P * pm_Rp, 1),
            pm_g_rows=pm_grows,
            pm_g_fill=ne / max(pm_grows, 1),
            pm_g_padded_fill=ne / max(pm_Rg_total, 1))
    return stats


def plan_paged_gather(sg) -> PagedPlan:
    """Dense-engine plan: one part per row, pages of the FULL
    ``[num_parts * vpad]`` flat state table, destination tiles the
    part's own ``vpad // 128``.  Requires vpad % 128 == 0 (build the
    ShardedGraph with vpad_align=128, like pair delivery)."""
    if sg.local_parts is not None:
        raise ValueError("paged gather does not support multi-host "
                         "local-parts builds yet")
    if sg.vpad % W:
        raise ValueError("paged gather needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    n_src_rows = sg.num_parts * sg.vpad // W
    n_dst_tiles = sg.vpad // W
    parts = []
    for r in range(sg.num_parts):
        nep = int(sg.ne_part[r])
        dst = sg.dst_local[r, :nep].astype(np.int64)
        wp = (np.asarray(sg.edge_weight[r, :nep]) if sg.weighted
              else None)
        parts.append(_part_rows(sg.src_slot[r, :nep], dst // W,
                                dst % W, n_dst_tiles, n_src_rows, wp))
    return _assemble(parts, n_dst_tiles, n_src_rows, int(sg.ne),
                     sg.weighted)


def _owner_part_edges(sg):
    """Edges regrouped per SOURCE part (the owner layout's
    src-part-major view, ops/owner.OwnerLayout.build): yields one
    (src_local, global_dst_tile, rel, weight) tuple per src part."""
    P, vpad = sg.num_parts, sg.vpad
    n_tiles_part = vpad // W
    sp_l, srcl_l, gt_l, rel_l, w_l = [], [], [], [], []
    for r in range(P):
        nep = int(sg.ne_part[r])
        slot = sg.src_slot[r, :nep].astype(np.int64)
        s = slot // vpad
        dst = sg.dst_local[r, :nep].astype(np.int64)
        sp_l.append(s)
        srcl_l.append(slot - s * vpad)
        gt_l.append(r * n_tiles_part + dst // W)
        rel_l.append(dst % W)
        if sg.weighted:
            w_l.append(np.asarray(sg.edge_weight[r, :nep]))
    sp = np.concatenate(sp_l) if sp_l else np.zeros(0, np.int64)
    srcl = np.concatenate(srcl_l) if srcl_l else np.zeros(0, np.int64)
    gt = np.concatenate(gt_l) if gt_l else np.zeros(0, np.int64)
    rel = np.concatenate(rel_l) if rel_l else np.zeros(0, np.int64)
    wall = np.concatenate(w_l) if w_l else None
    for s in range(P):
        m = sp == s
        yield (srcl[m], gt[m], rel[m],
               wall[m] if wall is not None else None)


def plan_owner_paged(sg) -> PagedPlan:
    """Owner-exchange plan: one row per SOURCE part, pages within the
    part's OWN ``[vpad]`` state shard, destination tiles GLOBAL
    (G = num_parts * vpad // 128) — the paged form of the owner
    layout's src-part-major re-lay (ops/owner.OwnerLayout.build).
    Each generation-scan step then runs ``paged_partial`` against one
    shard and contributes ``[G, 128]`` tile partials."""
    if sg.local_parts is not None:
        raise ValueError("paged gather does not support multi-host "
                         "local-parts builds yet")
    if sg.vpad % W:
        raise ValueError("paged gather needs vpad % 128 == 0; build "
                         "the ShardedGraph with vpad_align=128")
    G = sg.num_parts * sg.vpad // W
    n_src_rows = sg.vpad // W
    parts = [_part_rows(srcl, gt, rel, G, n_src_rows, w)
             for srcl, gt, rel, w in _owner_part_edges(sg)]
    return _assemble(parts, G, n_src_rows, int(sg.ne), sg.weighted)


def engine_page_plan(sg, gather: str, program,
                     exchange: str) -> PagedPlan | None:
    """The engines' shared plan-or-not resolution: build the paged or
    page-major plan (owner- or dense-shaped by ``exchange``) and
    resolve ``gather`` via ``resolve_gather``.  Returns the plan when
    a page-binned path engages, None when the flat gather stays; an
    explicit ``gather="paged"``/``"pagemajor"`` raises on unsupported
    configurations while ``"auto"`` silently stays flat."""
    dot = getattr(program, "edge_value_from_dot", None) is not None
    explicit = gather in ("paged", "pagemajor")
    why = None
    if getattr(program, "needs_dst", False) and not dot:
        why = ("programs reading destination state (needs_dst "
               "without edge_value_from_dot) keep the flat gather")
    elif sg.local_parts is not None:
        why = "multi-host local-parts builds are not paged yet"
    elif sg.vpad % W:
        why = ("paged gather needs vpad % 128 == 0; build the "
               "ShardedGraph with vpad_align=128")
    elif gather == "pagemajor" and dot:
        why = ("page-major rows split the reduce from the MXU dot "
               "pipeline; K-dim (SDDMM) programs keep gather='paged'")
    if why is not None:
        if explicit:
            raise ValueError(f"gather={gather!r}: {why}")
        return None
    if gather == "auto":
        # resolve from the COUNTING pass only — a flat-resolving
        # build must not pay the full [P, Rp, 128] plan-array
        # assembly (multi-GB at billion-edge scale) for two numbers
        itemsize = getattr(program, "state_bytes", None)
        if itemsize is None:
            ident = getattr(program, "identity", None)
            itemsize = (np.asarray(ident).dtype.itemsize
                        if ident is not None else 4)
            itemsize *= getattr(program, "batch", None) or 1
        table = sg.num_parts * sg.vpad * itemsize
        kdim = 1
        if dot:
            sb = getattr(program, "state_bytes", None)
            kdim = max(1, (sb or 4) // 4)
        stats = plan_paged_stats(sg, exchange=exchange,
                                 pagemajor=not dot)
        gather = resolve_gather("auto", stats, table, kdim,
                                exchange=exchange)
        if gather == "flat":
            return None
    if gather == "pagemajor":
        return (plan_owner_pagemajor(sg) if exchange == "owner"
                else plan_pagemajor(sg))
    return (plan_owner_paged(sg) if exchange == "owner"
            else plan_paged_gather(sg))


def resolve_gather(gather: str, stats: dict, table_bytes: int,
                   kdim: int = 1, exchange: str = "gather") -> str:
    """'auto' resolves by the scalemodel break-even on the plan's
    MEASURED unique-page ratio and row fills (R-MAT vs real-graph
    ratios differ, which is why the plan records them): a page-binned
    mode wins when its modeled delivered ns/edge undercuts what the
    SAME engine would otherwise run — the flat gather rate for this
    table size (scalemodel.page_gather_ns / flat_gather_ns), or, for
    ``exchange="owner"`` engines, the owner scan's per-slot rate
    (OWNER_SLOT_NS x the default chunk inflation, the same baseline
    scalemodel.phase_model prices the flat owner delivery at) —
    comparing an owner plan against the flat-gather cliff rate would
    flip paged on in exactly the 11.9-14.6 ns window where the owner
    scan is cheaper.  When the stats carry the page-major counting
    (``pm_*`` keys, scalar programs only) the arbitration is
    THREE-way: flat vs paged vs page-major, the latter priced with
    its split gather/virtual rates plus the routing hop
    (scalemodel.pagemajor_gather_ns)."""
    if gather in ("paged", "flat", "pagemajor"):
        return gather
    if gather != "auto":
        raise ValueError(f"unknown gather {gather!r} (one of 'paged',"
                         f" 'pagemajor', 'flat', 'auto')")
    from lux_tpu import scalemodel
    paged = scalemodel.page_gather_ns(
        stats["page_ratio"], stats.get("padded_fill", stats["fill"]),
        kdim)
    if exchange == "owner":
        baseline = scalemodel.OWNER_SLOT_NS * 1.2
    elif kdim > 1:
        baseline = scalemodel.residual_edge_ns(kdim)
    else:
        baseline = scalemodel.flat_gather_ns(table_bytes)
    best, best_ns = "flat", baseline
    if paged < best_ns:
        best, best_ns = "paged", paged
    if kdim <= 1 and "pm_padded_vfill" in stats:
        pm = scalemodel.pagemajor_gather_ns(
            stats["page_ratio"], stats["pm_g_padded_fill"],
            stats["pm_padded_vfill"], routed=exchange == "owner")
        if pm < best_ns:
            best = "pagemajor"
    return best


# ---------------------------------------------------------------------
# device side
# ---------------------------------------------------------------------


def _shuffle_kernel(rows_ref, sl_ref, out_ref):
    import jax.numpy as jnp

    # decode inside the kernel: the lane field is the low 7 bits of
    # the packed uint32; Mosaic's dynamic_gather wants int32 indices
    lane = (sl_ref[:] & jnp.uint32(0x7F)).astype(jnp.int32)
    out_ref[:] = jnp.take_along_axis(rows_ref[:], lane, axis=1)


def _lane_shuffle_pallas(rows, slot_lane, block_r: int = 512,
                         interpret: bool = False):
    """[R, 128] lane shuffle as a Pallas kernel — ``take_along_axis``
    axis=1 lowers to ``tpu.dynamic_gather`` dim 1, the measured 0.38
    ns/elem primitive (PERF_NOTES round 2, scripts/profile_shuffle.py).
    R must be a multiple of 8 (PagedPlan pads to this)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, Wd = rows.shape
    bm = block_r if R % block_r == 0 else 8
    return pl.pallas_call(
        _shuffle_kernel,
        grid=(R // bm,),
        in_specs=[
            pl.BlockSpec((bm, Wd), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, Wd), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, Wd), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, Wd), rows.dtype),
        interpret=interpret,
    )(rows, slot_lane)


def lane_resolve(rows, slot_lane, reduce_method: str = "xla"):
    """Resolve each lane's value within its row's page:
    ``out[r, c] = rows[r, lane[r, c]]``.  Pallas kernel for scalar
    rows under a pallas reduce_method; plain XLA ``take_along_axis``
    otherwise (the CPU formulation, and the vector/batched payload
    path — Mosaic's dynamic_gather is 2D)."""
    import jax.numpy as jnp

    if (reduce_method.startswith("pallas") and rows.ndim == 2
            and rows.shape[0] % 8 == 0):
        return _lane_shuffle_pallas(
            rows, slot_lane,
            interpret=reduce_method == "pallas-interpret")
    lane = (slot_lane & jnp.uint32(0x7F)).astype(jnp.int32)
    lane = lane.reshape(lane.shape + (1,) * (rows.ndim - 2))
    return jnp.take_along_axis(rows, lane, axis=1)


def paged_values(pp: PagedPlan, flat_state, page_ids, slot_lane,
                 reduce_method: str = "xla"):
    """The two-level gather itself: unique-page row fetch (THE
    state-table access), buffer row fetch, lane shuffle.  Returns the
    delivered values ``[Rp, 128, ...]``."""
    import jax
    import jax.numpy as jnp

    trail = flat_state.shape[1:]
    s2d = flat_state.reshape((-1, W) + trail)
    pages = jnp.take(s2d, page_ids, axis=0)          # [n_pages, 128, .]
    row_slot = jax.lax.shift_right_logical(
        slot_lane[:, 0], jnp.uint32(7)).astype(jnp.int32)
    rows = jnp.take(pages, row_slot, axis=0)         # [Rp, 128, ...]
    return lane_resolve(rows, slot_lane, reduce_method)


def paged_partial(pp: PagedPlan, flat_state, page_ids, slot_lane, rel,
                  weight, tile_pos, kind: str, msg_fn,
                  reduce_method: str = "xla", vrow_src=None):
    """Full paged delivery + reduce for ONE part ->
    ``[n_tiles * 128, ...]`` partial (identity where no row delivers).
    msg_fn(vals [Rp, 128, ...], weight [Rp, 128] | None) -> messages;
    dead lanes carry garbage masked by rel == -1 downstream.

    ``vrow_src`` (page-major plans): the gather level ran over FULL
    page-bound rows (``slot_lane`` holds the Rg gather rows); each
    virtual reduce row materializes by one row-granular ``take`` of
    the delivered values — the 24 ns/row static class, not a second
    state-table access (the take's operand is the [Rg, 128] value
    buffer, shape-distinct from the table by _pad8_distinct)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.pairs import _class_combine
    from lux_tpu.ops.tiled import chunk_partials

    vals = paged_values(pp, flat_state, page_ids, slot_lane,
                        reduce_method)
    if vrow_src is not None:
        vals = jnp.take(vals, vrow_src, axis=0)      # [Rp, 128, ...]
    msgs = msg_fn(vals, weight)
    if reduce_method.startswith("pallas") and msgs.ndim == 2:
        from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
        partials = chunk_partials_pallas(
            msgs, rel, W, kind,
            block_c=64 if msgs.shape[0] % 64 == 0 else 8,
            interpret=reduce_method == "pallas-interpret")
    else:
        # keep the shuffle/gather out of the W-wide broadcast consumer
        # on the XLA path (the PullEngine._part_msgs barrier rationale)
        msgs = jax.lax.optimization_barrier(msgs)
        partials = chunk_partials(msgs, rel, W, kind)
    red = _class_combine(pp, partials[:pp.R], tile_pos, kind)
    return red.reshape((pp.n_tiles * W,) + red.shape[2:])


def paged_partial_dot(pp: PagedPlan, state, page_ids, slot_lane, rel,
                      weight, row_tile, tile_pos, part_tile0,
                      msg_dot_fn, block_rows: int = 256):
    """Paged delivery for VECTOR-state dot programs (colfilter's
    SDDMM, PullProgram.edge_value_from_dot) — pair_partial_dot's MXU
    pipeline with one extra one-hot shuffle matmul resolving each
    lane's source row within the fetched page block:

      P  = page block [128, K]      (one reshaped-row fetch from the
                                     deduplicated page buffer)
      S  = onehot(lane) @ P         (the lane shuffle as an MXU
                                     contraction — 128-way selection
                                     costs about one shuffle,
                                     PERF_NOTES round 2)
      T  = dst tile block [128, K]
      D  = S @ T^T; dot[c] = D[c, rel[c]]; msgs = msg_dot_fn(S, dot, w)
      partial = onehot(rel)^T @ msgs

    Rows are processed in ``block_rows`` lax.map blocks to bound the
    [B, 128, 128] intermediates.  Returns [n_tiles * 128, K] sums."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.pairs import _class_combine

    if weight is None:
        raise ValueError("paged_partial_dot needs per-lane weights")
    Kdim = state.shape[-1]
    s3 = state.reshape(-1, W * Kdim)
    pages = jnp.take(s3, page_ids, axis=0)       # [n_pages, 128*K]
    Rp = slot_lane.shape[0]
    B = max(1, min(block_rows, Rp))
    nB = -(-Rp // B)
    Rpp = nB * B

    def pad(x):
        return jnp.pad(x, ((0, Rpp - Rp),) + ((0, 0),) * (x.ndim - 1))

    lanes32 = jnp.arange(W, dtype=jnp.int32)
    lanes8 = jnp.arange(W, dtype=rel.dtype)

    def block(args):
        sl, rl, wt, rt = args
        rs = jax.lax.shift_right_logical(
            sl[:, 0], jnp.uint32(7)).astype(jnp.int32)
        Pg = jnp.take(pages, rs, axis=0).reshape(-1, W, Kdim)
        lane = (sl & jnp.uint32(0x7F)).astype(jnp.int32)
        sel = (lane[..., None] == lanes32).astype(state.dtype)
        S = jnp.einsum("rcl,rlk->rck", sel, Pg,
                       preferred_element_type=state.dtype)
        # dst-tile block fetch: row-granular [*, 128K] movement (the
        # 24 ns/row static class) — the SAME fetch pair_partial_dot
        # makes, exempt there because its operand shape differs from
        # the flat table; here the paged table view shares the shape,
        # so the exemption is explicit:
        # audit: allow(gather-budget)
        T = jnp.take(s3, part_tile0 + rt, axis=0).reshape(-1, W, Kdim)
        D = jnp.einsum("rck,rwk->rcw", S, T,
                       preferred_element_type=S.dtype)
        mask = rl[..., None] == lanes8               # [B, 128, 128]
        dot = jnp.sum(jnp.where(mask, D, 0), axis=-1)
        msgs = msg_dot_fn(S, dot, wt)                # [B, 128, K]
        # dead lanes (rel == -1) match no output lane -> contribute 0
        return jnp.einsum("rcw,rck->rwk", mask.astype(S.dtype), msgs)

    partials = jax.lax.map(
        block, (pad(slot_lane).reshape(nB, B, W),
                pad(rel).reshape(nB, B, W),
                pad(weight).reshape(nB, B, W),
                pad(row_tile).reshape(nB, B)))
    partials = partials.reshape(Rpp, W, Kdim)[:pp.R]
    red = _class_combine(pp, partials, tile_pos, "sum")
    return red.reshape(-1, Kdim)


# graph-array dict keys the paged OWNER generation scan consumes
# (leading dim = local src-part rows, like ops/owner.OWNER_SCAN_KEYS)
PAGED_OWNER_KEYS = ("own_pg_ids", "own_pg_sl", "own_pg_rel",
                    "own_pg_w", "own_pg_tp")

# page-major owner routing (round 16): SENDER keys ride the
# generation scan (leading dim = local SRC parts); RECEIVER keys are
# consumed after the all_to_all routing hop (leading dim = local DST
# parts)
PAGEMAJOR_OWNER_SEND_KEYS = ("own_pm_ids", "own_pm_gsl", "own_pm_w")
PAGEMAJOR_OWNER_RECV_KEYS = ("own_pm_vrs", "own_pm_rel", "own_pm_tp")


def plan_graph_arrays(pp: PagedPlan, dev, owner: bool, dot: bool,
                      num_parts: int, vpad: int) -> dict:
    """The plan's per-part graph arrays for an engine's array dict
    (leading dim num_parts; owner plans lead with SOURCE parts —
    page-major owner plans split sender/receiver key sets, both
    leading with num_parts so they shard identically)."""
    if owner and pp.mode == "pagemajor":
        arrays = {"own_pm_ids": dev(pp.page_ids),
                  "own_pm_gsl": dev(pp.slot_lane),
                  "own_pm_vrs": dev(pp.vrow_src),
                  "own_pm_rel": dev(pp.rel_dst),
                  "own_pm_tp": dev(pp.tile_pos)}
        if pp.weight is not None:
            arrays["own_pm_w"] = dev(pp.weight)
        return arrays
    pre = "own_pg_" if owner else "pg_"
    arrays = {pre + "ids": dev(pp.page_ids),
              pre + "sl": dev(pp.slot_lane),
              pre + "rel": dev(pp.rel_dst),
              pre + "tp": dev(pp.tile_pos)}
    if pp.weight is not None:
        arrays[pre + "w"] = dev(pp.weight)
    if not owner and pp.vrow_src is not None:
        arrays["pg_vrs"] = dev(pp.vrow_src)
    if not owner and dot:
        # the paged SDDMM path also fetches each row's dst tile
        arrays["pg_rt"] = dev(pp.row_tile)
        arrays["pg_t0"] = dev(
            (np.arange(num_parts) * (vpad // W)).astype(
                np.int32)[:, None])
    return arrays


def paged_owner_contribs(pp: PagedPlan, state_rows, g: dict, kind: str,
                         msg_fn, msg_dtype, num_parts: int,
                         reduce_method: str, varying_axis=None):
    """lax.scan over the locally-held SOURCE parts, each step running
    the paged delivery against ONE [vpad, ...] state shard (the shard
    reshapes to its own [vpad/128, 128, ...] page table — the scan
    keeps the XLA emitter at the small-table rate exactly like
    ops/owner.owner_contribs) and folding its [G, W] global-tile
    partials into the accumulated per-destination-part contribution
    ``[num_parts, n_tiles*W, ...]``."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.segment import identity_for
    from lux_tpu.ops.tiled import combine_op

    ntw = pp.n_tiles * W // num_parts
    comb = combine_op(kind)
    xs = {k: g[k] for k in PAGED_OWNER_KEYS if k in g}

    def step(acc, x):
        st_s, d = x
        tiles = paged_partial(
            pp, st_s, d["own_pg_ids"], d["own_pg_sl"], d["own_pg_rel"],
            d.get("own_pg_w"), d["own_pg_tp"], kind, msg_fn,
            reduce_method)
        contrib = tiles.reshape((num_parts, ntw) + tiles.shape[1:])
        return comb(acc, contrib), None

    acc0 = jnp.full((num_parts, ntw) + state_rows.shape[2:],
                    identity_for(kind, msg_dtype), msg_dtype)
    if varying_axis is not None:
        acc0 = jax.lax.pcast(acc0, (varying_axis,), to="varying")
    acc, _ = jax.lax.scan(step, acc0, (state_rows, xs))
    return acc


def pagemajor_owner_deliver(pp: PagedPlan, state_rows, g: dict,
                            kind: str, msg_fn, msg_dtype,
                            num_parts: int, reduce_method: str,
                            axis=None, varying_axis=None):
    """The PAGE-MAJOR owner delivery, routing included: a lax.scan
    over the locally-held SOURCE parts runs the full-fill gather-row
    pipeline against each shard's own page table and emits COMPLETE
    message rows grouped by destination part (weights applied
    sender-side); one ``all_to_all`` over the mesh axis routes each
    destination part its ``[P_src, Mg]`` row block (the owner
    exchange's routing collective, ops/owner.owner_exchange — here
    carrying un-reduced full rows instead of reduced partials, the
    priced trade: scalemodel.pagemajor_route_ns); each local
    DESTINATION part then reduces its received buffer through its
    virtual-row plan.  Returns ``[local_parts, n_tiles * 128, ...]``
    — already routed, no further exchange."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.pairs import _class_combine
    from lux_tpu.ops.tiled import chunk_partials

    Mg = pp.route
    xs = {k: g[k] for k in PAGEMAJOR_OWNER_SEND_KEYS if k in g}
    carry0 = jnp.zeros((), jnp.int32)
    if varying_axis is not None:
        carry0 = jax.lax.pcast(carry0, (varying_axis,), to="varying")

    def step(c, x):
        st_s, d = x
        vals = paged_values(pp, st_s, d["own_pm_ids"],
                            d["own_pm_gsl"], reduce_method)
        msgs = msg_fn(vals, d.get("own_pm_w")).astype(msg_dtype)
        return c, msgs

    _, msgs = jax.lax.scan(step, carry0, (state_rows, xs))
    # msgs [L_src, P_dst * Mg, 128, ...] -> route whole rows
    L = msgs.shape[0]
    m = msgs.reshape((L, num_parts, Mg) + msgs.shape[2:])
    if axis is None:
        recv = jnp.swapaxes(m, 0, 1)       # [P_dst, P_src, Mg, ...]
    else:
        recv = jax.lax.all_to_all(m, axis, split_axis=1,
                                  concat_axis=0, tiled=True)
        recv = jnp.swapaxes(recv, 0, 1)    # [L_dst, P_src, Mg, ...]

    def reduce_part(rows_sd, d):
        rb = rows_sd.reshape((-1,) + rows_sd.shape[2:])  # [P*Mg, 128]
        vals = jnp.take(rb, d["own_pm_vrs"], axis=0)     # [Rp, 128]
        if reduce_method.startswith("pallas") and vals.ndim == 2:
            from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
            partials = chunk_partials_pallas(
                vals, d["own_pm_rel"], W, kind,
                block_c=64 if vals.shape[0] % 64 == 0 else 8,
                interpret=reduce_method == "pallas-interpret")
        else:
            vals = jax.lax.optimization_barrier(vals)
            partials = chunk_partials(vals, d["own_pm_rel"], W, kind)
        red = _class_combine(pp, partials[:pp.R], d["own_pm_tp"],
                             kind)
        return red.reshape((pp.n_tiles * W,) + red.shape[2:])

    rkeys = {k: g[k] for k in PAGEMAJOR_OWNER_RECV_KEYS if k in g}
    return jax.vmap(reduce_part)(recv, rkeys)


# ---------------------------------------------------------------------
# NumPy oracles
# ---------------------------------------------------------------------


def decode_plan(pp: PagedPlan, p: int):
    """Decode part ``p``'s live lanes back to (src index, dst index)
    pairs — the plan-resolution oracle's view: src = page_ids[slot] *
    128 + lane, dst = row_tile * 128 + rel.  Page-major plans decode
    through the virtual row's gather row (``vrow_src``); the OWNER
    page-major plan's vrow_src indexes the routed buffer and is
    decoded by ``decode_pagemajor_owner`` instead."""
    if pp.mode == "pagemajor" and pp.route:
        raise ValueError("owner page-major plans decode via "
                         "decode_pagemajor_owner (vrow_src indexes "
                         "the routed buffer, not this part's rows)")
    sl = pp.slot_lane[p]
    rel = pp.rel_dst[p]
    live = rel != -1
    rows, cols = np.nonzero(live)
    gr = (pp.vrow_src[p][rows].astype(np.int64)
          if pp.vrow_src is not None else rows)
    slot = (sl[gr, 0] >> np.uint32(7)).astype(np.int64)
    lane = (sl[gr, cols] & np.uint32(0x7F)).astype(np.int64)
    src = pp.page_ids[p][slot].astype(np.int64) * W + lane
    dst = pp.row_tile[p][rows].astype(np.int64) * W \
        + rel[rows, cols].astype(np.int64)
    return src, dst


def decode_pagemajor_owner(pp: PagedPlan, d: int):
    """Decode DESTINATION part ``d``'s live lanes of an owner
    page-major plan back to (src part, src local index, local dst
    index) — vrow_src indexes the routed ``[P_src * Mg]`` buffer, so
    the sender and its gather row recover as divmod(vrow_src, Mg)."""
    if not (pp.mode == "pagemajor" and pp.route):
        raise ValueError("not an owner page-major plan")
    Mg = pp.route
    rel = pp.rel_dst[d]
    live = rel != -1
    rows, cols = np.nonzero(live)
    buf = pp.vrow_src[d][rows].astype(np.int64)
    s = buf // Mg                       # source part
    j = buf % Mg                        # row within the (s -> d) block
    send_row = d * Mg + j               # its slot in s's send layout
    sl = pp.slot_lane[s, send_row]      # [n, 128]
    slot = (sl[:, 0] >> np.uint32(7)).astype(np.int64)
    lane = (sl[np.arange(len(rows)), cols]
            & np.uint32(0x7F)).astype(np.int64)
    src_local = pp.page_ids[s, slot].astype(np.int64) * W + lane
    dst_local = pp.row_tile[d][rows].astype(np.int64) * W \
        + rel[rows, cols].astype(np.int64)
    return s, src_local, dst_local


def paged_reduce_numpy(pp: PagedPlan, p: int, state_flat: np.ndarray,
                       kind: str = "sum", msg=None) -> np.ndarray:
    """Oracle for one part of a paged plan -> [n_tiles * 128] partial
    (identity where no row delivers).  msg(vals [Rp, 128], weight)
    maps delivered values to messages; default passes them through.
    Padding (rel == -1, dead rows) contributes the identity."""
    if pp.mode == "pagemajor" and pp.route:
        # owner page-major vrow_src indexes the ROUTED [P_src * Mg]
        # buffer, not this part's own send rows — the same guard as
        # decode_plan, or the oracle would silently reduce the wrong
        # rows
        raise ValueError("owner page-major plans have no single-part "
                         "reduce oracle (vrow_src indexes the routed "
                         "buffer); compare whole engines instead")
    s2d = np.asarray(state_flat, np.float64).reshape(-1, W)
    sl = pp.slot_lane[p]
    slot = (sl[:, 0] >> np.uint32(7)).astype(np.int64)
    lane = (sl & np.uint32(0x7F)).astype(np.int64)
    pages = s2d[pp.page_ids[p].astype(np.int64)]
    vals = np.take_along_axis(pages[slot], lane, axis=1)  # [Rg, 128]
    if pp.vrow_src is not None:
        # page-major: virtual reduce rows read their gather row's
        # delivered values (the device's row-granular take)
        vals = vals[pp.vrow_src[p].astype(np.int64)]      # [Rp, 128]
    wp = pp.weight[p] if pp.weight is not None else None
    if msg is not None:
        vals = msg(vals, wp)
    ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[kind]
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[kind]
    out = np.full(pp.n_tiles * W, ident)
    rel = pp.rel_dst[p]
    for r in range(pp.Rp):
        t = int(pp.row_tile[p, r])
        for c in range(W):
            w = int(rel[r, c])
            if 0 <= w < W:
                out[t * W + w] = op(out[t * W + w], vals[r, c])
    return out


def paged_dot_numpy(pp: PagedPlan, p: int, state: np.ndarray,
                    part_tile0: int, msg_dot_fn) -> np.ndarray:
    """float64 oracle for one part of the paged SDDMM delivery
    (paged_partial_dot).  With integer-valued states/weights whose
    products stay under 2^24 this equals the f32 device result
    EXACTLY (the pair-dot oracle's order-independent-exactness trick,
    ops/pairs.stacked_pair_dot_numpy)."""
    s2 = np.asarray(state, np.float64)
    Kdim = s2.shape[-1]
    out = np.zeros((pp.n_tiles * W, Kdim))
    sl = pp.slot_lane[p]
    rel = pp.rel_dst[p]
    for r in range(pp.Rp):
        t = int(pp.row_tile[p, r])
        slot = int(sl[r, 0] >> np.uint32(7))
        page = int(pp.page_ids[p][slot])
        Pg = s2[page * W:(page + 1) * W]                    # [128, K]
        T = s2[(part_tile0 + t) * W:(part_tile0 + t + 1) * W]
        for c in range(W):
            w = int(rel[r, c])
            if not 0 <= w < W:
                continue
            lane = int(sl[r, c] & np.uint32(0x7F))
            S = Pg[lane]
            dot = S @ T[w]
            m = msg_dot_fn(S, dot, np.float64(pp.weight[p, r, c]))
            out[t * W + w] += np.asarray(m).reshape(Kdim)
    return out
