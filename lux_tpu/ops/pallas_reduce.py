"""Pallas TPU kernel for the per-chunk segment partial reduction.

This is the hot loop of every vertex program — the TPU replacement for
the reference's CUB BlockScan + atomic scatter CTA pattern
(reference pagerank_gpu.cu:49-102, sssp_gpu.cu:148-244; SURVEY.md
§3.3).  It consumes the tiled chunk layout of ops/tiled.py: edge
messages ``vals [C, E]`` with relative destinations ``rel_dst [C, E]``
in ``[0, W)`` (negative = padding lane) and produces per-chunk partials
``[C, W]``, which ops/tiled.combine_chunks folds into vertex tiles.

Why a kernel instead of the XLA broadcast-compare reduction
(ops/tiled.chunk_partials):

- The ``[C, E, W]`` one-hot intermediate stays in VMEM one grid block
  at a time instead of spilling W× the edge data to HBM.
- ``pallas_call`` is an opaque custom call, so XLA cannot fuse the
  (serial, expensive) source-state gather that produces ``vals`` into
  the W-wide broadcast — re-executing the gather per output lane —
  which it measurably does to the pure-XLA formulation on TPU v5e.

The kernel is shape-generic over the reduction kind (sum/min/max) and
runs in interpret mode off-TPU so the same code path is testable on
CPU (tests/test_pallas_reduce.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lux_tpu.ops.segment import identity_for


def _partial_kernel(vals_ref, rel_ref, out_ref, *, W: int, kind: str):
    vals = vals_ref[:]                                   # [B, E]
    rel = rel_ref[:]                                     # [B, E]
    B, E = vals.shape
    ident = identity_for(kind, vals.dtype)
    # compare in int32: rel rides HBM as int8 (valid lanes 0..W-1,
    # pad -1 — matches nothing); Mosaic's iota is 32-bit and its
    # minor-dim broadcast insertion only supports 32-bit types, so
    # widen BEFORE the reshape
    rel32 = rel.astype(jnp.int32)                        # [B, E]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (B, E, W), 2)
    match = rel32[:, :, None] == lanes
    masked = jnp.where(match, vals[:, :, None], ident)   # [B, E, W]
    if kind == "sum":
        out_ref[:] = jnp.sum(masked, axis=1)
    elif kind == "min":
        out_ref[:] = jnp.min(masked, axis=1)
    elif kind == "max":
        out_ref[:] = jnp.max(masked, axis=1)
    else:
        raise ValueError(f"unknown reduce kind {kind!r}")


@functools.partial(jax.jit, static_argnames=("W", "kind", "block_c",
                                             "interpret"))
def chunk_partials_pallas(vals, rel_dst, W: int, kind: str,
                          block_c: int = 8, interpret: bool = False):
    """Per-chunk partial reduction [C, E] -> [C, W] on the TPU.

    C must be a multiple of block_c (TiledLayout pads to this).
    Scalar payloads only — vector payloads (colfilter) use the XLA
    path, whose [C, E, W, K] broadcast XLA handles acceptably once the
    gather is materialized.
    """
    C, E = vals.shape
    if C % block_c:
        raise ValueError(f"C={C} not a multiple of block_c={block_c}")
    kern = functools.partial(_partial_kernel, W=W, kind=kind)
    return pl.pallas_call(
        kern,
        grid=(C // block_c,),
        in_specs=[
            pl.BlockSpec((block_c, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_c, E), lambda b: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_c, W), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((C, W), vals.dtype),
        interpret=interpret,
    )(vals, rel_dst)
