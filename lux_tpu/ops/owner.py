"""Owner-side (source-part-major) edge layout for the pull exchange.

The pull engine's default exchange makes the FULL vertex state visible
to every part and gathers per edge from the flattened ``[P*vpad]``
table — the analogue of the reference's whole-region READ_ONLY
requirement (reference pull_model.inl:454-461).  Past ~64-128 MB of
table the XLA gather emitter steps from ~8.8 to ~14.6 ns/elem
(scripts/profile_bigtable.py; a step, not locality decay — sorted
indices are WORSE), which capped every round-2 big-graph number at
~27 ns/edge.

This module flips the exchange to OWNER-SIDE message generation — the
structural cousin of the reference's per-source-part push processing
(reference sssp_gpu.cu:422-459, one CUDA stream per source part):

- Edges are re-laid SRC-part-major: each source part's out-edges are
  sorted by global destination tile (dst part x 128-vertex tile) and
  chunked exactly like ops/tiled.py, but with ``src_local`` indices
  into the part's OWN ``[vpad]`` state shard.
- Each source part gathers only from its own shard (< 64 MB/part at
  any scale with enough parts) and reduces its messages into
  per-destination-tile partials ``[G, W]`` — its contribution to
  EVERY destination part.
- Contributions combine across source parts: on one chip a
  ``lax.scan`` accumulates them (measured 7.8-9.1 ns/elem vs 14.7 for
  both the flat AND the vmapped-batched gather — the scan is what
  makes the emitter see the small table, scripts/profile_owner.py);
  on a mesh they ride a ``psum_scatter`` (sum) or ``all_to_all`` +
  local combine (min/max) over ICI, replacing the per-iteration
  all_gather entirely.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from lux_tpu.ops.tiled import STREAM_MSG_BYTES


def _ceil_div(a, b):
    return -(-a // b)


@dataclasses.dataclass
class OwnerLayout:
    """Host-side src-part-major chunk plan (stacked over src parts).

    Attribute names n_chunks/E/W/needs_scan match TiledLayout so the
    shared device helpers (streamed_chunk_partials, combine_chunks)
    accept either.

    Array leading dim R = MATERIALIZED src-part rows: all num_parts on
    a full build, this process's local parts on a multi-host build
    (row i is sg.part_ids()[i], not global part i)."""

    W: int                      # vertices per destination tile
    E: int                      # edges per chunk
    n_tiles: int                # dst tiles per PART = ceil(vpad / W)
    G: int                      # global dst tiles = num_parts * n_tiles
    n_chunks: int               # padded per-src-part chunk count C
    needs_scan: bool
    src_local: np.ndarray | None  # int32 [R, C, E] into own shard;
    #                               pad->0.  None in packed mode
    rel_dst: np.ndarray | None  # int8 [R, C, E] in [0, W); -1 = pad.
    #                             None in packed mode
    weight: np.ndarray | None   # float32 [R, C, E]
    chunk_start: np.ndarray     # bool [R, C] True at each tile's 1st chunk
    last_chunk: np.ndarray      # int32 [R, G]; -1 for edge-less tiles
    stats: dict
    # PACKED slot encoding (billion-edge fit, round 5): ONE uint32
    # carries src_local << 7 | rel (W=128 => rel is exactly 7 bits;
    # usable whenever vpad <= 2^25), and pad lanes are recovered from
    # a per-chunk live-lane count instead of rel == -1 — chunks fill
    # contiguously, so a count replaces the whole int8 rel array
    # (2.66 GB at RMAT27; the difference between fitting one chip and
    # OOMing it by 1.3 GB, PERF_NOTES round 5)
    src_rel: np.ndarray | None = None   # uint32 [R, C, E]; pad->0
    n_valid: np.ndarray | None = None   # uint16 [R, C] live lanes

    @property
    def packed(self) -> bool:
        return self.src_rel is not None

    # vpad bound for the 25-bit src_local field of the packed encoding
    PACK_VPAD_MAX = 1 << 25

    @classmethod
    def build(cls, sg, E: int = 256,
              packed: bool | None = None) -> "OwnerLayout":
        """Re-lay a ShardedGraph's edges src-part-major (host, once).

        Chunks bind to one global dst tile each, so per-(src-part,
        dst-tile) edge counts round up to E — smaller E wastes fewer
        padded gather slots when parts spread a tile's in-edges
        thinly (the inflation is reported in ``stats``).

        Multi-host local-parts builds (sg.local_parts set): the
        materialized rows are keyed by DESTINATION part, but the owner
        layout needs edges keyed by SOURCE part — a planning-time
        edge exchange streams every dst part's row across the process
        group (``_local_src_edges``) and each process keeps only the
        edges its own source parts emit; chunk geometry (C,
        needs_scan) is then agreed with a host allreduce, exactly how
        ``plan_sharded_pairs`` agrees on the depth profile.  The
        result's leading dim is the LOCAL row count (the analogue of
        the reference's per-node region instances,
        reference push_model.inl:8-51)."""
        from lux_tpu.ops.tiled import warn_sub128_tile
        warn_sub128_tile(E)
        P, vpad, W = sg.num_parts, sg.vpad, 128
        if packed is None:
            # auto: pack whenever the 25-bit src_local field fits AND
            # the uint16 live-lane count can hold a full chunk
            packed = (vpad <= cls.PACK_VPAD_MAX
                      and E <= np.iinfo(np.uint16).max)
        elif packed and vpad > cls.PACK_VPAD_MAX:
            raise ValueError(
                f"packed owner layout needs vpad <= {cls.PACK_VPAD_MAX}"
                f" (25-bit src_local), got vpad={vpad}")
        elif packed and E > np.iinfo(np.uint16).max:
            # n_valid is uint16 [R, C]; a bigger chunk would silently
            # wrap the live-lane count and corrupt the pad recovery
            # (round-5 ADVICE #2 — the analogue of the vpad check)
            raise ValueError(
                f"packed owner layout needs E <= "
                f"{np.iinfo(np.uint16).max} (uint16 live-lane "
                f"counts), got E={E}; pass packed=False")
        n_tiles = max(1, _ceil_div(vpad, W))
        G = P * n_tiles
        local = sg.local_parts is not None
        own_rows = np.asarray(sg.part_ids(), np.int64)
        R = len(own_rows)

        if local:
            key, srcl, rel, wgt = _local_src_edges(sg, n_tiles, G)
        else:
            # per-edge (src part, src local, global dst tile, rel)
            # rows, then ONE stable sort by (src part, dst tile)
            key_l, srcl_l, rel_l, w_l = [], [], [], []
            for r in range(P):
                nep = int(sg.ne_part[r])
                slot = sg.src_slot[r, :nep].astype(np.int64)
                s = slot // vpad
                srcl_l.append((slot - s * vpad).astype(np.int32))
                dst = sg.dst_local[r, :nep].astype(np.int64)
                gt = r * n_tiles + (dst // W)
                key_l.append(s * G + gt)
                rel_l.append((dst % W).astype(np.int8))
                if sg.weighted:
                    w_l.append(sg.edge_weight[r, :nep])
            key = (np.concatenate(key_l) if key_l
                   else np.empty(0, np.int64))
            del key_l
            srcl = (np.concatenate(srcl_l) if srcl_l
                    else np.empty(0, np.int32))
            del srcl_l
            rel = (np.concatenate(rel_l) if rel_l
                   else np.empty(0, np.int8))
            del rel_l
            wgt = np.concatenate(w_l) if w_l else None
            del w_l
        from lux_tpu import native
        # fused radix sort: key + every edge payload move together —
        # no argsort permutation array and no post-sort gathers
        # (native.sort_kv; parallel on pod hosts, PERF_NOTES round 4)
        native.sort_kv(key, (srcl, rel) + (() if wgt is None
                                           else (wgt,)))
        s_of = key // G

        # chunk counts per OWNED src part (sizing pass); geometry is
        # program shape, so multi-host builds allreduce it global
        per_part = []
        for p in own_rows:
            lo, hi = (int(np.searchsorted(s_of, p)),
                      int(np.searchsorted(s_of, p + 1)))
            # key[lo:hi] is already sorted (the global argsort):
            # group boundaries by a diff pass — np.unique would
            # RE-SORT the slice (measured a large slice of the
            # big-graph build time, round 4)
            ks = key[lo:hi] - p * np.int64(G)
            if ks.size:
                newg = np.ones(len(ks), bool)
                newg[1:] = ks[1:] != ks[:-1]
                b = np.nonzero(newg)[0]
                uniq_g = ks[b]
                counts = np.diff(np.concatenate((b, [len(ks)])))
            else:
                uniq_g = np.empty(0, np.int64)
                counts = np.empty(0, np.int64)
            per_part.append((lo, uniq_g.astype(np.int64), counts))
        C = max(1, max((int(_ceil_div(c, E).sum())
                        for _, _, c in per_part), default=1))
        needs_scan = any((_ceil_div(c, E) > 1).any()
                         for _, _, c in per_part if c.size)
        if local:
            from lux_tpu.parallel.multihost import allreduce_host
            C = int(allreduce_host(np.int64(C), "max"))
            needs_scan = bool(allreduce_host(np.int64(needs_scan),
                                             "max"))
        C = _ceil_div(C, 8) * 8          # Pallas block granularity

        if packed:
            src_local = rel_dst = None
            src_rel = np.zeros((R, C, E), dtype=np.uint32)
            n_valid = np.zeros((R, C), dtype=np.uint16)
        else:
            src_rel = n_valid = None
            src_local = np.zeros((R, C, E), dtype=np.int32)
            rel_dst = np.full((R, C, E), -1, dtype=np.int8)
        weight = (np.zeros((R, C, E), dtype=np.float32)
                  if sg.weighted else None)
        chunk_start = np.ones((R, C), dtype=bool)   # pad chunks isolated
        last_chunk = np.full((R, G), -1, dtype=np.int32)

        lanes = np.arange(E, dtype=np.int64)
        used = 0
        for s, (lo, uniq_g, counts) in enumerate(per_part):
            if not counts.size:
                continue
            n_ch = _ceil_div(counts, E)
            nc = int(n_ch.sum())
            used += nc
            # chunk -> position in this part's sorted edge slice
            ci = np.repeat(np.arange(len(uniq_g)), n_ch)  # chunk->tile idx
            tile_lo = lo + np.concatenate(([0], np.cumsum(counts)[:-1]))
            tile_hi = tile_lo + counts
            tile_first = np.concatenate(([0], np.cumsum(n_ch)[:-1]))
            cj = np.arange(nc, dtype=np.int64) - tile_first[ci]
            start = tile_lo[ci] + cj * E
            idx = start[:, None] + lanes[None, :]          # [nc, E]
            valid = idx < tile_hi[ci][:, None]
            idx = np.where(valid, idx, lo)
            if packed:
                sr = (srcl[idx].astype(np.uint32) << np.uint32(7)
                      | rel[idx].astype(np.uint32))
                src_rel[s, :nc] = np.where(valid, sr, 0)
                n_valid[s, :nc] = np.minimum(
                    tile_hi[ci] - start, E).astype(np.uint16)
            else:
                src_local[s, :nc] = np.where(valid, srcl[idx], 0)
                rel_dst[s, :nc] = np.where(valid, rel[idx], -1)
            if weight is not None:
                weight[s, :nc] = np.where(valid, wgt[idx], 0)
            chunk_start[s, :nc] = cj == 0
            last_chunk[s, uniq_g] = (tile_first + n_ch - 1).astype(
                np.int32)

        # on local-parts builds the slot/used counts cover only this
        # process's rows; ne is global, so the ratios are per-process
        # estimates there (each process owns P/nproc of both)
        stats = dict(slots=R * C * E, used_chunks=used,
                     inflation=round(P * C * E / max(1, sg.ne), 3),
                     chunk_inflation=round(
                         (P // max(1, R)) * used * E / max(1, sg.ne),
                         3),
                     packed=packed)
        return cls(W=W, E=E, n_tiles=n_tiles, G=G, n_chunks=C,
                   needs_scan=needs_scan, src_local=src_local,
                   rel_dst=rel_dst, weight=weight,
                   chunk_start=chunk_start, last_chunk=last_chunk,
                   stats=stats, src_rel=src_rel, n_valid=n_valid)

    def streams(self) -> bool:
        """Stream gather+partials in lax.map blocks once one src
        part's [C, E] f32 message temporary passes the shared budget
        (same rule the dst-major engines use)."""
        return self.n_chunks * self.E * 4 > STREAM_MSG_BYTES

    def extract_plan(self):
        """Per-src-part extraction indices for the FUSED streamed
        combine (ops/tiled.streamed_chunk_combined) — avoids the two
        [C, W] temporaries that push billion-edge owner programs past
        HBM (PERF_NOTES round 4).  Returns (extr_pos [R, nB, L],
        extr_tile [R, nB, L]) numpy.

        The extraction width L is program shape: on multi-process
        runs it is allreduced across the group, exactly like C."""
        import jax

        from lux_tpu.ops.tiled import (build_extract_plan,
                                       extract_plan_width)
        L = extract_plan_width(self.last_chunk, self.n_chunks)
        if jax.process_count() > 1:
            from lux_tpu.parallel.multihost import allreduce_host
            L = int(allreduce_host(np.int64(L), "max"))
        return build_extract_plan(self.last_chunk, self.n_chunks, L=L)


def _local_src_edges(sg, n_tiles: int, G: int):
    """Planning-time edge exchange for multi-host owner builds: stream
    every destination part's edge row across the process group and
    keep only the edges whose SOURCE part this process owns.

    Returns (key, srcl, rel, wgt) in the same per-edge encoding the
    single-host build produces (key = src_part * G + global dst tile).
    Per-row ``process_allgather`` shapes come from the GLOBAL
    ``ne_part`` metadata, so every process participates with identical
    shapes.  Peak memory is O(nproc x one part's edges); total traffic
    is O(ne x nproc) — a one-shot planning cost, the analogue of the
    reference building its whole-graph CSR on every node
    (reference pull_model.inl:253-320)."""
    import jax

    P, vpad, W = sg.num_parts, sg.vpad, 128
    own = np.asarray(sg.local_parts, np.int64)
    own_mask = np.zeros(P, bool)
    own_mask[own] = True
    local_row = {int(p): i for i, p in enumerate(own)}
    nproc = jax.process_count()
    holders = np.full(P, -1, np.int64)
    if nproc > 1:
        from jax.experimental import multihost_utils
        # part -> holding process: allgather the row lists once.
        # process_allgather needs identical shapes, so every process
        # must hold the SAME NUMBER of parts (process_parts enforces
        # this; int32 — see the x64-truncation note below)
        lp = multihost_utils.process_allgather(
            own.astype(np.int32))                       # [nproc, R]
        for q in range(nproc):
            holders[np.asarray(lp[q], np.int64)] = q
    else:
        holders[own] = 0
    if (holders < 0).any():
        # an uncovered part's zero placeholder would otherwise be
        # mistaken for real (vertex-0 -> tile-0) edges of src part 0
        raise ValueError("local_parts rows do not cover every "
                         "partition across the process group")

    key_l, srcl_l, rel_l, w_l = [], [], [], []
    for r in range(P):
        nep = int(sg.ne_part[r])        # global metadata: same shape
        if nep == 0:                    # on every process
            continue
        rows = 3 if sg.weighted else 2
        if r in local_row:
            i = local_row[r]
            # [rows, nep] int32 — NOT a packed int64: jax collectives
            # truncate int64 to int32 unless jax_enable_x64 is on.
            # Weights ride along bit-cast to int32: one collective
            # per part instead of two
            both = np.empty((rows, nep), np.int32)
            both[0] = sg.src_slot[i, :nep]
            both[1] = sg.dst_local[i, :nep]
            if sg.weighted:
                both[2] = np.asarray(sg.edge_weight[i, :nep],
                                     np.float32).view(np.int32)
        else:
            both = np.zeros((rows, nep), np.int32)
        if nproc > 1:
            from jax.experimental import multihost_utils
            q = int(holders[r])
            both = np.asarray(
                multihost_utils.process_allgather(both)[q])
        wrow = both[2].view(np.float32) if sg.weighted else None
        slot = both[0].astype(np.int64)
        dst = both[1].astype(np.int64)
        s = slot // vpad
        keep = own_mask[s]
        if not keep.any():
            continue
        s = s[keep]
        slot = slot[keep]
        dst = dst[keep]
        key_l.append(s * G + (r * n_tiles + dst // W))
        srcl_l.append((slot - s * vpad).astype(np.int32))
        rel_l.append((dst % W).astype(np.int8))
        if wrow is not None:
            w_l.append(wrow[keep])
    key = np.concatenate(key_l) if key_l else np.empty(0, np.int64)
    srcl = np.concatenate(srcl_l) if srcl_l else np.empty(0, np.int32)
    rel = np.concatenate(rel_l) if rel_l else np.empty(0, np.int8)
    wgt = (np.concatenate(w_l) if w_l
           else (np.empty(0, np.float32) if sg.weighted else None))
    return key, srcl, rel, wgt


# graph-array dict keys holding the owner scan inputs (all leading-
# dim local src rows); own_w only on weighted graphs, own_ep/own_et
# only when the layout streams (the fused-combine extraction plan)
OWNER_SCAN_KEYS = ("own_src", "own_rel", "own_cs", "own_lc", "own_w",
                   "own_ep", "own_et", "own_sr", "own_nv")


def owner_contribs(lay: OwnerLayout, state_rows, g: dict,
                   kind: str, msg_fn, msg_dtype, num_parts: int,
                   reduce_method: str, varying_axis=None,
                   use_mxu: bool = False):
    """lax.scan over the locally-held SOURCE parts: each step gathers
    from ONE [vpad, ...] state shard (the scan is what makes the XLA
    emitter see the small table — a vmapped batched gather still pays
    the big-table rate, scripts/profile_owner.py) and folds its
    [G, W] tile partials into the accumulated contribution
    ``[num_parts, n_tiles*W, ...]`` to every destination part.

    g: graph-array dict; the OWNER_SCAN_KEYS present in it ride the
    scan with the local-row leading dim.  varying_axis: mesh axis name
    when called under shard_map (marks the identity carry
    device-varying)."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.segment import identity_for
    from lux_tpu.ops.tiled import combine_op

    ntw = lay.n_tiles * lay.W
    comb = combine_op(kind)
    xs = {k: g[k] for k in OWNER_SCAN_KEYS if k in g}

    def step(acc, x):
        st_s, d = x
        tiles = owner_part_tiles(
            lay, st_s, d.get("own_sr", d.get("own_src")),
            d.get("own_rel"), d.get("own_w"),
            d["own_cs"], d["own_lc"], kind, msg_fn, reduce_method,
            use_mxu=use_mxu, extr_pos=d.get("own_ep"),
            extr_tile=d.get("own_et"), varying_axis=varying_axis,
            nvalid=d.get("own_nv"))
        contrib = tiles.reshape((num_parts, ntw) + tiles.shape[2:])
        return comb(acc, contrib), None

    acc0 = jnp.full((num_parts, ntw) + state_rows.shape[2:],
                    identity_for(kind, msg_dtype), msg_dtype)
    if varying_axis is not None:
        # the scan folds in device-varying contributions; the constant
        # initial carry must be marked varying too (VMA)
        acc0 = jax.lax.pcast(acc0, (varying_axis,), to="varying")
    acc, _ = jax.lax.scan(step, acc0, (state_rows, xs))
    return acc


def owner_exchange(acc, kind: str, axis=None, ndev: int = 1,
                   minmax_fused: bool = False):
    """Route accumulated contributions [P, ntw, ...] to their
    destination parts.  axis=None (single device): identity — every
    dst row is already local.  On a mesh: reduce_scatter over ICI —
    ``psum_scatter`` for sum, ``all_to_all`` + local combine for
    min/max (the TPU-native replacement for the whole-region
    all_gather, reference pull_model.inl:454-461).

    minmax_fused=True routes min/max through the psum_scatter-style
    RING reduce-scatter (``ring_reduce_scatter``) instead: the combine
    happens en route, so the receive working set per step is ONE
    device's row chunk [P/ndev, ntw] instead of the all_to_all's full
    [P, ntw] landing buffer + ndev-way local reduction (round-5
    pointer #5).  Opt-in until measured on a real mesh; oracle-equal
    to the all_to_all path (tests/test_owner.py)."""
    import jax
    import jax.numpy as jnp

    if axis is None:
        return acc
    if kind == "sum":
        return jax.lax.psum_scatter(acc, axis, scatter_dimension=0,
                                    tiled=True)
    if minmax_fused:
        return ring_reduce_scatter(acc, kind, axis, ndev)
    recv = jax.lax.all_to_all(acc, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    rows = acc.shape[0] // ndev
    red = recv.reshape((ndev, rows) + recv.shape[1:])
    return {"min": jnp.min, "max": jnp.max}[kind](red, axis=0)


def ring_reduce_scatter(acc, kind: str, axis, ndev: int):
    """Ring reduce-scatter for any combine kind (shard_map body).

    acc [P, ...] per device; returns [P/ndev, ...] — device d ends
    with the fully-combined rows of ITS chunk d (the same contract as
    ``psum_scatter(..., scatter_dimension=0, tiled=True)``).  Chunk c
    starts at device c+1 and travels the ring c+1 -> c+2 -> ... -> c,
    each hop folding the visiting device's local contribution, so the
    partial being combined is always one chunk — ndev-1 ppermute hops
    of [P/ndev, ...] each, combine fused per hop."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.tiled import combine_op

    comb = combine_op(kind)
    rows = acc.shape[0] // ndev
    chunks = acc.reshape((ndev, rows) + acc.shape[1:])
    idx = jax.lax.axis_index(axis)
    perm = [(j, (j + 1) % ndev) for j in range(ndev)]
    # device i launches its contribution to chunk i-1
    cur = jnp.take(chunks, (idx - 1) % ndev, axis=0)
    for s in range(ndev - 1):
        cur = jax.lax.ppermute(cur, axis, perm)
        # after hop s, device i holds chunk (i - 2 - s) mod ndev and
        # folds its own contribution; the last fold (s = ndev - 2)
        # lands chunk i fully combined at device i
        cur = comb(cur, jnp.take(chunks, (idx - 2 - s) % ndev, axis=0))
    return cur


def owner_part_tiles(lay: OwnerLayout, state_s, src, rel, weight, cs,
                     lc, kind: str, msg_fn, reduce_method: str,
                     use_mxu: bool = False, extr_pos=None,
                     extr_tile=None, varying_axis=None, nvalid=None):
    """One source part's contribution: gather from its OWN shard
    ``state_s [vpad, ...]``, message, chunk-reduce, and combine into
    per-global-tile results ``[G, W, ...]`` (identity where the part
    contributes nothing).

    extr_pos/extr_tile (this part's rows of OwnerLayout.extract_plan):
    run the FUSED streamed combine, which never materializes the
    [C, W] running values."""
    import jax
    import jax.numpy as jnp

    from lux_tpu.ops.tiled import (chunk_partials, combine_chunks,
                                   streamed_chunk_combined,
                                   streamed_chunk_partials)

    if extr_pos is not None:
        return streamed_chunk_combined(
            state_s, src, rel, weight, lay, kind, msg_fn,
            reduce_method, cs, extr_pos, extr_tile, lc,
            use_mxu=use_mxu,
            varying_axis=varying_axis, nvalid=nvalid)  # [G, W, ...]
    if lay.streams():
        partials = streamed_chunk_partials(
            state_s, src, rel, weight, lay, kind, msg_fn, reduce_method,
            use_mxu=use_mxu, nvalid=nvalid)
    else:
        if nvalid is not None:
            from lux_tpu.ops.tiled import unpack_src_rel
            src, rel = unpack_src_rel(src, nvalid)
        vals = jnp.take(state_s, src, axis=0)
        msgs = msg_fn(vals, weight)
        if reduce_method.startswith("pallas") and msgs.ndim == 2:
            from lux_tpu.ops.pallas_reduce import chunk_partials_pallas
            partials = chunk_partials_pallas(
                msgs, rel, lay.W, kind,
                interpret=reduce_method == "pallas-interpret")
        else:
            # keep the (serial, expensive) gather out of the W-wide
            # broadcast consumer (see PullEngine._part_msgs)
            msgs = jax.lax.optimization_barrier(msgs)
            partials = chunk_partials(msgs, rel, lay.W, kind,
                                      use_mxu=use_mxu)
    return combine_chunks(partials, lay, cs, lc, kind,
                          use_mxu=use_mxu)                 # [G, W, ...]
