"""Segmented reductions over dst-sorted edge arrays.

This is the TPU replacement for the reference's atomicAdd/atomicMin/
atomicMax edge scatters (reference pagerank_gpu.cu:90,
sssp_gpu.cu:55-59, components_gpu.cu:57-59): because ShardedGraph keeps
each partition's edges sorted by local destination, the scatter becomes
a *sorted* segmented reduction, which XLA lowers without atomics.

A Pallas fast path (ops/pallas/) can override this for the hot loop;
this module is the portable XLA implementation and the correctness
oracle for it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_KINDS = {
    "sum": jax.ops.segment_sum,
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}

# Identity elements per reduction, used for padding/masked lanes.
def identity_for(kind: str, dtype) -> jnp.ndarray:
    if kind == "sum":
        return jnp.zeros((), dtype)
    if kind == "min":
        return (jnp.array(jnp.iinfo(dtype).max, dtype)
                if jnp.issubdtype(dtype, jnp.integer)
                else jnp.array(jnp.inf, dtype))
    if kind == "max":
        return (jnp.array(jnp.iinfo(dtype).min, dtype)
                if jnp.issubdtype(dtype, jnp.integer)
                else jnp.array(-jnp.inf, dtype))
    raise ValueError(f"unknown reduction {kind!r}")


def segment_reduce(vals, seg_ids, num_segments: int, kind: str):
    """Reduce ``vals`` ([ne, ...]) into ``num_segments`` rows by sorted
    ``seg_ids``.  Empty segments get the reduction identity."""
    # jax.ops.segment_min/max already fill empty segments with the
    # reduction identity, so no fix-up pass is needed.
    return _KINDS[kind](vals, seg_ids, num_segments=num_segments,
                        indices_are_sorted=True)
