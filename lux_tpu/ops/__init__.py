from lux_tpu.ops.segment import segment_reduce
