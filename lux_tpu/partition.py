"""Edge-balanced contiguous vertex partitioning.

The reference assigns each GPU a contiguous vertex range holding an
approximately equal number of in-edges (reference pull_model.inl:108-131,
push_model.inl:378-423: cut when a running edge count exceeds
``edge_cap = ceil(ne / num_parts)``).  We compute the same family of
partitions with a direct quantile search over the CSC end-offset array:
cut point p is the smallest vertex whose cumulative edge count reaches
``p * ne / num_parts``.  This is O(parts · log nv), balances at least as
well as the reference's greedy sweep, and is a pure function — the
partition is host-side metadata only; on device it becomes sharding
layout (SURVEY.md §2.2 item 1).
"""

from __future__ import annotations

import numpy as np


def edge_balanced_bounds(row_ptrs, num_parts: int) -> np.ndarray:
    """Return cut points ``starts`` with shape [num_parts + 1].

    Part p owns the half-open vertex range [starts[p], starts[p+1]) and
    in-edges col_idx[b : e] with b = row_ptrs[starts[p]-1] if
    starts[p] > 0 else 0 and e = row_ptrs[starts[p+1]-1].
    starts[0] == 0 and starts[-1] == nv.  Every part is non-empty in
    vertices as long as num_parts <= nv.
    """
    row_ptrs = np.asarray(row_ptrs)
    nv = row_ptrs.shape[0]
    if num_parts < 1:
        raise ValueError("num_parts must be >= 1")
    if num_parts > nv:
        raise ValueError(f"num_parts={num_parts} exceeds nv={nv}")
    ne = int(row_ptrs[-1]) if nv else 0
    targets = (np.arange(1, num_parts) * ne) // num_parts
    # Smallest v with row_ptrs[v] >= target == edge count through v
    # reaches the quantile; +1 converts to a cut point (exclusive end).
    cuts = np.searchsorted(row_ptrs, targets, side="left") + 1
    starts = np.empty(num_parts + 1, dtype=np.int64)
    starts[0] = 0
    starts[1:num_parts] = cuts
    starts[num_parts] = nv
    # Degenerate distributions (one vertex owning most edges) can make
    # quantile cuts collide or run past nv; enforce strict monotonicity
    # so every part keeps at least one vertex, as the reference's greedy
    # sweep does.  Feasible because num_parts <= nv.
    for p in range(1, num_parts):
        if starts[p] <= starts[p - 1]:
            starts[p] = starts[p - 1] + 1
    for p in range(num_parts - 1, 0, -1):
        if starts[p] >= starts[p + 1]:
            starts[p] = starts[p + 1] - 1
    assert starts[0] == 0 and starts[num_parts] == nv
    return starts


def weighted_balanced_bounds(cost_ptrs, num_parts: int,
                             align: int = 1) -> np.ndarray:
    """Cut points balancing an arbitrary per-vertex cumulative COST
    (``cost_ptrs[v]`` = total cost through vertex v, END-offset
    semantics like row_ptrs).  ``edge_balanced_bounds`` is the special
    case cost = in-degree.

    align > 1 rounds interior cuts to multiples of ``align`` (e.g. 128
    keeps every part's vertex range tile-aligned so (src-tile,
    dst-tile) pair structure is identical to the global tiling); falls
    back to align=1 when num_parts * align > nv.
    """
    cost_ptrs = np.asarray(cost_ptrs)
    nv = cost_ptrs.shape[0]
    if num_parts < 1:
        raise ValueError("num_parts must be >= 1")
    if num_parts > nv:
        raise ValueError(f"num_parts={num_parts} exceeds nv={nv}")
    if align > 1 and num_parts * align > nv:
        align = 1
    total = float(cost_ptrs[-1]) if nv else 0.0
    targets = np.arange(1, num_parts) * (total / num_parts)
    cuts = np.searchsorted(cost_ptrs, targets, side="left") + 1
    if align > 1:
        cuts = np.round(cuts / align).astype(np.int64) * align
    starts = np.empty(num_parts + 1, dtype=np.int64)
    starts[0] = 0
    starts[1:num_parts] = cuts
    starts[num_parts] = nv
    # Same degenerate-distribution fixups as edge_balanced_bounds,
    # stepping by ``align`` to preserve alignment where feasible (the
    # backward pass near an unaligned nv may break alignment for the
    # last interior cut; alignment is an optimization, not a
    # correctness requirement).
    for p in range(1, num_parts):
        if starts[p] <= starts[p - 1]:
            starts[p] = starts[p - 1] + align
    for p in range(num_parts - 1, 0, -1):
        if starts[p] >= starts[p + 1]:
            starts[p] = starts[p + 1] - (align if starts[p + 1] % align
                                         == 0 else 1)
    starts[1:num_parts] = np.clip(starts[1:num_parts], 1, nv - 1)
    for p in range(1, num_parts):
        if starts[p] <= starts[p - 1]:
            starts[p] = starts[p - 1] + 1
    assert starts[0] == 0 and starts[num_parts] == nv
    assert (np.diff(starts) > 0).all()
    return starts


def part_edge_counts(row_ptrs, starts) -> np.ndarray:
    """Edges owned by each part (in-edges of its vertex range)."""
    row_ptrs = np.asarray(row_ptrs)
    ends = row_ptrs[np.asarray(starts[1:]) - 1].astype(np.int64)
    begins = np.empty_like(ends)
    begins[0] = 0
    begins[1:] = ends[:-1]
    return ends - begins


def frontier_capacity(part_nv: int, sparse_threshold: int = 16,
                      slack: int = 100) -> int:
    """Sparse-frontier queue slot budget for a partition.

    Mirrors the reference's sizing rule: a part's sparse queue holds
    ``part_nv / SPARSE_THRESHOLD + 100`` vertex ids
    (reference push_model.inl:393-397, sssp/app.h:19).
    """
    return part_nv // sparse_threshold + slack
