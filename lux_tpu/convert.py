"""Edge-list <-> .lux conversion and synthetic graph generators.

Python implementations of the reference's offline converter tool
(reference tools/converter.cc:72-130: read `src dst` text pairs, sort by
destination, emit binary CSC + trailing out-degrees).  A native C++ CLI
with the same behavior lives in lux_tpu/native/ for billion-edge inputs;
this module is the in-process path and the test oracle.

Also provides an R-MAT generator (Chakrabarti et al., SDM'04 — the
standard recursive-matrix power-law generator; the reference's RMAT27
benchmark graph is such a graph, README.md:86) so benchmarks run without
downloading datasets.
"""

from __future__ import annotations

import numpy as np

from lux_tpu import format as luxfmt


def edges_to_csc(src, dst, nv: int, weights=None):
    """Sort edges by destination and build CSC end-offset arrays.

    Returns (row_ptrs[u8 nv], col_idx[u4 ne] = sources, sorted_weights,
    out_degrees[u4 nv]).  Same output semantics as the reference
    converter (converter.cc:98-124); the canonical order is (dst, src)
    so the Python and native converters produce byte-identical files.
    """
    src = np.asarray(src, dtype=np.uint32)
    dst = np.asarray(dst, dtype=np.uint32)
    if src.size and (int(src.max()) >= nv or int(dst.max()) >= nv):
        raise ValueError("edge endpoint out of range")
    # one packed-u64 FUSED radix sort instead of lexsort's two stable
    # passes (then instead of argsort + gathers: measured 2.1x at one
    # thread, parallel on pod hosts — PERF_NOTES round 4); identical
    # (dst, src) order.  The key carries src in its low 32 bits, so
    # the sorted col_idx falls out as a truncating cast and weights
    # ride as a sort payload — no post-sort gathers at all.
    from lux_tpu import native
    # compose the key in ONE uint64 buffer (three transient u64 copies
    # would cost ~50 GB extra peak at RMAT27 scale)
    key = dst.astype(np.uint64)
    key <<= np.uint64(32)
    np.bitwise_or(key, src, out=key)
    w_sorted = None
    if weights is not None:
        w_sorted = np.ascontiguousarray(weights)
        if np.shares_memory(w_sorted, weights):   # sort_kv permutes
            w_sorted = w_sorted.copy()            # IN PLACE
    native.sort_kv(key, () if w_sorted is None else (w_sorted,))
    col_idx = key.astype(np.uint32)  # truncation keeps the low half
    del key
    counts = np.bincount(dst, minlength=nv).astype(np.uint64)
    row_ptrs = np.cumsum(counts, dtype=np.uint64)
    out_degrees = np.bincount(src, minlength=nv).astype(np.uint32)
    return row_ptrs, col_idx, w_sorted, out_degrees


def convert_edge_list(text_path: str, lux_path: str, nv: int,
                      weighted: bool = False, weight_dtype=np.int32):
    """Convert a text edge list (`src dst [weight]` per line) to .lux."""
    ncols = 3 if weighted else 2
    data = np.loadtxt(text_path, dtype=np.float64, ndmin=2)
    if data.size == 0:
        data = data.reshape(0, ncols)
    if data.shape[1] != ncols:
        raise ValueError(
            f"{text_path}: expected {ncols} columns "
            f"({'src dst weight' if weighted else 'src dst'}), "
            f"got {data.shape[1]}")
    src = data[:, 0].astype(np.uint32)
    dst = data[:, 1].astype(np.uint32)
    w = data[:, 2].astype(weight_dtype) if weighted else None
    row_ptrs, col_idx, w_sorted, deg = edges_to_csc(src, dst, nv, w)
    luxfmt.write_lux(lux_path, row_ptrs, col_idx, w_sorted, deg)
    return row_ptrs, col_idx, w_sorted, deg


def rmat_edges(scale: int, edge_factor: int = 16, seed: int = 0,
               a: float = 0.57, b: float = 0.19, c: float = 0.19):
    """Generate an R-MAT edge list: nv = 2**scale, ne = nv * edge_factor.

    Vectorized: draws all `scale` quadrant choices for all edges at once.
    Produces a skewed power-law degree distribution comparable to the
    reference's RMAT27 benchmark graph.
    """
    nv = 1 << scale
    ne = nv * edge_factor
    rng = np.random.default_rng(seed)
    src = np.zeros(ne, dtype=np.uint64)
    dst = np.zeros(ne, dtype=np.uint64)
    if not 0.0 < a + b + c <= 1.0:
        raise ValueError("quadrant probabilities must satisfy 0 < a+b+c <= 1")
    # Per bit level: pick quadrant with probs (a, b, c, 1-a-b-c).
    for _ in range(scale):
        r = rng.random(ne)
        src_bit = (r >= a + b).astype(np.uint64)          # quadrants c,d
        dst_bit = ((r >= a) & (r < a + b) | (r >= a + b + c)).astype(np.uint64)
        src = (src << np.uint64(1)) | src_bit
        dst = (dst << np.uint64(1)) | dst_bit
    # Permute vertex ids so the skew is not correlated with id order.
    perm = rng.permutation(nv).astype(np.uint32)
    return perm[src.astype(np.uint32)], perm[dst.astype(np.uint32)], nv


def rmat_graph(scale: int, edge_factor: int = 16, seed: int = 0,
               prefer_native: bool = True):
    """Build an R-MAT Graph, using the native C++ generate+sort+CSC
    path when available (~10x faster host setup at benchmark scales);
    falls back to rmat_edges + edges_to_csc.  The two paths use
    different RNG streams: same distribution, different instances."""
    from lux_tpu.graph import Graph

    if prefer_native:
        from lux_tpu import native
        if native.available():
            row_ptrs, col_idx, degrees = native.rmat_csc(
                scale, edge_factor, seed)
            nv = 1 << scale
            return Graph(nv=nv, ne=int(col_idx.shape[0]),
                         row_ptrs=row_ptrs, col_idx=col_idx,
                         weights=None, out_degrees=degrees)
    src, dst, nv = rmat_edges(scale, edge_factor, seed)
    return Graph.from_edges(src, dst, nv)


def netflix_like_edges(n_users: int = 480_000, n_items: int = 17_700,
                       n_ratings: int = 100_000_000, seed: int = 0,
                       user_skew: float = 0.6, item_skew: float = 0.9):
    """Synthesize a NetFlix-shaped weighted bipartite rating set — the
    reference's fifth benchmark workload (reference README.md:88,
    col_filter/colfilter_gpu.cu:32-104): ~480K users x ~17.7K items,
    ~100M integer ratings 1..5, with power-law skew on BOTH sides
    (the most-rated item draws ~0.2-0.5% of all ratings, like the
    real dataset's top titles).

    Returns (src, dst, weights, nv): DIRECTED edges in BOTH
    directions (user->item and item->user, each rating twice — both
    endpoint states must receive gradient updates, exactly how the
    reference feeds its SGD), so ne = 2 * n_ratings after dedup.
    Vertex ids: users [0, n_users), items [n_users, n_users+n_items).
    (user, item) pairs are deduplicated like the real dataset's unique
    ratings; expect a few percent under 2*n_ratings."""
    rng = np.random.default_rng(seed)
    # Zipf-ish endpoint distributions via inverse-CDF sampling on
    # rank^(-skew) weights (exact rank popularity, no rejection).
    def sample(n, skew, count):
        w = (np.arange(1, n + 1, dtype=np.float64)) ** -skew
        cdf = np.cumsum(w)
        cdf /= cdf[-1]
        return np.searchsorted(cdf, rng.random(count)).astype(np.uint32)

    users = sample(n_users, user_skew, n_ratings)
    items = sample(n_items, item_skew, n_ratings)
    # dedup (user, item) pairs: one fused u64 key sort + boundary pass
    from lux_tpu import native
    key = users.astype(np.uint64)
    key *= np.uint64(n_items)
    key += items
    native.sort_kv(key, ())
    keep = np.ones(len(key), bool)
    keep[1:] = key[1:] != key[:-1]
    key = key[keep]
    users = (key // np.uint64(n_items)).astype(np.uint32)
    items = (key % np.uint64(n_items)).astype(np.uint32) + n_users
    # integer ratings 1..5, roughly the public dataset's marginal
    w = rng.choice(np.arange(1, 6, dtype=np.int32), size=len(users),
                   p=[0.05, 0.10, 0.23, 0.34, 0.28])
    src = np.concatenate([users, items])
    dst = np.concatenate([items, users])
    weights = np.concatenate([w, w])
    return src, dst, weights, n_users + n_items


def community_edges(scale: int, edge_factor: int = 16,
                    community_scale: int = 8, p_in: float = 0.98,
                    seed: int = 0, scrambled: bool = True,
                    weighted: bool = False):
    """Planted-partition (stochastic-block-model family) edge list:
    2^scale vertices in communities of 2^community_scale, each vertex
    drawing ``edge_factor`` out-edges, fraction ``p_in`` inside its
    own community — the LOCALITY-RICH synthetic counterpart of the
    R-MAT presets (real social/web graphs cluster like this; R-MAT
    famously does not, which is exactly the round-15 paged-gather
    finding).  The default ``p_in`` = 0.98 is web-graph-like
    intra-domain locality (most links stay within a host/domain);
    note the paged economics are SHARP in it — uniform cross edges
    pay one delivery row each, so achievable page fill is about
    128 / (p_in + 128 * (1 - p_in)) under perfect clustering: ~36 at
    0.98, only ~10 at 0.9.  ``scrambled`` (default) applies a seeded
    random relabel, so the locality EXISTS but is not handed to the
    layout for free — recovering it is the reorder pass's job
    (lux_tpu/reorder.py); scrambled=False keeps communities
    contiguous (the oracle best order, for break-even pins).

    Returns (src, dst, weights|None, nv) uint32 edge arrays.
    """
    if not 0.0 <= p_in <= 1.0:
        raise ValueError(f"p_in must be in [0, 1], got {p_in}")
    if community_scale > scale:
        raise ValueError(f"community_scale {community_scale} > "
                         f"scale {scale}")
    rng = np.random.default_rng(seed)
    nv = 1 << scale
    csize = 1 << community_scale
    ne = nv * edge_factor
    src = np.repeat(np.arange(nv, dtype=np.int64), edge_factor)
    comm = src // csize
    inside = rng.random(ne) < p_in
    dst = np.where(
        inside,
        comm * csize + rng.integers(0, csize, size=ne),
        rng.integers(0, nv, size=ne))
    if scrambled:
        shuf = rng.permutation(nv)
        src = shuf[src]
        dst = shuf[dst]
    w = (rng.integers(1, 6, size=ne).astype(np.int32)
         if weighted else None)
    return src.astype(np.uint32), dst.astype(np.uint32), w, nv


def community_graph(scale: int, edge_factor: int = 16,
                    community_scale: int = 8, p_in: float = 0.98,
                    seed: int = 0, scrambled: bool = True,
                    weighted: bool = False):
    """community_edges assembled into a Graph (dst-sorted CSC)."""
    from lux_tpu.graph import Graph

    src, dst, w, nv = community_edges(
        scale, edge_factor, community_scale, p_in, seed,
        scrambled=scrambled, weighted=weighted)
    return Graph.from_edges(src, dst, nv, weights=w)


def uniform_random_edges(nv: int, ne: int, seed: int = 0, weighted=False):
    """Erdos-Renyi-ish random edge list (test-sized graphs)."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, nv, size=ne, dtype=np.uint32)
    dst = rng.integers(0, nv, size=ne, dtype=np.uint32)
    if weighted:
        w = rng.integers(1, 6, size=ne, dtype=np.int32)
        return src, dst, w
    return src, dst
