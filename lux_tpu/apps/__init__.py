from lux_tpu.apps import pagerank, colfilter
