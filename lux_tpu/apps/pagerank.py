"""PageRank (pull model, fixed iteration count).

Semantics match the reference exactly (reference pagerank_gpu.cu:49-102,
pagerank/app.h:24, pull_init at pagerank_gpu.cu:255-259):

- ALPHA = 0.15 used as ``pr = (1-ALPHA)/nv + ALPHA * sum`` — i.e. the
  damping factor is 0.15, not the usual 0.85 (SURVEY.md §7 quirks;
  preserved for parity).
- State is *degree-normalized* rank: after each update the rank is
  divided by out-degree so the next gather needs no degree lookup
  (pagerank_gpu.cu:97-100); init seeds ``(1/nv)/deg`` (deg==0 -> 1/nv).
- Final output is therefore also degree-scaled; ``true_ranks``
  un-scales it for conventional PageRank values.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import PullProgram
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph, degree_relabel  # noqa: F401
# degree_relabel moved to graph.py; re-exported for existing callers

ALPHA = 0.15  # reference pagerank/app.h:24


def make_program(dtype=jnp.float32) -> PullProgram:
    def edge_value(src_val, dst_val, weight):
        return src_val

    def apply(old, red, ctx):
        pr = (1.0 - ALPHA) / ctx.nv + ALPHA * red
        deg = ctx.deg.astype(pr.dtype)
        return jnp.where(ctx.deg > 0, pr / jnp.maximum(deg, 1), pr)

    def init(sg: ShardedGraph):
        rank = 1.0 / sg.nv
        deg = sg.deg_padded
        state = np.where(deg > 0, rank / np.maximum(deg, 1), rank)
        return state.astype(np.dtype(dtype))

    return PullProgram(reduce="sum", edge_value=edge_value, apply=apply,
                       init=init, needs_dst=False,
                       state_bytes=np.dtype(dtype).itemsize,
                       name="pagerank")


def one_hot_resets(nv: int, sources) -> np.ndarray:
    """[nv, B] reset matrix with column q the one-hot distribution of
    ``sources[q]`` — the classic 'personalized to one vertex' case."""
    sources = [int(s) for s in sources]
    resets = np.zeros((nv, len(sources)), dtype=np.float32)
    for q, s in enumerate(sources):
        if not 0 <= s < nv:
            raise ValueError(f"source vertex {s} out of range [0, {nv})")
        resets[s, q] = 1.0
    return resets


def make_batched_program(resets, dtype=jnp.float32) -> PullProgram:
    """Personalized PageRank over a query batch: state ``[vpad, B]``
    degree-normalized ranks, one column per query, with per-query
    reset vectors ``resets [nv, B]`` (each column a distribution over
    vertices; the uniform column 1/nv recovers the classic program).
    Update per column: ``pr = (1-ALPHA) * reset_q + ALPHA * sum``
    (the reference's damping quirk, see module docstring), then the
    same degree normalization.

    The reset matrix rides ``PullProgram.extra_arrays`` — a jit
    ARGUMENT the engine ships like any graph array (``ctx.extra
    ['reset']``), so the no-closure convention holds and the serving
    front-end can swap retired columns' resets in place
    (PullEngine.update_program_arrays).  ONE state-table gather per
    dense iteration serves all B queries (audit gather-budget);
    ``state_bytes = 4B`` keeps the auto-exchange and ledger
    estimates honest at B > 1.

    ``deg_corr`` (round 21, live graphs) is a second extra array
    [nv, B] of per-column out-degree CORRECTIONS, zero by default (a
    float 0 add keeps the static case bitwise).  The live serving
    tier sets column q to the delta-append out-degree at q's
    admission epoch, so the engine normalizes by the EFFECTIVE
    degree of ``graph_at(epoch_q)`` while iterating the base edges;
    the host-side correction step adds the delta edges' rank mass at
    each boundary (serve.PullBatchRunner — together one exact PPR
    iteration over the epoch's graph, which is how pull admissions
    advance with published epochs without waiting for a fold)."""
    resets = np.asarray(resets, dtype=np.dtype(dtype))
    if resets.ndim != 2:
        raise ValueError(f"resets must be [nv, B], got {resets.shape}")
    B = resets.shape[1]

    def edge_value(src_val, dst_val, weight):
        return src_val

    def apply(old, red, ctx):
        reset = ctx.extra["reset"]
        pr = (1.0 - ALPHA) * reset + ALPHA * red
        deg = ctx.deg.astype(pr.dtype)[:, None] \
            + ctx.extra["deg_corr"]
        return jnp.where(deg > 0, pr / jnp.maximum(deg, 1), pr)

    def init(sg: ShardedGraph):
        if resets.shape[0] != sg.nv:
            raise ValueError(f"resets rows {resets.shape[0]} != nv "
                             f"{sg.nv}")
        deg = np.asarray(sg.deg_padded)[..., None]
        r = sg.to_padded(resets)
        return np.where(deg > 0, r / np.maximum(deg, 1),
                        r).astype(np.dtype(dtype))

    def extra_arrays(sg: ShardedGraph):
        zeros = np.zeros(resets.shape, np.dtype(dtype))
        return {"reset": sg.to_padded(resets),
                "deg_corr": sg.to_padded(zeros)}

    return PullProgram(reduce="sum", edge_value=edge_value, apply=apply,
                       init=init, needs_dst=False,
                       state_bytes=np.dtype(dtype).itemsize * B,
                       name="ppr", extra_arrays=extra_arrays,
                       batch=B)


def build_engine(g: Graph, num_parts: int = 1, mesh=None,
                 dtype=jnp.float32, sg: ShardedGraph | None = None,
                 pair_threshold: int | None = None,
                 pair_min_fill: int | None = None,
                 starts=None, tile_e: int | None = None,
                 exchange: str = "auto",
                 gather: str = "flat",
                 owner_tile_e: int | None = None,
                 use_mxu: bool | str = "auto",
                 health: bool = False,
                 sources=None, resets=None,
                 audit: str | None = None) -> PullEngine:
    """starts: partition cut points (e.g. from graph.pair_relabel for
    balanced multi-part pair delivery).  tile_e default: 128 with pair
    delivery (residual edges are sparse; shorter chunks waste far
    fewer padded gather slots), else 512.  exchange='owner' switches
    to owner-side message generation (ops/owner.py) — the fast path
    once the state table outgrows ~64 MB.  health=True runs the
    device-side health watchdog loop variants (lux_tpu/health.py).
    audit='warn'|'error' statically audits every compiled program
    variant at build time (lux_tpu/audit.py).

    sources=[a, b, ...] builds the QUERY-BATCHED personalized engine
    with one-hot reset vectors (state [vpad, B] — one gather serves
    every query); resets [nv, B] passes arbitrary per-query reset
    distributions instead.  Batched engines reject pair_threshold
    (pair delivery reads scalar state)."""
    if sources is not None and resets is not None:
        raise ValueError("pass sources=[...] OR resets=[nv, B], "
                         "not both")
    if sources is not None:
        resets = one_hot_resets(g.nv, sources)
    if sg is None:
        # gather="paged"|"auto": the paged plan needs 128-aligned
        # vertex padding, like pair delivery (ops/pagegather.py)
        sg = ShardedGraph.build(
            g, num_parts, starts=starts,
            pair_threshold=pair_threshold,
            vpad_align=128 if gather != "flat" else 8)
    if tile_e is None:
        tile_e = 128 if pair_threshold is not None else 512
    program = (make_program(dtype) if resets is None
               else make_batched_program(resets, dtype))
    return PullEngine(sg, program, mesh=mesh,
                      pair_threshold=pair_threshold,
                      pair_min_fill=pair_min_fill, tile_e=tile_e,
                      exchange=exchange, gather=gather,
                      owner_tile_e=owner_tile_e, use_mxu=use_mxu,
                      health=health, audit=audit)




def run(g: Graph, num_iters: int, num_parts: int = 1, mesh=None):
    """Run PageRank; returns degree-normalized ranks [nv] (host)."""
    eng = build_engine(g, num_parts, mesh)
    state = eng.init_state()
    state = eng.run(state, num_iters)
    return eng.unpad(state)


def run_until(g: Graph, tol: float = 1e-9, max_iters: int = 10000,
              num_parts: int = 1, mesh=None):
    """Convergence-driven PageRank (a superset of the reference's
    fixed -ni runs): iterate until the max-abs change of the DEGREE-
    SCALED rank state (the iteration variable, see module docstring)
    is <= tol.  Conventional-rank changes can be up to out_degree
    times larger; pick tol accordingly.  Returns
    (ranks [nv], iterations)."""
    import jax

    eng = build_engine(g, num_parts, mesh)
    state, it, _res = eng.run_until(eng.init_state(), tol, max_iters)
    return eng.unpad(state), int(jax.device_get(it))


def true_ranks(norm_ranks: np.ndarray, out_degrees: np.ndarray):
    """Undo the degree scaling: conventional PageRank values."""
    deg = np.asarray(out_degrees)
    return np.where(deg > 0, norm_ranks * np.maximum(deg, 1), norm_ranks)


def reference_pagerank(g: Graph, num_iters: int) -> np.ndarray:
    """NumPy oracle with identical semantics (degree-normalized)."""
    src, dst = g.edge_arrays()
    deg = g.out_degrees.astype(np.float64)
    state = np.where(deg > 0, (1.0 / g.nv) / np.maximum(deg, 1), 1.0 / g.nv)
    for _ in range(num_iters):
        acc = np.zeros(g.nv, dtype=np.float64)
        np.add.at(acc, dst, state[src])
        pr = (1.0 - ALPHA) / g.nv + ALPHA * acc
        state = np.where(deg > 0, pr / np.maximum(deg, 1), pr)
    return state


def reference_pagerank_batched(g: Graph, resets,
                               num_iters: int) -> np.ndarray:
    """NumPy personalized-PageRank oracle -> ``[nv, B]``
    degree-normalized ranks, one column per reset vector.

    Column q is BITWISE-equal to running this oracle with the single
    column ``resets[:, q:q+1]``: the vectorized ``np.add.at``
    accumulates each column over the identical edge sequence, so the
    per-column float-summation order is the single-query order
    (tests/test_batched.py asserts it).  A uniform 1/nv column
    reproduces ``reference_pagerank`` exactly."""
    src, dst = g.edge_arrays()
    resets = np.asarray(resets, dtype=np.float64)
    deg = g.out_degrees.astype(np.float64)[:, None]
    state = np.where(deg > 0, resets / np.maximum(deg, 1), resets)
    for _ in range(num_iters):
        acc = np.zeros_like(state)
        np.add.at(acc, dst, state[src])
        pr = (1.0 - ALPHA) * resets + ALPHA * acc
        state = np.where(deg > 0, pr / np.maximum(deg, 1), pr)
    return state
