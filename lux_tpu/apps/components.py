"""Connected components via max-label propagation (push model).

Matches the reference's algorithm (reference components_gpu.cu:57-59,
733-739): every vertex starts active with label = its own id; each
iteration a destination takes the max label over its in-neighbors;
convergence when no label changes.  On a symmetrized (undirected)
graph every component converges to the max vertex id in the component.
The check audits the fixed point: labels[dst] >= labels[src] for every
edge (components_gpu.cu:788).
"""

from __future__ import annotations

import numpy as np

from lux_tpu.engine.push import PushEngine, PushProgram
from lux_tpu.graph import Graph, ShardedGraph


def make_program() -> PushProgram:
    def relax(src_label, w):
        return src_label

    def init(sg: ShardedGraph):
        labels = np.arange(sg.nv, dtype=np.int32)
        active = np.ones(sg.nv, dtype=bool)
        return sg.to_padded(labels), sg.to_padded(active)

    return PushProgram(reduce="max", relax=relax,
                       identity=np.int32(-1), init=init,
                       name="components")


def make_batched_program(seeds) -> PushProgram:
    """Batched SEEDED components: labels ``[vpad, B]`` with column q
    the propagation from the single seed ``seeds[q]`` — label[v, q]
    converges to ``seeds[q]`` where v is reachable from the seed and
    stays -1 elsewhere (on a symmetrized graph: the membership
    labeling of the seed's component).  One label gather per dense
    iteration serves every query (ROADMAP item 2); columns retire
    independently through their active masks.  Max fixed points are
    unique, so each column is bitwise-equal to the single-seed run
    (tests/test_batched.py)."""
    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("seeds must name at least one query")
    B = len(seeds)

    def relax(src_label, w):
        return src_label

    def init(sg: ShardedGraph):
        for s in seeds:
            if not 0 <= s < sg.nv:
                raise ValueError(
                    f"seed vertex {s} out of range [0, {sg.nv})")
        labels = np.full((sg.nv, B), -1, dtype=np.int32)
        active = np.zeros((sg.nv, B), dtype=bool)
        for q, s in enumerate(seeds):
            labels[s, q] = s
            active[s, q] = True
        return sg.to_padded(labels), sg.to_padded(active)

    return PushProgram(reduce="max", relax=relax,
                       identity=np.int32(-1), init=init,
                       name="cc_seeded", batch=B)


def build_engine(g: Graph, num_parts: int = 1, mesh=None,
                 sg: ShardedGraph | None = None,
                 pair_threshold: int | None = None,
                 pair_min_fill: int | None = None,
                 starts=None, exchange: str = "auto",
                 gather: str = "flat",
                 enable_sparse: bool = True,
                 owner_tile_e: int | None = None,
                 owner_minmax_fused: bool = False,
                 use_mxu: bool | str = "auto",
                 health: bool = False,
                 sources=None,
                 audit: str | None = None) -> PushEngine:
    """pair_threshold enables pair-lane delivery on dense iterations
    (best after graph.pair_relabel, passing its ``starts`` through;
    labels are vertex ids, so map results back through the relabel
    permutation).  enable_sparse=False drops the src-sorted frontier
    view — the big-scale fit lever (it re-doubles edge memory,
    ShardedGraph.memory_report(push_sparse=True)); every iteration
    then runs dense.

    sources=[a, b, ...] builds the QUERY-BATCHED seeded engine
    (``make_batched_program``): column q labels the vertices
    reachable from seed a with the seed's id (labels [vpad, B], one
    gather serving every query); pair_threshold must be off then."""
    if sg is None:
        sg = ShardedGraph.build(
            g, num_parts, starts=starts,
            pair_threshold=pair_threshold,
            vpad_align=128 if gather != "flat" else 8)
    program = (make_program() if sources is None
               else make_batched_program(sources))
    return PushEngine(sg, program, mesh=mesh,
                      pair_threshold=pair_threshold,
                      pair_min_fill=pair_min_fill, exchange=exchange,
                      gather=gather, enable_sparse=enable_sparse,
                      owner_tile_e=owner_tile_e,
                      owner_minmax_fused=owner_minmax_fused,
                      use_mxu=use_mxu, health=health, audit=audit)


def run(g: Graph, num_parts: int = 1, mesh=None, max_iters=None,
        verbose: bool = False):
    """Returns (labels [nv], iterations)."""
    eng = build_engine(g, num_parts, mesh)
    return eng.run(max_iters=max_iters, verbose=verbose)


def symmetrize(src, dst, weights=None):
    """Add reverse edges — CC semantics expect an undirected graph."""
    s = np.concatenate([src, dst])
    d = np.concatenate([dst, src])
    if weights is not None:
        return s, d, np.concatenate([weights, weights])
    return s, d


def reference_components(g: Graph) -> np.ndarray:
    """NumPy oracle: iterate max-propagation to fixed point."""
    src, dst = g.edge_arrays()
    labels = np.arange(g.nv, dtype=np.int64)
    while True:
        new = labels.copy()
        np.maximum.at(new, dst, labels[src])
        if np.array_equal(new, labels):
            return labels
        labels = new


def reference_components_incremental(g_new: Graph,
                                     labels_old: np.ndarray,
                                     new_src, new_dst) -> np.ndarray:
    """NumPy INCREMENTAL oracle (round 20, live graphs): revalidate
    converged max-propagation labels after edge appends by
    propagating ONLY from the touched endpoints (the worklist
    analogue of lux_tpu/livegraph.LiveGraph.revalidate).  Appends
    only ever RAISE max-fixed-point labels (components can merge,
    never split), so seeding from the old fixed point and pushing
    improvements from the new edges converges to exactly
    ``reference_components(g_new)`` — proved in
    tests/test_livegraph.py."""
    src, dst = g_new.edge_arrays()
    labels = np.asarray(labels_old, dtype=np.int64).copy()
    frontier = np.zeros(g_new.nv, dtype=bool)
    for s, d in zip(np.asarray(new_src, np.int64),
                    np.asarray(new_dst, np.int64)):
        if labels[s] > labels[d]:
            labels[d] = labels[s]
            frontier[d] = True
    while frontier.any():
        on = frontier[src]
        new = labels.copy()
        np.maximum.at(new, dst[on], labels[src[on]])
        frontier = new > labels
        labels = new
    return labels


def reference_components_decremental(g_new: Graph,
                                     labels_old: np.ndarray,
                                     touched_dst) -> np.ndarray:
    """NumPy DECREMENTAL oracle (round 21, mutation algebra): repair
    converged max-propagation labels after edge DELETIONS by the
    affected-cone re-seed rule (lux_tpu/livegraph.LiveGraph.
    revalidate's device mirror).  A deletion can LOWER a label
    (a component splits), which max-propagation can never repair; any
    vertex whose label changes is reachable in ``g_new`` from some
    deleted edge's destination (the suffix of its stale label-witness
    path past the LAST deleted edge survives).  Re-seed the cone —
    forward reachability from ``touched_dst`` over ``g_new`` — to the
    init labels (own id) and propagate to fixed point: every label
    starts <= the true fixed point and >= its init seed, so the max
    fixed point is exactly ``reference_components(g_new)`` (proved in
    tests/test_livegraph.py)."""
    src, dst = g_new.edge_arrays()
    labels = np.asarray(labels_old, dtype=np.int64).copy()
    cone = np.zeros(g_new.nv, dtype=bool)
    cone[np.asarray(touched_dst, np.int64)] = True
    while True:
        add = np.zeros(g_new.nv, dtype=bool)
        add[dst[cone[src]]] = True
        add &= ~cone
        if not add.any():
            break
        cone |= add
    labels[cone] = np.arange(g_new.nv, dtype=np.int64)[cone]
    while True:
        new = labels.copy()
        np.maximum.at(new, dst, labels[src])
        if np.array_equal(new, labels):
            return labels
        labels = new


def reference_components_batched(g: Graph, seeds) -> np.ndarray:
    """NumPy seeded-propagation oracle -> ``[nv, B]`` labels: column q
    is ``seeds[q]`` where the vertex is reachable from the seed, -1
    elsewhere.  Column q is BITWISE-equal to running this oracle with
    the single seed ``[seeds[q]]`` (max fixed points are unique;
    tests/test_batched.py asserts the column equality)."""
    src, dst = g.edge_arrays()
    B = len(seeds)
    labels = np.full((g.nv, B), -1, dtype=np.int64)
    for q, s in enumerate(seeds):
        labels[int(s), q] = int(s)
    while True:
        new = labels.copy()
        np.maximum.at(new, dst, labels[src])
        if np.array_equal(new, labels):
            return labels
        labels = new
