"""Connected components via max-label propagation (push model).

Matches the reference's algorithm (reference components_gpu.cu:57-59,
733-739): every vertex starts active with label = its own id; each
iteration a destination takes the max label over its in-neighbors;
convergence when no label changes.  On a symmetrized (undirected)
graph every component converges to the max vertex id in the component.
The check audits the fixed point: labels[dst] >= labels[src] for every
edge (components_gpu.cu:788).
"""

from __future__ import annotations

import numpy as np

from lux_tpu.engine.push import PushEngine, PushProgram
from lux_tpu.graph import Graph, ShardedGraph


def make_program() -> PushProgram:
    def relax(src_label, w):
        return src_label

    def init(sg: ShardedGraph):
        labels = np.arange(sg.nv, dtype=np.int32)
        active = np.ones(sg.nv, dtype=bool)
        return sg.to_padded(labels), sg.to_padded(active)

    return PushProgram(reduce="max", relax=relax,
                       identity=np.int32(-1), init=init,
                       name="components")


def build_engine(g: Graph, num_parts: int = 1, mesh=None,
                 sg: ShardedGraph | None = None,
                 pair_threshold: int | None = None,
                 pair_min_fill: int | None = None,
                 starts=None, exchange: str = "auto",
                 enable_sparse: bool = True,
                 owner_tile_e: int | None = None,
                 owner_minmax_fused: bool = False,
                 health: bool = False,
                 audit: str | None = None) -> PushEngine:
    """pair_threshold enables pair-lane delivery on dense iterations
    (best after graph.pair_relabel, passing its ``starts`` through;
    labels are vertex ids, so map results back through the relabel
    permutation).  enable_sparse=False drops the src-sorted frontier
    view — the big-scale fit lever (it re-doubles edge memory,
    ShardedGraph.memory_report(push_sparse=True)); every iteration
    then runs dense."""
    if sg is None:
        sg = ShardedGraph.build(g, num_parts, starts=starts,
                                pair_threshold=pair_threshold)
    return PushEngine(sg, make_program(), mesh=mesh,
                      pair_threshold=pair_threshold,
                      pair_min_fill=pair_min_fill, exchange=exchange,
                      enable_sparse=enable_sparse, owner_tile_e=owner_tile_e,
                      owner_minmax_fused=owner_minmax_fused,
                      health=health, audit=audit)


def run(g: Graph, num_parts: int = 1, mesh=None, max_iters=None,
        verbose: bool = False):
    """Returns (labels [nv], iterations)."""
    eng = build_engine(g, num_parts, mesh)
    return eng.run(max_iters=max_iters, verbose=verbose)


def symmetrize(src, dst, weights=None):
    """Add reverse edges — CC semantics expect an undirected graph."""
    s = np.concatenate([src, dst])
    d = np.concatenate([dst, src])
    if weights is not None:
        return s, d, np.concatenate([weights, weights])
    return s, d


def reference_components(g: Graph) -> np.ndarray:
    """NumPy oracle: iterate max-propagation to fixed point."""
    src, dst = g.edge_arrays()
    labels = np.arange(g.nv, dtype=np.int64)
    while True:
        new = labels.copy()
        np.maximum.at(new, dst, labels[src])
        if np.array_equal(new, labels):
            return labels
        labels = new
