"""Collaborative filtering: matrix-factorization SGD on a weighted
bipartite graph (pull model, fixed iterations).

Semantics match the reference (reference col_filter/colfilter_gpu.cu:
32-104, col_filter/app.h:24-28): vertex state is a K=20 latent-factor
vector, initialized to sqrt(1/K) (colfilter_gpu.cu:261-264).  Per
iteration, for each vertex d with in-edges (s -> d, rating w):

    err_e   = w - <old[s], old[d]>
    acc[d]  = sum_e err_e * old[s]
    new[d]  = old[d] + GAMMA * (acc[d] - LAMBDA * old[d])

Note LAMBDA regularizes once per vertex, not per edge — preserved.
This is a naturally TPU-friendly program: state is [vpad, K] (K=20
lanes), messages are rank-2, and the segment-sum feeds the VPU/MXU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.program import PullProgram
from lux_tpu.engine.pull import PullEngine
from lux_tpu.graph import Graph, ShardedGraph

K = 20              # reference col_filter/app.h:28
LAMBDA = 0.001      # reference col_filter/app.h:26
GAMMA = 0.00000035  # reference col_filter/app.h:27


def make_program(k: int = K, lam: float = LAMBDA,
                 gamma: float = GAMMA) -> PullProgram:
    def edge_value(src_val, dst_val, weight):
        # err per edge, then the gradient contribution to the dst vertex
        err = weight - jnp.sum(src_val * dst_val, axis=-1)
        return err[..., None] * src_val

    def edge_value_from_dot(src_val, dot, weight):
        # dst dependence is only <src, dst>: lets the tiled engine get
        # the dot from MXU matmuls instead of a per-edge dst gather
        return (weight - dot)[..., None] * src_val

    def apply(old, red, ctx):
        return old + gamma * (red - lam * old)

    def init(sg: ShardedGraph):
        val = np.sqrt(1.0 / k).astype(np.float32)
        return np.full((sg.num_parts, sg.vpad, k), val, dtype=np.float32)

    return PullProgram(reduce="sum", edge_value=edge_value, apply=apply,
                       init=init, needs_dst=True,
                       edge_value_from_dot=edge_value_from_dot,
                       state_bytes=4 * k, name="colfilter")


def build_engine(g: Graph, num_parts: int = 1, mesh=None,
                 sg: ShardedGraph | None = None,
                 pair_threshold: int | None = None,
                 pair_min_fill: int | str | None = None,
                 pair_stream: bool | None = None,
                 starts=None, gather: str = "flat",
                 use_mxu: bool | str = "auto",
                 health: bool = False,
                 audit: str | None = None) -> PullEngine:
    """pair_threshold routes dense tile pairs through the blocked-
    SDDMM pair path (ops/pairs.pair_partial_dot, streamed past the
    memory budget — pair_partial_dot_streamed): one reshaped-row
    fetch per pair row instead of a per-edge [*, K] row gather — best
    after graph.pair_relabel, whose ``starts`` pass through here.

    pair_min_fill="auto" applies the K-AWARE occupancy cap: SDDMM
    rows cost more per row than scalar rows (~260 vs 150 ns at K=20,
    scalemodel.pair_row_ns), so under-filled rows ride the residual
    at a higher break-even fill (~22) than the scalar ~16
    (ops/pairs.resolve_min_fill)."""
    if g.weights is None:
        raise ValueError("collaborative filtering needs a weighted graph")
    if sg is None:
        sg = ShardedGraph.build(
            g, num_parts, starts=starts,
            pair_threshold=pair_threshold,
            vpad_align=128 if gather != "flat" else 8)
    tile_e = 128 if pair_threshold is not None else 512
    return PullEngine(sg, make_program(), mesh=mesh,
                      pair_threshold=pair_threshold,
                      pair_min_fill=pair_min_fill,
                      pair_stream=pair_stream, tile_e=tile_e,
                      gather=gather, use_mxu=use_mxu,
                      health=health, audit=audit)


def run(g: Graph, num_iters: int, num_parts: int = 1, mesh=None):
    """Returns latent factors [nv, K] (host)."""
    eng = build_engine(g, num_parts, mesh)
    state = eng.init_state()
    state = eng.run(state, num_iters)
    return eng.unpad(state)


def reference_colfilter(g: Graph, num_iters: int,
                        k: int = K) -> np.ndarray:
    """NumPy oracle with identical semantics."""
    src, dst = g.edge_arrays()
    w = np.asarray(g.weights, dtype=np.float64)
    state = np.full((g.nv, k), np.sqrt(1.0 / k), dtype=np.float64)
    for _ in range(num_iters):
        err = w - np.einsum("ek,ek->e", state[src], state[dst])
        acc = np.zeros_like(state)
        np.add.at(acc, dst, err[:, None] * state[src])
        state = state + GAMMA * (acc - LAMBDA * state)
    return state


def rmse(g: Graph, state: np.ndarray) -> float:
    """Root-mean-square rating prediction error over all edges."""
    src, dst = g.edge_arrays()
    pred = np.einsum("ek,ek->e", state[src], state[dst])
    err = np.asarray(g.weights, dtype=np.float64) - pred
    return float(np.sqrt(np.mean(err * err)))
