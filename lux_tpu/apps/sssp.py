"""Single-source shortest paths (push model, convergence-driven).

Two modes:

- ``hops`` (default): unweighted hop-count distances, candidate =
  dist[src] + 1.  This matches the reference exactly — its "SSSP" never
  loads edge weights and computes BFS levels (reference
  sssp_gpu.cu:122,208,225; weights unread in PushLoadTask,
  push_model.inl:60-75; SURVEY.md §7 quirks).
- ``weighted``: true shortest paths with float edge weights, candidate
  = dist[src] + w — the superset BASELINE.md's config list asks for.

Distances of unreachable vertices stay at INF (the reference seeds
dist = nv as its infinity, sssp_gpu.cu:733-744; we use a large sentinel
and expose ``unreachable`` masks instead of leaking graph-size-dependent
magic values).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lux_tpu.engine.push import PushEngine, PushProgram
from lux_tpu.graph import Graph, ShardedGraph

HOP_INF = np.int32(np.iinfo(np.int32).max // 2)   # +1 cannot overflow
DIST_INF = np.float32(np.inf)


def make_program(start_vertex: int, weighted: bool = False) -> PushProgram:
    if weighted:
        def relax(src_label, w):
            return src_label + w
        identity = np.float32(np.inf)
        dtype = np.float32
        inf = DIST_INF
    else:
        def relax(src_label, w):
            return src_label + np.int32(1)
        identity = HOP_INF
        dtype = np.int32
        inf = HOP_INF

    def init(sg: ShardedGraph):
        if not 0 <= start_vertex < sg.nv:
            raise ValueError(
                f"start vertex {start_vertex} out of range [0, {sg.nv})")
        dist = np.full(sg.nv, inf, dtype=dtype)
        dist[start_vertex] = 0
        active = np.zeros(sg.nv, dtype=bool)
        active[start_vertex] = True
        return sg.to_padded(dist), sg.to_padded(active)

    return PushProgram(reduce="min", relax=relax, identity=identity,
                       init=init, name="sssp")


def make_batched_program(sources, weighted: bool = False) -> PushProgram:
    """k-source SSSP: labels carry a query-batch axis ``[vpad, B]``
    with column q the independent single-source run from
    ``sources[q]`` (ROADMAP item 2: ONE label gather per dense
    iteration serves all B queries; columns retire independently
    through their per-query active masks).  Bitwise contract:
    tests/test_batched.py proves each column equals the single-source
    engine's run — min fixed points are unique, so the dense batched
    schedule and the single-query sparse/dense schedule agree
    exactly."""
    sources = [int(s) for s in sources]
    if not sources:
        raise ValueError("sources must name at least one query")
    B = len(sources)
    if weighted:
        def relax(src_label, w):
            # weight [.., E] broadcasts over the trailing query axis
            return src_label + w[..., None]
        identity = np.float32(np.inf)
        dtype = np.float32
        inf = DIST_INF
    else:
        def relax(src_label, w):
            return src_label + np.int32(1)
        identity = HOP_INF
        dtype = np.int32
        inf = HOP_INF

    def init(sg: ShardedGraph):
        for s in sources:
            if not 0 <= s < sg.nv:
                raise ValueError(
                    f"source vertex {s} out of range [0, {sg.nv})")
        dist = np.full((sg.nv, B), inf, dtype=dtype)
        active = np.zeros((sg.nv, B), dtype=bool)
        for q, s in enumerate(sources):
            dist[s, q] = 0
            active[s, q] = True
        return sg.to_padded(dist), sg.to_padded(active)

    return PushProgram(reduce="min", relax=relax, identity=identity,
                       init=init, name="ksssp", batch=B)


def default_delta(g: Graph) -> float:
    """Bucket width heuristic: the smallest positive edge weight,
    floored at mean/16.

    Measured sweep at the bench shape (RMAT21 ef16, weights 1..5,
    PERF_NOTES round 4): width=min (1.0) -> 0.1498 GTEPS beats the
    old mean-width (3.0 -> 0.1455) and plain weighted frontiers
    (0.1297).  Near-settled narrow buckets maximize the fraction of
    USEFUL relaxations when every engine iteration is fixed-shape;
    the mean/16 floor stops degenerate widths (near-zero float
    weights) from turning the run into relax-free bucket advances."""
    w = np.asarray(g.weights, np.float64)
    pos = w[w > 0]
    if not pos.size:
        return 1.0
    return float(max(pos.min(), np.mean(w) / 16.0))


def build_engine(g: Graph, start_vertex: int | None = 0,
                 num_parts: int = 1,
                 mesh=None, weighted: bool = False,
                 delta: float | str | None = None,
                 sg: ShardedGraph | None = None,
                 pair_threshold: int | None = None,
                 pair_min_fill: int | None = None,
                 starts=None, exchange: str = "auto",
                 gather: str = "flat",
                 enable_sparse: bool = True,
                 owner_tile_e: int | None = None,
                 owner_minmax_fused: bool = False,
                 use_mxu: bool | str = "auto",
                 health: bool = False,
                 sources=None,
                 audit: str | None = None) -> PushEngine:
    """delta: bucket width for delta-stepping priority ordering
    (weighted runs); "auto" picks a heuristic; None disables (plain
    Bellman-Ford frontier relaxation).  pair_threshold enables pair-
    lane delivery on dense iterations (best after graph.pair_relabel,
    whose ``starts`` should be passed through here).
    enable_sparse=False drops the src-sorted frontier view — the
    big-scale fit lever (it re-doubles edge memory,
    ShardedGraph.memory_report(push_sparse=True)); every iteration
    then runs dense.

    sources=[a, b, c, ...] builds the QUERY-BATCHED k-source engine
    instead (labels [vpad, B], one gather serving every query —
    ``make_batched_program``); start_vertex is then ignored, and
    delta/pair_threshold must be off (single-query machinery)."""
    if weighted and g.weights is None:
        raise ValueError("weighted SSSP needs a weighted graph")
    if sources is not None:
        if delta is not None:
            raise ValueError("delta-stepping is single-query; "
                             "sources=[...] requires delta=None")
        program = make_batched_program(sources, weighted)
    else:
        if start_vertex is None:
            raise ValueError("single-query SSSP needs start_vertex "
                             "(or pass sources=[...] for a batch)")
        if delta == "auto":
            delta = default_delta(g) if weighted else 1.0
        program = make_program(start_vertex, weighted)
    if sg is None:
        sg = ShardedGraph.build(
            g, num_parts, starts=starts,
            pair_threshold=pair_threshold,
            vpad_align=128 if gather != "flat" else 8)
    return PushEngine(sg, program, mesh=mesh,
                      delta=delta, pair_threshold=pair_threshold,
                      pair_min_fill=pair_min_fill,
                      exchange=exchange, gather=gather,
                      enable_sparse=enable_sparse,
                      owner_tile_e=owner_tile_e,
                      owner_minmax_fused=owner_minmax_fused,
                      use_mxu=use_mxu, health=health, audit=audit)


def run(g: Graph, start_vertex: int = 0, num_parts: int = 1, mesh=None,
        weighted: bool = False, delta=None, max_iters=None,
        verbose: bool = False):
    """Returns (dist [nv], iterations)."""
    eng = build_engine(g, start_vertex, num_parts, mesh, weighted,
                       delta=delta)
    return eng.run(max_iters=max_iters, verbose=verbose)


def unreachable(dist: np.ndarray) -> np.ndarray:
    if dist.dtype == np.int32:
        return dist >= HOP_INF
    return ~np.isfinite(dist)


def reference_sssp(g: Graph, start_vertex: int = 0,
                   weighted: bool = False) -> np.ndarray:
    """NumPy Bellman-Ford oracle (exact fixed point)."""
    src, dst = g.edge_arrays()
    if weighted:
        w = np.asarray(g.weights, dtype=np.float64)
        dist = np.full(g.nv, np.inf)
    else:
        w = np.ones(g.ne, dtype=np.int64)
        dist = np.full(g.nv, int(HOP_INF), dtype=np.int64)
    dist[start_vertex] = 0
    while True:
        cand = dist[src] + w
        new = dist.copy()
        np.minimum.at(new, dst, cand)
        if np.array_equal(new, dist):
            break
        dist = new
    return dist


def reference_sssp_incremental(g_new: Graph, dist_old: np.ndarray,
                               new_src, new_dst, new_w=None,
                               weighted: bool = False) -> np.ndarray:
    """NumPy INCREMENTAL oracle (round 20, live graphs): revalidate a
    converged distance vector after edge appends by re-relaxing ONLY
    vertices reachable from the touched endpoints — the worklist
    analogue of the frontier-seeded device revalidation
    (lux_tpu/livegraph.LiveGraph.revalidate).

    ``g_new`` is the AUGMENTED graph (base plus the new edges —
    ``Graph.with_edges``), ``dist_old`` the fixed point on the base
    graph, (new_src, new_dst[, new_w]) the appended edges.  Edge
    appends only ever LOWER min-fixed-point distances, so seeding
    from the old fixed point and propagating improvements from the
    new edges' destinations converges to exactly
    ``reference_sssp(g_new, ...)`` — the equality
    tests/test_livegraph.py proves on every sweep point.  Returns the
    new distance vector in dist_old's dtype discipline (int64 hops /
    float64 weighted, matching reference_sssp)."""
    src, dst = g_new.edge_arrays()
    if weighted:
        if new_w is None:
            # same contract as Graph.with_edges: a silently
            # one-weighted append seeds below the true fixed point,
            # and monotone propagation can never repair it
            raise ValueError("weighted incremental oracle needs "
                             "new_w for every appended edge")
        w = np.asarray(g_new.weights, dtype=np.float64)
        dist = np.asarray(dist_old, dtype=np.float64).copy()
        nw = np.asarray(new_w, np.float64)
    else:
        w = np.ones(g_new.ne, dtype=np.int64)
        dist = np.asarray(dist_old, dtype=np.int64).copy()
        nw = np.ones(len(new_src), dtype=np.int64)
    # seed: relax the appended edges against the old fixed point
    frontier = np.zeros(g_new.nv, dtype=bool)
    cand = dist[np.asarray(new_src, np.int64)] + nw
    for d, c in zip(np.asarray(new_dst, np.int64), cand):
        if c < dist[d]:
            dist[d] = c
            frontier[d] = True
    # propagate: only out-edges of improved vertices relax — the
    # touched-reachable region, not the whole graph
    while frontier.any():
        on = frontier[src]
        cand = dist[src[on]] + w[on]
        new = dist.copy()
        np.minimum.at(new, dst[on], cand)
        frontier = new < dist
        dist = new
    return dist


def reference_sssp_decremental(g_new: Graph, dist_old: np.ndarray,
                               touched_dst, start_vertex: int = 0,
                               weighted: bool = False) -> np.ndarray:
    """NumPy DECREMENTAL oracle (round 21, mutation algebra): repair a
    converged distance vector after ANTI-MONOTONE mutations — edge
    deletions and weight updates — by the affected-cone re-seed rule
    the device path mirrors (lux_tpu/livegraph.LiveGraph.revalidate).

    ``g_new`` is the post-mutation graph, ``dist_old`` the fixed point
    on the pre-mutation graph, ``touched_dst`` the destinations of
    every deleted/reweighted edge.  Deletions and weight increases can
    RAISE min-fixed-point distances, which monotone relaxation can
    never repair; but any vertex whose distance changes is reachable
    in ``g_new`` from some touched destination (take the LAST mutated
    edge (u, v) on its stale shortest path: the suffix from v survives
    in ``g_new``).  So: (1) the affected CONE = forward reachability
    from the touched destinations over ``g_new``, (2) re-seed the cone
    from identity (keeping the source seed), (3) relax to fixed point
    — every label starts >= the true fixed point with the source at 0,
    so Bellman-Ford converges to exactly ``reference_sssp(g_new)``
    (the equality tests/test_livegraph.py proves per sweep point;
    weight DECREASES are covered too — the improved paths route
    through a touched destination, hence through the cone)."""
    src, dst = g_new.edge_arrays()
    if weighted:
        w = np.asarray(g_new.weights, dtype=np.float64)
        dist = np.asarray(dist_old, dtype=np.float64).copy()
        inf = np.inf
    else:
        w = np.ones(g_new.ne, dtype=np.int64)
        dist = np.asarray(dist_old, dtype=np.int64).copy()
        inf = np.int64(int(HOP_INF))
    cone = np.zeros(g_new.nv, dtype=bool)
    cone[np.asarray(touched_dst, np.int64)] = True
    while True:
        add = np.zeros(g_new.nv, dtype=bool)
        add[dst[cone[src]]] = True
        add &= ~cone
        if not add.any():
            break
        cone |= add
    dist[cone] = inf
    dist[start_vertex] = 0
    while True:
        cand = dist[src] + w
        new = dist.copy()
        np.minimum.at(new, dst, cand)
        if np.array_equal(new, dist):
            break
        dist = new
    return dist


def reference_sssp_batched(g: Graph, sources,
                           weighted: bool = False) -> np.ndarray:
    """NumPy k-source Bellman-Ford oracle -> ``[nv, B]`` distances.

    Column q is BITWISE-equal to ``reference_sssp(g, sources[q])``:
    the vectorized relaxation applies the identical per-column
    ``np.minimum.at`` updates in the identical edge order, and min
    fixed points are unique (tests/test_batched.py asserts the
    column-equality explicitly — the batched-oracle contract of
    ROADMAP item 2)."""
    src, dst = g.edge_arrays()
    B = len(sources)
    if weighted:
        w = np.asarray(g.weights, dtype=np.float64)[:, None]
        dist = np.full((g.nv, B), np.inf)
    else:
        w = np.ones((g.ne, 1), dtype=np.int64)
        dist = np.full((g.nv, B), int(HOP_INF), dtype=np.int64)
    for q, s in enumerate(sources):
        dist[int(s), q] = 0
    while True:
        cand = dist[src] + w
        new = dist.copy()
        np.minimum.at(new, dst, cand)
        if np.array_equal(new, dist):
            break
        dist = new
    return dist
